"""Test configuration: force an 8-virtual-device CPU platform.

This is the TPU-native analog of the reference's "test multi-node without
a cluster" strategy (pickle round-trips, SURVEY.md §4.3): all sharding /
island / multi-host-shaped tests run against
``--xla_force_host_platform_device_count=8`` so CI needs no TPU.

Note: the environment's TPU plugin pins ``jax_platforms`` to
``axon,cpu``, overriding the JAX_PLATFORMS env var — so CPU must be
forced through ``jax.config`` after import, while XLA_FLAGS still must
be set *before* backend initialisation.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_collection_modifyitems(config, items):
    """Two-tier suite: everything not marked ``slow`` is ``fast``, so
    both ``-m fast`` and ``-m "not slow"`` select the quick tier
    (target: ~2 minutes on one CPU core; the full suite is dominated by
    XLA compiles and the reference's 100+-generation quality gates)."""
    for item in items:
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.fast)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Drop compiled executables between test modules.

    A full-suite run accumulates hundreds of CPU XLA executables in one
    process; past a threshold that has produced segfaults during
    *tracing* of later complex programs (observed in the multiswarm
    change-recovery test). Clearing per module keeps peak state bounded
    at the cost of a few re-traces within the suite. Set
    ``DEAP_TPU_NO_CACHE_CLEAR=1`` to disable (used to reproduce the
    crash when chasing the root cause).
    """
    yield
    if not os.environ.get("DEAP_TPU_NO_CACHE_CLEAR"):
        jax.clear_caches()
