"""Test configuration: force an 8-virtual-device CPU platform.

This is the TPU-native analog of the reference's "test multi-node without
a cluster" strategy (pickle round-trips, SURVEY.md §4.3): all sharding /
island / multi-host-shaped tests run against
``--xla_force_host_platform_device_count=8`` so CI needs no TPU.

Note: the environment's TPU plugin pins ``jax_platforms`` to
``axon,cpu``, overriding the JAX_PLATFORMS env var — so CPU must be
forced through ``jax.config`` after import, while XLA_FLAGS still must
be set *before* backend initialisation.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
