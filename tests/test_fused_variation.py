"""Fused variation plane — bit-identity against the unfused composition.

The contract under test (docs/advanced/fused_variation.md): for every
recognised (mate, mutate) pair, every fused mode computes EXACTLY the
arrays the unfused var_and/var_or composition computes — same RNG
draws, same selects — across operators, dtypes, degenerate population
sizes, probability extremes, and all four EA loops (where 'auto' is now
the default, so these pins are what lets that default exist).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import ops
from deap_tpu.algorithms import (ea_generate_update, ea_mu_comma_lambda,
                                 ea_mu_plus_lambda, ea_simple,
                                 evaluate_invalid, var_and, var_or)
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import gather, init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.ops import variation


def _bit_toolbox(indpb=0.05, mate=ops.cx_two_point):
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", mate)
    tb.register("mutate", ops.mut_flip_bit, indpb=indpb)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def _bit_pop(n, L=23, seed=1):
    pop = init_population(jax.random.key(seed), n,
                          ops.bernoulli_genome(L), FitnessSpec((1.0,)))
    return evaluate_invalid(pop, lambda g: g.sum(-1).astype(jnp.float32))


def _same_pop(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ------------------------------------------------------------ resolve ----

def test_resolve_plan_recognises_supported_pairs():
    for mate in (ops.cx_one_point, ops.cx_two_point):
        tb = _bit_toolbox(mate=mate)
        plan = variation.resolve_plan(tb)
        assert plan is not None and plan.mut_kind == "flip"


def test_resolve_plan_rejects_unrecognised_and_positional():
    tb = _bit_toolbox()
    tb.register("mutate", ops.mut_shuffle_indexes, indpb=0.1)
    assert variation.resolve_plan(tb) is None
    tb = _bit_toolbox()
    tb.register("mutate", ops.mut_flip_bit, 0.05)  # positional bind
    assert variation.resolve_plan(tb) is None
    tb = _bit_toolbox()
    tb.register("mate", ops.cx_uniform, indpb=0.3)  # per-gene cx mask
    assert variation.resolve_plan(tb) is None


def test_explicit_fused_mode_raises_when_unsupported():
    tb = _bit_toolbox()
    tb.register("mutate", lambda k, g: g)
    pop = _bit_pop(16)
    with pytest.raises(ValueError, match="fused"):
        var_and(jax.random.key(0), pop, tb, 0.5, 0.2, fused="xla")
    # 'auto' silently falls back to the unfused composition
    a = var_and(jax.random.key(0), pop, tb, 0.5, 0.2, fused="auto")
    b = var_and(jax.random.key(0), pop, tb, 0.5, 0.2, fused=False)
    _same_pop(a, b)


# ----------------------------------------------------- var_and parity ----

@pytest.mark.parametrize("n", [1, 2, 3, 16, 101])
@pytest.mark.parametrize("probs", [(0.5, 0.2), (0.0, 0.0), (1.0, 1.0)])
def test_var_and_fused_bit_identical(n, probs):
    cxpb, mutpb = probs
    tb = _bit_toolbox()
    pop = _bit_pop(n)
    key = jax.random.key(7)
    _same_pop(var_and(key, pop, tb, cxpb, mutpb, fused=False),
              var_and(key, pop, tb, cxpb, mutpb, fused="xla"))


@pytest.mark.parametrize("mate", [ops.cx_one_point, ops.cx_two_point])
def test_var_and_fused_gaussian_float(mate):
    tb = Toolbox()
    tb.register("evaluate", lambda g: -jnp.sum(g ** 2, -1))
    tb.register("mate", mate)
    tb.register("mutate", ops.mut_gaussian, mu=0.0, sigma=0.4,
                indpb=0.25)
    pop = init_population(jax.random.key(3), 51,
                          ops.uniform_genome(14, -1, 1),
                          FitnessSpec((1.0,)))
    pop = evaluate_invalid(pop, tb.evaluate)
    key = jax.random.key(9)
    _same_pop(var_and(key, pop, tb, 0.6, 0.3, fused=False),
              var_and(key, pop, tb, 0.6, 0.3, fused="xla"))


def test_var_and_fused_uniform_int():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_uniform_int, low=0, up=9, indpb=0.2)
    pop = init_population(jax.random.key(4), 33,
                          ops.randint_genome(12, 0, 10),
                          FitnessSpec((1.0,)))
    pop = evaluate_invalid(pop, tb.evaluate)
    key = jax.random.key(10)
    _same_pop(var_and(key, pop, tb, 0.5, 0.5, fused=False),
              var_and(key, pop, tb, 0.5, 0.5, fused="xla"))


def test_var_and_sel_idx_composition():
    """var_and(pop, sel_idx=idx) == var_and(gather(pop, idx)) — the
    selection gather composes into the fused pass losslessly."""
    tb = _bit_toolbox()
    pop = _bit_pop(64)
    idx = tb.select(jax.random.key(5), pop.wvalues, pop.size)
    key = jax.random.key(6)
    _same_pop(var_and(key, gather(pop, idx), tb, 0.5, 0.2, fused=False),
              var_and(key, pop, tb, 0.5, 0.2, fused="xla", sel_idx=idx))
    # and the unfused fallback honours sel_idx the same way
    tb2 = _bit_toolbox()
    tb2.register("mutate", lambda k, g: g)  # force fallback
    _same_pop(
        var_and(key, gather(pop, idx), tb2, 0.5, 0.2, fused=False),
        var_and(key, pop, tb2, 0.5, 0.2, fused="auto", sel_idx=idx))


# ------------------------------------------------------ var_or parity ----

@pytest.mark.parametrize("lam", [1, 20, 64])
def test_var_or_fused_bit_identical(lam):
    tb = _bit_toolbox()
    pop = _bit_pop(40)
    key = jax.random.key(11)
    _same_pop(var_or(key, pop, tb, lam, 0.4, 0.3, fused=False),
              var_or(key, pop, tb, lam, 0.4, 0.3, fused="xla"))


def test_var_or_fused_reproduction_keeps_fitness():
    """cxpb=mutpb=0: every child is an unchanged copy that keeps its
    parent's valid fitness — identical in both modes."""
    tb = _bit_toolbox()
    pop = _bit_pop(16)
    key = jax.random.key(12)
    a = var_or(key, pop, tb, 16, 0.0, 0.0, fused=False)
    b = var_or(key, pop, tb, 16, 0.0, 0.0, fused="xla")
    _same_pop(a, b)
    assert bool(b.valid.all())


# ------------------------------------------------------- loop parity ----

def _same_result(a, b):
    _same_pop((a[0], a[2]), (b[0], b[2]))
    assert str(a[1]) == str(b[1])  # logbooks render identically


def test_ea_simple_fused_bit_identical():
    tb = _bit_toolbox()
    pop = _bit_pop(64)
    args = (jax.random.key(2), pop, tb, 0.5, 0.2, 6)
    _same_result(ea_simple(*args, halloffame_size=4, fused=False),
                 ea_simple(*args, halloffame_size=4, fused="auto"))


def test_ea_mu_plus_lambda_fused_bit_identical():
    tb = _bit_toolbox()
    pop = _bit_pop(48)
    args = (jax.random.key(2), pop, tb, 48, 64, 0.4, 0.3, 5)
    _same_result(
        ea_mu_plus_lambda(*args, halloffame_size=4, fused=False),
        ea_mu_plus_lambda(*args, halloffame_size=4, fused="auto"))


def test_ea_mu_comma_lambda_fused_bit_identical():
    tb = _bit_toolbox()
    pop = _bit_pop(48)
    args = (jax.random.key(2), pop, tb, 48, 72, 0.4, 0.3, 5)
    _same_result(
        ea_mu_comma_lambda(*args, halloffame_size=4, fused=False),
        ea_mu_comma_lambda(*args, halloffame_size=4, fused="auto"))


def test_ea_generate_update_accepts_fused():
    """The ask-tell loop has no variation plane: fused= is accepted
    (signature uniformity) and inert."""
    from deap_tpu.strategies import Strategy

    strat = Strategy(centroid=[1.0] * 4, sigma=0.5, lambda_=8,
                     spec=FitnessSpec((-1.0,)))
    tb = Toolbox()
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    tb.register("evaluate", lambda g: jnp.sum(g ** 2, -1))
    a = ea_generate_update(jax.random.key(1), strat.initial_state(),
                           tb, 4, strat.spec, fused=False)
    b = ea_generate_update(jax.random.key(1), strat.initial_state(),
                           tb, 4, strat.spec, fused="auto")
    for x, y in zip(jax.tree_util.tree_leaves(a[0]),
                    jax.tree_util.tree_leaves(b[0])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ------------------------------------------------- dispatch journaling ----

def test_variation_dispatch_journaled(tmp_path):
    from deap_tpu.telemetry.journal import RunJournal, read_journal

    tb = _bit_toolbox()
    pop = _bit_pop(16)
    path = str(tmp_path / "j.jsonl")
    with RunJournal(path):
        var_and(jax.random.key(0), pop, tb, 0.5, 0.2, fused="auto")
        tb2 = _bit_toolbox()
        tb2.register("mutate", lambda k, g: g)
        var_and(jax.random.key(0), pop, tb2, 0.5, 0.2, fused="auto")
    rows = [e for e in read_journal(path)
            if e.get("kind") == "variation_dispatch"]
    paths = [e["path"] for e in rows]
    assert "fused_xla" in paths or "fused_kernel" in paths
    assert "unfused" in paths
    fused_row = next(e for e in rows if e["path"].startswith("fused"))
    assert fused_row["mate"] == "cx_two_point"
    assert fused_row["mutate"] == "mut_flip_bit"
