"""Chaos suite — deterministic fault plans against every recovery path.

Every test runs a full evolution twice: once uninterrupted, once under
an injected failure schedule (hard kill before/after the checkpoint
lands, corrupted-latest-checkpoint, simulated preemption, combined
plans) followed by a resume — and pins the recovered result
**bit-identical** to the uninterrupted one. Marked ``chaos`` (which the
conftest folds into the slow tier): select with ``pytest -m chaos``.
"""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.resilience import (
    CorruptCheckpoint,
    FaultPlan,
    InjectedCrash,
    KillAt,
    Preempted,
    PreemptAt,
    ResilientRun,
)
from deap_tpu.telemetry import RunTelemetry, read_journal

pytestmark = pytest.mark.chaos

NGEN = 9
SEG = 2


def _toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def _pop(n=64, length=16, seed=0):
    return init_population(jax.random.key(seed), n,
                           ops.bernoulli_genome(length),
                           FitnessSpec((1.0,)))


def _assert_pop_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.genomes),
                                  np.asarray(b.genomes))
    np.testing.assert_array_equal(np.asarray(a.fitness),
                                  np.asarray(b.fitness))


def _mono(tb, pop, key):
    return algorithms.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                                halloffame_size=4)


@pytest.mark.parametrize("when", ["before_save", "after_save"])
def test_hard_kill_then_resume_bit_exact(tmp_path, when):
    """Hard kill at gen 6 — before the segment's checkpoint lands
    (that segment's work is lost, resume replays it) and after (resume
    continues from it). Both recover bit-exactly."""
    tb, pop, key = _toolbox(), _pop(), jax.random.key(21)
    p1, lb1, h1 = _mono(tb, pop, key)
    d = str(tmp_path / "ck")
    with pytest.raises(InjectedCrash):
        ResilientRun(d, segment_len=SEG,
                     fault_plan=FaultPlan([KillAt(6, when=when)])
                     ).ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                                 halloffame_size=4)
    # the crash left a checkpoint at gen 4 (before_save) or 6 (after)
    ck = ResilientRun(d, segment_len=SEG)
    assert ck.ckpt.latest_step() == (4 if when == "before_save" else 6)
    p2, lb2, h2 = ck.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                               halloffame_size=4)
    _assert_pop_equal(p1, p2)
    np.testing.assert_array_equal(np.asarray(h1.fitness),
                                  np.asarray(h2.fitness))
    assert [r["nevals"] for r in lb1] == [r["nevals"] for r in lb2]


def test_corrupted_latest_checkpoint_falls_back(tmp_path):
    """The latest checkpoint is byte-corrupted after it lands, then the
    process dies; resume must detect the CRC mismatch, journal it, fall
    back to the previous valid step, replay — and still end
    bit-exact."""
    tb, pop, key = _toolbox(), _pop(), jax.random.key(22)
    p1, _, _ = _mono(tb, pop, key)
    d = str(tmp_path / "ck")
    with pytest.raises(InjectedCrash):
        ResilientRun(d, segment_len=SEG,
                     fault_plan=FaultPlan(
                         [CorruptCheckpoint(6, mode="flip")])
                     ).ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                                 halloffame_size=4)
    jpath = str(tmp_path / "resume.jsonl")
    with RunTelemetry(jpath) as tel:
        res = ResilientRun(d, segment_len=SEG, telemetry=tel)
        p2, _, _ = res.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                                 halloffame_size=4)
    _assert_pop_equal(p1, p2)
    rows = read_journal(jpath)
    kinds = [r["kind"] for r in rows]
    assert "checkpoint_corrupt" in kinds  # the detection is visible
    resumed = [r for r in rows if r["kind"] == "resumed"]
    assert resumed and resumed[0]["step"] == 4  # fell back past gen 6


def test_corrupted_latest_truncated_falls_back(tmp_path):
    tb, pop, key = _toolbox(), _pop(), jax.random.key(23)
    p1, _, _ = _mono(tb, pop, key)
    d = str(tmp_path / "ck")
    with pytest.raises(InjectedCrash):
        ResilientRun(d, segment_len=SEG,
                     fault_plan=FaultPlan(
                         [CorruptCheckpoint(4, mode="truncate")])
                     ).ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                                 halloffame_size=4)
    p2, _, _ = ResilientRun(d, segment_len=SEG).ea_simple(
        key, pop, tb, 0.5, 0.2, ngen=NGEN, halloffame_size=4)
    _assert_pop_equal(p1, p2)


def test_double_preemption_chain(tmp_path):
    """Two SIGTERMs across three processes: preempt at gen 2, resume,
    preempt again at gen 6, resume, finish — the run-id chain links all
    three and the result is bit-exact."""
    tb, pop, key = _toolbox(), _pop(), jax.random.key(24)
    p1, _, _ = _mono(tb, pop, key)
    d = str(tmp_path / "ck")
    ids = []
    r1 = ResilientRun(d, segment_len=SEG,
                      fault_plan=FaultPlan([PreemptAt(2)]))
    with pytest.raises(Preempted):
        r1.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                     halloffame_size=4)
    ids.append(r1.run_id)
    r2 = ResilientRun(d, segment_len=SEG,
                      fault_plan=FaultPlan([PreemptAt(6)]))
    with pytest.raises(Preempted) as exc:
        r2.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                     halloffame_size=4)
    assert exc.value.step == 6
    assert r2.resumed_from == ids[0]
    r3 = ResilientRun(d, segment_len=SEG)
    p2, _, _ = r3.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                            halloffame_size=4)
    assert r3.resumed_from == r2.run_id
    _assert_pop_equal(p1, p2)


def test_sigint_also_preempts(tmp_path):
    tb, pop, key = _toolbox(), _pop(), jax.random.key(25)
    d = str(tmp_path / "ck")
    with pytest.raises(Preempted) as exc:
        ResilientRun(d, segment_len=SEG,
                     fault_plan=FaultPlan(
                         [PreemptAt(4, signum=signal.SIGINT)])
                     ).ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
    assert exc.value.signum == signal.SIGINT
    assert os.path.exists(exc.value.path)


def test_gp_loop_kill_and_corrupt_chain(tmp_path):
    """The GP host engine under a combined plan: corrupt the gen-4
    checkpoint, crash, resume (falls back to gen 2, replays), finish —
    bit-exact against the uninterrupted run."""
    import deap_tpu.gp as gp
    from deap_tpu.gp.loop import make_symbreg_loop

    ps = gp.math_set(n_args=1)
    X = jnp.linspace(-1.0, 1.0, 32, endpoint=False)[:, None]
    y = X[:, 0] ** 3 + X[:, 0]
    genomes = jax.vmap(gp.gen_half_and_half(ps, 48, 1, 2))(
        jax.random.split(jax.random.key(3), 128))
    run = make_symbreg_loop(ps, 48, X, y, height_limit=6)
    r1 = run(jax.random.key(9), genomes, NGEN)
    d = str(tmp_path / "ck")
    with pytest.raises(InjectedCrash):
        ResilientRun(d, segment_len=SEG,
                     fault_plan=FaultPlan(
                         [CorruptCheckpoint(4, mode="flip")])).gp_loop(
            make_symbreg_loop(ps, 48, X, y, height_limit=6),
            jax.random.key(9), genomes, NGEN)
    r2 = ResilientRun(d, segment_len=SEG).gp_loop(
        make_symbreg_loop(ps, 48, X, y, height_limit=6),
        jax.random.key(9), genomes, NGEN)
    np.testing.assert_array_equal(np.asarray(r1["fitness"]),
                                  np.asarray(r2["fitness"]))
    for k in ("nodes", "consts", "length"):
        np.testing.assert_array_equal(np.asarray(r1["genomes"][k]),
                                      np.asarray(r2["genomes"][k]))
    assert r1["nevals"] == r2["nevals"]


def test_island_kill_then_resume(tmp_path):
    from deap_tpu.parallel import island_init, make_island_step

    tb = _toolbox()
    pops = island_init(jax.random.key(2), 4, 32,
                       ops.bernoulli_genome(16), FitnessSpec((1.0,)))
    pops = jax.vmap(lambda p: algorithms.evaluate_invalid(
        p, tb.evaluate))(pops)
    step = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=2, mig_k=1)
    key = jax.random.key(7)
    ref = pops
    for epoch in range(6):
        ref = step(jax.random.fold_in(key, epoch), ref)
    d = str(tmp_path / "ck")
    with pytest.raises(InjectedCrash):
        ResilientRun(d, segment_len=2,
                     fault_plan=FaultPlan([KillAt(4)])).island_run(
            step, key, pops, 6)
    got = ResilientRun(d, segment_len=2).island_run(step, key, pops, 6)
    _assert_pop_equal(ref, got)


def test_mu_plus_lambda_kill_then_resume(tmp_path):
    tb, pop, key = _toolbox(), _pop(), jax.random.key(26)
    p1, lb1, _ = algorithms.ea_mu_plus_lambda(
        key, pop, tb, 64, 128, 0.4, 0.3, ngen=NGEN)
    d = str(tmp_path / "ck")
    with pytest.raises(InjectedCrash):
        ResilientRun(d, segment_len=SEG,
                     fault_plan=FaultPlan([KillAt(6)])
                     ).ea_mu_plus_lambda(key, pop, tb, 64, 128, 0.4,
                                         0.3, ngen=NGEN)
    p2, lb2, _ = ResilientRun(d, segment_len=SEG).ea_mu_plus_lambda(
        key, pop, tb, 64, 128, 0.4, 0.3, ngen=NGEN)
    _assert_pop_equal(p1, p2)
    assert [r["nevals"] for r in lb1] == [r["nevals"] for r in lb2]


def test_generate_update_kill_then_resume(tmp_path):
    from deap_tpu.strategies import cma

    strat = cma.Strategy(centroid=[0.0] * 6, sigma=0.5)
    tb = Toolbox()
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    tb.register("evaluate", lambda g: -jnp.sum(g ** 2, axis=-1))
    key = jax.random.key(27)
    s1, lb1, _ = algorithms.ea_generate_update(
        key, strat.initial_state(), tb, ngen=NGEN, spec=strat.spec)
    d = str(tmp_path / "ck")
    with pytest.raises(InjectedCrash):
        ResilientRun(d, segment_len=SEG,
                     fault_plan=FaultPlan([KillAt(6)])
                     ).ea_generate_update(key, strat.initial_state(),
                                          tb, ngen=NGEN,
                                          spec=strat.spec)
    s2, lb2, _ = ResilientRun(d, segment_len=SEG).ea_generate_update(
        key, strat.initial_state(), tb, ngen=NGEN, spec=strat.spec)
    for a, b in zip(jax.tree_util.tree_leaves(s1),
                    jax.tree_util.tree_leaves(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_chaos_marker_rides_slow_tier(request):
    """This file's tests must be excluded from `-m "not slow"` (the
    tier-1 gate) and selected by `-m chaos` — the conftest folds the
    chaos marker into the slow tier."""
    assert "chaos" in request.node.keywords
    assert "slow" in request.node.keywords
