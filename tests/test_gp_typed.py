"""Typed-GP and ADF tests: well-typedness invariants under generation
and every typed variation operator, and ADF interpreter semantics
(reference: deap/gp.py:260-429 typed sets, :414-423/:490-513 ADFs,
examples/gp/spambase.py, examples/gp/adf_symbreg.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import gp

MAX_LEN = 48


@pytest.fixture(scope="module")
def tset():
    return gp.spam_set(n_features=2)


def well_typed(genome, pset):
    """Independent numpy type-checker: walk the prefix with a stack of
    required types and verify every node's return type matches."""
    arity = np.asarray(pset.arity_table())
    rett = np.asarray(pset.ret_type_table())
    argt = np.asarray(pset.arg_type_table())
    nodes = np.asarray(genome["nodes"])
    length = int(genome["length"])
    stack = [pset.ret]
    for t in range(length):
        if not stack:
            return False
        want = stack.pop()
        node = int(nodes[t])
        if int(rett[node]) != want:
            return False
        ar = int(arity[node])
        if ar:
            for j in reversed(range(ar)):
                stack.append(int(argt[node][j]))
    return not stack


def _unstack(genomes, i):
    return jax.tree_util.tree_map(lambda a: a[i], genomes)


def test_typed_generator_well_typed(tset):
    gen = gp.make_generator_typed(tset, MAX_LEN, 1, 5)
    genomes = jax.vmap(lambda k: gen(k))(
        jax.random.split(jax.random.key(0), 64))
    for i in range(64):
        assert well_typed(_unstack(genomes, i), tset)


def test_typed_generator_root_type_override(tset):
    gen = gp.make_generator_typed(tset, MAX_LEN, 1, 4)
    rett = np.asarray(tset.ret_type_table())
    float_id = tset.type_id("float")
    for seed in range(8):
        g = gen(jax.random.key(seed), ret_type=float_id)
        assert int(rett[int(g["nodes"][0])]) == float_id


def test_validate_rejects_terminal_free_type():
    ps = gp.PrimitiveSetTyped("BAD", ["float"], "bool")
    ps.add_primitive(lambda a, b: a * b, ["bool", "bool"], "bool", "and_")
    # no bool terminal anywhere
    with pytest.raises(ValueError, match="no terminal"):
        gp.make_generator_typed(ps, 16, 1, 3)


def test_typed_crossover_preserves_types(tset):
    gen = gp.make_generator_typed(tset, MAX_LEN, 2, 5)
    cx = gp.make_cx_one_point_typed(tset)
    keys = jax.random.split(jax.random.key(1), 32)
    g1 = jax.vmap(lambda k: gen(k))(keys)
    g2 = jax.vmap(lambda k: gen(k))(jax.random.split(jax.random.key(2), 32))
    c1, c2 = jax.vmap(cx)(jax.random.split(jax.random.key(3), 32), g1, g2)
    for i in range(32):
        assert well_typed(_unstack(c1, i), tset)
        assert well_typed(_unstack(c2, i), tset)


@pytest.mark.parametrize("op_name", [
    "node_replacement", "uniform", "insert", "shrink", "ephemeral"])
def test_typed_mutations_preserve_types(tset, op_name):
    gen = gp.make_generator_typed(tset, MAX_LEN, 2, 5)
    if op_name == "node_replacement":
        mut = gp.make_mut_node_replacement_typed(tset)
    elif op_name == "uniform":
        expr = gp.make_generator_typed(tset, MAX_LEN, 0, 2, "grow")
        mut = gp.make_mut_uniform_typed(tset, expr)
    elif op_name == "insert":
        mut = gp.make_mut_insert_typed(tset)
    elif op_name == "shrink":
        mut = gp.make_mut_shrink_typed(tset)
    else:
        mut = gp.make_mut_ephemeral_typed(tset, "all")
    genomes = jax.vmap(lambda k: gen(k))(
        jax.random.split(jax.random.key(4), 32))
    out = jax.vmap(mut)(jax.random.split(jax.random.key(5), 32), genomes)
    for i in range(32):
        assert well_typed(_unstack(out, i), tset)


def test_typed_interpreter_runs(tset):
    gen = gp.make_generator_typed(tset, MAX_LEN, 1, 5)
    interp = gp.make_interpreter(tset, MAX_LEN)
    X = jax.random.uniform(jax.random.key(6), (16, 2)) * 100.0
    genomes = jax.vmap(lambda k: gen(k))(
        jax.random.split(jax.random.key(7), 16))
    out = jax.vmap(lambda g: interp(g, X))(genomes)
    assert out.shape == (16, 16)
    # boolean root → outputs in {0, 1}
    assert np.all((np.asarray(out) == 0.0) | (np.asarray(out) == 1.0))


# -------------------------------------------------------------------- ADFs ----

def _adf_branches():
    """MAIN(x) may call ADF0(a); ADF0 is plain arithmetic."""
    adf0 = gp.math_set(n_args=1, trig=False, erc=False, name="ADF0")
    main = gp.math_set(n_args=1, trig=False, erc=False, name="MAIN")
    main.add_adf("ADF0", 1, branch=1)
    return [(main, 32), (adf0, 32)]


def test_adf_interpreter_matches_manual_composition():
    branches = _adf_branches()
    main, adf0 = branches[0][0], branches[1][0]
    interp = gp.make_adf_interpreter(branches)
    from deap_tpu.gp.string import from_string

    # ADF0(a) = a * a ; MAIN(x) = ADF0(x + 1)  →  (x+1)²
    g_adf = from_string("mul(ARG0, ARG0)", adf0, 32)
    adf_call = main.n_ops - 1   # add_adf appended last
    g_main = {
        "nodes": jnp.zeros((32,), jnp.int32)
        .at[0].set(adf_call)
        .at[1].set(0)                       # add
        .at[2].set(main.n_ops)              # ARG0
        .at[3].set(main.const_id),          # const 1.0
        "consts": jnp.zeros((32,), jnp.float32).at[3].set(1.0),
        "length": jnp.int32(4),
    }
    X = jnp.linspace(-2.0, 2.0, 9)[:, None]
    got = interp((g_main, g_adf), X)
    np.testing.assert_allclose(got, (X[:, 0] + 1.0) ** 2, rtol=1e-6)


def test_adf_batch_interpreter_matches_single():
    """The active-length-bounded ADF batch path must agree with the
    vmapped per-individual ADF interpreter on a random population."""
    branches = _adf_branches()
    gen = gp.make_adf_generator(branches, 1, 3)
    single = gp.make_adf_interpreter(branches)
    batch = gp.make_adf_batch_interpreter(branches)
    pop = [gen(jax.random.key(s)) for s in range(16)]
    genomes = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *pop)
    X = jnp.linspace(-2.0, 2.0, 11)[:, None]
    want = jax.vmap(lambda gt: single(gt, X))(genomes)
    got = jax.jit(batch)(genomes, X)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5)


def test_adf_rejects_forward_recursion():
    adf0 = gp.math_set(n_args=1, erc=False, name="ADF0")
    adf0.add_adf("SELF", 1, branch=1)   # branch calling itself
    with pytest.raises(ValueError, match="later branches"):
        gp.make_adf_interpreter([(gp.math_set(1), 16), (adf0, 16)])


def test_adf_generate_evolve_smoke():
    """adf_symbreg-shaped loop: generation + branch-wise variation keeps
    every branch a valid prefix program and fitness improves."""
    branches = _adf_branches()
    gen = gp.make_adf_generator(branches, 1, 3)
    cx = gp.branch_wise_cx([
        gp.make_cx_one_point(branches[0][0]),
        gp.make_cx_one_point(branches[1][0]),
    ])
    mut = gp.branch_wise_mut([
        gp.make_mut_node_replacement(branches[0][0]),
        gp.make_mut_node_replacement(branches[1][0]),
    ])
    interp = gp.make_adf_interpreter(branches)
    X = jnp.linspace(-1.0, 1.0, 20)[:, None]
    y = X[:, 0] ** 2 + X[:, 0]

    def fitness(genomes):
        pred = interp(genomes, X)
        return -jnp.mean((pred - y) ** 2)

    pop = 64
    keys = jax.random.split(jax.random.key(8), pop)
    genomes = jax.vmap(gen)(keys)
    fit0 = jax.vmap(fitness)(genomes)

    def step(key, genomes, fits):
        k_sel, k_cx, k_mut = jax.random.split(key, 3)
        idx = jax.random.randint(k_sel, (pop, 3), 0, pop)
        winner = idx[jnp.arange(pop), jnp.argmax(fits[idx], axis=1)]
        parents = jax.tree_util.tree_map(lambda a: a[winner], genomes)
        perm = jnp.roll(jnp.arange(pop), 1)
        mates = jax.tree_util.tree_map(lambda a: a[perm], parents)
        c1, _ = jax.vmap(cx)(jax.random.split(k_cx, pop), parents, mates)
        c1 = jax.vmap(mut)(jax.random.split(k_mut, pop), c1)
        return c1, jax.vmap(fitness)(c1)

    fits = fit0
    for g in range(10):
        genomes, fits = step(jax.random.key(100 + g), genomes, fits)
    assert float(fits.max()) >= float(fit0.max())
