"""Property tests for the M >= 3 nd-sort engines (mo/ndsort.py):
ranks from the Fenwick sweep (M=3) and the prefix-streamed chain
reduction (any M) must be bit-identical to the dominance-matrix
oracle on adversarial fitness sets — exact ties, duplicated rows,
mixed maximise/minimise weights — and the staircase must agree with
the sweep on 2-objective data embedded in 3-D."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import mo
from deap_tpu.mo.ndsort import nd_rank_prefix, nd_rank_sweep3


def _oracle(w):
    return np.asarray(mo.nd_rank(jnp.asarray(w), impl="matrix"))


def _cases(seed, nobj, trials=12):
    """Random fitness sets biased toward the failure modes: coarse
    integer grids (massive tie planes), injected duplicate rows, and
    sign-mixed weights."""
    rng = np.random.default_rng(seed)
    # a handful of fixed sizes (not fully random) so repeated trials
    # reuse compiled shapes — same coverage, a fraction of the compiles
    sizes = (1, 2, 37, 96, 201)
    for trial in range(trials):
        n = int(sizes[int(rng.integers(0, len(sizes)))])
        kind = trial % 3
        if kind == 0:
            w = rng.integers(0, 4, (n, nobj)).astype(np.float32)
        elif kind == 1:
            w = rng.normal(size=(n, nobj)).astype(np.float32)
        else:
            signs = rng.choice([-1.0, 1.0], nobj).astype(np.float32)
            w = rng.integers(0, 3, (n, nobj)).astype(np.float32) * signs
        if n > 4:  # duplicate a third of the rows onto random others
            w[rng.integers(0, n, n // 3)] = w[rng.integers(0, n, n // 3)]
        yield w


def test_sweep3_matches_oracle_property():
    for w in _cases(0, 3):
        got = np.asarray(nd_rank_sweep3(jnp.asarray(w)))
        np.testing.assert_array_equal(got, _oracle(w))


@pytest.mark.parametrize("nobj", [3, 4, 5])
def test_prefix_matches_oracle_property(nobj):
    for w in _cases(nobj, nobj, trials=8):
        got = np.asarray(nd_rank_prefix(jnp.asarray(w), block=32))
        np.testing.assert_array_equal(got, _oracle(w))


def test_sweep3_agrees_with_staircase_on_embedded_2d():
    # 2-objective data with a constant third objective: the M=3 sweep
    # must reproduce the bi-objective staircase exactly (constant
    # columns change no dominance relation)
    rng = np.random.default_rng(7)
    for _ in range(6):
        n = int(rng.integers(2, 300))
        w2 = rng.integers(0, 6, (n, 2)).astype(np.float32)
        w3 = np.concatenate([w2, np.full((n, 1), 3.5, np.float32)], 1)
        stair = np.asarray(mo.nd_rank_staircase(jnp.asarray(w2)))
        sweep = np.asarray(nd_rank_sweep3(jnp.asarray(w3)))
        np.testing.assert_array_equal(sweep, stair)


def test_sweep3_and_prefix_agree_at_m3():
    rng = np.random.default_rng(11)
    w = rng.normal(size=(500, 3)).astype(np.float32)
    w[rng.integers(0, 500, 100)] = w[rng.integers(0, 500, 100)]
    s = np.asarray(nd_rank_sweep3(jnp.asarray(w)))
    p = np.asarray(nd_rank_prefix(jnp.asarray(w), block=64))
    np.testing.assert_array_equal(s, p)


@pytest.mark.parametrize("impl", ["sweep", "dc"])
def test_max_rank_sentinel_contract(impl):
    rng = np.random.default_rng(3)
    w = rng.integers(0, 5, (120, 3)).astype(np.float32)
    full = _oracle(w)
    budget = 2
    got = np.asarray(mo.nd_rank(jnp.asarray(w), max_rank=budget,
                                impl=impl))
    exp = np.where(full < budget, full, 120)
    np.testing.assert_array_equal(got, exp)


@pytest.mark.parametrize("impl", ["sweep", "dc"])
def test_return_peels_counts_fronts(impl):
    rng = np.random.default_rng(4)
    w = rng.integers(0, 5, (150, 3)).astype(np.float32)
    nf = int(_oracle(w).max()) + 1
    _, peels = mo.nd_rank(jnp.asarray(w), impl=impl, return_peels=True)
    assert int(peels) == nf
    # under a budget the reported peel count is clamped like the
    # matrix/tiled paths', even though the ranks are exact
    _, peels_b = mo.nd_rank(jnp.asarray(w), impl=impl, max_rank=2,
                            fallback="count", return_peels=True)
    assert int(peels_b) <= 2


def test_auto_dispatch_picks_new_engines_on_cpu():
    # above the prefix threshold at M=3 the auto path must route off
    # the matrix and stay bit-identical to it
    rng = np.random.default_rng(5)
    n = mo.ND_PREFIX_THRESHOLD + 64
    w = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
    np.testing.assert_array_equal(np.asarray(mo.nd_rank(w)),
                                  np.asarray(mo.nd_rank(w, impl="matrix")))


def test_sel_nsga2_identical_across_engines():
    rng = np.random.default_rng(6)
    w = jnp.asarray(rng.normal(size=(400, 3)).astype(np.float32))
    base = np.asarray(mo.sel_nsga2(None, w, 150, nd="matrix"))
    for nd in ("sweep", "dc", "auto"):
        np.testing.assert_array_equal(
            np.asarray(mo.sel_nsga2(None, w, 150, nd=nd)), base)


def test_sel_nsga3_identical_across_engines():
    rng = np.random.default_rng(8)
    w = jnp.asarray(rng.normal(size=(300, 3)).astype(np.float32))
    rp = mo.uniform_reference_points(3, 4)
    key = jax.random.key(2)
    base = np.asarray(mo.sel_nsga3(key, w, 100, rp, nd="matrix"))
    for nd in ("sweep", "dc"):
        np.testing.assert_array_equal(
            np.asarray(mo.sel_nsga3(key, w, 100, rp, nd=nd)), base)


def test_engines_jit_and_vmap():
    rng = np.random.default_rng(9)
    w = jnp.asarray(rng.normal(size=(4, 96, 3)).astype(np.float32))
    ranks_v = jax.vmap(jax.jit(nd_rank_sweep3))(w)
    ranks_p = jax.vmap(lambda wi: nd_rank_prefix(wi, block=32))(w)
    for i in range(4):
        oracle = _oracle(np.asarray(w[i]))
        np.testing.assert_array_equal(np.asarray(ranks_v[i]), oracle)
        np.testing.assert_array_equal(np.asarray(ranks_p[i]), oracle)


@pytest.mark.parametrize("n", [0, 1, 2, 3])
def test_tiny_populations(n):
    w = jnp.asarray(np.arange(n * 3, dtype=np.float32).reshape(n, 3))
    for fn in (nd_rank_sweep3, lambda x: nd_rank_prefix(x, block=4)):
        got = np.asarray(fn(w))
        assert got.shape == (n,)
        if n:
            np.testing.assert_array_equal(got, _oracle(np.asarray(w)))


def test_prefix_pallas_cross_matches_xla():
    # the Pallas cross-step (interpreter off-TPU) must agree with the
    # fused XLA broadcast it replaces on-chip
    rng = np.random.default_rng(10)
    w = jnp.asarray(rng.integers(0, 6, (100, 4)).astype(np.float32))
    a = np.asarray(nd_rank_prefix(w, block=32, cross="xla"))
    b = np.asarray(nd_rank_prefix(w, block=32, cross="pallas",
                                  interpret=True))
    np.testing.assert_array_equal(a, b)
