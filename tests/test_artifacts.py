"""Executable artifact store (ISSUE 18): integrity, fallback and
concurrency contracts.

The store's one promise is that it can only ever REMOVE compiles from a
restart, never change results or add failure modes: every corruption /
mismatch path must fall back to ``None`` (caller compiles, journaled),
and a loaded artifact must execute bit-identically to the executable it
serialized.
"""

import json
import importlib.util
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu.support.artifacts import (ARTIFACT_JOURNAL_KINDS,
                                        ExecutableArtifactStore,
                                        disable_artifact_store,
                                        enable_artifact_store)
from deap_tpu.telemetry.journal import RunJournal, read_journal

DOCS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs")


def _compiled(c=2.0):
    x = jnp.arange(8, dtype=jnp.float32)
    lowered = jax.jit(lambda v: v * c + 1.0).lower(x)
    return lowered.compile(), x


def _rows(path, kind):
    return [e for e in read_journal(path) if e.get("kind") == kind]


def test_round_trip_bit_identity(tmp_path):
    store = ExecutableArtifactStore(str(tmp_path / "a"))
    compiled, x = _compiled()
    want = np.asarray(compiled(x)[0])
    assert store.put("f", "h1", compiled)

    # a FRESH store over the same directory (the restarted process)
    jpath = str(tmp_path / "j.jsonl")
    with RunJournal(jpath):
        loaded = ExecutableArtifactStore(str(tmp_path / "a")).get(
            "f", "h1")
        assert loaded is not None
        np.testing.assert_array_equal(np.asarray(loaded(x)[0]), want)
    hits = _rows(jpath, "artifact_hit")
    assert len(hits) == 1 and hits[0]["hlo_hash"] == "h1"


@pytest.mark.parametrize("damage", ["flip", "truncate"])
def test_corrupt_blob_falls_back_to_compile(tmp_path, damage):
    store = ExecutableArtifactStore(str(tmp_path / "a"))
    compiled, x = _compiled()
    assert store.put("f", "h1", compiled)
    blob = store._blob_path(store.key_for("h1"))
    raw = open(blob, "rb").read()
    if damage == "flip":
        bad = raw[: len(raw) // 2] + bytes([raw[len(raw) // 2] ^ 0xFF]) \
            + raw[len(raw) // 2 + 1:]
    else:
        bad = raw[: len(raw) // 3]
    with open(blob, "wb") as fh:
        fh.write(bad)

    jpath = str(tmp_path / "j.jsonl")
    with RunJournal(jpath):
        assert ExecutableArtifactStore(str(tmp_path / "a")).get(
            "f", "h1") is None
    misses = _rows(jpath, "artifact_miss")
    assert len(misses) == 1
    assert misses[0]["reason"] == "crc_mismatch"
    # ... and the caller's compile of the same program is the result
    # the store would have produced: bit-identity holds through the
    # fallback path too
    want = np.asarray(compiled(x)[0])
    refetched, _ = _compiled()
    np.testing.assert_array_equal(np.asarray(refetched(x)[0]), want)


def test_stamp_mismatch_skips_entry(tmp_path):
    store = ExecutableArtifactStore(str(tmp_path / "a"))
    compiled, _ = _compiled()
    assert store.put("f", "h1", compiled)
    mpath = store.manifest_path
    doc = json.load(open(mpath))
    for entry in doc["entries"].values():
        entry["jax"] = "0.0.0-other"
    with open(mpath, "w") as fh:
        json.dump(doc, fh)

    jpath = str(tmp_path / "j.jsonl")
    with RunJournal(jpath):
        assert ExecutableArtifactStore(str(tmp_path / "a")).get(
            "f", "h1") is None
    assert _rows(jpath, "artifact_miss")[0]["reason"] == "stamp_mismatch"


def test_missing_key_is_a_journaled_miss(tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    with RunJournal(jpath):
        assert ExecutableArtifactStore(str(tmp_path / "a")).get(
            "f", "never-compiled") is None
    assert _rows(jpath, "artifact_miss")[0]["reason"] == "absent"


def test_concurrent_read_merge_write_merges_both(tmp_path):
    """Two store instances over one directory — the two-process race a
    serving restart actually runs (the dying child's last put vs the
    fresh child's first). Both entries must survive the merge."""
    a = ExecutableArtifactStore(str(tmp_path / "a"))
    b = ExecutableArtifactStore(str(tmp_path / "a"))
    ca, _ = _compiled(2.0)
    cb, _ = _compiled(3.0)
    assert a.put("fa", "ha", ca)
    # b's in-memory manifest predates a's put; its own put must merge,
    # not clobber
    assert b.put("fb", "hb", cb)
    fresh = ExecutableArtifactStore(str(tmp_path / "a"))
    assert fresh.get("fa", "ha") is not None
    assert fresh.get("fb", "hb") is not None


def test_manifest_and_container_load_without_jax(tmp_path):
    """The manifest is stdlib JSON and the blob container a plain
    pickled dict — tooling (report.py, fleet jobs) must be able to
    inventory a store with no jax importable at all."""
    store = ExecutableArtifactStore(str(tmp_path / "a"))
    compiled, _ = _compiled()
    assert store.put("f", "h1", compiled)
    mod_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deap_tpu", "support", "artifacts.py")
    child = textwrap.dedent(f"""
        import builtins, importlib.util, json, pickle, os, sys
        real_import = builtins.__import__
        def guard(name, *a, **k):
            if name == "jax" or name.startswith("jax."):
                raise AssertionError("jax imported in no-jax child")
            return real_import(name, *a, **k)
        builtins.__import__ = guard
        spec = importlib.util.spec_from_file_location(
            "artifacts_standalone", {mod_path!r})
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        store = mod.ExecutableArtifactStore({str(tmp_path / "a")!r})
        assert store._entries, "manifest empty in child"
        entry = next(iter(store._entries.values()))
        blob = os.path.join(store.directory, entry["file"])
        doc = pickle.loads(open(blob, "rb").read())
        assert isinstance(doc["blob"], bytes)
        print("OK", len(store._entries))
    """)
    out = subprocess.run([sys.executable, "-c", child],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.startswith("OK 1")


def test_enable_is_idempotent_and_disable_restores(tmp_path):
    try:
        s1 = enable_artifact_store(str(tmp_path / "a"))
        s2 = enable_artifact_store(str(tmp_path / "a"))
        assert s1 is s2
    finally:
        disable_artifact_store()
    from deap_tpu.support.artifacts import active_store
    assert active_store() is None


def test_journal_kinds_documented():
    """Drift gate: every journal kind this module writes is in the
    telemetry doc's kind table (mirrors the SLO_JOURNAL_KINDS gate)."""
    doc = open(os.path.join(DOCS, "advanced", "telemetry.md")).read()
    for kind in ARTIFACT_JOURNAL_KINDS:
        assert f"`{kind}`" in doc, f"{kind} missing from telemetry.md"
