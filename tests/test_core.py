"""Core semantics tests — counterpart of the reference's creator/Fitness
unit tests (deap/tests/test_creator.py, base.py:209-250 semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu.core import (
    FitnessSpec,
    Population,
    Toolbox,
    dominates,
    lex_gt,
    lex_sort_desc,
)
from deap_tpu.core.population import concat, gather, init_population


def test_wvalues_sign_convention():
    spec = FitnessSpec((-1.0, 2.0))
    w = spec.wvalues(jnp.array([3.0, 4.0]))
    np.testing.assert_allclose(w, [-3.0, 8.0])


def test_dominates_matches_reference_semantics():
    # minimisation on both objectives: weights (-1, -1)
    spec = FitnessSpec((-1.0, -1.0))
    a = spec.wvalues(jnp.array([1.0, 2.0]))
    b = spec.wvalues(jnp.array([2.0, 2.0]))
    assert bool(dominates(a, b))
    assert not bool(dominates(b, a))
    assert not bool(dominates(a, a))  # equal never dominates


def test_dominance_matrix_broadcast():
    spec = FitnessSpec((-1.0, -1.0))
    vals = jnp.array([[1.0, 1.0], [2.0, 2.0], [1.0, 3.0]])
    w = vals * spec.warray
    m = dominates(w[:, None], w[None, :])
    expected = np.array(
        [[False, True, True], [False, False, False], [False, False, False]]
    )
    np.testing.assert_array_equal(np.asarray(m), expected)


def test_lexicographic_compare():
    # reference compares wvalues tuples with > (base.py:234-250)
    a = jnp.array([1.0, 5.0])
    b = jnp.array([1.0, 4.0])
    c = jnp.array([2.0, 0.0])
    assert bool(lex_gt(a, b))
    assert not bool(lex_gt(b, a))
    assert bool(lex_gt(c, a))
    assert not bool(lex_gt(a, a))


def test_lex_sort_desc_stable_and_primary_first():
    w = jnp.array([[1.0, 0.0], [2.0, 0.0], [2.0, 1.0], [0.0, 9.0]])
    order = lex_sort_desc(w)
    np.testing.assert_array_equal(np.asarray(order), [2, 1, 0, 3])


def test_population_roundtrip_and_masked_fitness():
    key = jax.random.key(0)
    spec = FitnessSpec((1.0,))
    pop = init_population(
        key, 8, lambda k: jax.random.bernoulli(k, 0.5, (10,)), spec
    )
    assert pop.size == 8
    assert not bool(pop.valid.any())

    vals = jnp.arange(8.0)[:, None]
    pop = pop.with_fitness(vals)
    assert bool(pop.valid.all())
    assert int(pop.best_index()) == 7

    # invalidate half, masked re-assign only touches invalid rows
    mask = jnp.arange(8) < 4
    pop = pop.invalidate(mask)
    assert int(pop.valid.sum()) == 4
    pop2 = pop.with_fitness(jnp.full((8, 1), 100.0), mask=~pop.valid)
    np.testing.assert_allclose(np.asarray(pop2.fitness[:4, 0]), 100.0)
    np.testing.assert_allclose(np.asarray(pop2.fitness[4:, 0]), np.arange(4.0, 8.0))
    assert bool(pop2.valid.all())


def test_invalid_rows_sort_last_and_never_dominate():
    spec = FitnessSpec((1.0,))
    pop = Population(
        genomes=jnp.zeros((3, 2)),
        fitness=jnp.array([[1.0], [99.0], [2.0]]),
        valid=jnp.array([True, False, True]),
        spec=spec,
    )
    assert int(pop.best_index()) == 2
    w = pop.wvalues
    assert not bool(dominates(w[1], w[0]))


def test_gather_and_concat():
    spec = FitnessSpec((1.0,))
    pop = Population(
        genomes={"x": jnp.arange(6.0).reshape(3, 2)},
        fitness=jnp.arange(3.0)[:, None],
        valid=jnp.ones(3, bool),
        extras={"s": jnp.arange(3.0)},
        spec=spec,
    )
    sub = gather(pop, jnp.array([2, 0]))
    np.testing.assert_allclose(np.asarray(sub.genomes["x"][0]), [4.0, 5.0])
    np.testing.assert_allclose(np.asarray(sub.extras["s"]), [2.0, 0.0])
    both = concat([pop, sub])
    assert both.size == 5


def test_population_is_jittable_pytree():
    spec = FitnessSpec((-1.0,))

    @jax.jit
    def step(pop):
        return pop.with_fitness(pop.genomes.sum(-1, keepdims=True))

    pop = Population(
        genomes=jnp.ones((4, 3)),
        fitness=jnp.zeros((4, 1)),
        valid=jnp.zeros(4, bool),
        spec=spec,
    )
    out = step(pop)
    np.testing.assert_allclose(np.asarray(out.fitness[:, 0]), 3.0)
    # best under minimisation is any row (all equal) — smoke the wvalues sign
    assert float(out.wvalues[0, 0]) == -3.0


def test_toolbox_register_unregister_decorate():
    tb = Toolbox()

    def mate(a, b, scale=1.0):
        """docstring survives"""
        return (a + b) * scale

    tb.register("mate", mate, scale=2.0)
    assert tb.mate.__name__ == "mate"
    assert tb.mate.__doc__ == "docstring survives"
    assert tb.mate(1, 2) == 6.0
    assert tb.mate(1, 2, scale=1.0) == 3.0

    def double_result(fn):
        def wrapper(*args, **kw):
            return 2 * fn(*args, **kw)
        return wrapper

    tb.decorate("mate", double_result)
    assert tb.mate(1, 2) == 12.0  # bound scale=2.0 preserved, then doubled

    tb.unregister("mate")
    assert not hasattr(tb, "mate")


def test_toolbox_defaults():
    tb = Toolbox()
    assert list(tb.map(lambda x: x + 1, [1, 2])) == [2, 3]
    assert tb.clone(5) == 5
