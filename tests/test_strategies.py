"""Strategy-engine tests: CMA-ES family, DE, PSO, PBIL, EMNA.

Quality-threshold integration tests with fixed PRNG keys, the
reference's signature pattern (deap/tests/test_algorithms.py:52-186;
SURVEY.md §4.1): run the full optimiser, assert solution quality.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import algorithms, benchmarks
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import Population, init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.ops import uniform_genome
from deap_tpu.strategies import (
    DifferentialEvolution,
    EMNA,
    PBIL,
    PSO,
    Strategy,
    StrategyMultiObjective,
    StrategyOnePlusLambda,
    hypervolume_contributions_2d,
)


# ------------------------------------------------------------------ CMA-ES ----

def test_cma_sphere_converges():
    """CMA-ES on sphere n=5, 100 gens → best < 1e-8 (the reference's
    quality gate, test_algorithms.py:53-66)."""
    N = 5
    strat = Strategy(centroid=[5.0] * N, sigma=5.0, lambda_=20,
                     spec=FitnessSpec((-1.0,)))
    tb = Toolbox()
    tb.register("evaluate", jax.vmap(benchmarks.sphere))
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    state, logbook, hof = algorithms.ea_generate_update(
        jax.random.key(7), strat.initial_state(), tb, ngen=100,
        spec=strat.spec, halloffame_size=1)
    best = float(hof.fitness[0, 0])
    assert best < 1e-8
    assert np.isfinite(np.asarray(state.C)).all()


def test_cma_rosenbrock_makes_progress():
    N = 8
    strat = Strategy(centroid=[0.0] * N, sigma=0.5, lambda_=32)
    tb = Toolbox()
    tb.register("evaluate", jax.vmap(benchmarks.rosenbrock))
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    _, _, hof = algorithms.ea_generate_update(
        jax.random.key(3), strat.initial_state(), tb, ngen=150,
        spec=strat.spec, halloffame_size=1)
    assert float(hof.fitness[0, 0]) < 1.0


def test_cma_one_plus_lambda_sphere():
    """(1+λ)-CMA-ES converges on the sphere (cma.py:208-325)."""
    N = 5
    parent = jnp.full((N,), 2.0)
    strat = StrategyOnePlusLambda(
        parent, benchmarks.sphere(parent), sigma=1.0, lambda_=8)
    tb = Toolbox()
    tb.register("evaluate", jax.vmap(benchmarks.sphere))
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    state, _, _ = algorithms.ea_generate_update(
        jax.random.key(11), strat.initial_state(), tb, ngen=300,
        spec=strat.spec)
    best = float(-state.parent_w[0])  # weighted max convention, weight -1
    assert best < 1e-6


# --------------------------------------------------------------- MO-CMA-ES ----

def test_hypervolume_contributions_2d_matches_leave_one_out():
    """Device 2-D contributions == leave-one-out of the host WFG HV, on a
    mutually non-dominated front (the kernel's contract: it is applied to
    fronts produced by nd-sort, where no member dominates another)."""
    from deap_tpu.native import hypervolume

    rng = np.random.default_rng(5)
    x = np.sort(rng.uniform(0.2, 1.0, size=8).astype(np.float32))
    y = np.sort(rng.uniform(0.2, 1.0, size=8).astype(np.float32))[::-1]
    pts = np.stack([x, y.copy()], axis=1)  # descending y vs ascending x
    w = jnp.asarray(pts)
    ref = jnp.asarray([0.0, 0.0], jnp.float32)
    contrib = np.asarray(hypervolume_contributions_2d(
        w, jnp.ones(8, bool), ref))
    # host leave-one-out (minimisation form)
    pts_min = -pts
    ref_min = np.asarray([0.0, 0.0])
    total = hypervolume(pts_min, ref_min)
    for i in range(8):
        excl = total - hypervolume(np.delete(pts_min, i, axis=0), ref_min)
        assert contrib[i] == pytest.approx(excl, rel=1e-4, abs=1e-5)


def test_mo_cma_zdt1_hypervolume():
    """MO-CMA-ES on ZDT1 reaches hypervolume > 116 of ref [11, 11]
    (test_algorithms.py:119-186, threshold at :183-186)."""
    from deap_tpu.native import hypervolume

    MU, NDIM = 16, 5
    rng = np.random.default_rng(128)
    x0 = rng.uniform(0.0, 1.0, size=(MU, NDIM)).astype(np.float32)
    f0 = np.asarray(jax.vmap(benchmarks.zdt1)(jnp.asarray(x0)))
    strat = StrategyMultiObjective(
        x0, f0, sigma=0.05, mu=MU, lambda_=MU,
        spec=FitnessSpec((-1.0, -1.0)))
    tb = Toolbox()
    tb.register("evaluate",
                lambda g: jax.vmap(benchmarks.zdt1)(jnp.clip(g["x"], 0, 1)))
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    state, _, _ = algorithms.ea_generate_update(
        jax.random.key(128), strat.initial_state(), tb, ngen=500,
        spec=strat.spec)
    front = np.asarray(jax.vmap(benchmarks.zdt1)(jnp.clip(state.x, 0, 1)))
    # validity: ZDT1 objectives within the reference's asserted bounds
    assert (front[:, 0] >= 0).all() and (front[:, 0] <= 1).all()
    hv = hypervolume(front, np.array([11.0, 11.0]))
    assert hv > 116.0


# ---------------------------------------------------------------------- DE ----

def test_de_sphere():
    """DE/rand/1/bin on sphere n=10 (examples/de/basic.py config)."""
    NDIM, MU = 10, 300
    de = DifferentialEvolution(jax.vmap(benchmarks.sphere), F=1.0, CR=0.25)
    pop = init_population(
        jax.random.key(2), MU, uniform_genome(NDIM, -3.0, 3.0),
        FitnessSpec((-1.0,)))
    pop, traj = de.run(jax.random.key(42), pop, ngen=200)
    best = float(-jnp.max(pop.wvalues[:, 0]))
    assert best < 1e-2
    # greedy replacement ⇒ monotone best trajectory
    assert bool(jnp.all(jnp.diff(traj) >= 0))


# --------------------------------------------------------------------- PSO ----

def test_pso_h1():
    """PSO on the h1 maximisation landscape (examples/pso/basic.py:
    pop=5 is tiny; use 20 particles, target near the optimum of 2)."""
    pso = PSO(jax.vmap(benchmarks.h1), phi1=2.0, phi2=2.0, smin=0.001, smax=3.0,
              spec=FitnessSpec((1.0,)))
    s = pso.init(jax.random.key(9), 20, 2, pmin=-6.0, pmax=6.0,
                 smin=-3.0, smax=3.0)
    s, traj = pso.run(jax.random.key(10), s, ngen=1000)
    assert float(s.gbest_w[0]) > 1.6
    assert bool(jnp.all(jnp.diff(traj) >= 0))  # gbest is monotone


# --------------------------------------------------------------------- EDA ----

def test_pbil_onemax():
    """PBIL solves 50-bit OneMax (examples/eda/pbil.py config)."""
    pbil = PBIL(ndim=50, learning_rate=0.3, mut_prob=0.1, mut_shift=0.05,
                lambda_=20)
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1))
    tb.register("generate", pbil.generate)
    tb.register("update", pbil.update)
    _, _, hof = algorithms.ea_generate_update(
        jax.random.key(1), pbil.initial_state(jax.random.key(2)), tb,
        ngen=50, spec=pbil.spec, halloffame_size=1)
    assert float(hof.fitness[0, 0]) >= 45.0


def test_emna_sphere():
    """EMNA_global on sphere n=30 (examples/eda/emna.py config)."""
    N, LAMBDA = 30, 1000
    emna = EMNA(centroid=[5.0] * N, sigma=5.0, mu=LAMBDA // 4,
                lambda_=LAMBDA)
    tb = Toolbox()
    tb.register("evaluate", jax.vmap(benchmarks.sphere))
    tb.register("generate", emna.generate)
    tb.register("update", emna.update)
    _, _, hof = algorithms.ea_generate_update(
        jax.random.key(4), emna.initial_state(), tb, ngen=150,
        spec=emna.spec, halloffame_size=1)
    assert float(hof.fitness[0, 0]) < 1e-3


def test_cmaes_lazy_eigen_gap():
    """Hansen's lazy eigenupdate (eigen_gap > 1): the basis refreshes
    only every gap generations — between refreshes B/diagD are carried
    unchanged while C keeps updating — and the sphere quality gate
    (best < 1e-8 in 100 gens, deap/tests/test_algorithms.py:53-66)
    still holds. gap=1 is the reference's every-generation behavior."""
    import jax
    from jax import lax

    from deap_tpu.benchmarks import sphere
    from deap_tpu.strategies.cma import Strategy

    ev = jax.vmap(sphere)

    with pytest.raises(ValueError, match="eigen_gap"):
        Strategy(jnp.full(5, 5.0), sigma=0.5, eigen_gap=0)

    strat = Strategy(jnp.full(5, 5.0), sigma=0.5, lambda_=20, eigen_gap=4)
    state = strat.initial_state()

    # staleness semantics: non-refresh generations carry B unchanged
    key = jax.random.key(3)
    st = state
    bases = []
    for i in range(4):
        pop = strat.generate(jax.random.fold_in(key, i), st)
        st = strat.update(st, pop, ev(pop))
        bases.append(np.asarray(st.B))
    # counts run 1,2,3,4 → only count=4 (i=3) refreshes
    assert np.array_equal(bases[0], np.asarray(state.B))
    assert np.array_equal(bases[1], bases[0])
    assert np.array_equal(bases[2], bases[1])
    assert not np.array_equal(bases[3], bases[2])

    @jax.jit
    def run(key, state):
        def step(st, k):
            pop = strat.generate(k, st)
            vals = ev(pop)
            return strat.update(st, pop, vals), jnp.min(vals)
        return lax.scan(step, state, jax.random.split(key, 100))

    _, best = run(jax.random.key(128), strat.initial_state())
    assert float(best.min()) < 1e-8
