"""DEAP-compatible CPU/list backend.

The tensor framework cannot represent arbitrary Python-object
individuals (dicts, sets, user classes — SURVEY.md §7.3); the reference
serves them through ``creator`` + list-based operators behind the
``Toolbox`` seam. This package is that seam's CPU side, written fresh
for modern Python against the reference's *documented semantics*
(weights/wvalues compare, clone=deepcopy, map as the distribution
boundary):

- :mod:`deap_tpu.compat.creator` — runtime type factory.
- :mod:`deap_tpu.compat.base` — ``Fitness`` and ``Toolbox``.
- :mod:`deap_tpu.compat.tools` — list operators + support objects.
- :mod:`deap_tpu.compat.algorithms` — the four generational loops over
  lists of individuals.
- :mod:`deap_tpu.compat.gp` — list-based genetic programming
  (PrimitiveTree/PrimitiveSet/compile without eval).
- :mod:`deap_tpu.compat.benchmarks` — the problem library with list
  individuals in / fitness tuples out (+ ``.binary``, ``.gp``,
  ``.tools``, and a per-evaluation ``.movingpeaks.MovingPeaks``).
- :func:`jax_map` — the bridge the north-star names: register a
  jax-backed ``map`` so ``toolbox.map(toolbox.evaluate, invalids)``
  dispatches ONE batched, jit-compiled evaluation over a device tensor
  while individuals stay Python lists.
"""

from deap_tpu.compat import (
    algorithms,
    base,
    benchmarks,
    cma,
    creator,
    gp,
    tools,
)
from deap_tpu.compat.bridge import jax_map

__all__ = ["algorithms", "base", "benchmarks", "cma", "creator", "gp",
           "tools", "jax_map"]
