"""The reference's ``deap.cma`` ask-tell API over the tensor engines.

Counterpart of /root/reference/deap/cma.py for list-individual programs:
``Strategy`` (cma.py:30-205), ``StrategyOnePlusLambda`` (cma.py:208-325)
and ``StrategyMultiObjective`` (cma.py:328-547) with the reference's
protocol — ``generate(ind_init) -> list`` and ``update(population)``,
driven by ``compat.algorithms.eaGenerateUpdate``. The math runs in
:mod:`deap_tpu.strategies.cma` (device tensors, jit-able); these
wrappers only materialise individuals and read fitnesses back.

Minimisation/maximisation direction is taken from the individuals'
``fitness.weights`` on first contact, exactly like the reference, which
sorts by the weighted fitness (cma.py:130).
"""

from __future__ import annotations

import random

import numpy as np

from deap_tpu.core.fitness import FitnessSpec

__all__ = ["Strategy", "StrategyOnePlusLambda", "StrategyMultiObjective"]


def _key():
    import jax

    return jax.random.key(random.getrandbits(32))


def _values(population) -> np.ndarray:
    return np.asarray([ind.fitness.values for ind in population],
                      np.float32)


def _genomes(population) -> np.ndarray:
    return np.asarray([list(ind) for ind in population], np.float32)


def _spec_of(ind) -> FitnessSpec:
    return FitnessSpec(tuple(ind.fitness.weights))


class Strategy:
    """Hansen CMA-ES with the reference's constructor keywords
    (``lambda_``, ``mu``, ``weights``, ``cmatrix``, and the learning
    rates, cma.py:41-78)."""

    def __init__(self, centroid, sigma, **params):
        from deap_tpu.strategies.cma import Strategy as Impl

        self._impl = Impl(centroid, sigma, **params)
        self._state = self._impl.initial_state()
        self._spec_set = "spec" in params
        self.update_count = 0

    # -- attribute surface used by the reference's examples (cma_plotting)
    @property
    def centroid(self):
        return np.asarray(self._state.centroid)

    @property
    def sigma(self):
        return float(self._state.sigma)

    @property
    def C(self):
        return np.asarray(self._state.C)

    @property
    def B(self):
        return np.asarray(self._state.B)

    @property
    def diagD(self):
        return np.asarray(self._state.diagD)

    @property
    def ps(self):
        return np.asarray(self._state.ps)

    @property
    def pc(self):
        return np.asarray(self._state.pc)

    @property
    def lambda_(self):
        return self._impl.lambda_

    @property
    def mu(self):
        return self._impl.mu

    def generate(self, ind_init):
        """λ individuals around the centroid (cma.py:111-121)."""
        x = np.asarray(self._impl.generate(_key(), self._state))
        return [ind_init(row) for row in x]

    def update(self, population):
        """Paths/covariance/step-size update from the evaluated
        offspring (cma.py:123-171)."""
        if not self._spec_set:
            self._impl.spec = _spec_of(population[0])
            self._spec_set = True
        import jax.numpy as jnp

        self._state = self._impl.update(
            self._state, jnp.asarray(_genomes(population)),
            jnp.asarray(_values(population)))
        self.update_count += 1


class StrategyOnePlusLambda:
    """(1+λ) CMA-ES (cma.py:208-325). ``parent`` must carry a valid
    fitness, like the reference's constructor expects."""

    def __init__(self, parent, sigma, **params):
        from deap_tpu.strategies.cma import StrategyOnePlusLambda as Impl

        params.setdefault("spec", _spec_of(parent))
        self._impl = Impl(list(parent), parent.fitness.values, sigma,
                          **params)
        self._state = self._impl.initial_state()
        self._make_parent = type(parent)

    @property
    def parent(self):
        """The current parent *with* its fitness, like the reference
        (update deepcopies the winning offspring incl. fitness,
        cma.py:300-306); raw values are recovered from the stored
        weighted fitness."""
        p = self._make_parent(np.asarray(self._state.parent))
        w = np.atleast_1d(np.asarray(self._state.parent_w))
        weights = np.asarray(self._impl.spec.weights, np.float64)
        # zero-weighted objectives are unrecoverable from wvalues (the
        # state stores values·weights); report 0.0 for those components
        vals = np.divide(w, weights, out=np.zeros_like(w, np.float64),
                         where=weights != 0)
        p.fitness.values = tuple(vals)
        return p

    @property
    def sigma(self):
        return float(self._state.sigma)

    @property
    def lambda_(self):
        return self._impl.lambda_

    def generate(self, ind_init):
        x = np.asarray(self._impl.generate(_key(), self._state))
        return [ind_init(row) for row in x]

    def update(self, population):
        import jax.numpy as jnp

        self._state = self._impl.update(
            self._state, jnp.asarray(_genomes(population)),
            jnp.asarray(_values(population)))


class StrategyMultiObjective:
    """MO-CMA-ES (cma.py:328-547): µ independent (1+1) strategies with
    indicator-based selection. Offspring remember their parent index
    internally (the reference smuggles it through ``ind._ps``,
    cma.py:408-426 — also attached here for program compatibility)."""

    def __init__(self, population, sigma, mu=None, lambda_=1, **params):
        from deap_tpu.strategies.cma import StrategyMultiObjective as Impl

        params.setdefault("spec", _spec_of(population[0]))
        self._impl = Impl(_genomes(population), _values(population),
                          sigma, mu=mu, lambda_=lambda_, **params)
        self._state = self._impl.initial_state()

    @property
    def mu(self):
        return self._impl.mu

    @property
    def lambda_(self):
        return self._impl.lambda_

    @property
    def sigmas(self):
        return np.asarray(self._state.sigmas)

    @property
    def parents(self):
        return np.asarray(self._state.x)

    def generate(self, ind_init):
        out = self._impl.generate(_key(), self._state)
        x = np.asarray(out["x"])
        parent = np.asarray(out["parent"])
        individuals = [ind_init(row) for row in x]
        for i, ind in enumerate(individuals):
            ind._ps = ("o", int(parent[i]))
        return individuals

    def update(self, population):
        """Select the next parents from ``population`` + the current
        parents and update the per-parent (1+1) strategies.

        Like the reference (cma.py:489-504), individuals tagged
        ``('p', idx)`` are accepted: the current parents are *always*
        candidates inside the tensor engine, so re-passing them is
        simply ignored here (the reference would count them twice —
        a quirk of its ``population + self.parents`` concatenation).
        The remaining ``('o', idx)`` offspring must number exactly
        ``lambda_``: the engine's selection kernel is compiled for
        fixed shapes. Drop-in programs that feed a *subset* of the
        offspring back must re-generate instead (see docs/porting.md,
        "Differences you may notice").

        Consumed offspring are re-tagged ``('p', -1)`` on the way out —
        the moral equivalent of the reference's next-``generate()``
        parent re-tagging (cma.py:408-410) done eagerly, since this
        wrapper keeps parents as state arrays, not live objects. So
        survivors from a previous generation re-passed alongside fresh
        offspring are recognised as parents (ignored), and re-calling
        update() on an already-consumed list raises instead of
        corrupting the per-parent strategies with stale indices.
        """
        import jax.numpy as jnp

        # parent indices travel on the individuals (the reference's
        # ``_ps`` tag, cma.py:500-504), so reordering the offspring
        # between generate() and update() stays correct
        try:
            offspring = [ind for ind in population if ind._ps[0] == "o"]
        except AttributeError:
            raise RuntimeError(
                "update() expects individuals produced by generate() "
                "(they carry the parent-index _ps tag)") from None
        if len(offspring) != self._impl.lambda_:
            raise RuntimeError(
                f"update() needs exactly lambda_={self._impl.lambda_} "
                f"('o', idx)-tagged offspring, got {len(offspring)} "
                "(current parents are implicit candidates and may be "
                "passed or omitted freely)")
        parent = np.asarray([ind._ps[1] for ind in offspring], np.int32)
        genomes = {"x": jnp.asarray(_genomes(offspring)),
                   "parent": jnp.asarray(parent)}
        self._state = self._impl.update(
            self._state, genomes, jnp.asarray(_values(offspring)))
        for ind in offspring:
            ind._ps = ("p", -1)
