"""Evaluation-transform decorators and MO metrics for list-individual
programs (reference benchmarks/tools.py).

Decorators are re-implemented in plain Python with the reference's
exact semantics (the tensor versions in
:mod:`deap_tpu.benchmarks.tools` transform jnp arrays and, for noise,
take explicit PRNG keys — both wrong shapes for ported programs).
Metrics convert individuals' fitness values and delegate to the tensor
implementations.
"""

from functools import wraps
from itertools import repeat

import numpy as np

from deap_tpu.benchmarks import tools as _t

__all__ = ["translate", "rotate", "scale", "noise", "bound",
           "diversity", "convergence", "hypervolume", "igd"]


class translate:
    """Shift the objective function by ``vector``: the inverse
    translation is applied to the individual (tools.py:25-62).
    Adds a ``translate`` method to the decorated function."""

    def __init__(self, vector):
        self.vector = list(vector)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            return func([v - t for v, t in zip(individual, self.vector)],
                        *args, **kwargs)
        wrapper.translate = self.translate
        return wrapper

    def translate(self, vector):
        self.vector = list(vector)


class rotate:
    """Rotate the objective function by orthogonal ``matrix``: the
    inverse rotation is applied to the individual (tools.py:64-115)."""

    def __init__(self, matrix):
        self.matrix = np.linalg.inv(np.asarray(matrix))

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            return func(list(self.matrix @ np.asarray(individual)),
                        *args, **kwargs)
        wrapper.rotate = self.rotate
        return wrapper

    def rotate(self, matrix):
        self.matrix = np.linalg.inv(np.asarray(matrix))


class scale:
    """Scale the objective function by ``factor``: the inverse factors
    are applied to the individual (tools.py:171-210)."""

    def __init__(self, factor):
        self.factor = tuple(1.0 / f for f in factor)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            return func([v * f for v, f in zip(individual, self.factor)],
                        *args, **kwargs)
        wrapper.scale = self.scale
        return wrapper

    def scale(self, factor):
        self.factor = tuple(1.0 / f for f in factor)


class noise:
    """Add noise drawn from argument-less ``noise`` function(s) to each
    objective of the wrapped evaluation (tools.py:117-168); ``None``
    leaves an objective noiseless."""

    def __init__(self, noise):
        try:
            self.rand_funcs = tuple(noise)
        except TypeError:
            self.rand_funcs = repeat(noise)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            result = func(individual, *args, **kwargs)
            return tuple(r if f is None else r + f()
                         for r, f in zip(result, self.rand_funcs))
        wrapper.noise = self.noise
        return wrapper

    def noise(self, noise):
        try:
            self.rand_funcs = tuple(noise)
        except TypeError:
            self.rand_funcs = repeat(noise)


def bound(bounds, type_):
    """Clamp-decorator stub matching the reference's surface
    (tools.py:212-254): returns the evaluation unchanged ('clip' is the
    only behaviour the reference actually implements for individuals,
    and it documents the decorator as experimental)."""
    def wrap(func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            return func(individual, *args, **kwargs)
        return wrapper
    return wrap


def _front_values(front):
    return np.asarray([ind.fitness.values for ind in front], np.float64)


def diversity(first_front, first, last):
    """Deb's NSGA-II spread Δ over a front of individuals
    (tools.py:256-276)."""
    return float(_t.diversity(_front_values(front=first_front)[:, :2],
                              first, last))


def convergence(first_front, optimal_front):
    """Mean distance from each front individual to the optimal front
    (tools.py:278-296)."""
    return float(_t.convergence(_front_values(first_front),
                                np.asarray(optimal_front, np.float64)))


def hypervolume(front, ref=None):
    """Hypervolume of a front of individuals, minimisation via
    ``-wvalues`` like the reference (tools.py:299-311); the flip and
    default-reference logic live in the tensor metric."""
    wv = np.asarray([ind.fitness.wvalues for ind in front], np.float64)
    return float(_t.hypervolume(wv, ref=ref,
                                weights=np.ones(wv.shape[-1])))


def igd(A, Z):
    """Inverse generational distance between value arrays
    (tools.py:314-320)."""
    return float(_t.igd(np.asarray(A, np.float64),
                        np.asarray(Z, np.float64)))
