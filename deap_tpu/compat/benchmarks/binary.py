"""List-individual binary benchmarks (reference benchmarks/binary.py).

``bin2float`` decodes bit-list individuals in pure Python with the
reference's grouping semantics (binary.py:20-41); the building-block
functions delegate to the tensor implementations and return plain
numbers (the reference returns bare ints here, not fitness tuples —
they are meant to be summed by windowed evaluators).
"""

from functools import wraps

import jax.numpy as jnp

from deap_tpu.benchmarks import binary as _t

__all__ = ["bin2float", "trap", "inv_trap", "chuang_f1", "chuang_f2",
           "chuang_f3", "royal_road1", "royal_road2"]


def bin2float(min_, max_, nbits):
    """Decorator: decode groups of ``nbits`` bits into floats in
    ``[min_, max_]`` and call the wrapped evaluate on the decoded list
    (binary.py:20-41). Python 3 semantics: true division, so the
    decoded values are continuous (the reference's Py2 floor-division
    quirk on malformed input is not reproduced)."""
    def wrap(function):
        @wraps(function)
        def wrapped(individual, *args, **kwargs):
            nelem = len(individual) // nbits
            div = 2 ** nbits - 1
            decoded = []
            for i in range(nelem):
                gene = 0
                for bit in individual[i * nbits:(i + 1) * nbits]:
                    gene = (gene << 1) | int(bit)
                decoded.append(min_ + gene / div * (max_ - min_))
            return function(decoded, *args, **kwargs)
        return wrapped
    return wrap


def _scalar(fn, individual, *args):
    return float(jnp.squeeze(
        fn(jnp.asarray(individual, jnp.float32), *args)))


def trap(individual):
    return _scalar(_t.trap, individual)


def inv_trap(individual):
    return _scalar(_t.inv_trap, individual)


def chuang_f1(individual):
    return (_scalar(_t.chuang_f1, individual),)


def chuang_f2(individual):
    return (_scalar(_t.chuang_f2, individual),)


def chuang_f3(individual):
    return (_scalar(_t.chuang_f3, individual),)


def royal_road1(individual, order):
    return (_scalar(_t.royal_road1, individual, order),)


def royal_road2(individual, order):
    return (_scalar(_t.royal_road2, individual, order),)
