"""Symbolic-regression target functions on plain sequences (reference
benchmarks/gp.py:18-128). These are the functions GP tries to *fit*;
like the reference they take a data point and return a bare float."""

import jax.numpy as jnp

from deap_tpu.benchmarks import gp as _t

__all__ = ["kotanchek", "salustowicz_1d", "salustowicz_2d",
           "unwrapped_ball", "rational_polynomial",
           "rational_polynomial2", "sin_cos", "ripple"]


def _wrap(fn):
    def wrapper(data):
        return float(jnp.squeeze(fn(jnp.asarray(data, jnp.float32))))
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


kotanchek = _wrap(_t.kotanchek)
salustowicz_1d = _wrap(_t.salustowicz_1d)
salustowicz_2d = _wrap(_t.salustowicz_2d)
unwrapped_ball = _wrap(_t.unwrapped_ball)
rational_polynomial = _wrap(_t.rational_polynomial)
rational_polynomial2 = _wrap(_t.rational_polynomial2)
sin_cos = _wrap(_t.sin_cos)
ripple = _wrap(_t.ripple)
