"""List-individual surface of the benchmark library — the drop-in
``from deap import benchmarks`` replacement.

The tensor functions in :mod:`deap_tpu.benchmarks` take ``[dim]``
arrays and return ``[nobj]`` arrays; reference programs
(benchmarks/__init__.py:26-688) call them with list individuals and
assign the result to ``fitness.values``. Each wrapper here accepts any
numeric sequence and returns a plain tuple of floats, so
``toolbox.register("evaluate", benchmarks.rastrigin)`` ports verbatim.

Submodules mirror the reference layout: :mod:`.binary`, :mod:`.gp`,
:mod:`.movingpeaks` (a per-evaluation ``MovingPeaks`` class — unlike
the tensor ``mp_evaluate``, peak changes here fire on the exact
evaluation count, reference movingpeaks.py:241-242), :mod:`.tools`.
"""

import random as _random
from functools import wraps as _wraps

import jax.numpy as _jnp

from deap_tpu import benchmarks as _t

from . import binary, gp, movingpeaks, tools  # noqa: F401

__all__ = [
    "rand", "plane", "sphere", "cigar", "rosenbrock", "h1", "ackley",
    "bohachevsky", "griewank", "rastrigin", "rastrigin_scaled",
    "rastrigin_skew", "schaffer", "schwefel", "himmelblau", "shekel",
    "kursawe", "schaffer_mo", "zdt1", "zdt2", "zdt3", "zdt4", "zdt6",
    "dtlz1", "dtlz2", "dtlz3", "dtlz4", "dtlz5", "dtlz6", "dtlz7",
    "fonseca", "poloni", "dent",
    "binary", "gp", "movingpeaks", "tools",
]


def _listwrap(fn):
    @_wraps(fn)
    def wrapper(individual, *args, **kwargs):
        out = fn(_jnp.asarray(individual, _jnp.float32), *args, **kwargs)
        return tuple(float(v) for v in out)
    return wrapper


def rand(individual):
    """Random-fitness "function" (benchmarks/__init__.py:26-42): like
    the reference, draws from the stdlib global ``random`` stream."""
    return (_random.random(),)


plane = _listwrap(_t.plane)
sphere = _listwrap(_t.sphere)
cigar = _listwrap(_t.cigar)
rosenbrock = _listwrap(_t.rosenbrock)
h1 = _listwrap(_t.h1)
ackley = _listwrap(_t.ackley)
bohachevsky = _listwrap(_t.bohachevsky)
griewank = _listwrap(_t.griewank)
rastrigin = _listwrap(_t.rastrigin)
rastrigin_scaled = _listwrap(_t.rastrigin_scaled)
rastrigin_skew = _listwrap(_t.rastrigin_skew)
schaffer = _listwrap(_t.schaffer)
schwefel = _listwrap(_t.schwefel)
himmelblau = _listwrap(_t.himmelblau)

kursawe = _listwrap(_t.kursawe)
schaffer_mo = _listwrap(_t.schaffer_mo)
zdt1 = _listwrap(_t.zdt1)
zdt2 = _listwrap(_t.zdt2)
zdt3 = _listwrap(_t.zdt3)
zdt4 = _listwrap(_t.zdt4)
zdt6 = _listwrap(_t.zdt6)
dtlz1 = _listwrap(_t.dtlz1)
dtlz2 = _listwrap(_t.dtlz2)
dtlz3 = _listwrap(_t.dtlz3)
dtlz4 = _listwrap(_t.dtlz4)
dtlz5 = _listwrap(_t.dtlz5)
dtlz6 = _listwrap(_t.dtlz6)
dtlz7 = _listwrap(_t.dtlz7)
fonseca = _listwrap(_t.fonseca)
poloni = _listwrap(_t.poloni)
dent = _listwrap(_t.dent)


def shekel(individual, a, c):
    """Shekel foxholes (benchmarks/__init__.py:341-361); ``a``/``c``
    may be nested lists exactly as reference programs build them."""
    out = _t.shekel(_jnp.asarray(individual, _jnp.float32),
                    _jnp.asarray(a, _jnp.float32),
                    _jnp.asarray(c, _jnp.float32))
    return tuple(float(v) for v in out)
