"""A per-evaluation ``MovingPeaks`` class for drop-in programs.

The tensor path (:mod:`deap_tpu.benchmarks.movingpeaks`) evaluates
populations in batches and fires peak changes at batch boundaries — a
documented divergence from the reference's per-evaluation counter
(PARITY.md). This class closes that gap for ported list-individual
programs: it wraps the same config/state machinery but evaluates one
individual per call, so ``nevals`` and the change trigger advance
exactly like the reference (movingpeaks.py:209-252). Error bookkeeping
is shared with the tensor path and proven identical to the reference on
frozen landscapes (tests/test_stream_parity.py).
"""

from typing import Optional

import jax
import jax.numpy as jnp

from deap_tpu.benchmarks.movingpeaks import (
    SCENARIO_1,
    SCENARIO_2,
    SCENARIO_3,
    MovingPeaksConfig,
    change_peaks,
    cone,
    function1,
    maximums as _maximums,
    mp_evaluate,
    mp_init,
    offline_error,
    sphere_peak,
)

__all__ = ["MovingPeaks", "cone", "function1", "sphere_peak",
           "SCENARIO_1", "SCENARIO_2", "SCENARIO_3"]


class MovingPeaks:
    """Drop-in dynamic landscape: ``mp = MovingPeaks(dim=5,
    **SCENARIO_2); toolbox.register("evaluate", mp)``.

    Accepts the reference's scenario keywords (npeaks, pfunc, bfunc,
    min/max_coord, min/max/uniform_height, min/max/uniform_width,
    lambda_, move/height/width_severity, period). ``pfunc`` must be one
    of this module's peak functions (``cone``, ``sphere_peak``,
    ``function1`` — the set the reference scenarios use); arbitrary
    Python peak callables are not supported on the tensor state.
    Randomness comes from an explicit ``seed`` instead of the
    reference's ``random`` module argument.
    """

    def __init__(self, dim: int, seed: int = 0, random=None, **kwargs):
        del random  # reference API compat; explicit keys instead
        kwargs.setdefault("pfunc", function1)
        self.config = MovingPeaksConfig(dim=dim, **kwargs)
        key, self._key = jax.random.split(jax.random.key(seed))
        self.state = mp_init(key, self.config)

    # -- reference surface (movingpeaks.py:182-252) --------------------
    @property
    def nevals(self) -> int:
        return int(self.state.nevals)

    def _peak_own_values(self):
        """Each peak's value at its own position against itself only —
        ``pfunc(pos, pos, h, w)`` like the reference (movingpeaks.py:
        190, 204). Equal to the raw height for height-valued peak
        functions (cone, function1) but NOT for sphere_peak, whose own
        value is 0."""
        import numpy as np

        cfg, st = self.config, self.state
        own = jax.vmap(lambda p, h, w: cfg.pfunc(
            p, p[None, :], h[None], w[None])[0])(
            st.position, st.height, st.width)
        return np.asarray(own)

    def globalMaximum(self):
        """(value, position) of the best peak by its *own* value
        ``pfunc(pos, pos, h, w)`` (movingpeaks.py:182-191), which
        ignores basis/neighbour interference here."""
        import numpy as np

        own = self._peak_own_values()
        i = int(own.argmax())
        pos = np.asarray(self.state.position)[i]
        return float(own[i]), [float(v) for v in pos]

    def maximums(self):
        """All *visible* peaks as (own value, position), global maximum
        first (movingpeaks.py:193-207): a peak swallowed by a higher
        neighbour (or the basis function) is dropped, and entries are
        sorted descending."""
        import numpy as np

        land, poss = _maximums(self.config, self.state)
        land = np.asarray(land)
        own = self._peak_own_values()
        poss = np.asarray(poss)
        out = [(float(own[i]), [float(v) for v in poss[i]])
               for i in range(len(own)) if own[i] >= land[i] - 1e-5]
        return sorted(out, reverse=True)

    def __call__(self, individual, count: bool = True):
        """Evaluate one individual; when ``count``, advance ``nevals``,
        the error bookkeeping, and — every ``period`` evaluations —
        the landscape, exactly like movingpeaks.py:209-244."""
        x = jnp.asarray(individual, jnp.float32)[None, :]
        if count:
            self.state, vals = mp_evaluate(self.config, self.state, x)
            return (float(vals[0, 0]),)
        # no-count path: evaluate and discard all state updates
        import dataclasses

        _, vals = mp_evaluate(dataclasses.replace(self.config, period=0),
                              self.state, x)
        return (float(vals[0, 0]),)

    def changePeaks(self) -> None:
        """Force a landscape change now (movingpeaks.py:252)."""
        self.state = change_peaks(self.config, self.state).replace(
            current_error=jnp.asarray(jnp.inf))

    def currentError(self) -> float:
        return float(self.state.current_error)

    def offlineError(self) -> float:
        return float(offline_error(self.state))
