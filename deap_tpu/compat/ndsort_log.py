"""Divide-and-conquer non-dominated sorting for the compat list path.

Independent implementation of the Jensen (2003) / Fortin-Grenier-
Parizeau (2013) divide-and-conquer non-dominated sort — the algorithm
class behind the reference's ``sortLogNondominated``
(emo.py:234-441) — written from the published recursion, not ported.
O(n log^(m-1) n) versus the O(m n²) pairwise matrix, which is the
asymptotic win the tensor kernels deliberately forgo on device (the
dominance matrix IS the TPU fast path, mo/emo.py) but which a large
CPU-side *list* population has no other way to recover.

Structure (minimisation internally; callers pass maximisation wvalues):

- points are de-duplicated (dominance is a function of the fitness
  vector, so duplicates share a rank — the reference groups unique
  fitnesses the same way) and lex-sorted once;
- ``_helper_a(S, m)`` assigns front indices within ``S`` considering
  objectives ``0..m``: 2-objective base case is a staircase sweep, the
  general case median-splits on objective ``m`` into L = {<= pivot} /
  H = {> pivot} — H cannot touch L, L's effect on H needs only
  objectives ``0..m-1`` (obj m is strictly ordered across the split);
- ``_helper_b(L, H, m)`` propagates "every l componentwise-<= h on
  objectives 0..m bumps h's front past l's" — the INCLUSIVE contract:
  strictness was established by the split that created the call, so
  pairs equal on all of ``0..m`` genuinely dominate. Its own base case
  is a one-directional sweep (L inserts, H queries).

The 2-D sweeps share a "staircase of fronts": entries ``(y, f)`` with
both coordinates ascending after pruning, so "max front among inserted
points with obj1 <= Y" is one bisect.
"""

from __future__ import annotations

import bisect
from typing import List, Sequence

import numpy as np

# pairwise fallback below this size — recursion overhead beats the
# quadratic scan on tiny sets
_SMALL = 8


class _Stairs:
    """Monotone (y ascending, front ascending) staircase supporting
    ``add(y, front)`` and ``query(Y) -> max front with y <= Y``."""

    __slots__ = ("ys", "fs")

    def __init__(self):
        self.ys: List[float] = []
        self.fs: List[int] = []

    def add(self, y: float, f: int) -> None:
        i = bisect.bisect_right(self.ys, y)
        if i and self.fs[i - 1] >= f:
            return  # an entry at y' <= y already promises f' >= f
        # drop entries this one supersedes (y' >= y with f' <= f)
        j = i
        while j < len(self.ys) and self.fs[j] <= f:
            j += 1
        self.ys[i:j] = [y]
        self.fs[i:j] = [f]

    def query(self, y: float) -> int:
        """Max front among added entries with y' <= y; -1 if none."""
        i = bisect.bisect_right(self.ys, y)
        return self.fs[i - 1] if i else -1


def _dominates_leq(a: np.ndarray, b: np.ndarray, m: int) -> bool:
    """a componentwise-<= b on objectives 0..m (inclusive contract)."""
    return bool((a[: m + 1] <= b[: m + 1]).all())


def _sweep_a(pts: np.ndarray, fronts: np.ndarray, S: Sequence[int]) -> None:
    """2-objective front assignment within lex-sorted ``S``. For
    distinct (obj0, obj1) pairs, an earlier point dominates a later one
    iff its obj1 is <= — pairs EQUAL on both coordinates don't
    interact at this level (their ordering, if any, belongs to the
    split on the higher objective that separated them), so each
    equal-key group queries before any of it is inserted."""
    st = _Stairs()
    i = 0
    while i < len(S):
        j = i
        key = (pts[S[i], 0], pts[S[i], 1])
        while j < len(S) and (pts[S[j], 0], pts[S[j], 1]) == key:
            j += 1
        for k in range(i, j):
            p = S[k]
            fronts[p] = max(fronts[p], st.query(pts[p, 1]) + 1)
        for k in range(i, j):
            st.add(pts[S[k], 1], fronts[S[k]])
        i = j


def _sweep_b(pts: np.ndarray, fronts: np.ndarray,
             L: Sequence[int], H: Sequence[int]) -> None:
    """2-objective one-directional propagation: every l with
    (obj0, obj1) componentwise-<= h bumps h past l. Inclusive, so at
    equal obj0 the L side inserts before H queries."""
    st = _Stairs()
    li = hi = 0
    while hi < len(H):
        h = H[hi]
        while li < len(L) and pts[L[li], 0] <= pts[h, 0]:
            st.add(pts[L[li], 1], fronts[L[li]])
            li += 1
        fronts[h] = max(fronts[h], st.query(pts[h, 1]) + 1)
        hi += 1


def _split_pivot(vals: np.ndarray):
    """A pivot such that {v <= pivot} and {v > pivot} are both
    non-empty, or None if all values are equal."""
    lo, hi = vals.min(), vals.max()
    if lo == hi:
        return None
    med = np.median(vals)
    if med < hi:
        return med
    # median == max (top-heavy ties): largest value strictly below it
    return vals[vals < hi].max()


def _helper_b(pts: np.ndarray, fronts: np.ndarray,
              L: List[int], H: List[int], m: int) -> None:
    if not L or not H:
        return
    if len(L) * len(H) <= _SMALL * _SMALL or (len(L) == 1 or len(H) == 1):
        for h in H:
            best = fronts[h]
            for l in L:
                if fronts[l] >= best and _dominates_leq(pts[l], pts[h], m):
                    best = fronts[l] + 1
            fronts[h] = best
        return
    if m == 1:
        _sweep_b(pts, fronts, L, H)
        return
    allv = pts[L + H, m]
    if pts[L, m].max() <= pts[H, m].min():
        _helper_b(pts, fronts, L, H, m - 1)
        return
    piv = _split_pivot(allv)
    L1 = [i for i in L if pts[i, m] <= piv]
    L2 = [i for i in L if pts[i, m] > piv]
    H1 = [i for i in H if pts[i, m] <= piv]
    H2 = [i for i in H if pts[i, m] > piv]
    _helper_b(pts, fronts, L1, H1, m)      # both low: still open on m
    _helper_b(pts, fronts, L1, H2, m - 1)  # obj m resolved: l <= piv < h
    _helper_b(pts, fronts, L2, H2, m)      # both high: still open on m
    # L2 -> H1 impossible: l > piv >= h on objective m


def _helper_a(pts: np.ndarray, fronts: np.ndarray,
              S: List[int], m: int) -> None:
    if len(S) < 2:
        return
    if len(S) == 2 or len(S) <= _SMALL:
        # pairwise on 0..m; lex order makes domination one-directional
        for bi in range(1, len(S)):
            b = S[bi]
            best = fronts[b]
            for ai in range(bi):
                a = S[ai]
                if (fronts[a] >= best
                        and _dominates_leq(pts[a], pts[b], m)
                        and not (pts[a, : m + 1]
                                 == pts[b, : m + 1]).all()):
                    best = fronts[a] + 1
            fronts[b] = best
        return
    if m == 1:
        _sweep_a(pts, fronts, S)
        return
    piv = _split_pivot(pts[S, m])
    if piv is None:  # objective m constant across S: drop it
        _helper_a(pts, fronts, S, m - 1)
        return
    L = [i for i in S if pts[i, m] <= piv]
    H = [i for i in S if pts[i, m] > piv]
    _helper_a(pts, fronts, L, m)
    _helper_b(pts, fronts, L, H, m - 1)  # strict on m across the split
    _helper_a(pts, fronts, H, m)


def nd_rank_log(wvalues: np.ndarray) -> np.ndarray:
    """Non-domination rank per row (0 = first front) of MAXIMISATION
    ``wvalues`` ([n, m]) by divide-and-conquer — same ranks as the
    dominance-matrix peel (``mo.emo.nd_rank``), different cost model."""
    w = np.asarray(wvalues, dtype=np.float64)
    if w.ndim != 2:
        raise ValueError("wvalues must be [n, m]")
    n, m = w.shape
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    pts = -w  # minimisation internally
    uniq, inv = np.unique(pts, axis=0, return_inverse=True)
    # np.unique returns rows lex-sorted on ALL objectives — exactly the
    # processing order every sweep and base case relies on
    fronts = np.zeros(len(uniq), dtype=np.int64)
    if m == 1:
        # single objective: rank = index among the distinct values
        # (uniq is ascending in the minimised objective)
        return inv.astype(np.int64)
    _helper_a(uniq, fronts, list(range(len(uniq))), m - 1)
    return fronts[inv]


def sort_log_nondominated(individuals, k, first_front_only=False):
    """Fronts-of-lists shim over :func:`nd_rank_log` matching the
    reference's return contract (emo.py:234-441): fronts covering at
    least ``k`` individuals; bare first front when
    ``first_front_only`` (emo.py:275-276)."""
    if k == 0 or not individuals:
        return []
    # float32, like every other compat MO entry point (_wvalues):
    # ranking at a higher precision than sortNondominated would let
    # sub-float32 differences split fronts the matrix path merges
    w = np.asarray([ind.fitness.wvalues for ind in individuals],
                   dtype=np.float32)
    ranks = nd_rank_log(w)
    fronts: List[list] = [[] for _ in range(int(ranks.max()) + 1)]
    for ind, r in zip(individuals, ranks):
        fronts[int(r)].append(ind)
    if first_front_only:
        return fronts[0]
    out = []
    total = 0
    for fr in fronts:
        out.append(fr)
        total += len(fr)
        if total >= k:
            break
    return out
