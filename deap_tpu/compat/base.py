"""``Fitness`` and ``Toolbox`` for list individuals.

Counterpart of /root/reference/deap/base.py. Semantics reproduced:

- ``Fitness.weights`` is a class tuple; assigned values are stored as
  ``wvalues = values * weights`` (base.py:187-198); rich comparison is
  lexicographic on wvalues (base.py:234-250); deleting ``values``
  invalidates (base.py:200-207); ``dominates`` is weighted Pareto
  dominance (base.py:209-224).
- ``Toolbox.register(alias, fn, *args, **kwargs)`` stores a partial with
  ``__name__``/``__doc__`` copied (base.py:81-91); ``decorate`` rebuilds
  the partial with decorators applied (base.py:100-122); defaults
  ``clone = deepcopy`` and ``map = builtin map`` (base.py:48-50) — the
  map alias is the distribution seam.
"""

from __future__ import annotations

import copy
from functools import partial
from operator import mul, truediv
from typing import Sequence, Tuple


class Fitness:
    """Multi-objective fitness compared in weighted space."""

    weights: Tuple[float, ...] = ()
    wvalues: Tuple[float, ...] = ()

    def __init__(self, values: Sequence[float] = ()):
        if self.weights is None:
            raise TypeError(
                f"Can't instantiate abstract {self.__class__.__name__} "
                "with abstract attribute weights.")
        if values:
            self.values = values

    def getValues(self):
        return tuple(map(truediv, self.wvalues, self.weights))

    def setValues(self, values):
        try:
            self.wvalues = tuple(map(mul, values, self.weights))
        except TypeError:
            raise TypeError(
                f"Both weights and assigned values must be a sequence "
                f"of numbers when assigning to values of "
                f"{self.__class__.__name__}.")

    def delValues(self):
        self.wvalues = ()

    values = property(getValues, setValues, delValues)

    def dominates(self, other: "Fitness", obj: slice = slice(None)) -> bool:
        """Weighted Pareto dominance: at least as good everywhere,
        strictly better somewhere."""
        not_equal = False
        for a, b in zip(self.wvalues[obj], other.wvalues[obj]):
            if a > b:
                not_equal = True
            elif a < b:
                return False
        return not_equal

    @property
    def valid(self) -> bool:
        return len(self.wvalues) != 0

    def __hash__(self):
        return hash(self.wvalues)

    def __le__(self, other):
        return self.wvalues <= other.wvalues

    def __lt__(self, other):
        return self.wvalues < other.wvalues

    def __eq__(self, other):
        return self.wvalues == other.wvalues

    def __ne__(self, other):
        return not self.__eq__(other)

    def __gt__(self, other):
        return other.__lt__(self)

    def __ge__(self, other):
        return other.__le__(self)

    def __deepcopy__(self, memo):
        copy_ = self.__class__()
        copy_.wvalues = self.wvalues
        return copy_

    def __repr__(self):
        return (f"{self.__class__.__name__}"
                f"({self.values if self.valid else tuple()})")


class Toolbox:
    """Alias registry of partially-bound callables."""

    def __init__(self):
        self.register("clone", copy.deepcopy)
        self.register("map", map)

    def register(self, alias: str, function, *args, **kwargs) -> None:
        pfunc = partial(function, *args, **kwargs)
        pfunc.__name__ = alias
        pfunc.__doc__ = function.__doc__
        if hasattr(function, "__dict__") and not isinstance(function, type):
            pfunc.__dict__.update(function.__dict__.copy())
        setattr(self, alias, pfunc)

    def unregister(self, alias: str) -> None:
        delattr(self, alias)

    def decorate(self, alias: str, *decorators) -> None:
        pfunc = getattr(self, alias)
        function, args, kwargs = pfunc.func, pfunc.args, pfunc.keywords
        for decorator in decorators:
            function = decorator(function)
        self.register(alias, function, *args, **kwargs)
