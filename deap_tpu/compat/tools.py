"""List operators and support objects for the CPU backend.

A representative set of the reference's ``deap/tools`` surface working on
plain Python sequences (the full batched library lives in
``deap_tpu.ops``/``mo``; this module exists for arbitrary-object
individuals the tensor path cannot host). Behavior follows the
reference's documented semantics; randomness uses the stdlib ``random``
module like the reference, seedable with ``random.seed``.
"""

from __future__ import annotations

import random
from bisect import bisect_right
from copy import deepcopy
from operator import attrgetter


# ---------------------------------------------------------------- init ----

def initRepeat(container, func, n):
    """container(func() for _ in range(n)) (init.py:3-25)."""
    return container(func() for _ in range(n))


def initIterate(container, generator):
    """container(generator()) (init.py:27-52)."""
    return container(generator())


def initCycle(container, seq_of_funcs, n=1):
    """container(f() for each func, cycled n times) (init.py:54-75)."""
    return container(f() for _ in range(n) for f in seq_of_funcs)


# ------------------------------------------------------------ crossover ----

def cxOnePoint(ind1, ind2):
    size = min(len(ind1), len(ind2))
    cx = random.randint(1, size - 1)
    ind1[cx:], ind2[cx:] = ind2[cx:], ind1[cx:]
    return ind1, ind2


def cxTwoPoint(ind1, ind2):
    size = min(len(ind1), len(ind2))
    a = random.randint(1, size)
    b = random.randint(1, size - 1)
    if b >= a:
        b += 1
    else:
        a, b = b, a
    ind1[a:b], ind2[a:b] = ind2[a:b], ind1[a:b]
    return ind1, ind2


def cxUniform(ind1, ind2, indpb):
    for i in range(min(len(ind1), len(ind2))):
        if random.random() < indpb:
            ind1[i], ind2[i] = ind2[i], ind1[i]
    return ind1, ind2


def cxBlend(ind1, ind2, alpha):
    for i, (x1, x2) in enumerate(zip(ind1, ind2)):
        gamma = (1.0 + 2.0 * alpha) * random.random() - alpha
        ind1[i] = (1.0 - gamma) * x1 + gamma * x2
        ind2[i] = gamma * x1 + (1.0 - gamma) * x2
    return ind1, ind2


# ------------------------------------------------------------- mutation ----

def mutGaussian(individual, mu, sigma, indpb):
    for i in range(len(individual)):
        if random.random() < indpb:
            individual[i] += random.gauss(mu, sigma)
    return (individual,)


def mutFlipBit(individual, indpb):
    for i in range(len(individual)):
        if random.random() < indpb:
            individual[i] = type(individual[i])(not individual[i])
    return (individual,)


def mutShuffleIndexes(individual, indpb):
    size = len(individual)
    for i in range(size):
        if random.random() < indpb:
            j = random.randint(0, size - 2)
            if j >= i:
                j += 1
            individual[i], individual[j] = individual[j], individual[i]
    return (individual,)


def mutUniformInt(individual, low, up, indpb):
    for i in range(len(individual)):
        if random.random() < indpb:
            individual[i] = random.randint(low, up)
    return (individual,)


# ------------------------------------------------------------ selection ----

def selRandom(individuals, k):
    return [random.choice(individuals) for _ in range(k)]


def selBest(individuals, k, fit_attr="fitness"):
    return sorted(individuals, key=attrgetter(fit_attr), reverse=True)[:k]


def selWorst(individuals, k, fit_attr="fitness"):
    return sorted(individuals, key=attrgetter(fit_attr))[:k]


def selTournament(individuals, k, tournsize, fit_attr="fitness"):
    chosen = []
    for _ in range(k):
        aspirants = selRandom(individuals, tournsize)
        chosen.append(max(aspirants, key=attrgetter(fit_attr)))
    return chosen


def selRoulette(individuals, k, fit_attr="fitness"):
    s_inds = sorted(individuals, key=attrgetter(fit_attr), reverse=True)
    fits = [getattr(ind, fit_attr).values[0] for ind in s_inds]
    total = sum(fits)
    cums = []
    acc = 0.0
    for f in fits:
        acc += f
        cums.append(acc)
    chosen = []
    for _ in range(k):
        u = random.random() * total
        chosen.append(s_inds[min(bisect_right(cums, u), len(s_inds) - 1)])
    return chosen


# -------------------------------------------------------------- support ----

class Statistics:
    """key extractor + registered reducers (support.py:154-210)."""

    def __init__(self, key=lambda obj: obj):
        self.key = key
        self.functions = {}
        self.fields = []

    def register(self, name, function, *args, **kwargs):
        self.functions[name] = lambda data: function(data, *args, **kwargs)
        self.fields.append(name)

    def compile(self, data):
        values = tuple(self.key(elem) for elem in data)
        return {name: fn(values) for name, fn in self.functions.items()}


class MultiStatistics(dict):
    """Named Statistics compiled together (support.py:212-259)."""

    @property
    def fields(self):
        return sorted(self.keys())

    def register(self, name, function, *args, **kwargs):
        for stats in self.values():
            stats.register(name, function, *args, **kwargs)

    def compile(self, data):
        return {key: stats.compile(data) for key, stats in self.items()}


class HallOfFame:
    """Bounded best-ever archive with similarity dedup
    (support.py:490-588)."""

    def __init__(self, maxsize, similar=lambda a, b: a == b):
        self.maxsize = maxsize
        self.similar = similar
        self.items = []

    def update(self, population):
        for ind in population:
            if len(self.items) == 0 and self.maxsize != 0:
                self.insert(population[0])
                continue
            if ind.fitness > self.items[-1].fitness \
                    or len(self.items) < self.maxsize:
                if not any(self.similar(ind, h) for h in self.items):
                    if len(self.items) >= self.maxsize:
                        self.remove(-1)
                    self.insert(ind)

    def insert(self, item):
        item = deepcopy(item)
        # full lexicographic order on weighted values, best first —
        # negated tuples ascending == wvalues descending
        keys = [tuple(-w for w in h.fitness.wvalues) for h in self.items]
        i = bisect_right(keys, tuple(-w for w in item.fitness.wvalues))
        self.items.insert(i, item)

    def remove(self, index):
        del self.items[index]

    def clear(self):
        del self.items[:]

    def __len__(self):
        return len(self.items)

    def __getitem__(self, i):
        return self.items[i]

    def __iter__(self):
        return iter(self.items)


# the tensor Logbook is already a plain list-of-dicts structure — shared
from deap_tpu.support.logbook import Logbook  # noqa: E402,F401


# ------------------------------------------------ multi-objective (emo) ----
# List-individual fronts for the reference's tools.emo surface: fitness
# wvalues are gathered into one array and the tensor kernels in
# deap_tpu.mo do the O(MN²) work (the bridge pattern of compat.jax_map:
# individuals stay Python objects, math runs batched).

def _wvalues(individuals):
    import numpy as np

    return np.asarray([ind.fitness.wvalues for ind in individuals],
                      dtype=np.float32)


def _mo():
    import jax
    import jax.numpy as jnp

    from deap_tpu.mo import emo
    return jax, jnp, emo


def sortNondominated(individuals, k, first_front_only=False):
    """List of non-dominated fronts covering at least ``k`` individuals
    (emo.py:53-117); ``k == 0`` returns no fronts (emo.py:70)."""
    import numpy as np

    if k == 0 or not individuals:
        return []
    jax, jnp, emo = _mo()
    max_rank = 1 if first_front_only else None
    ranks = np.asarray(emo.nd_rank(jnp.asarray(_wvalues(individuals)),
                                   max_rank=max_rank, impl="auto"))
    fronts = []
    total = 0
    for r in range(int(ranks.max()) + 1 if len(ranks) else 0):
        front = [individuals[i] for i in np.flatnonzero(ranks == r)]
        fronts.append(front)
        total += len(front)
        if first_front_only or total >= k:
            break
    return fronts


def assignCrowdingDist(individuals):
    """Attach ``fitness.crowding_dist`` per individual (emo.py:119-143).
    All individuals are treated as one front, matching the reference's
    per-front calls."""
    import numpy as np

    if not individuals:
        return
    jax, jnp, emo = _mo()
    w = jnp.asarray(_wvalues(individuals))
    dists = np.asarray(emo.crowding_distances(
        w, jnp.zeros(len(individuals), jnp.int32)))
    for ind, d in zip(individuals, dists):
        ind.fitness.crowding_dist = float(d)


def selNSGA2(individuals, k, nd="standard"):
    """NSGA-II environmental selection over list individuals
    (emo.py:15-50)."""
    import numpy as np

    jax, jnp, emo = _mo()
    idx = np.asarray(emo.sel_nsga2(
        jax.random.key(0), jnp.asarray(_wvalues(individuals)), k, nd=nd))
    return [individuals[i] for i in idx]


def selSPEA2(individuals, k):
    """SPEA2 environmental selection (emo.py:692-842)."""
    import numpy as np

    jax, jnp, emo = _mo()
    idx = np.asarray(emo.sel_spea2(
        jax.random.key(0), jnp.asarray(_wvalues(individuals)), k))
    return [individuals[i] for i in idx]


def selNSGA3(individuals, k, ref_points, nd="log"):
    """NSGA-III reference-point selection (emo.py:479-561). Randomized
    niching draws from the stdlib ``random`` stream like every other
    compat operator; ``nd`` accepted for reference parity (both sort
    variants hit the same kernel)."""
    import numpy as np

    del nd
    jax, jnp, emo = _mo()
    key = jax.random.key(random.getrandbits(32))
    idx = np.asarray(emo.sel_nsga3(
        key, jnp.asarray(_wvalues(individuals)), k,
        jnp.asarray(ref_points)))
    return [individuals[i] for i in idx]


def selTournamentDCD(individuals, k):
    """Dominance/crowding binary tournament (emo.py:145-195); requires
    ``assignCrowdingDist`` semantics, which the kernel recomputes."""
    import numpy as np

    jax, jnp, emo = _mo()
    key = jax.random.key(random.getrandbits(32))
    idx = np.asarray(emo.sel_tournament_dcd(
        key, jnp.asarray(_wvalues(individuals)), k))
    return [individuals[i] for i in idx]


def uniformReferencePoints(nobj, p=4, scaling=None):
    """Das-Dennis reference points for selNSGA3 (emo.py:664-689)."""
    import numpy as np

    _, _, emo = _mo()
    return np.asarray(emo.uniform_reference_points(nobj, p, scaling))


#: reference name (emo.py:664) — programs call tools.uniform_reference_points
uniform_reference_points = uniformReferencePoints


# ----------------------------------------------------------- migration ----

def migRing(populations, k, selection, replacement=None,
            migarray=None):
    """In-place ring migration between list demes (migration.py:4-51):
    deme i's k selected emigrants replace deme (i+1)'s k
    replacement-selected (default: same selection) individuals."""
    nbr = len(populations)
    if migarray is None:
        migarray = [(i + 1) % nbr for i in range(nbr)]
    immigrants = [selection(pop, k) for pop in populations]
    if replacement is None:
        replaced = immigrants
    else:
        replaced = [replacement(pop, k) for pop in populations]
    for from_deme, to_deme in enumerate(migarray):
        pop = populations[to_deme]
        for out_ind, in_ind in zip(replaced[to_deme],
                                   immigrants[from_deme]):
            pop[pop.index(out_ind)] = deepcopy(in_ind)


# -------------------------------------------------------- ParetoFront ----

class ParetoFront(HallOfFame):
    """Unbounded archive of the first non-dominated front
    (support.py:591-640): inserts keep only mutually non-dominated,
    non-duplicate individuals."""

    def __init__(self, similar=None):
        super().__init__(None, similar or (lambda a, b: list(a) == list(b)))

    def update(self, population):
        for ind in population:
            dominated = False
            to_remove = []
            for i, hofer in enumerate(self.items):
                if hofer.fitness.dominates(ind.fitness):
                    dominated = True
                    break
                if ind.fitness.dominates(hofer.fitness):
                    to_remove.append(i)
                elif ind.fitness == hofer.fitness and self.similar(
                        ind, hofer):
                    dominated = True
                    break
            if not dominated:
                for i in reversed(to_remove):
                    self.remove(i)
                self.insert(ind)  # insert deepcopies


# ------------------------------------------------------------ History ----

class History:
    """Genealogy tracer (support.py:21-152): decorate variation
    operators; every produced individual gets a ``history_index`` and a
    parent-index record replayable with :meth:`getGenealogy`."""

    def __init__(self):
        self.genealogy_index = 0
        self.genealogy_history: dict = {}
        self.genealogy_tree: dict = {}

    def update(self, individuals):
        parents = [getattr(ind, "history_index", None)
                   for ind in individuals]
        parents = [p for p in parents if p is not None]
        for ind in individuals:
            self.genealogy_index += 1
            ind.history_index = self.genealogy_index
            self.genealogy_history[self.genealogy_index] = deepcopy(ind)
            self.genealogy_tree[self.genealogy_index] = parents

    @property
    def decorator(self):
        def wrap(func):
            def wrapped(*args, **kwargs):
                inds = func(*args, **kwargs)
                self.update(list(inds))
                return inds
            return wrapped
        return wrap

    def getGenealogy(self, individual, max_depth=float("inf")):
        """Parent-tree dict rooted at ``individual`` (support.py:123-152).
        ``max_depth`` counts generations like the reference: 1 = the
        individual's own entry only, 0 = empty."""
        gtree = {}
        visited = set()

        def walk(index, depth):
            if index not in self.genealogy_tree:
                return
            depth += 1
            if depth > max_depth or index in visited:
                return
            visited.add(index)
            parents = self.genealogy_tree[index]
            gtree[index] = parents
            for p in parents:
                walk(p, depth)

        walk(individual.history_index, 0)
        return gtree
