"""List operators and support objects for the CPU backend.

A representative set of the reference's ``deap/tools`` surface working on
plain Python sequences (the full batched library lives in
``deap_tpu.ops``/``mo``; this module exists for arbitrary-object
individuals the tensor path cannot host). Behavior follows the
reference's documented semantics; randomness uses the stdlib ``random``
module like the reference, seedable with ``random.seed``.
"""

from __future__ import annotations

import random
from bisect import bisect_right
from copy import deepcopy
from functools import partial
from operator import attrgetter


# ---------------------------------------------------------------- init ----

def initRepeat(container, func, n):
    """container(func() for _ in range(n)) (init.py:3-25)."""
    return container(func() for _ in range(n))


def initIterate(container, generator):
    """container(generator()) (init.py:27-52)."""
    return container(generator())


def initCycle(container, seq_of_funcs, n=1):
    """container(f() for each func, cycled n times) (init.py:54-75)."""
    return container(f() for _ in range(n) for f in seq_of_funcs)


# ------------------------------------------------------------ crossover ----

def cxOnePoint(ind1, ind2):
    size = min(len(ind1), len(ind2))
    cx = random.randint(1, size - 1)
    ind1[cx:], ind2[cx:] = ind2[cx:], ind1[cx:]
    return ind1, ind2


def cxTwoPoint(ind1, ind2):
    size = min(len(ind1), len(ind2))
    a = random.randint(1, size)
    b = random.randint(1, size - 1)
    if b >= a:
        b += 1
    else:
        a, b = b, a
    ind1[a:b], ind2[a:b] = ind2[a:b], ind1[a:b]
    return ind1, ind2


def cxUniform(ind1, ind2, indpb):
    for i in range(min(len(ind1), len(ind2))):
        if random.random() < indpb:
            ind1[i], ind2[i] = ind2[i], ind1[i]
    return ind1, ind2


def cxBlend(ind1, ind2, alpha):
    for i, (x1, x2) in enumerate(zip(ind1, ind2)):
        gamma = (1.0 + 2.0 * alpha) * random.random() - alpha
        ind1[i] = (1.0 - gamma) * x1 + gamma * x2
        ind2[i] = gamma * x1 + (1.0 - gamma) * x2
    return ind1, ind2


def _bounds(bound, size, name):
    """Scalar → repeated; sequence → length-checked (crossover.py:315-323)."""
    if isinstance(bound, (int, float)):
        return [bound] * size
    if len(bound) < size:
        raise IndexError(
            "%s must be at least the size of the shorter individual: "
            "%d < %d" % (name, len(bound), size))
    return bound


def cxPartialyMatched(ind1, ind2):
    """PMX on permutations (crossover.py:94-142): swap a segment, then
    repair duplicates through the position maps so both children stay
    permutations."""
    size = min(len(ind1), len(ind2))
    pos1 = [0] * size
    pos2 = [0] * size
    for i in range(size):
        pos1[ind1[i]] = i
        pos2[ind2[i]] = i
    a = random.randint(0, size)
    b = random.randint(0, size - 1)
    if b >= a:
        b += 1
    else:
        a, b = b, a
    for i in range(a, b):
        v1, v2 = ind1[i], ind2[i]
        ind1[i], ind1[pos1[v2]] = v2, v1
        ind2[i], ind2[pos2[v1]] = v1, v2
        pos1[v1], pos1[v2] = pos1[v2], pos1[v1]
        pos2[v1], pos2[v2] = pos2[v2], pos2[v1]
    return ind1, ind2


def cxUniformPartialyMatched(ind1, ind2, indpb):
    """UPMX (crossover.py:144-186): PMX's matching swap applied per
    position with probability ``indpb`` instead of over a segment."""
    size = min(len(ind1), len(ind2))
    pos1 = [0] * size
    pos2 = [0] * size
    for i in range(size):
        pos1[ind1[i]] = i
        pos2[ind2[i]] = i
    for i in range(size):
        if random.random() < indpb:
            v1, v2 = ind1[i], ind2[i]
            ind1[i], ind1[pos1[v2]] = v2, v1
            ind2[i], ind2[pos2[v1]] = v1, v2
            pos1[v1], pos1[v2] = pos1[v2], pos1[v1]
            pos2[v1], pos2[v2] = pos2[v2], pos2[v1]
    return ind1, ind2


def cxOrdered(ind1, ind2):
    """OX on permutations (crossover.py:188-239): keep the [a, b] slice,
    fill the rest in the other parent's circular order starting after b."""
    size = min(len(ind1), len(ind2))
    a, b = random.sample(range(size), 2)
    if a > b:
        a, b = b, a
    keep1 = [True] * size  # value v of ind2 outside the slice → hole in ind1
    keep2 = [True] * size
    for i in range(size):
        if i < a or i > b:
            keep1[ind2[i]] = False
            keep2[ind1[i]] = False
    orig1, orig2 = list(ind1), list(ind2)
    k1 = k2 = b + 1
    for i in range(size):
        j = (b + 1 + i) % size
        if not keep1[orig1[j]]:
            ind1[k1 % size] = orig1[j]
            k1 += 1
        if not keep2[orig2[j]]:
            ind2[k2 % size] = orig2[j]
            k2 += 1
    for i in range(a, b + 1):
        ind1[i], ind2[i] = ind2[i], ind1[i]
    return ind1, ind2


def cxSimulatedBinary(ind1, ind2, eta):
    """SBX (crossover.py:263-289): spread factor β from one U[0,1) draw
    per gene."""
    for i, (x1, x2) in enumerate(zip(ind1, ind2)):
        rand = random.random()
        beta = 2.0 * rand if rand <= 0.5 else 1.0 / (2.0 * (1.0 - rand))
        beta **= 1.0 / (eta + 1.0)
        ind1[i] = 0.5 * ((1 + beta) * x1 + (1 - beta) * x2)
        ind2[i] = 0.5 * ((1 - beta) * x1 + (1 + beta) * x2)
    return ind1, ind2


def cxSimulatedBinaryBounded(ind1, ind2, eta, low, up):
    """Bounded SBX, Deb's NSGA-II C formulation (crossover.py:291-364):
    each gene crosses with prob ½; β_q is computed separately against
    each bound, children are clipped and randomly swapped."""
    size = min(len(ind1), len(ind2))
    low = _bounds(low, size, "low")
    up = _bounds(up, size, "up")

    def _betaq(rand, beta, eta):
        alpha = 2.0 - beta ** -(eta + 1.0)
        if rand <= 1.0 / alpha:
            return (rand * alpha) ** (1.0 / (eta + 1.0))
        return (1.0 / (2.0 - rand * alpha)) ** (1.0 / (eta + 1.0))

    for i in range(size):
        if random.random() > 0.5:
            continue
        if abs(ind1[i] - ind2[i]) <= 1e-14:
            continue
        xl, xu = low[i], up[i]
        x1, x2 = min(ind1[i], ind2[i]), max(ind1[i], ind2[i])
        rand = random.random()
        c1 = 0.5 * (x1 + x2 - _betaq(
            rand, 1.0 + 2.0 * (x1 - xl) / (x2 - x1), eta) * (x2 - x1))
        c2 = 0.5 * (x1 + x2 + _betaq(
            rand, 1.0 + 2.0 * (xu - x2) / (x2 - x1), eta) * (x2 - x1))
        c1 = min(max(c1, xl), xu)
        c2 = min(max(c2, xl), xu)
        if random.random() <= 0.5:
            ind1[i], ind2[i] = c2, c1
        else:
            ind1[i], ind2[i] = c1, c2
    return ind1, ind2


def cxMessyOnePoint(ind1, ind2):
    """Length-changing one-point crossover (crossover.py:367-383):
    independent cut points in each parent, tails swapped."""
    p1 = random.randint(0, len(ind1))
    p2 = random.randint(0, len(ind2))
    ind1[p1:], ind2[p2:] = ind2[p2:], ind1[p1:]
    return ind1, ind2


def cxESBlend(ind1, ind2, alpha):
    """Blend crossover on values AND per-gene ``strategy`` vectors
    (crossover.py:390-416), one fresh γ per value and per strategy."""
    for i, (x1, s1, x2, s2) in enumerate(
            zip(ind1, ind1.strategy, ind2, ind2.strategy)):
        gamma = (1.0 + 2.0 * alpha) * random.random() - alpha
        ind1[i] = (1.0 - gamma) * x1 + gamma * x2
        ind2[i] = gamma * x1 + (1.0 - gamma) * x2
        gamma = (1.0 + 2.0 * alpha) * random.random() - alpha
        ind1.strategy[i] = (1.0 - gamma) * s1 + gamma * s2
        ind2.strategy[i] = gamma * s1 + (1.0 - gamma) * s2
    return ind1, ind2


def cxESTwoPoint(ind1, ind2):
    """Two-point crossover mirrored on value and strategy vectors with
    the same cut points (crossover.py:419-445)."""
    size = min(len(ind1), len(ind2))
    a = random.randint(1, size)
    b = random.randint(1, size - 1)
    if b >= a:
        b += 1
    else:
        a, b = b, a
    ind1[a:b], ind2[a:b] = ind2[a:b], ind1[a:b]
    ind1.strategy[a:b], ind2.strategy[a:b] = \
        ind2.strategy[a:b], ind1.strategy[a:b]
    return ind1, ind2


# deprecated aliases kept by the reference (crossover.py:63, :448-451)
cxTwoPoints = cxTwoPoint
cxESTwoPoints = cxESTwoPoint


# ------------------------------------------------------------- mutation ----

def mutGaussian(individual, mu, sigma, indpb):
    for i in range(len(individual)):
        if random.random() < indpb:
            individual[i] += random.gauss(mu, sigma)
    return (individual,)


def mutFlipBit(individual, indpb):
    for i in range(len(individual)):
        if random.random() < indpb:
            individual[i] = type(individual[i])(not individual[i])
    return (individual,)


def mutShuffleIndexes(individual, indpb):
    size = len(individual)
    for i in range(size):
        if random.random() < indpb:
            j = random.randint(0, size - 2)
            if j >= i:
                j += 1
            individual[i], individual[j] = individual[j], individual[i]
    return (individual,)


def mutUniformInt(individual, low, up, indpb):
    for i in range(len(individual)):
        if random.random() < indpb:
            individual[i] = random.randint(low, up)
    return (individual,)


def mutPolynomialBounded(individual, eta, low, up, indpb):
    """Deb's polynomial bounded mutation (mutation.py:51-96)."""
    size = len(individual)
    low = _bounds(low, size, "low")
    up = _bounds(up, size, "up")
    for i in range(size):
        if random.random() > indpb:
            continue
        x, xl, xu = individual[i], low[i], up[i]
        rand = random.random()
        mut_pow = 1.0 / (eta + 1.0)
        if rand < 0.5:
            xy = 1.0 - (x - xl) / (xu - xl)
            val = 2.0 * rand + (1.0 - 2.0 * rand) * xy ** (eta + 1.0)
            delta_q = val ** mut_pow - 1.0
        else:
            xy = 1.0 - (xu - x) / (xu - xl)
            val = 2.0 * (1.0 - rand) + 2.0 * (rand - 0.5) * xy ** (eta + 1.0)
            delta_q = 1.0 - val ** mut_pow
        individual[i] = min(max(x + delta_q * (xu - xl), xl), xu)
    return (individual,)


def mutESLogNormal(individual, c, indpb):
    """Self-adaptive ES mutation (mutation.py:180-215): one global
    log-normal factor per call plus per-gene factors on ``strategy``,
    then a gaussian step scaled by the new strategy."""
    import math

    size = len(individual)
    t = c / math.sqrt(2.0 * math.sqrt(size))
    t0 = c / math.sqrt(2.0 * size)
    n = random.gauss(0, 1)
    t0_n = t0 * n
    for i in range(size):
        if random.random() < indpb:
            individual.strategy[i] *= math.exp(t0_n + t * random.gauss(0, 1))
            individual[i] += individual.strategy[i] * random.gauss(0, 1)
    return (individual,)


# ------------------------------------------------------------ selection ----

def selRandom(individuals, k):
    return [random.choice(individuals) for _ in range(k)]


def selBest(individuals, k, fit_attr="fitness"):
    return sorted(individuals, key=attrgetter(fit_attr), reverse=True)[:k]


def selWorst(individuals, k, fit_attr="fitness"):
    return sorted(individuals, key=attrgetter(fit_attr))[:k]


def selTournament(individuals, k, tournsize, fit_attr="fitness"):
    chosen = []
    for _ in range(k):
        aspirants = selRandom(individuals, tournsize)
        chosen.append(max(aspirants, key=attrgetter(fit_attr)))
    return chosen


def selRoulette(individuals, k, fit_attr="fitness"):
    s_inds = sorted(individuals, key=attrgetter(fit_attr), reverse=True)
    fits = [getattr(ind, fit_attr).values[0] for ind in s_inds]
    total = sum(fits)
    cums = []
    acc = 0.0
    for f in fits:
        acc += f
        cums.append(acc)
    chosen = []
    for _ in range(k):
        u = random.random() * total
        chosen.append(s_inds[min(bisect_right(cums, u), len(s_inds) - 1)])
    return chosen


def selStochasticUniversalSampling(individuals, k, fit_attr="fitness"):
    """SUS (selection.py:182-212): k evenly spaced pointers over the
    fitness-sorted cumulative distribution, one random phase."""
    s_inds = sorted(individuals, key=attrgetter(fit_attr), reverse=True)
    fits = [getattr(ind, fit_attr).values[0] for ind in s_inds]
    spacing = sum(fits) / float(k)
    start = random.uniform(0, spacing)
    chosen = []
    i, acc = 0, fits[0]
    for j in range(k):
        p = start + j * spacing
        while acc < p:
            i += 1
            acc += fits[i]
        chosen.append(s_inds[i])
    return chosen


def selDoubleTournament(individuals, k, fitness_size, parsimony_size,
                        fitness_first, fit_attr="fitness"):
    """Luke & Panait double tournament (selection.py:105-180): a fitness
    tournament composed with a probabilistic size tournament
    (``parsimony_size``/2 chance for the shorter of two) in either
    order."""
    assert 1 <= parsimony_size <= 2, \
        "Parsimony tournament size has to be in the range [1, 2]."

    def size_tournament(pool, k, select):
        chosen = []
        for _ in range(k):
            prob = parsimony_size / 2.0
            ind1, ind2 = select(pool, k=2)
            if len(ind1) > len(ind2):
                ind1, ind2 = ind2, ind1
            elif len(ind1) == len(ind2):
                prob = 0.5
            chosen.append(ind1 if random.random() < prob else ind2)
        return chosen

    def fit_tournament(pool, k, select):
        chosen = []
        for _ in range(k):
            aspirants = select(pool, k=fitness_size)
            chosen.append(max(aspirants, key=attrgetter(fit_attr)))
        return chosen

    if fitness_first:
        inner = partial(fit_tournament, select=selRandom)
        return size_tournament(individuals, k, inner)
    inner = partial(size_tournament, select=selRandom)
    return fit_tournament(individuals, k, inner)


def _lexicase(individuals, k, survivors):
    """Shared lexicase loop (selection.py:214-326): shuffle cases; keep
    ``survivors(candidates, case_values, maximizing)`` each round until
    one candidate or no cases remain; pick uniformly among the rest."""
    selected = []
    weights = individuals[0].fitness.weights
    ncases = len(individuals[0].fitness.values)
    for _ in range(k):
        candidates = individuals
        cases = list(range(ncases))
        random.shuffle(cases)
        while cases and len(candidates) > 1:
            c = cases.pop(0)
            vals = [ind.fitness.values[c] for ind in candidates]
            mask = survivors(vals, weights[c] > 0)
            candidates = [ind for ind, m in zip(candidates, mask) if m]
        selected.append(random.choice(candidates))
    return selected


def selLexicase(individuals, k):
    """Exact-best lexicase (selection.py:214-245)."""
    def survivors(vals, maximizing):
        best = max(vals) if maximizing else min(vals)
        return [v == best for v in vals]

    return _lexicase(individuals, k, survivors)


def selEpsilonLexicase(individuals, k, epsilon):
    """ε_y lexicase (selection.py:247-281): survive within a fixed ε of
    the round's best."""
    def survivors(vals, maximizing):
        if maximizing:
            thresh = max(vals) - epsilon
            return [v >= thresh for v in vals]
        thresh = min(vals) + epsilon
        return [v <= thresh for v in vals]

    return _lexicase(individuals, k, survivors)


def selAutomaticEpsilonLexicase(individuals, k):
    """λ_ε_y lexicase (selection.py:283-321): ε = median absolute
    deviation of the candidates' errors on the case."""
    def survivors(vals, maximizing):
        med = _median(vals)
        mad = _median([abs(v - med) for v in vals])
        if maximizing:
            thresh = max(vals) - mad
            return [v >= thresh for v in vals]
        thresh = min(vals) + mad
        return [v <= thresh for v in vals]

    return _lexicase(individuals, k, survivors)


def _median(vals):
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


# ----------------------------------------------------------- constraint ----

class DeltaPenalty:
    """Evaluate-decorator returning ``Δ_i - w_i·d_i`` for infeasible
    individuals (constraint.py:10-64); feasible ones evaluate normally.
    ``delta`` may be a scalar (broadcast per objective) or a sequence;
    ``distance(individual)`` likewise scalar or per-objective."""

    def __init__(self, feasibility, delta, distance=None):
        self.feasibility = feasibility
        self.delta = delta
        self.distance = distance

    def __call__(self, func):
        def wrapper(individual, *args, **kwargs):
            if self.feasibility(individual):
                return func(individual, *args, **kwargs)
            weights = individual.fitness.weights
            signs = [1.0 if w >= 0 else -1.0 for w in weights]
            deltas = _per_objective(self.delta, len(weights))
            dists = [0.0] * len(weights)
            if self.distance is not None:
                dists = _per_objective(self.distance(individual),
                                       len(weights))
            return tuple(d - s * dist
                         for d, s, dist in zip(deltas, signs, dists))

        wrapper.__name__ = getattr(func, "__name__", "evaluate")
        wrapper.__doc__ = func.__doc__
        return wrapper


class ClosestValidPenalty:
    """Evaluate-decorator scoring an infeasible individual by its
    closest valid projection, penalised by ``α·w_i·d_i(valid, x)``
    (constraint.py:68-132)."""

    def __init__(self, feasibility, feasible, alpha, distance=None):
        self.feasibility = feasibility
        self.feasible = feasible
        self.alpha = alpha
        self.distance = distance

    def __call__(self, func):
        def wrapper(individual, *args, **kwargs):
            if self.feasibility(individual):
                return func(individual, *args, **kwargs)
            f_ind = self.feasible(individual)
            f_fbl = func(f_ind, *args, **kwargs)
            weights = individual.fitness.weights
            if len(weights) != len(f_fbl):
                raise IndexError("Fitness weights and computed fitness "
                                 "are of different size.")
            signs = [1.0 if w >= 0 else -1.0 for w in weights]
            dists = [0.0] * len(weights)
            if self.distance is not None:
                dists = _per_objective(self.distance(f_ind, individual),
                                       len(weights))
            return tuple(f - s * self.alpha * d
                         for f, s, d in zip(f_fbl, signs, dists))

        wrapper.__name__ = getattr(func, "__name__", "evaluate")
        wrapper.__doc__ = func.__doc__
        return wrapper


def _per_objective(value, nobj):
    if isinstance(value, (int, float)):
        return [value] * nobj
    return list(value)


# misspelled aliases the reference keeps (constraint.py:66, :134)
DeltaPenality = DeltaPenalty
ClosestValidPenality = ClosestValidPenalty


# -------------------------------------------------------------- support ----

class Statistics:
    """key extractor + registered reducers (support.py:154-210)."""

    def __init__(self, key=lambda obj: obj):
        self.key = key
        self.functions = {}
        self.fields = []

    def register(self, name, function, *args, **kwargs):
        self.functions[name] = lambda data: function(data, *args, **kwargs)
        self.fields.append(name)

    def compile(self, data):
        values = tuple(self.key(elem) for elem in data)
        return {name: fn(values) for name, fn in self.functions.items()}


class MultiStatistics(dict):
    """Named Statistics compiled together (support.py:212-259)."""

    @property
    def fields(self):
        return sorted(self.keys())

    def register(self, name, function, *args, **kwargs):
        for stats in self.values():
            stats.register(name, function, *args, **kwargs)

    def compile(self, data):
        return {key: stats.compile(data) for key, stats in self.items()}


class HallOfFame:
    """Bounded best-ever archive with similarity dedup
    (support.py:490-588)."""

    def __init__(self, maxsize, similar=lambda a, b: a == b):
        self.maxsize = maxsize
        self.similar = similar
        self.items = []

    def update(self, population):
        for ind in population:
            if len(self.items) == 0 and self.maxsize != 0:
                self.insert(population[0])
                continue
            if ind.fitness > self.items[-1].fitness \
                    or len(self.items) < self.maxsize:
                if not any(self.similar(ind, h) for h in self.items):
                    if len(self.items) >= self.maxsize:
                        self.remove(-1)
                    self.insert(ind)

    def insert(self, item):
        item = deepcopy(item)
        # full lexicographic order on weighted values, best first —
        # negated tuples ascending == wvalues descending
        keys = [tuple(-w for w in h.fitness.wvalues) for h in self.items]
        i = bisect_right(keys, tuple(-w for w in item.fitness.wvalues))
        self.items.insert(i, item)

    def remove(self, index):
        del self.items[index]

    def clear(self):
        del self.items[:]

    def __len__(self):
        return len(self.items)

    def __getitem__(self, i):
        return self.items[i]

    def __iter__(self):
        return iter(self.items)


# the tensor Logbook is already a plain list-of-dicts structure — shared
from deap_tpu.support.logbook import Logbook  # noqa: E402,F401


# ------------------------------------------------ multi-objective (emo) ----
# List-individual fronts for the reference's tools.emo surface: fitness
# wvalues are gathered into one array and the tensor kernels in
# deap_tpu.mo do the O(MN²) work (the bridge pattern of compat.jax_map:
# individuals stay Python objects, math runs batched).

def _wvalues(individuals):
    import numpy as np

    return np.asarray([ind.fitness.wvalues for ind in individuals],
                      dtype=np.float32)


def _mo():
    import jax
    import jax.numpy as jnp

    from deap_tpu.mo import emo
    return jax, jnp, emo


def sortNondominated(individuals, k, first_front_only=False):
    """List of non-dominated fronts covering at least ``k`` individuals
    (emo.py:53-117); ``k == 0`` returns no fronts (emo.py:70).

    Rides ``emo.nd_rank``'s auto dispatch, so list populations get the
    staircase (M = 2), Fenwick-sweep (M = 3) and prefix-reduction
    (M ≥ 3) engines for free — the O(MN²) dominance matrix only below
    the measured crossovers (docs/advanced/ndsort.md). Front slicing
    is one stable argsort of the rank vector, not a per-front scan."""
    import numpy as np

    if k == 0 or not individuals:
        return []
    jax, jnp, emo = _mo()
    max_rank = 1 if first_front_only else None
    ranks = np.asarray(emo.nd_rank(jnp.asarray(_wvalues(individuals)),
                                   max_rank=max_rank, impl="auto"))
    order = np.argsort(ranks, kind="stable")
    sorted_ranks = ranks[order]
    # boundaries between consecutive rank groups, in rank order
    cuts = np.flatnonzero(np.diff(sorted_ranks)) + 1
    fronts = []
    total = 0
    for group in np.split(order, cuts):
        fronts.append([individuals[i] for i in group])
        total += len(group)
        if first_front_only or total >= k:
            break
    return fronts


def assignCrowdingDist(individuals):
    """Attach ``fitness.crowding_dist`` per individual (emo.py:119-143).
    All individuals are treated as one front, matching the reference's
    per-front calls."""
    import numpy as np

    if not individuals:
        return
    jax, jnp, emo = _mo()
    w = jnp.asarray(_wvalues(individuals))
    dists = np.asarray(emo.crowding_distances(
        w, jnp.zeros(len(individuals), jnp.int32)))
    for ind, d in zip(individuals, dists):
        ind.fitness.crowding_dist = float(d)


def selNSGA2(individuals, k, nd="standard"):
    """NSGA-II environmental selection over list individuals
    (emo.py:15-50)."""
    import numpy as np

    jax, jnp, emo = _mo()
    idx = np.asarray(emo.sel_nsga2(
        jax.random.key(0), jnp.asarray(_wvalues(individuals)), k, nd=nd))
    return [individuals[i] for i in idx]


def selSPEA2(individuals, k):
    """SPEA2 environmental selection (emo.py:692-842)."""
    import numpy as np

    jax, jnp, emo = _mo()
    idx = np.asarray(emo.sel_spea2(
        jax.random.key(0), jnp.asarray(_wvalues(individuals)), k))
    return [individuals[i] for i in idx]


def selNSGA3(individuals, k, ref_points, nd="log"):
    """NSGA-III reference-point selection (emo.py:479-561). Randomized
    niching draws from the stdlib ``random`` stream like every other
    compat operator; ``nd`` follows ``emo.sel_nsga3``'s contract (the
    reference's ``'standard'``/``'log'`` hit the auto dispatch, the
    engine names force one nd-sort implementation)."""
    import numpy as np

    jax, jnp, emo = _mo()
    key = jax.random.key(random.getrandbits(32))
    idx = np.asarray(emo.sel_nsga3(
        key, jnp.asarray(_wvalues(individuals)), k,
        jnp.asarray(ref_points), nd=nd))
    return [individuals[i] for i in idx]


def selTournamentDCD(individuals, k):
    """Dominance/crowding binary tournament (emo.py:145-195); requires
    ``assignCrowdingDist`` semantics, which the kernel recomputes."""
    import numpy as np

    jax, jnp, emo = _mo()
    key = jax.random.key(random.getrandbits(32))
    idx = np.asarray(emo.sel_tournament_dcd(
        key, jnp.asarray(_wvalues(individuals)), k))
    return [individuals[i] for i in idx]


def uniformReferencePoints(nobj, p=4, scaling=None):
    """Das-Dennis reference points for selNSGA3 (emo.py:664-689)."""
    import numpy as np

    _, _, emo = _mo()
    return np.asarray(emo.uniform_reference_points(nobj, p, scaling))


def selNSGA3WithMemory(ref_points, nd="log"):
    """Stateful NSGA-III selector (emo.py:450-476): remembers
    best/worst/extreme points between calls so intercept normalisation
    keeps history. Returns a callable ``(individuals, k) → list``."""
    import numpy as np

    del nd
    jax, jnp, emo = _mo()
    state = emo.SelNSGA3WithMemory(jnp.asarray(ref_points))

    def select(individuals, k):
        key = jax.random.key(random.getrandbits(32))
        idx = np.asarray(state(key, jnp.asarray(_wvalues(individuals)), k))
        return [individuals[i] for i in idx]

    return select


def sortLogNondominated(individuals, k, first_front_only=False):
    """Fortin-2013 divide-and-conquer nd-sort (emo.py:234-441), the
    real O(n log^(m-1) n) algorithm (compat.ndsort_log) — identical
    fronts to :func:`sortNondominated`, asymptotically cheaper than its
    O(m n²) dominance matrix for large list populations. The tensor
    path keeps the matrix/tiled kernels on device (mo/emo.py docstring:
    the matrix IS the fast path there); this variant is where the
    Python-side asymptotic win lives.

    Return-shape parity quirk preserved from the reference: with
    ``first_front_only`` this returns the bare first front
    (emo.py:275-276), while ``sortNondominated`` returns a one-element
    list of fronts (emo.py:103-117) — MO-CMA-ES indexes individuals out
    of this variant's return directly (cma.py:421-424)."""
    from deap_tpu.compat.ndsort_log import sort_log_nondominated

    return sort_log_nondominated(individuals, k, first_front_only)


def hypervolume(front, **kargs):
    """Index of the least hypervolume contributor, leave-one-out
    (tools/indicator.py:10-31); the MO-CMA-ES 'hypervolume' indicator.
    Equivalent to the reference's *intended* argmax of leave-one-out
    hypervolumes: the row whose removal costs least is the one with the
    smallest contribution.

    Note: the Python-3-converted reference is buggy here — after 2to3,
    ``numpy.argmax`` is applied to an unconsumed ``map`` object and
    always returns 0. This implementation returns the correct index, so
    drop-in MO-CMA-ES runs can follow different (better) trajectories
    than the converted reference they were ported from (see
    docs/porting.md, "Differences you may notice")."""
    import numpy as np

    wobj = np.asarray(_wvalues(front)) * -1.0
    ref = kargs.get("ref", None)
    if ref is None:
        ref = np.max(wobj, axis=0) + 1.0
    from deap_tpu.native import hv_contributions

    contribs = hv_contributions(wobj, ref)
    return int(np.argmin(contribs))


#: reference name (emo.py:664) — programs call tools.uniform_reference_points
uniform_reference_points = uniformReferencePoints


# ----------------------------------------------------------- migration ----

def migRing(populations, k, selection, replacement=None,
            migarray=None):
    """In-place ring migration between list demes (migration.py:4-51):
    deme i's k selected emigrants replace deme (i+1)'s k
    replacement-selected (default: same selection) individuals."""
    nbr = len(populations)
    if migarray is None:
        migarray = [(i + 1) % nbr for i in range(nbr)]
    immigrants = [selection(pop, k) for pop in populations]
    if replacement is None:
        replaced = immigrants
    else:
        replaced = [replacement(pop, k) for pop in populations]
    for from_deme, to_deme in enumerate(migarray):
        pop = populations[to_deme]
        for out_ind, in_ind in zip(replaced[to_deme],
                                   immigrants[from_deme]):
            pop[pop.index(out_ind)] = deepcopy(in_ind)


# -------------------------------------------------------- ParetoFront ----

class ParetoFront(HallOfFame):
    """Unbounded archive of the first non-dominated front
    (support.py:591-640): inserts keep only mutually non-dominated,
    non-duplicate individuals."""

    def __init__(self, similar=None):
        super().__init__(None, similar or (lambda a, b: list(a) == list(b)))

    def update(self, population):
        for ind in population:
            dominated = False
            to_remove = []
            for i, hofer in enumerate(self.items):
                if hofer.fitness.dominates(ind.fitness):
                    dominated = True
                    break
                if ind.fitness.dominates(hofer.fitness):
                    to_remove.append(i)
                elif ind.fitness == hofer.fitness and self.similar(
                        ind, hofer):
                    dominated = True
                    break
            if not dominated:
                for i in reversed(to_remove):
                    self.remove(i)
                self.insert(ind)  # insert deepcopies


# ------------------------------------------------------------ History ----

class History:
    """Genealogy tracer (support.py:21-152): decorate variation
    operators; every produced individual gets a ``history_index`` and a
    parent-index record replayable with :meth:`getGenealogy`."""

    def __init__(self):
        self.genealogy_index = 0
        self.genealogy_history: dict = {}
        self.genealogy_tree: dict = {}

    def update(self, individuals):
        parents = [getattr(ind, "history_index", None)
                   for ind in individuals]
        parents = [p for p in parents if p is not None]
        for ind in individuals:
            self.genealogy_index += 1
            ind.history_index = self.genealogy_index
            self.genealogy_history[self.genealogy_index] = deepcopy(ind)
            self.genealogy_tree[self.genealogy_index] = parents

    @property
    def decorator(self):
        def wrap(func):
            def wrapped(*args, **kwargs):
                inds = func(*args, **kwargs)
                self.update(list(inds))
                return inds
            return wrapped
        return wrap

    def getGenealogy(self, individual, max_depth=float("inf")):
        """Parent-tree dict rooted at ``individual`` (support.py:123-152).
        ``max_depth`` counts generations like the reference: 1 = the
        individual's own entry only, 0 = empty."""
        gtree = {}
        visited = set()

        def walk(index, depth):
            if index not in self.genealogy_tree:
                return
            depth += 1
            if depth > max_depth or index in visited:
                return
            visited.add(index)
            parents = self.genealogy_tree[index]
            gtree[index] = parents
            for p in parents:
                walk(p, depth)

        walk(individual.history_index, 0)
        return gtree
