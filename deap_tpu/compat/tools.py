"""List operators and support objects for the CPU backend.

A representative set of the reference's ``deap/tools`` surface working on
plain Python sequences (the full batched library lives in
``deap_tpu.ops``/``mo``; this module exists for arbitrary-object
individuals the tensor path cannot host). Behavior follows the
reference's documented semantics; randomness uses the stdlib ``random``
module like the reference, seedable with ``random.seed``.
"""

from __future__ import annotations

import random
from bisect import bisect_right
from copy import deepcopy
from operator import attrgetter


# ---------------------------------------------------------------- init ----

def initRepeat(container, func, n):
    """container(func() for _ in range(n)) (init.py:3-25)."""
    return container(func() for _ in range(n))


def initIterate(container, generator):
    """container(generator()) (init.py:27-52)."""
    return container(generator())


def initCycle(container, seq_of_funcs, n=1):
    """container(f() for each func, cycled n times) (init.py:54-75)."""
    return container(f() for _ in range(n) for f in seq_of_funcs)


# ------------------------------------------------------------ crossover ----

def cxOnePoint(ind1, ind2):
    size = min(len(ind1), len(ind2))
    cx = random.randint(1, size - 1)
    ind1[cx:], ind2[cx:] = ind2[cx:], ind1[cx:]
    return ind1, ind2


def cxTwoPoint(ind1, ind2):
    size = min(len(ind1), len(ind2))
    a = random.randint(1, size)
    b = random.randint(1, size - 1)
    if b >= a:
        b += 1
    else:
        a, b = b, a
    ind1[a:b], ind2[a:b] = ind2[a:b], ind1[a:b]
    return ind1, ind2


def cxUniform(ind1, ind2, indpb):
    for i in range(min(len(ind1), len(ind2))):
        if random.random() < indpb:
            ind1[i], ind2[i] = ind2[i], ind1[i]
    return ind1, ind2


def cxBlend(ind1, ind2, alpha):
    for i, (x1, x2) in enumerate(zip(ind1, ind2)):
        gamma = (1.0 + 2.0 * alpha) * random.random() - alpha
        ind1[i] = (1.0 - gamma) * x1 + gamma * x2
        ind2[i] = gamma * x1 + (1.0 - gamma) * x2
    return ind1, ind2


# ------------------------------------------------------------- mutation ----

def mutGaussian(individual, mu, sigma, indpb):
    for i in range(len(individual)):
        if random.random() < indpb:
            individual[i] += random.gauss(mu, sigma)
    return (individual,)


def mutFlipBit(individual, indpb):
    for i in range(len(individual)):
        if random.random() < indpb:
            individual[i] = type(individual[i])(not individual[i])
    return (individual,)


def mutShuffleIndexes(individual, indpb):
    size = len(individual)
    for i in range(size):
        if random.random() < indpb:
            j = random.randint(0, size - 2)
            if j >= i:
                j += 1
            individual[i], individual[j] = individual[j], individual[i]
    return (individual,)


def mutUniformInt(individual, low, up, indpb):
    for i in range(len(individual)):
        if random.random() < indpb:
            individual[i] = random.randint(low, up)
    return (individual,)


# ------------------------------------------------------------ selection ----

def selRandom(individuals, k):
    return [random.choice(individuals) for _ in range(k)]


def selBest(individuals, k, fit_attr="fitness"):
    return sorted(individuals, key=attrgetter(fit_attr), reverse=True)[:k]


def selWorst(individuals, k, fit_attr="fitness"):
    return sorted(individuals, key=attrgetter(fit_attr))[:k]


def selTournament(individuals, k, tournsize, fit_attr="fitness"):
    chosen = []
    for _ in range(k):
        aspirants = selRandom(individuals, tournsize)
        chosen.append(max(aspirants, key=attrgetter(fit_attr)))
    return chosen


def selRoulette(individuals, k, fit_attr="fitness"):
    s_inds = sorted(individuals, key=attrgetter(fit_attr), reverse=True)
    fits = [getattr(ind, fit_attr).values[0] for ind in s_inds]
    total = sum(fits)
    cums = []
    acc = 0.0
    for f in fits:
        acc += f
        cums.append(acc)
    chosen = []
    for _ in range(k):
        u = random.random() * total
        chosen.append(s_inds[min(bisect_right(cums, u), len(s_inds) - 1)])
    return chosen


# -------------------------------------------------------------- support ----

class Statistics:
    """key extractor + registered reducers (support.py:154-210)."""

    def __init__(self, key=lambda obj: obj):
        self.key = key
        self.functions = {}
        self.fields = []

    def register(self, name, function, *args, **kwargs):
        self.functions[name] = lambda data: function(data, *args, **kwargs)
        self.fields.append(name)

    def compile(self, data):
        values = tuple(self.key(elem) for elem in data)
        return {name: fn(values) for name, fn in self.functions.items()}


class MultiStatistics(dict):
    """Named Statistics compiled together (support.py:212-259)."""

    @property
    def fields(self):
        return sorted(self.keys())

    def register(self, name, function, *args, **kwargs):
        for stats in self.values():
            stats.register(name, function, *args, **kwargs)

    def compile(self, data):
        return {key: stats.compile(data) for key, stats in self.items()}


class HallOfFame:
    """Bounded best-ever archive with similarity dedup
    (support.py:490-588)."""

    def __init__(self, maxsize, similar=lambda a, b: a == b):
        self.maxsize = maxsize
        self.similar = similar
        self.items = []

    def update(self, population):
        for ind in population:
            if len(self.items) == 0 and self.maxsize != 0:
                self.insert(population[0])
                continue
            if ind.fitness > self.items[-1].fitness \
                    or len(self.items) < self.maxsize:
                if not any(self.similar(ind, h) for h in self.items):
                    if len(self.items) >= self.maxsize:
                        self.remove(-1)
                    self.insert(ind)

    def insert(self, item):
        item = deepcopy(item)
        # full lexicographic order on weighted values, best first —
        # negated tuples ascending == wvalues descending
        keys = [tuple(-w for w in h.fitness.wvalues) for h in self.items]
        i = bisect_right(keys, tuple(-w for w in item.fitness.wvalues))
        self.items.insert(i, item)

    def remove(self, index):
        del self.items[index]

    def clear(self):
        del self.items[:]

    def __len__(self):
        return len(self.items)

    def __getitem__(self, i):
        return self.items[i]

    def __iter__(self):
        return iter(self.items)


# the tensor Logbook is already a plain list-of-dicts structure — shared
from deap_tpu.support.logbook import Logbook  # noqa: E402,F401
