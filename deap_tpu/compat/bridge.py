"""The jax-backed ``toolbox.map`` — CPU individuals, TPU evaluation.

The north-star integration (BASELINE.json): keep DEAP-style list
individuals and loops, but route the fitness hot loop through one
batched, jit-compiled device evaluation by swapping the ``map`` alias —
exactly how the reference swaps in ``multiprocessing.Pool.map`` or
SCOOP's ``futures.map`` (doc/tutorials/basic/part4.rst), with the device
replacing the worker pool.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def jax_map(batched_evaluate: Callable, dtype=jnp.float32,
            to_array: Optional[Callable] = None) -> Callable:
    """Build a ``map``-compatible callable around a batched evaluator.

    :param batched_evaluate: ``genomes [n, L] -> values [n] | [n, nobj]``
        (pure jnp; jit-compiled here once, reused every generation).
    :param to_array: optional ``individuals -> [n, L] array`` converter
        for custom individual containers; default stacks sequences.

    Usage::

        toolbox.register("map", jax_map(batched_onemax))
        # algorithms' toolbox.map(toolbox.evaluate, invalid) now runs
        # ONE device program; the per-individual evaluate is bypassed.

    Returns a list of per-individual fitness tuples, so
    ``ind.fitness.values = fit`` works unchanged.
    """
    compiled = jax.jit(batched_evaluate)

    def convert(individuals):
        if to_array is not None:
            return to_array(individuals)
        return jnp.asarray(np.asarray([list(ind) for ind in individuals]),
                           dtype=dtype)

    def map_(fn, individuals, *rest):
        del fn  # the batched evaluator replaces the scalar one
        individuals = list(individuals)
        if not individuals:
            return []
        arr = convert(individuals)
        n = arr.shape[0]
        # pad the batch to a power of two: evolutionary loops produce a
        # different invalid-count every generation, and each distinct n
        # would otherwise trigger a fresh XLA compile
        padded = 1 << max(n - 1, 1).bit_length()
        if padded != n:
            fill = jnp.zeros((padded - n,) + arr.shape[1:], arr.dtype)
            arr = jnp.concatenate([arr, fill])
        values = np.asarray(compiled(arr))[:n]
        if values.ndim == 1:
            values = values[:, None]
        return [tuple(row) for row in values]

    return map_
