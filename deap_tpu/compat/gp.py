"""Genetic programming over Python-object trees — the reference GP API.

Counterpart of /root/reference/deap/gp.py for users porting list-based
GP programs verbatim: ``PrimitiveTree`` (a list of node objects in
prefix order, gp.py:63-184), ``PrimitiveSet`` with arbitrary Python
callables (gp.py:260-456), the ``genFull/genGrow/genHalfAndHalf``
generators (gp.py:519-638), subtree crossover and the mutation family
(gp.py:645-886), and ``staticLimit`` (gp.py:890-931).

One deliberate difference: the reference's ``compile`` builds a source
string and ``eval``s it (gp.py:462-487, with its >90-depth failure mode
and ``__builtins__`` hazard); here :func:`compile` walks the prefix
array with an explicit stack — same results, no codegen, no depth
limit, no eval.

This is the host/CPU path for arbitrary Python primitives. Tensor GP —
the TPU path with batched interpretation — lives in :mod:`deap_tpu.gp`;
see docs/advanced/gp.md for when to use which.
"""

from __future__ import annotations

import copy
import random
import warnings
from functools import wraps
from typing import Callable, List

__all__ = [
    "PrimitiveTree", "Primitive", "Terminal", "Ephemeral",
    "PrimitiveSet", "PrimitiveSetTyped", "compile", "compileADF",
    "genFull", "genGrow", "genHalfAndHalf",
    "cxOnePoint", "mutUniform", "mutNodeReplacement", "mutEphemeral",
    "mutInsert", "mutShrink", "staticLimit",
]


class Primitive:
    """An operator node: name, argument types, return type
    (gp.py:187-221)."""

    __slots__ = ("name", "arity", "args", "ret", "fn")

    def __init__(self, name, args, ret, fn):
        self.name = name
        self.arity = len(args)
        self.args = list(args)
        self.ret = ret
        self.fn = fn

    def __eq__(self, other):
        return (type(self) is type(other) and self.name == other.name
                and self.arity == other.arity)

    def __hash__(self):
        return hash((self.name, self.arity))


class Terminal:
    """A leaf node holding a value or input symbol (gp.py:224-244)."""

    __slots__ = ("name", "value", "ret")

    def __init__(self, name, value, ret):
        self.name = name
        self.value = value
        self.ret = ret

    arity = 0

    def __eq__(self, other):
        return type(self) is type(other) and self.name == other.name

    def __hash__(self):
        return hash(self.name)


#: ephemeral templates by name — lets trees holding lambda-backed
#: ephemerals pickle by *name* (the reference reaches the same end by
#: caching a dynamically-created class per ephemeral in the gp module,
#: gp.py:247-257 + MetaEphemeral registry)
_EPHEMERAL_REGISTRY: dict = {}


def _restore_ephemeral(name, value):
    try:
        template = _EPHEMERAL_REGISTRY[name]
    except KeyError:
        raise RuntimeError(
            f"cannot restore ephemeral constant {name!r}: its primitive "
            "set has not been built in this process — call "
            "addEphemeralConstant (rebuild the pset) before unpickling "
            "or copying individuals that use it") from None
    e = Ephemeral.__new__(Ephemeral)
    e.func = template.func
    e.name = name
    e.value = value
    e.ret = template.ret
    return e


class Ephemeral(Terminal):
    """A terminal whose value is drawn fresh per occurrence
    (gp.py:247-257)."""

    __slots__ = ("func",)

    def __init__(self, name, func, ret):
        self.func = func
        super().__init__(name, func(), ret)

    def regen(self):
        return Ephemeral(self.name, self.func, self.ret)

    def __reduce__(self):
        # the generator function itself may be a lambda; pickle the
        # (registered) name + drawn value instead
        return (_restore_ephemeral, (self.name, self.value))


class PrimitiveTree(list):
    """Prefix-ordered list of nodes (gp.py:63-184)."""

    @property
    def height(self):
        stack = [0]
        max_depth = 0
        for node in self:
            depth = stack.pop()
            max_depth = max(max_depth, depth)
            stack.extend([depth + 1] * node.arity)
        return max_depth

    @property
    def root(self):
        return self[0]

    def search_subtree(self, begin):
        """slice spanning the subtree rooted at ``begin``
        (gp.py:174-184)."""
        end = begin + 1
        total = self[begin].arity
        while total > 0:
            total += self[end].arity - 1
            end += 1
        return slice(begin, end)

    searchSubtree = search_subtree

    def __str__(self):
        """Infix rendering, same shape as the reference's printer
        (gp.py:90-104)."""
        string = ""
        stack: list = []
        for node in self:
            stack.append((node, []))
            while stack and len(stack[-1][1]) == stack[-1][0].arity:
                node, args = stack.pop()
                if node.arity:
                    string = f"{node.name}({', '.join(args)})"
                elif node.value is None:
                    string = node.name      # input argument
                else:
                    string = str(node.value)
                if not stack:
                    break
                stack[-1][1].append(string)
        return string


class PrimitiveSetTyped:
    """Typed primitive registry (gp.py:260-429) holding real callables —
    no string context, since compile never builds source."""

    def __init__(self, name, in_types, ret_type, prefix="ARG"):
        self.name = name
        self.ret = ret_type
        self.ins = list(in_types)
        self.arguments: List[str] = []
        self.primitives: dict = {}
        self.terminals: dict = {}
        self.mapping: dict = {}
        for i, t in enumerate(self.ins):
            arg = f"{prefix}{i}"
            self.arguments.append(arg)
            self._add_terminal(Terminal(arg, None, t))

    # ------------------------------------------------------------ builders --

    def _add_primitive(self, prim):
        self.primitives.setdefault(prim.ret, []).append(prim)
        self.mapping[prim.name] = prim

    def _add_terminal(self, term):
        self.terminals.setdefault(term.ret, []).append(term)
        self.mapping[term.name] = term

    def addPrimitive(self, fn, in_types, ret_type, name=None):
        name = name or fn.__name__
        self._add_primitive(Primitive(name, in_types, ret_type, fn))

    def addTerminal(self, value, ret_type, name=None):
        if name is None:
            name = repr(value)
        self._add_terminal(Terminal(name, value, ret_type))

    def addEphemeralConstant(self, name, func, ret_type):
        existing = _EPHEMERAL_REGISTRY.get(name)
        if existing is not None and existing.func is not func:
            # the name is the pickle/copy identity (the reference raises
            # here, gp.py:402-408; warn-and-overwrite keeps the common
            # rebuild-the-pset-with-a-fresh-lambda workflow alive while
            # still flagging genuine cross-pset collisions)
            warnings.warn(
                f"ephemeral constant {name!r} is being re-registered "
                "with a different function; restored/copied individuals "
                "will draw from the NEW generator. Name ephemerals "
                "uniquely across primitive sets.", RuntimeWarning)
        eph = Ephemeral(name, func, ret_type)
        _EPHEMERAL_REGISTRY[name] = eph
        self._add_terminal(eph)

    def addADF(self, adfset: "PrimitiveSetTyped"):
        """Register a callable slot for an automatically defined
        function branch (gp.py:414-423): a primitive named after
        ``adfset`` whose function is bound per-individual by
        :func:`compileADF` (``fn`` stays None here so the shared
        registry never carries one individual's compiled branch)."""
        self._add_primitive(
            Primitive(adfset.name, adfset.ins, adfset.ret, None))

    def renameArguments(self, **kwargs):
        for key, name in kwargs.items():
            if key.startswith("ARG"):
                i = int(key[3:])
                old = self.arguments[i]
                self.arguments[i] = name
                for terms in self.terminals.values():
                    for t in terms:
                        if t.name == old:
                            t.name = name
                self.mapping[name] = self.mapping.pop(old)

    @property
    def terminalRatio(self):
        n_t = sum(len(v) for v in self.terminals.values())
        n_p = sum(len(v) for v in self.primitives.values())
        return n_t / (n_t + n_p)


class PrimitiveSet(PrimitiveSetTyped):
    """Untyped set: every slot shares one type (gp.py:432-456)."""

    def __init__(self, name, arity, prefix="ARG"):
        super().__init__(name, [object] * arity, object, prefix)

    def addPrimitive(self, fn, arity, name=None):
        super().addPrimitive(fn, [object] * arity, object, name)

    def addTerminal(self, value, name=None):
        super().addTerminal(value, object, name)

    def addEphemeralConstant(self, name, func):
        super().addEphemeralConstant(name, func, object)


# ----------------------------------------------------------------- compile --

def compile(expr: PrimitiveTree, pset: PrimitiveSetTyped,
            _adfs=None) -> Callable:
    """Executable function from a tree — one iterative right-to-left
    pass with a value stack instead of the reference's source-string
    ``eval`` (gp.py:462-487): O(len(tree)) per call, no recursion, so
    no depth limit beyond memory (the reference fails past depth ~90;
    a recursive evaluator would merely move that to the interpreter's
    recursion limit). Returns ``f(*args)`` when the set has inputs,
    else the evaluated value. ``_adfs`` maps ADF names to callables
    (bound by :func:`compileADF`)."""
    arg_names = pset.arguments
    nodes = list(expr)
    adfs = _adfs or {}

    def run(*args):
        if len(args) != len(arg_names):
            raise TypeError(
                f"{pset.name} expects {len(arg_names)} arguments, "
                f"got {len(args)}")
        env = dict(zip(arg_names, args))
        stack: list = []
        for node in reversed(nodes):
            if isinstance(node, Primitive):
                vals = [stack.pop() for _ in range(node.arity)]
                fn = node.fn if node.fn is not None else adfs[node.name]
                stack.append(fn(*vals))
            elif node.value is None and node.name in env:
                stack.append(env[node.name])
            else:
                stack.append(node.value)
        return stack[0]

    if not arg_names:
        return run()
    return run


def compileADF(expr, psets) -> Callable:
    """Compile a multi-branch individual with automatically defined
    functions (gp.py:490-513): branches are compiled last-first and
    each earlier branch sees the later ones as callable primitives
    (registered via ``addADF``) — bound per individual, never written
    into the shared primitive set."""
    adfdict: dict = {}
    func = None
    for subexpr, pset in reversed(list(zip(expr, psets))):
        func = compile(subexpr, pset, _adfs=dict(adfdict))
        adfdict[pset.name] = func
    return func


# -------------------------------------------------------------- generators --

def _generate(pset, min_, max_, condition, type_=None):
    if type_ is None:
        type_ = pset.ret
    expr = []
    height = random.randint(min_, max_)
    stack = [(0, type_)]
    while stack:
        depth, type_ = stack.pop()
        if condition(height, depth):
            terms = pset.terminals.get(type_, [])
            if not terms:
                raise IndexError(
                    f"no terminal of type {type_} available")
            term = random.choice(terms)
            if isinstance(term, Ephemeral):
                term = term.regen()
            expr.append(term)
        else:
            prims = pset.primitives.get(type_, [])
            if not prims:
                # fall back to a terminal when no primitive fits (the
                # reference raises; generators guard with condition)
                terms = pset.terminals.get(type_, [])
                if not terms:
                    raise IndexError(
                        f"no primitive or terminal of type {type_}")
                term = random.choice(terms)
                if isinstance(term, Ephemeral):
                    term = term.regen()
                expr.append(term)
                continue
            prim = random.choice(prims)
            expr.append(prim)
            for arg_t in reversed(prim.args):
                stack.append((depth + 1, arg_t))
    return expr


def genFull(pset, min_, max_, type_=None):
    """Leaves all at depth in [min, max] (gp.py:546-565)."""
    return PrimitiveTree(_generate(
        pset, min_, max_, lambda h, d: d == h, type_))


def genGrow(pset, min_, max_, type_=None):
    """Leaves at varying depths (gp.py:568-589)."""
    def condition(height, depth):
        return depth == height or (
            depth >= min_ and random.random() < pset.terminalRatio)
    return PrimitiveTree(_generate(pset, min_, max_, condition, type_))


def genHalfAndHalf(pset, min_, max_, type_=None):
    """Koza ramped half-and-half (gp.py:592-608)."""
    return random.choice((genFull, genGrow))(pset, min_, max_, type_)


# -------------------------------------------------------------- variation --

def cxOnePoint(ind1, ind2):
    """Type-aware subtree swap (gp.py:645-682)."""
    if len(ind1) < 2 or len(ind2) < 2:
        return ind1, ind2
    types1: dict = {}
    types2: dict = {}
    for idx, node in enumerate(ind1[1:], 1):
        types1.setdefault(node.ret, []).append(idx)
    for idx, node in enumerate(ind2[1:], 1):
        types2.setdefault(node.ret, []).append(idx)
    common = set(types1) & set(types2)
    if not common:
        return ind1, ind2
    type_ = random.choice(list(common))
    i1 = random.choice(types1[type_])
    i2 = random.choice(types2[type_])
    s1, s2 = ind1.search_subtree(i1), ind2.search_subtree(i2)
    ind1[s1], ind2[s2] = ind2[s2], ind1[s1]
    return ind1, ind2


def mutUniform(individual, expr, pset):
    """Replace a random subtree with ``expr(pset=pset, type_=...)``
    (gp.py:743-757)."""
    index = random.randrange(len(individual))
    slice_ = individual.search_subtree(index)
    type_ = individual[index].ret
    individual[slice_] = expr(pset=pset, type_=type_)
    return (individual,)


def mutNodeReplacement(individual, pset):
    """Swap one node for another of the same arity/type
    (gp.py:760-783)."""
    index = random.randrange(len(individual))
    node = individual[index]
    if node.arity == 0:
        terms = pset.terminals.get(node.ret, [])
        if terms:
            term = random.choice(terms)
            if isinstance(term, Ephemeral):
                term = term.regen()
            individual[index] = term
    else:
        prims = [p for p in pset.primitives.get(node.ret, [])
                 if p.args == node.args]
        if prims:
            individual[index] = random.choice(prims)
    return (individual,)


def mutEphemeral(individual, mode="one"):
    """Redraw ephemeral constant values (gp.py:786-811)."""
    if mode not in ("one", "all"):
        raise ValueError("Mode must be one of 'one' or 'all'")
    ephemerals = [i for i, node in enumerate(individual)
                  if isinstance(node, Ephemeral)]
    if ephemerals:
        if mode == "one":
            ephemerals = [random.choice(ephemerals)]
        for i in ephemerals:
            individual[i] = individual[i].regen()
    return (individual,)


def mutInsert(individual, pset):
    """Insert a primitive above a random subtree; the old subtree
    becomes one argument, the rest are fresh terminals
    (gp.py:814-851)."""
    index = random.randrange(len(individual))
    node = individual[index]
    slice_ = individual.search_subtree(index)
    choices = [p for p in pset.primitives.get(node.ret, [])
               if node.ret in p.args]
    if not choices:
        return (individual,)
    new_node = random.choice(choices)
    position = random.choice(
        [i for i, t in enumerate(new_node.args) if t == node.ret])
    new_subtree: list = []
    for i, arg_type in enumerate(new_node.args):
        if i == position:
            new_subtree.extend(individual[slice_])
        else:
            terms = pset.terminals.get(arg_type, [])
            if not terms:
                return (individual,)
            term = random.choice(terms)
            if isinstance(term, Ephemeral):
                term = term.regen()
            new_subtree.append(term)
    individual[slice_.start:slice_.stop] = [new_node] + new_subtree
    return (individual,)


def mutShrink(individual, *_):
    """Replace a random primitive by one of its argument subtrees
    (gp.py:854-886). The root is never shrunk and trees at/below one
    level are returned unchanged (gp.py:862-863)."""
    if len(individual) < 3 or individual.height <= 1:
        return (individual,)
    prims = [i for i, node in enumerate(individual)
             if isinstance(node, Primitive) and i != 0]
    if not prims:
        return (individual,)
    index = random.choice(prims)
    node = individual[index]
    # pick an argument subtree whose type matches the node's return
    arg_idx = [i for i, t in enumerate(node.args) if t == node.ret]
    if not arg_idx:
        return (individual,)
    chosen = random.choice(arg_idx)
    j = index + 1
    for _ in range(chosen):
        j = individual.search_subtree(j).stop
    sub = individual[individual.search_subtree(j)]
    individual[individual.search_subtree(index)] = sub
    return (individual,)


def staticLimit(key: Callable, max_value):
    """Reject-with-parent decorator (Koza limit; gp.py:890-931)."""
    def decorator(func):
        @wraps(func)
        def wrapper(*args, **kwargs):
            keep = [copy.deepcopy(ind) for ind in args
                    if isinstance(ind, PrimitiveTree)]
            new = func(*args, **kwargs)
            out = list(new)
            for i, ind in enumerate(out):
                if isinstance(ind, PrimitiveTree) and key(ind) > max_value:
                    out[i] = copy.deepcopy(random.choice(keep))
            return tuple(out)
        return wrapper
    return decorator
