"""Genetic programming over Python-object trees — the reference GP API.

Counterpart of /root/reference/deap/gp.py for users porting list-based
GP programs verbatim: ``PrimitiveTree`` (a list of node objects in
prefix order, gp.py:63-184), ``PrimitiveSet`` with arbitrary Python
callables (gp.py:260-456), the ``genFull/genGrow/genHalfAndHalf``
generators (gp.py:519-638), subtree crossover and the mutation family
(gp.py:645-886), and ``staticLimit`` (gp.py:890-931).

One deliberate difference: the reference's ``compile`` builds a source
string and ``eval``s it (gp.py:462-487, with its >90-depth failure mode
and ``__builtins__`` hazard); here :func:`compile` walks the prefix
array with an explicit stack — same results, no codegen, no depth
limit, no eval.

This is the host/CPU path for arbitrary Python primitives. Tensor GP —
the TPU path with batched interpretation — lives in :mod:`deap_tpu.gp`;
see docs/advanced/gp.md for when to use which.
"""

from __future__ import annotations

import copy
import random
import warnings
from functools import wraps
from typing import Callable, List

__all__ = [
    "PrimitiveTree", "Primitive", "Terminal", "Ephemeral",
    "PrimitiveSet", "PrimitiveSetTyped", "compile", "compileADF",
    "genFull", "genGrow", "genHalfAndHalf", "genRamped", "generate",
    "cxOnePoint", "cxOnePointLeafBiased", "cxSemantic", "mutSemantic",
    "mutUniform", "mutNodeReplacement", "mutEphemeral",
    "mutInsert", "mutShrink", "staticLimit", "harm", "graph",
]


class Primitive:
    """An operator node: name, argument types, return type
    (gp.py:187-221)."""

    __slots__ = ("name", "arity", "args", "ret", "fn")

    def __init__(self, name, args, ret, fn):
        self.name = name
        self.arity = len(args)
        self.args = list(args)
        self.ret = ret
        self.fn = fn

    def __eq__(self, other):
        return (type(self) is type(other) and self.name == other.name
                and self.arity == other.arity)

    def __hash__(self):
        return hash((self.name, self.arity))


class Terminal:
    """A leaf node holding a value or input symbol (gp.py:224-244)."""

    __slots__ = ("name", "value", "ret")

    def __init__(self, name, value, ret):
        self.name = name
        self.value = value
        self.ret = ret

    arity = 0

    def __eq__(self, other):
        return type(self) is type(other) and self.name == other.name

    def __hash__(self):
        return hash(self.name)


#: ephemeral templates by name — lets trees holding lambda-backed
#: ephemerals pickle by *name* (the reference reaches the same end by
#: caching a dynamically-created class per ephemeral in the gp module,
#: gp.py:247-257 + MetaEphemeral registry)
_EPHEMERAL_REGISTRY: dict = {}


def _restore_ephemeral(name, value):
    try:
        template = _EPHEMERAL_REGISTRY[name]
    except KeyError:
        raise RuntimeError(
            f"cannot restore ephemeral constant {name!r}: its primitive "
            "set has not been built in this process — call "
            "addEphemeralConstant (rebuild the pset) before unpickling "
            "or copying individuals that use it") from None
    e = Ephemeral.__new__(Ephemeral)
    e.func = template.func
    e.name = name
    e.value = value
    e.ret = template.ret
    return e


class Ephemeral(Terminal):
    """A terminal whose value is drawn fresh per occurrence
    (gp.py:247-257)."""

    __slots__ = ("func",)

    def __init__(self, name, func, ret):
        self.func = func
        super().__init__(name, func(), ret)

    def regen(self):
        return Ephemeral(self.name, self.func, self.ret)

    def __reduce__(self):
        # the generator function itself may be a lambda; pickle the
        # (registered) name + drawn value instead
        return (_restore_ephemeral, (self.name, self.value))


class PrimitiveTree(list):
    """Prefix-ordered list of nodes (gp.py:63-184)."""

    @classmethod
    def from_string(cls, string, pset):
        """Parse the prefix/function-call rendering produced by
        ``str(tree)`` — e.g. ``"add(x, 3.0)"`` — back into a tree
        (gp.py:106-153):
        split on whitespace/parens/commas; names resolve through
        ``pset.mapping``, anything else must literal-eval to a constant.
        Type expectations are tracked through a queue like the
        reference, so typed sets reject mismatched strings."""
        import ast
        from collections import deque

        import re

        tokens = re.split(r"[ \t\n\r\f\v(),]", string)
        expr = []
        ret_types: deque = deque()
        for token in tokens:
            if token == "":
                continue
            type_ = ret_types.popleft() if ret_types else None
            if token in pset.mapping:
                node = pset.mapping[token]
                if (type_ is not None and isinstance(node.ret, type)
                        and isinstance(type_, type)
                        and not issubclass(node.ret, type_)):
                    raise TypeError(
                        f"Primitive {token} return type {node.ret} does "
                        f"not match the expected one: {type_}.")
                expr.append(node)
                if node.arity > 0:
                    ret_types.extendleft(reversed(node.args))
            else:
                try:
                    value = ast.literal_eval(token)
                except (ValueError, SyntaxError):
                    raise TypeError(
                        f"Unable to evaluate terminal: {token}.") from None
                if (isinstance(type_, type)
                        and not issubclass(type(value), type_)):
                    raise TypeError(
                        f"Terminal {value} type {type(value)} does not "
                        f"match the expected one: {type_}.")
                expr.append(Terminal(token, value, type_ or type(value)))
        return cls(expr)

    @property
    def height(self):
        stack = [0]
        max_depth = 0
        for node in self:
            depth = stack.pop()
            max_depth = max(max_depth, depth)
            stack.extend([depth + 1] * node.arity)
        return max_depth

    @property
    def root(self):
        return self[0]

    def search_subtree(self, begin):
        """slice spanning the subtree rooted at ``begin``
        (gp.py:174-184)."""
        end = begin + 1
        total = self[begin].arity
        while total > 0:
            total += self[end].arity - 1
            end += 1
        return slice(begin, end)

    searchSubtree = search_subtree

    def __str__(self):
        """Infix rendering, same shape as the reference's printer
        (gp.py:90-104)."""
        string = ""
        stack: list = []
        for node in self:
            stack.append((node, []))
            while stack and len(stack[-1][1]) == stack[-1][0].arity:
                node, args = stack.pop()
                if node.arity:
                    string = f"{node.name}({', '.join(args)})"
                elif node.value is None:
                    string = node.name      # input argument
                else:
                    string = str(node.value)
                if not stack:
                    break
                stack[-1][1].append(string)
        return string


class PrimitiveSetTyped:
    """Typed primitive registry (gp.py:260-429) holding real callables —
    no string context, since compile never builds source."""

    def __init__(self, name, in_types, ret_type, prefix="ARG"):
        self.name = name
        self.ret = ret_type
        self.ins = list(in_types)
        self.arguments: List[str] = []
        self.primitives: dict = {}
        self.terminals: dict = {}
        self.mapping: dict = {}
        for i, t in enumerate(self.ins):
            arg = f"{prefix}{i}"
            self.arguments.append(arg)
            self._add_terminal(Terminal(arg, None, t))

    # ------------------------------------------------------------ builders --

    def _add_primitive(self, prim):
        self.primitives.setdefault(prim.ret, []).append(prim)
        self.mapping[prim.name] = prim

    def _add_terminal(self, term):
        self.terminals.setdefault(term.ret, []).append(term)
        self.mapping[term.name] = term

    def addPrimitive(self, fn, in_types, ret_type, name=None):
        name = name or fn.__name__
        self._add_primitive(Primitive(name, in_types, ret_type, fn))

    def addTerminal(self, value, ret_type, name=None):
        if name is None:
            name = repr(value)
        self._add_terminal(Terminal(name, value, ret_type))

    def addEphemeralConstant(self, name, func, ret_type):
        existing = _EPHEMERAL_REGISTRY.get(name)
        if existing is not None and existing.func is not func:
            # the name is the pickle/copy identity (the reference raises
            # here, gp.py:402-408; warn-and-overwrite keeps the common
            # rebuild-the-pset-with-a-fresh-lambda workflow alive while
            # still flagging genuine cross-pset collisions)
            warnings.warn(
                f"ephemeral constant {name!r} is being re-registered "
                "with a different function; restored/copied individuals "
                "will draw from the NEW generator. Name ephemerals "
                "uniquely across primitive sets.", RuntimeWarning)
        eph = Ephemeral(name, func, ret_type)
        _EPHEMERAL_REGISTRY[name] = eph
        self._add_terminal(eph)

    def addADF(self, adfset: "PrimitiveSetTyped"):
        """Register a callable slot for an automatically defined
        function branch (gp.py:414-423): a primitive named after
        ``adfset`` whose function is bound per-individual by
        :func:`compileADF` (``fn`` stays None here so the shared
        registry never carries one individual's compiled branch)."""
        self._add_primitive(
            Primitive(adfset.name, adfset.ins, adfset.ret, None))

    def renameArguments(self, **kwargs):
        for key, name in kwargs.items():
            if key.startswith("ARG"):
                i = int(key[3:])
                old = self.arguments[i]
                self.arguments[i] = name
                for terms in self.terminals.values():
                    for t in terms:
                        if t.name == old:
                            t.name = name
                self.mapping[name] = self.mapping.pop(old)

    @property
    def terminalRatio(self):
        n_t = sum(len(v) for v in self.terminals.values())
        n_p = sum(len(v) for v in self.primitives.values())
        return n_t / (n_t + n_p)


class PrimitiveSet(PrimitiveSetTyped):
    """Untyped set: every slot shares one type (gp.py:432-456)."""

    def __init__(self, name, arity, prefix="ARG"):
        super().__init__(name, [object] * arity, object, prefix)

    def addPrimitive(self, fn, arity, name=None):
        super().addPrimitive(fn, [object] * arity, object, name)

    def addTerminal(self, value, name=None):
        super().addTerminal(value, object, name)

    def addEphemeralConstant(self, name, func):
        super().addEphemeralConstant(name, func, object)


# ----------------------------------------------------------------- compile --

def compile(expr: PrimitiveTree, pset: PrimitiveSetTyped,
            _adfs=None) -> Callable:
    """Executable function from a tree — one iterative right-to-left
    pass with a value stack instead of the reference's source-string
    ``eval`` (gp.py:462-487): O(len(tree)) per call, no recursion, so
    no depth limit beyond memory (the reference fails past depth ~90;
    a recursive evaluator would merely move that to the interpreter's
    recursion limit). Returns ``f(*args)`` when the set has inputs,
    else the evaluated value. ``_adfs`` maps ADF names to callables
    (bound by :func:`compileADF`)."""
    arg_names = pset.arguments
    nodes = list(expr)
    adfs = _adfs or {}

    def run(*args):
        if len(args) != len(arg_names):
            raise TypeError(
                f"{pset.name} expects {len(arg_names)} arguments, "
                f"got {len(args)}")
        env = dict(zip(arg_names, args))
        stack: list = []
        for node in reversed(nodes):
            if isinstance(node, Primitive):
                vals = [stack.pop() for _ in range(node.arity)]
                fn = node.fn if node.fn is not None else adfs[node.name]
                stack.append(fn(*vals))
            elif node.value is None and node.name in env:
                stack.append(env[node.name])
            else:
                stack.append(node.value)
        return stack[0]

    if not arg_names:
        return run()
    return run


def compileADF(expr, psets) -> Callable:
    """Compile a multi-branch individual with automatically defined
    functions (gp.py:490-513): branches are compiled last-first and
    each earlier branch sees the later ones as callable primitives
    (registered via ``addADF``) — bound per individual, never written
    into the shared primitive set."""
    adfdict: dict = {}
    func = None
    for subexpr, pset in reversed(list(zip(expr, psets))):
        func = compile(subexpr, pset, _adfs=dict(adfdict))
        adfdict[pset.name] = func
    return func


# -------------------------------------------------------------- generators --

def _generate(pset, min_, max_, condition, type_=None):
    if type_ is None:
        type_ = pset.ret
    expr = []
    height = random.randint(min_, max_)
    stack = [(0, type_)]
    while stack:
        depth, type_ = stack.pop()
        if condition(height, depth):
            terms = pset.terminals.get(type_, [])
            if not terms:
                raise IndexError(
                    f"no terminal of type {type_} available")
            term = random.choice(terms)
            if isinstance(term, Ephemeral):
                term = term.regen()
            expr.append(term)
        else:
            prims = pset.primitives.get(type_, [])
            if not prims:
                # fall back to a terminal when no primitive fits (the
                # reference raises; generators guard with condition)
                terms = pset.terminals.get(type_, [])
                if not terms:
                    raise IndexError(
                        f"no primitive or terminal of type {type_}")
                term = random.choice(terms)
                if isinstance(term, Ephemeral):
                    term = term.regen()
                expr.append(term)
                continue
            prim = random.choice(prims)
            expr.append(prim)
            for arg_t in reversed(prim.args):
                stack.append((depth + 1, arg_t))
    return expr


def genFull(pset, min_, max_, type_=None):
    """Leaves all at depth in [min, max] (gp.py:546-565)."""
    return PrimitiveTree(_generate(
        pset, min_, max_, lambda h, d: d == h, type_))


def genGrow(pset, min_, max_, type_=None):
    """Leaves at varying depths (gp.py:568-589)."""
    def condition(height, depth):
        return depth == height or (
            depth >= min_ and random.random() < pset.terminalRatio)
    return PrimitiveTree(_generate(pset, min_, max_, condition, type_))


def genHalfAndHalf(pset, min_, max_, type_=None):
    """Koza ramped half-and-half (gp.py:592-608)."""
    return random.choice((genFull, genGrow))(pset, min_, max_, type_)


def genRamped(pset, min_, max_, type_=None):
    """Deprecated alias of :func:`genHalfAndHalf` (gp.py:611-616)."""
    warnings.warn("gp.genRamped has been renamed. Use genHalfAndHalf "
                  "instead.", FutureWarning)
    return genHalfAndHalf(pset, min_, max_, type_)


def generate(pset, min_, max_, condition, type_=None):
    """Core tree builder (gp.py:611-638): grow node-by-node from a
    type stack, placing a terminal wherever ``condition(height, depth)``
    holds. Public like the reference, for custom generators."""
    return PrimitiveTree(_generate(pset, min_, max_, condition, type_))


# -------------------------------------------------------------- variation --

def cxOnePoint(ind1, ind2):
    """Type-aware subtree swap (gp.py:645-682)."""
    if len(ind1) < 2 or len(ind2) < 2:
        return ind1, ind2
    types1: dict = {}
    types2: dict = {}
    for idx, node in enumerate(ind1[1:], 1):
        types1.setdefault(node.ret, []).append(idx)
    for idx, node in enumerate(ind2[1:], 1):
        types2.setdefault(node.ret, []).append(idx)
    common = set(types1) & set(types2)
    if not common:
        return ind1, ind2
    type_ = random.choice(list(common))
    i1 = random.choice(types1[type_])
    i2 = random.choice(types2[type_])
    s1, s2 = ind1.search_subtree(i1), ind2.search_subtree(i2)
    ind1[s1], ind2[s2] = ind2[s2], ind1[s1]
    return ind1, ind2


def cxOnePointLeafBiased(ind1, ind2, termpb):
    """Subtree swap with Koza's 90/10 node-category bias
    (gp.py:685-737): each parent independently restricts its crossover
    points to terminals with probability ``termpb``, else to
    primitives."""
    if len(ind1) < 2 or len(ind2) < 2:
        return ind1, ind2

    def points(ind, want_terminals):
        by_type: dict = {}
        for idx, node in enumerate(ind[1:], 1):
            if (node.arity == 0) == want_terminals:
                by_type.setdefault(node.ret, []).append(idx)
        return by_type

    types1 = points(ind1, random.random() < termpb)
    types2 = points(ind2, random.random() < termpb)
    common = set(types1) & set(types2)
    if common:
        type_ = random.choice(sorted(common, key=str))
        i1 = random.choice(types1[type_])
        i2 = random.choice(types2[type_])
        s1, s2 = ind1.search_subtree(i1), ind2.search_subtree(i2)
        ind1[s1], ind2[s2] = ind2[s2], ind1[s1]
    return ind1, ind2


def _semantic_nodes(pset):
    for p in ("lf", "mul", "add", "sub"):
        if p not in pset.mapping:
            raise AssertionError(
                "A '%s' function is required in order to perform "
                "semantic variation" % p)
    return (pset.mapping["lf"], pset.mapping["mul"],
            pset.mapping["add"], pset.mapping["sub"])


def mutSemantic(individual, gen_func=genGrow, pset=None, ms=None,
                min=2, max=6):
    """Geometric semantic mutation (Moraglio et al. 2012;
    gp.py:1215-1267): ``ind + ms · (lf(tr1) - lf(tr2))`` built
    structurally, where ``lf`` is the pset's logistic wrapper."""
    lf, mul, add, sub = _semantic_nodes(pset)
    tr1 = gen_func(pset, min, max)
    tr2 = gen_func(pset, min, max)
    if ms is None:
        ms = random.uniform(0, 2)
    step = Terminal(repr(ms), ms, object)
    new = individual
    new.insert(0, add)
    new.extend([mul, step, sub, lf])
    new.extend(tr1)
    new.append(lf)
    new.extend(tr2)
    return (new,)


def cxSemantic(ind1, ind2, gen_func=genGrow, pset=None, min=2, max=6):
    """Geometric semantic crossover (Moraglio et al. 2012;
    gp.py:1270-1329): with one shared random tree ``tr``,
    ``child1 = lf(tr)·ind1 + (1-lf(tr))·ind2`` and symmetrically for
    ``child2``. Unlike the reference — whose in-place build lets
    child2 absorb the already-rebuilt child1 (gp.py:1319-1327 extends
    the mutated ``ind1``) — both children are built from the *original*
    parents, matching the operator's published definition."""
    lf, mul, add, sub = _semantic_nodes(pset)
    tr = gen_func(pset, min, max)
    one = Terminal("1.0", 1.0, object)
    p1, p2 = list(ind1), list(ind2)

    def build(a, b):
        out = [add, mul] + a + [lf] + list(tr)
        out += [mul, sub, one, lf] + list(tr) + b
        return out

    ind1[:] = build(p1, p2)
    ind2[:] = build(p2, p1)
    return ind1, ind2


def mutUniform(individual, expr, pset):
    """Replace a random subtree with ``expr(pset=pset, type_=...)``
    (gp.py:743-757)."""
    index = random.randrange(len(individual))
    slice_ = individual.search_subtree(index)
    type_ = individual[index].ret
    individual[slice_] = expr(pset=pset, type_=type_)
    return (individual,)


def mutNodeReplacement(individual, pset):
    """Swap one node for another of the same arity/type
    (gp.py:760-783)."""
    index = random.randrange(len(individual))
    node = individual[index]
    if node.arity == 0:
        terms = pset.terminals.get(node.ret, [])
        if terms:
            term = random.choice(terms)
            if isinstance(term, Ephemeral):
                term = term.regen()
            individual[index] = term
    else:
        prims = [p for p in pset.primitives.get(node.ret, [])
                 if p.args == node.args]
        if prims:
            individual[index] = random.choice(prims)
    return (individual,)


def mutEphemeral(individual, mode="one"):
    """Redraw ephemeral constant values (gp.py:786-811)."""
    if mode not in ("one", "all"):
        raise ValueError("Mode must be one of 'one' or 'all'")
    ephemerals = [i for i, node in enumerate(individual)
                  if isinstance(node, Ephemeral)]
    if ephemerals:
        if mode == "one":
            ephemerals = [random.choice(ephemerals)]
        for i in ephemerals:
            individual[i] = individual[i].regen()
    return (individual,)


def mutInsert(individual, pset):
    """Insert a primitive above a random subtree; the old subtree
    becomes one argument, the rest are fresh terminals
    (gp.py:814-851)."""
    index = random.randrange(len(individual))
    node = individual[index]
    slice_ = individual.search_subtree(index)
    choices = [p for p in pset.primitives.get(node.ret, [])
               if node.ret in p.args]
    if not choices:
        return (individual,)
    new_node = random.choice(choices)
    position = random.choice(
        [i for i, t in enumerate(new_node.args) if t == node.ret])
    new_subtree: list = []
    for i, arg_type in enumerate(new_node.args):
        if i == position:
            new_subtree.extend(individual[slice_])
        else:
            terms = pset.terminals.get(arg_type, [])
            if not terms:
                return (individual,)
            term = random.choice(terms)
            if isinstance(term, Ephemeral):
                term = term.regen()
            new_subtree.append(term)
    individual[slice_.start:slice_.stop] = [new_node] + new_subtree
    return (individual,)


def mutShrink(individual, *_):
    """Replace a random primitive by one of its argument subtrees
    (gp.py:854-886). The root is never shrunk and trees at/below one
    level are returned unchanged (gp.py:862-863)."""
    if len(individual) < 3 or individual.height <= 1:
        return (individual,)
    prims = [i for i, node in enumerate(individual)
             if isinstance(node, Primitive) and i != 0]
    if not prims:
        return (individual,)
    index = random.choice(prims)
    node = individual[index]
    # pick an argument subtree whose type matches the node's return
    arg_idx = [i for i, t in enumerate(node.args) if t == node.ret]
    if not arg_idx:
        return (individual,)
    chosen = random.choice(arg_idx)
    j = index + 1
    for _ in range(chosen):
        j = individual.search_subtree(j).stop
    sub = individual[individual.search_subtree(j)]
    individual[individual.search_subtree(index)] = sub
    return (individual,)


def staticLimit(key: Callable, max_value):
    """Reject-with-parent decorator (Koza limit; gp.py:890-931)."""
    def decorator(func):
        @wraps(func)
        def wrapper(*args, **kwargs):
            keep = [copy.deepcopy(ind) for ind in args
                    if isinstance(ind, PrimitiveTree)]
            new = func(*args, **kwargs)
            out = list(new)
            for i, ind in enumerate(out):
                if isinstance(ind, PrimitiveTree) and key(ind) > max_value:
                    out[i] = copy.deepcopy(random.choice(keep))
            return tuple(out)
        return wrapper
    return decorator


def graph(expr):
    """(nodes, edges, labels) for pygraphviz/networkx plotting
    (gp.py:1138-1208): one arity-countdown stack pass over the prefix
    array."""
    nodes = list(range(len(expr)))
    edges = []
    labels = {}
    stack = []
    for i, node in enumerate(expr):
        if stack:
            edges.append((stack[-1][0], i))
            stack[-1][1] -= 1
        labels[i] = node.name if node.arity > 0 else node.value
        stack.append([i, node.arity])
        while stack and stack[-1][1] == 0:
            stack.pop()
    return nodes, edges, labels


def harm(population, toolbox, cxpb, mutpb, ngen,
         alpha, beta, gamma, rho, nbrindsmodel=-1, mincutoff=20,
         stats=None, halloffame=None, verbose=True):
    """HARM-GP bloat control (Gardner, Gagné & Parizeau 2015;
    gp.py:938-1135) as an eaSimple-shaped loop over list populations.

    Each generation: (1) sample ``nbrindsmodel`` offspring to estimate
    the *natural* size distribution (kernel-smoothed histogram), (2) put
    the cutoff at the size of the smallest individual among the top
    (1-rho) fraction by fitness, floored at ``mincutoff``, (3) accept
    offspring above the cutoff with exponentially decaying probability
    (half-life ``alpha·size + beta``, mass ``gamma``), re-drawing until
    the population refills. The tensor-path counterpart is
    :mod:`deap_tpu.gp.harm`.
    """
    import math

    from deap_tpu.compat.tools import Logbook

    def halflife(x):
        return x * float(alpha) + beta

    def vary_pairs():
        """Produce offspring one operator application at a time,
        yielding 1-2 individuals (gp.py:1019-1042)."""
        op = random.random()
        if op < cxpb:
            a1, a2 = toolbox.mate(*map(toolbox.clone,
                                       toolbox.select(population, 2)))
            del a1.fitness.values, a2.fitness.values
            return [a1, a2]
        aspirant = toolbox.clone(toolbox.select(population, 1)[0])
        if op - cxpb < mutpb:
            aspirant = toolbox.mutate(aspirant)[0]
            del aspirant.fitness.values
        return [aspirant]

    def genpop(n, pickfrom=None, accept=lambda s: True,
               producesizes=False):
        produced, sizes = [], []
        pickfrom = pickfrom if pickfrom is not None else []
        while len(produced) < n:
            candidates = [pickfrom.pop()] if pickfrom else vary_pairs()
            for ind in candidates:
                if len(produced) < n and accept(len(ind)):
                    produced.append(ind)
                    sizes.append(len(ind))
        return (produced, sizes) if producesizes else produced

    if nbrindsmodel == -1:
        nbrindsmodel = max(2000, len(population))

    logbook = Logbook()
    logbook.header = ["gen", "nevals"] + (stats.fields if stats else [])

    invalid = [ind for ind in population if not ind.fitness.valid]
    for ind, fit in zip(invalid, toolbox.map(toolbox.evaluate, invalid)):
        ind.fitness.values = fit
    if halloffame is not None:
        halloffame.update(population)
    record = stats.compile(population) if stats else {}
    logbook.record(gen=0, nevals=len(invalid), **record)
    if verbose:
        print(logbook.stream)

    for gen in range(1, ngen + 1):
        naturalpop, naturalsizes = genpop(nbrindsmodel, producesizes=True)

        # kernel-smoothed size histogram (gp.py:1076-1087)
        hist = [0.0] * (max(naturalsizes) + 3)
        for s in naturalsizes:
            hist[s] += 0.4
            hist[s - 1] += 0.2
            hist[s + 1] += 0.2
            hist[s + 2] += 0.1
            if s - 2 >= 0:
                hist[s - 2] += 0.1
        hist = [v * len(population) / nbrindsmodel for v in hist]

        # cutoff: smallest size among the top (1-rho) by fitness
        # (gp.py:1092-1096)
        bytfit = sorted(naturalpop, key=lambda ind: ind.fitness)
        candidates = bytfit[int(len(population) * rho - 1):]
        cutoff = max(mincutoff, min(len(ind) for ind in candidates))

        def target(x):
            return (gamma * len(population) * math.log(2) / halflife(x)
                    ) * math.exp(-math.log(2) * (x - cutoff) / halflife(x))

        targethist = [hist[b] if b <= cutoff else target(b)
                      for b in range(len(hist))]
        probhist = [t / n if n > 0 else t
                    for n, t in zip(hist, targethist)]

        def accept(s):
            p = probhist[s] if s < len(probhist) else target(s)
            return random.random() <= p

        offspring = genpop(len(population), pickfrom=naturalpop,
                           accept=accept)

        invalid = [ind for ind in offspring if not ind.fitness.valid]
        for ind, fit in zip(invalid,
                            toolbox.map(toolbox.evaluate, invalid)):
            ind.fitness.values = fit
        if halloffame is not None:
            halloffame.update(offspring)
        population[:] = offspring
        record = stats.compile(population) if stats else {}
        logbook.record(gen=gen, nevals=len(invalid), **record)
        if verbose:
            print(logbook.stream)

    return population, logbook
