"""Runtime type factory — the ``creator.create`` semantics.

Counterpart of /root/reference/deap/creator.py:96-171: ``create(name,
base, **attrs)`` manufactures a subclass of any container in this
module's namespace; class-valued kwargs become *per-instance* attributes
instantiated at construction time, plain values become class attributes.
numpy arrays get ``__deepcopy__``/``__reduce__`` fixes so clone and
pickle behave like values (creator.py:51-93).
"""

from __future__ import annotations

import array
import copy
import warnings

import numpy

#: base class → fixed-up stand-in, consulted by :func:`create` exactly
#: like the reference's ``class_replacers`` (creator.py:44-93). Users
#: can register their own replacers for containers whose deepcopy or
#: pickling needs patching.
class_replacers = {}


class _NumpyMixin:
    """Deepcopy/pickle fixes for ndarray subclasses (creator.py:51-73)."""

    @staticmethod
    def _numpy_new(cls, iterable=()):
        return numpy.asarray(iterable).view(cls)

    def __deepcopy__(self, memo):
        copy_ = numpy.copy(self).view(type(self))
        copy_.__dict__.update(copy.deepcopy(self.__dict__, memo))
        return copy_

    def __reduce__(self):
        return (type(self), (list(self),), self.__dict__)


class _FixedArray(array.array):
    """array.array stand-in (creator.py:76-93): the typecode comes from
    the created class, so ``Individual([1, 0, 1])`` works, and
    deepcopy/pickle carry the instance ``__dict__`` (the fitness)."""

    @staticmethod
    def __new__(cls, seq=()):
        return super().__new__(cls, cls.typecode, seq)

    def __deepcopy__(self, memo):
        cls = self.__class__
        copy_ = cls.__new__(cls, self)
        memo[id(self)] = copy_
        copy_.__dict__.update(copy.deepcopy(self.__dict__, memo))
        return copy_

    def __reduce__(self):
        return (self.__class__, (list(self),), self.__dict__)


class_replacers[array.array] = _FixedArray


def create(name: str, base: type, **kwargs) -> type:
    """Create class ``name`` deriving from ``base`` in this module.

    ``create("Individual", list, fitness=FitnessMin)`` builds a list
    subclass whose instances carry a fresh ``fitness`` object; plain
    values (``speed=None``) become shared class attributes.
    """
    if name in globals():
        warnings.warn(
            f"A class named '{name}' has already been created and it "
            "will be overwritten. Consider deleting previous creation "
            "of that class or rename it.", RuntimeWarning)

    instance_attrs = {}
    class_attrs = {}
    for key, value in kwargs.items():
        if isinstance(value, type):
            instance_attrs[key] = value
        else:
            class_attrs[key] = value

    if base not in class_replacers and issubclass(base, numpy.ndarray):
        # built-in ndarray handling; a user-registered replacer for
        # numpy.ndarray takes precedence via the branch below, exactly
        # like the reference's class_replacers lookup (creator.py:145)
        def __new__(cls, iterable=()):
            return _NumpyMixin._numpy_new(cls, iterable)

        def __init__(self, iterable=()):
            for attr, klass in instance_attrs.items():
                setattr(self, attr, klass())

        body = dict(class_attrs)
        body["__new__"] = __new__
        body["__init__"] = __init__
        body["__deepcopy__"] = _NumpyMixin.__deepcopy__
        body["__reduce__"] = _NumpyMixin.__reduce__
        cls = type(name, (base,), body)
    else:
        # swap bases whose deepcopy/pickling needs patching — e.g.
        # array.array, whose __new__ needs the class typecode threaded
        base = class_replacers.get(base, base)

        def __init__(self, *args, **kw):
            if base.__init__ is not object.__init__:
                base.__init__(self, *args, **kw)
            for attr, klass in instance_attrs.items():
                setattr(self, attr, klass())

        # default pickling handles list/dict/set subclasses correctly
        # (listitems/dictitems + __dict__ state); only ndarray and
        # array.array need explicit fixes, matching the reference's
        # scope (creator.py:51-93)
        body = dict(class_attrs)
        body["__init__"] = __init__
        cls = type(name, (base,), body)

    cls.__module__ = __name__
    globals()[name] = cls
    return cls
