"""Generational loops over list populations.

Counterpart of /root/reference/deap/algorithms.py for the CPU backend:
identical protocol — clone, vary, delete fitness of touched children,
evaluate exactly the invalid ones through ``toolbox.map`` (the
distribution seam where :func:`deap_tpu.compat.jax_map` plugs in).
"""

from __future__ import annotations

import random

from deap_tpu.compat.tools import Logbook


def varAnd(population, toolbox, cxpb, mutpb):
    """Clone → pairwise mate (prob cxpb) → mutate (prob mutpb),
    invalidating touched fitnesses (algorithms.py:33-82)."""
    offspring = [toolbox.clone(ind) for ind in population]
    for i in range(1, len(offspring), 2):
        if random.random() < cxpb:
            offspring[i - 1], offspring[i] = toolbox.mate(
                offspring[i - 1], offspring[i])
            del offspring[i - 1].fitness.values, offspring[i].fitness.values
    for i in range(len(offspring)):
        if random.random() < mutpb:
            offspring[i], = toolbox.mutate(offspring[i])
            del offspring[i].fitness.values
    return offspring


def varOr(population, toolbox, lambda_, cxpb, mutpb):
    """λ children, each by crossover | mutation | reproduction
    (algorithms.py:192-245)."""
    assert (cxpb + mutpb) <= 1.0, (
        "The sum of the crossover and mutation probabilities must be "
        "smaller or equal to 1.0.")
    offspring = []
    for _ in range(lambda_):
        op_choice = random.random()
        if op_choice < cxpb:
            ind1, ind2 = [toolbox.clone(i)
                          for i in random.sample(population, 2)]
            ind1, ind2 = toolbox.mate(ind1, ind2)
            del ind1.fitness.values
            offspring.append(ind1)
        elif op_choice < cxpb + mutpb:
            ind = toolbox.clone(random.choice(population))
            ind, = toolbox.mutate(ind)
            del ind.fitness.values
            offspring.append(ind)
        else:
            offspring.append(random.choice(population))
    return offspring


def _evaluate_invalid(population, toolbox):
    invalid = [ind for ind in population if not ind.fitness.valid]
    fitnesses = toolbox.map(toolbox.evaluate, invalid)
    for ind, fit in zip(invalid, fitnesses):
        ind.fitness.values = fit
    return len(invalid)


def _log(logbook, stats, population, gen, nevals, verbose):
    record = stats.compile(population) if stats else {}
    logbook.record(gen=gen, nevals=nevals, **record)
    if verbose:
        print(logbook.stream)


def eaSimple(population, toolbox, cxpb, mutpb, ngen, stats=None,
             halloffame=None, verbose=False):
    """select → varAnd → evaluate → replace (algorithms.py:85-189)."""
    logbook = Logbook()
    logbook.header = ["gen", "nevals"] + (stats.fields if stats else [])
    nevals = _evaluate_invalid(population, toolbox)
    if halloffame is not None:
        halloffame.update(population)
    _log(logbook, stats, population, 0, nevals, verbose)
    for gen in range(1, ngen + 1):
        offspring = toolbox.select(population, len(population))
        offspring = varAnd(offspring, toolbox, cxpb, mutpb)
        nevals = _evaluate_invalid(offspring, toolbox)
        if halloffame is not None:
            halloffame.update(offspring)
        population[:] = offspring
        _log(logbook, stats, population, gen, nevals, verbose)
    return population, logbook


def eaMuPlusLambda(population, toolbox, mu, lambda_, cxpb, mutpb, ngen,
                   stats=None, halloffame=None, verbose=False):
    """(μ + λ): parents compete with offspring (algorithms.py:248-337)."""
    logbook = Logbook()
    logbook.header = ["gen", "nevals"] + (stats.fields if stats else [])
    nevals = _evaluate_invalid(population, toolbox)
    if halloffame is not None:
        halloffame.update(population)
    _log(logbook, stats, population, 0, nevals, verbose)
    for gen in range(1, ngen + 1):
        offspring = varOr(population, toolbox, lambda_, cxpb, mutpb)
        nevals = _evaluate_invalid(offspring, toolbox)
        if halloffame is not None:
            halloffame.update(offspring)
        population[:] = toolbox.select(population + offspring, mu)
        _log(logbook, stats, population, gen, nevals, verbose)
    return population, logbook


def eaMuCommaLambda(population, toolbox, mu, lambda_, cxpb, mutpb, ngen,
                    stats=None, halloffame=None, verbose=False):
    """(μ, λ): only offspring survive (algorithms.py:340-437)."""
    assert lambda_ >= mu, \
        "lambda must be greater or equal to mu."
    logbook = Logbook()
    logbook.header = ["gen", "nevals"] + (stats.fields if stats else [])
    nevals = _evaluate_invalid(population, toolbox)
    if halloffame is not None:
        halloffame.update(population)
    _log(logbook, stats, population, 0, nevals, verbose)
    for gen in range(1, ngen + 1):
        offspring = varOr(population, toolbox, lambda_, cxpb, mutpb)
        nevals = _evaluate_invalid(offspring, toolbox)
        if halloffame is not None:
            halloffame.update(offspring)
        population[:] = toolbox.select(offspring, mu)
        _log(logbook, stats, population, gen, nevals, verbose)
    return population, logbook


def eaGenerateUpdate(toolbox, ngen, halloffame=None, stats=None,
                     verbose=False):
    """ask-tell: generate → evaluate → update (algorithms.py:440-503)."""
    logbook = Logbook()
    logbook.header = ["gen", "nevals"] + (stats.fields if stats else [])
    for gen in range(ngen):
        population = toolbox.generate()
        fitnesses = toolbox.map(toolbox.evaluate, population)
        for ind, fit in zip(population, fitnesses):
            ind.fitness.values = fit
        if halloffame is not None:
            halloffame.update(population)
        toolbox.update(population)
        _log(logbook, stats, population, gen, len(population), verbose)
    return population, logbook
