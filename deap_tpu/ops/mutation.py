"""Mutation operators as pure per-genome functions.

Counterpart of /root/reference/deap/tools/mutation.py. Signature
convention: ``(key, genome, **params) -> genome`` (ES log-normal also
takes and returns the strategy vector). The reference's per-gene
``random.random() < indpb`` loops become whole Bernoulli masks drawn in
one op; batch over a population with ``jax.vmap``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def genome_vmap(mut):
    """Lift a per-genome mutation to ``(key, G, ...)`` over ``[n, L]``."""
    def batched(key, g, *args, **kwargs):
        keys = jax.random.split(key, g.shape[0])
        return jax.vmap(lambda k, x: mut(k, x, *args, **kwargs))(keys, g)
    return batched


def mut_gaussian(key, g, mu, sigma, indpb):
    """Gaussian additive mutation (mutation.py:17-48): each gene gets
    ``+ N(mu, sigma)`` with prob indpb."""
    km, kn = jax.random.split(key)
    mask = jax.random.bernoulli(km, indpb, g.shape)
    noise = mu + sigma * jax.random.normal(kn, g.shape, dtype=g.dtype)
    return jnp.where(mask, g + noise, g)


# --- fused-plan factories (ops.variation) ------------------------------
#
# Each factory takes the operator's bound keyword parameters and returns
# ``(kind, draw)`` where ``draw(key, L, dtype) -> (mask, arg)``
# reproduces the operator's internal jax.random calls bit-exactly —
# same key splits, same shapes, same dtypes — so the fused variation
# plane's masked apply computes the identical child rows.

def _gaussian_fused(mu, sigma, indpb):
    def draw(key, L, dtype):
        km, kn = jax.random.split(key)
        mask = jax.random.bernoulli(km, indpb, (L,))
        noise = mu + sigma * jax.random.normal(kn, (L,), dtype=dtype)
        return mask, noise
    return "add", draw


mut_gaussian.fused_plan = _gaussian_fused


def mut_polynomial_bounded(key, g, eta, low, up, indpb):
    """Deb's polynomial bounded mutation (mutation.py:51-97), per-gene
    with prob indpb, clipped to [low, up]."""
    low = jnp.broadcast_to(jnp.asarray(low, g.dtype), g.shape)
    up = jnp.broadcast_to(jnp.asarray(up, g.dtype), g.shape)
    km, kr = jax.random.split(key)
    mask = jax.random.bernoulli(km, indpb, g.shape)
    rand = jax.random.uniform(kr, g.shape)

    span = up - low
    delta_1 = (g - low) / span
    delta_2 = (up - g) / span
    mut_pow = 1.0 / (eta + 1.0)

    val_lo = 2.0 * rand + (1.0 - 2.0 * rand) * (1.0 - delta_1) ** (eta + 1.0)
    val_hi = 2.0 * (1.0 - rand) + 2.0 * (rand - 0.5) * (1.0 - delta_2) ** (eta + 1.0)
    delta_q = jnp.where(rand < 0.5, val_lo ** mut_pow - 1.0, 1.0 - val_hi ** mut_pow)

    out = jnp.clip(g + delta_q * span, low, up)
    return jnp.where(mask, out, g)


def mut_shuffle_indexes(key, g, indpb):
    """Positional shuffle (mutation.py:100-122): sequentially, each slot i
    swaps with a uniformly-drawn other slot with prob indpb. Sequential
    data dependence → fori_loop, vmapped across the population."""
    size = g.shape[0]
    km, kj = jax.random.split(key)
    do = jax.random.bernoulli(km, indpb, (size,))
    # reference: randint(0, size-2) bumped past i → uniform over others
    raw = jax.random.randint(kj, (size,), 0, size - 1)
    partner = jnp.where(raw >= jnp.arange(size), raw + 1, raw)

    def body(i, arr):
        j = partner[i]
        swapped = arr.at[i].set(arr[j]).at[j].set(arr[i])
        return jnp.where(do[i], swapped, arr)

    return lax.fori_loop(0, size, body, g)


def mut_flip_bit(key, g, indpb):
    """Bit flip (mutation.py:124-142): logical-not with prob indpb."""
    mask = jax.random.bernoulli(key, indpb, g.shape)
    flipped = (~g.astype(bool)).astype(g.dtype)
    return jnp.where(mask, flipped, g)


def _flip_bit_fused(indpb):
    def draw(key, L, dtype):
        del dtype  # flip needs no values, only the operator's mask bits
        return jax.random.bernoulli(key, indpb, (L,)), None
    return "flip", draw


mut_flip_bit.fused_plan = _flip_bit_fused


def mut_uniform_int(key, g, low, up, indpb):
    """Integer replacement (mutation.py:145-172): redraw in [low, up]
    (inclusive) with prob indpb."""
    km, kv = jax.random.split(key)
    mask = jax.random.bernoulli(km, indpb, g.shape)
    low_a = jnp.broadcast_to(jnp.asarray(low, g.dtype), g.shape)
    up_a = jnp.broadcast_to(jnp.asarray(up, g.dtype), g.shape)
    # per-gene bounds via uniform scaling (handles sequence low/up)
    u = jax.random.uniform(kv, g.shape)
    draw = (low_a + jnp.floor(u * (up_a - low_a + 1))).astype(g.dtype)
    return jnp.where(mask, draw, g)


def _uniform_int_fused(low, up, indpb):
    def draw(key, L, dtype):
        km, kv = jax.random.split(key)
        mask = jax.random.bernoulli(km, indpb, (L,))
        low_a = jnp.broadcast_to(jnp.asarray(low, dtype), (L,))
        up_a = jnp.broadcast_to(jnp.asarray(up, dtype), (L,))
        u = jax.random.uniform(kv, (L,))
        val = (low_a + jnp.floor(u * (up_a - low_a + 1))).astype(dtype)
        return mask, val
    return "set", draw


mut_uniform_int.fused_plan = _uniform_int_fused


def mut_es_log_normal(key, g, strategy, c, indpb):
    """Self-adaptive ES mutation (Beyer & Schwefel 2002; mutation.py:180-215).

    One global draw n0 scales all strategies this call
    (``t0 = c/sqrt(2L)``); per gene with prob indpb the strategy is
    log-normally perturbed (``t = c/sqrt(2 sqrt(L))``) and the value
    moves by ``strategy * N(0,1)``. Returns ``(genome, strategy)``.
    """
    size = g.shape[0]
    t = c / jnp.sqrt(2.0 * jnp.sqrt(float(size)))
    t0 = c / jnp.sqrt(2.0 * float(size))
    k0, km, k1, k2 = jax.random.split(key, 4)
    n0 = jax.random.normal(k0, ())
    mask = jax.random.bernoulli(km, indpb, g.shape)
    n1 = jax.random.normal(k1, g.shape, dtype=g.dtype)
    n2 = jax.random.normal(k2, g.shape, dtype=g.dtype)
    new_strategy = strategy * jnp.exp(t0 * n0 + t * n1)
    new_g = g + new_strategy * n2
    return (jnp.where(mask, new_g, g), jnp.where(mask, new_strategy, strategy))


def strategy_floor(minstrategy):
    """Decorator enforcing a minimum strategy value — counterpart of the
    ``checkStrategy`` decorator pattern in examples/es/fctmin.py."""
    def decorator(mut):
        def wrapper(*args, **kwargs):
            g, s = mut(*args, **kwargs)
            return g, jnp.maximum(s, minstrategy)
        return wrapper
    return decorator


def mut_two_opt(key, g, dist, steps: int | None = None):
    """Best-improvement 2-opt local-search sweep over a permutation
    genome — a memetic polish operator for tour problems.

    Not in the reference's operator set (its tsp example,
    examples/ga/tsp.py, is pure PMX + shuffle); added so the GA
    reaches published TSPLIB optima (gr17/gr24) rather than stalling a
    few percent above them. Tensor formulation: all L² candidate edge
    pairs are scored at once — reversing ``g[i+1..j]`` swaps edges
    ``(g[i], g[i+1])``/``(g[j], g[j+1])`` for
    ``(g[i], g[j])``/``(g[i+1], g[j+1])`` — and the single best
    improving reversal is applied per step via an index remap (a
    gather, no dynamic slicing), scanned ``steps`` times. Steps after
    a local optimum is reached are identity, so a fixed step count
    stays scan/jit-friendly while behaving like
    sweep-until-no-improvement.

    :param key: unused (the sweep is deterministic); kept for the
        ``(key, genome, **params)`` mutation signature.
    :param g: ``int[L]`` permutation genome.
    :param dist: ``[L, L]`` symmetric distance matrix (closed over or
        passed via ``functools.partial`` at registration).
    :param steps: reversal steps; defaults to ``L`` (enough to reach a
        local optimum from GA offspring in practice).
    """
    del key
    L = g.shape[0]
    steps = L if steps is None else steps
    pos = jnp.arange(L)

    def step(perm, _):
        nxt = jnp.roll(perm, -1)
        d_pp = dist[perm[:, None], perm[None, :]]   # dist[p_i, p_j]
        d_nn = dist[nxt[:, None], nxt[None, :]]     # dist[p_i+1, p_j+1]
        d_edge = dist[perm, nxt]                    # current edge lengths
        delta = d_pp + d_nn - d_edge[:, None] - d_edge[None, :]
        delta = jnp.where(pos[:, None] < pos[None, :], delta, jnp.inf)
        flat = jnp.argmin(delta)
        i, j = flat // L, flat % L
        improving = delta[i, j] < 0
        newpos = jnp.where((pos > i) & (pos <= j), i + 1 + j - pos, pos)
        return jnp.where(improving, perm[newpos], perm), None

    out, _ = lax.scan(step, g, None, length=steps)
    return out
