"""Batched small-matrix linear algebra — pure-XLA eigendecomposition.

``jnp.linalg.eigh`` lowers to a LAPACK ``syevd`` custom call on CPU:
correct and fast for ONE matrix, but under ``vmap`` the batch dimension
executes as a *serial host loop* over lanes — which is exactly what the
multi-tenant CMA serving bucket does every generation
(:mod:`deap_tpu.serving.multirun` vmaps the CMA update across lanes;
the committed 3.0× CMA serving number is eigh-loop-bound, ROADMAP
item 1). For the small covariance matrices CMA serves (dim ≤ a few
dozen), **parallel-ordered Jacobi** is the classic batched answer: a
round-robin schedule applies ⌊d/2⌋ *disjoint* rotations per round as
one d×d rotation matrix, so a whole round is two small matmuls — and
under ``vmap`` those become batched matmuls over the lane axis, one
wide vectorised program instead of a LAPACK queue.

Contract: :func:`eigh_jacobi` matches the ``jnp.linalg.eigh`` interface
(ascending eigenvalues, ``C ≈ V @ diag(w) @ V.T``) to f32 working
precision. It is NOT bit-identical to LAPACK — a strategy must use one
implementation consistently (``cma.Strategy(eigh_impl=...)``), and the
serving bit-identity contract (solo == batched per lane) holds within
each implementation (``tests/test_sharding_plan.py`` pins jacobi
solo==vmapped bit-exactness alongside the existing LAPACK pins).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["eigh_jacobi"]


def _round_robin_schedule(d: int) -> Tuple[np.ndarray, np.ndarray]:
    """The circle-method tournament schedule: ``m - 1`` rounds of
    ``m // 2`` disjoint pairs covering every (p, q) exactly once per
    sweep (``m = d`` rounded up to even; the odd-d bye appears as a
    ``(b, b)`` self-pair, applied as an identity rotation). Returns
    ``(ps, qs)`` int32 arrays of shape ``[m - 1, m // 2]``."""
    m = d + (d % 2)
    players = list(range(m))
    ps, qs = [], []
    for _ in range(m - 1):
        rp, rq = [], []
        for k in range(m // 2):
            a, b = players[k], players[m - 1 - k]
            if a >= d:  # the bye slot of an odd dimension
                a = b
            elif b >= d:
                b = a
            rp.append(min(a, b))
            rq.append(max(a, b))
        ps.append(rp)
        qs.append(rq)
        players = [players[0]] + [players[-1]] + players[1:-1]
    return np.asarray(ps, np.int32), np.asarray(qs, np.int32)


def eigh_jacobi(C: jnp.ndarray,
                sweeps: Optional[int] = None) -> Tuple[jnp.ndarray,
                                                       jnp.ndarray]:
    """Symmetric eigendecomposition by fixed-sweep parallel Jacobi.

    ``(w, V)`` with ascending eigenvalues and orthonormal columns,
    ``C ≈ V @ diag(w) @ V.T`` — the ``jnp.linalg.eigh`` contract. One
    round applies all of a round-robin round's disjoint rotations at
    once, expressed as row/column pair-combinations (elementwise
    arithmetic + static-permutation gathers — no scatters, no matmuls,
    both of which XLA CPU would serialise per batch element), so the
    whole solve is ``sweeps × (d - 1)`` short vector steps that stay
    fully vectorised across a ``vmap`` batch — a thousand-lane CMA
    serving bucket decomposes in one wide program. Fixed ``sweeps``
    (default: enough for f32 working precision at small dims; Jacobi
    converges quadratically after the first few) keeps the program
    shape-static and deterministic.

    Intended for the small, well-conditioned covariance matrices of
    CMA-style strategies (dim ≲ 64); for one large matrix LAPACK wins.
    """
    C = jnp.asarray(C)
    d = C.shape[-1]
    if C.shape[-2] != d:
        raise ValueError(f"eigh_jacobi needs a square matrix, got "
                         f"{C.shape}")
    if d == 1:
        return C[..., 0, 0][..., None], jnp.ones_like(C)
    if sweeps is None:
        # 5 sweeps reach f32 working precision for d <= 8 under the
        # parallel ordering (measured: sweeps=5 matches sweeps=8 to
        # the last converged digit); one extra per doubling past that
        sweeps = 5 + max(0, int(np.ceil(np.log2(d / 8))) if d > 8
                         else 0)

    ps_np, qs_np = _round_robin_schedule(d)
    n_rounds = ps_np.shape[0]
    eye = jnp.eye(d, dtype=C.dtype)

    # everything index-shaped about a round is SCHEDULE, not data — so
    # it is precomputed into per-round constant tables (one-hot masks,
    # the partner permutation, a pivot-pinning mask) and the loop body
    # is pure elementwise arithmetic plus permutation row/column
    # gathers: no scatters and NO matmuls (XLA CPU executes a batched
    # tiny matmul — and a batched LAPACK call — as a per-lane loop,
    # the exact serialisation this solver exists to avoid). One small
    # fori body over sweeps × rounds keeps compiles fast at any d.
    npairs = ps_np.shape[1]
    real_np = ps_np != qs_np  # odd-d byes rotate by identity
    pq_hot_np = np.zeros((n_rounds, npairs, d), np.float32)
    sign_np = np.zeros((n_rounds, d), np.float32)
    partner_np = np.tile(np.arange(d, dtype=np.int32), (n_rounds, 1))
    piv_np = np.ones((n_rounds, d, d), np.float32)
    for r in range(n_rounds):
        ps, qs, real = ps_np[r], qs_np[r], real_np[r]
        pq_hot_np[r, np.arange(npairs), ps] = 1.0
        pq_hot_np[r, np.arange(npairs)[real], qs[real]] = 1.0
        # sign of the s entry per index: +1 at the pair's low index,
        # -1 at the high one
        sign_np[r, ps[real]] = 1.0
        sign_np[r, qs[real]] = -1.0
        partner_np[r, ps[real]] = qs[real]
        partner_np[r, qs[real]] = ps[real]
        # zero mask pinning the rotated pivots (analytic zeros)
        piv_np[r, ps[real], qs[real]] = 0.0
        piv_np[r, qs[real], ps[real]] = 0.0
    ps_all = jnp.asarray(ps_np)
    qs_all = jnp.asarray(qs_np)
    real_all = jnp.asarray(real_np)
    pq_hot_all = jnp.asarray(pq_hot_np)
    sign_all = jnp.asarray(sign_np)
    partner_all = jnp.asarray(partner_np)
    piv_all = jnp.asarray(piv_np)

    def round_step(i, carry):
        A, V = carry
        r = i % n_rounds
        ps, qs, real = ps_all[r], qs_all[r], real_all[r]
        app = A[ps, ps]
        aqq = A[qs, qs]
        apq = A[ps, qs]
        small = (jnp.abs(apq) <= jnp.finfo(A.dtype).tiny) | ~real
        tau = (aqq - app) / jnp.where(small, 1.0, 2.0 * apq)
        t = jnp.sign(tau) / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
        t = jnp.where(tau == 0.0, 1.0, t)
        c = 1.0 / jnp.sqrt(1.0 + t * t)
        s = jnp.where(small, 0.0, t * c)
        c = jnp.where(small, 1.0, c)
        # the round's implicit rotation matrix R has R[p,p] = R[q,q] =
        # c, R[p,q] = s, R[q,p] = -s per disjoint pair; expand to
        # per-index vectors and apply RᵀAR / VR as row+column pair
        # combinations:
        #   (RᵀA)[i, :] = cvec[i]·A[i, :] + svp[i]·A[partner[i], :]
        # with svp[i] = svec[partner[i]] (= R[partner[i], i])
        partner = partner_all[r]
        cvec = 1.0 + (c - 1.0) @ pq_hot_all[r]           # [d]
        svec = (s @ pq_hot_all[r]) * sign_all[r]         # [d]
        svp = jnp.take(svec, partner)
        B = cvec[:, None] * A + svp[:, None] * jnp.take(A, partner,
                                                        axis=0)
        A = (cvec[None, :] * B
             + svp[None, :] * jnp.take(B, partner, axis=1)) * piv_all[r]
        V = cvec[None, :] * V + svp[None, :] * jnp.take(V, partner,
                                                        axis=1)
        return A, V

    def one(C1):
        A = 0.5 * (C1 + C1.T)  # enforce exact symmetry
        A, V = lax.fori_loop(0, sweeps * n_rounds, round_step,
                             (A, eye))
        w = jnp.diagonal(A)
        order = jnp.argsort(w)
        return w[order], V[:, order]

    if C.ndim == 2:
        return one(C)
    batch = C.shape[:-2]
    w, V = jax.vmap(one)(C.reshape((-1, d, d)))
    return w.reshape(batch + (d,)), V.reshape(batch + (d, d))
