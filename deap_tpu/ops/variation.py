"""Fused variation plane — one-pass select-gather + crossover + mutation.

The reference's generation step (``varAnd``, algorithms.py:33-82) and our
:func:`deap_tpu.algorithms.var_and` port both execute the variation
plane as a chain of separate ops: materialise the selection gather,
compute both crossover children for every pair, interleave, compute a
full mutant population, and select between them row by row — at
pop = 100k × 100 genes that is six-plus full HBM sweeps of the genome
plane per generation. This module collapses the chain into **one pass**
while staying **bit-identical** to the unfused composition:

- every random draw (pair/row Bernoullis, crossover points, per-gene
  mutation masks and values) is replicated with *exactly* the key-split
  tree and jax.random calls of the unfused operators — see
  :func:`var_and_masks` / :func:`var_or_masks`;
- the apply step (:func:`apply_variation`) is then a pure function of
  those masks: per output row, gather self + partner (composing the
  selection indices, so selection's genome-plane gather never
  materialises separately), one segment-select for crossover, one
  masked write for mutation. Selects and adds of identical operands are
  bit-identical to the unfused ``where`` chains by construction —
  pinned by tests/test_fused_variation.py across all four EA loops.

Recognition is capability-based: crossover operators advertise a
``fused_segment_draw`` attribute (the draw that reproduces their cut
points — :mod:`deap_tpu.ops.crossover` tags ``cx_one_point`` and
``cx_two_point``) and mutation operators a ``fused_plan`` factory
(:mod:`deap_tpu.ops.mutation` tags ``mut_flip_bit``, ``mut_gaussian``,
``mut_uniform_int``). Anything else — or a genome pytree that is not a
single ``[n, L]`` array — falls back to the unfused composition, which
is bit-identical anyway; the decision is journaled as a
``variation_dispatch`` event either way.

Two apply backends share the mask contract:

- ``'xla'`` — the fused formulation below: XLA fuses the mask logic
  into the two gathers' consumers, so the plane is ~3 genome sweeps
  instead of 6+. The CPU/GPU path, and the default off-TPU.
- ``'kernel'`` — :func:`deap_tpu.ops.kernels.fused_variation`: a
  Pallas kernel that DMAs each tile's self/partner rows straight out
  of HBM and applies crossover + mutation in VMEM, one genome sweep.
  TPU only (the Pallas interpreter would be slower than XLA); its
  interpret-mode bit-parity against this module's XLA apply is pinned
  in tests/test_kernels.py.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["VariationPlan", "resolve_plan", "var_and_masks",
           "var_or_masks", "apply_variation", "pair_partner_positions"]


class VariationPlan(NamedTuple):
    """The fused plane's static description of a (mate, mutate) pair.

    ``mate_draw(key, L) -> (lo, hi)`` reproduces the crossover
    operator's cut draw as a half-open swap segment ``[lo, hi)``;
    ``mut_draw(key, L, dtype) -> (mask, arg)`` reproduces the mutation
    operator's per-gene draws (``arg`` is ``None`` for ``'flip'``, the
    additive noise for ``'add'``, the replacement values for
    ``'set'``)."""

    mate_draw: Callable
    mate_name: str
    mut_kind: str  # 'flip' | 'add' | 'set'
    mut_draw: Callable
    mut_name: str


def _partial_parts(op) -> Tuple[Callable, tuple, dict]:
    fn = getattr(op, "func", op)
    args = tuple(getattr(op, "args", ()) or ())
    kwargs = dict(getattr(op, "keywords", {}) or {})
    return fn, args, kwargs


def resolve_plan(toolbox) -> Optional[VariationPlan]:
    """A :class:`VariationPlan` for ``toolbox``'s (mate, mutate) pair,
    or ``None`` when either operator lacks fused support. Bound
    operator parameters must be keywords (the reference registration
    style, ``tb.register("mutate", mut_flip_bit, indpb=0.05)``);
    positional binds shift the ``(key, genome)`` call signature and are
    not recognised."""
    mate = getattr(toolbox, "mate", None)
    mutate = getattr(toolbox, "mutate", None)
    if mate is None or mutate is None:
        return None
    mate_fn, mate_args, mate_kwargs = _partial_parts(mate)
    mut_fn, mut_args, mut_kwargs = _partial_parts(mutate)
    seg_draw = getattr(mate_fn, "fused_segment_draw", None)
    mut_factory = getattr(mut_fn, "fused_plan", None)
    if seg_draw is None or mut_factory is None:
        return None
    if mate_args or mate_kwargs or mut_args:
        return None
    try:
        mut_kind, mut_draw = mut_factory(**mut_kwargs)
    except TypeError:  # missing/unknown bound params: not this config
        return None
    return VariationPlan(
        mate_draw=seg_draw,
        mate_name=getattr(mate_fn, "__name__", "?"),
        mut_kind=mut_kind,
        mut_draw=mut_draw,
        mut_name=getattr(mut_fn, "__name__", "?"),
    )


def single_genome_leaf(genomes) -> Optional[jnp.ndarray]:
    """The ``[n, L]`` array of a single-leaf genome pytree, or ``None``
    when the structure is not one the fused plane handles."""
    leaves = jax.tree_util.tree_leaves(genomes)
    if len(leaves) != 1 or leaves[0].ndim != 2:
        return None
    return leaves[0]


def pair_partner_positions(n: int) -> jnp.ndarray:
    """Row ``i``'s adjacent-pair mate: ``i ^ 1``, clamped so an odd
    trailing row partners itself (it never mates — var_and's zip
    drop)."""
    pos = jnp.arange(n, dtype=jnp.int32)
    return jnp.minimum(pos ^ 1, n - 1)


# ------------------------------------------------------------- var_and ----

def var_and_masks(key: jax.Array, n: int, L: int, cxpb: float,
                  mutpb: float, plan: VariationPlan, dtype):
    """Replicate :func:`deap_tpu.algorithms.var_and`'s draw tree
    bit-exactly, expanded to row level.

    Returns ``(cx_row [n], lo [n], hi [n], do_mut [n], mask [n, L],
    arg [n, L] | None)`` — the same bits the unfused composition would
    have consumed: ``split(key, 4)`` into pair/cx/row/mut keys, the
    crossover draw vmapped over ``split(k_cx, npairs)``, the mutation
    draw vmapped over ``split(k_mut, n)``."""
    npairs = n // 2
    k_pair, k_cx, k_ind, k_mut = jax.random.split(key, 4)

    if npairs:
        cx_keys = jax.random.split(k_cx, npairs)
        lo_p, hi_p = jax.vmap(lambda k: plan.mate_draw(k, L))(cx_keys)
        do_cx = jax.random.bernoulli(k_pair, cxpb, (npairs,))
        rep = lambda a: jnp.zeros(n, a.dtype).at[: 2 * npairs].set(
            jnp.repeat(a, 2))
        cx_row = jnp.zeros(n, bool).at[: 2 * npairs].set(
            jnp.repeat(do_cx, 2))
        lo = rep(lo_p.astype(jnp.int32))
        hi = rep(hi_p.astype(jnp.int32))
    else:
        cx_row = jnp.zeros(n, bool)
        lo = jnp.zeros(n, jnp.int32)
        hi = jnp.zeros(n, jnp.int32)

    mut_keys = jax.random.split(k_mut, n)
    mask, arg = jax.vmap(lambda k: plan.mut_draw(k, L, dtype))(mut_keys)
    do_mut = jax.random.bernoulli(k_ind, mutpb, (n,))
    return cx_row, lo, hi, do_mut, mask, arg


# -------------------------------------------------------------- var_or ----

def var_or_masks(key: jax.Array, n: int, lambda_: int, L: int,
                 cxpb: float, mutpb: float, plan: VariationPlan, dtype):
    """Replicate :func:`deap_tpu.algorithms.var_or`'s draw tree
    bit-exactly. Returns ``(base_idx [λ], partner_idx [λ], choice_cx,
    lo, hi, choice_mut, mask, arg)`` — base/partner compose the
    parent gathers into the fused apply."""
    k_u, k_p1, k_p2, k_pm, k_cx, k_mut = jax.random.split(key, 6)
    u = jax.random.uniform(k_u, (lambda_,))
    choice_cx = u < cxpb
    choice_mut = (u >= cxpb) & (u < cxpb + mutpb)

    i = jax.random.randint(k_p1, (lambda_,), 0, n)
    j = jax.random.randint(k_p2, (lambda_,), 0, n - 1)
    j = jnp.where(j >= i, j + 1, j)
    m = jax.random.randint(k_pm, (lambda_,), 0, n)
    base_idx = jnp.where(choice_cx, i, m)

    cx_keys = jax.random.split(k_cx, lambda_)
    lo, hi = jax.vmap(lambda k: plan.mate_draw(k, L))(cx_keys)
    mut_keys = jax.random.split(k_mut, lambda_)
    mask, arg = jax.vmap(lambda k: plan.mut_draw(k, L, dtype))(mut_keys)
    return (base_idx, j, choice_cx, lo.astype(jnp.int32),
            hi.astype(jnp.int32), choice_mut, mask, arg)


# --------------------------------------------------------------- apply ----

def _pair_swapped(rows: jnp.ndarray) -> jnp.ndarray:
    """Rows with each adjacent pair's members exchanged (an odd tail
    row maps to itself) — the var_and partner view, built by reshaping
    the already-gathered rows instead of a second full gather."""
    n = rows.shape[0]
    npairs = n // 2
    if npairs == 0:
        return rows
    head = rows[: 2 * npairs].reshape(npairs, 2, -1)[:, ::-1, :]
    head = head.reshape(2 * npairs, rows.shape[-1])
    if n == 2 * npairs:
        return head
    return jnp.concatenate([head, rows[2 * npairs:]], axis=0)


def apply_variation(genomes: jnp.ndarray,
                    src_idx: Optional[jnp.ndarray],
                    partner_idx: Optional[jnp.ndarray],
                    cx_row: jnp.ndarray, lo: jnp.ndarray,
                    hi: jnp.ndarray, mut_row: jnp.ndarray,
                    mut_mask: jnp.ndarray,
                    mut_arg: Optional[jnp.ndarray], mut_kind: str,
                    ) -> jnp.ndarray:
    """The fused XLA apply: composed gather(s) + one segment select +
    one masked mutation write.

    ``out[r] = mut(cx(genomes[src_idx[r]], genomes[partner_idx[r]]))``
    where crossover swaps columns ``[lo[r], hi[r])`` when ``cx_row[r]``
    and mutation rewrites ``mut_mask[r]`` genes when ``mut_row[r]`` —
    bit-identical to the unfused compute-both-then-select chains for
    the same masks. ``src_idx=None`` means rows are already in place.
    ``partner_idx=None`` means adjacent-pair partners (the var_and
    pairing): the partner view is then a pair-swap reshape of the
    already-gathered rows, so the whole plane costs ONE genome gather
    where the unfused chain pays a gather plus an interleave copy plus
    the discarded-candidate intermediates.
    """
    self_rows = (genomes if src_idx is None
                 else jnp.take(genomes, src_idx, axis=0))
    partner_rows = (_pair_swapped(self_rows) if partner_idx is None
                    else jnp.take(genomes, partner_idx, axis=0))
    L = genomes.shape[-1]
    col = jnp.arange(L, dtype=jnp.int32)[None, :]
    seg = cx_row[:, None] & (col >= lo[:, None]) & (col < hi[:, None])
    child = jnp.where(seg, partner_rows, self_rows)
    if mut_kind == "flip":
        mval = (~child.astype(bool)).astype(child.dtype)
    elif mut_kind == "add":
        mval = child + mut_arg
    elif mut_kind == "set":
        mval = mut_arg
    else:
        raise ValueError(f"unknown mut_kind {mut_kind!r}")
    m = mut_row[:, None] & mut_mask
    return jnp.where(m, mval, child)
