"""Genome initialisers and structural combinators.

Counterpart of /root/reference/deap/tools/init.py (initRepeat :3-25,
initIterate :27-52, initCycle :54-75). In the tensor backend an
"attribute generator" is a pure function ``key -> array`` and an
individual initialiser is built by composing them; populations are built
by vmapping the individual initialiser over split keys
(:func:`deap_tpu.core.population.init_population`).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp


# ---- attribute/genome generators (the `attr_bool`-style building blocks) ----

def bernoulli_genome(length: int, p: float = 0.5, dtype=jnp.bool_):
    """`attr_bool` x length: random bitstring (cf. examples/ga/onemax.py)."""
    def init(key):
        return jax.random.bernoulli(key, p, (length,)).astype(dtype)
    return init


def uniform_genome(length: int, minval: float = 0.0, maxval: float = 1.0,
                   dtype=jnp.float32):
    """`random.uniform` x length: real-valued genome."""
    def init(key):
        return jax.random.uniform(key, (length,), dtype=dtype,
                                  minval=minval, maxval=maxval)
    return init


def normal_genome(length: int, mu: float = 0.0, sigma: float = 1.0,
                  dtype=jnp.float32):
    def init(key):
        return mu + sigma * jax.random.normal(key, (length,), dtype=dtype)
    return init


def randint_genome(length: int, low: int, high: int, dtype=jnp.int32):
    """`random.randint(low, high)` x length — high inclusive like the
    reference's random.randint."""
    def init(key):
        return jax.random.randint(key, (length,), low, high + 1, dtype=dtype)
    return init


def permutation_genome(length: int, dtype=jnp.int32):
    """`random.sample(range(n), n)`: permutation genome (TSP, NQueens)."""
    def init(key):
        return jax.random.permutation(key, length).astype(dtype)
    return init


def constant_genome(value: jnp.ndarray):
    def init(key):
        del key
        return jnp.asarray(value)
    return init


# ---- structural combinators (initRepeat / initIterate / initCycle) ----

def init_repeat(genome_init: Callable, n: int):
    """Stack ``n`` draws of ``genome_init`` — initRepeat (init.py:3-25)."""
    def init(key):
        return jax.vmap(genome_init)(jax.random.split(key, n))
    return init


def init_iterate(genome_inits: Sequence[Callable]):
    """Concatenate one draw of each generator — initIterate (init.py:27-52),
    for heterogeneous genomes laid out as one flat vector."""
    def init(key):
        keys = jax.random.split(key, len(genome_inits))
        parts = [jnp.atleast_1d(g(k)) for g, k in zip(genome_inits, keys)]
        return jnp.concatenate(parts)
    return init


def init_cycle(genome_inits: Sequence[Callable], n: int = 1):
    """``n`` cycles through the generators — initCycle (init.py:54-75)."""
    def init(key):
        keys = jax.random.split(key, n)
        return jnp.concatenate([init_iterate(genome_inits)(k) for k in keys])
    return init
