"""Constraint handling — penalty decorators for evaluation functions.

Counterpart of /root/reference/deap/tools/constraint.py: ``DeltaPenalty``
(:10-64) and ``ClosestValidPenalty`` (:68-132) wrap an evaluate function
so infeasible individuals receive a penalised fitness instead. Where the
reference branches per individual in Python, these wrap *batched*
evaluators: feasibility is a boolean mask and the penalty applies via
``jnp.where``, so decorated evaluators stay jittable and fuse into the
generation step.

Toolbox usage mirrors the reference's tutorial
(doc/tutorials/advanced/constraints.rst)::

    tb.register("evaluate", my_eval)
    tb.decorate("evaluate", delta_penalty(feasible_fn, 7.0, distance_fn,
                                          spec=spec))
"""

from __future__ import annotations

from functools import wraps
from typing import Callable, Optional, Sequence, Union

import jax.numpy as jnp

from deap_tpu.core.fitness import FitnessSpec


def _sign_weights(spec: FitnessSpec) -> jnp.ndarray:
    """±1 per objective (the reference's ``1 if w >= 0 else -1``,
    constraint.py:55)."""
    return jnp.where(spec.warray >= 0, 1.0, -1.0)


def _as_obj(values: jnp.ndarray, nobj: int) -> jnp.ndarray:
    v = jnp.asarray(values, jnp.float32)
    if v.ndim == 1:
        v = v[:, None]
    if v.shape[-1] == 1 and nobj > 1:
        v = jnp.broadcast_to(v, v.shape[:-1] + (nobj,))
    return v


def delta_penalty(feasibility: Callable, delta: Union[float, Sequence[float]],
                  distance: Optional[Callable] = None,
                  spec: FitnessSpec = FitnessSpec((-1.0,))) -> Callable:
    """Penalised fitness Δ_i − w_i·d_i(x) for infeasible rows
    (constraint.py:10-64).

    :param feasibility: batched ``genomes -> bool[n]``.
    :param delta: scalar or per-objective constants, worse than any real
        fitness.
    :param distance: optional batched ``genomes -> f32[n] | f32[n, nobj]``
        growing away from the feasible region.
    """
    nobj = spec.nobj
    delta_arr = jnp.broadcast_to(
        jnp.asarray(delta, jnp.float32).reshape(-1), (nobj,))
    signs = _sign_weights(spec)

    def decorator(func):
        @wraps(func)
        def wrapper(genomes, *args, **kwargs):
            values = _as_obj(func(genomes, *args, **kwargs), nobj)
            feas = feasibility(genomes)
            if distance is not None:
                dists = _as_obj(distance(genomes), nobj)
            else:
                dists = jnp.zeros_like(values)
            penal = delta_arr[None, :] - signs[None, :] * dists
            return jnp.where(feas[:, None], values, penal)

        return wrapper

    return decorator


def closest_valid_penalty(feasibility: Callable, feasible: Callable,
                          alpha: float,
                          distance: Optional[Callable] = None,
                          spec: FitnessSpec = FitnessSpec((-1.0,))) -> Callable:
    """Penalised fitness f_i(valid(x)) − α·w_i·d_i(valid(x), x)
    (constraint.py:68-132).

    :param feasible: batched projection ``genomes -> genomes`` returning
        the closest feasible individual per row.
    :param distance: optional batched ``(valid_genomes, genomes) ->
        f32[n] | f32[n, nobj]``.
    """
    nobj = spec.nobj
    signs = _sign_weights(spec)

    def decorator(func):
        @wraps(func)
        def wrapper(genomes, *args, **kwargs):
            values = _as_obj(func(genomes, *args, **kwargs), nobj)
            feas = feasibility(genomes)
            projected = feasible(genomes)
            f_fbl = _as_obj(func(projected, *args, **kwargs), nobj)
            if distance is not None:
                dists = _as_obj(distance(projected, genomes), nobj)
            else:
                dists = jnp.zeros_like(values)
            penal = f_fbl - alpha * signs[None, :] * dists
            return jnp.where(feas[:, None], values, penal)

        return wrapper

    return decorator


# DEAP-style aliases, including the reference's kept misspellings
# (constraint.py:66, :134).
DeltaPenalty = delta_penalty
DeltaPenality = delta_penalty
ClosestValidPenalty = closest_valid_penalty
ClosestValidPenality = closest_valid_penalty
