"""Selection operators — batched, index-returning.

Counterpart of /root/reference/deap/tools/selection.py. Every operator
takes weighted fitness values ``w: f32[n, nobj]`` (the comparison
currency, see core.fitness) and returns ``int32[k]`` indices into the
population; callers materialise the selection with
:func:`deap_tpu.core.population.gather`. Returning indices keeps
selection a pure gather — the reference returns *references* into the
input list and relies on ``varAnd`` to clone (algorithms.py:68), which a
gather subsumes.

The lexicase family takes the raw per-case error matrix plus per-case
weights, matching the reference's use of fitness.values as cases
(selection.py:214-330).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu.core.fitness import lex_gt, lex_sort_desc


def _lex_sort_asc(w):
    keys = tuple(w[..., j] for j in range(w.shape[-1] - 1, -1, -1))
    return jnp.lexsort(keys)


def _tournament_winners(w, aspirants):
    """Lexicographic-best aspirant per row; ties go to the earliest drawn,
    matching Python ``max`` (selection.py:51-69)."""
    t = aspirants.shape[-1]
    best = aspirants[..., 0]
    for j in range(1, t):
        cand = aspirants[..., j]
        better = lex_gt(jnp.take(w, cand, axis=0), jnp.take(w, best, axis=0))
        best = jnp.where(better, cand, best)
    return best


def sel_random(key, w, k):
    """k uniform draws with replacement (selection.py:12-24)."""
    n = w.shape[0]
    return jax.random.randint(key, (k,), 0, n)


def sel_best(key, w, k):
    """k lexicographically-best (selection.py:27-36). Stable."""
    del key
    return lex_sort_desc(w)[:k]


def sel_worst(key, w, k):
    """k lexicographically-worst (selection.py:39-48). Stable ascending."""
    del key
    return _lex_sort_asc(w)[:k]


def tournament_aspirants(key, n, k, tournsize):
    """The tournament's aspirant draw, factored out so every consumer
    shares one RNG contract: :func:`sel_tournament` resolves winners
    from it here, and the fused variation plane
    (:mod:`deap_tpu.ops.variation`) composes those winners straight
    into its one-pass gather+crossover+mutation apply — selection's
    genome-plane gather never materialises separately, and bit-parity
    between the fused and unfused generation steps holds by
    construction."""
    return jax.random.randint(key, (k, tournsize), 0, n)


def sel_tournament(key, w, k, tournsize):
    """k tournaments of tournsize uniform aspirants (selection.py:51-69)."""
    aspirants = tournament_aspirants(key, w.shape[0], k, tournsize)
    return _tournament_winners(w, aspirants)


def sel_tournament_sorted(key, w, k, tournsize):
    """Tournament selection via ranks — same winner distribution as
    :func:`sel_tournament`, one lexsort instead of ``tournsize``
    per-aspirant fitness gathers.

    A tournament's winner is the lexicographically best of ``tournsize``
    uniform draws; with ``order`` the best-first sort of the population,
    that is exactly ``order[min(tournsize uniform ranks)]``. Identical
    in distribution for distinct fitness values; ties are broken by
    population index (stable sort) rather than by draw order as in the
    reference's Python ``max`` (selection.py:51-69) — both are
    fitness-indistinguishable. Preferable on large populations where the
    aspirant gathers dominate the generation step.
    """
    order = lex_sort_desc(w)
    ranks = jax.random.randint(key, (tournsize, k), 0, w.shape[0])
    return jnp.take(order, jnp.min(ranks, axis=0))


def counting_order_desc(values: jnp.ndarray, low: int, high: int,
                        mode: str = "auto") -> jnp.ndarray:
    """Best-first permutation of integer-valued fitnesses WITHOUT a
    comparison sort — a counting sort over ``high - low + 1`` buckets.

    Bit-exact with :func:`deap_tpu.core.fitness.lex_sort_desc` on a
    single integer-valued objective (both are stable: ties keep
    ascending population index), but O(n·B) streaming instead of XLA's
    O(n log² n) sorting network — the difference is most of a
    generation at pop ≈ 100k, where the full sort dominates the fused
    variation kernel (BASELINE.md). Valid whenever fitness takes
    integer values in ``[low, high]`` — OneMax-style bit counts, match
    counts, error counts.

    ``mode`` picks how the stable within-bucket occurrence numbers are
    computed; both produce identical output:

    - ``"scan"`` — full-length ``cumsum`` over the ``[n, B]`` one-hot.
      On TPU, XLA lowers that cumsum to ~log2(n) shifted-add passes
      over the whole matrix (~17 × 40 MB of HBM at n=100k, B=101) —
      the dominant term of the binned tournament.
    - ``"mxu"`` — tiled prefix: rows in tiles of 128, the within-tile
      inclusive prefix is ``tril(ones(128,128)) @ onehot_tile`` on the
      MXU (bf16 inputs are exact 0/1, f32 accumulation holds counts
      ≤ 128 exactly) and tiles are stitched with one tiny ``[n/128,
      B]`` exclusive scan. Same O(n·B) memory, but the log-pass
      full-matrix traffic collapses into one matmul sweep.
    - ``"auto"`` — mxu on TPU, scan elsewhere (CPU cumsum is a cheap
      serial loop; the matmul formulation only pays off on the MXU).
    """
    n = values.shape[0]
    nbins = int(high) - int(low) + 1
    b = (jnp.round(values).astype(jnp.int32) - low).clip(0, nbins - 1)
    if mode == "auto":
        mode = "mxu" if jax.default_backend() == "tpu" else "scan"
    if mode == "mxu" and n >= (1 << 24):
        # f32 tile-base accumulation is exact only to 2^24; beyond that
        # the permutation would corrupt silently — the int32 cumsum
        # path stays exact to 2^31
        mode = "scan"
    if mode == "scan":
        onehot = b[:, None] == jnp.arange(nbins, dtype=jnp.int32)[None, :]
        # occurrence number of each row within its bucket (0-based, stable)
        within = jnp.take_along_axis(
            jnp.cumsum(onehot, axis=0), b[:, None], axis=1)[:, 0] - 1
        counts = onehot.sum(0)
    elif mode == "mxu":
        T = 128
        G = -(-n // T)
        # padding rows get bucket id nbins -> all-zero one-hot rows,
        # invisible to counts and (being last) to every real prefix
        bp = jnp.full(G * T, nbins, jnp.int32).at[:n].set(b)
        onehot = (bp[:, None] == jnp.arange(nbins, dtype=jnp.int32)
                  ).reshape(G, T, nbins).astype(jnp.bfloat16)
        tril = jnp.tril(jnp.ones((T, T), jnp.bfloat16))
        ptile = jax.lax.dot_general(
            tril, onehot, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [T, G, B]
        ptile = ptile.transpose(1, 0, 2)             # [G, T, B] inclusive
        tot = ptile[:, -1, :]                        # [G, B]
        base = jnp.cumsum(tot, axis=0) - tot         # exclusive over tiles
        incl = (ptile + base[:, None, :]).reshape(G * T, nbins)
        within = (jnp.take_along_axis(
            incl[:n], b[:, None], axis=1)[:, 0]).astype(jnp.int32) - 1
        counts = tot.sum(0).astype(jnp.int32)
    else:
        raise ValueError(f"unknown counting_order_desc mode {mode!r}")
    # descending buckets: bucket b starts after all strictly-better ones
    starts_desc = jnp.cumsum(counts[::-1])[::-1] - counts
    pos = jnp.take(starts_desc, b) + within
    return jnp.zeros(n, jnp.int32).at[pos].set(
        jnp.arange(n, dtype=jnp.int32), unique_indices=True,
        indices_are_sorted=False)


def sel_tournament_binned(key, w, k, tournsize, low: int, high: int):
    """:func:`sel_tournament_sorted` for integer-valued single-objective
    fitness: identical winners for the same key (the rank→index
    permutation is bit-identical), with the full lexsort replaced by
    :func:`counting_order_desc`. ``w`` is ``[n, 1]`` weighted values
    taking integer values in ``[low, high]``."""
    if not isinstance(w, jax.core.Tracer) and w.shape[0]:
        # counting_order_desc silently clips out-of-range values and
        # rounds non-integers into edge buckets — a misranking with no
        # signal. When called outside jit (values concrete), validate
        # loudly; the reductions run on device and only three scalars
        # cross to the host (an eager caller is sync-bound anyway).
        v = w[:, 0]
        mn, mx = float(v.min()), float(v.max())
        if mn < low or mx > high:
            raise ValueError(
                f"sel_tournament_binned: fitness values span "
                f"[{mn}, {mx}], outside the declared integer "
                f"range [{low}, {high}]")
        if not bool(jnp.all(jnp.abs(v - jnp.round(v)) <= 1e-6)):
            raise ValueError(
                "sel_tournament_binned: fitness values are not "
                "integer-valued; the counting sort would misrank them")
    order = counting_order_desc(w[:, 0], low, high)
    ranks = jax.random.randint(key, (tournsize, k), 0, w.shape[0])
    return jnp.take(order, jnp.min(ranks, axis=0))


def _validate_positive_mass(values, name):
    """Roulette-family contract: positive fitness mass. Like
    ``sel_tournament_binned``'s range check, validated loudly when the
    values are concrete (an eager caller is sync-bound anyway); under
    jit the contract is the caller's responsibility — the reference
    makes the same silent assumption (selection.py:71-103)."""
    if not isinstance(values, jax.core.Tracer) and values.shape[0]:
        if float(values.min()) < 0 or float(values.sum()) <= 0:
            raise ValueError(
                f"{name}: fitness-proportionate selection needs "
                f"non-negative values with positive total mass; got "
                f"min={float(values.min())}, sum={float(values.sum())}")


def sel_roulette(key, w, k, values: Optional[jnp.ndarray] = None):
    """Fitness-proportionate selection on the first objective
    (selection.py:71-103): individuals sorted best-first, k spins over the
    cumulative raw first-objective values. ``values`` defaults to the
    first column of ``w`` (equal to raw values for weight +1; the
    reference likewise only makes sense for positive maximised fitness).
    """
    if values is None:
        values = w[..., 0]
    _validate_positive_mass(values, "sel_roulette")
    order = lex_sort_desc(w)
    sorted_vals = jnp.take(values, order)
    cs = jnp.cumsum(sorted_vals)
    total = cs[-1]
    u = jax.random.uniform(key, (k,)) * total
    # first index with cumsum > u (reference: `if sum_ > u: break`)
    pick = jnp.searchsorted(cs, u, side="right")
    return jnp.take(order, jnp.clip(pick, 0, w.shape[0] - 1))


def sel_stochastic_universal_sampling(key, w, k, values: Optional[jnp.ndarray] = None):
    """SUS (Baker 1987; selection.py:182-212): k evenly spaced pointers
    from one random start over the best-first cumulative distribution."""
    if values is None:
        values = w[..., 0]
    _validate_positive_mass(values, "sel_stochastic_universal_sampling")
    order = lex_sort_desc(w)
    sorted_vals = jnp.take(values, order)
    cs = jnp.cumsum(sorted_vals)
    total = cs[-1]
    distance = total / k
    start = jax.random.uniform(key, ()) * distance
    points = start + distance * jnp.arange(k)
    # first index with cumsum >= p (reference: `while sum_ < p`)
    pick = jnp.searchsorted(cs, points, side="left")
    return jnp.take(order, jnp.clip(pick, 0, w.shape[0] - 1))


def sel_double_tournament(key, w, lengths, k, fitness_size, parsimony_size,
                          fitness_first):
    """Luke & Panait's double (fitness + parsimony) tournament
    (selection.py:105-180). ``lengths`` is the per-individual genome size
    used by the 2-way size tournament; the shorter wins with prob
    ``parsimony_size / 2`` (0.5 on ties).
    """
    n = w.shape[0]
    base_prob = parsimony_size / 2.0
    ka, ku = jax.random.split(key)

    def size_round(ku, i1, i2):
        l1 = jnp.take(lengths, i1)
        l2 = jnp.take(lengths, i2)
        first = jnp.where(l1 > l2, i2, i1)
        second = jnp.where(l1 > l2, i1, i2)
        p = jnp.where(l1 == l2, 0.5, base_prob)
        u = jax.random.uniform(ku, i1.shape)
        return jnp.where(u < p, first, second)

    if fitness_first:
        aspirants = jax.random.randint(ka, (k, 2, fitness_size), 0, n)
        finalists = _tournament_winners(w, aspirants)  # [k, 2]
        return size_round(ku, finalists[:, 0], finalists[:, 1])
    else:
        aspirants = jax.random.randint(ka, (k, fitness_size, 2), 0, n)
        cands = size_round(ku, aspirants[..., 0], aspirants[..., 1])  # [k, fs]
        return _tournament_winners(w, cands)


# ------------------------------------------------------------- lexicase ----

def _masked_extreme(vals, mask, maximize):
    hi = jnp.max(jnp.where(mask, vals, -jnp.inf))
    lo = jnp.min(jnp.where(mask, vals, jnp.inf))
    return jnp.where(maximize, hi, lo)


def _masked_median(vals, mask):
    s = jnp.sort(jnp.where(mask, vals, jnp.inf))
    m = jnp.sum(mask)
    lo = jnp.take(s, jnp.maximum((m - 1) // 2, 0))
    hi = jnp.take(s, jnp.clip(m // 2, 0, vals.shape[0] - 1))
    return 0.5 * (lo + hi)


def _lexicase_select(key, values, weights, k, survive_fn):
    """Shared scaffold (selection.py:214-330): per pick, shuffle cases and
    successively filter the candidate mask; keeping the filter running
    after one candidate remains is a no-op, so no data-dependent exit is
    needed — the loop is a clean `lax.scan` over cases."""
    n, ncases = values.shape
    maximize = weights > 0

    def one(key):
        kp, kc = jax.random.split(key)
        order = jax.random.permutation(kp, ncases)

        def body(mask, case):
            v = values[:, case]
            best = _masked_extreme(v, mask, maximize[case])
            keep = survive_fn(v, mask, best, maximize[case], case)
            return mask & keep, None

        mask, _ = lax.scan(body, jnp.ones(n, bool), order)
        p = mask / jnp.sum(mask)
        return jax.random.choice(kc, n, p=p)

    return jax.vmap(one)(jax.random.split(key, k))


def sel_lexicase(key, values, weights, k):
    """Lexicase selection (Spector; selection.py:214-243): survive a case
    only by exactly matching the elite error on it."""
    def survive(v, mask, best, maximize, case):
        del mask, maximize, case
        return v == best
    return _lexicase_select(key, values, jnp.asarray(weights), k, survive)


def sel_epsilon_lexicase(key, values, weights, k, epsilon):
    """ε-lexicase (La Cava 2016, epsilon_y; selection.py:247-280)."""
    def survive(v, mask, best, maximize, case):
        del mask, case
        return jnp.where(maximize, v >= best - epsilon, v <= best + epsilon)
    return _lexicase_select(key, values, jnp.asarray(weights), k, survive)


def sel_automatic_epsilon_lexicase(key, values, weights, k):
    """Automatic-ε-lexicase (lambda_epsilon_y; selection.py:283-330):
    ε = median absolute deviation of the surviving candidates' errors."""
    def survive(v, mask, best, maximize, case):
        del case
        med = _masked_median(v, mask)
        mad = _masked_median(jnp.abs(v - med), mask)
        return jnp.where(maximize, v >= best - mad, v <= best + mad)
    return _lexicase_select(key, values, jnp.asarray(weights), k, survive)
