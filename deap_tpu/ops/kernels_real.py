"""Fused Pallas generation kernel for real-valued GAs.

The real-genome twin of :func:`deap_tpu.ops.kernels.fused_variation_eval`
(bitstrings) for the continuous eaSimple configuration — blend crossover
(reference tools/crossover.py:241-260) + gaussian mutation
(tools/mutation.py:17-48) + the fitness function — fused so each
``[n, L]`` float32 genome tile crosses HBM↔VMEM once per generation.
With ``prng='hw'`` every per-gene draw (blend γ, flip gates, Box-Muller
normals) comes from the TPU core's hardware PRNG and never touches HBM;
this removes the dominant random-tensor traffic of the XLA path (four
``[n, L]`` uniforms per generation).

Distributional semantics match the reference operators exactly:

- blend: per-gene ``γ = (1+2α)·u - α``; both children of a pair use the
  *same* γ draws, child = ``(1-γ)·self + γ·partner`` (the two reference
  output formulas, crossover.py:256-258, are this one expression under
  the self/partner naming).
- gaussian: per-gene Bernoulli(indpb) gate, then ``x += N(μ, σ)``
  (mutation.py:43-47), row-gated by var_and's mutpb
  (algorithms.py:76-80).

Evaluation is compiled into the kernel: pass ``evaluate="rastrigin"`` /
``"sphere"`` or any ``fn(child_tile, valid_col_mask) -> [TI, 1]``
traceable on the ``[TI, Lp]`` float32 tile.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deap_tpu.ops.kernels import (
    _auto_interpret,
    _pair_consistent,
    _resolve_prng,
    _round_up,
    _u01,
    run_fused_kernel,
)

__all__ = ["fused_variation_eval_real", "eval_rastrigin", "eval_sphere"]

_TWO_PI = 6.283185307179586


def eval_rastrigin(child: jnp.ndarray, valid_col: jnp.ndarray) -> jnp.ndarray:
    """Rastrigin on a genome tile (benchmarks/__init__.py:87-91):
    ``10·N + Σ x² - 10·cos(2πx)`` over the real (unpadded) columns."""
    term = child * child - 10.0 * jnp.cos(_TWO_PI * child)
    n_real = jnp.sum(valid_col[0:1, :].astype(jnp.float32))
    return (10.0 * n_real
            + jnp.sum(jnp.where(valid_col, term, 0.0), axis=1,
                      keepdims=True))


def eval_sphere(child: jnp.ndarray, valid_col: jnp.ndarray) -> jnp.ndarray:
    """Σ x² (benchmarks/__init__.py:38-41)."""
    return jnp.sum(jnp.where(valid_col, child * child, 0.0), axis=1,
                   keepdims=True)


_EVALS = {"rastrigin": eval_rastrigin, "sphere": eval_sphere}


def _boxmuller(u1: jnp.ndarray, u2: jnp.ndarray) -> jnp.ndarray:
    """Standard normals from two U[0,1) planes; ``1-u1 ∈ (0, 1]`` keeps
    the log finite (24-bit uniforms never reach 1.0)."""
    r = jnp.sqrt(-2.0 * jnp.log1p(-u1))
    return r * jnp.cos(_TWO_PI * u2)


def _real_body(g, pairu, gammau, rowu, flipu, nu1, nu2, *, n, L, TI, cxpb,
               mutpb, indpb, alpha, mu, sigma, evaluate, tile_idx):
    """One [TI, Lp] tile: blend cx over adjacent pairs + gaussian
    mutation + in-kernel evaluation. ``pairu``/``gammau`` must already be
    pair-consistent."""
    Lp = g.shape[1]
    col = jax.lax.broadcasted_iota(jnp.int32, (TI, Lp), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (TI, Lp), 0)
    valid_col = col < L

    # adjacent pairing via roll, exactly the bitstring kernel's scheme
    up = pltpu.roll(g, TI - 1, 0)
    dn = pltpu.roll(g, 1, 0)
    partner = jnp.where((row % 2) == 0, up, dn)
    grow = row + tile_idx * TI
    has_partner = jnp.bitwise_or(grow, 1) < n

    do_cx = (pairu[:, 0:1] < cxpb) & has_partner[:, 0:1]
    gamma = (1.0 + 2.0 * alpha) * gammau - alpha
    blended = (1.0 - gamma) * g + gamma * partner
    child = jnp.where(do_cx & valid_col, blended, g)

    do_mut = rowu < mutpb
    z = _boxmuller(nu1, nu2)
    step = jnp.where((flipu < indpb) & do_mut & valid_col,
                     mu + sigma * z, 0.0)
    child = child + step

    return child, evaluate(child, valid_col)


def _real_kernel_bits(g_ref, pairbits_ref, rowbits_ref, genebits_ref,
                      out_ref, fit_ref, *, n, L, Lp, **kw):
    TI = g_ref.shape[0]
    gb = genebits_ref[:]
    pairu = _u01(_pair_consistent(pairbits_ref[:]))
    gammau = _u01(_pair_consistent(gb[:, 0:Lp]))
    child, fit = _real_body(
        g_ref[:], pairu, gammau, _u01(rowbits_ref[:][:, 0:1]),
        _u01(gb[:, Lp:2 * Lp]), _u01(gb[:, 2 * Lp:3 * Lp]),
        _u01(gb[:, 3 * Lp:4 * Lp]), n=n, L=L, TI=TI,
        tile_idx=pl.program_id(0), **kw)
    out_ref[:] = child
    fit_ref[:] = fit


def _real_kernel_hw(seed_ref, g_ref, out_ref, fit_ref, *, n, L, Lp, **kw):
    TI = g_ref.shape[0]
    i = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0] + i)
    draw = lambda cols: pltpu.bitcast(
        pltpu.prng_random_bits((TI, cols)), jnp.uint32)
    # pair (4) + row (1) draws share one block: separate calls each
    # cost a full vreg generation per 8 sublanes at <4% lane use
    prbits = draw(8)
    pairu = _u01(_pair_consistent(prbits[:, 0:4]))
    gammau = _u01(_pair_consistent(draw(Lp)))
    child, fit = _real_body(
        g_ref[:], pairu, gammau, _u01(prbits[:, 4:5]), _u01(draw(Lp)),
        _u01(draw(Lp)), _u01(draw(Lp)), n=n, L=L, TI=TI, tile_idx=i, **kw)
    out_ref[:] = child
    fit_ref[:] = fit


def fused_variation_eval_real(
        key: jax.Array, genomes: jnp.ndarray, *, cxpb: float, mutpb: float,
        indpb: float, alpha: float = 0.5, mu: float = 0.0,
        sigma: float = 1.0,
        evaluate: Union[str, Callable] = "rastrigin",
        prng: str = "auto", block_i: int = 256,
        interpret: Optional[bool] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One fused eaSimple variation+evaluation pass over f32 genomes.

    Equivalent in distribution to ``var_and`` with ``cx_blend(alpha)`` +
    ``mut_gaussian(mu, sigma, indpb)`` followed by a full evaluation —
    the continuous-GA generation (BASELINE.md's rastrigin_n30_pop100k
    config) in one HBM round trip.

    :param genomes: ``f32[n, L]``.
    :param evaluate: built-in name (``"rastrigin"``, ``"sphere"``) or a
        traceable ``fn(child_tile [TI, Lp], valid_col bool[TI, Lp]) ->
        f32[TI, 1]``.
    :returns: ``(children f32[n, L], fitness f32[n])``.
    """
    n, L = genomes.shape
    assert block_i % 2 == 0, "pairs must not straddle tiles"
    if isinstance(evaluate, str):
        if evaluate not in _EVALS:
            raise ValueError(
                f"unknown evaluate {evaluate!r}; built-ins are "
                f"{sorted(_EVALS)} (or pass a callable)")
        ev = _EVALS[evaluate]
    else:
        ev = evaluate
    Lp = _round_up(L, 128)
    ni = _round_up(n, block_i)
    interp = _auto_interpret(interpret)
    prng = _resolve_prng(prng, interp)
    g = jnp.pad(genomes.astype(jnp.float32), ((0, ni - n), (0, Lp - L)))

    common = dict(n=n, L=L, Lp=Lp, cxpb=cxpb, mutpb=mutpb, indpb=indpb,
                  alpha=alpha, mu=mu, sigma=sigma, evaluate=ev)
    out, fit = run_fused_kernel(
        key, g,
        kernel_hw=functools.partial(_real_kernel_hw, **common),
        kernel_bits=functools.partial(_real_kernel_bits, **common),
        prng=prng, interp=interp, block_i=block_i, genebit_cols=4 * Lp,
        out_dtype=jnp.float32)
    return out[:n, :L], fit[:n, 0]
