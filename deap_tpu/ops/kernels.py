"""Pallas TPU kernels for the hot ops.

Two kernels cover the framework's dominant inner loops:

1. :func:`dominated_counts` — tiled pairwise Pareto-domination counting
   for non-dominated sorting (the O(MN²) heart of NSGA-II, reference
   deap/tools/emo.py:53-117 / selSPEA2 emo.py:692-720). The XLA
   formulation in :mod:`deap_tpu.mo.emo` materialises the full ``[n, n]``
   dominance matrix in HBM (2.5 GB of bools at n=50k); this kernel
   streams ``[TI, m] × [m, TJ]`` tiles through VMEM and writes only the
   ``[n]`` count vector, so non-dominated sorting scales to populations
   that the matrix path cannot hold.

2. :func:`fused_variation_eval` — one-pass bitstring generation:
   two-point crossover over adjacent pairs + flip-bit mutation + fitness
   (row popcount), the eaSimple/varAnd hot loop of the reference
   (deap/algorithms.py:68-82, tools/crossover.py:37-60,
   tools/mutation.py:124-142) fused so each genome tile crosses
   HBM↔VMEM exactly once per generation. With ``prng='hw'`` the per-gene
   random bits come from the TPU core's hardware PRNG
   (``pltpu.prng_random_bits``) and never touch HBM at all — the
   dominant random tensor (4 bytes/gene) simply disappears.

Both kernels run under the Pallas interpreter off-TPU (``interpret`` is
auto-detected), except the hardware-PRNG path, which exists only on real
TPU cores; tests cover the bit-input path everywhere and the hw path on
TPU. Distributional semantics match the reference operators exactly
(two-point draw per tools/crossover.py:44-50; per-gene indpb Bernoulli
per tools/mutation.py:124-142); RNG streams differ, as everywhere in
this framework.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "dominated_counts",
    "dominated_weight_sums",
    "dominated_weight_maxes",
    "strengths_tiled",
    "nd_rank_tiled",
    "fused_variation",
    "fused_variation_eval",
    "run_fused_kernel",
    "gp_grouped_dispatch",
]

_INV24 = 1.0 / (1 << 24)


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is None:
        return jax.default_backend() not in ("tpu",)
    return interpret


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _u01(bits: jnp.ndarray) -> jnp.ndarray:
    """uint32 bits → U[0, 1) float32 (top 24 bits). Mosaic has no
    uint32→f32 cast, so route through int32 (sign bit is clear after the
    shift)."""
    i = jax.lax.bitcast_convert_type(bits >> jnp.uint32(8), jnp.int32)
    return i.astype(jnp.float32) * _INV24


# ------------------------------------------------------ dominance counting ----

def _dom_counts_kernel(wi_ref, wjt_ref, rem_ref, out_ref):
    """One [TI, TJ] tile of the dominance matrix, reduced over j on the
    fly. dom[i, j] = all_k(w[j,k] >= w[i,k]) & any_k(w[j,k] > w[i,k]),
    the weighted-value domination test of base.Fitness.dominates
    (reference deap/base.py:209-224)."""
    j = pl.program_id(1)
    m = wi_ref.shape[1]
    geq = None
    gt = None
    for k in range(m):  # m = nobj is tiny and static: unrolled
        a = wi_ref[:, k : k + 1]   # [TI, 1]
        b = wjt_ref[k : k + 1, :]  # [1, TJ]
        ge = b >= a
        g = b > a
        geq = ge if geq is None else (geq & ge)
        gt = g if gt is None else (gt | g)
    dom = (geq & gt).astype(jnp.float32) * rem_ref[0:1, :]
    counts = jnp.sum(dom, axis=1, keepdims=True)  # [TI, 1]

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += counts


def dominated_weight_sums(w: jnp.ndarray, weights: jnp.ndarray, *,
                          block_i: int = 256, block_j: int = 512,
                          interpret: Optional[bool] = None) -> jnp.ndarray:
    """``out[i] = Σ_{j dominates i} weights[j]`` without ever
    materialising the [n, n] dominance matrix.

    With 0/1 weights this is a dominator count; with SPEA2 strengths it
    is the raw fitness R(i) (emo.py:720-724) — both stream [TI, m] ×
    [m, TJ] tiles through VMEM.

    :param w: ``f32[n, nobj]`` weighted fitness values (maximisation).
    :param weights: ``f32[n]`` per-dominator weights (bools accepted).
    :returns: ``f32[n]``.
    """
    n, m = w.shape
    # the same padded array is viewed in block_i-rows (i side) and
    # block_j-columns (j side); pad to a common multiple so the grid
    # covers every row/column for any block combination
    npad = _round_up(n, math.lcm(block_i, block_j))
    wp = jnp.pad(w.astype(jnp.float32), ((0, npad - n), (0, 0)),
                 constant_values=-jnp.inf)  # padded rows dominate nothing
    rem = jnp.pad(weights.astype(jnp.float32), (0, npad - n))[None, :]
    out = pl.pallas_call(
        _dom_counts_kernel,
        grid=(npad // block_i, npad // block_j),
        in_specs=[
            pl.BlockSpec((block_i, m), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((m, block_j), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_j), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_i, 1), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((npad, 1), jnp.float32),
        interpret=_auto_interpret(interpret),
    )(wp, wp.T, rem)
    return out[:n, 0]


def _dom_maxes_kernel(wq_ref, wjt_ref, rem_ref, out_ref):
    """One [TI, TJ] tile of ``max_j(weights[j] · dom[j → query i])``,
    reduced over j on the fly — the max-combining sibling of
    :func:`_dom_counts_kernel` (weights must be >= 0; 0 encodes
    "absent")."""
    j = pl.program_id(1)
    m = wq_ref.shape[1]
    geq = None
    gt = None
    for k in range(m):  # m = nobj is tiny and static: unrolled
        a = wq_ref[:, k : k + 1]   # [TI, 1]
        b = wjt_ref[k : k + 1, :]  # [1, TJ]
        ge = b >= a
        g = b > a
        geq = ge if geq is None else (geq & ge)
        gt = g if gt is None else (gt | g)
    vals = jnp.where(geq & gt, rem_ref[0:1, :], 0.0)
    tile_max = jnp.max(vals, axis=1, keepdims=True)  # [TI, 1]

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] = jnp.maximum(out_ref[:], tile_max)


def dominated_weight_maxes(w: jnp.ndarray, weights: jnp.ndarray,
                           queries: Optional[jnp.ndarray] = None, *,
                           block_i: int = 256, block_j: int = 512,
                           interpret: Optional[bool] = None) -> jnp.ndarray:
    """``out[i] = max_{j dominates queries[i]} weights[j]`` (0 with no
    dominator), streaming [TI, m] × [m, TJ] tiles through VMEM like
    :func:`dominated_weight_sums`.

    This is the cross step of the prefix-streamed chain reduction
    (mo.ndsort.nd_rank_prefix): with ``weights = (rank + 1) ·
    prefix_mask`` it hands every query row the deepest dominating
    chain in the already-ranked prefix without materialising any
    [n, n] object. ``queries`` defaults to ``w`` (self-ranking);
    weights must be non-negative — 0 is the "no dominator" identity.

    :param w: ``f32[n, nobj]`` candidate dominators (weighted values).
    :param weights: ``f32[n]`` per-dominator weights (>= 0).
    :param queries: ``f32[nq, nobj]`` rows to rank against ``w``.
    :returns: ``f32[nq]``.
    """
    if queries is None:
        queries = w
    n, m = w.shape
    nq = queries.shape[0]
    njp = _round_up(n, block_j)
    nip = _round_up(nq, block_i)
    wp = jnp.pad(w.astype(jnp.float32), ((0, njp - n), (0, 0)),
                 constant_values=-jnp.inf)  # padded rows dominate nothing
    qp = jnp.pad(queries.astype(jnp.float32), ((0, nip - nq), (0, 0)),
                 constant_values=jnp.inf)   # padded queries match nothing
    rem = jnp.pad(weights.astype(jnp.float32), (0, njp - n))[None, :]
    out = pl.pallas_call(
        _dom_maxes_kernel,
        grid=(nip // block_i, njp // block_j),
        in_specs=[
            pl.BlockSpec((block_i, m), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((m, block_j), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_j), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_i, 1), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nip, 1), jnp.float32),
        interpret=_auto_interpret(interpret),
    )(qp, wp.T, rem)
    return out[:nq, 0]


def dominated_counts(w: jnp.ndarray, remaining: jnp.ndarray, *,
                     block_i: int = 256, block_j: int = 512,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """``counts[i] = #{j : remaining[j] and j dominates i}`` —
    :func:`dominated_weight_sums` with 0/1 weights."""
    return dominated_weight_sums(
        w, remaining, block_i=block_i, block_j=block_j,
        interpret=interpret).astype(jnp.int32)


def strengths_tiled(w: jnp.ndarray, *, block_i: int = 256,
                    block_j: int = 512,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """SPEA2 strength ``S(i) = #{j : i dominates j}`` (emo.py:712-718),
    streaming. Negating ``w`` flips the domination direction
    (``dominates(-a, -b) == dominates(b, a)``), so the same kernel
    counts dominated rows instead of dominators."""
    n = w.shape[0]
    return dominated_weight_sums(
        -w, jnp.ones(n, jnp.float32), block_i=block_i, block_j=block_j,
        interpret=interpret)


def nd_rank_tiled(w: jnp.ndarray, max_fronts: Optional[int] = None, *,
                  block_i: int = 256, block_j: int = 512,
                  interpret: Optional[bool] = None,
                  cover_k: Optional[int] = None,
                  fallback: str = "none",
                  return_peels: bool = False) -> jnp.ndarray:
    """Non-domination rank (0 = first front) by iterative front peeling,
    recomputing domination tile-wise each round instead of holding the
    [n, n] matrix resident (cf. emo.nd_rank, reference emo.py:53-117).

    O(fronts · n²·m) VPU flops, O(n·m) memory — the XLA matrix path is
    O(n²) memory. Crossover point on one chip is around n ≈ 20-30k.

    ``max_fronts`` stops peeling early (emo.nd_rank's ``max_rank``);
    unpeeled rows keep rank ``n``.  ``cover_k`` / ``fallback='count'``
    bound the data-dependent front count exactly as in emo.nd_rank:
    stop once ``cover_k`` rows are ranked (exact for top-k selection),
    and/or assign the unpeeled remainder Fonseca-Fleming
    dominance-count ranks in one extra tile sweep.
    """
    n = w.shape[0]
    stop = n if max_fronts is None else min(max_fronts, n)
    covered_stop = n if cover_k is None else min(cover_k, n)
    if fallback not in ("none", "count"):
        raise ValueError(f"unknown nd_rank fallback {fallback!r}")
    count = functools.partial(dominated_counts, block_i=block_i,
                              block_j=block_j, interpret=interpret)

    def cond(state):
        _, current, remaining = state
        covered = n - jnp.sum(remaining)
        return (remaining.any() & (current < stop)
                & (covered < covered_stop))

    def body(state):
        ranks, current, remaining = state
        ndom = count(w, remaining)
        front = remaining & (ndom == 0)
        ranks = jnp.where(front, current, ranks)
        return ranks, current + 1, remaining & ~front

    ranks, current, remaining = jax.lax.while_loop(
        cond, body,
        (jnp.full(n, n, jnp.int32), jnp.int32(0), jnp.ones(n, bool)))
    if fallback == "count":
        # only on a genuine budget stop (see emo.nd_rank): a cover_k
        # stop or complete peel never consumes the count-ranks, and
        # this sweep is a full O(n²·m) pass at the sizes this kernel
        # targets
        def count_rank(ranks):
            ndom = count(w, remaining).astype(jnp.int32)
            return jnp.where(remaining, current + ndom, ranks)

        ranks = jax.lax.cond(remaining.any() & (current >= stop),
                             count_rank, lambda r: r, ranks)
    return (ranks, current) if return_peels else ranks


# ------------------------------------------- GP opcode-major dispatch ----

def gp_grouped_dispatch(buf: jnp.ndarray, chunk_ops: jnp.ndarray,
                        src_idx: jnp.ndarray, src_const: jnp.ndarray,
                        src_isc: jnp.ndarray, ops_fns, *, chunk: int,
                        n_args: int,
                        interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused gather-dispatch-scatter for the opcode-major GP
    interpreter (gp/interpreter.py ``mode='grouped'``).

    The whole chunk sequence runs as ONE kernel launch: grid step ``c``
    DMAs its ``chunk`` instructions' operand rows out of the shared
    value buffer (held in HBM, input/output-aliased), applies exactly
    one primitive — ``ops_fns[chunk_ops[c]]`` — to the gathered block,
    and DMAs the result back to the chunk's own contiguous rows. TPU
    grid steps execute in order, so the data dependency (children sort
    into earlier chunks) is honoured without host round trips; the XLA
    formulation pays a ``dynamic_slice``/``switch``/``update`` dispatch
    per chunk instead.

    :param buf: ``f32[n_args + nchunks·chunk, P]`` value buffer with
        the argument rows filled; returned with every instruction row
        computed (donated/aliased).
    :param chunk_ops: ``int32[nchunks]`` branch index per chunk.
    :param src_idx: ``int32[nchunks·chunk, max_ar]`` operand row ids.
    :param src_const: ``f32[...]`` inline constants where ``src_isc``.
    :param src_isc: operand-is-constant mask (any numeric/bool dtype).
    :param ops_fns: ``[(fn, arity), ...]`` — the live primitives.
    """
    R, P = buf.shape
    nchunks = chunk_ops.shape[0]
    max_ar = src_idx.shape[1]
    interp = _auto_interpret(interpret)
    isc = src_isc.astype(jnp.float32)

    def kernel(op_ref, si_ref, sc_ref, sb_ref, buf_ref, out_ref,
               gath_ref, res_ref, sem, out_sem):
        del buf_ref  # aliased with out_ref; all access goes through out
        c = pl.program_id(0)

        def fetch(k, _):
            # operand rows come from the OUTPUT ref: it aliases the
            # input buffer, and earlier chunks' results live there
            for j in range(max_ar):
                cp = pltpu.make_async_copy(
                    out_ref.at[si_ref[k, j]], gath_ref.at[j, k], sem)
                cp.start()
                cp.wait()
            return 0

        lax.fori_loop(0, chunk, fetch, 0, unroll=False)
        # constants REPLACE the gathered row (a select, not a blend —
        # a gathered NaN/inf must not leak through the constant path)
        ops_in = [jnp.where(sb_ref[:, j][:, None] > 0.5,
                            sc_ref[:, j][:, None], gath_ref[j])
                  for j in range(max_ar)]
        for b, (fn, ar) in enumerate(ops_fns):
            @pl.when(op_ref[0] == b)
            def _(fn=fn, ar=ar):
                res_ref[:] = fn(*ops_in[:ar])
        cp = pltpu.make_async_copy(
            res_ref, out_ref.at[pl.ds(n_args + c * chunk, chunk)],
            out_sem)
        cp.start()
        cp.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(nchunks,),
        in_specs=[
            pl.BlockSpec((1,), lambda c: (c,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((chunk, max_ar), lambda c: (c, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((chunk, max_ar), lambda c: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk, max_ar), lambda c: (c, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((max_ar, chunk, P), jnp.float32),
            pltpu.VMEM((chunk, P), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, P), jnp.float32),
        input_output_aliases={4: 0},
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interp,
    )(chunk_ops, src_idx, src_const, isc, buf)


# ------------------------------------------------ fused variation plane ----

def _fused_variation_kernel(si_ref, pi_ref, cx_ref, lo_ref, hi_ref,
                            mut_ref, mask_ref, arg_ref, g_ref, out_ref,
                            selfb, partb, sem, *, mut_kind):
    """One [TI, Lp] output tile of the mask-driven variation plane:
    DMA each row's self + partner genomes straight out of the (ANY-
    space) population, segment-swap where the crossover mask says so,
    apply the mutation mask — one VMEM residency per genome row.
    ``arg_ref`` is ``None`` for the 'flip' kind (the wrapper drops the
    input entirely rather than streaming a dead [n, Lp] tensor)."""
    TI, Lp = selfb.shape

    def fetch(k, _):
        cp = pltpu.make_async_copy(g_ref.at[si_ref[k]], selfb.at[k], sem)
        cp.start()
        cp.wait()
        cp = pltpu.make_async_copy(g_ref.at[pi_ref[k]], partb.at[k], sem)
        cp.start()
        cp.wait()
        return 0

    lax.fori_loop(0, TI, fetch, 0, unroll=False)
    col = jax.lax.broadcasted_iota(jnp.int32, (TI, Lp), 1)
    seg = (cx_ref[:] > 0.5) & (col >= lo_ref[:]) & (col < hi_ref[:])
    child = jnp.where(seg, partb[:], selfb[:])
    if mut_kind == "flip":
        mval = 1.0 - child
    elif mut_kind == "add":
        mval = child + arg_ref[:]
    else:  # 'set'
        mval = arg_ref[:]
    m = (mut_ref[:] > 0.5) & (mask_ref[:] > 0.5)
    out_ref[:] = jnp.where(m, mval, child)


def fused_variation(genomes: jnp.ndarray, src_idx: jnp.ndarray,
                    partner_idx: jnp.ndarray, cx_row: jnp.ndarray,
                    lo: jnp.ndarray, hi: jnp.ndarray,
                    mut_row: jnp.ndarray, mut_mask: jnp.ndarray,
                    mut_arg: Optional[jnp.ndarray] = None, *,
                    mut_kind: str = "flip", block_i: int = 256,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Tournament-select gather + paired segment crossover + per-gene
    mutation in ONE pass over the population — the Pallas apply of the
    fused variation plane (:mod:`deap_tpu.ops.variation`).

    The caller computes the selection winners (``src_idx`` — e.g.
    tournament winners from :func:`ops.selection.tournament_aspirants`,
    whose fitness-plane work is tiny) and the variation masks with the
    unfused operators' exact RNG draws; this kernel then does ALL the
    genome-plane work in one sweep: each output row's self and partner
    parents are DMA'd from HBM into VMEM (the selection gather, the
    crossover partner gather), the swap segment ``[lo, hi)`` is applied
    where ``cx_row``, and the mutation mask rewrites genes where
    ``mut_row & mut_mask`` — against the unfused chain's 6+ HBM sweeps
    (gather, both crossover children, interleave, mutant population,
    final selects). Bit-parity with
    :func:`ops.variation.apply_variation` is pinned in
    tests/test_kernels.py (interpret mode; f32 ops are IEEE-identical).

    :param genomes: ``[N, L]`` population (bool / 0-1 ints / float32).
    :param src_idx: ``int32[n]`` self-parent row per output row.
    :param partner_idx: ``int32[n]`` crossover-partner row.
    :param cx_row: ``bool[n]`` crossover applies to this row.
    :param lo: ``int32[n]`` / ``hi``: the half-open swap segment.
    :param mut_row: ``bool[n]`` mutation applies to this row.
    :param mut_mask: ``bool[n, L]`` per-gene mutation mask.
    :param mut_arg: ``[n, L]`` additive noise (``'add'``) or
        replacement values (``'set'``); ``None`` for ``'flip'``.
    :param mut_kind: ``'flip' | 'add' | 'set'``.
    :returns: ``[n, L]`` children in the input dtype.
    """
    if mut_kind not in ("flip", "add", "set"):
        raise ValueError(f"unknown mut_kind {mut_kind!r}")
    if mut_kind != "flip" and mut_arg is None:
        raise ValueError(f"mut_kind={mut_kind!r} needs mut_arg")
    n = src_idx.shape[0]
    N, L = genomes.shape
    interp = _auto_interpret(interpret)
    Lp = _round_up(L, 128)
    ni = _round_up(n, block_i)
    g = jnp.pad(genomes.astype(jnp.float32), ((0, 0), (0, Lp - L)))
    pad1 = lambda a: jnp.pad(a, (0, ni - n))
    # padded rows: index 0 (a real row — harmless), flags 0 → identity;
    # the tail is sliced off before returning
    si = pad1(src_idx.astype(jnp.int32))
    pi = pad1(partner_idx.astype(jnp.int32))
    cxf = pad1(cx_row.astype(jnp.float32))[:, None]
    mutf = pad1(mut_row.astype(jnp.float32))[:, None]
    lo2 = pad1(lo.astype(jnp.int32))[:, None]
    hi2 = pad1(hi.astype(jnp.int32))[:, None]
    mask = jnp.pad(mut_mask.astype(jnp.float32),
                   ((0, ni - n), (0, Lp - L)))

    ispec = lambda: pl.BlockSpec((block_i,), lambda i: (i,),
                                 memory_space=pltpu.SMEM)
    vrow = lambda: pl.BlockSpec((block_i, 1), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)
    vtile = lambda: pl.BlockSpec((block_i, Lp), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
    in_specs = [ispec(), ispec(), vrow(), vrow(), vrow(), vrow(),
                vtile()]
    inputs = [si, pi, cxf, lo2, hi2, mutf, mask]
    if mut_kind == "flip":
        kernel = functools.partial(
            lambda *refs, mut_kind: _fused_variation_kernel(
                *refs[:7], None, *refs[7:], mut_kind=mut_kind),
            mut_kind=mut_kind)
    else:
        arg = jnp.pad(mut_arg.astype(jnp.float32),
                      ((0, ni - n), (0, Lp - L)))
        in_specs.append(vtile())
        inputs.append(arg)
        kernel = functools.partial(_fused_variation_kernel,
                                   mut_kind=mut_kind)
    in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
    inputs.append(g)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(ni // block_i,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_i, Lp), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((block_i, Lp), jnp.float32),
            pltpu.VMEM((block_i, Lp), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((ni, Lp), jnp.float32),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interp,
    )(*inputs)
    return out[:n, :L].astype(genomes.dtype)


# ------------------------------------------------- fused bitstring varAnd ----

def _variation_body(g, pairu, rowu, geneu, *, n, L, TI, cxpb, mutpb, indpb,
                    tile_idx):
    """Shared kernel body: two-point cx over adjacent pairs + flip-bit
    mutation + popcount fitness on one [TI, Lp] tile of 0/1 genomes
    (float32 workspace). ``pairu``/``rowu``: [TI, 1] U[0,1) draws;
    ``geneu``: [TI, Lp] U[0,1); pair draws must already be
    pair-consistent (both rows of a pair carry the even row's draws)."""
    Lp = g.shape[1]
    col = jax.lax.broadcasted_iota(jnp.int32, (TI, Lp), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (TI, Lp), 0)
    valid_col = col < L

    # two-point draw, exactly the reference's distribution
    # (tools/crossover.py:44-50): p1 ~ U{1..L}, p2 ~ U{1..L-1} bumped
    # past p1; swap segment [min, max).
    do_cx = pairu[:, 0:1] < cxpb
    p1 = 1 + (pairu[:, 1:2] * L).astype(jnp.int32)
    p2 = 1 + (pairu[:, 2:3] * (L - 1)).astype(jnp.int32)
    p2 = jnp.where(p2 >= p1, p2 + 1, p2)
    lo = jnp.minimum(p1, p2)
    hi = jnp.maximum(p1, p2)

    # adjacent pairing (0,1), (2,3), ... — partner row via roll; an odd
    # trailing individual never mates (algorithms.py:70-73's zip drop).
    up = pltpu.roll(g, TI - 1, 0)   # up[i] = g[i+1]
    dn = pltpu.roll(g, 1, 0)        # dn[i] = g[i-1]
    partner = jnp.where((row % 2) == 0, up, dn)
    grow = row + tile_idx * TI      # global row index
    has_partner = jnp.bitwise_or(grow, 1) < n
    seg = (col >= lo) & (col < hi) & do_cx & has_partner
    child = jnp.where(seg, partner, g)

    do_mut = rowu < mutpb
    flip = (geneu < indpb) & do_mut & valid_col
    child = jnp.where(flip, 1.0 - child, child)

    fit = jnp.sum(jnp.where(valid_col, child, 0.0), axis=1, keepdims=True)
    return child, fit


def _pair_consistent(u, axis: int = 0):
    """Per-individual draws → both members of each adjacent pair along
    ``axis`` carry the even member's draw. ``axis=0`` for row-major
    tiles ([TI, k]), ``axis=1`` for lane-major layouts ([k, N]) — one
    home for the even-member-wins convention, whatever the layout."""
    down = pltpu.roll(u, 1, axis)
    even = (jax.lax.broadcasted_iota(jnp.int32, u.shape, axis) % 2) == 0
    return jnp.where(even, u, down)


def _fused_kernel_bits(g_ref, pairbits_ref, rowbits_ref, genebits_ref,
                       out_ref, fit_ref, *, n, L, cxpb, mutpb, indpb):
    TI = g_ref.shape[0]
    pairu = _u01(_pair_consistent(pairbits_ref[:]))
    child, fit = _variation_body(
        g_ref[:].astype(jnp.float32), pairu, _u01(rowbits_ref[:][:, 0:1]),
        _u01(genebits_ref[:]), n=n, L=L, TI=TI, cxpb=cxpb, mutpb=mutpb,
        indpb=indpb, tile_idx=pl.program_id(0))
    out_ref[:] = child.astype(out_ref.dtype)
    fit_ref[:] = fit


def _fused_kernel_hw(seed_ref, g_ref, out_ref, fit_ref, *, n, L, cxpb,
                     mutpb, indpb):
    TI, Lp = g_ref.shape
    i = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0] + i)
    # pair (4) + row (1) draws share one block: separate calls each
    # cost a full vreg generation per 8 sublanes at <4% lane use
    prbits = pltpu.bitcast(pltpu.prng_random_bits((TI, 8)), jnp.uint32)
    pairbits, rowbits = prbits[:, 0:4], prbits[:, 4:5]
    genebits = pltpu.bitcast(pltpu.prng_random_bits((TI, Lp)), jnp.uint32)
    pairu = _u01(_pair_consistent(pairbits))
    child, fit = _variation_body(
        g_ref[:].astype(jnp.float32), pairu, _u01(rowbits),
        _u01(genebits), n=n, L=L, TI=TI, cxpb=cxpb, mutpb=mutpb,
        indpb=indpb, tile_idx=i)
    out_ref[:] = child.astype(out_ref.dtype)
    fit_ref[:] = fit


def _resolve_prng(prng: str, interp: bool) -> str:
    """'auto' → hw on real TPU, input elsewhere; reject hw+interpreter
    (the interpreter stubs prng_random_bits to zeros — the GA would
    silently degenerate: fixed crossover points, all genes flipped)."""
    if prng == "auto":
        return "input" if interp else "hw"
    if prng == "hw" and interp:
        raise ValueError(
            "prng='hw' needs a real TPU core; use prng='input' (or "
            "'auto') under the Pallas interpreter")
    if prng not in ("hw", "input"):
        raise ValueError(f"unknown prng mode {prng!r}")
    return prng


def run_fused_kernel(key: jax.Array, g: jnp.ndarray, *, kernel_hw,
                     kernel_bits, prng: str, interp: bool, block_i: int,
                     genebit_cols: int, out_dtype) -> Tuple[jnp.ndarray,
                                                            jnp.ndarray]:
    """Shared pallas_call plumbing for the fused variation kernels (this
    module's byte-genome pair and ops.packed's word-genome pair).

    ``g`` must already be padded to ``[ni, cols]`` with ``ni`` a
    multiple of ``block_i``; returns the padded ``(children, fitness)``
    for the caller to slice. ``kernel_hw(seed_ref, g_ref, out, fit)``
    draws its randomness from the TPU hardware PRNG; ``kernel_bits
    (g_ref, pairbits, rowbits, genebits, out, fit)`` receives uint32
    streams (``genebit_cols`` columns of per-gene bits).
    """
    ni, cols = g.shape
    gspec = pl.BlockSpec((block_i, cols), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    out_specs = [
        gspec,
        pl.BlockSpec((block_i, 1), lambda i: (i, 0),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((ni, cols), out_dtype),
        jax.ShapeDtypeStruct((ni, 1), jnp.float32),
    ]
    grid = (ni // block_i,)

    if prng == "hw":
        seed = jax.random.randint(key, (1,), 0, 2**31 - 1, jnp.int32)
        return pl.pallas_call(
            kernel_hw,
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), gspec],
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interp,
        )(seed, g)
    k1, k2, k3 = jax.random.split(key, 3)
    pairbits = jax.random.bits(k1, (ni, 4), jnp.uint32)
    rowbits = jax.random.bits(k2, (ni, 1), jnp.uint32)
    genebits = jax.random.bits(k3, (ni, genebit_cols), jnp.uint32)
    bspec = lambda k: pl.BlockSpec((block_i, k), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel_bits,
        grid=grid,
        in_specs=[gspec, bspec(4), bspec(1), bspec(genebit_cols)],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interp,
    )(g, pairbits, rowbits, genebits)


def fused_variation_eval(key: jax.Array, genomes: jnp.ndarray, *,
                         cxpb: float, mutpb: float, indpb: float,
                         prng: str = "auto", block_i: int = 256,
                         interpret: Optional[bool] = None,
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One fused eaSimple variation+evaluation pass over 0/1 genomes.

    Equivalent (in distribution) to ``var_and`` with ``cx_two_point`` +
    ``mut_flip_bit(indpb)`` followed by a full sum-of-bits evaluation —
    the reference OneMax generation (algorithms.py:68-82 after
    selection), in one HBM round trip.

    :param genomes: ``[n, L]`` 0/1 array (bool or numeric).
    :param prng: ``'hw'`` — TPU hardware PRNG in-kernel (no random
        tensors in HBM; TPU only); ``'input'`` — draw bits with
        jax.random outside and stream them in (runs anywhere, incl. the
        interpreter); ``'auto'`` — hw on TPU else input.
    :returns: ``(children [n, L], fitness f32[n])``.
    """
    n, L = genomes.shape
    assert block_i % 2 == 0, "pairs must not straddle tiles"
    Lp = _round_up(L, 128)
    ni = _round_up(n, block_i)
    interp = _auto_interpret(interpret)
    prng = _resolve_prng(prng, interp)
    g = jnp.pad(genomes, ((0, ni - n), (0, Lp - L)))

    common = dict(n=n, L=L, cxpb=cxpb, mutpb=mutpb, indpb=indpb)
    out, fit = run_fused_kernel(
        key, g,
        kernel_hw=functools.partial(_fused_kernel_hw, **common),
        kernel_bits=functools.partial(_fused_kernel_bits, **common),
        prng=prng, interp=interp, block_i=block_i, genebit_cols=Lp,
        out_dtype=genomes.dtype)
    return out[:n, :L], fit[:n, 0]
