"""Operator library — the counterpart of the reference's ``deap.tools``
operator modules, as pure batched array functions.

DEAP-style camelCase aliases are exported alongside the snake_case
canonical names so reference users find the operators they know
(cxTwoPoint, mutFlipBit, selTournament, ...). Multi-objective selection
(NSGA-II/III, SPEA2) lives in :mod:`deap_tpu.mo`; migration in
:mod:`deap_tpu.parallel`.
"""

from deap_tpu.ops.init import (
    bernoulli_genome,
    constant_genome,
    init_cycle,
    init_iterate,
    init_repeat,
    normal_genome,
    permutation_genome,
    randint_genome,
    uniform_genome,
)
from deap_tpu.ops.constraint import (
    ClosestValidPenality,
    ClosestValidPenalty,
    DeltaPenality,
    DeltaPenalty,
    closest_valid_penalty,
    delta_penalty,
)
from deap_tpu.ops.crossover import (
    cx_blend,
    cx_es_blend,
    cx_es_two_point,
    cx_messy_one_point,
    cx_one_point,
    cx_ordered,
    cx_partialy_matched,
    cx_simulated_binary,
    cx_simulated_binary_bounded,
    cx_two_point,
    cx_uniform,
    cx_uniform_partialy_matched,
    pair_vmap,
)
from deap_tpu.ops.mutation import (
    genome_vmap,
    mut_es_log_normal,
    mut_flip_bit,
    mut_gaussian,
    mut_polynomial_bounded,
    mut_shuffle_indexes,
    mut_two_opt,
    mut_uniform_int,
    strategy_floor,
)
from deap_tpu.ops.kernels import (
    dominated_counts,
    dominated_weight_maxes,
    dominated_weight_sums,
    fused_variation,
    fused_variation_eval,
    nd_rank_tiled,
    strengths_tiled,
)
from deap_tpu.ops.linalg import eigh_jacobi
from deap_tpu.ops.variation import (
    VariationPlan,
    apply_variation,
    resolve_plan,
)
from deap_tpu.ops.kernels_real import (
    eval_rastrigin,
    eval_sphere,
    fused_variation_eval_real,
)
from deap_tpu.ops.packed import (
    cx_two_point_packed,
    evolve_packed,
    fused_variation_eval_packed,
    mut_flip_bit_packed,
    pack_genomes,
    packed_fitness,
    popcount,
    sel_tournament_gather_packed,
    unpack_genomes,
)
from deap_tpu.ops.selection import (
    counting_order_desc,
    sel_automatic_epsilon_lexicase,
    sel_best,
    sel_double_tournament,
    sel_epsilon_lexicase,
    sel_lexicase,
    sel_random,
    sel_roulette,
    sel_stochastic_universal_sampling,
    sel_tournament,
    sel_tournament_binned,
    sel_tournament_sorted,
    sel_worst,
    tournament_aspirants,
)

# DEAP-style aliases (reference names → tensor ops)
cxOnePoint = cx_one_point
cxTwoPoint = cx_two_point
cxUniform = cx_uniform
cxPartialyMatched = cx_partialy_matched
cxUniformPartialyMatched = cx_uniform_partialy_matched
cxOrdered = cx_ordered
cxBlend = cx_blend
cxSimulatedBinary = cx_simulated_binary
cxSimulatedBinaryBounded = cx_simulated_binary_bounded
cxMessyOnePoint = cx_messy_one_point
cxESBlend = cx_es_blend
cxESTwoPoint = cx_es_two_point

mutGaussian = mut_gaussian
mutPolynomialBounded = mut_polynomial_bounded
mutShuffleIndexes = mut_shuffle_indexes
mutFlipBit = mut_flip_bit
mutUniformInt = mut_uniform_int
mutESLogNormal = mut_es_log_normal

selRandom = sel_random
selBest = sel_best
selWorst = sel_worst
selTournament = sel_tournament
selTournamentSorted = sel_tournament_sorted
selRoulette = sel_roulette
selDoubleTournament = sel_double_tournament
selStochasticUniversalSampling = sel_stochastic_universal_sampling
selLexicase = sel_lexicase
selEpsilonLexicase = sel_epsilon_lexicase
selAutomaticEpsilonLexicase = sel_automatic_epsilon_lexicase

initRepeat = init_repeat
initIterate = init_iterate
initCycle = init_cycle
