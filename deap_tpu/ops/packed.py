"""Bit-packed bitstring populations — 32 genes per uint32 word.

HBM bandwidth is the generation-step ceiling for bitstring GAs (the
genome matrix crosses HBM several times per generation), and XLA stores
``bool`` genes one byte each. Packing 32 genes per ``uint32`` cuts that
traffic 8× for every pass — gather, variation, evaluation — at zero
algorithmic change: these operators reproduce the reference semantics
(``cxTwoPoint`` tools/crossover.py:37-60, ``mutFlipBit``
tools/mutation.py:124-142, OneMax popcount) directly on words.

Key formulations:

- a two-point segment ``[lo, hi)`` becomes per-word masks: word ``j``
  holds bits ``[32j, 32j+32)``; the intersection with ``[lo, hi)`` is
  ``bits_below(hi - 32j) & ~bits_below(lo - 32j)`` with
  ``bits_below(k) = (1 << clip(k, 0, 32)) - 1`` (computed
  overflow-free).
- per-gene Bernoulli(indpb) flip masks are built from 32 independent
  uniform draws — one per bit position — so the per-bit distribution is
  exactly the reference's, not a power-of-two approximation.
- fitness is a SWAR popcount (no reliance on a native
  ``population_count`` lowering).

Works as plain XLA ops and as the fused Pallas kernel
(:func:`fused_variation_eval_packed`), the packed twin of
``ops.kernels.fused_variation_eval``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl  # noqa: F401 (kernel plumbing)
from jax.experimental.pallas import tpu as pltpu

from deap_tpu.ops.crossover import _two_points
# shared with the byte-genome kernel: bits -> U[0,1) and the adjacent-
# pair draw-consistency roll must stay identical across both kernels
from deap_tpu.ops.kernels import _pair_consistent
from deap_tpu.ops.kernels import _u01 as _u01_from_bits

__all__ = [
    "pack_genomes",
    "unpack_genomes",
    "popcount",
    "packed_fitness",
    "cx_two_point_packed",
    "mut_flip_bit_packed",
    "fused_variation_eval_packed",
    "sel_tournament_gather_packed",
    "evolve_packed",
]

WORD = 32
_U1 = np.uint32(1)  # numpy scalar: embeds as a literal inside Pallas kernels


def words_for(length: int) -> int:
    return -(-length // WORD)


def pack_genomes(bits: jnp.ndarray) -> jnp.ndarray:
    """``[..., L]`` 0/1 array → ``uint32[..., ceil(L/32)]``; bit ``k`` of
    word ``j`` is gene ``32j + k``. Tail bits of the last word are 0."""
    L = bits.shape[-1]
    W = words_for(L)
    pad = W * WORD - L
    b = jnp.pad(bits.astype(jnp.uint32), [(0, 0)] * (bits.ndim - 1) + [(0, pad)])
    b = b.reshape(*bits.shape[:-1], W, WORD)
    shifts = (_U1 << jnp.arange(WORD, dtype=jnp.uint32))
    return jnp.sum(b * shifts, axis=-1, dtype=jnp.uint32)


def unpack_genomes(packed: jnp.ndarray, length: int) -> jnp.ndarray:
    """Inverse of :func:`pack_genomes` → ``bool[..., length]``."""
    bits = (packed[..., :, None] >> jnp.arange(WORD, dtype=jnp.uint32)) & _U1
    return bits.reshape(*packed.shape[:-1], packed.shape[-1] * WORD)[
        ..., :length].astype(jnp.bool_)


def popcount(words: jnp.ndarray) -> jnp.ndarray:
    """Per-word set-bit count (SWAR; uint32 in, uint32 out)."""
    v = words
    v = v - ((v >> _U1) & np.uint32(0x55555555))
    v = (v & np.uint32(0x33333333)) + ((v >> np.uint32(2)) & np.uint32(0x33333333))
    v = (v + (v >> np.uint32(4))) & np.uint32(0x0F0F0F0F)
    return (v * np.uint32(0x01010101)) >> np.uint32(24)


def packed_fitness(packed: jnp.ndarray) -> jnp.ndarray:
    """OneMax fitness: total set bits per row → f32 (tail words are 0 by
    the pack invariant, so no length mask is needed)."""
    return popcount(packed).sum(-1).astype(jnp.float32)


def _bits_below(k: jnp.ndarray) -> jnp.ndarray:
    """uint32 with bits [0, clip(k, 0, 32)) set, overflow-free."""
    k = jnp.clip(k, 0, WORD)
    full = k >= WORD
    kk = jnp.where(full, 0, k).astype(jnp.uint32)
    return jnp.where(full, np.uint32(0xFFFFFFFF), (_U1 << kk) - _U1)


def segment_mask_words(lo: jnp.ndarray, hi: jnp.ndarray, W: int) -> jnp.ndarray:
    """Per-word masks of gene range [lo, hi): uint32[..., W]. ``lo``/
    ``hi`` broadcast against a trailing word axis."""
    starts = jnp.arange(W, dtype=jnp.int32) * WORD
    lo = lo[..., None] - starts
    hi = hi[..., None] - starts
    return _bits_below(hi) & ~_bits_below(lo)


def cx_two_point_packed(key, g1, g2, length: int):
    """Two-point crossover on packed rows ``uint32[W]`` — word-masked
    segment swap, the same ``(p1, p2)`` draw as ``cx_two_point``
    (shared ``crossover._two_points``, tools/crossover.py:44-50)."""
    lo, hi = _two_points(key, length)
    m = segment_mask_words(lo, hi, g1.shape[-1])
    return (g1 & ~m) | (g2 & m), (g2 & ~m) | (g1 & m)


def flip_words(key, shape_words: Tuple[int, ...], indpb: float,
               length: int) -> jnp.ndarray:
    """Bernoulli(indpb) per *gene*, packed: one uniform draw per bit
    position keeps the exact per-bit probability. Tail bits beyond
    ``length`` are never set."""
    W = shape_words[-1]
    u = jax.random.uniform(key, (*shape_words, WORD))
    bits = (u < indpb).astype(jnp.uint32)
    shifts = (_U1 << jnp.arange(WORD, dtype=jnp.uint32))
    words = jnp.sum(bits * shifts, axis=-1, dtype=jnp.uint32)
    starts = jnp.arange(W, dtype=jnp.int32) * WORD
    return words & _bits_below(length - starts)


def mut_flip_bit_packed(key, g, indpb: float, length: int):
    """Flip-bit mutation on a packed row (mutation.py:124-142): XOR with
    a Bernoulli(indpb) word mask."""
    return g ^ flip_words(key, g.shape, indpb, length)


# ------------------------------------------------- fused Pallas kernel ----

def _flip_words_matmul(geneu, indpb, Wp):
    """Bernoulli(indpb) flip words from the ``[TI, 32·Wp]`` per-bit
    uniform block, packed via two small MXU matmuls instead of a
    32-iteration shift-or loop.

    The loop formulation compared and or-ed ``(TI, Wp)`` slices — at
    W = 4 words that is 4 of 128 vector lanes doing work, ~96 narrow
    VPU ops per tile. Here the whole block is compared against
    ``indpb`` once at full lane width, then bit-plane columns are
    folded into word values by multiplying with a constant
    ``(32·Wp, Wp)`` matrix whose ``(b·Wp + j, j)`` entry is ``2^b``
    (column layout matching the bits-path genebit stream: plane ``b``
    occupies columns ``[b·Wp, (b+1)·Wp)``). Sums of distinct powers of
    two stay exact in f32 only below 2^24, so the fold splits into
    bits 0-15 and 16-31 (each word sum < 2^16, exact) and recombines
    bitwise — bit-identical to the loop it replaces.
    """
    cols = WORD * Wp
    mask = (geneu < indpb).astype(jnp.float32)
    # fold matrices built in-kernel from iota arithmetic (pallas_call
    # rejects captured array constants): row r = b*Wp + j carries 2^b
    # at column j. (1 << b) in int32 then int32->f32 is exact (< 2^16).
    r = jax.lax.broadcasted_iota(jnp.int32, (cols, Wp), 0)
    j = jax.lax.broadcasted_iota(jnp.int32, (cols, Wp), 1)
    b = r // Wp
    sel = (r % Wp) == j

    def fold(half_sel, shift):
        m = jnp.where(sel & half_sel,
                      jnp.left_shift(1, b - shift), 0).astype(jnp.float32)
        s = jax.lax.dot_general(mask, m, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        # f32 -> int32 (exact: values < 2^16) -> uint32 bit view
        return jax.lax.bitcast_convert_type(s.astype(jnp.int32),
                                            jnp.uint32)

    return fold(b < 16, 0) | (fold(b >= 16, 16) << np.uint32(16))


def _packed_body(g, pairu, rowu, geneu, *, n, L, W, TI, Wp, cxpb, mutpb,
                 indpb, tile_idx):
    """Kernel body on a ``uint32[TI, Wp]`` tile. ``geneu`` is the full
    ``[TI, 32·Wp]`` per-bit uniform block (plane ``b`` in columns
    ``[b·Wp, (b+1)·Wp)``); pair draws must already be
    pair-consistent."""
    col = jax.lax.broadcasted_iota(jnp.int32, (TI, Wp), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (TI, Wp), 0)
    word_start = col * WORD

    do_cx = pairu[:, 0:1] < cxpb
    p1 = 1 + (pairu[:, 1:2] * L).astype(jnp.int32)
    p2 = 1 + (pairu[:, 2:3] * (L - 1)).astype(jnp.int32)
    p2 = jnp.where(p2 >= p1, p2 + 1, p2)
    lo = jnp.minimum(p1, p2)
    hi = jnp.maximum(p1, p2)

    up = pltpu.roll(g, TI - 1, 0)
    dn = pltpu.roll(g, 1, 0)
    partner = jnp.where((row % 2) == 0, up, dn)
    grow = row + tile_idx * TI
    has_partner = jnp.bitwise_or(grow, 1) < n
    seg = _bits_below(hi - word_start) & ~_bits_below(lo - word_start)
    seg = jnp.where(do_cx & has_partner, seg, np.uint32(0))
    child = (g & ~seg) | (partner & seg)

    do_mut = rowu < mutpb
    flip = _flip_words_matmul(geneu, indpb, Wp)
    flip &= _bits_below(L - word_start)          # tail + padded words
    flip = jnp.where(do_mut, flip, np.uint32(0))
    child = child ^ flip

    # Mosaic has no uint32->f32 cast; popcount <= 32 so the sign bit is
    # clear and a bitcast through int32 is exact
    counts = jax.lax.bitcast_convert_type(popcount(child), jnp.int32)
    fit = counts.astype(jnp.float32).sum(axis=1, keepdims=True)
    return child, fit


def _packed_kernel_bits(g_ref, pairbits_ref, rowbits_ref, genebits_ref,
                        out_ref, fit_ref, *, n, L, W, cxpb, mutpb, indpb):
    TI, Wp = g_ref.shape
    child, fit = _packed_body(
        g_ref[:], _u01_from_bits(_pair_consistent(pairbits_ref[:])),
        _u01_from_bits(rowbits_ref[:][:, 0:1]),
        _u01_from_bits(genebits_ref[:]), n=n, L=L, W=W,
        TI=TI, Wp=Wp, cxpb=cxpb, mutpb=mutpb, indpb=indpb,
        tile_idx=pl.program_id(0))
    out_ref[:] = child
    fit_ref[:] = fit


def _packed_kernel_hw(seed_ref, g_ref, out_ref, fit_ref, *, n, L, W, cxpb,
                      mutpb, indpb):
    TI, Wp = g_ref.shape
    i = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0] + i)
    # pair (4) + row (1) draws share one block: separate calls each
    # cost a full vreg generation per 8 sublanes at <4% lane use
    prbits = pltpu.bitcast(pltpu.prng_random_bits((TI, 8)), jnp.uint32)
    pairbits, rowbits = prbits[:, 0:4], prbits[:, 4:5]
    # ONE full-width draw for all 32 bit planes: a per-plane
    # prng_random_bits((TI, Wp)) touches Wp (= 4 at L=100) of the 128
    # vector lanes and costs a full vreg generation each — 32 calls per
    # tile wasting ~97% of the PRNG's vector width. The consolidated
    # (TI, WORD*Wp) block is the exact same bit budget in full-lane
    # strides, laid out exactly like the bits-input stream.
    genebits = pltpu.bitcast(
        pltpu.prng_random_bits((TI, WORD * Wp)), jnp.uint32)
    child, fit = _packed_body(
        g_ref[:], _u01_from_bits(_pair_consistent(pairbits)),
        _u01_from_bits(rowbits), _u01_from_bits(genebits), n=n, L=L, W=W,
        TI=TI, Wp=Wp, cxpb=cxpb, mutpb=mutpb, indpb=indpb, tile_idx=i)
    out_ref[:] = child
    fit_ref[:] = fit


def fused_variation_eval_packed(key: jax.Array, packed: jnp.ndarray,
                                length: int, *, cxpb: float, mutpb: float,
                                indpb: float, prng: str = "auto",
                                block_i: int = 256,
                                interpret: Optional[bool] = None,
                                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One fused variation+evaluation pass on packed genomes — the
    packed twin of :func:`deap_tpu.ops.kernels.fused_variation_eval`
    with identical semantics and an up-to-8× smaller genome stream.

    The word axis is NOT padded to the 128-lane tile: a [TI, W] block
    with W ≪ 128 wastes vector-register lanes (the kernel is memory-
    bound, so that is cheap) but streams only the real ``4·W`` bytes per
    row through HBM — padding to 128 lanes would stream 32× more than
    the byte-genome kernel at W=4 and erase the packing win.

    :param packed: ``uint32[n, W]`` rows from :func:`pack_genomes`.
    :returns: ``(children uint32[n, W], fitness f32[n])``.
    """
    from deap_tpu.ops.kernels import (
        _auto_interpret,
        _resolve_prng,
        _round_up,
        run_fused_kernel,
    )

    n, W = packed.shape
    assert block_i % 2 == 0, "pairs must not straddle tiles"
    ni = _round_up(n, block_i)
    interp = _auto_interpret(interpret)
    prng = _resolve_prng(prng, interp)

    g = jnp.pad(packed, ((0, ni - n), (0, 0)))
    common = dict(n=n, L=length, W=W, cxpb=cxpb, mutpb=mutpb, indpb=indpb)
    out, fit = run_fused_kernel(
        key, g,
        kernel_hw=functools.partial(_packed_kernel_hw, **common),
        kernel_bits=functools.partial(_packed_kernel_bits, **common),
        prng=prng, interp=interp, block_i=block_i,
        genebit_cols=W * WORD, out_dtype=jnp.uint32)
    return out[:n], fit[:n, 0]


# ======================================================= select + gather ==

def _selgather_body(gT, fitT, draws, *, n, tournsize):
    """Tournament winners and their gathered columns, all in VMEM.

    Everything is LANE-MAJOR: the population axis runs along the 128
    vector lanes, because VMEM tiles the minor axis to 128 lanes — a
    row-major ``[n, W]`` resident table at W=4 would silently allocate
    32× its logical size (51 MB at n=100k) and blow the ~16 MB VMEM
    budget, while ``[W, n]`` is dense (~3.2 MB).

    Tournament rule lives in :func:`_tournament_idx` (shared with the
    whole-GA mega-kernel). The fitness lookups and the final column
    gather are lane-axis ``take_along_axis`` ops, which Mosaic lowers
    to the native ``tpu.dynamic_gather`` — the point of this kernel:
    no serial XLA gather ever touches HBM.
    """
    best_idx = _tournament_idx(fitT, draws, n=n, tournsize=tournsize)
    W, N = gT.shape
    idx_w = jnp.broadcast_to(best_idx, (W, N))
    return jnp.take_along_axis(gT, idx_w, axis=1,
                               mode="promise_in_bounds")


def _selgather_kernel_hw(seed_ref, gT_ref, fitT_ref, out_ref, *, n,
                         tournsize):
    pltpu.prng_seed(seed_ref[0])
    N = gT_ref.shape[1]
    # one (1, N) draw per stage: full lane width each, nothing wasted
    draws = jnp.concatenate(
        [pltpu.bitcast(pltpu.prng_random_bits((1, N)), jnp.uint32)
         for _ in range(tournsize)], axis=0)
    out_ref[:] = _selgather_body(gT_ref[:], fitT_ref[:], draws,
                                 n=n, tournsize=tournsize)


def _selgather_kernel_bits(gT_ref, fitT_ref, draws_ref, out_ref, *, n,
                           tournsize):
    out_ref[:] = _selgather_body(gT_ref[:], fitT_ref[:], draws_ref[:],
                                 n=n, tournsize=tournsize)


def _tournament_idx(fitT, draws, *, n, tournsize):
    """Lane-major tournament: winning population index per lane.
    ``fitT`` is ``f32[1, N]``, ``draws`` ``uint32[tournsize, N]``;
    aspirant ``t`` of lane ``j`` is ``draws[t, j] % n`` (modulo bias
    < n/2**32). Strict ``>`` keeps the first-drawn on ties, matching
    the reference's ``max()`` (selection.py:63-69). The single home of
    the tournament rule for both the selgather kernel and the
    whole-GA mega-kernel."""
    best_idx = (draws[0:1, :] % np.uint32(n)).astype(jnp.int32)
    best_fit = jnp.take_along_axis(fitT, best_idx, axis=1,
                                   mode="promise_in_bounds")
    for t in range(1, tournsize):
        idx = (draws[t:t + 1, :] % np.uint32(n)).astype(jnp.int32)
        f = jnp.take_along_axis(fitT, idx, axis=1,
                                mode="promise_in_bounds")
        better = f > best_fit
        best_idx = jnp.where(better, idx, best_idx)
        best_fit = jnp.where(better, f, best_fit)
    return best_idx


def _fold_bitplanes_lanes(mask_f32, W):
    """[32·W, C] per-bit 0/1 mask (plane-major rows: plane ``b`` of
    word ``w`` at row ``b·W + w``) → ``uint32[W, C]`` flip words, via
    two MXU matmuls with a constant [W, 32·W] fold matrix — the
    lane-major mirror of :func:`_flip_words_matmul`, with the fold on
    the LEFT because the population axis runs along lanes here. Exact:
    the 16/16 bit-plane split keeps each f32 sum below 2^16."""
    rows = WORD * W
    w = jax.lax.broadcasted_iota(jnp.int32, (W, rows), 0)
    r = jax.lax.broadcasted_iota(jnp.int32, (W, rows), 1)
    b = r // W
    sel = (r % W) == w

    def fold(half, shift):
        m = jnp.where(sel & half,
                      jnp.left_shift(1, b - shift), 0).astype(jnp.float32)
        s = jax.lax.dot_general(m, mask_f32, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return jax.lax.bitcast_convert_type(s.astype(jnp.int32),
                                            jnp.uint32)

    return fold(b < 16, 0) | (fold(b >= 16, 16) << np.uint32(16))


def _evolve_body(pop_ref, fit_ref, tmp_ref, *, n, N, L, W, G, tournsize,
                 cxpb, mutpb, indpb, chunk, draw_sel, draw_pair,
                 draw_row, draw_gene):
    """G whole generations — tournament selection, two-point
    crossover, flip-bit mutation, popcount fitness — over the
    VMEM-resident lane-major population ``pop_ref`` (uint32[W, N]) and
    fitness ``fit_ref`` (f32[1, N]). ``tmp_ref`` is the double buffer.
    The draw_* callbacks supply uint32 randomness (hardware PRNG on
    chip, preloaded refs under the interpreter) in a fixed consumption
    order. Padding lanes (>= n) are never selected (draws are % n) and
    their junk fitness is inert."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
    even = (lane % 2) == 0
    has_partner = jnp.bitwise_or(lane, 1) < n
    word_start = jax.lax.broadcasted_iota(jnp.int32, (W, 1), 0) * WORD
    tailmask = _bits_below(L - word_start)          # [W, 1]

    def gen(g_idx, _):
        # --- selection + parent gather, all lane-axis dynamic_gather —
        best_idx = _tournament_idx(fit_ref[:], draw_sel(g_idx),
                                   n=n, tournsize=tournsize)
        parents = jnp.take_along_axis(
            pop_ref[:], jnp.broadcast_to(best_idx, (W, N)), axis=1,
            mode="promise_in_bounds")

        # --- two-point crossover on adjacent-lane pairs (children
        # 2j/2j+1 are adjacent LANES here: axis=1 pair consistency) ---
        pairu = _u01_from_bits(_pair_consistent(draw_pair(g_idx),
                                                axis=1))
        do_cx = pairu[0:1] < cxpb
        p1 = 1 + (pairu[1:2] * L).astype(jnp.int32)
        p2 = 1 + (pairu[2:3] * (L - 1)).astype(jnp.int32)
        p2 = jnp.where(p2 >= p1, p2 + 1, p2)
        lo = jnp.minimum(p1, p2)
        hi = jnp.maximum(p1, p2)
        fwd = pltpu.roll(parents, N - 1, 1)         # lane j <- j+1
        bwd = pltpu.roll(parents, 1, 1)             # lane j <- j-1
        partner = jnp.where(even, fwd, bwd)
        seg = _bits_below(hi - word_start) & ~_bits_below(lo - word_start)
        seg = jnp.where(do_cx & has_partner, seg, np.uint32(0))
        tmp_ref[:] = (parents & ~seg) | (partner & seg)

        # --- mutation + fitness, chunked over lanes -----------------
        # the per-gene uniform block is [32W, chunk] f32 — full
        # population width at once would be ~50 MB of VMEM at n=100k
        do_mut = _u01_from_bits(draw_row(g_idx)) < mutpb   # [1, N]
        def mchunk(c, _):
            sl = pl.ds(c * chunk, chunk)
            mask = (_u01_from_bits(draw_gene(g_idx, c))
                    < indpb).astype(jnp.float32)
            flip = _fold_bitplanes_lanes(mask, W) & tailmask
            dm = jax.lax.dynamic_slice(do_mut, (0, c * chunk),
                                       (1, chunk))
            newc = tmp_ref[:, sl] ^ jnp.where(dm, flip, np.uint32(0))
            tmp_ref[:, sl] = newc
            counts = jax.lax.bitcast_convert_type(popcount(newc),
                                                  jnp.int32)
            fit_ref[:, sl] = counts.astype(jnp.float32).sum(
                axis=0, keepdims=True)
            return 0

        jax.lax.fori_loop(0, N // chunk, mchunk, 0)
        pop_ref[:] = tmp_ref[:]
        return 0

    jax.lax.fori_loop(0, G, gen, 0)


def _evolve_kernel_hw(seed_ref, gT_ref, fT_ref, outpop_ref, outfit_ref,
                      tmp_ref, *, n, N, L, W, G, tournsize, cxpb, mutpb,
                      indpb, chunk):
    pltpu.prng_seed(seed_ref[0])
    outpop_ref[:] = gT_ref[:]
    outfit_ref[:] = fT_ref[:]
    bits = lambda shape: pltpu.bitcast(pltpu.prng_random_bits(shape),
                                       jnp.uint32)
    _evolve_body(
        outpop_ref, outfit_ref, tmp_ref, n=n, N=N, L=L, W=W, G=G,
        tournsize=tournsize, cxpb=cxpb, mutpb=mutpb, indpb=indpb,
        chunk=chunk,
        draw_sel=lambda g: bits((tournsize, N)),
        draw_pair=lambda g: bits((3, N)),
        draw_row=lambda g: bits((1, N)),
        draw_gene=lambda g, c: bits((WORD * W, chunk)))


def _evolve_kernel_bits(gT_ref, fT_ref, sel_ref, pair_ref, row_ref,
                        gene_ref, outpop_ref, outfit_ref, tmp_ref, *,
                        n, N, L, W, G, tournsize, cxpb, mutpb, indpb,
                        chunk):
    outpop_ref[:] = gT_ref[:]
    outfit_ref[:] = fT_ref[:]
    _evolve_body(
        outpop_ref, outfit_ref, tmp_ref, n=n, N=N, L=L, W=W, G=G,
        tournsize=tournsize, cxpb=cxpb, mutpb=mutpb, indpb=indpb,
        chunk=chunk,
        draw_sel=lambda g: sel_ref[g],
        draw_pair=lambda g: pair_ref[g],
        draw_row=lambda g: row_ref[g],
        draw_gene=lambda g, c: gene_ref[g, :, pl.ds(c * chunk, chunk)])


def evolve_packed(key: jax.Array, packed: jnp.ndarray, fit: jnp.ndarray,
                  length: int, ngen: int, *, tournsize: int = 3,
                  cxpb: float, mutpb: float, indpb: float,
                  prng: str = "auto", chunk: int = 4096,
                  interpret: Optional[bool] = None,
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run ``ngen`` WHOLE generations of the OneMax eaSimple loop —
    tournament selection, two-point crossover, flip-bit mutation,
    popcount evaluation — inside ONE single-program Pallas kernel with
    the population resident in VMEM.

    Motivation (r4): at 449 gens/s the measured per-generation time is
    ~2.2 ms against an ~9 µs HBM floor for the ~7 MB a generation
    actually moves — the chip is >99% idle and the cost must be
    per-generation launch/dispatch overheads of the multi-op XLA step.
    This kernel removes them wholesale: HBM sees one population read
    and one write per ``ngen`` generations; selection needs no sort,
    rank permutation, or XLA gather (lane-axis ``dynamic_gather``
    against the resident population, as in
    :func:`sel_tournament_gather_packed`); variation and popcount run
    on the same resident buffers (double-buffered via one scratch).

    Semantics per generation match the raced XLA/kernel composition —
    ``sel_tournament`` (+gather) then ``fused_variation_eval_packed``
    (reference loop being replaced: ``eaSimple``,
    deap/algorithms.py:85-189) — with the same tournament tie rule
    (first-drawn wins), pair-consistent crossover draws, exact per-bit
    Bernoulli(indpb) flips, and OneMax-specific popcount fitness.
    Draw streams differ from the other candidates (one hardware PRNG
    stream per kernel), so runs are distribution-equivalent, not
    bit-identical.

    :param packed: ``uint32[n, W]`` rows from :func:`pack_genomes`.
    :param fit: ``f32[n]`` current fitness (e.g. ``packed_fitness``).
    :param ngen: static generation count baked into the program.
    :param chunk: lanes per mutation sub-block (bounds the [32W, chunk]
        per-gene uniform block's VMEM footprint); population is padded
        to a multiple.
    :returns: ``(population uint32[n, W], fitness f32[n])`` after
        ``ngen`` generations.
    """
    from deap_tpu.ops.kernels import (
        _auto_interpret,
        _resolve_prng,
        _round_up,
    )

    n, W = packed.shape
    if ngen == 0:
        return packed, fit.astype(jnp.float32)
    interp = _auto_interpret(interpret)
    prng = _resolve_prng(prng, interp)
    N = _round_up(n, chunk)
    gT = jnp.pad(packed.T, ((0, 0), (0, N - n)))
    fT = jnp.pad(fit.astype(jnp.float32), (0, N - n),
                 constant_values=-jnp.inf)[None, :]
    vmem = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    out_shapes = (jax.ShapeDtypeStruct((W, N), jnp.uint32),
                  jax.ShapeDtypeStruct((1, N), jnp.float32))
    scratch = [pltpu.VMEM((W, N), jnp.uint32)]
    common = dict(n=n, N=N, L=length, W=W, G=ngen, tournsize=tournsize,
                  cxpb=cxpb, mutpb=mutpb, indpb=indpb, chunk=chunk)
    if prng == "hw":
        seed = jax.random.randint(key, (1,), 0, 2**31 - 1, jnp.int32)
        outT, outfit = pl.pallas_call(
            functools.partial(_evolve_kernel_hw, **common),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), vmem(),
                      vmem()],
            out_specs=(vmem(), vmem()),
            out_shape=out_shapes,
            scratch_shapes=scratch,
            interpret=interp,
        )(seed, gT, fT)
    else:
        # The 'input' path feeds every generation's draws as VMEM-resident
        # kernel inputs — gene alone is (ngen, 32W, N) uint32 — so off the
        # interpreter it only fits for tiny ngen/N; past ~a VMEM's worth
        # Mosaic fails allocation with an opaque error. Fail fast instead.
        draw_bytes = 4 * ngen * (tournsize + 3 + 1 + WORD * W) * N
        if not interp and draw_bytes > 12 * 2**20:
            raise ValueError(
                f"evolve_packed(prng='input') would materialise "
                f"{draw_bytes / 2**20:.0f} MiB of draw tensors as "
                f"VMEM-resident kernel inputs (ngen={ngen}, pop={n}, "
                f"W={W}); this cannot fit on hardware. Use prng='hw' "
                f"(per-kernel hardware PRNG stream) or interpret=True "
                f"(testing only).")
        ks, kp, kr, kg = jax.random.split(key, 4)
        sel = jax.random.bits(ks, (ngen, tournsize, N), jnp.uint32)
        pair = jax.random.bits(kp, (ngen, 3, N), jnp.uint32)
        row = jax.random.bits(kr, (ngen, 1, N), jnp.uint32)
        gene = jax.random.bits(kg, (ngen, WORD * W, N), jnp.uint32)
        outT, outfit = pl.pallas_call(
            functools.partial(_evolve_kernel_bits, **common),
            in_specs=[vmem()] * 6,
            out_specs=(vmem(), vmem()),
            out_shape=out_shapes,
            scratch_shapes=scratch,
            interpret=interp,
        )(gT, fT, sel, pair, row, gene)
    return outT.T[:n], outfit[0, :n]


def sel_tournament_gather_packed(key: jax.Array, packed: jnp.ndarray,
                                 fit: jnp.ndarray, tournsize: int = 3,
                                 prng: str = "auto",
                                 interpret: Optional[bool] = None,
                                 ) -> jnp.ndarray:
    """Tournament-select ``n`` parents AND gather their rows in one
    single-program Pallas kernel — the population-resident-in-VMEM
    formulation of ``sel_tournament`` + ``packed[idx]``.

    At pop = 100k the packed population is ``n·W`` words — lane-major
    (transposed to ``[W, n]``, population along the 128 lanes) that is
    ~3.2 MB resident in VMEM incl. sublane padding, leaving room for
    the fitness row and the parent output inside the ~16 MB budget;
    selection then needs no sort, no rank permutation, and no XLA
    gather — each child draws ``tournsize`` aspirant indices, looks
    their fitness up with the lane-axis ``dynamic_gather``, and copies
    the winning column, all inside the chip. One HBM read of the
    population and one write of the parents replace the counting-sort
    + double-gather chain of the binned path (reference hot loop being
    replaced: examples/ga/onemax.py:72-157 select step; semantics:
    selTournament, tools/selection.py:32-46). The XLA transposes at
    the boundary are dense-layout copies (~1.6 MB each way at 100k).

    :param packed: ``uint32[n, W]`` rows from :func:`pack_genomes`.
    :param fit: ``f32[n]`` fitness (weighted first objective).
    :returns: ``uint32[n, W]`` parent rows, one per child slot.
    """
    from deap_tpu.ops.kernels import (
        _auto_interpret,
        _resolve_prng,
        _round_up,
    )

    n, W = packed.shape
    interp = _auto_interpret(interpret)
    prng = _resolve_prng(prng, interp)
    ni = _round_up(n, 128)
    gT = jnp.pad(packed.T, ((0, 0), (0, ni - n)))
    # -inf pad: unreachable anyway (draws are % n), belt and braces
    fT = jnp.pad(fit.astype(jnp.float32), (0, ni - n),
                 constant_values=-jnp.inf)[None, :]
    vmem = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((W, ni), jnp.uint32)
    if prng == "hw":
        seed = jax.random.randint(key, (1,), 0, 2**31 - 1, jnp.int32)
        outT = pl.pallas_call(
            functools.partial(_selgather_kernel_hw, n=n,
                              tournsize=tournsize),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), vmem(),
                      vmem()],
            out_specs=vmem(),
            out_shape=out_shape,
            interpret=interp,
        )(seed, gT, fT)
    else:
        draws = jax.random.bits(key, (tournsize, ni), jnp.uint32)
        outT = pl.pallas_call(
            functools.partial(_selgather_kernel_bits, n=n,
                              tournsize=tournsize),
            in_specs=[vmem(), vmem(), vmem()],
            out_specs=vmem(),
            out_shape=out_shape,
            interpret=interp,
        )(gT, fT, draws)
    return outT.T[:n]
