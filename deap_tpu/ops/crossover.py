"""Crossover operators as pure per-pair functions.

Counterpart of /root/reference/deap/tools/crossover.py. Every operator is
``(key, g1, g2, **params) -> (c1, c2)`` on single genomes ``[L]``; batch
them over a population with :func:`pair_vmap` (or ``jax.vmap`` directly).
Where the reference draws ``random.random() < p`` per gene inside Python
loops, these draw whole Bernoulli/uniform masks in one op; where it
mutates lists in place, these build children with ``where`` masks and
functional scatters. Distributional behaviour matches the reference;
RNG streams obviously do not (explicit `jax.random` keys replace the
global `random` module).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pair_vmap(cx):
    """Lift a per-pair crossover to ``(key, G1, G2, ...)`` over ``[n, L]``."""
    def batched(key, g1, g2, *args, **kwargs):
        keys = jax.random.split(key, g1.shape[0])
        return jax.vmap(lambda k, a, b: cx(k, a, b, *args, **kwargs))(keys, g1, g2)
    return batched


# ---------------------------------------------------------------- generic ----

def cx_one_point(key, g1, g2):
    """One-point crossover (crossover.py:18-34): swap tails after a point
    drawn in [1, L-1]."""
    size = g1.shape[0]
    point = jax.random.randint(key, (), 1, size)
    mask = jnp.arange(size) >= point
    return jnp.where(mask, g2, g1), jnp.where(mask, g1, g2)


def _one_point_segment(key, size):
    """``cx_one_point``'s cut as a half-open swap segment — the SAME
    single randint draw from the whole key, so the fused variation
    plane (ops.variation) reproduces the operator's bits exactly."""
    point = jax.random.randint(key, (), 1, size)
    return point, jnp.int32(size)


cx_one_point.fused_segment_draw = _one_point_segment


def _two_points(key, size):
    """The reference's two-point draw (crossover.py:44-50): p1 ~ U{1..L}
    (randint is inclusive there), p2 ~ U{1..L-1} bumped past p1 — a
    uniform distinct ordered pair whose segment may include the last
    gene."""
    k1, k2 = jax.random.split(key)
    p1 = jax.random.randint(k1, (), 1, size + 1)
    p2 = jax.random.randint(k2, (), 1, size)
    p2 = jnp.where(p2 >= p1, p2 + 1, p2)
    return jnp.minimum(p1, p2), jnp.maximum(p1, p2)


def cx_two_point(key, g1, g2):
    """Two-point crossover (crossover.py:37-60): swap the middle segment."""
    lo, hi = _two_points(key, g1.shape[0])
    idx = jnp.arange(g1.shape[0])
    mask = (idx >= lo) & (idx < hi)
    return jnp.where(mask, g2, g1), jnp.where(mask, g1, g2)


# the fused variation plane consumes _two_points directly: the swap
# segment [lo, hi) IS the operator's whole randomness
cx_two_point.fused_segment_draw = _two_points


def cx_uniform(key, g1, g2, indpb):
    """Uniform crossover (crossover.py:73-91): per-gene swap with prob indpb."""
    mask = jax.random.bernoulli(key, indpb, g1.shape)
    return jnp.where(mask, g2, g1), jnp.where(mask, g1, g2)


# ----------------------------------------------------------- permutations ----

def _positions(perm):
    """pos[value] = index of value in perm."""
    size = perm.shape[0]
    return jnp.zeros(size, jnp.int32).at[perm].set(jnp.arange(size, dtype=jnp.int32))


def cx_partialy_matched(key, g1, g2):
    """PMX (Goldberg & Lingle 1985; crossover.py:94-141).

    Sequentially swaps matched value pairs inside a random segment while
    maintaining value→position lookups — the data dependence is inherent,
    so it runs as a ``fori_loop`` over gene slots (masked outside the
    segment) and is vmapped across the population.
    """
    size = g1.shape[0]
    k1, k2 = jax.random.split(key)
    # reference draw: c1 ~ U{0..L}, c2 ~ U{0..L-1} bumped past c1
    c1 = jax.random.randint(k1, (), 0, size + 1)
    c2 = jax.random.randint(k2, (), 0, size)
    c2 = jnp.where(c2 >= c1, c2 + 1, c2)
    lo, hi = jnp.minimum(c1, c2), jnp.maximum(c1, c2)

    a = g1.astype(jnp.int32)
    b = g2.astype(jnp.int32)
    p1, p2 = _positions(a), _positions(b)

    def body(i, carry):
        a, b, p1, p2 = carry
        t1, t2 = a[i], b[i]
        j1, j2 = p1[t2], p2[t1]
        a2 = a.at[i].set(t2).at[j1].set(t1)
        b2 = b.at[i].set(t1).at[j2].set(t2)
        p1_2 = p1.at[t1].set(j1).at[t2].set(i)
        p2_2 = p2.at[t2].set(j2).at[t1].set(i)
        in_seg = (i >= lo) & (i < hi)
        pick = lambda new, old: jnp.where(in_seg, new, old)
        return pick(a2, a), pick(b2, b), pick(p1_2, p1), pick(p2_2, p2)

    a, b, _, _ = lax.fori_loop(0, size, body, (a, b, p1, p2))
    return a.astype(g1.dtype), b.astype(g2.dtype)


def cx_uniform_partialy_matched(key, g1, g2, indpb):
    """UPMX (Cicirello & Smith 2000; crossover.py:144-186): PMX swap at
    each slot independently with prob indpb."""
    size = g1.shape[0]
    kmask, _ = jax.random.split(key)
    do = jax.random.bernoulli(kmask, indpb, (size,))
    a = g1.astype(jnp.int32)
    b = g2.astype(jnp.int32)
    p1, p2 = _positions(a), _positions(b)

    def body(i, carry):
        a, b, p1, p2 = carry
        t1, t2 = a[i], b[i]
        j1, j2 = p1[t2], p2[t1]
        a2 = a.at[i].set(t2).at[j1].set(t1)
        b2 = b.at[i].set(t1).at[j2].set(t2)
        p1_2 = p1.at[t1].set(j1).at[t2].set(i)
        p2_2 = p2.at[t2].set(j2).at[t1].set(i)
        pick = lambda new, old: jnp.where(do[i], new, old)
        return pick(a2, a), pick(b2, b), pick(p1_2, p1), pick(p2_2, p2)

    a, b, _, _ = lax.fori_loop(0, size, body, (a, b, p1, p2))
    return a.astype(g1.dtype), b.astype(g2.dtype)


def cx_ordered(key, g1, g2):
    """Ordered crossover OX (Goldberg 1989; crossover.py:188-239).

    Child 1 keeps parent 2's segment [a, b] and fills the remaining slots
    (starting after b, wrapping) with parent 1's values not present in
    that segment, in parent-1 rotation order — and symmetrically.
    """
    size = g1.shape[0]
    k1, k2 = jax.random.split(key)
    # random.sample(range(L), 2) → uniform distinct unordered pair, ordered
    i1 = jax.random.randint(k1, (), 0, size)
    i2 = jax.random.randint(k2, (), 0, size - 1)
    i2 = jnp.where(i2 >= i1, i2 + 1, i2)
    lo, hi = jnp.minimum(i1, i2), jnp.maximum(i1, i2)  # segment inclusive

    a = g1.astype(jnp.int32)
    b = g2.astype(jnp.int32)
    posa, posb = _positions(a), _positions(b)
    # value v is a "hole" for child1 iff v sits inside b's segment
    hole1 = (posb >= lo) & (posb <= hi)
    hole2 = (posa >= lo) & (posa <= hi)

    def body(i, carry):
        c1, k1p, c2, k2p = carry
        j = (i + hi + 1) % size
        v1, v2 = a[j], b[j]
        take1, take2 = ~hole1[v1], ~hole2[v2]
        c1 = jnp.where(take1, c1.at[k1p % size].set(v1), c1)
        c2 = jnp.where(take2, c2.at[k2p % size].set(v2), c2)
        return c1, k1p + take1, c2, k2p + take2

    c1, _, c2, _ = lax.fori_loop(0, size, body, (a, hi + 1, b, hi + 1))
    idx = jnp.arange(size)
    in_seg = (idx >= lo) & (idx <= hi)
    c1 = jnp.where(in_seg, b, c1)
    c2 = jnp.where(in_seg, a, c2)
    return c1.astype(g1.dtype), c2.astype(g2.dtype)


# ------------------------------------------------------------- real-valued ----

def cx_blend(key, g1, g2, alpha):
    """BLX-alpha blend (crossover.py:241-260): per-gene gamma in
    [-alpha, 1+alpha]."""
    gamma = (1.0 + 2.0 * alpha) * jax.random.uniform(key, g1.shape) - alpha
    c1 = (1.0 - gamma) * g1 + gamma * g2
    c2 = gamma * g1 + (1.0 - gamma) * g2
    return c1, c2


def _sbx_beta(rand, eta):
    beta = jnp.where(rand <= 0.5, 2.0 * rand, 1.0 / (2.0 * (1.0 - rand)))
    return beta ** (1.0 / (eta + 1.0))


def cx_simulated_binary(key, g1, g2, eta):
    """SBX (crossover.py:263-289): spread factor beta per gene."""
    beta = _sbx_beta(jax.random.uniform(key, g1.shape), eta)
    c1 = 0.5 * ((1 + beta) * g1 + (1 - beta) * g2)
    c2 = 0.5 * ((1 - beta) * g1 + (1 + beta) * g2)
    return c1, c2


def cx_simulated_binary_bounded(key, g1, g2, eta, low, up):
    """Bounded SBX per Deb's NSGA-II C code (crossover.py:291-364).

    Per gene: applied with prob 0.5 and only when the parents differ;
    children are clipped to [low, up] and swapped with prob 0.5.
    """
    low = jnp.broadcast_to(jnp.asarray(low, g1.dtype), g1.shape)
    up = jnp.broadcast_to(jnp.asarray(up, g1.dtype), g1.shape)
    kg, kr, ks = jax.random.split(key, 3)
    gate = jax.random.bernoulli(kg, 0.5, g1.shape) & (jnp.abs(g1 - g2) > 1e-14)
    rand = jax.random.uniform(kr, g1.shape)
    swap = jax.random.bernoulli(ks, 0.5, g1.shape)

    x1 = jnp.minimum(g1, g2)
    x2 = jnp.maximum(g1, g2)
    diff = jnp.where(gate, x2 - x1, 1.0)  # avoid 0-div on inactive lanes

    def child(bound_term, sign):
        beta = 1.0 + 2.0 * bound_term / diff
        alpha = 2.0 - beta ** -(eta + 1.0)
        beta_q = jnp.where(
            rand <= 1.0 / alpha,
            (rand * alpha) ** (1.0 / (eta + 1.0)),
            (1.0 / (2.0 - rand * alpha)) ** (1.0 / (eta + 1.0)),
        )
        return 0.5 * (x1 + x2 + sign * beta_q * diff)

    c1 = jnp.clip(child(x1 - low, -1.0), low, up)
    c2 = jnp.clip(child(up - x2, +1.0), low, up)
    o1 = jnp.where(swap, c2, c1)
    o2 = jnp.where(swap, c1, c2)
    return jnp.where(gate, o1, g1), jnp.where(gate, o2, g2)


# ------------------------------------------------------- length-changing ----

def cx_messy_one_point(key, g1, len1, g2, len2):
    """Messy one-point crossover (crossover.py:367-383) for fixed-capacity
    padded genomes with explicit lengths.

    ``c1 = g1[:k1] ++ g2[k2:len2]`` (and symmetrically); the reference
    lets lists grow unboundedly — here results are truncated at the
    padded capacity, the standard tensor formulation of ragged genomes
    (SURVEY.md §7.3).
    """
    cap = g1.shape[0]
    k1key, k2key = jax.random.split(key)
    k1 = jax.random.randint(k1key, (), 0, len1 + 1)
    k2 = jax.random.randint(k2key, (), 0, len2 + 1)
    idx = jnp.arange(cap)

    def splice(a, ka, b, kb, lb):
        # child[i] = a[i] for i < ka else b[i - ka + kb]
        src = jnp.clip(idx - ka + kb, 0, cap - 1)
        child = jnp.where(idx < ka, a, b[src])
        newlen = jnp.minimum(ka + jnp.maximum(lb - kb, 0), cap)
        return jnp.where(idx < newlen, child, jnp.zeros_like(child)), newlen

    c1, n1 = splice(g1, k1, g2, k2, len2)
    c2, n2 = splice(g2, k2, g1, k1, len1)
    return (c1, n1), (c2, n2)


# ------------------------------------------------------------------- ES ----

def cx_es_blend(key, g1, s1, g2, s2, alpha):
    """ES blend (crossover.py:390-417): independent gammas for values and
    strategies."""
    kg, ks = jax.random.split(key)
    c1, c2 = cx_blend(kg, g1, g2, alpha)
    n1, n2 = cx_blend(ks, s1, s2, alpha)
    return (c1, n1), (c2, n2)


def cx_es_two_point(key, g1, s1, g2, s2):
    """ES two-point (crossover.py:419-445): same crossover points applied
    to values and strategy vectors."""
    lo, hi = _two_points(key, g1.shape[0])
    idx = jnp.arange(g1.shape[0])
    mask = (idx >= lo) & (idx < hi)
    c1 = jnp.where(mask, g2, g1)
    c2 = jnp.where(mask, g1, g2)
    n1 = jnp.where(mask, s2, s1)
    n2 = jnp.where(mask, s1, s2)
    return (c1, n1), (c2, n2)
