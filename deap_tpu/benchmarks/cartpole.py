"""CartPole — a jnp control benchmark for neuroevolution.

The classic cart-pole balancing task (Barto, Sutton & Anderson 1983)
with the standard Gym-era constants: state ``[x, ẋ, θ, θ̇]``, bang-bang
force ±10 N, Euler integration at dt=0.02, failure when |x| > 2.4 m or
|θ| > 12°, reward 1 per surviving step, capped at ``max_steps``.

This is the environment for BASELINE.json config #5 ("evolve MLP weights
for CartPole"): rollouts are pure ``lax.scan`` programs, so a whole
population of policies runs as one vmapped XLA program — the TPU-native
replacement for the per-individual simulator processes a CPU
neuroevolution setup would use.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

GRAVITY = 9.8
MASS_CART = 1.0
MASS_POLE = 0.1
TOTAL_MASS = MASS_CART + MASS_POLE
HALF_LENGTH = 0.5
POLEMASS_LENGTH = MASS_POLE * HALF_LENGTH
FORCE_MAG = 10.0
DT = 0.02
X_LIMIT = 2.4
THETA_LIMIT = 12.0 * jnp.pi / 180.0


def cartpole_step(state: jnp.ndarray, action: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One Euler step; ``action`` ∈ {0, 1} (left/right). Returns
    (next_state, failed)."""
    x, x_dot, theta, theta_dot = state
    force = jnp.where(action > 0, FORCE_MAG, -FORCE_MAG)
    cos_t = jnp.cos(theta)
    sin_t = jnp.sin(theta)
    temp = (force + POLEMASS_LENGTH * theta_dot ** 2 * sin_t) / TOTAL_MASS
    theta_acc = (GRAVITY * sin_t - cos_t * temp) / (
        HALF_LENGTH * (4.0 / 3.0 - MASS_POLE * cos_t ** 2 / TOTAL_MASS))
    x_acc = temp - POLEMASS_LENGTH * theta_acc * cos_t / TOTAL_MASS
    new = jnp.stack([
        x + DT * x_dot,
        x_dot + DT * x_acc,
        theta + DT * theta_dot,
        theta_dot + DT * theta_acc,
    ])
    failed = (jnp.abs(new[0]) > X_LIMIT) | (jnp.abs(new[2]) > THETA_LIMIT)
    return new, failed


def initial_state(key: jax.Array) -> jnp.ndarray:
    """Uniform(-0.05, 0.05) start, the Gym convention."""
    return jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)


def rollout(policy: Callable, params, key: jax.Array,
            max_steps: int = 500) -> jnp.ndarray:
    """Total reward of ``policy(params, state) -> action logits [2]``
    over one episode; a failed episode stops accumulating (mask, not
    early exit — uniform control flow for the batch)."""
    s0 = initial_state(key)

    def step(carry, _):
        state, alive = carry
        logits = policy(params, state)
        action = jnp.argmax(logits)
        new, failed = cartpole_step(state, action)
        reward = alive.astype(jnp.float32)
        return (new, alive & ~failed), reward

    (_, _), rewards = lax.scan(step, (s0, jnp.bool_(True)),
                               None, length=max_steps)
    return rewards.sum()


def rollout_population(policy: Callable, genomes: jnp.ndarray,
                       keys: jax.Array, max_steps: int = 500,
                       chunk: int = 10, min_size: int = 512
                       ) -> jnp.ndarray:
    """Episode returns for a whole population at once — ``[P, E]`` for
    ``P`` policies × ``E`` shared episode keys — with active-episode
    compaction, so cost tracks the population's survivor curve instead
    of always paying ``max_steps`` per episode.

    This removes :func:`rollout`'s structural tax: a vmapped
    per-episode scan pays ``max_steps`` iterations for every episode,
    but random policies fail in ~20 steps, so ~96% of that work steps
    dead episodes; a single batch-wide early exit barely helps because
    a 0.1%-tail of episodes reaches the cap and pins the loop open
    (measured: mean length 17.8, p99.9 = cap). Structure here — a
    cascade of halving levels:

    1. at the current level size, run ``chunk``-step scans inside a
       ``while_loop`` until the alive count drops to half the level
       (or the step cap hits);
    2. scatter this level's rewards into the full-batch result, then
       compact the alive episodes (stable argsort on the dead mask) to
       a half-size buffer and recurse, down to ``min_size``.

    Total stepping work is ≤ 2× the survivor-curve integral (each
    episode is stepped in a buffer at most 2× the concurrent alive
    count) — ≈ ``B·2·mean(len)`` vs the scan path's ``B·max_steps``.
    The level ladder is static (python loop over halvings), so the
    whole thing stays one jittable program, usable inside a generation
    ``lax.scan``; the cap-hit case degrades gracefully (later levels'
    loops exit immediately).

    Fitness matches ``rollout`` exactly: reward 1 per step entered
    alive; dead episodes hold their state frozen (``where`` mask) so
    late-failure physics can't overflow while a level finishes.

    Sharding note: the per-chunk alive count and the per-level
    argsort/gather are GLOBAL over the flattened episode axis — on a
    population sharded across a multi-device mesh they induce
    collectives (an all-reduce per chunk, an all-to-all per level).
    Single-device runs (the one-chip benchmark target) are unaffected;
    for a large mesh, wrap a per-shard instance in ``shard_map`` so
    compaction stays device-local."""
    if max_steps % chunk:
        # the loop advances whole chunks; an overshoot past the cap
        # would keep accruing reward beyond max_steps
        raise ValueError(f"max_steps ({max_steps}) must be a multiple "
                         f"of chunk ({chunk})")
    P, E = genomes.shape[0], keys.shape[0]
    B = P * E
    s0 = jax.vmap(initial_state)(keys)                    # [E, 4]
    state = jnp.broadcast_to(s0, (P, E, 4)).reshape(B, 4)
    params = jnp.repeat(genomes, E, axis=0)               # [B, n]
    step_policy = jax.vmap(policy)                        # [b,n],[b,4]→[b,2]

    alive = jnp.ones(B, jnp.bool_)
    reward = jnp.zeros(B, jnp.float32)
    orig = jnp.arange(B)
    total = jnp.zeros(B, jnp.float32)
    t = jnp.int32(0)
    size = B

    while True:
        last = size <= min_size
        target = size // 2

        def chunk_step(carry, _, params=params):
            st, al, rw = carry
            action = jnp.argmax(step_policy(params, st), axis=-1)
            new, failed = jax.vmap(cartpole_step)(st, action)
            rw = rw + al.astype(jnp.float32)
            st = jnp.where(al[:, None], new, st)
            return (st, al & ~failed, rw), None

        def body(carry):
            st, al, rw, tt = carry
            (st, al, rw), _ = lax.scan(chunk_step, (st, al, rw), None,
                                       length=chunk)
            return st, al, rw, tt + chunk

        def cond(carry, last=last, target=target):
            _, al, _, tt = carry
            more = al.any() if last else jnp.sum(al) > target
            return more & (tt < max_steps)

        state, alive, reward, t = lax.while_loop(
            cond, body, (state, alive, reward, t))
        # scatter this level's rewards; alive rows are re-scattered
        # with their final values at a later (smaller) level
        total = total.at[orig].set(reward)
        if last:
            break
        keep = jnp.argsort(~alive)[:target]   # stable: alive first
        state, alive, reward, orig = (state[keep], alive[keep],
                                      reward[keep], orig[keep])
        params = params[keep]
        size = target
    return total.reshape(P, E)


def mlp_policy(sizes=(4, 16, 2)) -> Tuple[Callable, int]:
    """A plain tanh MLP policy over a *flat* genome vector. Returns
    ``(policy(params_vector, state) -> logits, n_params)`` — flat
    genomes keep every GA operator (crossover, gaussian mutation)
    applicable unchanged."""
    shapes = []
    n = 0
    for a, b in zip(sizes[:-1], sizes[1:]):
        shapes.append(((a, b), (b,)))
        n += a * b + b

    def policy(params: jnp.ndarray, state: jnp.ndarray) -> jnp.ndarray:
        h = state
        off = 0
        for (in_d, out_d), _ in shapes:
            W = params[off: off + in_d * out_d].reshape(in_d, out_d)
            off += in_d * out_d
            b = params[off: off + out_d]
            off += out_d
            h = jnp.tanh(h @ W + b)
        return h

    return policy, n
