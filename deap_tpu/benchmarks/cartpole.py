"""CartPole — a jnp control benchmark for neuroevolution.

The classic cart-pole balancing task (Barto, Sutton & Anderson 1983)
with the standard Gym-era constants: state ``[x, ẋ, θ, θ̇]``, bang-bang
force ±10 N, Euler integration at dt=0.02, failure when |x| > 2.4 m or
|θ| > 12°, reward 1 per surviving step, capped at ``max_steps``.

This is the environment for BASELINE.json config #5 ("evolve MLP weights
for CartPole"): rollouts are pure ``lax.scan`` programs, so a whole
population of policies runs as one vmapped XLA program — the TPU-native
replacement for the per-individual simulator processes a CPU
neuroevolution setup would use.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

GRAVITY = 9.8
MASS_CART = 1.0
MASS_POLE = 0.1
TOTAL_MASS = MASS_CART + MASS_POLE
HALF_LENGTH = 0.5
POLEMASS_LENGTH = MASS_POLE * HALF_LENGTH
FORCE_MAG = 10.0
DT = 0.02
X_LIMIT = 2.4
THETA_LIMIT = 12.0 * jnp.pi / 180.0


def cartpole_step(state: jnp.ndarray, action: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One Euler step; ``action`` ∈ {0, 1} (left/right). Returns
    (next_state, failed)."""
    x, x_dot, theta, theta_dot = state
    force = jnp.where(action > 0, FORCE_MAG, -FORCE_MAG)
    cos_t = jnp.cos(theta)
    sin_t = jnp.sin(theta)
    temp = (force + POLEMASS_LENGTH * theta_dot ** 2 * sin_t) / TOTAL_MASS
    theta_acc = (GRAVITY * sin_t - cos_t * temp) / (
        HALF_LENGTH * (4.0 / 3.0 - MASS_POLE * cos_t ** 2 / TOTAL_MASS))
    x_acc = temp - POLEMASS_LENGTH * theta_acc * cos_t / TOTAL_MASS
    new = jnp.stack([
        x + DT * x_dot,
        x_dot + DT * x_acc,
        theta + DT * theta_dot,
        theta_dot + DT * theta_acc,
    ])
    failed = (jnp.abs(new[0]) > X_LIMIT) | (jnp.abs(new[2]) > THETA_LIMIT)
    return new, failed


def initial_state(key: jax.Array) -> jnp.ndarray:
    """Uniform(-0.05, 0.05) start, the Gym convention."""
    return jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)


def rollout(policy: Callable, params, key: jax.Array,
            max_steps: int = 500) -> jnp.ndarray:
    """Total reward of ``policy(params, state) -> action logits [2]``
    over one episode; a failed episode stops accumulating (mask, not
    early exit — uniform control flow for the batch)."""
    s0 = initial_state(key)

    def step(carry, _):
        state, alive = carry
        logits = policy(params, state)
        action = jnp.argmax(logits)
        new, failed = cartpole_step(state, action)
        reward = alive.astype(jnp.float32)
        return (new, alive & ~failed), reward

    (_, _), rewards = lax.scan(step, (s0, jnp.bool_(True)),
                               None, length=max_steps)
    return rewards.sum()


def mlp_policy(sizes=(4, 16, 2)) -> Tuple[Callable, int]:
    """A plain tanh MLP policy over a *flat* genome vector. Returns
    ``(policy(params_vector, state) -> logits, n_params)`` — flat
    genomes keep every GA operator (crossover, gaussian mutation)
    applicable unchanged."""
    shapes = []
    n = 0
    for a, b in zip(sizes[:-1], sizes[1:]):
        shapes.append(((a, b), (b,)))
        n += a * b + b

    def policy(params: jnp.ndarray, state: jnp.ndarray) -> jnp.ndarray:
        h = state
        off = 0
        for (in_d, out_d), _ in shapes:
            W = params[off: off + in_d * out_d].reshape(in_d, out_d)
            off += in_d * out_d
            b = params[off: off + out_d]
            off += out_d
            h = jnp.tanh(h @ W + b)
        return h

    return policy, n
