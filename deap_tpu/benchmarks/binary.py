"""Binary/deceptive benchmark functions and the genotype-decode decorator.

Counterpart of /root/reference/deap/benchmarks/binary.py: ``bin2float``
(:20-41), trap/inv_trap (:44-59), chuang_f1/f2/f3, royal_road1/2. All
operate on a bit genome ``x: {0,1}[L]`` (bool or int) and vectorise the
reference's string-conversion loops into reshapes + dot products with
powers of two.
"""

from __future__ import annotations

from functools import wraps

import jax.numpy as jnp


def bin2float(min_, max_, nbits):
    """Decorator: decode a bit genome into ``L // nbits`` floats in
    [min_, max_] before calling the wrapped evaluation (binary.py:20-41).
    """
    def wrap(function):
        @wraps(function)
        def wrapped(individual, *args, **kwargs):
            bits = individual.astype(jnp.float32)
            nelem = bits.shape[0] // nbits
            chunks = bits[: nelem * nbits].reshape(nelem, nbits)
            weights = 2.0 ** jnp.arange(nbits - 1, -1, -1, dtype=jnp.float32)
            gene = chunks @ weights
            decoded = min_ + gene / (2.0 ** nbits - 1.0) * (max_ - min_)
            return function(decoded, *args, **kwargs)
        return wrapped
    return wrap


def _trap_window(u, k):
    """trap on a window with unitation u of size k (binary.py:44-51)."""
    return jnp.where(u == k, jnp.asarray(k, jnp.float32), k - 1.0 - u)


def _inv_trap_window(u, k):
    """inverse trap (binary.py:54-59)."""
    return jnp.where(u == 0, jnp.asarray(k, jnp.float32), u - 1.0)


def trap(x):
    u = jnp.sum(x.astype(jnp.float32))
    return _trap_window(u, x.shape[0])[None]


def inv_trap(x):
    u = jnp.sum(x.astype(jnp.float32))
    return _inv_trap_window(u, x.shape[0])[None]


def _windowed_unitation(x, width):
    n = (x.shape[0] // width) * width
    return jnp.sum(x[:n].astype(jnp.float32).reshape(-1, width), axis=1)


def chuang_f1(x):
    """Chuang & Hsu deceptive f1 (binary.py:65-77): 40+1 bits; last bit
    selects trap vs inv_trap over ten 4-bit windows."""
    u = _windowed_unitation(x[:-1], 4)
    t = jnp.sum(_trap_window(u, 4))
    i = jnp.sum(_inv_trap_window(u, 4))
    return jnp.where(x[-1] == 0, i, t)[None]


def chuang_f2(x):
    """Chuang & Hsu f2 (binary.py:80-99): 40+2 bits; last two bits select
    trap/inv_trap per 4-bit half of each 8-bit window."""
    body = x[:-2]
    u = _windowed_unitation(body, 4)          # [10] windows of 4
    first = u[0::2]
    second = u[1::2]
    b0, b1 = x[-2], x[-1]
    f_first = jnp.where(b0 == 0, jnp.sum(_inv_trap_window(first, 4)),
                        jnp.sum(_trap_window(first, 4)))
    f_second = jnp.where(b1 == 0, jnp.sum(_inv_trap_window(second, 4)),
                         jnp.sum(_trap_window(second, 4)))
    return (f_first + f_second)[None]


def chuang_f3(x):
    """Chuang & Hsu f3 (binary.py:102-117): like f1 but the 1-branch uses
    windows shifted by two with a wrapped trap on the seam."""
    u0 = _windowed_unitation(x[:-1], 4)
    branch0 = jnp.sum(_inv_trap_window(u0, 4))
    body = x[:-1]
    u1 = _windowed_unitation(body[2:], 4)
    seam = jnp.concatenate([x[-2:], x[:2]]).astype(jnp.float32)
    branch1 = (jnp.sum(_inv_trap_window(u1, 4))
               + _trap_window(jnp.sum(seam), 4))
    return jnp.where(x[-1] == 0, branch0, branch1)[None]


def royal_road1(x, order):
    """Mitchell's Royal Road R1 (binary.py:121-131): each complete block
    of ``order`` bits scores ``order`` iff all ones."""
    u = _windowed_unitation(x, order)
    return (order * jnp.sum(jnp.floor(u / order)))[None]


def royal_road2(x, order):
    """Royal Road R2 (binary.py:134-143): sum of R1 at doubling orders
    up to order²."""
    total = jnp.zeros(())
    norder = order
    while norder < order ** 2:
        total = total + royal_road1(x, norder)[0]
        norder *= 2
    return total[None]
