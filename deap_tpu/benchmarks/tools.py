"""Benchmark transform decorators and multi-objective quality metrics.

Counterpart of /root/reference/deap/benchmarks/tools.py: evaluation
transforms translate (:25), rotate (:64), noise (:117), scale (:171),
bound (:212) and metrics diversity (:256), convergence (:278),
hypervolume (:299), igd (:314).

The transforms are decorator *objects* carrying a mutable parameter with
an update method, exactly like the reference (so
``evaluate.translate(new_vector)`` works); they pre-transform the genome
before the wrapped evaluation, which therefore sees "a plain array" —
and everything stays jnp so the composition still jits. ``noise`` takes
an explicit PRNG key (the functional replacement for the reference's
global-``random`` noise draw): the decorated evaluate's signature
becomes ``(x, key)``.

Metrics operate on plain arrays of objective values (minimisation),
rather than lists of individuals.
"""

from __future__ import annotations

from functools import wraps

import jax
import jax.numpy as jnp
import numpy as np

from deap_tpu.native import hypervolume as _hv


class translate:
    """Translate the objective function by ``vector`` (tools.py:25-62)."""

    def __init__(self, vector):
        self.vector = jnp.asarray(vector)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            return func(individual - self.vector, *args, **kwargs)
        wrapper.translate = self.translate
        return wrapper

    def translate(self, vector):
        self.vector = jnp.asarray(vector)


class rotate:
    """Rotate the objective function by an orthogonal ``matrix``; the
    inverse rotation is applied to the genome (tools.py:64-115)."""

    def __init__(self, matrix):
        self.matrix = jnp.linalg.inv(jnp.asarray(matrix))

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            return func(self.matrix @ individual, *args, **kwargs)
        wrapper.rotate = self.rotate
        return wrapper

    def rotate(self, matrix):
        self.matrix = jnp.linalg.inv(jnp.asarray(matrix))


class scale:
    """Scale the objective function by ``factor`` per dimension; the
    inverse factor is applied to the genome (tools.py:171-210)."""

    def __init__(self, factor):
        self.factor = 1.0 / jnp.asarray(factor)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            return func(individual * self.factor, *args, **kwargs)
        wrapper.scale = self.scale
        return wrapper

    def scale(self, factor):
        self.factor = 1.0 / jnp.asarray(factor)


class noise:
    """Additive objective noise (tools.py:117-169). ``sigma`` may be a
    scalar or per-objective; the decorated evaluation takes an explicit
    key: ``evaluate(x, key)``."""

    def __init__(self, sigma):
        self.sigma = None if sigma is None else jnp.asarray(sigma)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, key, *args, **kwargs):
            values = func(individual, *args, **kwargs)
            if self.sigma is None:
                return values
            return values + self.sigma * jax.random.normal(
                key, jnp.shape(values))
        wrapper.noise = self.noise
        return wrapper

    def noise(self, sigma):
        self.sigma = None if sigma is None else jnp.asarray(sigma)


class bound:
    """Clip/wrap/mirror decorated *operator* outputs back into [low, up]
    (tools.py:212-254 — a stub in the reference; functional here)."""

    def __init__(self, bounds, type_="clip"):
        self.low, self.up = (jnp.asarray(b) for b in bounds)
        if type_ not in ("clip", "wrap", "mirror"):
            raise ValueError(type_)
        self.type = type_

    def _apply(self, x):
        low, up = self.low, self.up
        if self.type == "clip":
            return jnp.clip(x, low, up)
        span = up - low
        if self.type == "wrap":
            return low + jnp.mod(x - low, span)
        t = jnp.mod(x - low, 2 * span)
        return low + jnp.where(t > span, 2 * span - t, t)

    def __call__(self, func):
        @wraps(func)
        def wrapper(*args, **kwargs):
            out = func(*args, **kwargs)
            if isinstance(out, tuple):
                return tuple(self._apply(o) for o in out)
            return self._apply(out)
        return wrapper


# ------------------------------------------------------------- metrics ----

def diversity(first_front, first, last):
    """Deb's NSGA-II spread Δ (tools.py:256-276): ``first_front`` is
    [n, 2] objective values in front order; ``first``/``last`` the
    extreme points of the optimal front. Smaller is better."""
    ff = jnp.asarray(first_front)
    df = jnp.hypot(ff[0, 0] - first[0], ff[0, 1] - first[1])
    dl = jnp.hypot(ff[-1, 0] - last[0], ff[-1, 1] - last[1])
    if ff.shape[0] == 1:
        return float(df + dl)
    dt = jnp.hypot(ff[:-1, 0] - ff[1:, 0], ff[:-1, 1] - ff[1:, 1])
    dm = jnp.mean(dt)
    di = jnp.sum(jnp.abs(dt - dm))
    return float((df + dl + di) / (df + dl + dt.shape[0] * dm))


def convergence(first_front, optimal_front):
    """Mean distance from each front member to its nearest optimal point
    (tools.py:278-296). Smaller is better."""
    a = jnp.asarray(first_front)[:, None, :]
    z = jnp.asarray(optimal_front)[None, :, :]
    d = jnp.sqrt(jnp.sum((a - z) ** 2, axis=-1))
    return float(jnp.mean(jnp.min(d, axis=1)))


def hypervolume(front, ref=None, weights=None):
    """Hypervolume of a front (tools.py:299-311).

    ``front`` is a Population, or an array of raw objective values with
    ``weights`` (defaults to minimisation), or weighted values directly.
    Internally flipped to minimisation space like the reference's
    ``wvalues * -1``.
    """
    from deap_tpu.core.population import Population

    if isinstance(front, Population):
        w = front.fitness * front.spec.warray
        w = np.asarray(w)[np.asarray(front.valid)]
    else:
        front = np.asarray(front)
        if weights is None:
            weights = -np.ones(front.shape[-1])
        w = front * np.asarray(weights)
    wobj = -w
    if ref is None:
        ref = np.max(wobj, axis=0) + 1
    return _hv(wobj, np.asarray(ref))


def igd(A, Z):
    """Inverted generational distance (tools.py:314-320): mean over A? —
    the reference averages, per its scipy formulation, the minimum
    distance from each member of ``A`` to ``Z`` taken column-wise
    (``min(cdist(A, Z), axis=0)``): the average nearest-neighbour
    distance from each reference point in ``Z`` to the approximation
    ``A``."""
    a = jnp.asarray(A)[:, None, :]
    z = jnp.asarray(Z)[None, :, :]
    d = jnp.sqrt(jnp.sum((a - z) ** 2, axis=-1))  # [|A|, |Z|]
    return float(jnp.mean(jnp.min(d, axis=0)))
