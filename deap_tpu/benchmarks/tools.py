"""Benchmark transform decorators and multi-objective quality metrics.

Counterpart of /root/reference/deap/benchmarks/tools.py: evaluation
transforms translate (:25), rotate (:64), noise (:117), scale (:171),
bound (:212) and metrics diversity (:256), convergence (:278),
hypervolume (:299), igd (:314).

The transforms are decorator *objects* carrying a mutable parameter with
an update method, exactly like the reference (so
``evaluate.translate(new_vector)`` works); they pre-transform the genome
before the wrapped evaluation, which therefore sees "a plain array" —
and everything stays jnp so the composition still jits. ``noise`` takes
an explicit PRNG key (the functional replacement for the reference's
global-``random`` noise draw): the decorated evaluate's signature
becomes ``(x, key)``.

Metrics operate on plain arrays of objective values (minimisation),
rather than lists of individuals.
"""

from __future__ import annotations

from functools import wraps

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from deap_tpu.native import hypervolume as _hv


class translate:
    """Translate the objective function by ``vector`` (tools.py:25-62)."""

    def __init__(self, vector):
        self.vector = jnp.asarray(vector)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            return func(individual - self.vector, *args, **kwargs)
        wrapper.translate = self.translate
        return wrapper

    def translate(self, vector):
        self.vector = jnp.asarray(vector)


class rotate:
    """Rotate the objective function by an orthogonal ``matrix``; the
    inverse rotation is applied to the genome (tools.py:64-115)."""

    def __init__(self, matrix):
        self.matrix = jnp.linalg.inv(jnp.asarray(matrix))

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            return func(self.matrix @ individual, *args, **kwargs)
        wrapper.rotate = self.rotate
        return wrapper

    def rotate(self, matrix):
        self.matrix = jnp.linalg.inv(jnp.asarray(matrix))


class scale:
    """Scale the objective function by ``factor`` per dimension; the
    inverse factor is applied to the genome (tools.py:171-210)."""

    def __init__(self, factor):
        self.factor = 1.0 / jnp.asarray(factor)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, *args, **kwargs):
            return func(individual * self.factor, *args, **kwargs)
        wrapper.scale = self.scale
        return wrapper

    def scale(self, factor):
        self.factor = 1.0 / jnp.asarray(factor)


class noise:
    """Additive objective noise (tools.py:117-169). ``sigma`` may be a
    scalar or per-objective; the decorated evaluation takes an explicit
    key: ``evaluate(x, key)``."""

    def __init__(self, sigma):
        self.sigma = None if sigma is None else jnp.asarray(sigma)

    def __call__(self, func):
        @wraps(func)
        def wrapper(individual, key, *args, **kwargs):
            values = func(individual, *args, **kwargs)
            if self.sigma is None:
                return values
            return values + self.sigma * jax.random.normal(
                key, jnp.shape(values))
        wrapper.noise = self.noise
        return wrapper

    def noise(self, sigma):
        self.sigma = None if sigma is None else jnp.asarray(sigma)


class bound:
    """Clip/wrap/mirror decorated *operator* outputs back into [low, up]
    (tools.py:212-254 — a stub in the reference; functional here)."""

    def __init__(self, bounds, type_="clip"):
        self.low, self.up = (jnp.asarray(b) for b in bounds)
        if type_ not in ("clip", "wrap", "mirror"):
            raise ValueError(type_)
        self.type = type_

    def _apply(self, x):
        low, up = self.low, self.up
        if self.type == "clip":
            return jnp.clip(x, low, up)
        span = up - low
        if self.type == "wrap":
            return low + jnp.mod(x - low, span)
        t = jnp.mod(x - low, 2 * span)
        return low + jnp.where(t > span, 2 * span - t, t)

    def __call__(self, func):
        @wraps(func)
        def wrapper(*args, **kwargs):
            out = func(*args, **kwargs)
            if isinstance(out, tuple):
                return tuple(self._apply(o) for o in out)
            return self._apply(out)
        return wrapper


# ------------------------------------------------------------- metrics ----

def diversity(first_front, first, last):
    """Deb's NSGA-II spread Δ (tools.py:256-276): ``first_front`` is
    [n, 2] objective values in front order; ``first``/``last`` the
    extreme points of the optimal front. Smaller is better."""
    ff = jnp.asarray(first_front)
    df = jnp.hypot(ff[0, 0] - first[0], ff[0, 1] - first[1])
    dl = jnp.hypot(ff[-1, 0] - last[0], ff[-1, 1] - last[1])
    if ff.shape[0] == 1:
        return float(df + dl)
    dt = jnp.hypot(ff[:-1, 0] - ff[1:, 0], ff[:-1, 1] - ff[1:, 1])
    dm = jnp.mean(dt)
    di = jnp.sum(jnp.abs(dt - dm))
    return float((df + dl + di) / (df + dl + dt.shape[0] * dm))


def convergence(first_front, optimal_front):
    """Mean distance from each front member to its nearest optimal point
    (tools.py:278-296). Smaller is better."""
    a = jnp.asarray(first_front)[:, None, :]
    z = jnp.asarray(optimal_front)[None, :, :]
    d = jnp.sqrt(jnp.sum((a - z) ** 2, axis=-1))
    return float(jnp.mean(jnp.min(d, axis=1)))


def hypervolume(front, ref=None, weights=None):
    """Hypervolume of a front (tools.py:299-311).

    ``front`` is a Population, or an array of raw objective values with
    ``weights`` (defaults to minimisation), or weighted values directly.
    Internally flipped to minimisation space like the reference's
    ``wvalues * -1``.
    """
    from deap_tpu.core.population import Population

    if isinstance(front, Population):
        w = front.fitness * front.spec.warray
        w = np.asarray(w)[np.asarray(front.valid)]
    else:
        front = np.asarray(front)
        if weights is None:
            weights = -np.ones(front.shape[-1])
        w = front * np.asarray(weights)
    wobj = -w
    if ref is None:
        ref = np.max(wobj, axis=0) + 1
    return _hv(wobj, np.asarray(ref))


def optimal_front(name: str, n: int = 100, nobj: int = 3):
    """Analytic Pareto-optimal fronts for the ZDT/DTLZ families — the
    counterpart of the reference's sampled JSON fixtures
    (examples/ga/pareto_front/zdt*.json, dtlz*.json consumed by
    convergence/diversity, benchmarks/tools.py:256-296), generated
    exactly instead of shipped as data.

    Returns ``f32[n, 2]`` for ZDT (``f32[m, nobj]`` for DTLZ with
    ``m ≈ n`` lattice points). ZDT3's disconnected front is the
    non-dominated subset of the dense curve.
    """
    name = name.lower()
    if name in ("zdt1", "zdt4"):
        f1 = jnp.linspace(0.0, 1.0, n)
        return jnp.stack([f1, 1.0 - jnp.sqrt(f1)], axis=1)
    if name == "zdt2":
        f1 = jnp.linspace(0.0, 1.0, n)
        return jnp.stack([f1, 1.0 - f1 ** 2], axis=1)
    if name == "zdt3":
        # dense curve has strictly increasing f1 = x, so a point is
        # non-dominated iff its f2 beats every earlier f2: an O(N)
        # exclusive running-min, no pairwise matrix
        x = jnp.linspace(0.0, 1.0, 16 * n)
        f2 = 1.0 - jnp.sqrt(x) - x * jnp.sin(10.0 * jnp.pi * x)
        cummin_prev = jnp.concatenate(
            [jnp.array([jnp.inf]), lax.associative_scan(jnp.minimum, f2)[:-1]])
        keep = jnp.flatnonzero(f2 < cummin_prev)
        # subsample evenly so all five disconnected segments survive
        pick = jnp.linspace(0, keep.shape[0] - 1, n).astype(jnp.int32)
        idx = keep[pick]
        return jnp.stack([x[idx], f2[idx]], axis=1)
    if name == "zdt6":
        # f1 is non-monotone in x and hits 1.0 at every sin zero; the
        # front is f2 = 1 - f1² over the attained f1 range, so sample
        # the attained f1 values, sorted and deduplicated
        x = jnp.linspace(0.0, 1.0, 16 * n)
        f1 = 1.0 - jnp.exp(-4.0 * x) * jnp.sin(6.0 * jnp.pi * x) ** 6
        u = jnp.unique(f1)
        pick = jnp.linspace(0, u.shape[0] - 1, n).astype(jnp.int32)
        f1s = u[pick]
        return jnp.stack([f1s, 1.0 - f1s ** 2], axis=1)
    if name == "dtlz1":
        # simplex Σf_i = 0.5: Das-Dennis lattice scaled by 0.5
        from deap_tpu.mo.emo import uniform_reference_points

        return 0.5 * uniform_reference_points(nobj, _dd_partitions(n, nobj))
    if name in ("dtlz2", "dtlz3", "dtlz4"):
        # unit hypersphere ‖f‖₂ = 1, first orthant
        from deap_tpu.mo.emo import uniform_reference_points

        w = uniform_reference_points(nobj, _dd_partitions(n, nobj))
        return w / jnp.linalg.norm(w, axis=1, keepdims=True)
    raise ValueError(f"no analytic front for {name!r}")


def _dd_partitions(n: int, nobj: int) -> int:
    """Smallest Das-Dennis partition count whose lattice reaches ≥ n
    points (the lattice has C(p+nobj-1, nobj-1) points, not
    p^(nobj-1))."""
    from math import comb

    p = 1
    while comb(p + nobj - 1, nobj - 1) < n:
        p += 1
    return p


def igd(A, Z):
    """Inverted generational distance (tools.py:314-320): mean over A? —
    the reference averages, per its scipy formulation, the minimum
    distance from each member of ``A`` to ``Z`` taken column-wise
    (``min(cdist(A, Z), axis=0)``): the average nearest-neighbour
    distance from each reference point in ``Z`` to the approximation
    ``A``."""
    a = jnp.asarray(A)[:, None, :]
    z = jnp.asarray(Z)[None, :, :]
    d = jnp.sqrt(jnp.sum((a - z) ** 2, axis=-1))  # [|A|, |Z|]
    return float(jnp.mean(jnp.min(d, axis=0)))
