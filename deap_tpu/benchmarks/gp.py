"""Symbolic-regression target functions.

Counterpart of /root/reference/deap/benchmarks/gp.py (:18-130). Each
takes ``data: f32[n_dims]`` (a single input point) and returns a scalar;
vmap over sample points. These are the ground-truth functions a GP run
tries to rediscover.
"""

from __future__ import annotations

import jax.numpy as jnp


def kotanchek(data):
    """exp(-(x0-1)²) / (3.2 + (x1-2.5)²), x ∈ [-1, 7]² (gp.py:18)."""
    return jnp.exp(-((data[0] - 1.0) ** 2)) / (3.2 + (data[1] - 2.5) ** 2)


def salustowicz_1d(data):
    """e^-x x³ cos x sin x (cos x sin²x - 1), x ∈ [0, 10] (gp.py:32)."""
    x = data[0]
    return (jnp.exp(-x) * x ** 3 * jnp.cos(x) * jnp.sin(x)
            * (jnp.cos(x) * jnp.sin(x) ** 2 - 1.0))


def salustowicz_2d(data):
    """salustowicz_1d(x0) · (x1 - 5), x ∈ [0, 7]² (gp.py:46)."""
    return salustowicz_1d(data) * (data[1] - 5.0)


def unwrapped_ball(data):
    """10 / (5 + Σ (x_i - 3)²), x ∈ [-2, 8]ⁿ (gp.py:60)."""
    return 10.0 / (5.0 + jnp.sum((data - 3.0) ** 2))


def rational_polynomial(data):
    """30 (x0-1)(x2-1) / (x1² (x0-10)) (gp.py:74)."""
    return (30.0 * (data[0] - 1.0) * (data[2] - 1.0)
            / (data[1] ** 2 * (data[0] - 10.0)))


def sin_cos(data):
    """6 sin(x0) cos(x1), x ∈ [0, 6]² (gp.py:88)."""
    return 6.0 * jnp.sin(data[0]) * jnp.cos(data[1])


def ripple(data):
    """(x0-3)(x1-3) + 2 sin((x0-4)(x1-4)), x ∈ [-5, 5]² (gp.py:102)."""
    return ((data[0] - 3.0) * (data[1] - 3.0)
            + 2.0 * jnp.sin((data[0] - 4.0) * (data[1] - 4.0)))


def rational_polynomial2(data):
    """((x0-3)⁴ + (x1-3)³ - (x1-3)) / ((x1-2)⁴ + 10) (gp.py:116)."""
    return (((data[0] - 3.0) ** 4 + (data[1] - 3.0) ** 3 - (data[1] - 3.0))
            / ((data[1] - 2.0) ** 4 + 10.0))
