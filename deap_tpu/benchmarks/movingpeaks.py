"""Moving Peaks — a dynamic fitness landscape, device-resident.

Counterpart of /root/reference/deap/benchmarks/movingpeaks.py: peaks of
changing position/height/width (peak functions cone/sphere/function1,
:33-59), evaluation-count-triggered landscape changes (:209-252,
``changePeaks`` :252-332), offline/current error tracking (:246-249) and
the SCENARIO_1/2/3 parameter sets (:334+).

Functional redesign: the landscape is a :class:`MovingPeaksState` pytree
(peak arrays + PRNG key + error accumulators) and every operation is a
pure function usable inside jit/scan:

- :func:`mp_init` → state
- :func:`mp_evaluate` — batched evaluation of a whole population;
  bumps ``nevals``, updates the running current/offline error exactly
  like the reference's per-call bookkeeping (cumulative-min over the
  batch), and triggers :func:`change_peaks` through ``lax.cond`` when
  the evaluation counter crosses a period boundary. By default the
  change lands at batch granularity; ``exact=True`` reproduces the
  reference's per-individual mid-batch trigger exactly (r5), paying a
  per-individual scan only on batches that actually cross a boundary.

Divergence kept deliberately: the reference can fluctuate the *number*
of peaks ([min, init, max] npeaks, :126-129); here the peak count is
static per jit program — fluctuation would need a capacity mask, noted
for the host-level wrapper.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax


def cone(x, position, height, width):
    """h - w·‖x - p‖ (movingpeaks.py:33-42). Batched over peaks."""
    d = jnp.sqrt(jnp.sum((x[None, :] - position) ** 2, axis=-1))
    return height - width * d


def sphere_peak(x, position, height, width):
    """h·‖x - p‖² (movingpeaks.py:44-48)."""
    del width
    return height * jnp.sum((x[None, :] - position) ** 2, axis=-1)


def function1(x, position, height, width):
    """h / (1 + w·‖x - p‖²) (movingpeaks.py:50-59)."""
    d2 = jnp.sum((x[None, :] - position) ** 2, axis=-1)
    return height / (1.0 + width * d2)


@struct.dataclass
class MovingPeaksState:
    position: jnp.ndarray       # [npeaks, dim]
    height: jnp.ndarray         # [npeaks]
    width: jnp.ndarray          # [npeaks]
    last_change: jnp.ndarray    # [npeaks, dim]
    key: jax.Array
    nevals: jnp.ndarray         # int32 scalar
    current_error: jnp.ndarray  # f32 scalar
    offline_error_sum: jnp.ndarray  # f32 scalar


@dataclasses.dataclass(frozen=True)
class MovingPeaksConfig:
    """Static configuration (the SCENARIO dict equivalent)."""
    dim: int
    npeaks: int = 5
    pfunc: Callable = function1
    bfunc: Optional[Callable] = None
    min_coord: float = 0.0
    max_coord: float = 100.0
    min_height: float = 30.0
    max_height: float = 70.0
    uniform_height: float = 50.0
    min_width: float = 0.0001
    max_width: float = 0.2
    uniform_width: float = 0.1
    lambda_: float = 0.0
    move_severity: float = 1.0
    height_severity: float = 7.0
    width_severity: float = 0.01
    period: int = 5000


SCENARIO_1 = dict(npeaks=5, pfunc=function1, bfunc=None, min_coord=0.0,
                  max_coord=100.0, min_height=30.0, max_height=70.0,
                  uniform_height=50.0, min_width=0.0001, max_width=0.2,
                  uniform_width=0.1, lambda_=0.0, move_severity=1.0,
                  height_severity=7.0, width_severity=0.01, period=5000)
SCENARIO_2 = dict(npeaks=10, pfunc=cone, bfunc=None, min_coord=0.0,
                  max_coord=100.0, min_height=30.0, max_height=70.0,
                  uniform_height=50.0, min_width=1.0, max_width=12.0,
                  uniform_width=0.0, lambda_=0.5, move_severity=1.5,
                  height_severity=7.0, width_severity=1.0, period=5000)
SCENARIO_3 = dict(npeaks=50, pfunc=cone, bfunc=lambda x: jnp.asarray(10.0),
                  min_coord=0.0, max_coord=100.0, min_height=30.0,
                  max_height=70.0, uniform_height=0.0, min_width=1.0,
                  max_width=12.0, uniform_width=0.0, lambda_=0.5,
                  move_severity=1.0, height_severity=1.0,
                  width_severity=0.5, period=1000)


def mp_init(key: jax.Array, cfg: MovingPeaksConfig) -> MovingPeaksState:
    kp, kh, kw, kc, knext = jax.random.split(key, 5)
    position = jax.random.uniform(
        kp, (cfg.npeaks, cfg.dim), minval=cfg.min_coord, maxval=cfg.max_coord)
    if cfg.uniform_height > 0:
        height = jnp.full((cfg.npeaks,), cfg.uniform_height)
    else:
        height = jax.random.uniform(
            kh, (cfg.npeaks,), minval=cfg.min_height, maxval=cfg.max_height)
    if cfg.uniform_width > 0:
        width = jnp.full((cfg.npeaks,), cfg.uniform_width)
    else:
        width = jax.random.uniform(
            kw, (cfg.npeaks,), minval=cfg.min_width, maxval=cfg.max_width)
    last_change = jax.random.uniform(kc, (cfg.npeaks, cfg.dim)) - 0.5
    return MovingPeaksState(
        position=position, height=height, width=width,
        last_change=last_change, key=knext,
        nevals=jnp.zeros((), jnp.int32),
        current_error=jnp.asarray(jnp.inf),
        offline_error_sum=jnp.zeros(()))


def _landscape(cfg: MovingPeaksConfig, state: MovingPeaksState, x):
    vals = cfg.pfunc(x, state.position, state.height, state.width)
    best = jnp.max(vals)
    if cfg.bfunc is not None:
        best = jnp.maximum(best, cfg.bfunc(x))
    return best


def maximums(cfg: MovingPeaksConfig, state: MovingPeaksState):
    """Per-peak ``(value, position)`` of the landscape at each peak
    centre (movingpeaks.py:185-193's `maximums` property) — values
    include basin/other-peak interference, hence landscape-evaluated
    rather than read off ``state.height``."""
    vals = jax.vmap(lambda p: _landscape(cfg, state, p))(state.position)
    return vals, state.position


def global_maximum(cfg: MovingPeaksConfig, state: MovingPeaksState):
    """Current optimum value: the best landscape value over all peak
    centres (movingpeaks.py:182-193)."""
    return jnp.max(maximums(cfg, state)[0])


def _bounce(new, old, delta, lo, hi):
    below = new < lo
    above = new > hi
    bounced = jnp.where(below, 2.0 * lo - old - delta,
                        jnp.where(above, 2.0 * hi - old - delta, new))
    flipped = jnp.where(below | above, -delta, delta)
    return bounced, flipped


def change_peaks(cfg: MovingPeaksConfig, state: MovingPeaksState
                 ) -> MovingPeaksState:
    """One landscape change (movingpeaks.py:252-332): correlated random
    walk of positions (severity-normalised, lambda-blended with the last
    move, bounced at the coordinate bounds) and Gaussian height/width
    perturbations bounced at their bounds."""
    key, ks, kh, kw = jax.random.split(state.key, 4)
    shift = jax.random.uniform(ks, state.position.shape) - 0.5
    norm = jnp.sqrt(jnp.sum(shift ** 2, axis=1, keepdims=True))
    shift = jnp.where(norm > 0, cfg.move_severity * shift / norm, 0.0)
    shift = (1.0 - cfg.lambda_) * shift + cfg.lambda_ * state.last_change
    norm = jnp.sqrt(jnp.sum(shift ** 2, axis=1, keepdims=True))
    shift = jnp.where(norm > 0, cfg.move_severity * shift / norm, 0.0)

    new_pos, final_shift = _bounce(
        state.position + shift, state.position, shift,
        cfg.min_coord, cfg.max_coord)

    dh = jax.random.normal(kh, state.height.shape) * cfg.height_severity
    new_h, _ = _bounce(state.height + dh, state.height, dh,
                       cfg.min_height, cfg.max_height)
    dw = jax.random.normal(kw, state.width.shape) * cfg.width_severity
    new_w, _ = _bounce(state.width + dw, state.width, dw,
                       cfg.min_width, cfg.max_width)

    return state.replace(position=new_pos, height=new_h, width=new_w,
                         last_change=final_shift, key=key)


def mp_evaluate(cfg: MovingPeaksConfig, state: MovingPeaksState,
                genomes: jnp.ndarray, exact: bool = False):
    """Evaluate a population ``[n, dim]`` → (new_state, values [n, 1]).

    Error bookkeeping matches the reference's sequential semantics
    (movingpeaks.py:225-244): running min of |f - optimum| threaded
    through the batch, summed into the offline error. By default the
    peak change fires once per batch if ``nevals`` crosses a period
    boundary — the batched analog of the reference's per-individual
    trigger.

    ``exact=True`` reproduces the reference's EXACT mid-batch
    semantics (movingpeaks.py:231-241: evaluate, count, then change
    when ``nevals % period == 0``): individuals before the boundary
    see the old landscape, individuals after see the new one, with as
    many changes per batch as boundaries crossed. Implemented as a
    ``lax.cond`` that keeps the fully-batched path when no boundary
    falls inside the batch (the common case — identical bookkeeping,
    full speed) and switches to a per-individual ``lax.scan`` only for
    crossing batches, so exactness costs nothing between changes.
    """
    if exact:
        return _mp_evaluate_exact(cfg, state, genomes)
    n = genomes.shape[0]
    values = jax.vmap(lambda x: _landscape(cfg, state, x))(genomes)

    optimum = global_maximum(cfg, state)
    errs = jnp.abs(values - optimum)
    run_min = lax.associative_scan(jnp.minimum, jnp.concatenate(
        [state.current_error[None], errs]))
    new_state = state.replace(
        nevals=state.nevals + n,
        current_error=run_min[-1],
        offline_error_sum=state.offline_error_sum + jnp.sum(run_min[1:]))

    if cfg.period > 0:
        crossed = (new_state.nevals // cfg.period) > (state.nevals // cfg.period)
        # A landscape change restarts the running error minimum, like the
        # reference's `self._optimum = None` at the end of changePeaks
        # (movingpeaks.py:332) which re-initialises _error on the next call.
        new_state = lax.cond(
            crossed,
            lambda s: change_peaks(cfg, s).replace(
                current_error=jnp.asarray(jnp.inf)),
            lambda s: s, new_state)
    return new_state, values[:, None]


def _mp_evaluate_exact(cfg: MovingPeaksConfig, state: MovingPeaksState,
                       genomes: jnp.ndarray):
    """Per-evaluation-exact form of :func:`mp_evaluate` (see its
    docstring). The scan step is the reference's ``__call__`` body
    verbatim in order: landscape value on the current state, count,
    running-error update against the current optimum, then
    ``change_peaks`` when the counter hits a period multiple
    (movingpeaks.py:231-241). The optimum is recomputed per step
    rather than cached-until-None like the reference — identical
    values, since the landscape only changes when the cache would be
    invalidated anyway."""
    n = genomes.shape[0]

    def scan_path(state):
        def step(st, x):
            val = _landscape(cfg, st, x)
            optimum = global_maximum(cfg, st)
            cur = jnp.minimum(st.current_error, jnp.abs(val - optimum))
            st = st.replace(
                nevals=st.nevals + 1, current_error=cur,
                offline_error_sum=st.offline_error_sum + cur)
            if cfg.period > 0:
                st = lax.cond(
                    st.nevals % cfg.period == 0,
                    lambda s: change_peaks(cfg, s).replace(
                        current_error=jnp.asarray(jnp.inf)),
                    lambda s: s, st)
            return st, val

        return lax.scan(step, state, genomes)

    def batched_path(state):
        # no boundary inside this batch: the batched bookkeeping is
        # bit-identical to the sequential one and no change can fire
        values = jax.vmap(lambda x: _landscape(cfg, state, x))(genomes)
        optimum = global_maximum(cfg, state)
        errs = jnp.abs(values - optimum)
        run_min = lax.associative_scan(jnp.minimum, jnp.concatenate(
            [state.current_error[None], errs]))
        return state.replace(
            nevals=state.nevals + n,
            current_error=run_min[-1],
            offline_error_sum=state.offline_error_sum
            + jnp.sum(run_min[1:])), values

    if cfg.period <= 0:
        new_state, values = batched_path(state)
        return new_state, values[:, None]
    crossing = (state.nevals + n) // cfg.period > state.nevals // cfg.period
    new_state, values = lax.cond(crossing, scan_path, batched_path, state)
    return new_state, values[:, None]


def offline_error(state: MovingPeaksState):
    """Mean running error over all evaluations (movingpeaks.py:246-247)."""
    return state.offline_error_sum / jnp.maximum(state.nevals, 1)


def current_error(state: MovingPeaksState):
    return state.current_error
