"""Benchmark objective functions — jnp ports of the reference suite.

Counterpart of /root/reference/deap/benchmarks/__init__.py (single-
objective :26-362, multi-objective :364-688). Convention: every function
takes one genome ``x: f32[n_dims]`` and returns ``f32[nobj]`` — batch
over a population with ``jax.vmap(fn)`` (or register directly:
``toolbox.register("evaluate", jax.vmap(benchmarks.rastrigin))``).
All are pure jnp and fuse into the generation step under jit.

Weights conventions match the reference docs (minimisation for most,
h1/shekel maximisation; kursawe/zdt*/dtlz* multi-objective
minimisation).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from deap_tpu.benchmarks import binary, gp, movingpeaks, tools  # noqa: F401

# ------------------------------------------------------------ unimodal ----

def rand(key, individual):
    """Random "fitness" (benchmarks/__init__.py:26-42). Unlike the rest,
    needs an explicit PRNG key."""
    del individual
    return jax.random.uniform(key, (1,))


def plane(x):
    """f = x_0 (minimisation, :44-60)."""
    return x[:1]


def sphere(x):
    """f = Σ x_i² (:62-78)."""
    return jnp.sum(x * x, keepdims=True)


def cigar(x):
    """f = x_0² + 1e6 Σ_{i>0} x_i² (:80-96)."""
    return (x[0] ** 2 + 1e6 * jnp.sum(x[1:] ** 2))[None]


def rosenbrock(x):
    """f = Σ 100(x_i² - x_{i+1})² + (1 - x_i)² (:98-117; note the
    reference's (x²-y)² form)."""
    a, b = x[:-1], x[1:]
    return jnp.sum(100.0 * (a * a - b) ** 2 + (1.0 - a) ** 2, keepdims=True)


def h1(x):
    """2-D multimodal maximisation, optimum 2 at (8.6998, 6.7665)
    (:120-146)."""
    num = jnp.sin(x[0] - x[1] / 8.0) ** 2 + jnp.sin(x[1] + x[0] / 8.0) ** 2
    den = jnp.sqrt((x[0] - 8.6998) ** 2 + (x[1] - 6.7665) ** 2) + 1.0
    return (num / den)[None]


# ----------------------------------------------------------- multimodal ----

def ackley(x):
    """Ackley (:150-171), optimum 0 at origin."""
    n = x.shape[0]
    return (20.0 - 20.0 * jnp.exp(-0.2 * jnp.sqrt(jnp.mean(x * x)))
            + math.e - jnp.exp(jnp.mean(jnp.cos(2.0 * jnp.pi * x))))[None]


def bohachevsky(x):
    """Bohachevsky (:174-194)."""
    a, b = x[:-1], x[1:]
    return jnp.sum(a ** 2 + 2.0 * b ** 2
                   - 0.3 * jnp.cos(3.0 * jnp.pi * a)
                   - 0.4 * jnp.cos(4.0 * jnp.pi * b) + 0.7, keepdims=True)


def griewank(x):
    """Griewank (:197-217)."""
    i = jnp.arange(1, x.shape[0] + 1, dtype=x.dtype)
    return (jnp.sum(x * x) / 4000.0
            - jnp.prod(jnp.cos(x / jnp.sqrt(i))) + 1.0)[None]


def rastrigin(x):
    """Rastrigin (:220-239), optimum 0 at origin."""
    return (10.0 * x.shape[0]
            + jnp.sum(x * x - 10.0 * jnp.cos(2.0 * jnp.pi * x)))[None]


def rastrigin_scaled(x):
    """Scaled Rastrigin (:242-251)."""
    n = x.shape[0]
    i = jnp.arange(n, dtype=x.dtype)
    s = 10.0 ** (i / (n - 1))
    return (10.0 * n + jnp.sum((s * x) ** 2
                               - 10.0 * jnp.cos(2.0 * jnp.pi * s * x)))[None]


def rastrigin_skew(x):
    """Skewed Rastrigin (:253-265)."""
    y = jnp.where(x > 0, 10.0 * x, x)
    return (10.0 * x.shape[0]
            + jnp.sum(y * y - 10.0 * jnp.cos(2.0 * jnp.pi * y)))[None]


def schaffer(x):
    """Schaffer (:267-288)."""
    a, b = x[:-1], x[1:]
    s = a * a + b * b
    return jnp.sum(s ** 0.25 * (jnp.sin(50.0 * s ** 0.1) ** 2 + 1.0),
                   keepdims=True)


def schwefel(x):
    """Schwefel (:291-313), optimum 0 at 420.96874636..."""
    return (418.9828872724339 * x.shape[0]
            - jnp.sum(x * jnp.sin(jnp.sqrt(jnp.abs(x)))))[None]


def himmelblau(x):
    """Himmelblau (:315-338), four optima at value 0."""
    return ((x[0] ** 2 + x[1] - 11.0) ** 2
            + (x[0] + x[1] ** 2 - 7.0) ** 2)[None]


def shekel(x, a, c):
    """Shekel foxholes maximisation (:341-361). ``a``: [M, N] maxima
    locations, ``c``: [M] widths."""
    a = jnp.asarray(a, x.dtype)
    c = jnp.asarray(c, x.dtype)
    d = jnp.sum((x[None, :] - a) ** 2, axis=1)
    return jnp.sum(1.0 / (c + d), keepdims=True)


# -------------------------------------------------------- multi-objective ----

def kursawe(x):
    """Kursawe 2-obj (:364-376)."""
    a, b = x[:-1], x[1:]
    f1 = jnp.sum(-10.0 * jnp.exp(-0.2 * jnp.sqrt(a * a + b * b)))
    f2 = jnp.sum(jnp.abs(x) ** 0.8 + 5.0 * jnp.sin(x ** 3))
    return jnp.stack([f1, f2])


def schaffer_mo(x):
    """Schaffer's 2-obj on one attribute (:379-389)."""
    return jnp.stack([x[0] ** 2, (x[0] - 2.0) ** 2])


def _zdt_g(x):
    return 1.0 + 9.0 * jnp.sum(x[1:]) / (x.shape[0] - 1)


def zdt1(x):
    """ZDT1 (:391-403)."""
    g = _zdt_g(x)
    f1 = x[0]
    return jnp.stack([f1, g * (1.0 - jnp.sqrt(f1 / g))])


def zdt2(x):
    """ZDT2 (:405-419)."""
    g = _zdt_g(x)
    f1 = x[0]
    return jnp.stack([f1, g * (1.0 - (f1 / g) ** 2)])


def zdt3(x):
    """ZDT3 (:421-435)."""
    g = _zdt_g(x)
    f1 = x[0]
    return jnp.stack([
        f1,
        g * (1.0 - jnp.sqrt(f1 / g) - f1 / g * jnp.sin(10.0 * jnp.pi * f1))])


def zdt4(x):
    """ZDT4 (:437-450)."""
    g = (1.0 + 10.0 * (x.shape[0] - 1)
         + jnp.sum(x[1:] ** 2 - 10.0 * jnp.cos(4.0 * jnp.pi * x[1:])))
    f1 = x[0]
    return jnp.stack([f1, g * (1.0 - jnp.sqrt(f1 / g))])


def zdt6(x):
    """ZDT6 (:452-465)."""
    g = 1.0 + 9.0 * (jnp.sum(x[1:]) / (x.shape[0] - 1)) ** 0.25
    f1 = 1.0 - jnp.exp(-4.0 * x[0]) * jnp.sin(6.0 * jnp.pi * x[0]) ** 6
    return jnp.stack([f1, g * (1.0 - (f1 / g) ** 2)])


def dtlz1(x, obj):
    """DTLZ1 (:467-493); returns ``obj`` objectives."""
    xm = x[obj - 1:]
    g = 100.0 * (xm.shape[0] + jnp.sum(
        (xm - 0.5) ** 2 - jnp.cos(20.0 * jnp.pi * (xm - 0.5))))
    xc = x[: obj - 1]
    # f_0 = 0.5 Π xc (1+g); f_k = 0.5 Π xc[:m] (1 - xc[m]) (1+g)
    cum = jnp.concatenate([jnp.ones(1, x.dtype), jnp.cumprod(xc)])  # [obj]
    fs = [0.5 * cum[obj - 1] * (1.0 + g)]
    for m in range(obj - 2, -1, -1):
        fs.append(0.5 * cum[m] * (1.0 - xc[m]) * (1.0 + g))
    return jnp.stack(fs)


def _dtlz_spherical(x, obj, g, transform=lambda t: t):
    xc = transform(x[: obj - 1])
    cosc = jnp.cos(0.5 * jnp.pi * xc)
    cum = jnp.concatenate([jnp.ones(1, x.dtype), jnp.cumprod(cosc)])  # [obj]
    fs = [(1.0 + g) * cum[obj - 1]]
    for m in range(obj - 2, -1, -1):
        fs.append((1.0 + g) * cum[m] * jnp.sin(0.5 * jnp.pi * xc[m]))
    return jnp.stack(fs)


def dtlz2(x, obj):
    """DTLZ2 (:495-521)."""
    g = jnp.sum((x[obj - 1:] - 0.5) ** 2)
    return _dtlz_spherical(x, obj, g)


def dtlz3(x, obj):
    """DTLZ3 (:523-548): DTLZ2 geometry with the Rastrigin-like g."""
    xm = x[obj - 1:]
    g = 100.0 * (xm.shape[0] + jnp.sum(
        (xm - 0.5) ** 2 - jnp.cos(20.0 * jnp.pi * (xm - 0.5))))
    return _dtlz_spherical(x, obj, g)


def dtlz4(x, obj, alpha):
    """DTLZ4 (:550-577): DTLZ2 with meta-variable mapping x→x^alpha."""
    g = jnp.sum((x[obj - 1:] - 0.5) ** 2)
    return _dtlz_spherical(x, obj, g, transform=lambda t: t ** alpha)


def _dtlz_theta(x, n_objs, g):
    """Shared DTLZ5/6 geometry (:579-617): first angle is x_0 directly,
    the rest pass through theta(.)"""
    theta = jnp.pi / (4.0 * (1.0 + g)) * (1.0 + 2.0 * g * x)
    c0 = jnp.cos(0.5 * jnp.pi * x[0])
    s0 = jnp.sin(0.5 * jnp.pi * x[0])
    cos_t = jnp.cos(theta)
    # cumulative products of cos(theta(x_1..x_k))
    cum = jnp.concatenate(
        [jnp.ones(1, x.dtype), jnp.cumprod(cos_t[1:])])
    fs = [(1.0 + g) * c0 * cum[x.shape[0] - 1]]
    for m in range(n_objs - 1, 0, -1):
        if m == 1:
            fs.append((1.0 + g) * s0)
        else:
            fs.append((1.0 + g) * c0 * cum[m - 2]
                      * jnp.sin(theta[m - 1]))
    return jnp.stack(fs)


def dtlz5(x, n_objs):
    """DTLZ5 (:579-597)."""
    g = jnp.sum((x[n_objs - 1:] - 0.5) ** 2)
    return _dtlz_theta(x, n_objs, g)


def dtlz6(x, n_objs):
    """DTLZ6 (:599-617): DTLZ5 with g = Σ x_i^0.1."""
    g = jnp.sum(x[n_objs - 1:] ** 0.1)
    return _dtlz_theta(x, n_objs, g)


def dtlz7(x, n_objs):
    """DTLZ7 (:619-628)."""
    tail = x[n_objs - 1:]
    g = 1.0 + 9.0 / tail.shape[0] * jnp.sum(tail)
    head = x[: n_objs - 1]
    last = (1.0 + g) * (n_objs - jnp.sum(
        head / (1.0 + g) * (1.0 + jnp.sin(3.0 * jnp.pi * head))))
    return jnp.concatenate([head, last[None]])


def fonseca(x):
    """Fonseca-Fleming 2-obj (:630-643), 3 attributes."""
    inv_sqrt = 1.0 / jnp.sqrt(3.0)
    f1 = 1.0 - jnp.exp(-jnp.sum((x[:3] - inv_sqrt) ** 2))
    f2 = 1.0 - jnp.exp(-jnp.sum((x[:3] + inv_sqrt) ** 2))
    return jnp.stack([f1, f2])


def poloni(x):
    """Poloni 2-obj maximisation (:645-668)."""
    a1 = (0.5 * jnp.sin(1.0) - 2.0 * jnp.cos(1.0)
          + jnp.sin(2.0) - 1.5 * jnp.cos(2.0))
    a2 = (1.5 * jnp.sin(1.0) - jnp.cos(1.0)
          + 2.0 * jnp.sin(2.0) - 0.5 * jnp.cos(2.0))
    b1 = (0.5 * jnp.sin(x[0]) - 2.0 * jnp.cos(x[0])
          + jnp.sin(x[1]) - 1.5 * jnp.cos(x[1]))
    b2 = (1.5 * jnp.sin(x[0]) - jnp.cos(x[0])
          + 2.0 * jnp.sin(x[1]) - 0.5 * jnp.cos(x[1]))
    return jnp.stack([1.0 + (a1 - b1) ** 2 + (a2 - b2) ** 2,
                      (x[0] + 3.0) ** 2 + (x[1] + 1.0) ** 2])


def dent(x, lambda_: float = 0.85):
    """Dent 2-obj (:670-687)."""
    d = lambda_ * jnp.exp(-((x[0] - x[1]) ** 2))
    s = jnp.sqrt(1.0 + (x[0] + x[1]) ** 2) + jnp.sqrt(1.0 + (x[0] - x[1]) ** 2)
    f1 = 0.5 * (s + x[0] - x[1]) + d
    f2 = 0.5 * (s - x[0] + x[1]) + d
    return jnp.stack([f1, f2])
