"""Multi-objective selection — NSGA-II, NSGA-III, SPEA2, nd-sort, crowding.

Counterpart of /root/reference/deap/tools/emo.py: selNSGA2 (:15-50),
sortNondominated O(MN²) (:53-117), assignCrowdingDist (:119-143),
selTournamentDCD (:145-195), sortLogNondominated (:234-441), NSGA-III
(:450-689), selSPEA2 (:692-842).

TPU-first formulations:

- Non-dominated sorting is one contract over five engines: the fused
  dominance matrix + ``while_loop`` front peeling (the O(MN²) work the
  reference does in Python loops is exactly what the VPU eats for
  breakfast), its tiled streaming twin, and the sort-based
  peeling-free engines — bi-objective staircase, 3-objective Fenwick
  sweep, any-M prefix chain reduction (mo/ndsort.py) — that drop the
  front-count multiplier entirely. ``impl='auto'`` picks by
  (n, M, backend); the measured selection matrix lives in
  docs/advanced/ndsort.md. The reference's 'log' divide-and-conquer
  variant exists to cut *Python* constant factors; its actual
  asymptotic content is what 'sweep'/'dc' deliver inside XLA.
- Crowding distances are computed for all fronts at once with a
  (rank, value) lexsort and segment min/max — no per-front Python.
- NSGA-III niching and SPEA2 truncation are data-dependent loops; they
  run as masked ``fori_loop``/``while_loop`` with static shapes so the
  whole selection stays inside one compiled step.

All selectors take weighted values ``w: f32[n, nobj]`` (maximisation
convention, see core.fitness) and return ``int32[k]`` indices.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deap_tpu import tuning
from deap_tpu.core.fitness import dominates
from deap_tpu.mo.ndsort import nd_rank_prefix, nd_rank_sweep3


# ---------------------------------------------------------------- nd-sort ----

def dominance_matrix(w: jnp.ndarray) -> jnp.ndarray:
    """dom[i, j] = True iff individual j dominates individual i."""
    return dominates(w[None, :, :], w[:, None, :])


#: population size above which nd_rank switches to the tiled Pallas
#: kernel (the resident [n, n] matrix would exceed ~64 MB of HBM and the
#: streaming kernel wins on bandwidth).
ND_TILED_THRESHOLD = 8192

#: CPU crossover (measured, docs/advanced/ndsort.md) above which the
#: M ≥ 3 prefix-streamed chain reduction (``impl='dc'``) beats matrix
#: peeling — the front count already costs the matrix path ~16 peels
#: there and keeps growing with n and M.
ND_PREFIX_THRESHOLD = 512

#: CPU crossover above which the M = 3 Fenwick sweep's linearithmic
#: scan overtakes the O(n²) prefix reduction (measured crossover
#: n ≈ 12-16k; both beat matrix peeling by orders of magnitude there).
ND_SWEEP_THRESHOLD = 16384

#: the impls with exact full ranks and no peel loop — cover_k is moot
#: for them and ``fallback='count'`` degrades gracefully to the exact
#: ranks themselves (strictly better than dominance counts).
_ND_EXACT_IMPLS = ("staircase", "sweep", "dc")


def _nd_static_auto(n: int, nobj: int, backend: str) -> str:
    """The static 'auto' heuristic — CPU-measured thresholds
    (docs/advanced/ndsort.md), each overridable via
    ``DEAP_TPU_TUNE_ND_{PREFIX,SWEEP,TILED}_THRESHOLD``.

    Bi-objective: the O(n log n) staircase beats any O(fronts·n²)
    peeling at scale — and it is the path that fits n ≫ 50k on a CPU
    host (the [n, n] matrix would be gigabytes; the tiled kernel needs
    a real TPU core). On a CPU host it wins from tiny n (measured 2×
    at n=64, 300× at n=4096, 3500× at n=8192). For M ≥ 3 the same
    logic picks between the prefix-streamed chain reduction
    (front-count-free O(n²·m), wins from n ≈ 512 on CPU) and — at
    M = 3 — the linearithmic Fenwick sweep once its scan outruns the
    O(n²) reduction (measured crossover n ≈ 12-16k; 129× over matrix
    peeling at n = 50k). On accelerators (TPU/GPU) the matrix is one
    fused parallel op while sequential scans pay per-step latency, so
    the static pick keeps the matrix/tiled split there — which is
    exactly what the dispatch tuner exists to re-measure on chip."""
    prefix_thr = tuning.int_env("ND_PREFIX_THRESHOLD",
                                ND_PREFIX_THRESHOLD)
    sweep_thr = tuning.int_env("ND_SWEEP_THRESHOLD", ND_SWEEP_THRESHOLD)
    tiled_thr = tuning.int_env("ND_TILED_THRESHOLD", ND_TILED_THRESHOLD)
    if nobj == 2 and (n >= tiled_thr
                      or (backend == "cpu" and n >= 64)):
        return "staircase"
    if nobj == 3 and backend == "cpu" and n >= sweep_thr:
        return "sweep"
    if nobj >= 3 and backend == "cpu" and n >= prefix_thr:
        return "dc"
    # off-TPU the tiled kernel runs under the Pallas interpreter and
    # is slower than the matrix path, so the static pick only
    # switches on TPU
    return ("tiled" if (backend == "tpu" and n >= tiled_thr)
            else "matrix")


def _nd_candidates(n: int, nobj: int, backend: str):
    """The impls worth racing at this shape: the exact impls for this
    M plus the matrix baseline (and the tiled kernel where it can
    win). All return bit-identical full ranks (tests/test_ndsort*)."""
    names = ["matrix"]
    if backend == "tpu" and n >= tuning.int_env("ND_TILED_THRESHOLD",
                                                ND_TILED_THRESHOLD):
        names.append("tiled")
    if nobj == 2:
        names.append("staircase")
    if nobj == 3:
        names.append("sweep")
    if nobj >= 3:
        names.append("dc")
    return names


def _resolve_nd_impl(w, n: int, plan) -> str:
    """``impl='auto'`` through the dispatch tuner's env / cache /
    probe / static ladder. Probes race full exact ranks on the actual
    ``w`` (bit-identity asserted); under jit tracing or with a
    sharding plan the ladder stops at the cache."""
    backend = jax.default_backend()
    nobj = int(w.shape[1])
    static = _nd_static_auto(n, nobj, backend)
    names = _nd_candidates(n, nobj, backend)
    candidates = dict.fromkeys(names)
    if (len(names) > 1 and plan is None
            and tuning.active_tuner() is not None
            and tuning.is_concrete(w)):
        candidates = {
            name: (lambda name=name: nd_rank(w, impl=name))
            for name in names}
    return tuning.resolve(
        "nd_impl", bucket=(nobj, tuning.shape_bucket(n)),
        default=static, candidates=candidates, check="bitwise",
        program="nd_rank")


def nd_rank(w: jnp.ndarray, max_rank: Optional[int] = None,
            impl: str = "auto", cover_k: Optional[int] = None,
            fallback: str = "none",
            return_peels: bool = False, plan=None) -> jnp.ndarray:
    """Non-domination rank per row (0 = first front).

    Deb's fast non-dominated sort (emo.py:53-117) re-expressed as
    iterative peeling of the dominance matrix: rows with no remaining
    dominator form the next front. Equal-fitness rows automatically share
    a rank, like the reference's fitness-grouping.

    ``impl``: ``'matrix'`` holds the [n, n] dominance matrix in HBM (fast
    for small n), ``'tiled'`` streams it through VMEM with the Pallas
    kernel (ops.kernels.nd_rank_tiled; scales to n ≫ 50k),
    ``'staircase'`` is the exact O(n log n) bi-objective sort
    (:func:`nd_rank_staircase`), ``'sweep'`` the exact O(n log² n)
    3-objective Fenwick sweep (:func:`deap_tpu.mo.ndsort
    .nd_rank_sweep3`), ``'dc'`` the exact any-M prefix-streamed chain
    reduction (:func:`deap_tpu.mo.ndsort.nd_rank_prefix` — one
    front-count-free O(n²·m) pass, [n, block] memory), ``'auto'``
    picks by objective count, population size, and backend (the
    selection matrix is tabulated in docs/advanced/ndsort.md).

    ``max_rank`` stops peeling after that many fronts (the reference's
    sortNondominated ``k`` early-exit, emo.py:71-77); unpeeled rows keep
    rank ``n``.

    Worst-case bounds — per-front peeling is O(fronts · n²) and front
    count is data-dependent (a near-totally-ordered population
    approaches n fronts, i.e. O(n³)); two escape hatches:

    - ``cover_k``: stop peeling once at least ``cover_k`` rows are
      ranked. EXACT for any top-k selection: unpeeled rows keep rank
      ``n``, worse than every peeled rank, so a rank-then-crowding cut
      at ``k ≤ cover_k`` never reaches them (sel_nsga2 passes its own
      ``k``). Bounds work by the fronts needed to cover k.
    - ``fallback='count'``: rows still unpeeled when the loop stops ON
      THE ``max_rank`` BUDGET get rank ``stop + (#dominators among the
      unpeeled)`` — Fonseca-Fleming dominance-count ranking (MOGA),
      exact when the remainder is totally ordered and order-consistent
      with true ranks otherwise (a dominator's count is strictly
      smaller within any set). With ``max_rank=B`` this caps total
      work at O(B · n²) while still returning a full, well-ordered
      ranking. After a ``cover_k`` stop or a complete peel the sweep
      is skipped (its result could never be consumed) and unpeeled
      rows keep the rank-``n`` sentinel.

    ``return_peels=True`` additionally returns the number of fronts the
    loop actually peeled (the data-dependent trip count) as an int32
    scalar — the front-count statistic for profiling peel behaviour at
    scale.
    """
    n = w.shape[0]
    stop = n if max_rank is None else min(max_rank, n)
    covered_stop = n if cover_k is None else min(cover_k, n)
    if fallback not in ("none", "count"):
        raise ValueError(f"unknown nd_rank fallback {fallback!r}")
    if plan is not None:
        # population sharding for the nd-sort (the mesh-native plan of
        # deap_tpu.parallel): pin the [n, m] weighted values to the
        # plan's row layout so the pairwise passes (matrix / the
        # prefix-streamed [n, block] slabs) partition their query rows
        # across the mesh. Layout only — ranks are bit-identical to
        # the unsharded call (tests/test_sharding_plan.py). Works both
        # eagerly and under an enclosing plan-compiled selector.
        w = plan.constrain(w)
    if impl == "auto":
        impl = _resolve_nd_impl(w, n, plan)
    if impl in _ND_EXACT_IMPLS:
        # exact full ranks are free here, so a ``fallback='count'``
        # caller — who asked for a well-ordered ranking past the peel
        # budget — gets the exact ranks themselves (strictly better
        # than dominance counts); the rank-``n`` budget sentinel only
        # applies under ``fallback='none'``, where the matrix/tiled
        # contract is "unpeeled rows report n"
        fn = {"staircase": nd_rank_staircase, "sweep": nd_rank_sweep3,
              "dc": nd_rank_prefix}[impl]
        res = fn(w, None if fallback == "count" else max_rank,
                 return_peels=return_peels)
        if return_peels and fallback == "count" and max_rank is not None:
            # keep the other impls' contract: peels never exceeds the
            # budget, even though the ranks themselves are exact
            ranks, peels = res
            res = (ranks, jnp.minimum(peels, jnp.int32(stop)))
        return res
    if impl == "tiled":
        from deap_tpu.ops.kernels import nd_rank_tiled

        return nd_rank_tiled(w, max_rank, cover_k=cover_k,
                             fallback=fallback,
                             return_peels=return_peels)
    if impl != "matrix":
        raise ValueError(f"unknown nd_rank impl {impl!r}")
    dom = dominance_matrix(w)  # [n, n] j dominates i

    def cond(state):
        ranks, current, remaining = state
        covered = n - jnp.sum(remaining)
        return (remaining.any() & (current < stop)
                & (covered < covered_stop))

    def body(state):
        ranks, current, remaining = state
        ndom = jnp.sum(dom & remaining[None, :], axis=1)
        front = remaining & (ndom == 0)
        ranks = jnp.where(front, current, ranks)
        return ranks, current + 1, remaining & ~front

    ranks, current, remaining = lax.while_loop(
        cond, body,
        (jnp.full(n, n, jnp.int32), jnp.int32(0), jnp.ones(n, bool)))
    if fallback == "count":
        # only when the loop stopped on the peel budget with rows left
        # — a cover_k stop or a complete peel never consumes the
        # count-ranks, so skip the extra O(n²) sweep there
        def count_rank(ranks):
            ndom = jnp.sum(dom & remaining[None, :],
                           axis=1).astype(jnp.int32)
            return jnp.where(remaining, current + ndom, ranks)

        ranks = lax.cond(remaining.any() & (current >= stop),
                         count_rank, lambda r: r, ranks)
    return (ranks, current) if return_peels else ranks


def nd_rank_staircase(w: jnp.ndarray, max_rank: Optional[int] = None,
                      return_peels: bool = False):
    """Exact 2-objective non-domination ranks in O(n log n) — the
    bi-objective specialisation (Jensen-2003-style) of the peeling
    sort, with no dominance matrix at all.

    Process rows in lexicographic descending ``(w0, w1)`` order and
    maintain one scalar per front: the largest ``w1`` seen in it
    (within a front, ``w1`` strictly increases along this processing
    order, so that is the latest member). A new point is dominated by
    front ``r`` iff that maximum is ``>= w1`` — predecessors have
    ``w0 >=`` it, and distinct rows with equal ``w1`` differ in ``w0``
    — and the maxima are nonincreasing in ``r``, so its rank is one
    binary search: the count of fronts whose maximum covers it.
    Identical rows share their group head's rank, like the reference's
    fitness-grouping (emo.py:53-77). A 100k-row rank is a single
    ``lax.scan`` of binary searches: linearithmic work and O(n) memory
    where matrix/tiled peeling is O(fronts·n²) — the path that makes
    NSGA-II pop=50k executable on a CPU host and launch-count-free on
    TPU.

    ``max_rank`` reproduces the peel-budget contract (rows past the
    budget report the rank-``n`` sentinel); the exact ranks make
    ``cover_k``/``fallback`` moot — callers get front-exact ranks for
    every row at no extra cost.
    """
    from deap_tpu.core.fitness import lex_sort_desc

    n, nobj = w.shape
    if nobj != 2:
        raise ValueError(f"nd_rank_staircase needs nobj == 2, got {nobj}")
    stop = n if max_rank is None else min(max_rank, n)
    order = lex_sort_desc(w)
    f2 = w[order, 1]
    neg_f2 = -f2
    same = (w[order[1:], 0] == w[order[:-1], 0]) & (f2[1:] == f2[:-1])
    head = jnp.concatenate([jnp.ones(1, bool), ~same])

    # The scan carries the NEGATED front maxima (ascending), so each
    # step is one binary search plus one single-element in-place carry
    # update — O(log n) per step, O(n log n) total. An earlier form
    # negated the carry and where-selected the full array every step,
    # which XLA materialises: O(n) per step, quadratic overall
    # (measured 3.7x per doubling at 50k→100k).
    def step(carry, x):
        neg_m, prev_rank = carry
        nf2i, is_head = x
        # fronts with max-w1 >= f2i ⟺ neg_m entries <= -f2i;
        # side='right' counts the equal case (equal w1 from an earlier
        # distinct row implies strictly larger w0 — a dominator)
        r_new = jnp.searchsorted(neg_m, nf2i,
                                 side="right").astype(jnp.int32)
        r = jnp.where(is_head, r_new, prev_rank)
        # non-heads write out of bounds and are dropped
        neg_m = neg_m.at[jnp.where(is_head, r, n)].set(nf2i, mode="drop")
        return (neg_m, r), r

    m0 = jnp.full(n, jnp.inf, w.dtype)
    _, sorted_ranks = lax.scan(step, (m0, jnp.int32(0)), (neg_f2, head))
    ranks = jnp.zeros(n, jnp.int32).at[order].set(sorted_ranks)
    peels = jnp.minimum(jnp.max(sorted_ranks) + 1, stop)
    if max_rank is not None:
        ranks = jnp.where(ranks < stop, ranks, n)
    return (ranks, peels) if return_peels else ranks


def sort_nondominated(w: jnp.ndarray, k: int, first_front_only: bool = False):
    """Ranks + the order that sorts by front (emo.py:53-117). Returns
    ``(ranks, order)``; slice ``order`` per rank on the host to recover
    the reference's list-of-fronts shape."""
    ranks = nd_rank(w)
    if first_front_only:
        return ranks, jnp.flatnonzero(ranks == 0, size=w.shape[0],
                                      fill_value=-1)
    order = jnp.argsort(ranks, stable=True)
    return ranks, order[:k]


# --------------------------------------------------------------- crowding ----

def crowding_distances(w: jnp.ndarray, ranks: jnp.ndarray) -> jnp.ndarray:
    """Crowding distance within each front, all fronts at once
    (emo.py:119-143).

    Per objective: sort by (rank, value); front boundary rows get +inf;
    interior rows accumulate (next - prev) / (nobj · (front_max -
    front_min)). Distances are invariant to the weight sign, so weighted
    values give the same result as the reference's raw values.
    """
    n, nobj = w.shape
    dist = jnp.zeros(n)
    for i in range(nobj):
        order = jnp.lexsort((w[:, i], ranks))
        v = w[order, i]
        r = ranks[order]
        first = jnp.concatenate([jnp.ones(1, bool), r[1:] != r[:-1]])
        last = jnp.concatenate([r[1:] != r[:-1], jnp.ones(1, bool)])
        fmin = jax.ops.segment_min(v, r, num_segments=n + 1)[r]
        fmax = jax.ops.segment_max(v, r, num_segments=n + 1)[r]
        norm = nobj * (fmax - fmin)
        prev = jnp.concatenate([v[:1], v[:-1]])
        nxt = jnp.concatenate([v[1:], v[-1:]])
        interior = jnp.where(norm > 0, (nxt - prev) / jnp.where(norm > 0, norm, 1.0), 0.0)
        contrib = jnp.where(first | last, jnp.inf, interior)
        dist = dist.at[order].add(contrib)
    return dist


# ---------------------------------------------------------------- NSGA-II ----

def sel_nsga2(key, w, k, nd: str = "standard",
              peel_budget: Optional[int] = None):
    """NSGA-II selection (emo.py:15-50): whole fronts in rank order, the
    last partial front by descending crowding distance.

    ``nd``: the reference's ``'standard'``/``'log'`` both map to
    ``nd_rank(impl='auto')`` (the log variant exists to cut Python
    constants the tensor kernels don't have); ``'matrix'``/``'tiled'``/
    ``'staircase'``/``'sweep'``/``'dc'`` force a specific nd-sort
    implementation.

    ``peel_budget`` caps the peel loop at that many fronts, ranking any
    remainder by Fonseca-Fleming dominance counts (``nd_rank``'s
    ``fallback='count'``). Default ``None`` is exact — already bounded
    by the fronts needed to cover ``k`` rows (``cover_k``) — but a
    pathological near-totally-ordered population can still need ~k
    peels; the budget turns that O(k·n²) tail into O(budget·n²) at the
    documented cost that a cut landing past the budget uses
    count-ranks (dominance-consistent, not front-exact)."""
    del key
    if nd in ("matrix", "tiled", "staircase", "sweep", "dc"):
        impl = nd
    elif nd in ("standard", "log", "auto"):
        impl = "auto"
    else:
        raise ValueError(f"unknown nd sort {nd!r}")
    # cover_k bounds the peel loop by the fronts needed to cover k rows
    # — exact: unpeeled rows keep rank n, and the cut never reaches them
    ranks = nd_rank(w, impl=impl, cover_k=k, max_rank=peel_budget,
                    fallback="none" if peel_budget is None else "count")
    crowd = crowding_distances(w, jnp.minimum(ranks, w.shape[0]))
    order = jnp.lexsort((-crowd, ranks))
    return order[:k]


def sel_tournament_dcd(key, w, k, peel_budget: Optional[int] = None):
    """Dominance/crowding binary tournament (emo.py:145-195): two random
    permutations supply pairs; dominance decides, then crowding, then a
    coin flip. Returns exactly ``k`` winners (the reference returns
    ceil(k/4)*4).

    Ranks are only consumed by the crowding computation (dominance is
    compared directly per pair), so ``peel_budget`` — cap the nd-sort
    at that many fronts — leaves winners on dominated pairs unaffected.
    All rows still unpeeled at the budget share the rank-``n`` sentinel
    and therefore form ONE crowding segment: crowding among the tail
    stays a genuine density measure over the whole remainder, with
    only the per-objective extremes getting the boundary infinity."""
    n = w.shape[0]
    # past-budget rows keep the rank-n sentinel, i.e. they form one
    # crowding segment
    ranks = nd_rank(w, max_rank=peel_budget)
    crowd = crowding_distances(w, ranks)
    k1, k2, kc = jax.random.split(key, 3)
    # ceil(k/2) pairs from each permutation stream, interleaved in the
    # reference's 4-block pattern
    p1 = jax.random.permutation(k1, n)
    p2 = jax.random.permutation(k2, n)
    reps = k // max(1, 2 * (n // 2)) + 1  # enough pairs even for k > n/2
    a1, b1 = p1[0::2], p1[1::2]
    a2, b2 = p2[0::2], p2[1::2]
    A = jnp.concatenate([jnp.stack([a1, a2], 1).reshape(-1)] * reps)[: k]
    B = jnp.concatenate([jnp.stack([b1, b2], 1).reshape(-1)] * reps)[: k]

    wa, wb = w[A], w[B]
    d_ab = dominates(wa, wb)
    d_ba = dominates(wb, wa)
    ca, cb = crowd[A], crowd[B]
    coin = jax.random.bernoulli(kc, 0.5, (k,))
    pick_a = d_ab | (~d_ba & ((ca > cb) | ((ca == cb) & coin)))
    return jnp.where(pick_a, A, B)


# --------------------------------------------------------------- NSGA-III ----

class NSGA3Memory(NamedTuple):
    best_point: jnp.ndarray
    worst_point: jnp.ndarray
    extreme_points: jnp.ndarray


def uniform_reference_points(nobj: int, p: int = 4, scaling=None) -> jnp.ndarray:
    """Das-Dennis reference points on the unit simplex (emo.py:664-689).
    Host-side (static configuration)."""
    def gen(ref, left, depth):
        if depth == nobj - 1:
            ref[depth] = left / p
            return [ref.copy()]
        pts = []
        for i in range(left + 1):
            ref[depth] = i / p
            pts.extend(gen(ref, left - i, depth + 1))
        return pts

    pts = np.array(gen(np.zeros(nobj), p, 0))
    if scaling is not None:
        pts = pts * scaling + (1.0 - scaling) / nobj
    return jnp.asarray(pts, jnp.float32)


def _find_extreme_points(fitnesses, best_point, extreme_points=None):
    """Min achievement-scalarising-function rows per axis (emo.py:564-580)."""
    if extreme_points is not None:
        fitnesses = jnp.concatenate([fitnesses, extreme_points], axis=0)
    ft = fitnesses - best_point
    nobj = best_point.shape[0]
    asf_w = jnp.where(jnp.eye(nobj) == 1.0, 1.0, 1e6)
    asf = jnp.max(ft[None, :, :] * asf_w[:, None, :], axis=2)  # [nobj, n]
    idx = jnp.argmin(asf, axis=1)
    return fitnesses[idx]


def _find_intercepts(extreme_points, best_point, current_worst, front_worst):
    """Hyperplane axis intercepts with degenerate-case fallbacks
    (emo.py:583-604)."""
    b = jnp.ones(extreme_points.shape[1])
    A = extreme_points - best_point
    x = jnp.linalg.solve(A, b[:, None])[:, 0]
    intercepts = 1.0 / x
    residual_ok = jnp.allclose(A @ x, b, rtol=1e-4, atol=1e-6)
    ok = (jnp.all(jnp.isfinite(x)) & jnp.all(x != 0.0)
          & jnp.all(intercepts > 1e-6)
          & jnp.all((intercepts + best_point) <= current_worst)
          & residual_ok)
    return jnp.where(ok, intercepts, front_worst)


def _associate_to_niche(fitnesses, ref_points, best_point, intercepts):
    """Perpendicular distance to each reference direction (emo.py:607-624)."""
    fn = (fitnesses - best_point) / (intercepts - best_point)
    norm = jnp.linalg.norm(ref_points, axis=1)
    proj_len = fn @ ref_points.T / norm[None, :]  # [n, nref]
    proj = proj_len[:, :, None] * (ref_points / norm[:, None])[None, :, :]
    distances = jnp.linalg.norm(proj - fn[:, None, :], axis=2)
    niches = jnp.argmin(distances, axis=1)
    return niches, jnp.min(distances, axis=1)


def sel_nsga3(key, w, k, ref_points, best_point=None, worst_point=None,
              extreme_points=None, return_memory: bool = False,
              nd: str = "standard"):
    """NSGA-III selection (Deb & Jain 2014; emo.py:479-561).

    Whole fronts in rank order; the last partial front is filled by
    reference-point niching: repeatedly pick a least-populated niche and
    take its closest (for empty niches) or a random available member —
    a one-at-a-time masked reformulation of the reference's batch round
    loop (emo.py:627-661).

    Pass the previous generation's memory (best/worst/extreme points) for
    the selNSGA3WithMemory behaviour (emo.py:450-476).

    ``nd`` follows :func:`sel_nsga2`'s contract: the reference's
    ``'standard'``/``'log'`` map to the auto dispatch, the engine
    names force one implementation.
    """
    if nd in ("matrix", "tiled", "staircase", "sweep", "dc"):
        impl = nd
    elif nd in ("standard", "log", "auto"):
        impl = "auto"
    else:
        raise ValueError(f"unknown nd sort {nd!r}")
    n, nobj = w.shape
    nref = ref_points.shape[0]
    ranks = nd_rank(w, impl=impl)
    fitnesses = -w  # minimisation space, like the reference's wvalues * -1

    if best_point is not None and worst_point is not None:
        best_point = jnp.minimum(jnp.min(fitnesses, axis=0), best_point)
        worst_point = jnp.maximum(jnp.max(fitnesses, axis=0), worst_point)
    else:
        best_point = jnp.min(fitnesses, axis=0)
        worst_point = jnp.max(fitnesses, axis=0)

    extreme = _find_extreme_points(fitnesses, best_point, extreme_points)
    front_worst = jnp.max(fitnesses, axis=0)
    intercepts = _find_intercepts(extreme, best_point, worst_point, front_worst)
    niches, dist = _associate_to_niche(fitnesses, ref_points, best_point,
                                       intercepts)

    # Cut rank: individuals with rank < cut are taken whole; rank == cut
    # is the partial front.
    sorted_ranks = jnp.sort(ranks)
    cut = sorted_ranks[k - 1]
    ahead = ranks < cut          # taken for sure
    partial = ranks == cut       # niching pool
    n_ahead = jnp.sum(ahead)
    n_fill = k - n_ahead

    niche_counts = jnp.zeros(nref, jnp.int32).at[niches].add(
        ahead.astype(jnp.int32))

    def body(i, state):
        counts, available, selected_mask = state
        take = i < n_fill
        # niches that still have available individuals
        niche_open = jnp.zeros(nref, bool).at[niches].max(available)
        min_count = jnp.min(jnp.where(niche_open, counts, jnp.iinfo(jnp.int32).max))
        cand_niche = niche_open & (counts == min_count)
        # random choice among candidate niches (deterministic fold per i)
        kk = jax.random.fold_in(key, i)
        scores = jax.random.uniform(kk, (nref,))
        niche = jnp.argmax(jnp.where(cand_niche, scores, -1.0))
        in_niche = available & (niches == niche)
        k2 = jax.random.fold_in(kk, 1)
        rand_scores = jax.random.uniform(k2, (n,))
        # empty niche → closest member; else random member
        by_dist = jnp.argmin(jnp.where(in_niche, dist, jnp.inf))
        by_rand = jnp.argmax(jnp.where(in_niche, rand_scores, -1.0))
        chosen = jnp.where(counts[niche] == 0, by_dist, by_rand)
        counts = counts.at[niche].add(jnp.where(take, 1, 0))
        available = jnp.where(take, available & (jnp.arange(n) != chosen),
                              available)
        selected_mask = selected_mask | (take & (jnp.arange(n) == chosen))
        return counts, available, selected_mask

    counts, _, selected_mask = lax.fori_loop(
        0, k, body, (niche_counts, partial, jnp.zeros(n, bool)))

    chosen_mask = ahead | selected_mask
    chosen = jnp.argsort(jnp.where(chosen_mask, ranks, jnp.int32(n + 1)),
                         stable=True)[:k]
    if return_memory:
        return chosen, NSGA3Memory(best_point, worst_point, extreme)
    return chosen


class SelNSGA3WithMemory:
    """Stateful NSGA-III wrapper carrying best/worst/extreme points across
    generations (emo.py:450-476). Host-side convenience; inside a scan,
    thread the NSGA3Memory pytree manually via ``sel_nsga3``."""

    def __init__(self, ref_points):
        self.ref_points = ref_points
        self.memory = None

    def __call__(self, key, w, k):
        mem = self.memory
        chosen, self.memory = sel_nsga3(
            key, w, k, self.ref_points,
            best_point=None if mem is None else mem.best_point,
            worst_point=None if mem is None else mem.worst_point,
            extreme_points=None if mem is None else mem.extreme_points,
            return_memory=True)
        return chosen


# ------------------------------------------------------------------ SPEA2 ----

def _two_sum(a, b):
    """Error-free float addition: returns (s, err) with s = fl(a+b)
    and s + err == a + b exactly (Knuth TwoSum; XLA does not
    reassociate floats, so the transform survives jit)."""
    s = a + b
    bb = s - a
    return s, (a - (s - bb)) + (b - bb)


def _two_prod_f32(a, b):
    """Error-free f32 product via Veltkamp splitting (no FMA in XLA's
    portable op set): (p, err) with p = fl(a·b), p + err == a·b."""
    split = jnp.float32(4097.0)            # 2^12 + 1 for f32
    ca, cb = a * split, b * split
    ah = ca - (ca - a)
    al = a - ah
    bh = cb - (cb - b)
    bl = b - bh
    p = a * b
    err = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, err


def _d2_compensated(w: jnp.ndarray):
    """Pairwise squared distances in double-float32: (hi, lo) with
    hi = the f32 head and lo the residual, together carrying ~48
    significant bits — enough to reproduce the reference's float64 tie
    structure from f32 inputs WITHOUT float64 hardware (f32 is the
    TPU-native dtype; plain f32 distances collapse distinct f64
    distances into spurious ties, measured 0.85 truncation-set overlap
    on the adversarial tied front before this, PARITY.md)."""
    n, nobj = w.shape
    hi = jnp.zeros((n, n), jnp.float32)
    lo = jnp.zeros((n, n), jnp.float32)
    for c in range(nobj):                  # nobj is tiny and static
        a = w[:, c][:, None]
        b = w[:, c][None, :]
        d, derr = _two_sum(a, -b)          # exact difference
        p, perr = _two_prod_f32(d, d)
        # (d + derr)² = d² + 2·d·derr + derr²; d² = p + perr exactly
        corr = perr + 2.0 * d * derr + derr * derr
        hi, e = _two_sum(hi, p)
        lo = lo + (e + corr)
    return hi, lo


def _knn_density(d2: jnp.ndarray, kth: jnp.ndarray) -> jnp.ndarray:
    """SPEA2 density ``1/(σ_k + 2)`` (emo.py:726-746) from a square
    pairwise-distance matrix. The diagonal is excluded, and ``kth`` is
    clamped below the last sorted column — which holds the excluded
    (inf) self-distance and would otherwise zero every density."""
    c = d2.shape[0]
    d2 = jnp.where(jnp.eye(c, dtype=bool), jnp.inf, d2)
    sigma_k = jnp.sort(d2, axis=1)[:, jnp.clip(kth, 0, max(c - 2, 0))]
    return 1.0 / (sigma_k + 2.0)


def sel_spea2(key, w, k):
    """SPEA2 environmental selection (Zitzler 2001; emo.py:692-842).

    Strength/raw fitness from the dominance matrix; if the non-dominated
    archive is too small, fill by raw fitness + k-NN density (k=√N); if
    too large, iteratively truncate the member whose sorted-distance
    vector is lexicographically smallest — run as masked loops with
    static shapes.

    Note: the density fill uses the k-th nearest-neighbour distance over
    *all* other members, the algorithm as published; the reference's
    Python implementation only fills the upper-triangular distances
    (emo.py:733-740), an artifact not reproduced.
    """
    del key
    n, nobj = w.shape
    dom = dominance_matrix(w)          # dom[i, j]: j dominates i
    strength = jnp.sum(dom, axis=0)    # how many each j dominates
    raw = jnp.sum(jnp.where(dom, strength[None, :], 0), axis=1)
    nd_mask = raw < 1
    n_nd = jnp.sum(nd_mask)

    d2 = jnp.sum((w[:, None, :] - w[None, :, :]) ** 2, axis=-1)

    # ---- under-full: order all by (not-nd, raw + density) and take k
    density = _knn_density(d2, kth=jnp.int32(jnp.floor(jnp.sqrt(n))))
    fill_score = raw + density
    under_order = jnp.lexsort((fill_score, ~nd_mask))

    # ---- over-full: truncation among the non-dominated set.
    # float32 inputs get double-float (hi, lo) distances: plain f32
    # squared distances collapse distinct reference-f64 distances into
    # spurious ties, so the truncation removed different members on
    # tie-heavy fronts (0.85 set overlap, VERDICT r5 weak #7). The
    # compensated pair carries ~48 significant bits and reproduces the
    # f64 tie structure on the TPU-native dtype; float64 inputs keep
    # the plain single-key compare (already reference-exact there).
    extended = w.dtype == jnp.float32
    if extended:
        d2_hi, d2_lo = _d2_compensated(w)
    else:
        d2_hi, d2_lo = d2, jnp.zeros_like(d2)

    def truncate(nd_mask):
        def cond(state):
            mask, count = state
            return count > k

        def body(state):
            mask, count = state
            big = jnp.inf
            alive = mask[:, None] & mask[None, :]
            off_diag = ~jnp.eye(n, dtype=bool)
            ddh = jnp.where(alive & off_diag, d2_hi, big)
            ddl = jnp.where(alive & off_diag, d2_lo, 0.0)
            # per-row ascending NN distances, ordered by the FULL
            # (hi, lo) value — lo only decides among equal-hi entries
            order = jnp.lexsort((ddl, ddh), axis=-1)
            rows_h = jnp.take_along_axis(ddh, order, axis=1)
            rows_l = jnp.take_along_axis(ddl, order, axis=1)
            # lexicographic argmin over rows, masked, to FULL depth —
            # the reference's removal scan (emo.py:776-790) compares
            # sorted-distance vectors until they differ, however deep;
            # residual full-vector ties fall to the lowest alive index
            # there (min_pos keeps the first candidate) exactly as
            # argmax over the surviving-candidate mask does here. An
            # earlier depth-8 cap measured 0.875 set overlap on a
            # fully-tied front (tests/test_spea2_divergence.py); exact
            # depth costs one data-dependent while_loop per removal.
            def tie_cond(s):
                cand, j = s
                return (jnp.sum(cand) > 1) & (j < n)

            def tie_body(s):
                cand, j = s
                colh = jnp.where(
                    cand, lax.dynamic_index_in_dim(
                        rows_h, j, axis=1, keepdims=False), big)
                cand = cand & (colh == jnp.min(colh))
                coll = jnp.where(
                    cand, lax.dynamic_index_in_dim(
                        rows_l, j, axis=1, keepdims=False), big)
                return cand & (coll == jnp.min(coll)), j + 1

            cand, _ = lax.while_loop(
                tie_cond, tie_body, (mask, jnp.int32(0)))
            drop = jnp.argmax(cand)
            return mask.at[drop].set(False), count - 1

        mask, _ = lax.while_loop(cond, body, (nd_mask, n_nd))
        return mask

    truncated = truncate(nd_mask)

    use_trunc = n_nd > k
    final_mask = jnp.where(use_trunc, truncated, nd_mask)
    # order: members of final_mask first (by raw fitness), then fill
    order = jnp.lexsort((fill_score, ~final_mask))
    return jnp.where(use_trunc | (n_nd == k), order, under_order)[:k]


def spea2_fitness_stream(w: jnp.ndarray, **kernel_kwargs):
    """SPEA2 strength + raw fitness without the [n, n] matrices
    (emo.py:712-724), via the streaming dominance kernels: ``S(i)`` by
    counting rows ``i`` dominates (sign-flip trick), ``R(i)`` as the
    dominator-weighted sum of strengths. Returns ``(strength, raw)``,
    both ``f32[n]``; ``raw < 1`` marks the non-dominated set. Matches
    :func:`sel_spea2`'s dense formulation exactly while raw values stay
    below 2²⁴ (f32 integer-exact range; raw is O(n²) in the worst case,
    so expect rounding in the ranking beyond n ≈ 4k fully-sorted
    populations — in practice raw stays far below the bound)."""
    from deap_tpu.ops.kernels import dominated_weight_sums, strengths_tiled

    strength = strengths_tiled(w, **kernel_kwargs)
    raw = dominated_weight_sums(w, strength, **kernel_kwargs)
    return strength, raw


def sel_spea2_stream(key, w, k, candidates: Optional[int] = None,
                     **kernel_kwargs):
    """SPEA2 selection for populations far past the dense formulation's
    memory wall (n ≫ 50k), built on :func:`spea2_fitness_stream`.

    Strength/raw fitness are the exact published quantities, streaming.
    The environmental step then ranks a bounded candidate set — the
    ``candidates`` best rows by raw fitness (default ``max(2k, 4096)``)
    — by ``raw + density`` with the k-NN density computed densely among
    candidates only, and takes the top ``k``. Documented divergence from
    :func:`sel_spea2` (and emo.py:726-834): the over-full archive is cut
    by one kth-distance ranking instead of the iterative
    minimum-distance removal loop, and density ignores points outside
    the candidate set; both effects vanish as ``candidates`` grows.
    """
    n, _ = w.shape
    if candidates is None:
        c = min(n, max(2 * k, 4096))
    else:
        c = min(candidates, n)
    c = max(c, min(k, n))  # never hand back fewer than the k requested
    _, raw = spea2_fitness_stream(w, **kernel_kwargs)
    # random tie-break: the whole non-dominated set shares raw == 0, and
    # a stable sort would keep only its lowest-index members — a
    # systematic bias at exactly the large-n sizes this targets
    u = jax.random.uniform(key, (n,))
    cand_idx = jnp.lexsort((u, raw))[:c]
    wc = w[cand_idx]
    d2 = jnp.sum((wc[:, None, :] - wc[None, :, :]) ** 2, axis=-1)
    density = _knn_density(d2, jnp.int32(jnp.floor(jnp.sqrt(n))))
    score = raw[cand_idx] + density
    return cand_idx[jnp.argsort(score, stable=True)[:k]]


# DEAP-style aliases
selNSGA2 = sel_nsga2
selNSGA3 = sel_nsga3
selSPEA2 = sel_spea2
selTournamentDCD = sel_tournament_dcd
sortNondominated = sort_nondominated
sortLogNondominated = sort_nondominated
