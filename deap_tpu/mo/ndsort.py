"""Linearithmic M ≥ 3 non-domination ranking — pure-XLA engines.

The matrix/tiled paths in :mod:`deap_tpu.mo.emo` peel fronts off a
pairwise dominance relation: O(fronts · n²·m) work, and for the matrix
variant O(n²) memory. The 2-objective ``nd_rank_staircase`` already
replaced that with an O(n log n) sweep; this module is the same move
for three and more objectives, built on two facts:

1. **Rank is the longest dominating chain.** A point's front index
   equals ``1 + max(rank of its dominators)`` (0 with none): every
   dominator sits in a strictly earlier front, and once fronts up to
   the deepest dominator are peeled nothing above the point remains.
   Ranking is therefore a longest-path DP over the dominance DAG — no
   peeling loop, no front-count-dependent trip count.
2. **Lexicographic order is topological.** After sorting rows
   lexicographically descending, every dominator of a row precedes it,
   and among *distinct* rows ``j`` before ``i`` dominance reduces to
   ``w_j ≥ w_i`` on the remaining objectives (the sort key supplies
   the first coordinate and the strictness). Exact duplicates share
   their group head's rank, like the staircase's fitness-grouping.

Two engines consume those facts:

- :func:`nd_rank_sweep3` (M = 3): one ``lax.scan`` over the sorted
  rows. Each step must answer "max rank among processed points with
  ``w1 ≥ y`` and ``w2 ≥ z``" — a dynamic 2-D dominated-max query. The
  classical structure is a Fenwick tree over ``w1``-rank whose nodes
  hold inner Fenwick trees over ``w2``-rank (O(log² n) per op), which
  sounds hostile to XLA — but every tree *position* depends only on
  the sort order, not on the ranks being computed, so the entire
  control flow is hoisted out of the scan: all gather/scatter chains
  are precomputed into two ``int32[n, ≤⌈log n⌉²]`` index tables with
  vectorised sorts and bisections, and the scan step collapses to
  ``gather → max → scatter-max`` on one flat f32 state vector.
  O(n log² n) work, O(n log n) memory, n sequential steps of ~4 ops.
- :func:`nd_rank_prefix` (any M): the divide-and-conquer front-rank
  reduction collapsed to its streaming schedule. Rows are processed in
  lex order in fixed blocks; each block's base ranks come from one
  masked dominance reduction against the already-ranked prefix (tiled
  — the ``[n, block]`` slab is the only pairwise object ever built;
  on TPU the Pallas kernel ``ops.kernels.dominated_weight_maxes``
  streams it through VMEM), then a serial in-block pass finishes the
  chain DP. O(n²·m) work like a *single* peel, O(n·block) memory, and
  — unlike peeling — one pass regardless of how many fronts the data
  has. The win over the matrix path is the front count itself
  (measured 34 fronts at n=4k and 81 at n=50k on uniform 3-objective
  populations, growing with n).

Both return ranks bit-identical to the dominance-matrix oracle
(property-tested against it, including exact ties, duplicated rows and
mixed maximise/minimise weights) and follow the ``max_rank`` sentinel
contract of :func:`deap_tpu.mo.emo.nd_rank`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deap_tpu.core.fitness import lex_sort_desc

__all__ = ["nd_rank_sweep3", "nd_rank_prefix"]


def _fenwick_offsets(n: int):
    """Flat-pool layout of a Fenwick-of-Fenwicks over ``n`` positions.

    Outer node ``t ∈ [1, n]`` owns the positions ``(t - lsb(t), t]`` —
    ``lsb(t)`` slots. Returns ``(off, F)``: ``off[t]`` is node ``t``'s
    base offset in the flat state vector, ``off[n+1] = F`` is the
    total size (and the lookup target for the invalid-node sentinel).
    Trace-time numpy — ``n`` is static.
    """
    t = np.arange(1, n + 1, dtype=np.int64)
    sizes = t & -t
    csum = np.cumsum(sizes)
    off = np.zeros(n + 2, np.int64)
    if n > 1:
        off[2:n + 1] = csum[:n - 1]
    off[n + 1] = csum[n - 1]
    return jnp.asarray(off, jnp.int32), int(csum[-1])


def _sorted_groups(w: jnp.ndarray):
    """Lex-desc processing order plus the duplicate-group head mask
    (identical rows are adjacent after the sort; only the first of a
    group computes a rank, the rest inherit it)."""
    order = lex_sort_desc(w)
    ws = w[order]
    if w.shape[0] > 1:
        same = jnp.all(ws[1:] == ws[:-1], axis=1)
        head = jnp.concatenate([jnp.ones(1, bool), ~same])
    else:
        head = jnp.ones(w.shape[0], bool)
    return order, ws, head


def nd_rank_sweep3(w: jnp.ndarray, max_rank: Optional[int] = None,
                   return_peels: bool = False):
    """Exact 3-objective non-domination ranks in O(n log² n).

    One pass over the rows in lexicographic descending order; the rank
    of each row is ``1 + max(rank)`` over the already-processed rows
    that cover it in the two trailing objectives (see module
    docstring). The 2-D dominated-max structure answering that query
    is a Fenwick tree over ``w1``-positions of inner Fenwick trees
    over ``w2``-positions — with every tree index precomputed offline,
    so the ``lax.scan`` step is one gather, one max, one scatter-max.

    ``max_rank`` reproduces the peel-budget contract (rows at or past
    the budget report the rank-``n`` sentinel); exactness makes
    ``cover_k``/``fallback`` moot, as for the staircase.
    """
    n, nobj = w.shape
    if nobj != 3:
        raise ValueError(f"nd_rank_sweep3 needs nobj == 3, got {nobj}")
    stop = n if max_rank is None else min(max_rank, n)
    if n == 0:
        ranks = jnp.zeros(0, jnp.int32)
        return (ranks, jnp.int32(0)) if return_peels else ranks

    A = int(n).bit_length()          # max Fenwick chain length
    order, ws, head = _sorted_groups(w)
    y = ws[:, 1].astype(jnp.float32)
    z = ws[:, 2].astype(jnp.float32)

    # Unique descending positions and inclusive-count query bounds per
    # trailing objective. Dominators of row i among processed distinct
    # rows are exactly {j : pos_y[j] <= cge_y[i] and rz[j] < cge_z[i]}:
    # the bounds come from the *values* (counting ties in), while each
    # point occupies one unique slot, so tie order never matters.
    ysort = jnp.argsort(-y, stable=True)
    posy = jnp.zeros(n, jnp.int32).at[ysort].set(
        jnp.arange(1, n + 1, dtype=jnp.int32))        # 1-based, y desc
    cge_y = jnp.searchsorted(-y[ysort], -y,
                             side="right").astype(jnp.int32)
    zsort = jnp.argsort(-z, stable=True)
    rz = jnp.zeros(n, jnp.int32).at[zsort].set(
        jnp.arange(n, dtype=jnp.int32))               # 0-based, z desc
    cge_z = jnp.searchsorted(-z[zsort], -z,
                             side="right").astype(jnp.int32)

    off, F = _fenwick_offsets(n)
    UD, QD = F, F + 1     # scatter dump / gather dump (never written)

    # ---- node membership pool: each point sits in the <= A outer
    # nodes of its update chain; one flat (node, rz)-sorted pool makes
    # every node's members a statically-offset, rz-sorted segment.
    node_cols = []
    t = posy
    for _ in range(A):
        valid = t <= n
        node_cols.append(jnp.where(valid, t, n + 1))
        t = jnp.where(valid, t + (t & -t), t)
    node_tab = jnp.stack(node_cols, 1)                     # [n, A]
    node_flat = node_tab.reshape(-1)
    rz_flat = jnp.broadcast_to(rz[:, None], (n, A)).reshape(-1)
    perm = jnp.lexsort((rz_flat, node_flat))
    rz_sorted = rz_flat[perm]
    inner0_sorted = (jnp.arange(node_flat.shape[0], dtype=jnp.int32)
                     - off[node_flat[perm]])
    q_tab = jnp.zeros_like(node_flat).at[perm].set(
        inner0_sorted).reshape(n, A)   # 0-based position inside node

    # ---- update table: flat slots of every (outer node, inner chain)
    # step of each point's insertion, padded with the dump slot.
    u_cols = []
    for a in range(A):
        node = node_tab[:, a]
        valid = node <= n
        m_t = node & -node
        base = off[node]
        x = q_tab[:, a] + 1                    # 1-based inner position
        for _ in range(A):
            ok = valid & (x <= m_t)
            u_cols.append(jnp.where(ok, base + x - 1, UD))
            x = x + (x & -x)
    U = jnp.stack(u_cols, 1)                               # [n, A*A]

    # ---- query table: prefix decomposition of cge_y into <= A outer
    # nodes; per node, a bisection finds how many members satisfy the
    # z-bound, and that count's inner query chain is emitted.
    q_cols = []
    t = cge_y
    for _ in range(A):
        validq = t > 0
        node = jnp.where(validq, t, n + 1)
        m_t = jnp.where(validq, node & -node, 0)
        base = off[node]
        lo, hi = base, base + m_t
        for _ in range(A + 1):                 # lower_bound on segment
            mid = (lo + hi) // 2
            v = rz_sorted[jnp.clip(mid, 0, rz_sorted.shape[0] - 1)]
            active = lo < hi
            go_right = active & (v < cge_z)
            lo, hi = (jnp.where(go_right, mid + 1, lo),
                      jnp.where(active & ~go_right, mid, hi))
        x = lo - base
        for _ in range(A):
            okq = validq & (x > 0)
            q_cols.append(jnp.where(okq, base + x - 1, QD))
            x = x - (x & -x)
        t = jnp.where(validq, t - (t & -t), t)
    Q = jnp.stack(q_cols, 1)                               # [n, A*A]

    # ---- the sweep: state holds (rank + 1) per inserted tree slot, so
    # a query's max IS the new rank (0 = undominated). f32 is exact for
    # ranks < 2²⁴, far past any population this runs on.
    def step(carry, xs):
        state, prev = carry
        qrow, urow, is_head = xs
        r = jnp.where(is_head, jnp.max(state[qrow]), prev)
        state = state.at[urow].max(r + 1.0)
        return (state, r), r

    (_, _), ranks_f = lax.scan(
        step, (jnp.zeros(F + 2, jnp.float32), jnp.float32(0)),
        (Q, U, head))
    sorted_ranks = ranks_f.astype(jnp.int32)
    ranks = jnp.zeros(n, jnp.int32).at[order].set(sorted_ranks)
    peels = jnp.minimum(jnp.max(sorted_ranks) + 1, jnp.int32(stop))
    if max_rank is not None:
        ranks = jnp.where(ranks < stop, ranks, n)
    return (ranks, peels) if return_peels else ranks


def nd_rank_prefix(w: jnp.ndarray, max_rank: Optional[int] = None,
                   return_peels: bool = False, *, block: int = 512,
                   cross: str = "auto",
                   interpret: Optional[bool] = None):
    """Exact any-M non-domination ranks in one front-count-free pass.

    The divide-and-conquer front-rank reduction, streamed: rows sorted
    lexicographically descending are consumed in fixed blocks; a
    block's base ranks are one masked dominance max-reduction against
    the already-ranked prefix (the cross step — only an ``[n, block]``
    slab is ever materialised), and a serial in-block pass closes the
    longest-chain DP. O(n²·m) work — a *single* peel's worth, against
    the matrix/tiled paths' O(fronts · n²·m) — with O(n·block) memory.

    ``cross``: ``'xla'`` computes the prefix reduction as a fused
    masked broadcast; ``'pallas'`` streams it through
    :func:`deap_tpu.ops.kernels.dominated_weight_maxes` tile by tile
    (the TPU path; also exercises under the interpreter); ``'auto'``
    picks pallas on TPU, xla elsewhere.
    """
    n, m = w.shape
    stop = n if max_rank is None else min(max_rank, n)
    if n == 0:
        ranks = jnp.zeros(0, jnp.int32)
        return (ranks, jnp.int32(0)) if return_peels else ranks
    if cross == "auto":
        from deap_tpu import tuning

        static = "pallas" if jax.default_backend() == "tpu" else "xla"
        # cache/env only here: nd_rank's tuner probe times the whole
        # dc pass, so the cross step is tuned through its caller —
        # this knob is the backend-local escape hatch
        # (DEAP_TPU_TUNE_ND_CROSS) plus any bench-recorded winner
        cross = tuning.resolve(
            "nd_cross", bucket=(),
            default=static,
            candidates={"xla": None, "pallas": None},
            check=None, program="nd_rank_prefix")
    if cross not in ("xla", "pallas"):
        raise ValueError(f"unknown nd_rank_prefix cross {cross!r}")

    order = lex_sort_desc(w)
    ws = w[order].astype(jnp.float32)
    block = max(1, min(block, n))
    nb = -(-n // block)
    npad = nb * block
    wp = jnp.pad(ws, ((0, npad - n), (0, 0)),
                 constant_values=-jnp.inf)   # pad rows dominate nothing
    idx = jnp.arange(npad)
    biota = jnp.arange(block)

    if cross == "pallas":
        from deap_tpu.ops.kernels import dominated_weight_maxes

    def block_step(R, k):
        start = k * block
        blk = lax.dynamic_slice(wp, (start, jnp.int32(0)), (block, m))
        if cross == "pallas":
            weights = jnp.where(idx < start, R + 1.0, 0.0)
            base = dominated_weight_maxes(wp, weights, queries=blk,
                                          interpret=interpret)
        else:
            dom = (jnp.all(wp[:, None, :] >= blk[None, :, :], -1)
                   & jnp.any(wp[:, None, :] > blk[None, :, :], -1)
                   & (idx[:, None] < start))
            base = jnp.max(jnp.where(dom, R[:, None] + 1.0, 0.0), axis=0)

        def inner(i, rb):
            wi = lax.dynamic_slice(blk, (i, jnp.int32(0)), (1, m))
            d = (jnp.all(blk >= wi, -1) & jnp.any(blk > wi, -1)
                 & (biota < i))
            ri = jnp.maximum(base[i],
                             jnp.max(jnp.where(d, rb + 1.0, 0.0)))
            return rb.at[i].set(ri)

        rb = lax.fori_loop(0, block, inner, jnp.zeros(block))
        return lax.dynamic_update_slice(R, rb, (start,)), None

    R, _ = lax.scan(block_step, jnp.zeros(npad), jnp.arange(nb))
    sorted_ranks = R[:n].astype(jnp.int32)
    ranks = jnp.zeros(n, jnp.int32).at[order].set(sorted_ranks)
    peels = jnp.minimum(jnp.max(sorted_ranks) + 1, jnp.int32(stop))
    if max_rank is not None:
        ranks = jnp.where(ranks < stop, ranks, n)
    return (ranks, peels) if return_peels else ranks
