"""Stdlib service client — submit/stream/fetch against the RPC front end.

The consumer half of :mod:`deap_tpu.serving.service`'s wire protocol.
Like :mod:`~deap_tpu.telemetry.metrics` and ``telemetry/report.py``,
this module imports **nothing heavier than numpy** (for the byte-exact
array codec in :mod:`~deap_tpu.serving.wire`): a box that submits jobs
and reads results must never initialise an XLA backend. One client per
thread — it holds a single keep-alive ``http.client`` connection.

::

    from deap_tpu.serving.client import ServiceClient

    c = ServiceClient(service_url, token="s3cret")
    tid = c.submit("onemax", params={"seed": 7, "ngen": 40})
    for ev in c.stream(tid):          # NDJSON per-segment events
        print(ev["event"], ev.get("gen"))
    res = c.result(tid, wait=True)    # wire-encoded result pytree
    leaves = c.decode_leaves(res)     # numpy arrays, byte-exact
"""

from __future__ import annotations

import http.client
import json
import sys
import urllib.parse
from typing import Any, Dict, Iterator, List, Optional

if "deap_tpu" in sys.modules:
    from deap_tpu.serving import wire
else:
    # standalone load (no deap_tpu in the process — e.g. a submit box
    # that must never initialise jax): pull the codec in by file path
    # instead of importing the package, whose __init__ imports jax.
    # tests/test_service.py pins the no-jax guarantee in a subprocess.
    import importlib.util as _ilu
    import os as _os

    _spec = _ilu.spec_from_file_location(
        "_deap_tpu_serving_wire_standalone",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      "wire.py"))
    wire = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(wire)

__all__ = ["ServiceClient", "ServiceError"]


class ServiceError(RuntimeError):
    """A non-2xx response from the service (``.code`` holds the HTTP
    status; 401/403 auth, 404 unknown, 429 quota, 503 draining)."""

    def __init__(self, code: int, message: str):
        super().__init__(f"HTTP {code}: {message}")
        self.code = code


class ServiceClient:
    def __init__(self, base_url: str, token: Optional[str] = None,
                 timeout: float = 600.0):
        u = urllib.parse.urlparse(base_url)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.token = token
        self.timeout = timeout
        self._conn: Optional[http.client.HTTPConnection] = None

    # ------------------------------------------------------- plumbing ----

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _connect(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        return self._conn

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> Any:
        conn = self._connect()
        try:
            conn.request(method, path,
                         body=(json.dumps(body).encode()
                               if body is not None else None),
                         headers=self._headers())
            resp = conn.getresponse()
            data = resp.read()
        except (http.client.HTTPException, ConnectionError, OSError):
            # stale keep-alive (server restarted): one reconnect
            self.close()
            conn = self._connect()
            conn.request(method, path,
                         body=(json.dumps(body).encode()
                               if body is not None else None),
                         headers=self._headers())
            resp = conn.getresponse()
            data = resp.read()
        try:
            payload = json.loads(data) if data else {}
        except json.JSONDecodeError:
            payload = {"error": data.decode("utf-8", "replace")[:200]}
        if resp.status >= 400:
            raise ServiceError(resp.status,
                               payload.get("error", resp.reason))
        payload["_status"] = resp.status
        return payload

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def __enter__(self) -> "ServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ API ----

    def healthz(self) -> Dict[str, Any]:
        try:
            return self._request("GET", "/healthz")
        except ServiceError as e:
            if e.code == 503:
                return {"status": "draining", "_status": 503}
            raise

    def metrics_text(self) -> str:
        conn = self._connect()
        conn.request("GET", "/metrics", headers=self._headers())
        resp = conn.getresponse()
        body = resp.read().decode("utf-8")
        if resp.status >= 400:
            raise ServiceError(resp.status, body[:200])
        return body

    def submit(self, problem: str, params: Optional[dict] = None,
               tenant_id: Optional[str] = None) -> str:
        body: Dict[str, Any] = {"problem": problem,
                                "params": params or {}}
        if tenant_id is not None:
            body["tenant_id"] = str(tenant_id)
        return self._request("POST", "/v1/jobs", body)["tenant_id"]

    def submit_many(self, jobs: List[dict]) -> List[str]:
        """Batch submit: ``jobs`` is a list of
        ``{"problem", "params", "tenant_id"?}`` specs; one HTTP round
        trip, returns the tenant ids in order."""
        return self._request("POST", "/v1/jobs",
                             {"jobs": jobs})["tenant_ids"]

    def results_many(self, tenant_ids: List[str], wait: bool = True,
                     timeout: Optional[float] = None
                     ) -> Dict[str, Dict[str, Any]]:
        """Batch result fetch: ``{tenant_id: status-dict}`` (each with
        ``result`` once finished); with ``wait`` the long-poll
        deadline is shared across the batch."""
        ids = ",".join(urllib.parse.quote(t) for t in tenant_ids)
        path = f"/v1/results?ids={ids}"
        if wait:
            t = timeout if timeout is not None else self.timeout
            path += f"&wait=1&timeout={t}"
        return self._request("GET", path)["results"]

    def status(self, tenant_id: str) -> Dict[str, Any]:
        return self._request(
            "GET", f"/v1/jobs/{urllib.parse.quote(tenant_id)}")

    def result(self, tenant_id: str, wait: bool = True,
               timeout: Optional[float] = None) -> Dict[str, Any]:
        """The job's status dict; once finished it carries ``result``
        (the wire-encoded pytree: ``treedef``/``leaves``/``digest``).
        ``wait=True`` long-polls until done/drained."""
        path = f"/v1/jobs/{urllib.parse.quote(tenant_id)}/result"
        if wait:
            t = timeout if timeout is not None else self.timeout
            path += f"?wait=1&timeout={t}"
        return self._request("GET", path)

    def drain(self) -> Dict[str, Any]:
        return self._request("POST", "/v1/drain")

    def stream(self, tenant_id: str) -> Iterator[Dict[str, Any]]:
        """Yield the job's NDJSON events (``status`` → ``segment``* →
        terminal ``finished``/``stopped``/``drained``) as dicts. Uses
        a dedicated connection (the stream holds it until the
        terminal event)."""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        try:
            conn.request(
                "GET",
                f"/v1/jobs/{urllib.parse.quote(tenant_id)}/stream",
                headers=self._headers())
            resp = conn.getresponse()
            if resp.status >= 400:
                body = resp.read()
                try:
                    msg = json.loads(body).get("error", "")
                except json.JSONDecodeError:
                    msg = body.decode("utf-8", "replace")[:200]
                raise ServiceError(resp.status, msg)
            buf = b""
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if line.strip():
                        yield json.loads(line)
        finally:
            conn.close()

    # ------------------------------------------------------- decoding ----

    @staticmethod
    def decode_leaves(result_payload: Dict[str, Any]) -> List[Any]:
        """The byte-exact numpy leaves of a :meth:`result` payload."""
        return [wire.unpack(leaf)
                for leaf in result_payload["result"]["leaves"]]

    @staticmethod
    def decode_records(segment_event: Dict[str, Any]) -> Any:
        """Decode a ``segment`` stream event's ``records`` block back
        into numpy arrays (``None`` when the segment carried none)."""
        rec = segment_event.get("records")
        return None if rec is None else wire.unpack(rec)
