"""Stdlib service client — submit/stream/fetch against the RPC front end.

The consumer half of :mod:`deap_tpu.serving.service`'s wire protocol.
Like :mod:`~deap_tpu.telemetry.metrics` and ``telemetry/report.py``,
this module imports **nothing heavier than numpy** (for the byte-exact
array codec in :mod:`~deap_tpu.serving.wire`): a box that submits jobs
and reads results must never initialise an XLA backend. One client per
thread — it holds a single keep-alive ``http.client`` connection.

::

    from deap_tpu.serving.client import ServiceClient

    c = ServiceClient(service_url, token="s3cret")
    tid = c.submit("onemax", params={"seed": 7, "ngen": 40})
    for ev in c.stream(tid):          # NDJSON per-segment events
        print(ev["event"], ev.get("gen"))
    res = c.result(tid, wait=True)    # wire-encoded result pytree
    leaves = c.decode_leaves(res)     # numpy arrays, byte-exact
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import sys
import threading
import urllib.parse
from typing import Any, Dict, Iterator, List, Optional

if "deap_tpu" in sys.modules:
    from deap_tpu.serving import wire
    from deap_tpu.resilience.retry import RetryPolicy
    from deap_tpu.telemetry import tracing
else:
    # standalone load (no deap_tpu in the process — e.g. a submit box
    # that must never initialise jax): pull the codec and the retry
    # policy in by file path instead of importing the package, whose
    # __init__ imports jax. tests/test_service.py pins the no-jax
    # guarantee in a subprocess.
    import importlib.util as _ilu
    import os as _os

    def _load(name: str, *relpath: str):
        spec = _ilu.spec_from_file_location(
            name,
            _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                          *relpath))
        mod = _ilu.module_from_spec(spec)
        # register BEFORE exec: dataclass processing (tracing's
        # TraceContext) resolves string annotations through
        # sys.modules[cls.__module__]
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod

    wire = _load("_deap_tpu_serving_wire_standalone", "wire.py")
    RetryPolicy = _load("_deap_tpu_resilience_retry_standalone",
                        _os.pardir, "resilience", "retry.py").RetryPolicy
    tracing = _load("_deap_tpu_telemetry_tracing_standalone",
                    _os.pardir, "telemetry", "tracing.py")

__all__ = ["ClientAbandoned", "ServiceClient", "ServiceError",
           "RetryPolicy"]


class ClientAbandoned(RuntimeError):
    """Raised locally when this client's ``abandon_after_s`` fired:
    the long-poll socket was closed mid-wait (the load generator's
    impatient-client model). The *server* never sees an error — its
    handler thread wakes at ``view.done`` or the ``max_poll_s`` clamp,
    the response write fails with a caught ``BrokenPipeError``, and
    the tenant keeps running (now idle: ``gens_since_interaction``
    grows until the autoscaler spills it)."""


class ServiceError(RuntimeError):
    """A non-2xx response from the service (``.code`` holds the HTTP
    status; 401/403 auth, 404 unknown, 429 quota/overload — then
    ``.retry_after`` carries the server's Retry-After seconds — 503
    draining, 504 deadline exceeded)."""

    def __init__(self, code: int, message: str,
                 retry_after: Optional[float] = None,
                 payload: Optional[Dict[str, Any]] = None):
        super().__init__(f"HTTP {code}: {message}")
        self.code = code
        self.retry_after = retry_after
        self.payload = payload or {}


#: HTTP statuses a retrying client may safely re-attempt: 429 is an
#: explicit "come back later" (load shed / quota) and 503 a draining /
#: restarting service. 504 (deadline exceeded) is FINAL by design and
#: anything else is the caller's bug, not the network's.
RETRYABLE_STATUSES = (429, 503)


class ServiceClient:
    """One service connection, optionally self-healing.

    ``retry=RetryPolicy(...)`` turns on transparent retries: connection
    errors (a killed/restarting service) back off on the policy's
    jittered exponential schedule, and 429/503 responses honour the
    server's ``Retry-After`` (never less than the policy's own delay).
    Retrying a **submit** is only safe with an idempotency key — the
    first attempt may have been durably accepted while its response
    was lost; the key maps the retry back to the same tenant
    (``idempotent_replay``). Without ``retry`` the behaviour is the
    PR 11 one: a single reconnect attempt on a stale keep-alive."""

    def __init__(self, base_url: str, token: Optional[str] = None,
                 timeout: float = 600.0,
                 retry: Optional[RetryPolicy] = None,
                 abandon_after_s: Optional[float] = None):
        u = urllib.parse.urlparse(base_url)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 80
        self.token = token
        self.timeout = timeout
        self.retry = retry
        #: abandonment model (ISSUE 17): when set, any long-poll
        #: request (``wait=1``) has its socket closed after this many
        #: seconds and raises :class:`ClientAbandoned` — never
        #: retried, the caller walked away. Seed-drawn per arrival by
        #: the load generator (``serving/loadgen.py``).
        self.abandon_after_s = (float(abandon_after_s)
                                if abandon_after_s is not None
                                else None)
        self._conn: Optional[http.client.HTTPConnection] = None
        self._abandon_timer: Optional[threading.Timer] = None
        self._abandoned = False
        self._rid_seq = 0

    # ------------------------------------------------------- plumbing ----

    def next_request_id(self) -> str:
        """A fresh client-generated request id. One id per *logical*
        request: retries inside :meth:`_request` reuse it, so a
        retried submit stays one trace server-side."""
        self._rid_seq += 1
        return f"req-cl-{os.getpid():x}-{self._rid_seq:x}"

    def _headers(self, request_id: Optional[str] = None
                 ) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        if request_id:
            # W3C trace propagation alongside the request id: both
            # derive deterministically from the id, so the server
            # (and a WAL-replaying restart of it) lands on the same
            # trace without the client holding any tracing state
            h["X-Request-Id"] = request_id
            h["traceparent"] = tracing.format_traceparent(
                tracing.trace_id_for(request_id),
                tracing.span_id_for(request_id, "client"))
        return h

    def _connect(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        return self._conn

    def _abandon(self) -> None:
        """The abandonment timer's target: close the live connection
        mid-long-poll. ``shutdown`` before ``close`` — closing alone
        doesn't wake the thread blocked in ``recv``; shutdown delivers
        it an immediate EOF. ``_request`` sees the ``_abandoned`` flag
        and raises :class:`ClientAbandoned` instead of retrying."""
        self._abandoned = True
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                if conn.sock is not None:
                    conn.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _request_once(self, method: str, path: str,
                      body: Optional[dict] = None,
                      request_id: Optional[str] = None):
        conn = self._connect()
        timer = None
        if self.abandon_after_s is not None and "wait=1" in path:
            timer = threading.Timer(self.abandon_after_s,
                                    self._abandon)
            timer.daemon = True
            self._abandon_timer = timer
            timer.start()
        try:
            conn.request(method, path,
                         body=(json.dumps(body).encode()
                               if body is not None else None),
                         headers=self._headers(request_id))
            resp = conn.getresponse()
            return resp, resp.read()
        finally:
            if timer is not None:
                timer.cancel()
                self._abandon_timer = None

    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> Any:
        attempt = 0
        rid = self.next_request_id()
        max_retries = (self.retry.max_retries
                       if self.retry is not None else 1)
        while True:
            retry_after = None
            try:
                resp, data = self._request_once(method, path, body,
                                                request_id=rid)
            except (http.client.HTTPException, ConnectionError,
                    OSError):
                if self._abandoned:
                    # our own abandonment timer closed the socket —
                    # final by design, the modelled client walked away
                    self._abandoned = False
                    self.close()
                    raise ClientAbandoned(
                        f"abandoned long-poll after "
                        f"{self.abandon_after_s}s: {method} {path}")
                # stale keep-alive or a killed/restarting service:
                # reconnect and (with a policy) back off jittered
                self.close()
                if attempt >= max_retries:
                    raise
                if self.retry is not None:
                    self.retry.sleep(self.retry.delay(attempt))
                attempt += 1
                continue
            try:
                payload = json.loads(data) if data else {}
            except json.JSONDecodeError:
                payload = {"error":
                           data.decode("utf-8", "replace")[:200]}
            if resp.status >= 400:
                ra = resp.getheader("Retry-After")
                try:
                    retry_after = float(ra) if ra else None
                except ValueError:
                    retry_after = None
                if self.retry is not None \
                        and resp.status in RETRYABLE_STATUSES \
                        and attempt < max_retries:
                    delay = self.retry.delay(attempt)
                    if retry_after is not None:
                        delay = max(delay, retry_after)
                    self.retry.sleep(delay)
                    attempt += 1
                    continue
                raise ServiceError(resp.status,
                                   payload.get("error", resp.reason),
                                   retry_after=retry_after,
                                   payload=payload)
            payload["_status"] = resp.status
            return payload

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def __enter__(self) -> "ServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ API ----

    def healthz(self) -> Dict[str, Any]:
        try:
            return self._request("GET", "/healthz")
        except ServiceError as e:
            if e.code == 503:
                # draining or stalled: the body says which
                return {"status": e.payload.get("status", "draining"),
                        **{k: v for k, v in e.payload.items()
                           if k != "status"}, "_status": 503}
            raise

    def metrics_text(self) -> str:
        conn = self._connect()
        conn.request("GET", "/metrics", headers=self._headers())
        resp = conn.getresponse()
        body = resp.read().decode("utf-8")
        if resp.status >= 400:
            raise ServiceError(resp.status, body[:200])
        return body

    def submit(self, problem: str, params: Optional[dict] = None,
               tenant_id: Optional[str] = None,
               idempotency_key: Optional[str] = None,
               deadline_s: Optional[float] = None) -> str:
        """Submit one job. ``idempotency_key`` makes the submit safe
        to retry (a duplicate key maps to the already-accepted
        tenant); ``deadline_s`` bounds how long the job may wait for
        admission — past it the service drops the job and result
        polls return 504."""
        body: Dict[str, Any] = {"problem": problem,
                                "params": params or {}}
        if tenant_id is not None:
            body["tenant_id"] = str(tenant_id)
        if idempotency_key is not None:
            body["idempotency_key"] = str(idempotency_key)
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        return self._request("POST", "/v1/jobs", body)["tenant_id"]

    def submit_many(self, jobs: List[dict]) -> List[str]:
        """Batch submit: ``jobs`` is a list of ``{"problem",
        "params", "tenant_id"?, "idempotency_key"?, "deadline_s"?}``
        specs; one HTTP round trip, returns the tenant ids in order.
        With a retrying client, give every spec an idempotency key —
        a retried batch then maps back onto the accepted tenants."""
        return self._request("POST", "/v1/jobs",
                             {"jobs": jobs})["tenant_ids"]

    def results_many(self, tenant_ids: List[str], wait: bool = True,
                     timeout: Optional[float] = None
                     ) -> Dict[str, Dict[str, Any]]:
        """Batch result fetch: ``{tenant_id: status-dict}`` (each with
        ``result`` once finished); with ``wait`` the long-poll
        deadline is shared across the batch."""
        ids = ",".join(urllib.parse.quote(t) for t in tenant_ids)
        path = f"/v1/results?ids={ids}"
        if wait:
            t = timeout if timeout is not None else self.timeout
            path += f"&wait=1&timeout={t}"
        return self._request("GET", path)["results"]

    def status(self, tenant_id: str) -> Dict[str, Any]:
        return self._request(
            "GET", f"/v1/jobs/{urllib.parse.quote(tenant_id)}")

    def result(self, tenant_id: str, wait: bool = True,
               timeout: Optional[float] = None) -> Dict[str, Any]:
        """The job's status dict; once finished it carries ``result``
        (the wire-encoded pytree: ``treedef``/``leaves``/``digest``).
        ``wait=True`` long-polls until done/drained."""
        path = f"/v1/jobs/{urllib.parse.quote(tenant_id)}/result"
        if wait:
            t = timeout if timeout is not None else self.timeout
            path += f"?wait=1&timeout={t}"
        return self._request("GET", path)

    def drain(self) -> Dict[str, Any]:
        return self._request("POST", "/v1/drain")

    def stream(self, tenant_id: str) -> Iterator[Dict[str, Any]]:
        """Yield the job's NDJSON events (``status`` → ``segment``* →
        terminal ``finished``/``stopped``/``drained``) as dicts. Uses
        a dedicated connection (the stream holds it until the
        terminal event)."""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        try:
            conn.request(
                "GET",
                f"/v1/jobs/{urllib.parse.quote(tenant_id)}/stream",
                headers=self._headers())
            resp = conn.getresponse()
            if resp.status >= 400:
                body = resp.read()
                try:
                    msg = json.loads(body).get("error", "")
                except json.JSONDecodeError:
                    msg = body.decode("utf-8", "replace")[:200]
                raise ServiceError(resp.status, msg)
            buf = b""
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if line.strip():
                        yield json.loads(line)
        finally:
            conn.close()

    # ------------------------------------------------------- decoding ----

    @staticmethod
    def decode_leaves(result_payload: Dict[str, Any]) -> List[Any]:
        """The byte-exact numpy leaves of a :meth:`result` payload."""
        return [wire.unpack(leaf)
                for leaf in result_payload["result"]["leaves"]]

    @staticmethod
    def decode_records(segment_event: Dict[str, Any]) -> Any:
        """Decode a ``segment`` stream event's ``records`` block back
        into numpy arrays (``None`` when the segment carried none)."""
        rec = segment_event.get("records")
        return None if rec is None else wire.unpack(rec)
