"""Admission write-ahead log — durable "this job exists" records.

The service plane's crash-consistency gap (ISSUE 12): the scheduler's
per-tenant checkpoints only exist once a tenant has *run* a segment,
so a ``kill -9`` between HTTP accept and the driver's admission loses
the job entirely — the client got a 200 and the restarted service has
never heard of it. This module closes that window with the same
durability discipline as :mod:`deap_tpu.support.checkpoint`, adapted
from rename-a-whole-file to an **append-only record log**:

- every record is one line ``<crc32:8 hex> <json>\\n`` — the CRC covers
  the exact JSON bytes, so a torn/bit-rotted record can never parse as
  a different record (the checkpoint module's per-blob CRC, per line);
- :meth:`AdmissionWAL.append` writes, flushes and **fsyncs before
  returning** — the service ACKs a submit only after the record is
  durable, which is the whole contract: *ACKed implies replayable* —
  and :meth:`AdmissionWAL.append_many` amortises the fsync: a batch
  submit's N accept records cost one durability sync;
- a record torn by a mid-``write`` kill is, by that same contract, a
  job that was never ACKed — :meth:`replay` detects it (CRC/parse
  fail on the final line), reports its byte offset, and opening for
  append **truncates the tear away** so the log stays parseable (the
  `read_journal` torn-tail policy, made self-healing).

Record kinds (free-form dicts; the service writes these):

- ``accept`` — tenant_id, problem, params, idempotency_key?,
  request_id?: journaled *before* the submit ACK.
- ``done`` — tenant_id, status: the job reached a terminal state
  (finished / stopped / failed / deadline_exceeded) — replay skips it.

Ownership-transfer records (ISSUE 20 — live migration): moving a
tenant between driver processes is a two-WAL handshake in which the
tenant is, at every instant, owned by exactly one log:

- ``offer`` (source WAL) — tenant_id, offer_id, target, gen + the
  original accept fields: fsync'd *before* the checkpoint is handed to
  the target. An offered tenant stays ``pending`` on the source — an
  offer is an intent, not a transfer — so a crash mid-handoff replays
  it on the source unless the target's durable adoption says otherwise
  (the resolution rule lives in ``serving/migration.py``).
- ``adopted`` (TARGET's own WAL) — same fields plus ``source``: folds
  exactly like an ``accept`` (the adopted tenant joins the target's
  pending set, its idempotency key maps on the target), and is indexed
  by ``offer_id`` in ``WALState.adoptions`` — the durable fact the
  source checks to decide who won.
- ``transferred`` (source WAL) — tenant_id, offer_id, target: the
  source's commit record, written only after the target ACKed. Folds
  as a terminal: the tenant leaves the source's pending set and its
  open offer closes.

:meth:`replay` folds the log into ``WALState``: the records, the
surviving ``pending`` jobs (accepted, not done — resubmitted by a
restarted :class:`~deap_tpu.serving.service.EvolutionService`, where
tenants with checkpoints resume and the rest re-run deterministically
from their problem factory) and the ``idempotency`` key→tenant map
(duplicate submit retries — a client that never saw its ACK — map back
to the same tenant instead of admitting twins).
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Any, Dict, List, Optional

__all__ = ["AdmissionWAL", "WALState", "scan_wal"]


class WALState:
    """:meth:`AdmissionWAL.replay`'s result."""

    def __init__(self):
        #: every valid record, in append order
        self.records: List[Dict[str, Any]] = []
        #: tenant_id -> its ``accept`` record, for jobs with no
        #: terminal ``done`` record — the restart's replay set
        self.pending: Dict[str, Dict[str, Any]] = {}
        #: idempotency key -> tenant_id for every accepted job (done
        #: or not: a retry of a finished job must still map to it)
        self.idempotency: Dict[str, str] = {}
        #: tenant_id -> its newest UNRESOLVED ``offer`` record (no
        #: ``transferred`` follow-up): the migrations a restarted
        #: source must resolve against the target's WAL
        self.offers: Dict[str, Dict[str, Any]] = {}
        #: offer_id -> the ``adopted`` record THIS log holds — the
        #: durable proof of adoption a source (or racing peer)
        #: resolves ownership against
        self.adoptions: Dict[str, Dict[str, Any]] = {}
        #: byte offset of a torn tail record (None = clean log)
        self.tear_offset: Optional[int] = None

    def __len__(self) -> int:
        return len(self.records)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                     os.O_RDONLY)
    except OSError:  # platform without dir-open: best effort
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class AdmissionWAL:
    """One append-only, CRC-framed, fsync-on-append record log.

    Thread-safe: front-end request threads append ``accept`` records
    while the driver appends ``done`` records; one lock keeps lines
    whole and fsyncs ordered.
    """

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        self.n_appended = 0
        # scan-then-heal: parse what survives, truncate a torn tail so
        # the first append lands on a clean line boundary
        self._state = self._scan()
        if self._state.tear_offset is not None:
            with open(self.path, "r+b") as fh:
                fh.truncate(self._state.tear_offset)
                fh.flush()
                os.fsync(fh.fileno())
        new = not os.path.exists(self.path)
        self._fh = open(self.path, "ab")
        if new:
            _fsync_dir(self.path)

    # ------------------------------------------------------------ write ----

    @staticmethod
    def _frame(kind: str, fields: Dict[str, Any]) -> bytes:
        rec = {"kind": str(kind), **fields}
        body = json.dumps(rec, sort_keys=True).encode("utf-8")
        return b"%08x %s\n" % (zlib.crc32(body), body)

    def append(self, kind: str, **fields: Any) -> None:
        """Append one record and make it durable (flush + fsync)
        before returning — callers ACK only after this returns."""
        self.append_many([(kind, fields)])

    def append_many(self, records) -> int:
        """Append ``[(kind, fields), ...]`` as one write + ONE fsync —
        a batch submit's N accept records cost a single durability
        sync, ACKed only after the last record is on disk. Returns the
        record count."""
        lines = [self._frame(kind, fields) for kind, fields in records]
        if not lines:
            return 0
        with self._lock:
            if self._fh.closed:
                raise ValueError("AdmissionWAL is closed")
            self._fh.write(b"".join(lines))
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self.n_appended += len(lines)
        return len(lines)

    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    def __enter__(self) -> "AdmissionWAL":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- read ----

    def _scan(self) -> WALState:
        return scan_wal(self.path)

    @staticmethod
    def _parse(line: bytes) -> Optional[Dict[str, Any]]:
        if len(line) < 10 or line[8:9] != b" ":
            return None
        crc_hex, body = line[:8], line[9:]
        try:
            if int(crc_hex, 16) != zlib.crc32(body):
                return None
            rec = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None
        return rec if isinstance(rec, dict) and "kind" in rec else None

    @staticmethod
    def _fold(state: WALState, rec: Dict[str, Any]) -> None:
        state.records.append(rec)
        kind = rec.get("kind")
        tid = rec.get("tenant_id")
        if kind == "accept" and tid is not None:
            state.pending.setdefault(str(tid), rec)
            key = rec.get("idempotency_key")
            if key:
                state.idempotency.setdefault(str(key), str(tid))
        elif kind == "done" and tid is not None:
            state.pending.pop(str(tid), None)
        elif kind == "offer" and tid is not None:
            # intent only: the tenant STAYS pending here — ownership
            # moves when `transferred` lands (or, after a crash, when
            # the resolution rule finds the target's durable adoption)
            state.offers[str(tid)] = rec
        elif kind == "adopted" and tid is not None:
            # the target's side: folds like an accept (this log now
            # owns the tenant) and is indexed by offer id as the
            # durable adoption proof
            state.pending.setdefault(str(tid), rec)
            key = rec.get("idempotency_key")
            if key:
                state.idempotency.setdefault(str(key), str(tid))
            oid = rec.get("offer_id")
            if oid:
                state.adoptions[str(oid)] = rec
        elif kind == "transferred" and tid is not None:
            state.pending.pop(str(tid), None)
            state.offers.pop(str(tid), None)

    def replay(self) -> WALState:
        """The fold of the log as it stood at open time (the
        constructor already healed any torn tail)."""
        return self._state


def scan_wal(path: str) -> WALState:
    """Read-only fold of a WAL file — **no healing**. The migration
    resolution rule reads a *peer's* log with this (is the adoption
    durable over there?); truncating another process's possibly-live
    torn tail would be a corruption, so only the owning
    :class:`AdmissionWAL` constructor ever heals."""
    state = WALState()
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except (FileNotFoundError, OSError):
        return state
    offset = 0
    for raw in data.split(b"\n"):
        terminated = offset + len(raw) < len(data)
        line = raw.strip()
        if line:
            rec = AdmissionWAL._parse(line)
            if rec is None:
                # CRC/parse failure: mid-file damage is skipped
                # (same policy as read_journal); an unterminated
                # final line is the torn tail — by the
                # fsync-before-ACK contract it was never ACKed,
                # so dropping it loses nothing
                if not terminated:
                    state.tear_offset = offset
            else:
                AdmissionWAL._fold(state, rec)
        offset += len(raw) + 1
    return state
