"""Device-resident batched GP generations + island epochs — the run
axis for the two host-driven families (ROADMAP item 2).

The four scan families ride :class:`deap_tpu.serving.MultiRunEngine`
because their whole run is already one ``lax.scan``. The GP
host-dispatch loop (:mod:`deap_tpu.gp.loop`) and the island epoch
driver could not be batched that way: the GP loop round-trips through
the host every generation (live-vocab masks, index compaction,
dispatch), and islands are driven one ``fold_in(key, epoch)`` step at a
time. This module closes the gap with two engines that speak the same
lane/batch/segment protocol the scheduler already serves:

- :class:`GpMultiRunEngine` — N independent GP runs through ONE jitted
  ``lax.scan``. The per-generation program is the *same* variation
  machinery the solo loop dispatches (:func:`gp.loop.make_gp_step_parts`
  — shared closures, not copies), vmapped over a leading run axis, with
  the compacted invalid-only evaluation replaced by a full-width
  where-select (duplicated work, zero host round trips — the waste
  model in docs/advanced/gp_interpreter.md). Live-vocab specialization
  survives batching through a **union-mask fixpoint**: the engine
  carries one monotone opcode mask covering every lane; a ``presence``
  bitvector accumulated on device over the segment records which
  opcodes the post-variation populations actually contained, and a
  segment whose presence escapes the mask is *replayed* from the
  retained input batch under the grown mask. Masks only grow, so total
  replays over an engine's lifetime are bounded by ``n_ops`` — the same
  lattice bound the solo dispatcher journals.
- :class:`IslandMultiRunEngine` — N island runs, each lane the exact
  solo :func:`deap_tpu.parallel.make_island_step` program (built inside
  the lane trace so per-lane cxpb/mutpb enter as tracers), keyed
  ``fold_in(base_key, epoch)`` exactly as the solo epoch driver does.

Correctness contract — **per-lane bit-identity to the solo drivers**
(populations, depth arrays, fitness, best individual, nevals), pinned
by ``tests/test_gp_serving.py`` across mixed-ngen / typed / ERC-heavy /
ADF lanes. The construction: per-lane base key + ``fold_in(key, gen)``
is stateless in the generation index (the solo loops' own property),
the vmapped step IS the solo step, full-width variation selected by the
same Bernoulli draws computes byte-identical offspring (crossover keys
derive from the pair id, mutation keys from the row id — duplicates
and non-drawn rows are ``where``-discarded, and everything outside the
evaluator is integer/gather/PRNG arithmetic), and a finished lane's
state latches into a shadow carry (the PR 7 masked-stepping scheme —
see :meth:`MultiRunEngine._segment` for why the mask must hang off the
recurrence rather than feed back into it).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deap_tpu import algorithms as algos
from deap_tpu.core.population import Population
from deap_tpu.gp.interpreter import _cached_factory, _traced_batch, _used_ops
from deap_tpu.gp.loop import make_gp_step_parts
from deap_tpu.gp.pset import PrimitiveSet
from deap_tpu.parallel.island import make_island_step
from deap_tpu.serving.multirun import (MultiRunEngine, _tree_index,
                                       _tree_stack, _tree_where)
from deap_tpu.support.checkpoint import _key_impl_name

__all__ = ["GpJobSpec", "GpMultiRunEngine", "IslandJobSpec",
           "IslandMultiRunEngine"]


@dataclasses.dataclass(frozen=True, eq=False)
class GpJobSpec:
    """Everything program-relevant about one GP serving bucket.

    Two evaluation modes:

    - **symbreg** (``evaluate=None``): negative-MSE fitness of each
      genome on ``(X, y)`` through the mask-specialized traced
      interpreter — the batched counterpart of
      :func:`deap_tpu.gp.loop.make_symbreg_loop` (whose grouped+dedup
      dispatch is bit-identical per row, pinned by
      tests/test_gp_dispatch.py).
    - **custom** (``evaluate`` given): ``evaluate(genomes) ->
      f32[rows]`` over a flattened row batch, trace-safe, and
      **row-independent** (each row's fitness must not depend on the
      other rows — the property that makes full-width in-scan
      evaluation bit-equal to the solo loop's touched-rows-only
      dispatch). It must also be **bit-stable under jit**: the solo
      loop calls it eagerly, the batch calls it inside a traced scan,
      so an evaluator that re-specializes on concrete inputs (e.g. a
      mask-specialized interpreter) breaks bit-identity — wrap those
      as ``specialize="none"`` instead. Mask specialization is bypassed (the engine cannot
      see inside a black-box evaluator), so no replay loop runs. This
      is how ADF-flavoured or typed losses ride the batch.
    """

    pset: PrimitiveSet
    max_len: int
    X: Any = None
    y: Any = None
    tournsize: int = 3
    height_limit: int = 17
    mut_min: int = 0
    mut_max: int = 2
    mut_width: Optional[int] = None
    evaluate: Optional[Callable] = None
    name: str = "symbreg"

    def __post_init__(self):
        if self.evaluate is None and (self.X is None or self.y is None):
            raise ValueError("GpJobSpec needs X= and y= (symbreg mode) "
                             "or a custom evaluate=")

    def static_key(self) -> Tuple:
        """The shape/program-static tuple that joins the bucket key."""
        return (self.name, int(self.max_len), int(self.tournsize),
                int(self.height_limit), int(self.mut_min),
                int(self.mut_max),
                None if self.mut_width is None else int(self.mut_width),
                self.pset.n_ops, self.pset.vocab,
                self.evaluate is not None)

    def fingerprint(self) -> str:
        """Content digest over the primitive roster, the loop statics
        and the dataset — the GP analogue of ``toolbox_fingerprint``
        for :func:`deap_tpu.serving.tenant.bucket_key`."""
        h = hashlib.sha1()
        for p in self.pset.primitives:
            h.update(f"{p.name}/{p.arity};".encode())
        h.update(repr((self.pset.n_args, self.pset.n_consts,
                       self.pset.has_erc, self.pset.vocab)
                      + self.static_key()).encode())
        if self.evaluate is not None:
            h.update(repr(getattr(self.evaluate, "__qualname__",
                                  repr(self.evaluate))).encode())
        if self.X is not None:
            h.update(np.ascontiguousarray(
                np.asarray(self.X, np.float32)).tobytes())
            h.update(np.ascontiguousarray(
                np.asarray(self.y, np.float32)).tobytes())
        return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class IslandJobSpec:
    """One island serving bucket's topology — everything that shapes
    the epoch program besides the toolbox (which rides the Job)."""

    n_islands: int
    island_size: int
    freq: int
    mig_k: int

    def static_key(self) -> Tuple:
        return (int(self.n_islands), int(self.island_size),
                int(self.freq), int(self.mig_k))


def _bwhere(m, a, b):
    """``jnp.where`` with a leading-axes mask broadcast against the
    value ranks (pair/row masks vs genome leaves)."""
    return jnp.where(m.reshape(m.shape + (1,) * (a.ndim - m.ndim)), a, b)


class _RunAxisEngine(MultiRunEngine):
    """Shared plumbing for the two fold_in-keyed engines: lanes carry
    their raw BASE key data (the solo drivers re-derive per-step keys
    as ``fold_in(key, gen)``, stateless in the generation index) rather
    than the scan families' pre-split key horizon, so :meth:`pack`
    ignores the bucket horizon and :meth:`unpack` never trims keys.
    Inherits the segment-boundary decode helpers (``_lane_rows``,
    ``lane_records``, ``lane_meter_rows``, ``concat_records``) and
    :meth:`done` from :class:`MultiRunEngine` unchanged."""

    #: filled by subclasses
    hyper_names: Tuple[str, ...] = ("cxpb", "mutpb")

    def _init_common(self, family: str, toolbox, telemetry) -> None:
        self.family = family
        self.toolbox = toolbox
        self.mu = self.lambda_ = None
        self.stats = None
        self.tel = telemetry
        self.probes = ()
        self.halloffame_size = 0
        self._key_impl: Optional[str] = None
        if self.tel is not None and getattr(self.tel, "stream", False):
            raise ValueError(
                "multirun: telemetry stream=True is unsupported "
                "(per-lane debug callbacks interleave); decode rows at "
                "segment boundaries instead")

    # ------------------------------------------------------- validation ----

    def _check_hyper(self, hyper) -> Dict[str, jnp.ndarray]:
        hyper = dict(hyper or {})
        missing = [h for h in self.hyper_names if h not in hyper]
        if missing:
            raise ValueError(f"{self.family} lane needs hyper {missing}")
        extra = [h for h in hyper if h not in self.hyper_names]
        if extra:
            raise ValueError(f"{self.family} takes no hyper {extra}")
        return {h: jnp.float32(hyper[h]) for h in self.hyper_names}

    def _check_key(self, key) -> None:
        impl = _key_impl_name(key)
        if self._key_impl is None:
            self._key_impl = impl
        elif impl != self._key_impl:
            raise ValueError(f"lane key impl {impl!r} != bucket impl "
                             f"{self._key_impl!r}")

    # ------------------------------------------------------ pack/unpack ----

    def pack(self, lanes: Sequence[Dict[str, Any]], n_lanes: int,
             horizon: int) -> Dict[str, Any]:
        """Stack lane states into ``n_lanes`` slots. ``horizon`` is
        accepted for scheduler-protocol compatibility and ignored —
        these lanes carry one base key each, not a per-generation key
        array, so there is nothing to pad to a horizon."""
        if not lanes:
            raise ValueError("pack needs at least one lane")
        if len(lanes) > n_lanes:
            raise ValueError(f"{len(lanes)} lanes > {n_lanes} slots")
        lanes = [self._on_pack_lane(lane) for lane in lanes]
        dummy = {**lanes[0], "gen": jnp.int32(0), "ngen": jnp.int32(0)}
        padded = list(lanes) + [dummy] * (n_lanes - len(lanes))
        stacked = _tree_stack(padded)
        batch = {"carry": stacked["carry"], "shadow": stacked["carry"],
                 "gen": stacked["gen"], "ngen": stacked["ngen"],
                 "keys": stacked["keys"], "hyper": stacked["hyper"],
                 "record0": stacked["record0"],
                 "mstate0": stacked["mstate0"], "n_real": len(lanes)}
        return self._finish_batch(batch, n_lanes)

    def _on_pack_lane(self, lane: Dict[str, Any]) -> Dict[str, Any]:
        return lane

    def _finish_batch(self, batch: Dict[str, Any],
                      n_lanes: int) -> Dict[str, Any]:
        return batch

    def unpack(self, batch: Dict[str, Any], i: int) -> Dict[str, Any]:
        """Lane ``i`` back out — carry read from the SHADOW (the frozen
        completion state of a finished lane); the base key needs no
        horizon trim."""
        lane = {k: _tree_index(batch[k], i)
                for k in ("gen", "ngen", "keys", "hyper", "record0",
                          "mstate0")}
        lane["carry"] = _tree_index(batch["shadow"], i)
        return lane

    def advance(self, batch: Dict[str, Any], k: int):
        return self._advance(batch, k=int(k))


# ------------------------------------------------------------------- GP ----


class GpMultiRunEngine(_RunAxisEngine):
    """N GP runs through one jitted scan, bit-identical per lane to the
    solo host-dispatch loop (:func:`deap_tpu.gp.loop.make_gp_loop`).

    Lifecycle mirrors :class:`MultiRunEngine`::

        eng = GpMultiRunEngine(spec)            # spec: GpJobSpec
        lanes = [eng.lane_init(key_r, genomes_r, ngen_r,
                               {"cxpb": .5, "mutpb": .1}) for ...]
        batch = eng.pack(lanes, n_lanes=8, horizon=64)
        batch, seg = eng.advance(batch, k=10)
        result = eng.lane_result(eng.unpack(batch, i),
                                 eng.lane_records([seg], i))

    ``lane_result`` returns the solo loop's finalize dict (genomes /
    depths / fitness / best_genome / best_fitness / nevals /
    stopped_at).

    **Union-mask fixpoint** (symbreg mode): every lane's evaluation
    runs under ONE opcode mask — the monotone union of every opcode the
    engine has ever seen. Mutation donors can introduce any opcode mid
    segment, so the segment accumulates a ``presence`` bitvector on
    device (post-variation genomes of ACTIVE lanes only) and
    :meth:`advance` re-runs the segment from the retained input batch
    whenever presence escaped the mask. A trajectory accepted under a
    covering mask never evaluated an out-of-mask opcode, hence is
    bit-exact to the full-vocabulary program (which per row equals the
    solo loop's grouped+dedup dispatch — tests/test_gp_dispatch.py);
    mask growth is monotone, so lifetime replays are bounded by
    ``n_ops``, journaled as ``gp_dispatch``/``gp_interpreter_build``
    events carrying ``n_lanes`` and ``mask_popcount``.
    """

    def __init__(self, spec: GpJobSpec, *, telemetry=None, probes=(),
                 stats=None, halloffame_size: int = 0):
        if probes:
            raise ValueError("GP batched lanes take no probes= (probe "
                             "context needs a Population; GP lanes "
                             "carry raw genome tensors)")
        if stats is not None or halloffame_size:
            raise ValueError("GP lanes carry their own best-individual "
                             "tracking; stats=/halloffame_size= do not "
                             "apply")
        self._init_common("gp", None, telemetry)
        self.spec = spec
        self.gen_offset = 1
        self._parts = make_gp_step_parts(
            spec.pset, spec.max_len, tournsize=spec.tournsize,
            height_limit=spec.height_limit, mut_min=spec.mut_min,
            mut_max=spec.mut_max, mut_width=spec.mut_width)
        self._track = spec.evaluate is None
        self._n_ops = spec.pset.n_ops
        self._mask: Tuple[int, ...] = ()
        self._n: Optional[int] = None
        self._n_lanes = 0
        self._seg_cache: Dict[Any, Callable] = {}
        self._fresh_cache: Dict[Any, Callable] = {}
        self._journaled: Any = None
        if self._track:
            self._X = jnp.asarray(spec.X, jnp.float32)
            self._y = jnp.asarray(spec.y, jnp.float32)
        if self.tel is not None:
            self.tel.begin_run("multirun/gp", None,
                               declare=algos._tel_declare, serving=True)

    # ---------------------------------------------------- mask plumbing ----

    def _mask_key(self):
        return self._mask if self._track else None

    def _grow_mask(self, used: Sequence[int]) -> None:
        if not self._track:
            return
        new = tuple(sorted(set(self._mask) | set(int(u) for u in used)))
        if new != self._mask:
            self._mask = new
        self._journal_dispatch()

    def _journal_dispatch(self) -> None:
        """``gp_dispatch`` with the batching dimensions (satellite: the
        mask-lattice rebuild budget stays auditable under a run axis).
        Tag-deduplicated like the solo dispatcher's journal."""
        if not self._track:
            return
        tag = (self._mask, self._n_lanes)
        if self._journaled == tag:
            return
        self._journaled = tag
        from deap_tpu.telemetry.journal import broadcast
        broadcast("gp_dispatch", mode="batched",
                  mask=[self.spec.pset.primitives[i].name
                        for i in self._mask],
                  mask_popcount=len(self._mask),
                  n_lanes=self._n_lanes)

    def _eval_rows_for(self, mask) -> Callable:
        """``f(flat_genomes) -> f32[rows]`` under ``mask`` — the traced
        evaluator every lane's rows flatten into (one population-level
        ``max(length)`` bound, which must stay unbatched, is why the
        eval sits OUTSIDE the lane vmap)."""
        spec = self.spec
        if spec.evaluate is not None:
            return spec.evaluate
        interp = _cached_factory(
            spec.pset, ("gpserve", spec.max_len, mask),
            lambda: _traced_batch(spec.pset, spec.max_len, "scan", mask),
            extra={"n_lanes": self._n_lanes,
                   "mask_popcount": len(mask)})
        X, y = self._X, self._y

        def eval_rows(genomes):
            preds = interp(genomes, X)
            return -jnp.mean((preds - y[None, :]) ** 2, axis=1)

        return eval_rows

    # -------------------------------------------------------- admission ----

    def _learn_n(self, genomes) -> int:
        n = int(np.asarray(genomes["length"]).shape[-1])
        if self._n is None:
            self._n = n
        elif n != self._n:
            raise ValueError(f"lane population size {n} != bucket "
                             f"size {self._n}")
        return n

    def _fresh_fn(self, mask) -> Callable:
        """Jitted vectorized gen-0: founder depths + fitness + best for
        a whole ``[R, n, ...]`` admission batch in one program."""
        fn = self._fresh_cache.get(mask)
        if fn is not None:
            return fn
        parts, tel = self._parts, self.tel
        eval_rows = self._eval_rows_for(mask)

        def fresh(genomes):
            R, n = genomes["length"].shape
            depths = jax.vmap(jax.vmap(parts.depths))(genomes)
            flat = jax.tree_util.tree_map(
                lambda a: a.reshape((R * n,) + a.shape[2:]), genomes)
            fit = eval_rows(flat).reshape(R, n)

            def one(g_r, d_r, f_r):
                bi = jnp.argmax(f_r)
                out = {"genomes": g_r, "depths": d_r, "fit": f_r,
                       "best_genome": jax.tree_util.tree_map(
                           lambda a: a[bi], g_r),
                       "best_fit": f_r[bi]}
                if tel is not None:
                    m = tel.meter
                    ms = m.inc(m.init(), "nevals", n)
                    ms = m.set(ms, "best", jnp.max(f_r))
                    ms = m.set(ms, "mean", jnp.mean(f_r))
                    ms = m.set(ms, "evaluated_frac", 1.0)
                    out["mstate"] = ms
                return out

            return jax.vmap(one)(genomes, depths, fit)

        fn = jax.jit(fresh)
        self._fresh_cache[mask] = fn
        return fn

    def lane_init(self, key, init, ngen: int,
                  hyper: Optional[Dict[str, float]] = None
                  ) -> Dict[str, Any]:
        """One lane from a solo job spec: ``init`` is the founder
        genome batch ``{"nodes": [n, ML], "consts": [n, ML],
        "length": [n]}``. Runs the solo loop's gen-0 protocol (founder
        evaluation, best seeding) and returns the checkpointable lane
        dict — the scheduler's swap unit."""
        ngen = int(ngen)
        if ngen < 1:
            raise ValueError("ngen must be >= 1")
        hyper_arr = self._check_hyper(hyper)
        self._check_key(key)
        n = self._learn_n(init)
        if self._track:
            self._grow_mask(_used_ops(self._n_ops,
                                      np.asarray(init["nodes"]),
                                      np.asarray(init["length"])))
        c = self._fresh_fn(self._mask_key())(
            jax.tree_util.tree_map(lambda a: jnp.asarray(a)[None], init))
        carry = _tree_index(c, 0)
        lane: Dict[str, Any] = {
            "gen": jnp.int32(0), "ngen": jnp.int32(ngen),
            "keys": jax.random.key_data(key), "hyper": hyper_arr,
            "record0": {"nevals": jnp.int32(n)},
            "mstate0": carry.get("mstate"),
        }
        lane["carry"] = carry
        return lane

    def pack_fresh(self, keys, inits, ngen: int,
                   hyper: Optional[Dict[str, Any]] = None,
                   *, n_lanes: Optional[int] = None,
                   horizon: Optional[int] = None) -> Dict[str, Any]:
        """Vectorized admission: the gen-0 protocol of a whole batch of
        FRESH same-``ngen`` jobs as ONE jitted program (founder depths,
        flattened founder evaluation, per-lane best) — O(1) host round
        trips however many tenants arrive. Bit-identical per lane to
        the lane-at-a-time path."""
        ngen = int(ngen)
        if ngen < 1:
            raise ValueError("ngen must be >= 1")
        if isinstance(keys, (list, tuple)):
            keys = jnp.stack(keys)
        R = int(keys.shape[0])
        n_lanes = R if n_lanes is None else int(n_lanes)
        if R > n_lanes:
            raise ValueError("batch exceeds n_lanes")
        self._check_key(keys)
        if isinstance(inits, (list, tuple)):
            inits = _tree_stack(inits)
        self._learn_n(_tree_index(inits, 0))
        self._n_lanes = max(self._n_lanes, n_lanes)
        if self._track:
            self._grow_mask(_used_ops(
                self._n_ops,
                np.asarray(inits["nodes"]).reshape(
                    -1, inits["nodes"].shape[-1]),
                np.asarray(inits["length"]).reshape(-1)))
        carry = self._fresh_fn(self._mask_key())(inits)
        hyper = dict(hyper or {})
        missing = [h for h in self.hyper_names if h not in hyper]
        if missing:
            raise ValueError(f"{self.family} needs hyper {missing}")
        hyper_arr = {
            h: jnp.broadcast_to(jnp.asarray(hyper[h], jnp.float32), (R,))
            for h in self.hyper_names}
        batch = {"carry": carry, "shadow": carry,
                 "gen": jnp.zeros(R, jnp.int32),
                 "ngen": jnp.full(R, ngen, jnp.int32),
                 "keys": jax.vmap(jax.random.key_data)(keys),
                 "hyper": hyper_arr,
                 "record0": {"nevals": jnp.full(R, self._n, jnp.int32)},
                 "mstate0": carry.get("mstate"), "n_real": R}
        if n_lanes > R:
            grow = lambda a: jnp.concatenate(
                [a, jnp.broadcast_to(a[:1],
                                     (n_lanes - R,) + a.shape[1:])])
            for k in ("carry", "shadow", "gen", "keys", "hyper",
                      "record0", "mstate0"):
                batch[k] = jax.tree_util.tree_map(grow, batch[k])
            batch["ngen"] = jnp.concatenate(
                [batch["ngen"], jnp.zeros(n_lanes - R, jnp.int32)])
        return self._finish_batch(batch, n_lanes)

    def _on_pack_lane(self, lane: Dict[str, Any]) -> Dict[str, Any]:
        # a checkpoint-restored lane may carry opcodes this (fresh)
        # engine's mask has never seen — grow from the concrete carry
        # before the batch compiles, exactly once per repack
        if self._track:
            g = lane["carry"]["genomes"]
            self._grow_mask(_used_ops(self._n_ops, np.asarray(g["nodes"]),
                                      np.asarray(g["length"])))
            self._learn_n(g)
        return lane

    def _finish_batch(self, batch: Dict[str, Any],
                      n_lanes: int) -> Dict[str, Any]:
        self._n_lanes = max(self._n_lanes, n_lanes)
        presence = np.zeros(self._n_ops + 1, bool)
        if self._track and self._mask:
            presence[list(self._mask)] = True
        batch["presence"] = jnp.asarray(presence)
        self._journal_dispatch()
        return batch

    # ---------------------------------------------------------- segment ----

    def _segment_for(self, mask) -> Callable:
        fn = self._seg_cache.get(mask)
        if fn is not None:
            return fn
        from deap_tpu.telemetry import costs
        fn = costs.instrument(
            jax.jit(self._build_segment(mask), static_argnames=("k",)),
            label="serving/gp/advance", static_argnames=("k",))
        self._seg_cache[mask] = fn
        return fn

    def _build_segment(self, mask) -> Callable:
        parts, tel = self._parts, self.tel
        n_ops, track = self._n_ops, self._track
        eval_rows = self._eval_rows_for(mask)
        impl = self._key_impl

        def lane_pre(kd, gen_r, hyper_r, lc):
            """Select + full-width vary for one lane — the solo loop's
            exact key schedule (advance(): gen+1, fold_in, select,
            draw, pair-id cx keys, row-id mut keys on post-cx rows)."""
            key = jax.random.wrap_key_data(kd, impl=impl)
            k = jax.random.fold_in(key, gen_r + 1)
            k_sel, k_var = jax.random.split(k)
            idx = parts.select_idx(k_sel, lc["fit"])
            genomes = jax.tree_util.tree_map(lambda a: a[idx],
                                             lc["genomes"])
            depths = lc["depths"][idx]
            fit = lc["fit"][idx]
            n = fit.shape[0]
            k_draw, k_cx, k_mut = jax.random.split(k_var, 3)
            k_pair, k_ind = jax.random.split(k_draw)
            do_cx = jax.random.bernoulli(k_pair, hyper_r["cxpb"],
                                         (n // 2,))
            do_mut = jax.random.bernoulli(k_ind, hyper_r["mutpb"], (n,))
            touched = do_mut
            if n // 2:
                pp = jnp.arange(n // 2)
                rows_e, rows_o = pp * 2, pp * 2 + 1
                ck = jax.vmap(lambda i: jax.random.fold_in(k_cx, i))(pp)
                g_e = jax.tree_util.tree_map(lambda a: a[rows_e], genomes)
                g_o = jax.tree_util.tree_map(lambda a: a[rows_o], genomes)
                c1, dd1, c2, dd2 = jax.vmap(parts.pair_cx)(
                    ck, g_e, depths[rows_e], g_o, depths[rows_o])
                # non-drawn pairs where-revert to their parents: the
                # drawn rows' offspring are byte-identical to the solo
                # compacted dispatch (same fold_in(k_cx, pair) keys)
                c1 = jax.tree_util.tree_map(
                    lambda a, b: _bwhere(do_cx, a, b), c1, g_e)
                c2 = jax.tree_util.tree_map(
                    lambda a, b: _bwhere(do_cx, a, b), c2, g_o)
                dd1 = _bwhere(do_cx, dd1, depths[rows_e])
                dd2 = _bwhere(do_cx, dd2, depths[rows_o])
                genomes = jax.tree_util.tree_map(
                    lambda a, s1, s2: a.at[rows_e].set(s1)
                                       .at[rows_o].set(s2),
                    genomes, c1, c2)
                depths = depths.at[rows_e].set(dd1).at[rows_o].set(dd2)
                touched = touched | jnp.zeros(n, bool) \
                    .at[: 2 * (n // 2)].set(jnp.repeat(do_cx, 2))
            mk = jax.vmap(lambda i: jax.random.fold_in(k_mut, i))(
                jnp.arange(n))
            m_g, m_d = jax.vmap(parts.one_mut)(mk, genomes, depths)
            genomes = jax.tree_util.tree_map(
                lambda a, s: _bwhere(do_mut, s, a), genomes, m_g)
            depths = _bwhere(do_mut, m_d, depths)
            return genomes, depths, fit, touched

        def lane_post(lc, genomes_r, depths_r, fit_r, ne_r):
            n = fit_r.shape[0]
            bi = jnp.argmax(fit_r)
            better = fit_r[bi] > lc["best_fit"]
            out = {"genomes": genomes_r, "depths": depths_r,
                   "fit": fit_r,
                   "best_genome": jax.tree_util.tree_map(
                       lambda a, b: jnp.where(better, a[bi], b),
                       genomes_r, lc["best_genome"]),
                   "best_fit": jnp.where(better, fit_r[bi],
                                         lc["best_fit"])}
            if tel is not None:
                m = tel.meter
                ms = m.inc(lc["mstate"], "nevals", ne_r)
                ms = m.set(ms, "best", jnp.max(fit_r))
                ms = m.set(ms, "mean", jnp.mean(fit_r))
                ms = m.set(ms, "evaluated_frac", ne_r / n)
                out["mstate"] = ms
            return out

        def segment(batch, k: int):
            keys, ngen, hyper = (batch["keys"], batch["ngen"],
                                 batch["hyper"])

            def body(carry_t, _):
                lane_carry, shadow, gen, presence = carry_t
                active = gen < ngen
                genomes, depths, fit_sel, touched = jax.vmap(lane_pre)(
                    keys, gen, hyper, lane_carry)
                R, n = touched.shape
                flat = jax.tree_util.tree_map(
                    lambda a: a.reshape((R * n,) + a.shape[2:]), genomes)
                # ONE flattened eval for every lane's full population —
                # max(length) is a population reduction and must stay
                # unbatched; untouched rows where-revert below, so the
                # redundant flops never reach a result (the waste
                # model: full-width eval buys zero per-gen host syncs)
                w = eval_rows(flat).reshape(R, n)
                fit = jnp.where(touched, w, fit_sel)
                ne = jnp.sum(touched, axis=1).astype(jnp.int32)
                lane_carry = jax.vmap(lane_post)(
                    lane_carry, genomes, depths, fit, ne)
                if track:
                    live = (jnp.arange(flat["nodes"].shape[1])[None, :]
                            < flat["length"][:, None]) \
                        & (flat["nodes"] < n_ops) \
                        & jnp.repeat(active, n)[:, None]
                    ids = jnp.where(live, flat["nodes"], n_ops)
                    presence = presence.at[ids.ravel()].max(
                        jnp.ones(ids.size, bool))
                shadow = jax.vmap(_tree_where)(active, lane_carry,
                                               shadow)
                ys = (({"nevals": ne}, lane_carry["mstate"])
                      if tel is not None else {"nevals": ne})
                return ((lane_carry, shadow,
                         gen + active.astype(gen.dtype), presence),
                        (ys, active))

            (lane_carry, shadow, gen, presence), (ys, active) = lax.scan(
                body, (batch["carry"], batch["shadow"], batch["gen"],
                       batch["presence"]), None, length=k)
            return ({**batch, "carry": lane_carry, "shadow": shadow,
                     "gen": gen, "presence": presence},
                    {"ys": ys, "active": active})

        return segment

    def advance(self, batch: Dict[str, Any], k: int):
        """One segment of ``k`` generations — with the union-mask
        fixpoint replay: run under the current mask from the RETAINED
        input batch, host-read the presence bitvector, and replay under
        the grown mask whenever a mutation donor escaped it. Each
        rejection strictly grows the (monotone) mask, so the loop — and
        the engine's lifetime replay count — is bounded by ``n_ops``."""
        k = int(k)
        if not self._track:
            return self._segment_for(None)(batch, k=k)
        for _ in range(self._n_ops + 1):
            out, seg = self._segment_for(self._mask)(batch, k=k)
            used = np.nonzero(
                np.asarray(out["presence"])[: self._n_ops])[0]
            if set(int(u) for u in used) <= set(self._mask):
                return out, seg
            self._grow_mask(used)
        raise AssertionError(
            "union-mask fixpoint failed to converge (mask grows "
            "strictly per replay and is bounded by n_ops)")

    # ------------------------------------------------------------ decode ----

    def lane_result(self, lane: Dict[str, Any], records: Any):
        """The solo loop's finalize dict, assembled from the lane carry
        and the accumulated per-generation ``nevals`` rows — the same
        keys :func:`deap_tpu.gp.loop.make_gp_loop`'s ``run`` returns."""
        carry = lane["carry"]
        nevals = [int(np.asarray(lane["record0"]["nevals"]))]
        if records is not None:
            nevals += [int(x) for x in np.asarray(records["nevals"])]
        return {"genomes": carry["genomes"], "depths": carry["depths"],
                "fitness": carry["fit"],
                "best_genome": carry["best_genome"],
                "best_fitness": float(np.asarray(carry["best_fit"])),
                "nevals": nevals, "stopped_at": None}


# -------------------------------------------------------------- islands ----


class IslandMultiRunEngine(_RunAxisEngine):
    """N island runs through one jitted scan — each lane IS the solo
    :func:`~deap_tpu.parallel.make_island_step` epoch program (built
    inside the lane trace so per-lane cxpb/mutpb enter as vmap-lane
    tracers), keyed ``fold_in(base_key, epoch)`` exactly like the solo
    epoch driver (``resilience._IslandSpec.segment``). The stacked-deme
    tensor gains a leading run axis; migration stays the deme-axis ring
    roll inside the one global program. ``lane_result`` returns the
    final stacked :class:`Population`, bit-identical to driving the
    solo step epoch by epoch."""

    def __init__(self, toolbox, spec: IslandJobSpec, *, telemetry=None,
                 probes=()):
        if probes:
            raise ValueError("island batched lanes take no probes= "
                             "(per-lane probe rows are the Meter "
                             "built-ins)")
        self._init_common("island", toolbox, telemetry)
        self.spec = spec
        self.gen_offset = 0  # epoch rows are 0-indexed, no gen-0 row
        if self.tel is not None:
            self.tel.begin_run("multirun/island", toolbox, serving=True)
            # land the meter declarations (idempotent on re-declare)
            # before any meter.init(): jit is lazy, nothing compiles
            make_island_step(toolbox, 0.5, 0.2, spec.freq, spec.mig_k,
                             telemetry=self.tel)
        from deap_tpu.telemetry import costs
        self._advance = costs.instrument(
            jax.jit(self._segment, static_argnames=("k",)),
            label="serving/island/advance", static_argnames=("k",))

    def lane_init(self, key, init, ngen: int,
                  hyper: Optional[Dict[str, float]] = None
                  ) -> Dict[str, Any]:
        """``init`` is the stacked island :class:`Population`
        (``[n_islands, island_size, ...]`` leaves, e.g. from
        :func:`~deap_tpu.parallel.island_init`); ``ngen`` counts
        epochs."""
        ngen = int(ngen)
        if ngen < 1:
            raise ValueError("ngen must be >= 1")
        hyper_arr = self._check_hyper(hyper)
        self._check_key(key)
        if not isinstance(init, Population):
            raise TypeError("island lane init must be a stacked "
                            f"Population, got {type(init).__name__}")
        shape = tuple(init.valid.shape[:2])
        want = (self.spec.n_islands, self.spec.island_size)
        if shape != want:
            raise ValueError(f"island lane shape {shape} != bucket "
                             f"topology {want}")
        carry: Dict[str, Any] = {"pops": init}
        if self.tel is not None:
            carry["mstate"] = self.tel.meter.init()
        return {"gen": jnp.int32(0), "ngen": jnp.int32(ngen),
                "keys": jax.random.key_data(key), "hyper": hyper_arr,
                "record0": None, "mstate0": None, "carry": carry}

    def pack_fresh(self, keys, inits, ngen: int,
                   hyper: Optional[Dict[str, Any]] = None,
                   *, n_lanes: Optional[int] = None,
                   horizon: Optional[int] = None) -> Dict[str, Any]:
        """Vectorized admission for same-``ngen`` island jobs: island
        gen-0 has no protocol to run (founders are evaluated inside
        the first epoch's first generation), so this is a pure stack —
        still one host dispatch for the whole batch."""
        if isinstance(keys, (list, tuple)):
            keys = list(keys)
        else:
            keys = [keys[i] for i in range(int(keys.shape[0]))]
        if isinstance(inits, (list, tuple)):
            inits = list(inits)
        else:
            inits = [_tree_index(inits, i) for i in range(len(keys))]
        lanes = [self.lane_init(k, p, ngen, hyper)
                 for k, p in zip(keys, inits)]
        return self.pack(lanes, n_lanes=n_lanes or len(lanes),
                         horizon=horizon or int(ngen))

    def _segment(self, batch: Dict[str, Any], k: int):
        keys, ngen, hyper = batch["keys"], batch["ngen"], batch["hyper"]
        spec, tb, tel = self.spec, self.toolbox, self.tel
        impl = self._key_impl

        def lane_step(kd, gen_r, hyper_r, lc):
            key = jax.random.wrap_key_data(kd, impl=impl)
            kk = jax.random.fold_in(key, gen_r)
            # the solo step factory, instantiated under the lane trace
            # so this lane's traced hyper close over it — meter
            # declarations are idempotent, jit-under-trace inlines
            step = make_island_step(tb, hyper_r["cxpb"],
                                    hyper_r["mutpb"], spec.freq,
                                    spec.mig_k, telemetry=tel)
            if tel is None:
                return {"pops": step(kk, lc["pops"])}
            pops, ms = step(kk, lc["pops"], lc["mstate"])
            return {"pops": pops, "mstate": ms}

        def body(carry_t, _):
            lane_carry, shadow, gen = carry_t
            active = gen < ngen
            lane_carry = jax.vmap(lane_step)(keys, gen, hyper,
                                             lane_carry)
            shadow = jax.vmap(_tree_where)(active, lane_carry, shadow)
            ys = (({}, lane_carry["mstate"]) if tel is not None else {})
            return ((lane_carry, shadow,
                     gen + active.astype(gen.dtype)), (ys, active))

        (lane_carry, shadow, gen), (ys, active) = lax.scan(
            body, (batch["carry"], batch["shadow"], batch["gen"]),
            None, length=k)
        return ({**batch, "carry": lane_carry, "shadow": shadow,
                 "gen": gen}, {"ys": ys, "active": active})

    def lane_result(self, lane: Dict[str, Any], records: Any):
        """The final stacked island :class:`Population` — what the solo
        epoch driver holds after its last epoch."""
        return lane["carry"]["pops"]
