"""Multi-tenant evolution serving — run axis + ask-tell scheduler.

Two planes on top of the core loops (ROADMAP item 1):

- :mod:`deap_tpu.serving.multirun` — the **vectorized multi-run
  engine**: N independent runs (distinct seeds, per-run hyperparams
  and generation budgets) advance through ONE compiled scan by
  vmapping the :mod:`deap_tpu.algorithms` step factories, with per-run
  telemetry riding the batched Meter carry and per-lane bit-identity
  to the solo loops pinned by ``tests/test_serving.py``.
- :mod:`deap_tpu.serving.scheduler` — the **ask-tell serving layer**:
  job admission into shape buckets, pow-2 lane-lattice packing so the
  compiled-shape set stays bounded (and reusable across processes via
  :func:`enable_compile_cache`), segment-cadence execution, and
  per-tenant eviction/resume with crash-consistent checkpoints as the
  swap unit.
- :mod:`deap_tpu.serving.service` — the **network service plane**:
  a stdlib HTTP/JSON front end (driver-thread queue handoff, bearer
  auth + per-token quotas, NDJSON per-segment streaming, graceful
  SIGTERM drain) with the :mod:`~deap_tpu.serving.autoscale` control
  loop closing the SLO feedback path, and the stdlib
  :mod:`~deap_tpu.serving.client`.

See ``docs/advanced/serving.md`` for the job model, the bucket
lattice, eviction semantics, the bit-identity contract and the
service wire protocol.
"""

from deap_tpu.serving.multirun import FAMILIES, MultiRunEngine, multirun
from deap_tpu.serving.gp_multirun import (
    GpJobSpec,
    GpMultiRunEngine,
    IslandJobSpec,
    IslandMultiRunEngine,
)
from deap_tpu.serving.tenant import (
    Job,
    Tenant,
    bucket_key,
    pad_pow2,
)
from deap_tpu.serving.scheduler import (
    Scheduler,
    SchedulerBusyError,
    prewarm,
)
from deap_tpu.serving.autoscale import (
    AutoscaleConfig,
    AutoscaleDecision,
    AutoscalePolicy,
)
from deap_tpu.serving.service import EvolutionService
from deap_tpu.serving.client import (
    ClientAbandoned,
    ServiceClient,
    ServiceError,
)
from deap_tpu.serving.loadgen import (
    Arrival,
    DiurnalTraffic,
    LoadgenReport,
    ParetoMixTraffic,
    PoissonTraffic,
    Schedule,
    ThunderingHerd,
    TrafficModel,
    UpgradePlan,
    replay_fidelity,
    run_schedule,
    schedule_from_journal,
)
from deap_tpu.serving.wal import AdmissionWAL, scan_wal
from deap_tpu.serving.migration import (
    MigrationError,
    adopt_orphans,
    migrate_tenant,
)
from deap_tpu.support.compilecache import enable_compile_cache

__all__ = [
    "AdmissionWAL",
    "Arrival",
    "AutoscaleConfig",
    "AutoscaleDecision",
    "AutoscalePolicy",
    "ClientAbandoned",
    "DiurnalTraffic",
    "EvolutionService",
    "FAMILIES",
    "LoadgenReport",
    "ParetoMixTraffic",
    "PoissonTraffic",
    "Schedule",
    "ThunderingHerd",
    "TrafficModel",
    "GpJobSpec",
    "GpMultiRunEngine",
    "IslandJobSpec",
    "IslandMultiRunEngine",
    "Job",
    "MigrationError",
    "MultiRunEngine",
    "Scheduler",
    "SchedulerBusyError",
    "ServiceClient",
    "ServiceError",
    "Tenant",
    "UpgradePlan",
    "adopt_orphans",
    "bucket_key",
    "enable_compile_cache",
    "migrate_tenant",
    "multirun",
    "pad_pow2",
    "prewarm",
    "replay_fidelity",
    "run_schedule",
    "scan_wal",
    "schedule_from_journal",
]
