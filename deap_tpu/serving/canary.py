"""Known-answer canary tenants — the bit-identity contract as a live
production probe (ISSUE 19).

The repo's differentiator is that identical submissions produce
byte-identical wire results (``bench.py --service`` gates on the
digest). Every check of that contract so far runs *offline* — tests,
benches, replay audits. A **canary** turns it into a live check: a
tiny fixed-seed job whose wire digest is known in advance, submitted
through the *real* front end (``POST /v1/jobs`` — auth, WAL, command
queue, scheduler, wire encode: the full production path) at a
configured cadence. Every completed canary is compared digest-for-
digest against the reference:

- match → one ``canary_ok`` journal row (and a 0.0 sample on the
  ``canary_failure`` burn-rate alert — evidence of health, not just
  absence of failure);
- mismatch → a ``canary_failed`` journal row, the HealthMonitor
  ``canary`` alarm, a ``deap_alarms_total{kind="canary"}`` increment,
  a 1.0 sample that fires the ``canary_failure`` alert within the
  same boundary (one known-answer failure IS an incident — no
  multi-sample confidence window needed), and ``/healthz`` flipping
  to ``degraded`` (503).

This is precisely the class of failure nothing else can see: a
*silent wrong answer* (bad compile cache hit, corrupted restore,
broken kernel) still journals success, still returns HTTP 200, still
leaves every latency SLO green. The
:class:`~deap_tpu.resilience.faultinject.CorruptResult` fault proves
the detection end to end, and ``bench.py --canary`` measures its
latency in segment boundaries plus the canary's steady-state overhead
at the 1k-tenant socket config.

The runner is **driver-thread-only** (called from the service's
boundary fan-out), which is what makes it deterministic and lock-free:
submission is safe from the driver thread because ``POST /v1/jobs``
never round-trips through the driver — the job is built on the
calling thread, WAL-fsynced, and enqueued with ``put_nowait`` (a full
command queue surfaces as a 429 the canary counts as a shed beat, not
a failure).

The reference digest is either precomputed (``expected_digest=``, the
strict deployment mode) or learned trust-on-first-use from the first
completed canary (the default — right for tests and single-version
runs; across upgrades, pin the digest so the canary also catches
version-to-version drift).
"""

from __future__ import annotations

import contextlib
import json
from typing import Any, Dict, Optional

from deap_tpu.telemetry import tracing

__all__ = ["CANARY_JOURNAL_KINDS", "CanarySpec", "CanaryRunner"]

#: journal kinds this module writes (rows land in the scheduler
#: journal via the service; documented in docs/advanced/telemetry.md,
#: drift-gated through SERVICE_JOURNAL_KINDS)
CANARY_JOURNAL_KINDS = ("canary_ok", "canary_failed")


class CanarySpec:
    """Configuration of the canary population.

    :param problem: registered problem name — must exist in the
        service's registry (submission 404s otherwise and counts as a
        failed beat).
    :param params: fixed params dict; together with the factory's
        fixed seed this pins the expected result bit-for-bit. Keep it
        tiny — the canary rides the production scheduler and its cost
        is the overhead ``bench.py --canary`` gates at ≤ 3%.
    :param expected_digest: the precomputed wire digest
        (``wire.pack_result(...)['digest']``); ``None`` = learn from
        the first completion (trust-on-first-use).
    :param cadence_boundaries: segment boundaries between canary
        submissions.
    :param max_in_flight: concurrent canaries (1 is right unless the
        cadence outruns the canary's own runtime).
    :param tenant_prefix: canary tenant ids are
        ``<prefix>-<n>`` — also the substring
        :class:`~deap_tpu.resilience.faultinject.CorruptResult`
        targets by default.
    """

    def __init__(self, problem: str,
                 params: Optional[Dict[str, Any]] = None, *,
                 expected_digest: Optional[str] = None,
                 cadence_boundaries: int = 20,
                 max_in_flight: int = 1,
                 tenant_prefix: str = "canary"):
        self.problem = str(problem)
        self.params = dict(params or {})
        self.expected_digest = (str(expected_digest)
                                if expected_digest else None)
        self.cadence_boundaries = max(1, int(cadence_boundaries))
        self.max_in_flight = max(1, int(max_in_flight))
        self.tenant_prefix = str(tenant_prefix)


class CanaryRunner:
    """The live canary loop, driven by the service at every segment
    boundary (driver thread only — no locks, no clocks of its own)."""

    def __init__(self, spec: CanarySpec):
        self.spec = spec
        #: the active reference digest (spec's, or learned)
        self.reference = spec.expected_digest
        self.submitted = 0
        self.ok = 0
        self.failed = 0
        self.shed = 0
        self._in_flight: Dict[str, str] = {}   # tenant id -> request id
        self._countdown = 0   # boundaries until the next submission

    # -- the boundary hook --------------------------------------------

    def on_boundary(self, service, t: float) -> None:
        """One canary beat: verdicts for completed canaries first
        (so an injected corruption is detected at the very boundary
        the canary finishes), then the cadence-gated next submission.
        ``t`` is the service-relative time fed to the alert engine."""
        self._check(service, t)
        self._maybe_submit(service)

    # -- verdicts ------------------------------------------------------

    def _check(self, service, t: float) -> None:
        for tid in list(self._in_flight):
            with service._lock:
                view = service._views.get(tid)
            if view is None:               # withdrawn (shed race)
                del self._in_flight[tid]
                continue
            if not view.done.is_set():
                continue
            rid = self._in_flight.pop(tid)
            payload = view.result_payload()
            digest = payload["digest"] if payload else None
            if digest is not None and view.status == "finished":
                if self.reference is None:
                    # trust-on-first-use: the first completion IS the
                    # known answer; journal it so the learned
                    # reference is auditable
                    self.reference = digest
                    self._ok(service, t, tid, rid, digest,
                             learned=True)
                elif digest == self.reference:
                    self._ok(service, t, tid, rid, digest)
                else:
                    self._failed(service, t, tid, rid, digest,
                                 reason="digest_mismatch")
            else:
                # a canary that cannot complete is a failure of the
                # path, not of bit-identity — same alarm, distinct
                # reason
                self._failed(service, t, tid, rid, digest,
                             reason=f"status:{view.status}")

    def _ok(self, service, t: float, tid: str, rid: str,
            digest: str, learned: bool = False) -> None:
        self.ok += 1
        row = dict(tenant_id=tid, request_id=rid, digest=digest,
                   boundary=self._boundary(service))
        if learned:
            row["learned"] = True
        service.journal.event("canary_ok", **row)
        self._observe(service, t, 0.0)

    def _failed(self, service, t: float, tid: str, rid: str,
                digest: Optional[str], reason: str) -> None:
        self.failed += 1
        service.journal.event(
            "canary_failed", tenant_id=tid, request_id=rid,
            expected=self.reference, got=digest, reason=reason,
            boundary=self._boundary(service))
        if service.health is not None:
            service.health.canary(tenant_id=tid, reason=reason,
                                  expected=self.reference,
                                  got=digest)
        service._alarm_metric("canary")
        self._observe(service, t, 1.0)

    def _observe(self, service, t: float, value: float) -> None:
        if service.alerts is not None:
            service.alerts.observe(t, "canary_fail", value)

    @staticmethod
    def _boundary(service) -> Optional[int]:
        return getattr(service.scheduler, "_boundaries", None)

    # -- submission ----------------------------------------------------

    def prime(self, service) -> None:
        """Bootstrap from the driver's *idle* loop: segment boundaries
        only happen while work runs, so a fully idle service would
        never submit its first canary. When nothing is in flight and
        the cadence countdown has expired, submit directly — the
        canary's own segments then drive the boundary cadence. (The
        countdown still only decrements at boundaries, so an idle
        service is probed when its first beat — or returning traffic —
        restarts the boundary clock, never in a busy loop.)"""
        if self._in_flight or self._countdown > 0:
            return
        self._submit(service)
        self._countdown = self.spec.cadence_boundaries

    def _maybe_submit(self, service) -> None:
        if self._countdown > 0:
            self._countdown -= 1
            return
        if len(self._in_flight) >= self.spec.max_in_flight:
            return
        self._submit(service)
        self._countdown = self.spec.cadence_boundaries

    def _submit(self, service) -> None:
        """Submit one canary through the real front end. Driver-thread
        safe: ``POST /v1/jobs`` builds + WAL-fsyncs on the calling
        thread and enqueues with ``put_nowait`` — it never waits on
        the driver. Sheds (429/503/queue-full) are counted, not
        alarmed: an overloaded service refusing its own canary is load
        shedding working as designed."""
        self.submitted += 1
        tid = f"{self.spec.tenant_prefix}-{self.submitted}"
        body = json.dumps({"problem": self.spec.problem,
                           "params": self.spec.params,
                           "tenant_id": tid}).encode()
        headers: Dict[str, str] = {}
        token = getattr(service, "_canary_token", None)
        if token:
            headers["Authorization"] = "Bearer " + token
        rid = service.next_request_id({})
        ctx = service.trace_context(rid)
        cm = (tracing.use(ctx) if ctx is not None
              else contextlib.nullcontext())
        try:
            with cm:
                code, _, _, _ = service.handle(
                    "POST", "/v1/jobs", headers, body,
                    request_id=rid)
        except Exception:
            code = 0
        if code == 200:
            self._in_flight[tid] = rid
        else:
            self.shed += 1

    # -- inspection ----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``/healthz`` detail block."""
        return {"submitted": self.submitted, "ok": self.ok,
                "failed": self.failed, "shed": self.shed,
                "in_flight": len(self._in_flight),
                "reference": self.reference}
