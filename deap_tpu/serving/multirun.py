"""Vectorized multi-run engine — N independent runs through ONE scan.

The serving data plane (ROADMAP item 1): today's loops drive exactly
one run per process, but the workload the north star describes is
millions of *small* independent jobs — evosax demonstrates that
vmapping independent ES runs through one compiled step is the natural
JAX win, and Kozax the same for GP populations (PAPERS.md). This module
adds the missing run axis on top of the PR 5 step factories
(:func:`deap_tpu.algorithms.make_ea_simple_step` and friends): N runs
with distinct seeds, per-run hyperparameters (cxpb/mutpb enter the
factories as vmap-lane tracers — probabilities feed only
bernoulli/uniform comparisons, never shapes) and per-run generation
budgets advance together through one jit-compiled ``lax.scan``.

Correctness contract — **per-lane bit-identity**: a run's batched
trajectory (population, logbook records, hall of fame, per-generation
Meter/probe rows) is bit-identical to the same job run solo through
the monolithic loop, pinned by ``tests/test_serving.py`` for the
ea_simple / (μ+λ) / (μ,λ) population families and the CMA ask-tell
family. The construction
that makes this exact rather than approximate:

- **per-run key folding** — each lane's per-generation keys are
  ``jax.random.split(base_key_r, ngen_r)``, exactly the array the solo
  loop scans; lanes store the raw ``key_data`` (uint32) padded to the
  bucket's key horizon and re-wrap per step, so no cross-run key
  arithmetic exists at all;
- **vmapped solo step** — the batched step is ``jax.vmap`` of a lane
  function that instantiates the *same* factory step the solo loop
  scans (with the lane's traced hyperparams), so each lane computes
  the solo program;
- **masked stepping** — a finished lane (``gen >= ngen``) becomes a
  no-op until the scheduler swaps it out: a *shadow* copy of the carry
  latches the lane's state on its last active step (see
  :meth:`MultiRunEngine._segment` for why the mask must hang off the
  recurrence instead of feeding back into it), so heterogeneous
  ``ngen`` in one batch costs no correctness, only the finished
  lanes' wasted flops.

The engine is deliberately host-light: :meth:`MultiRunEngine.advance`
runs one segment (k generations) on device and returns stacked
per-generation outputs plus the active mask; slicing a lane's rows out
(:meth:`lane_records` / :meth:`lane_meter_rows`) and assembling a
solo-format result (:meth:`lane_result`) happen at segment boundaries,
which are already host sync points in the scheduler
(:mod:`deap_tpu.serving.scheduler`).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deap_tpu import algorithms as algos
from deap_tpu.core.population import Population
from deap_tpu.support.checkpoint import _key_impl_name

__all__ = ["MultiRunEngine", "FAMILIES", "multirun"]

#: the scan-loop families THIS engine's run axis covers; the GP
#: host-dispatch loop and the island epoch driver ride the same
#: lane/batch/segment protocol through
#: :mod:`deap_tpu.serving.gp_multirun` ("gp" / "island" families)
FAMILIES = ("ea_simple", "ea_mu_plus_lambda", "ea_mu_comma_lambda",
            "ea_generate_update")

#: per-family hyperparameters that may vary per run (everything else —
#: mu/lambda/population shape/operators — is static per bucket)
_HYPER_NAMES = {
    "ea_simple": ("cxpb", "mutpb"),
    "ea_mu_plus_lambda": ("cxpb", "mutpb"),
    "ea_mu_comma_lambda": ("cxpb", "mutpb"),
    "ea_generate_update": (),
}


def _tree_stack(trees: Sequence[Any]) -> Any:
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def _tree_index(tree: Any, i: int) -> Any:
    return jax.tree_util.tree_map(lambda a: a[i], tree)


def _tree_where(mask, a, b):
    return algos._tree_where(jnp.asarray(mask), a, b)


class MultiRunEngine:
    """One compiled multi-run program for one *bucket* of jobs.

    A bucket fixes everything shape- or program-relevant: the loop
    ``family``, the ``toolbox`` (operators AND evaluate — tenants in a
    bucket share the problem program), population shape/dtype,
    mu/lambda, stats/probes/telemetry configuration and the hall-of-fame
    size. Per-run freedom: base PRNG key, initial population / strategy
    state values, ``ngen``, and the family's per-run hyperparameters
    (``cxpb``/``mutpb``; CMA's sigma/centroid vary through the initial
    state — see ``Strategy.initial_state(sigma=..., centroid=...)``).

    Lifecycle::

        eng = MultiRunEngine("ea_simple", toolbox, stats=stats)
        lanes = [eng.lane_init(key_r, pop_r, ngen_r,
                               {"cxpb": 0.5, "mutpb": 0.2})
                 for ...]
        batch = eng.pack(lanes, n_lanes=8, horizon=64)
        batch, seg = eng.advance(batch, k=10)      # one segment
        lane = eng.unpack(batch, i)                # swap unit
        pop, logbook, hof = eng.lane_result(
            lane, eng.lane_records([seg], i))

    ``telemetry`` may be a :class:`~deap_tpu.telemetry.RunTelemetry`;
    its Meter (built-ins + ``probes``) joins the lane carry, so the
    scan's stacked output holds *per-run* metric rows. Live streaming
    (``stream=True``) is rejected — a vmapped ``debug.callback`` would
    interleave lanes.
    """

    def __init__(self, family: str, toolbox, *, mu: Optional[int] = None,
                 lambda_: Optional[int] = None, spec=None,
                 state_template=None, stats=None, telemetry=None,
                 probes=(), halloffame_size: int = 0, fused="auto"):
        if family not in FAMILIES:
            raise ValueError(f"unknown loop family {family!r} "
                             f"(known: {FAMILIES})")
        self.family = family
        self.toolbox = toolbox
        self.mu = mu
        self.lambda_ = lambda_
        self.spec = spec
        self.stats = stats
        self.tel = telemetry
        self.probes = tuple(probes or ())
        self.halloffame_size = int(halloffame_size)
        self.fused = fused
        self.gen_offset = 0 if family == "ea_generate_update" else 1
        self.hyper_names = _HYPER_NAMES[family]
        if self.tel is not None:
            if getattr(self.tel, "stream", False):
                raise ValueError(
                    "multirun: telemetry stream=True is unsupported "
                    "(per-lane debug callbacks interleave); decode "
                    "rows at segment boundaries instead")
            self.tel.begin_run(
                f"multirun/{family}", toolbox,
                declare=algos._tel_declare, probes=self.probes,
                serving=True)
        if family == "ea_generate_update":
            if spec is None or state_template is None:
                raise ValueError(
                    "ea_generate_update needs spec= (FitnessSpec) and "
                    "state_template= (one strategy state, shape "
                    "template for λ/hof inference)")
            self.lam, self._hof0 = algos._generate_update_init(
                toolbox, state_template, spec, self.halloffame_size)
            # eigh-loop bound (ROADMAP item 1): this engine vmaps the
            # strategy update across lanes, and LAPACK eigh batches as
            # a SERIAL per-lane loop — Strategy(eigh_impl='jacobi')
            # keeps the eigendecomposition vectorised across lanes
            # (the accelerator-backend formulation; on CPU the LAPACK
            # loop measured faster at small dim — bench.py --mesh
            # commits the pair). Journal a loud hint when a
            # LAPACK-eigh CMA strategy lands in a batched bucket.
            upd = getattr(toolbox, "update", None)
            strat = getattr(getattr(upd, "func", upd), "__self__", None)
            if getattr(strat, "eigh_impl", None) == "lapack":
                from deap_tpu.telemetry.journal import broadcast
                broadcast(
                    "serving_eigh_hint", family=family,
                    dim=getattr(strat, "dim", None),
                    hint="CMA bucket uses eigh_impl='lapack': the "
                         "vmapped eigendecomposition loops per lane; "
                         "Strategy(eigh_impl='jacobi') keeps it "
                         "vectorised across lanes (the accelerator "
                         "path — on CPU at small dim the LAPACK loop "
                         "measured faster, see BENCH_MESH.json)")
        else:
            if family != "ea_simple" and (mu is None or lambda_ is None):
                raise ValueError(f"{family} needs mu= and lambda_=")
        self._key_impl: Optional[str] = None
        # one jitted segment program, cached per (lanes, horizon, k)
        # shape triple — the bucket lattice keeps that set small. The
        # costs.instrument wrapper is the AOT observability seam: with
        # a ProgramObservatory active every bucket program's
        # cost/memory analysis journals as a `program_profile` event
        from deap_tpu.telemetry import costs
        self._advance = costs.instrument(
            jax.jit(self._segment, static_argnames=("k",)),
            label=f"serving/{family}/advance",
            static_argnames=("k",))
        # jitted batch-admission programs (pack_fresh): stable function
        # identity per engine so repeated fresh admissions hit the jit
        # cache instead of re-tracing
        self._fresh_init = jax.jit(jax.vmap(self._fresh_lane0))
        self._presplit = jax.jit(
            lambda keys, ngen: jax.vmap(lambda k: jax.random.key_data(
                jax.random.split(k, ngen)))(keys),
            static_argnames=("ngen",))
        # one-dispatch lane extraction: the boundary drain unpacks
        # EVERY resident lane EVERY segment, and an eager per-leaf
        # `a[i]` costs a device round-trip per leaf (~10 dispatches
        # per lane — 2.4 s of a 200-tenant run). A single jitted
        # gather with a *dynamic* index is one dispatch per lane and
        # one compile per batch shape, bit-identical to the eager path
        self._unpack_jit = jax.jit(
            lambda sub, j: jax.tree_util.tree_map(lambda a: a[j], sub))
        # the admission-side mirror: stacking N padded lanes into a
        # batch is one fused program instead of an eager jnp.stack
        # dispatch per leaf (a 64-lane repack measured ~0.2 s eager)
        self._pack_jit = jax.jit(lambda *lanes: jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *lanes))

    # ------------------------------------------------------------ steps ----

    def _fresh_lane0(self, pop):
        """One lane's gen-0 protocol (population families) — vmapped
        and jitted by :meth:`pack_fresh`."""
        pop, hof, record0 = algos._pop_loop_init(
            pop, self.toolbox, self.halloffame_size, self.stats)
        c = (pop, hof)
        if self.tel is not None:
            c = c + (algos._tel_measure(
                self.tel, self.tel.meter.init(),
                record0["nevals"], pop, jnp.int32(0)),)
        return c, record0

    def _solo_step(self, hyper: Dict[str, Any]) -> Callable:
        """The factory step of this family for one lane's (possibly
        traced) hyperparameters — the exact program the solo loop
        scans."""
        tb, stats, tel = self.toolbox, self.stats, self.tel
        if self.family == "ea_simple":
            return algos.make_ea_simple_step(
                tb, hyper["cxpb"], hyper["mutpb"], stats, tel,
                fused=self.fused)
        if self.family == "ea_mu_plus_lambda":
            return algos.make_ea_mu_plus_lambda_step(
                tb, self.mu, self.lambda_, hyper["cxpb"],
                hyper["mutpb"], stats, tel, fused=self.fused)
        if self.family == "ea_mu_comma_lambda":
            return algos.make_ea_mu_comma_lambda_step(
                tb, self.mu, self.lambda_, hyper["cxpb"],
                hyper["mutpb"], stats, tel, fused=self.fused)
        return algos.make_ea_generate_update_step(
            tb, self.spec, self.lam, stats, tel)

    def _lane_step(self, hyper, carry, key_data, gen):
        """One generation of one lane (vmapped by the segment): the
        solo factory step with this lane's key and traced hyper."""
        key = jax.random.wrap_key_data(key_data, impl=self._key_impl)
        if self.tel is None:
            xs = key
        else:
            xs = (key, (gen + self.gen_offset).astype(jnp.int32))
        return self._solo_step(hyper)(carry, xs)

    def _segment(self, batch: Dict[str, Any], k: int):
        """k masked generations for every lane; returns the new batch
        and ``(ys, active)`` stacked ``[k, lanes, ...]``.

        Masked stepping is SHADOWED rather than fed back: the live
        carry always advances through the unmasked vmapped step (a
        finished lane just burns flops on its padded zero key), while
        a shadow copy latches each lane's carry on its last active
        step and freezes. Feeding a ``where`` back into the live
        recurrence would perturb XLA CPU's codegen of the step itself
        (fusion/FMA-contraction choices shift by 1 ulp — observed on
        the CMA covariance update), breaking the bit-identity
        contract; the shadow select hangs off the recurrence as a pure
        consumer, and per-lane results stay bit-exact — pinned by
        tests/test_serving.py for all four families."""
        keys, ngen, hyper = batch["keys"], batch["ngen"], batch["hyper"]

        def body(carry, _):
            lane_carry, shadow, gen = carry
            active = gen < ngen
            # lane r consumes split(base_r, ngen_r)[gen_r]; the clip
            # only guards the padded key rows of finished lanes
            idx = jnp.minimum(gen, keys.shape[1] - 1)
            kd = jax.vmap(lambda kr, i: kr[i])(keys, idx)
            lane_carry, ys = jax.vmap(self._lane_step)(
                hyper, lane_carry, kd, gen)
            shadow = jax.vmap(_tree_where)(active, lane_carry, shadow)
            return (lane_carry, shadow,
                    gen + active.astype(gen.dtype)), (ys, active)

        (lane_carry, shadow, gen), (ys, active) = lax.scan(
            body, (batch["carry"], batch["shadow"], batch["gen"]),
            None, length=k)
        return {**batch, "carry": lane_carry, "shadow": shadow,
                "gen": gen}, {"ys": ys, "active": active}

    # ------------------------------------------------------------ lanes ----

    def lane_init(self, key, init, ngen: int,
                  hyper: Optional[Dict[str, float]] = None
                  ) -> Dict[str, Any]:
        """Build one lane's state from a solo job spec.

        ``init`` is the founder :class:`Population` (population
        families) or the initial strategy state (ask-tell). Runs the
        exact gen-0 protocol of the solo loop (founder evaluation, hof
        seeding, gen-0 record and meter row), pre-splits the lane's
        per-generation keys, and returns the checkpointable lane dict —
        the scheduler's swap unit."""
        ngen = int(ngen)
        if ngen < 1:
            raise ValueError("ngen must be >= 1")
        hyper = dict(hyper or {})
        missing = [h for h in self.hyper_names if h not in hyper]
        if missing:
            raise ValueError(f"{self.family} lane needs hyper "
                             f"{missing}")
        extra = [h for h in hyper if h not in self.hyper_names]
        if extra:
            raise ValueError(f"{self.family} takes no hyper {extra}")
        if self.hyper_names == ("cxpb", "mutpb") and \
                self.family != "ea_simple":
            if hyper["cxpb"] + hyper["mutpb"] > 1.0:
                raise ValueError("cxpb + mutpb must be <= 1.0")
        impl = _key_impl_name(key)
        if self._key_impl is None:
            self._key_impl = impl
        elif impl != self._key_impl:
            raise ValueError(
                f"lane key impl {impl!r} != bucket impl "
                f"{self._key_impl!r}")
        keys = jax.random.key_data(jax.random.split(key, ngen))

        lane: Dict[str, Any] = {
            "gen": jnp.int32(0),
            "ngen": jnp.int32(ngen),
            "keys": keys,
            "hyper": {h: jnp.float32(hyper[h])
                      for h in self.hyper_names},
            "mstate0": None,
        }
        if self.family == "ea_generate_update":
            hof = self._hof0
            carry = (init, hof)
            if self.tel is not None:
                carry = carry + (self.tel.meter.init(),)
            lane["carry"] = carry
            lane["record0"] = None
            return lane
        if not isinstance(init, Population):
            raise TypeError(f"{self.family} lane init must be a "
                            f"Population, got {type(init).__name__}")
        pop, hof, record0 = algos._pop_loop_init(
            init, self.toolbox, self.halloffame_size, self.stats)
        carry = (pop, hof)
        if self.tel is not None:
            mstate0 = algos._tel_measure(
                self.tel, self.tel.meter.init(), record0["nevals"],
                pop, jnp.int32(0))
            carry = carry + (mstate0,)
            lane["mstate0"] = mstate0
        lane["carry"] = carry
        lane["record0"] = record0
        return lane

    def pack_fresh(self, keys, inits, ngen: int,
                   hyper: Optional[Dict[str, Any]] = None,
                   *, n_lanes: Optional[int] = None,
                   horizon: Optional[int] = None) -> Dict[str, Any]:
        """Vectorized :meth:`lane_init` + :meth:`pack` for a batch of
        FRESH same-``ngen`` jobs: the gen-0 protocol (founder
        evaluation, hof seeding, gen-0 record/meter row) runs as ONE
        vmapped program instead of R eager dispatches — how a 1k-tenant
        admission stays O(1) in host round trips. ``keys`` is a list or
        stacked typed-key array, ``inits`` a list of per-run
        Populations/states or one pytree with a leading run axis;
        ``hyper`` values may be scalars (broadcast) or per-run arrays.
        Per-lane results are bit-identical to the lane-at-a-time path
        (same key folding, same gen-0 program under vmap)."""
        ngen = int(ngen)
        if ngen < 1:
            raise ValueError("ngen must be >= 1")
        if isinstance(keys, (list, tuple)):
            keys = jnp.stack(keys)
        R = int(keys.shape[0])
        n_lanes = R if n_lanes is None else int(n_lanes)
        horizon = ngen if horizon is None else int(horizon)
        if R > n_lanes or ngen > horizon:
            raise ValueError("batch exceeds n_lanes/horizon")
        impl = _key_impl_name(keys)
        if self._key_impl is None:
            self._key_impl = impl
        if isinstance(inits, (list, tuple)):
            inits = _tree_stack(inits)
        hyper = dict(hyper or {})
        missing = [h for h in self.hyper_names if h not in hyper]
        if missing:
            raise ValueError(f"{self.family} needs hyper {missing}")
        hyper_arr = {
            h: jnp.broadcast_to(jnp.asarray(hyper[h], jnp.float32), (R,))
            for h in self.hyper_names}

        keys_data = self._presplit(keys, ngen=ngen)

        if self.family == "ea_generate_update":
            bcast = lambda a: jnp.broadcast_to(a[None], (R,) + a.shape)
            hof = jax.tree_util.tree_map(bcast, self._hof0)
            carry = (inits, hof)
            if self.tel is not None:
                carry = carry + (jax.tree_util.tree_map(
                    bcast, self.tel.meter.init()),)
            record0 = None
        else:
            carry, record0 = self._fresh_init(inits)

        mstate0 = (carry[2] if self.tel is not None
                   and self.family != "ea_generate_update" else None)
        batch = {"carry": carry, "shadow": carry,
                 "gen": jnp.zeros(R, jnp.int32),
                 "ngen": jnp.full(R, ngen, jnp.int32),
                 "keys": keys_data, "hyper": hyper_arr,
                 "record0": record0, "mstate0": mstate0, "n_real": R}
        if horizon > ngen:
            pad = jnp.zeros(
                (R, horizon - ngen) + keys_data.shape[2:],
                keys_data.dtype)
            batch["keys"] = jnp.concatenate([keys_data, pad], axis=1)
        if n_lanes > R:
            grow = lambda a: jnp.concatenate(
                [a, jnp.broadcast_to(a[:1],
                                     (n_lanes - R,) + a.shape[1:])])
            for k in ("carry", "shadow", "gen", "keys", "hyper",
                      "record0", "mstate0"):
                batch[k] = jax.tree_util.tree_map(grow, batch[k])
            batch["ngen"] = jnp.concatenate(
                [batch["ngen"], jnp.zeros(n_lanes - R, jnp.int32)])
        return batch

    def pack(self, lanes: Sequence[Dict[str, Any]], n_lanes: int,
             horizon: int) -> Dict[str, Any]:
        """Stack lane states into one batch of ``n_lanes`` slots with a
        key ``horizon`` (both lattice-padded by the scheduler so the
        compiled-shape set stays bounded). Missing slots are filled
        with an inactive clone of lane 0 (``ngen=0`` → the mask keeps
        it a no-op forever); each lane's key array is zero-padded to
        the horizon (padding rows are unreachable while active)."""
        if not lanes:
            raise ValueError("pack needs at least one lane")
        if len(lanes) > n_lanes:
            raise ValueError(f"{len(lanes)} lanes > {n_lanes} slots")
        padded = []
        for lane in lanes:
            T = int(lane["keys"].shape[0])
            if T > horizon:
                raise ValueError(
                    f"lane ngen {T} exceeds key horizon {horizon}")
            if T < horizon:
                pad = jnp.zeros((horizon - T,) + lane["keys"].shape[1:],
                                lane["keys"].dtype)
                lane = {**lane,
                        "keys": jnp.concatenate([lane["keys"], pad])}
            padded.append(lane)
        dummy = {**padded[0], "gen": jnp.int32(0),
                 "ngen": jnp.int32(0)}
        padded += [dummy] * (n_lanes - len(padded))
        stacked = self._pack_jit(*padded)
        return {"carry": stacked["carry"],
                "shadow": stacked["carry"], "gen": stacked["gen"],
                "ngen": stacked["ngen"], "keys": stacked["keys"],
                "hyper": stacked["hyper"],
                "record0": stacked["record0"],
                "mstate0": stacked["mstate0"],
                "n_real": len(lanes)}

    def unpack(self, batch: Dict[str, Any], i: int) -> Dict[str, Any]:
        """Lane ``i``'s state back out of a batch — the per-tenant swap
        unit the scheduler checkpoints. The carry is read from the
        SHADOW (== the live carry for a still-active lane; the frozen
        completion state for a finished one — see :meth:`_segment`).
        Key padding is trimmed back to the lane's own ``ngen`` so a
        resume into a different bucket horizon re-pads cleanly."""
        sub = {k: batch[k] for k in ("gen", "ngen", "keys", "hyper",
                                     "record0", "mstate0")}
        sub["carry"] = batch["shadow"]
        lane = dict(self._unpack_jit(sub, jnp.int32(i)))
        lane["keys"] = lane["keys"][: int(lane["ngen"])]
        return lane

    def advance(self, batch: Dict[str, Any], k: int
                ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Run one segment of ``k`` generations for every lane through
        the jitted scan; returns ``(batch, seg)`` where ``seg`` holds
        the stacked per-generation outputs ``ys`` ``[k, lanes, ...]``
        and the ``active`` mask ``[k, lanes]`` (host code drops the
        masked rows)."""
        return self._advance(batch, k=int(k))

    def done(self, batch: Dict[str, Any]) -> np.ndarray:
        """Host bool per slot: the lane finished its budget."""
        return np.asarray(batch["gen"]) >= np.asarray(batch["ngen"])

    # ---------------------------------------------------- result decode ----

    def _lane_rows(self, segs: Sequence[Dict[str, Any]], i: int,
                   part: int) -> Any:
        """Lane ``i``'s active generation rows of ys component ``part``
        (0 = records, 1 = meter rows), concatenated across segments as
        numpy stacked arrays (``None`` when no rows)."""
        chunks = []
        for seg in segs:
            mask = np.asarray(seg["active"])[:, i]
            if not mask.any():
                continue
            ys = seg["ys"]
            if self.tel is not None:
                ys = ys[part]
            elif part == 1:
                return None
            chunks.append(jax.tree_util.tree_map(
                lambda a: np.asarray(a)[:, i][mask], ys))
        if not chunks:
            return None
        return jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0), *chunks)

    def lane_records(self, segs: Sequence[Dict[str, Any]], i: int):
        """Lane ``i``'s logbook records (stacked over its completed
        generations) — same pytree the solo scan's ys carries."""
        return self._lane_rows(segs, i, 0)

    def lane_meter_rows(self, segs: Sequence[Dict[str, Any]], i: int,
                        lane: Optional[Dict[str, Any]] = None,
                        gen_start: int = 0) -> List[dict]:
        """Lane ``i``'s decoded per-generation Meter rows (telemetry
        engines only): the gen-0 row (from the lane's ``mstate0``, when
        given and ``gen_start == 0``) plus one row per completed
        generation — identical to the solo run's journal rows for the
        same seed. ``gen_start`` is the lane's completed-generation
        count *before* ``segs`` (the scheduler drains rows one segment
        at a time)."""
        if self.tel is None:
            return []
        rows: List[dict] = []
        if gen_start == 0 and lane is not None \
                and lane.get("mstate0") is not None:
            rows.append({"gen": 0,
                         **self.tel.meter.row(lane["mstate0"])})
        stacked = self._lane_rows(segs, i, 1)
        if stacked is not None:
            for g, row in enumerate(self.tel.meter.rows(stacked)):
                rows.append({"gen": gen_start + g + self.gen_offset,
                             **row})
        return rows

    @staticmethod
    def concat_records(chunks: Sequence[Any]):
        """Concatenate per-segment :meth:`lane_records` chunks along
        the generation axis (``None`` chunks skipped)."""
        chunks = [c for c in chunks if c is not None]
        if not chunks:
            return None
        return jax.tree_util.tree_map(
            lambda *xs: np.concatenate(xs, axis=0), *chunks)

    def lane_result(self, lane: Dict[str, Any], records: Any):
        """Assemble a lane's solo-format result from its state and its
        accumulated :meth:`lane_records` rows: ``(pop, logbook, hof)``
        for population families, ``(state, logbook, hof)`` for ask-tell
        — bit-identical to the monolithic loop's return."""
        carry = lane["carry"]
        if self.family == "ea_generate_update":
            if records is None:
                records = {"nevals": np.zeros((0,), np.int32)}
            logbook = algos._build_gu_logbook(records, self.stats)
            return carry[0], logbook, carry[1]
        if records is None:
            records = jax.tree_util.tree_map(
                lambda a: np.zeros((0,) + np.asarray(a).shape,
                                   np.asarray(a).dtype),
                lane["record0"])
        logbook = algos._build_logbook(lane["record0"], records,
                                       self.stats)
        return carry[0], logbook, carry[1]


def multirun(family: str, toolbox, keys, inits, ngen, hyper=None, *,
             segment_len: Optional[int] = None, **engine_kwargs
             ) -> List[tuple]:
    """Run N independent jobs to completion through one vectorized
    program and return each job's solo-format result.

    The convenience wrapper over :class:`MultiRunEngine` for callers
    that want the run axis without the serving scheduler (benchmarks,
    parameter sweeps, restarts-as-batch)::

        results = multirun(
            "ea_simple", toolbox,
            keys=[jax.random.key(s) for s in range(32)],
            inits=[pop] * 32, ngen=100,
            hyper=[{"cxpb": c, "mutpb": 0.2} for c in cx_grid])

    ``ngen`` and ``hyper`` broadcast (a scalar / single dict applies to
    every run). ``segment_len`` chunks the scan (default: one segment
    covering max ngen)."""
    n = len(keys)
    if len(inits) != n:
        raise ValueError("len(inits) != len(keys)")
    ngens = [int(g) for g in (ngen if isinstance(ngen, (list, tuple))
                              else [ngen] * n)]
    hypers = (hyper if isinstance(hyper, (list, tuple))
              else [hyper] * n)
    eng = MultiRunEngine(family, toolbox, **engine_kwargs)
    lanes = [eng.lane_init(k, p, g, h)
             for k, p, g, h in zip(keys, inits, ngens, hypers)]
    horizon = max(ngens)
    batch = eng.pack(lanes, n_lanes=n, horizon=horizon)
    k = int(segment_len) if segment_len else horizon
    segs = []
    while not eng.done(batch).all():
        batch, seg = eng.advance(batch, k)
        segs.append(seg)
    return [eng.lane_result(eng.unpack(batch, i),
                            eng.lane_records(segs, i))
            for i in range(n)]
