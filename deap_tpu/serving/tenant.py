"""Jobs, tenants and the shape-bucket lattice.

The serving layer's control-plane records. A :class:`Job` is what a
user submits: which loop family, which problem program (toolbox), a
seed, a generation budget and the per-run knobs. A :class:`Tenant` is
the scheduler's runtime record of one job: its status, its per-tenant
run directory (checkpoints + accumulated logbook rows), its lane state
while resident, and its health monitor.

**Bucketing.** One compiled multi-run program can only serve jobs that
share everything shape- or program-relevant, so jobs are admitted into
buckets keyed by :func:`bucket_key` — loop family, population/state
shapes and dtypes, fitness arity and weights, mu/lambda, the toolbox
program fingerprint (operators + evaluate), stats fields, probe types
and hall-of-fame size. Within a bucket, per-tenant freedom is exactly
what the engine vmaps: seed, initial values, ``ngen``, cxpb/mutpb (and,
for CMA, sigma/centroid through the initial state).

**Lattice.** Lane counts and key horizons are padded up to powers of
two (:func:`pad_pow2`) — the same bounded-shape-set trick as the GP
interpreter's chunk-count lattice — so a bucket compiles O(log)
distinct programs no matter how tenant counts and budgets churn, and a
persistent compile cache (:func:`deap_tpu.serving.enable_compile_cache`)
makes them one-time across processes.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from deap_tpu.core.population import Population
from deap_tpu.support.checkpoint import Checkpointer
from deap_tpu.telemetry.journal import toolbox_fingerprint

__all__ = ["Job", "Tenant", "bucket_key", "pad_pow2"]


def pad_pow2(n: int, cap: Optional[int] = None) -> int:
    """The smallest power of two >= ``n`` (optionally clamped to
    ``cap``) — the lane-count / key-horizon lattice."""
    if n < 1:
        raise ValueError("pad_pow2 needs n >= 1")
    p = 1
    while p < n:
        p *= 2
    if cap is not None:
        p = min(p, int(cap))
    return p


@dataclasses.dataclass
class Job:
    """One evolution job as submitted to the scheduler.

    ``init`` is the founder :class:`Population` (population families)
    or the initial strategy state (``ea_generate_update``, which also
    needs ``spec``). ``hyper`` holds the family's per-run knobs
    (``cxpb``/``mutpb``). ``program`` tags the problem program for
    bucketing; default is the toolbox fingerprint digest — override it
    when two toolboxes are built from the same factory and should
    share compiles (closures fingerprint by identity). ``health`` is a
    per-tenant :class:`~deap_tpu.telemetry.probes.HealthMonitor`
    (stateful — never share one instance across jobs); alarms journal
    under this tenant's id and ``early_stop`` frees the lane at the
    next segment boundary.
    """

    tenant_id: str
    family: str
    toolbox: Any
    key: Any
    init: Any
    ngen: int
    hyper: Dict[str, float] = dataclasses.field(default_factory=dict)
    mu: Optional[int] = None
    lambda_: Optional[int] = None
    spec: Any = None
    stats: Any = None
    probes: Tuple = ()
    halloffame_size: int = 0
    health: Any = None
    program: Optional[str] = None
    #: the X-Request-Id of the submitting HTTP request (None for
    #: in-process submits) — stamped into this tenant's
    #: ``job_submitted``/``tenant_admitted``/``tenant_finished``
    #: journal rows so one grep reconstructs the request's full path;
    #: deliberately NOT part of the bucket key
    request_id: Optional[str] = None


def _shape_sig(tree: Any) -> Tuple:
    return tuple((tuple(np.shape(leaf)), np.asarray(leaf).dtype.name)
                 for leaf in jax.tree_util.tree_leaves(tree))


def bucket_key(job: Job) -> Tuple:
    """The hashable bucket a job is admitted into: jobs with equal keys
    run through one compiled multi-run program. GP jobs carry no
    toolbox — their program identity is the ``GpJobSpec`` fingerprint
    (primitive roster + loop statics + dataset), and the spec's static
    tuple joins the shape signature so two psets with equal vocab but
    different rosters never share a mask-specialized program. Island
    jobs append their topology (n_islands/island_size/freq/mig_k) —
    the coordinates that shape the stacked-deme program."""
    if job.family == "gp":
        program = job.program
        if program is None:
            program = job.spec.fingerprint()
        shapes = (("gp",) + job.spec.static_key(), _shape_sig(job.init))
        return (job.family, program, shapes, job.mu, job.lambda_,
                (), (), int(job.halloffame_size))
    program = job.program
    if program is None:
        program = toolbox_fingerprint(job.toolbox)["digest"]
    if isinstance(job.init, Population):
        shapes = (("pop", job.init.size, job.init.nobj,
                   tuple(job.init.spec.weights)),
                  _shape_sig(job.init.genomes),
                  _shape_sig(job.init.extras))
    else:
        weights = (tuple(job.spec.weights)
                   if job.spec is not None and job.family != "island"
                   else None)
        shapes = (("state", weights), _shape_sig(job.init))
    if job.family == "island":
        shapes = shapes + (("island",) + job.spec.static_key(),)
    stats_fields = (tuple(job.stats.fields)
                    if job.stats is not None else ())
    probe_types = tuple(type(p).__name__ for p in job.probes)
    return (job.family, program, shapes, job.mu, job.lambda_,
            stats_fields, probe_types, int(job.halloffame_size))


class Tenant:
    """Runtime record of one admitted job.

    Owns the per-tenant run directory (``<root>/tenants/<id>/``) whose
    checkpoints are the scheduler's swap unit: :meth:`checkpoint`
    writes the lane state + accumulated logbook rows with
    ``tenant_id`` in the v2 meta, :meth:`restore` reads the newest
    valid checkpoint back *filtered on that id* — co-located or
    misconfigured tenant directories can never cross-restore
    (``Checkpointer.restore_latest(tenant_id=...)``).
    """

    #: admission/run states
    QUEUED, RUNNING, FINISHED, STOPPED = \
        "queued", "running", "finished", "stopped"

    def __init__(self, job: Job, root: str):
        self.job = job
        self.id = job.tenant_id
        self.run_dir = os.path.join(root, "tenants", str(job.tenant_id))
        self.status = self.QUEUED
        self.gen = 0
        self.slot: Optional[int] = None
        self.segments_resident = 0
        self.lane: Optional[Dict[str, Any]] = None
        self.record_chunks: List[Any] = []
        self.result: Optional[tuple] = None
        self.stopped_at: Optional[int] = None
        self.has_checkpoint = False
        # when this tenant last joined the queue (submission or
        # eviction) — the scheduler's queue-wait SLO histogram reads
        # it at admission; monotonic, so NTP steps can't skew SLOs
        self.enqueued_at = time.monotonic()
        # the generation count at the last client interaction (result
        # poll / status / stream read) — the autoscaler's true
        # idleness signal: a parked ask-tell tenant nobody polls
        # accumulates gens_since_interaction, a mid-job tenant whose
        # client is long-polling stays near zero
        self._interact_gen = 0
        self._ckpt: Optional[Checkpointer] = None

    def note_interaction(self) -> None:
        """A client touched this tenant (poll/stream/status) — resets
        the idleness clock. Written by the service's driver thread
        (which drains the front end's touch set each iteration)."""
        self._interact_gen = self.gen

    @property
    def gens_since_interaction(self) -> int:
        """Generations advanced since a client last interacted — the
        spill actuator's idleness signal (``slo_snapshot()`` exposes it
        per resident)."""
        return max(0, self.gen - self._interact_gen)

    @property
    def ckpt(self) -> Checkpointer:
        if self._ckpt is None:
            # fsync=False: tenants checkpoint every boundary, and a
            # service kill (SIGKILL) leaves the page cache intact — the
            # fsync pair per save only buys durability across a host
            # power cut, where restore falls back one boundary anyway
            self._ckpt = Checkpointer(
                os.path.join(self.run_dir, "ckpt"), keep=2, fsync=False)
        return self._ckpt

    @property
    def done(self) -> bool:
        return self.status in (self.FINISHED, self.STOPPED)

    def checkpoint(self, engine, meta: Optional[Dict[str, Any]] = None
                   ) -> str:
        """Persist the swap unit: lane state + logbook rows so far,
        keyed by the completed-generation count, ``tenant_id`` in the
        meta."""
        records = engine.concat_records(self.record_chunks)
        state = {"lane": self.lane, "records": records,
                 "family": engine.family}
        m = {"tenant_id": self.id, "gen": self.gen,
             "ngen": int(self.job.ngen), **(meta or {})}
        # the submitting request id rides the meta so checkpoint
        # save/restore journal rows stamp it (request-path grep +
        # the trace view's checkpoint spans)
        rid = getattr(self.job, "request_id", None)
        if rid and "request_id" not in m:
            m["request_id"] = rid
        path = self.ckpt.save(self.gen, state, meta=m)
        self.has_checkpoint = True
        return path

    def probe_checkpoint(self) -> bool:
        """True when this tenant's run dir already holds a checkpoint
        stamped with its id (a prior process checkpointed it — e.g. a
        service drain). Sets ``has_checkpoint`` so admission resumes
        instead of fresh-initialising. Fresh tenants take the stat-only
        fast path — probing must not cost a Checkpointer (mkdir +
        listdir) per admission at 1k tenants/submission burst."""
        if not os.path.isdir(os.path.join(self.run_dir, "ckpt")):
            return False
        from deap_tpu.support.checkpoint import checkpoint_meta
        for step in reversed(self.ckpt.steps()):
            try:
                meta = checkpoint_meta(self.ckpt.path_for(step)) or {}
            except Exception:
                continue
            if meta.get("tenant_id") == self.id:
                self.has_checkpoint = True
                return True
        return False

    def restore(self, engine) -> None:
        """Load the newest valid checkpoint *for this tenant* back into
        the in-memory lane/records (the resume half of the swap)."""
        got = self.ckpt.restore_latest(tenant_id=self.id)
        if got is None:
            raise FileNotFoundError(
                f"tenant {self.id}: no checkpoint under "
                f"{self.ckpt.directory}")
        step, state = got
        if state.get("family") != engine.family:
            raise ValueError(
                f"tenant {self.id}: checkpoint family "
                f"{state.get('family')!r} != bucket {engine.family!r}")
        self.lane = state["lane"]
        self.record_chunks = ([] if state["records"] is None
                              else [state["records"]])
        self.gen = int(step)

    def evict(self) -> None:
        self.status = self.QUEUED
        self.slot = None
        self.lane = None          # swap unit is on disk
        self.record_chunks = []   # rolled into the checkpoint
        self.segments_resident = 0
        self.enqueued_at = time.monotonic()
