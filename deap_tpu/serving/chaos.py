"""Network chaos harness — kill -9 / restart the service under live load.

The proof rig for the ISSUE 12 fault-tolerance layer. Everything the
admission WAL, idempotency keys, checkpoint resume and client retry
policy promise is one sentence: *a ``SIGKILL`` of the service process,
at the worst moment, under live concurrent client traffic, loses no
job and changes no bit of any tenant's result*. This module makes that
sentence executable:

- a **child entry point** (``python -m deap_tpu.serving.chaos``) runs
  an :class:`~deap_tpu.serving.service.EvolutionService` with a
  deterministic :class:`~deap_tpu.resilience.faultinject.KillServiceAt`
  fault plan — the kill fires at an exact driver step (or mid-boundary),
  replayable run after run;
- :func:`run_chaos` is the **parent harness**: spawn the child, drive
  ``clients`` concurrent threads of retrying
  :class:`~deap_tpu.serving.client.ServiceClient`\\ s (jittered
  :class:`~deap_tpu.resilience.retry.RetryPolicy`, idempotency keys on
  every submit), detect the kill, respawn the service over the same
  root (WAL replay + checkpoint resume), and keep the same clients
  retrying until every tenant converged;
- :func:`reference_digests` runs the *same* jobs through the
  :class:`~deap_tpu.serving.scheduler.Scheduler` in-process,
  uninterrupted — the PR 11 wire digest makes "chaos run ==
  uninterrupted run" one string compare per tenant.

Consumed by ``tests/test_service_chaos.py`` (``-m chaos``) and
``bench.py --service-chaos`` (``BENCH_CHAOS.json``, gated by
``bench_report.py --tripwire``'s ``chaos_tripwire``: zero lost jobs,
100% digest identity, bounded recovery time).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["chaos_problems", "reference_digests", "run_chaos",
           "child_main"]

#: default job shape: tiny pops, enough generations that a mid-run
#: kill lands with tenants in every state (queued / resident /
#: checkpointed / finished)
CHAOS_JOB = dict(pop=16, length=32, ngen=12)


def chaos_problems():
    """The harness's problem registry: per-tenant seeded OneMax jobs
    that are bit-reproducible from ``(tenant_id, params)`` alone —
    the WAL-replay determinism contract, and what lets the restarted
    service recompute a lost tenant to the identical digest."""
    import jax
    import jax.numpy as jnp

    from deap_tpu import ops
    from deap_tpu.core.fitness import FitnessSpec
    from deap_tpu.core.population import init_population
    from deap_tpu.core.toolbox import Toolbox
    from deap_tpu.serving.tenant import Job

    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    spec = FitnessSpec((1.0,))

    def onemax(tid, params):
        seed = int(params["seed"])
        pop = init_population(
            jax.random.key(seed),
            int(params.get("pop", CHAOS_JOB["pop"])),
            ops.bernoulli_genome(
                int(params.get("length", CHAOS_JOB["length"]))),
            spec)
        return Job(tenant_id=tid, family="ea_simple", toolbox=tb,
                   key=jax.random.key(20_000 + seed), init=pop,
                   ngen=int(params.get("ngen", CHAOS_JOB["ngen"])),
                   hyper={"cxpb": 0.5, "mutpb": 0.2},
                   program="chaos_onemax")

    return {"onemax": onemax}


def chaos_specs(n: int, ngen: Optional[int] = None) -> List[Tuple[str, dict]]:
    """``n`` job specs ``(tenant_id, params)`` on the harness shape."""
    params = dict(CHAOS_JOB)
    if ngen is not None:
        params["ngen"] = int(ngen)
    return [(f"c{i:04d}", {"seed": i, **params}) for i in range(n)]


def reference_digests(root: str, specs: Sequence[Tuple[str, dict]], *,
                      segment_len: int = 2, max_lanes: int = 8
                      ) -> Dict[str, str]:
    """The uninterrupted in-process run — the bit-identity reference
    every chaos survivor must match."""
    from deap_tpu.serving.scheduler import Scheduler
    from deap_tpu.serving.wire import result_digest

    onemax = chaos_problems()["onemax"]
    with Scheduler(str(root), max_lanes=max_lanes,
                   segment_len=segment_len, fair_quantum=None,
                   checkpoint_every=0, telemetry=False,
                   metrics=False) as sched:
        for tid, params in specs:
            sched.submit(onemax(tid, params))
        results = sched.run()
    return {tid: result_digest(r) for tid, r in results.items()}


# -------------------------------------------------------- child side ----

def child_main(argv: Optional[Sequence[str]] = None) -> None:
    """``python -m deap_tpu.serving.chaos`` — one service process,
    optionally scheduled to SIGKILL itself at an exact driver step.
    Writes ``<ready>`` (atomic rename) with the bound URL once
    serving; exits cleanly after a SIGTERM drain."""
    p = argparse.ArgumentParser()
    p.add_argument("--root", required=True)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--ready", required=True)
    p.add_argument("--kill-at", type=int, default=None)
    p.add_argument("--kill-event", default="step",
                   choices=("step", "boundary"))
    p.add_argument("--segment-len", type=int, default=2)
    p.add_argument("--max-lanes", type=int, default=8)
    p.add_argument("--max-pending", type=int, default=0)
    p.add_argument("--watchdog-s", type=float, default=0.0)
    p.add_argument("--trace-sample", type=float, default=None,
                   help="enable the tracing plane at this sample rate"
                        " (lifecycle spans always on); omitted ="
                        " tracing off")
    p.add_argument("--compile-cache", default=None,
                   help="persistent XLA compile cache directory; also "
                        "enables the sibling executable-artifact "
                        "store, so the restarted child deserializes "
                        "the lattice instead of recompiling it")
    p.add_argument("--telemetry", action="store_true",
                   help="keep the scheduler's run journal on — the "
                        "restarted child's startup_phase/artifact_* "
                        "rows land in <root>/journal.jsonl for "
                        "report.py --health's Startup ledger")
    p.add_argument("--platform", default="cpu")
    args = p.parse_args(argv)

    if args.platform:
        # the harness is a CPU rig by default (the test tier runs with
        # no accelerator); pass --platform '' to serve on hardware
        os.environ.setdefault("JAX_PLATFORMS", args.platform)
        import jax
        jax.config.update("jax_platforms", args.platform)

    from deap_tpu.resilience.faultinject import FaultPlan, KillServiceAt
    from deap_tpu.serving.service import EvolutionService

    plan = None
    if args.kill_at is not None:
        plan = FaultPlan([KillServiceAt(args.kill_at,
                                        event=args.kill_event)])
    svc = EvolutionService(
        args.root, chaos_problems(), port=args.port,
        fault_plan=plan,
        max_pending=(args.max_pending or None),
        watchdog_s=(args.watchdog_s or None),
        max_lanes=args.max_lanes, segment_len=args.segment_len,
        fair_quantum=None, checkpoint_every=1,
        telemetry=bool(args.telemetry),
        metrics=False, trace_sample=args.trace_sample,
        compile_cache=(args.compile_cache or None))
    ds = svc.install_signal_handlers()
    tmp = args.ready + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(svc.url)
    os.replace(tmp, args.ready)
    try:
        while not svc.drained:
            time.sleep(0.05)
    finally:
        ds.uninstall()
        svc.close()


# ------------------------------------------------------- parent side ----

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_child(root: str, port: int, ready: str, *,
                 kill_at: Optional[int], kill_event: str,
                 segment_len: int, max_lanes: int,
                 max_pending: Optional[int],
                 python: str,
                 trace_sample: Optional[float] = None,
                 compile_cache: Optional[str] = None,
                 telemetry: bool = False
                 ) -> subprocess.Popen:
    try:
        os.remove(ready)
    except FileNotFoundError:
        pass
    cmd = [python, "-m", "deap_tpu.serving.chaos",
           "--root", root, "--port", str(port), "--ready", ready,
           "--segment-len", str(segment_len),
           "--max-lanes", str(max_lanes),
           "--max-pending", str(max_pending or 0)]
    if kill_at is not None:
        cmd += ["--kill-at", str(kill_at), "--kill-event", kill_event]
    if trace_sample is not None:
        cmd += ["--trace-sample", str(trace_sample)]
    if compile_cache:
        cmd += ["--compile-cache", compile_cache]
    if telemetry:
        cmd += ["--telemetry"]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(cmd, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _wait_ready(proc: subprocess.Popen, ready: str,
                timeout: float = 120.0) -> str:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if os.path.exists(ready):
            with open(ready) as fh:
                url = fh.read().strip()
            if url:
                return url
        if proc.poll() is not None:
            raise RuntimeError(
                f"chaos child exited rc={proc.returncode} before ready")
        time.sleep(0.05)
    raise RuntimeError("chaos child never became ready")


def run_chaos(root: str, *, n_tenants: int = 8,
              ngen: Optional[int] = None,
              kill_at_step: Optional[int] = 4,
              kill_event: str = "step",
              segment_len: int = 2, max_lanes: int = 8,
              clients: int = 4, max_pending: Optional[int] = None,
              converge_timeout_s: float = 300.0,
              trace_sample: Optional[float] = None,
              compile_cache: Optional[str] = None,
              telemetry: bool = False,
              python: str = sys.executable) -> Dict[str, Any]:
    """The kill/restart acceptance run. Returns::

        {"digests": {tid: digest}, "lost": [tid...],
         "kill_rc": -9, "recovery_s": float,
         "client_errors": int, "wall_s": float}

    ``recovery_s`` is wall time from the child's death to the last
    tenant converging on the restarted service; ``lost`` is every
    tenant that never produced a result within ``converge_timeout_s``
    (the chaos pin requires it empty).

    ``compile_cache`` points both children at a shared persistent XLA
    compile cache — which also enables the sibling executable-artifact
    store and the warm-handoff manifest, i.e. the whole ISSUE 18
    startup fast path: the restarted child deserializes the pre-kill
    lattice instead of recompiling it. The committed
    ``BENCH_CHAOS.json`` runs with a root-local cache so the ≤ 8 s
    recovery gate measures the fast path, not a cold XLA pipeline.
    """
    from deap_tpu.serving.client import RetryPolicy, ServiceClient

    os.makedirs(root, exist_ok=True)
    port = _free_port()
    ready = os.path.join(root, "ready.url")
    specs = chaos_specs(n_tenants, ngen=ngen)
    url = f"http://127.0.0.1:{port}"

    proc = _spawn_child(root, port, ready, kill_at=kill_at_step,
                        kill_event=kill_event,
                        segment_len=segment_len, max_lanes=max_lanes,
                        max_pending=max_pending, python=python,
                        trace_sample=trace_sample,
                        compile_cache=compile_cache,
                        telemetry=telemetry)
    _wait_ready(proc, ready)

    kill_info: Dict[str, Any] = {"rc": None, "t": None, "proc2": None}

    def supervise():
        # the kill fires inside the child; the parent's job is to see
        # it die and restart the service over the same root — the
        # supervisor a real deployment provides
        proc.wait()
        kill_info["rc"] = proc.returncode
        kill_info["t"] = time.monotonic()
        p2 = _spawn_child(root, port, ready, kill_at=None,
                          kill_event=kill_event,
                          segment_len=segment_len,
                          max_lanes=max_lanes,
                          max_pending=max_pending, python=python,
                          trace_sample=trace_sample,
                          compile_cache=compile_cache,
                          telemetry=telemetry)
        kill_info["proc2"] = p2
        _wait_ready(p2, ready)

    sup = None
    if kill_at_step is not None:
        sup = threading.Thread(target=supervise, daemon=True)
        sup.start()

    digests: Dict[str, str] = {}
    dig_lock = threading.Lock()
    errors = [0]
    stop_at = time.monotonic() + converge_timeout_s
    per = (len(specs) + clients - 1) // clients
    t0 = time.monotonic()

    def drive(ci: int):
        chunk = specs[ci * per:(ci + 1) * per]
        if not chunk:
            return
        # jittered backoff, seeded per client: deterministic schedule,
        # de-synchronised across the fleet
        retry = RetryPolicy(max_retries=4, backoff_s=0.1,
                            backoff_factor=2.0, max_backoff_s=1.0,
                            jitter=0.5, seed=1000 + ci)
        c = ServiceClient(url, timeout=30, retry=retry)
        pending = {tid: {"problem": "onemax", "params": params,
                         "tenant_id": tid,
                         "idempotency_key": f"key-{tid}"}
                   for tid, params in chunk}
        while pending and time.monotonic() < stop_at:
            try:
                # idempotent re-offer of everything unresolved: live
                # tenants map back via their keys, tenants the restart
                # no longer knows (finished pre-kill, result unfetched)
                # are re-admitted and recomputed deterministically
                c.submit_many(list(pending.values()))
                got = c.results_many(sorted(pending), wait=True,
                                     timeout=5)
            except Exception:
                errors[0] += 1
                c.close()
                time.sleep(0.2)
                continue
            for tid, entry in got.items():
                res = entry.get("result")
                if res is not None:
                    with dig_lock:
                        digests[tid] = res["digest"]
                    pending.pop(tid, None)
        c.close()

    threads = [threading.Thread(target=drive, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=converge_timeout_s + 60)
    wall_s = time.monotonic() - t0
    done_t = time.monotonic()

    # graceful teardown of whichever child is serving now
    live = kill_info["proc2"] or proc
    if live.poll() is None:
        live.terminate()   # SIGTERM → drain → clean exit
        try:
            live.wait(timeout=60)
        except subprocess.TimeoutExpired:
            live.kill()

    lost = sorted(tid for tid, _ in specs if tid not in digests)
    recovery_s = (done_t - kill_info["t"]
                  if kill_info["t"] is not None else None)
    return {"digests": digests, "lost": lost,
            "kill_rc": kill_info["rc"],
            "recovery_s": (round(recovery_s, 3)
                           if recovery_s is not None else None),
            "client_errors": errors[0],
            "wall_s": round(wall_s, 3)}


if __name__ == "__main__":
    child_main()
