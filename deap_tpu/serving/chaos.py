"""Network chaos harness — kill -9 / restart the service under live load.

The proof rig for the ISSUE 12 fault-tolerance layer. Everything the
admission WAL, idempotency keys, checkpoint resume and client retry
policy promise is one sentence: *a ``SIGKILL`` of the service process,
at the worst moment, under live concurrent client traffic, loses no
job and changes no bit of any tenant's result*. This module makes that
sentence executable:

- a **child entry point** (``python -m deap_tpu.serving.chaos``) runs
  an :class:`~deap_tpu.serving.service.EvolutionService` with a
  deterministic :class:`~deap_tpu.resilience.faultinject.KillServiceAt`
  fault plan — the kill fires at an exact driver step (or mid-boundary),
  replayable run after run;
- :func:`run_chaos` is the **parent harness**: spawn the child, drive
  ``clients`` concurrent threads of retrying
  :class:`~deap_tpu.serving.client.ServiceClient`\\ s (jittered
  :class:`~deap_tpu.resilience.retry.RetryPolicy`, idempotency keys on
  every submit), detect the kill, respawn the service over the same
  root (WAL replay + checkpoint resume), and keep the same clients
  retrying until every tenant converged;
- :func:`reference_digests` runs the *same* jobs through the
  :class:`~deap_tpu.serving.scheduler.Scheduler` in-process,
  uninterrupted — the PR 11 wire digest makes "chaos run ==
  uninterrupted run" one string compare per tenant.

Consumed by ``tests/test_service_chaos.py`` (``-m chaos``) and
``bench.py --service-chaos`` (``BENCH_CHAOS.json``, gated by
``bench_report.py --tripwire``'s ``chaos_tripwire``: zero lost jobs,
100% digest identity, bounded recovery time).

Zero-downtime operations (ISSUE 20) extend the same rig three ways:

- :func:`run_migration_chaos` — live migration killed (SIGKILL) at an
  exact ownership-transfer seam (``after_offer`` on the source,
  ``before_adopted`` on the target, ``before_transferred`` on the
  source — :class:`~deap_tpu.resilience.faultinject.
  KillDuringHandoff`); the tenant must survive on exactly one driver
  with a bit-identical digest;
- :func:`run_orphan_drill` — a fleet member dies mid-run and a live
  peer adopts its accepted-not-terminal WAL records through the same
  transfer machinery (``--fleet-root`` registration +
  ``--adopt-every`` polling);
- :func:`run_upgrade_drill` — a rolling version upgrade under live
  load: old-version child drains with ``?handoff=`` to a new-version
  child (``DEAP_TPU_VERSION_OVERRIDE`` + ``--compat-restore``), zero
  lost jobs, all digests bit-identical, canaries green on both sides.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["chaos_problems", "reference_digests", "run_chaos",
           "run_migration_chaos", "run_orphan_drill",
           "run_upgrade_drill", "child_main"]

#: default job shape: tiny pops, enough generations that a mid-run
#: kill lands with tenants in every state (queued / resident /
#: checkpointed / finished)
CHAOS_JOB = dict(pop=16, length=32, ngen=12)


def chaos_problems():
    """The harness's problem registry: per-tenant seeded OneMax jobs
    that are bit-reproducible from ``(tenant_id, params)`` alone —
    the WAL-replay determinism contract, and what lets the restarted
    service recompute a lost tenant to the identical digest."""
    import jax
    import jax.numpy as jnp

    from deap_tpu import ops
    from deap_tpu.core.fitness import FitnessSpec
    from deap_tpu.core.population import init_population
    from deap_tpu.core.toolbox import Toolbox
    from deap_tpu.serving.tenant import Job

    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    spec = FitnessSpec((1.0,))

    def onemax(tid, params):
        seed = int(params["seed"])
        pop = init_population(
            jax.random.key(seed),
            int(params.get("pop", CHAOS_JOB["pop"])),
            ops.bernoulli_genome(
                int(params.get("length", CHAOS_JOB["length"]))),
            spec)
        return Job(tenant_id=tid, family="ea_simple", toolbox=tb,
                   key=jax.random.key(20_000 + seed), init=pop,
                   ngen=int(params.get("ngen", CHAOS_JOB["ngen"])),
                   hyper={"cxpb": 0.5, "mutpb": 0.2},
                   program="chaos_onemax")

    return {"onemax": onemax}


def chaos_specs(n: int, ngen: Optional[int] = None) -> List[Tuple[str, dict]]:
    """``n`` job specs ``(tenant_id, params)`` on the harness shape."""
    params = dict(CHAOS_JOB)
    if ngen is not None:
        params["ngen"] = int(ngen)
    return [(f"c{i:04d}", {"seed": i, **params}) for i in range(n)]


def reference_digests(root: str, specs: Sequence[Tuple[str, dict]], *,
                      segment_len: int = 2, max_lanes: int = 8
                      ) -> Dict[str, str]:
    """The uninterrupted in-process run — the bit-identity reference
    every chaos survivor must match."""
    from deap_tpu.serving.scheduler import Scheduler
    from deap_tpu.serving.wire import result_digest

    onemax = chaos_problems()["onemax"]
    with Scheduler(str(root), max_lanes=max_lanes,
                   segment_len=segment_len, fair_quantum=None,
                   checkpoint_every=0, telemetry=False,
                   metrics=False) as sched:
        for tid, params in specs:
            sched.submit(onemax(tid, params))
        results = sched.run()
    return {tid: result_digest(r) for tid, r in results.items()}


# -------------------------------------------------------- child side ----

def child_main(argv: Optional[Sequence[str]] = None) -> None:
    """``python -m deap_tpu.serving.chaos`` — one service process,
    optionally scheduled to SIGKILL itself at an exact driver step.
    Writes ``<ready>`` (atomic rename) with the bound URL once
    serving; exits cleanly after a SIGTERM drain."""
    p = argparse.ArgumentParser()
    p.add_argument("--root", required=True)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--ready", required=True)
    p.add_argument("--kill-at", type=int, default=None)
    p.add_argument("--kill-event", default="step",
                   choices=("step", "boundary"))
    p.add_argument("--kill-seam", default=None,
                   choices=("after_offer", "before_adopted",
                            "before_transferred"),
                   help="SIGKILL self at this ownership-transfer seam"
                        " (KillDuringHandoff): after_offer/"
                        "before_transferred fire on a migration "
                        "SOURCE, before_adopted on a TARGET")
    p.add_argument("--fleet-root", default=None,
                   help="federation root (PR 19): register this "
                        "process (pid + serving root + url) so peers "
                        "can detect death and adopt orphans")
    p.add_argument("--process-id", default=None)
    p.add_argument("--adopt-every", type=float, default=0.0,
                   help="poll the fleet root every S seconds and "
                        "adopt dead members' tenants (0 = off)")
    p.add_argument("--compat-restore", action="store_true",
                   help="open the checkpoint compat gate: this build "
                        "may restore checkpoints stamped by a "
                        "different deap_tpu version (rolling-upgrade "
                        "target side); each such restore journals "
                        "compat_restore")
    p.add_argument("--canary", action="store_true",
                   help="run a known-answer canary tenant "
                        "(trust-on-first-use digest) at a short "
                        "boundary cadence")
    p.add_argument("--segment-len", type=int, default=2)
    p.add_argument("--max-lanes", type=int, default=8)
    p.add_argument("--max-pending", type=int, default=0)
    p.add_argument("--watchdog-s", type=float, default=0.0)
    p.add_argument("--trace-sample", type=float, default=None,
                   help="enable the tracing plane at this sample rate"
                        " (lifecycle spans always on); omitted ="
                        " tracing off")
    p.add_argument("--compile-cache", default=None,
                   help="persistent XLA compile cache directory; also "
                        "enables the sibling executable-artifact "
                        "store, so the restarted child deserializes "
                        "the lattice instead of recompiling it")
    p.add_argument("--telemetry", action="store_true",
                   help="keep the scheduler's run journal on — the "
                        "restarted child's startup_phase/artifact_* "
                        "rows land in <root>/journal.jsonl for "
                        "report.py --health's Startup ledger")
    p.add_argument("--platform", default="cpu")
    args = p.parse_args(argv)

    if args.platform:
        # the harness is a CPU rig by default (the test tier runs with
        # no accelerator); pass --platform '' to serve on hardware
        os.environ.setdefault("JAX_PLATFORMS", args.platform)
        import jax
        jax.config.update("jax_platforms", args.platform)

    from deap_tpu.resilience.faultinject import (FaultPlan,
                                                 KillDuringHandoff,
                                                 KillServiceAt)
    from deap_tpu.serving.canary import CanarySpec
    from deap_tpu.serving.service import EvolutionService

    faults = []
    if args.kill_at is not None:
        faults.append(KillServiceAt(args.kill_at,
                                    event=args.kill_event))
    if args.kill_seam:
        faults.append(KillDuringHandoff(args.kill_seam))
    canary = None
    if args.canary:
        # fixed-seed known-answer probe, TOFU digest: the first clean
        # completion pins the expectation, every later completion must
        # match it bit-for-bit — across restarts AND upgrades, since
        # the expectation rides the journal
        canary = CanarySpec("onemax",
                            {"seed": 990_001, "pop": 16,
                             "length": 32, "ngen": 6},
                            cadence_boundaries=8)
    svc = EvolutionService(
        args.root, chaos_problems(), port=args.port,
        fault_plan=(FaultPlan(faults) if faults else None),
        max_pending=(args.max_pending or None),
        watchdog_s=(args.watchdog_s or None),
        max_lanes=args.max_lanes, segment_len=args.segment_len,
        fair_quantum=None, checkpoint_every=1,
        telemetry=bool(args.telemetry),
        canary=canary, compat_restore=bool(args.compat_restore),
        metrics=False, trace_sample=args.trace_sample,
        compile_cache=(args.compile_cache or None))
    if args.fleet_root:
        from deap_tpu.telemetry.federation import register_process
        register_process(args.fleet_root, args.process_id,
                         serving_root=os.path.abspath(args.root),
                         url=svc.url,
                         deap_tpu_version=os.environ.get(
                             "DEAP_TPU_VERSION_OVERRIDE") or None)
    adopt_stop = threading.Event()
    adopter = None
    if args.adopt_every > 0 and args.fleet_root:
        def adopt_loop():
            while not adopt_stop.wait(args.adopt_every):
                try:
                    svc.adopt_orphans(args.fleet_root,
                                      process_id=args.process_id)
                except Exception:
                    pass   # a racing peer or a torn meta is not fatal
        adopter = threading.Thread(target=adopt_loop, daemon=True)
        adopter.start()
    ds = svc.install_signal_handlers()
    tmp = args.ready + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(svc.url)
    os.replace(tmp, args.ready)
    try:
        while not svc.drained:
            time.sleep(0.05)
    finally:
        adopt_stop.set()
        if adopter is not None:
            adopter.join(timeout=5)
        ds.uninstall()
        svc.close()


# ------------------------------------------------------- parent side ----

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_child(root: str, port: int, ready: str, *,
                 kill_at: Optional[int] = None,
                 kill_event: str = "step",
                 segment_len: int = 2, max_lanes: int = 8,
                 max_pending: Optional[int] = None,
                 python: str = sys.executable,
                 trace_sample: Optional[float] = None,
                 compile_cache: Optional[str] = None,
                 telemetry: bool = False,
                 kill_seam: Optional[str] = None,
                 fleet_root: Optional[str] = None,
                 process_id: Optional[str] = None,
                 adopt_every: float = 0.0,
                 compat_restore: bool = False,
                 canary: bool = False,
                 version: Optional[str] = None
                 ) -> subprocess.Popen:
    try:
        os.remove(ready)
    except FileNotFoundError:
        pass
    cmd = [python, "-m", "deap_tpu.serving.chaos",
           "--root", root, "--port", str(port), "--ready", ready,
           "--segment-len", str(segment_len),
           "--max-lanes", str(max_lanes),
           "--max-pending", str(max_pending or 0)]
    if kill_at is not None:
        cmd += ["--kill-at", str(kill_at), "--kill-event", kill_event]
    if kill_seam:
        cmd += ["--kill-seam", kill_seam]
    if fleet_root:
        cmd += ["--fleet-root", fleet_root]
    if process_id:
        cmd += ["--process-id", process_id]
    if adopt_every:
        cmd += ["--adopt-every", str(adopt_every)]
    if compat_restore:
        cmd += ["--compat-restore"]
    if canary:
        cmd += ["--canary"]
    if trace_sample is not None:
        cmd += ["--trace-sample", str(trace_sample)]
    if compile_cache:
        cmd += ["--compile-cache", compile_cache]
    if telemetry:
        cmd += ["--telemetry"]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if version is not None:
        # the rolling-upgrade drill's version lever: the child's
        # checkpoint stamps (and compat gate) see this as the build
        # version — two binaries from one checkout
        env["DEAP_TPU_VERSION_OVERRIDE"] = version
    else:
        env.pop("DEAP_TPU_VERSION_OVERRIDE", None)
    return subprocess.Popen(cmd, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _wait_ready(proc: subprocess.Popen, ready: str,
                timeout: float = 120.0) -> str:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if os.path.exists(ready):
            with open(ready) as fh:
                url = fh.read().strip()
            if url:
                return url
        if proc.poll() is not None:
            raise RuntimeError(
                f"chaos child exited rc={proc.returncode} before ready")
        time.sleep(0.05)
    raise RuntimeError("chaos child never became ready")


def run_chaos(root: str, *, n_tenants: int = 8,
              ngen: Optional[int] = None,
              kill_at_step: Optional[int] = 4,
              kill_event: str = "step",
              segment_len: int = 2, max_lanes: int = 8,
              clients: int = 4, max_pending: Optional[int] = None,
              converge_timeout_s: float = 300.0,
              trace_sample: Optional[float] = None,
              compile_cache: Optional[str] = None,
              telemetry: bool = False,
              python: str = sys.executable) -> Dict[str, Any]:
    """The kill/restart acceptance run. Returns::

        {"digests": {tid: digest}, "lost": [tid...],
         "kill_rc": -9, "recovery_s": float,
         "client_errors": int, "wall_s": float}

    ``recovery_s`` is wall time from the child's death to the last
    tenant converging on the restarted service; ``lost`` is every
    tenant that never produced a result within ``converge_timeout_s``
    (the chaos pin requires it empty).

    ``compile_cache`` points both children at a shared persistent XLA
    compile cache — which also enables the sibling executable-artifact
    store and the warm-handoff manifest, i.e. the whole ISSUE 18
    startup fast path: the restarted child deserializes the pre-kill
    lattice instead of recompiling it. The committed
    ``BENCH_CHAOS.json`` runs with a root-local cache so the ≤ 8 s
    recovery gate measures the fast path, not a cold XLA pipeline.
    """
    from deap_tpu.serving.client import RetryPolicy, ServiceClient

    os.makedirs(root, exist_ok=True)
    port = _free_port()
    ready = os.path.join(root, "ready.url")
    specs = chaos_specs(n_tenants, ngen=ngen)
    url = f"http://127.0.0.1:{port}"

    proc = _spawn_child(root, port, ready, kill_at=kill_at_step,
                        kill_event=kill_event,
                        segment_len=segment_len, max_lanes=max_lanes,
                        max_pending=max_pending, python=python,
                        trace_sample=trace_sample,
                        compile_cache=compile_cache,
                        telemetry=telemetry)
    _wait_ready(proc, ready)

    kill_info: Dict[str, Any] = {"rc": None, "t": None, "proc2": None}

    def supervise():
        # the kill fires inside the child; the parent's job is to see
        # it die and restart the service over the same root — the
        # supervisor a real deployment provides
        proc.wait()
        kill_info["rc"] = proc.returncode
        kill_info["t"] = time.monotonic()
        p2 = _spawn_child(root, port, ready, kill_at=None,
                          kill_event=kill_event,
                          segment_len=segment_len,
                          max_lanes=max_lanes,
                          max_pending=max_pending, python=python,
                          trace_sample=trace_sample,
                          compile_cache=compile_cache,
                          telemetry=telemetry)
        kill_info["proc2"] = p2
        _wait_ready(p2, ready)

    sup = None
    if kill_at_step is not None:
        sup = threading.Thread(target=supervise, daemon=True)
        sup.start()

    digests: Dict[str, str] = {}
    dig_lock = threading.Lock()
    errors = [0]
    stop_at = time.monotonic() + converge_timeout_s
    per = (len(specs) + clients - 1) // clients
    t0 = time.monotonic()

    def drive(ci: int):
        chunk = specs[ci * per:(ci + 1) * per]
        if not chunk:
            return
        # jittered backoff, seeded per client: deterministic schedule,
        # de-synchronised across the fleet
        retry = RetryPolicy(max_retries=4, backoff_s=0.1,
                            backoff_factor=2.0, max_backoff_s=1.0,
                            jitter=0.5, seed=1000 + ci)
        c = ServiceClient(url, timeout=30, retry=retry)
        pending = {tid: {"problem": "onemax", "params": params,
                         "tenant_id": tid,
                         "idempotency_key": f"key-{tid}"}
                   for tid, params in chunk}
        while pending and time.monotonic() < stop_at:
            try:
                # idempotent re-offer of everything unresolved: live
                # tenants map back via their keys, tenants the restart
                # no longer knows (finished pre-kill, result unfetched)
                # are re-admitted and recomputed deterministically
                c.submit_many(list(pending.values()))
                got = c.results_many(sorted(pending), wait=True,
                                     timeout=5)
            except Exception:
                errors[0] += 1
                c.close()
                time.sleep(0.2)
                continue
            for tid, entry in got.items():
                res = entry.get("result")
                if res is not None:
                    with dig_lock:
                        digests[tid] = res["digest"]
                    pending.pop(tid, None)
        c.close()

    threads = [threading.Thread(target=drive, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=converge_timeout_s + 60)
    wall_s = time.monotonic() - t0
    done_t = time.monotonic()

    # graceful teardown of whichever child is serving now
    live = kill_info["proc2"] or proc
    if live.poll() is None:
        live.terminate()   # SIGTERM → drain → clean exit
        try:
            live.wait(timeout=60)
        except subprocess.TimeoutExpired:
            live.kill()

    lost = sorted(tid for tid, _ in specs if tid not in digests)
    recovery_s = (done_t - kill_info["t"]
                  if kill_info["t"] is not None else None)
    return {"digests": digests, "lost": lost,
            "kill_rc": kill_info["rc"],
            "recovery_s": (round(recovery_s, 3)
                           if recovery_s is not None else None),
            "client_errors": errors[0],
            "wall_s": round(wall_s, 3)}


# -------------------------------------- zero-downtime drills (ISSUE 20) ----

def _journal_rows(root: str) -> List[Dict[str, Any]]:
    """Every journal row under ``root``, across restart generations,
    oldest first — what the drills assert canary/migration/compat
    facts against."""
    from deap_tpu.telemetry.journal import (journal_generations,
                                            read_journal)
    rows: List[Dict[str, Any]] = []
    for gen in journal_generations(os.path.join(root,
                                                "journal.jsonl")):
        rows.extend(read_journal(gen))
    return rows


def _kinds(rows: Sequence[Dict[str, Any]]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for r in rows:
        k = r.get("kind")
        if k:
            out[k] = out.get(k, 0) + 1
    return out


def _post_drain(url: str, handoff: Optional[str] = None,
                timeout: float = 10.0) -> None:
    import urllib.request
    path = "/v1/drain"
    if handoff:
        import urllib.parse as up
        path += "?handoff=" + up.quote(handoff, safe="")
    req = urllib.request.Request(url + path, data=b"", method="POST")
    with urllib.request.urlopen(req, timeout=timeout):
        pass


def _submit_specs(url: str, specs: Sequence[Tuple[str, dict]]) -> None:
    from deap_tpu.serving.client import ServiceClient
    c = ServiceClient(url, timeout=30)
    try:
        c.submit_many([{"problem": "onemax", "params": params,
                        "tenant_id": tid,
                        "idempotency_key": f"key-{tid}"}
                       for tid, params in specs])
    finally:
        c.close()


def _wait_progress(url: str, tids: Sequence[str], min_gen: int,
                   timeout_s: float = 60.0) -> None:
    """Block until every tenant's view reports ``gen >= min_gen`` —
    the drills migrate MID-RUN, never at gen 0."""
    from deap_tpu.serving.client import ServiceClient
    c = ServiceClient(url, timeout=10)
    t0 = time.monotonic()
    try:
        while time.monotonic() - t0 < timeout_s:
            try:
                got = c.results_many(list(tids), wait=False)
            except Exception:
                time.sleep(0.1)
                continue
            gens = [int(got.get(t, {}).get("gen") or 0) for t in tids]
            done = [bool(got.get(t, {}).get("result")) for t in tids]
            if all(g >= min_gen or d
                   for g, d in zip(gens, done)):
                return
            time.sleep(0.05)
    finally:
        c.close()


def _converge(owner_of, specs: Sequence[Tuple[str, dict]],
              timeout_s: float, reoffer: bool = True
              ) -> Tuple[Dict[str, str], List[str]]:
    """Poll every tenant's OWNING service until all digests land.
    ``owner_of(tid) -> url`` is re-evaluated every round, so ownership
    that moves mid-drill (resolution, adoption) is followed. With
    ``reoffer`` the client idempotently re-submits tenants their owner
    no longer has live (the run_chaos client contract)."""
    from deap_tpu.serving.client import ServiceClient, ServiceError
    digests: Dict[str, str] = {}
    clients: Dict[str, ServiceClient] = {}
    stop_at = time.monotonic() + timeout_s

    def _offer(c, tid, params):
        try:
            c.submit_many([{"problem": "onemax", "params": params,
                            "tenant_id": tid,
                            "idempotency_key": f"key-{tid}"}])
        except Exception:
            pass

    try:
        while len(digests) < len(specs) \
                and time.monotonic() < stop_at:
            for tid, params in specs:
                if tid in digests:
                    continue
                url = owner_of(tid)
                if url is None:
                    continue
                c = clients.get(url)
                if c is None:
                    c = clients[url] = ServiceClient(url, timeout=10)
                try:
                    got = c.results_many([tid], wait=True, timeout=2)
                    entry = got.get(tid, {})
                except ServiceError as e:
                    # 404: the owner has never heard of the tenant —
                    # an adoption not yet registered, or a job that
                    # finished-and-exited on the departed side. The
                    # client contract is an idempotent re-offer:
                    # determinism makes a rerun bit-identical, and the
                    # idempotency key (which rides the ownership
                    # transfer) maps a raced re-offer onto the
                    # adopted tenant instead of forking a twin.
                    if reoffer and e.code == 404:
                        _offer(c, tid, params)
                    continue
                except Exception:
                    continue
                res = entry.get("result")
                if res is not None:
                    digests[tid] = res["digest"]
                elif reoffer and entry.get("status") in (
                        "drained", "migrated"):
                    _offer(c, tid, params)
            time.sleep(0.05)
    finally:
        for c in clients.values():
            c.close()
    lost = sorted(t for t, _ in specs if t not in digests)
    return digests, lost


def run_migration_chaos(root: str, seam: str, *, n_tenants: int = 6,
                        ngen: Optional[int] = None,
                        segment_len: int = 2, max_lanes: int = 8,
                        converge_timeout_s: float = 300.0,
                        python: str = sys.executable
                        ) -> Dict[str, Any]:
    """Kill -9 a live migration at an exact ownership-transfer seam.

    ``after_offer`` / ``before_transferred`` arm the SOURCE child's
    :class:`KillDuringHandoff`; ``before_adopted`` arms the TARGET's.
    The parent submits ``n_tenants``, waits for mid-run progress,
    triggers ``POST /v1/drain?handoff=<target>`` on the source, lets
    the kill fire, restarts the dead child over its own root, and
    converges every tenant against whichever driver the commit files
    say owns it. Returns digests/lost/kill_rc plus the per-side
    journal-kind counts and the set of tenants the target ended up
    owning."""
    from deap_tpu.serving import migration as migration_mod

    os.makedirs(root, exist_ok=True)
    src_root = os.path.join(root, "src")
    dst_root = os.path.join(root, "dst")
    specs = chaos_specs(n_tenants, ngen=ngen)
    src_port, dst_port = _free_port(), _free_port()
    src_ready = os.path.join(root, "src.url")
    dst_ready = os.path.join(root, "dst.url")
    src_url = f"http://127.0.0.1:{src_port}"
    dst_url = f"http://127.0.0.1:{dst_port}"
    kill_side = ("dst" if seam == "before_adopted" else "src")

    procs = {
        "src": _spawn_child(src_root, src_port, src_ready,
                            segment_len=segment_len,
                            max_lanes=max_lanes, python=python,
                            telemetry=True,
                            kill_seam=(seam if kill_side == "src"
                                       else None)),
        "dst": _spawn_child(dst_root, dst_port, dst_ready,
                            segment_len=segment_len,
                            max_lanes=max_lanes, python=python,
                            telemetry=True,
                            kill_seam=(seam if kill_side == "dst"
                                       else None)),
    }
    _wait_ready(procs["src"], src_ready)
    _wait_ready(procs["dst"], dst_ready)

    kill_info: Dict[str, Any] = {"rc": None}
    stopping = threading.Event()

    def supervise(side: str, proc: subprocess.Popen,
                  sroot: str, port: int, ready: str):
        # restart whoever dies — the real deployment's supervisor.
        # A clean drain exit (rc 0) restarts too: its parked tenants
        # need a live service to finish on. `stopping` gates the
        # respawn so the drill's own final SIGTERM isn't "healed".
        while not stopping.is_set():
            proc.wait()
            if stopping.is_set():
                return
            if side == kill_side and kill_info["rc"] is None:
                kill_info["rc"] = proc.returncode
            proc = _spawn_child(sroot, port, ready,
                                segment_len=segment_len,
                                max_lanes=max_lanes, python=python,
                                telemetry=True)
            procs[side] = proc
            _wait_ready(proc, ready)

    sups = [threading.Thread(target=supervise,
                             args=(side, procs[side], sroot, port,
                                   ready), daemon=True)
            for side, sroot, port, ready in (
                ("src", src_root, src_port, src_ready),
                ("dst", dst_root, dst_port, dst_ready))]
    for s in sups:
        s.start()

    _submit_specs(src_url, specs)
    _wait_progress(src_url, [t for t, _ in specs], min_gen=2)
    try:
        _post_drain(src_url, handoff=dst_url)
    except Exception:
        pass   # the source may die mid-response at the seam

    dst_abs = os.path.abspath(dst_root)

    def owner_of(tid: str) -> str:
        for rec in migration_mod.commits_for(src_root, tid):
            owner = rec.get("owner_root")
            if owner and os.path.abspath(owner) == dst_abs:
                return dst_url
        return src_url

    t0 = time.monotonic()
    digests, lost = _converge(owner_of, specs, converge_timeout_s)
    wall_s = time.monotonic() - t0

    stopping.set()
    for side in ("src", "dst"):
        p = procs[side]
        if p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()

    on_target = sorted(
        tid for tid, _ in specs
        if any(os.path.abspath(r.get("owner_root") or "") == dst_abs
               for r in migration_mod.commits_for(src_root, tid)))
    return {"digests": digests, "lost": lost,
            "kill_rc": kill_info["rc"],
            "adopted_by_target": on_target,
            "src_kinds": _kinds(_journal_rows(src_root)),
            "dst_kinds": _kinds(_journal_rows(dst_root)),
            "src_root": src_root, "dst_root": dst_root,
            "wall_s": round(wall_s, 3)}


def run_orphan_drill(root: str, *, n_tenants: int = 6,
                     ngen: Optional[int] = None,
                     kill_at_step: int = 4,
                     segment_len: int = 2, max_lanes: int = 8,
                     converge_timeout_s: float = 300.0,
                     python: str = sys.executable) -> Dict[str, Any]:
    """A fleet member dies mid-run; a live peer discovers the death
    through the federation metadata (recorded pid no longer alive)
    and adopts its accepted-not-terminal tenants. The dead member is
    NEVER restarted — every tenant must converge on the peer, bit-
    identical."""
    os.makedirs(root, exist_ok=True)
    fleet = os.path.join(root, "fleet")
    a_root, b_root = os.path.join(root, "a"), os.path.join(root, "b")
    specs = chaos_specs(n_tenants, ngen=ngen)
    a_port, b_port = _free_port(), _free_port()
    a_ready = os.path.join(root, "a.url")
    b_ready = os.path.join(root, "b.url")
    a_url = f"http://127.0.0.1:{a_port}"
    b_url = f"http://127.0.0.1:{b_port}"

    pa = _spawn_child(a_root, a_port, a_ready,
                      kill_at=kill_at_step,
                      segment_len=segment_len, max_lanes=max_lanes,
                      python=python, telemetry=True,
                      fleet_root=fleet, process_id="member-a")
    pb = _spawn_child(b_root, b_port, b_ready,
                      segment_len=segment_len, max_lanes=max_lanes,
                      python=python, telemetry=True,
                      fleet_root=fleet, process_id="member-b",
                      adopt_every=0.5)
    _wait_ready(pa, a_ready)
    _wait_ready(pb, b_ready)

    _submit_specs(a_url, specs)
    pa.wait()   # the deterministic kill
    kill_rc = pa.returncode

    # ownership follows adoption: a tenant 404s on the peer until its
    # orphan commit lands, then converges there. No re-offer — the
    # drill proves ADOPTION recovers the work, not client retry.
    def owner_of(tid: str) -> str:
        return b_url

    t_dead = time.monotonic()
    digests, lost = _converge(owner_of, specs, converge_timeout_s,
                              reoffer=False)
    adoption_s = time.monotonic() - t_dead

    if pb.poll() is None:
        pb.terminate()
        try:
            pb.wait(timeout=60)
        except subprocess.TimeoutExpired:
            pb.kill()

    return {"digests": digests, "lost": lost, "kill_rc": kill_rc,
            "peer_kinds": _kinds(_journal_rows(b_root)),
            "a_root": a_root, "b_root": b_root,
            "fleet_root": fleet,
            "adoption_s": round(adoption_s, 3)}


def run_upgrade_drill(root: str, *, n_tenants: int = 6,
                      ngen: Optional[int] = None,
                      old_version: str = "0.0.9+drill",
                      new_version: str = "0.1.1+drill",
                      segment_len: int = 2, max_lanes: int = 8,
                      converge_timeout_s: float = 300.0,
                      python: str = sys.executable) -> Dict[str, Any]:
    """Rolling upgrade under live load: an old-version child serves
    ``n_tenants`` (plus a known-answer canary); a new-version child
    starts with the compat gate open; ``POST /v1/drain?handoff=`` on
    the old child migrates every resident mid-run. The pin: zero lost
    jobs, all wire digests bit-identical to the uninterrupted
    reference, ``compat_restore`` journaled for the cross-version
    resumes, canaries green on both sides."""
    os.makedirs(root, exist_ok=True)
    fleet = os.path.join(root, "fleet")
    old_root = os.path.join(root, "old")
    new_root = os.path.join(root, "new")
    specs = chaos_specs(n_tenants, ngen=ngen)
    old_port, new_port = _free_port(), _free_port()
    old_ready = os.path.join(root, "old.url")
    new_ready = os.path.join(root, "new.url")
    old_url = f"http://127.0.0.1:{old_port}"
    new_url = f"http://127.0.0.1:{new_port}"

    po = _spawn_child(old_root, old_port, old_ready,
                      segment_len=segment_len, max_lanes=max_lanes,
                      python=python, telemetry=True, canary=True,
                      fleet_root=fleet, process_id="member-old",
                      version=old_version)
    # the new-version child boots BEFORE load is submitted: a rolling
    # upgrade drains into a warm replacement, and a cold ~10s jax
    # import here would let short jobs finish (and exit with the old
    # child) before the drain ever lands.
    pn = _spawn_child(new_root, new_port, new_ready,
                      segment_len=segment_len, max_lanes=max_lanes,
                      python=python, telemetry=True, canary=True,
                      fleet_root=fleet, process_id="member-new",
                      compat_restore=True, version=new_version)
    _wait_ready(po, old_ready)
    _wait_ready(pn, new_ready)
    _submit_specs(old_url, specs)
    _wait_progress(old_url, [t for t, _ in specs], min_gen=2)

    t_drain = time.monotonic()
    _post_drain(old_url, handoff=new_url)
    po.wait()
    old_rc = po.returncode
    drain_s = time.monotonic() - t_drain

    def owner_of(tid: str) -> str:
        return new_url

    digests, lost = _converge(owner_of, specs, converge_timeout_s)

    if pn.poll() is None:
        pn.terminate()
        try:
            pn.wait(timeout=60)
        except subprocess.TimeoutExpired:
            pn.kill()

    old_rows = _journal_rows(old_root)
    new_rows = _journal_rows(new_root)
    pauses = sorted(float(r.get("pause_s") or 0.0)
                    for r in old_rows
                    if r.get("kind") == "migration_offer"
                    and r.get("phase") == "transferred")
    return {"digests": digests, "lost": lost, "old_rc": old_rc,
            "drain_s": round(drain_s, 3),
            "migration_pauses_s": pauses,
            "old_kinds": _kinds(old_rows),
            "new_kinds": _kinds(new_rows),
            "old_root": old_root, "new_root": new_root}


if __name__ == "__main__":
    child_main()
