"""Network service plane — the RPC front end over the ask-tell scheduler.

PR 7 built the multi-tenant :class:`~deap_tpu.serving.scheduler.
Scheduler` and PR 9 gave it SLO instruments, but submit/result still
meant calling Python methods in-process. This module is the missing
half of the "millions of users" story (ROADMAP item 1): a
**stdlib-only** HTTP front end (``http.server.ThreadingHTTPServer`` +
JSON — no new dependency) that serves evolution as a network service,
with an autoscaling control loop closing the SLO feedback path and a
graceful drain that reuses the resilience plane's checkpoint machinery.

**The queue handoff.** The scheduler is a single-threaded data
structure by contract (:class:`~deap_tpu.serving.scheduler.
SchedulerBusyError`); an HTTP server is many threads by construction.
The service resolves this with one **driver thread** that owns the
scheduler outright (``Scheduler.bind_driver``): front-end request
threads never touch it — they enqueue commands onto a
``queue.Queue`` and read a driver-maintained **mirror** of job state
(status/gen/result, updated only by the driver, read under a lock).
Submissions round-trip through the queue (the reply carries the tenant
id); status/result/stream reads are pure mirror reads. The scheduler
therefore runs exactly as it does in-process — same admission order,
same segment cadence — which is what makes the service's per-tenant
results **bit-identical** to in-process runs (``bench.py --service``
gates on the wire digest).

**The fault-tolerance layer** (ISSUE 12 — the full catalogue, what the
client sees and what gets journaled, is the "Failure model" table in
``docs/advanced/serving.md``):

- *Durable admission.* Every accepted job is recorded in a
  crash-consistent **admission WAL** (:class:`~deap_tpu.serving.wal.
  AdmissionWAL` — CRC-framed records, fsync **before** the submit
  ACK) so a ``kill -9`` between accept and the driver's admission
  loses nothing: a restarted service replays every accepted-not-done
  record (rebuilding jobs from the problem registry — journaled
  ``wal_replay``), and tenants that already ran resume from their
  checkpoints. Client-supplied **idempotency keys** make submit
  retries safe: a duplicate key maps to the same tenant (journaled
  ``idempotent_replay``) instead of admitting a twin.
- *Deadlines.* A submit may carry ``deadline_s``; a request already
  expired at the front end gets 504 immediately, and a command whose
  deadline expires while queued is dropped by the driver **before**
  it reaches the scheduler (journaled ``deadline_exceeded``; result
  polls for that tenant return 504).
- *Load shedding.* ``max_pending`` bounds in-flight jobs and the
  command queue is bounded with it: past the bound, submits get
  **429 + Retry-After** — never a hang, never a 500 — journaled
  ``load_shed``. The stdlib client honours Retry-After with jittered
  exponential backoff (``resilience.retry.RetryPolicy``).
- *Driver watchdog.* With ``watchdog_s`` set, a monitor thread
  journals ``driver_stall`` (with the driver thread's stack) when no
  progress heartbeat lands within the budget, fires the
  HealthMonitor ``driver_stall`` alarm, flips ``/healthz`` to 503
  and — opt-in ``watchdog_exit`` — exits the process so a supervisor
  restarts into the WAL/checkpoint recovery path. Re-arms (and
  journals recovery) when the driver comes back.
- *Fault injection.* ``fault_plan`` fires deterministic
  service-shaped faults (:class:`~deap_tpu.resilience.faultinject.
  DropResponse` / ``DelaySegment`` / ``KillServiceAt`` / ``TornWAL``)
  at the driver-step, segment-boundary, response-write and WAL-append
  seams — the chaos harness (:mod:`deap_tpu.serving.chaos`,
  ``tests/test_service_chaos.py``) kills and restarts the service
  under live retrying load and pins bit-identical final digests.

**The wire protocol** (all JSON; newline-delimited on streams; every
response echoes an ``X-Request-Id`` — client-supplied or generated —
that is threaded through the journal for end-to-end tracing):

====================================  =================================
``POST /v1/jobs``                     submit ``{"problem", "params",
                                      "tenant_id"?, "idempotency_key"?,
                                      "deadline_s"?}`` →
                                      ``{"tenant_id"}``
``GET /v1/jobs/<id>``                 status ``{"status", "gen", "ngen"}``
``GET /v1/jobs/<id>/result[?wait=1]`` the wire-encoded result pytree
                                      (``serving.wire``: byte-exact
                                      arrays + digest); 504 when the
                                      job's deadline expired
``GET /v1/jobs/<id>/stream``          NDJSON per-segment events until a
                                      terminal event
``GET /healthz``                      liveness (``ok`` / ``warming`` /
                                      ``draining`` / ``stalled`` /
                                      ``degraded`` — firing alerts;
                                      only ``ok`` answers 200) + a
                                      JSON detail body (watchdog
                                      verdict, prewarm progress,
                                      startup phases, seconds since
                                      the last boundary, firing
                                      alerts, canary counters)
``GET /metrics``                      the scheduler's Prometheus
                                      registry (same text as
                                      ``serve_metrics`` — one port
                                      serves both planes)
``GET /v1/alerts``                    the burn-rate alert engine's
                                      state (per-rule state/burn
                                      rates + firing list)
``POST /v1/drain``                    begin graceful drain
====================================  =================================

**Problems, not pickles.** A network client cannot ship a toolbox;
the server is constructed with a registry of named **problem
factories** (``problems={"onemax": factory}``), each mapping a params
dict to a :class:`~deap_tpu.serving.tenant.Job`. Clients submit
``(problem, params)``; the server owns the program. Equal factories →
equal bucket keys → shared compiled programs across tenants, exactly
as in-process. Factories being pure functions of ``(tenant_id,
params)`` is also what makes WAL replay deterministic.

**Auth & quotas.** ``tokens={token: {"tenant": name, "max_jobs": n}}``
enables bearer-token auth: requests carry ``Authorization: Bearer
<token>``; a token sees only its own jobs; ``max_jobs`` bounds its
in-flight jobs (HTTP 429 past it). Rejections journal an
``auth_rejected`` event. *Within* the scheduler, fairness between
admitted tenants stays the existing ``fair_quantum`` eviction — quotas
bound admission, the quantum bounds residency.

**Autoscaling.** Every driver iteration (``autoscale_every``-th) reads
``Scheduler.slo_snapshot()`` (queue depth, queue-wait p99, occupancy,
per-resident gens-since-interaction — the PR 9 instruments plus the
ISSUE 12 idleness signal) into an :class:`~deap_tpu.serving.autoscale.
AutoscalePolicy`; applied decisions — lane-budget changes
(``set_bucket_lanes``), predicted-lattice prewarms
(``Scheduler.prewarm`` under the persistent compile cache) and
pressure spills (``request_spill``) — each journal an
``autoscale_decision`` event.

**Graceful drain.** On SIGTERM (:class:`deap_tpu.resilience.drain.
DrainSignal` — the resilience plane's signal pattern) or
``POST /v1/drain``: new submissions get 503, the in-flight segment
finishes, every resident tenant is checkpointed (tenant-stamped meta —
``Scheduler.checkpoint_all``), a ``service_drain`` event is journaled,
streams receive a terminal ``drained`` event, and the process may
exit. A new service over the same root replays the WAL and resumes
every drained tenant bit-exactly (``Scheduler(resume_tenants=True)``)
— pinned against an uninterrupted run by ``tests/test_service.py``.
"""

from __future__ import annotations

import contextlib
import http.server
import json
import os
import queue
import sys
import threading
import time
import traceback
import urllib.parse
from typing import Any, Callable, Dict, List, Optional, Tuple

from deap_tpu.resilience.faultinject import (InjectedCorruption,
                                             InjectedDrop,
                                             InjectedReject,
                                             corrupt_pytree)
from deap_tpu.serving import wire
from deap_tpu.serving.autoscale import AutoscaleConfig, AutoscalePolicy
from deap_tpu.serving.canary import CanaryRunner, CanarySpec
from deap_tpu.serving.scheduler import Scheduler
from deap_tpu.serving.tenant import Job, bucket_key
from deap_tpu.serving.wal import AdmissionWAL
from deap_tpu.telemetry import tracing
from deap_tpu.telemetry.alerts import (ALERT_STATE_VALUES, AlertEngine,
                                       service_rules)

__all__ = ["EvolutionService", "SERVICE_JOURNAL_KINDS"]

#: journal kinds this module writes (documented in the
#: docs/advanced/telemetry.md kind table; drift-gated by
#: tests/test_service.py). ``alert`` rows come from the burn-rate
#: engine (telemetry/alerts.py), ``canary_ok``/``canary_failed`` from
#: the known-answer canary runner (serving/canary.py) — both driven
#: from the service's boundary fan-out, so their rows land in the
#: scheduler journal alongside everything else.
SERVICE_JOURNAL_KINDS = ("service_request", "service_drain",
                         "autoscale_decision", "auth_rejected",
                         "wal_replay", "idempotent_replay",
                         "deadline_exceeded", "load_shed",
                         "driver_stall", "trace_span",
                         "startup_phase", "alert",
                         "canary_ok", "canary_failed",
                         "migration_offer", "migration_adopted",
                         "orphan_adopted", "compat_restore")

#: file the warm-handoff lattice manifest persists to, next to the WAL
WARM_MANIFEST_NAME = "warm_manifest.json"

#: warm-manifest file format; readers skip unknown formats
WARM_MANIFEST_FORMAT = 1

#: start the driver (and thus the warm-handoff prewarm) BEFORE the
#: WAL replay's job-factory builds, overlapping the two dominant
#: restart phases. A measured win on multicore hosts; on a single
#: hardware thread the two GIL-bound phases only contend, so the
#: flag lets the restart path fall back to sequential
_OVERLAP_REPLAY = os.environ.get(
    "DEAP_TPU_OVERLAP_REPLAY", "").lower() in ("1", "true", "yes") \
    or (os.cpu_count() or 1) > 1


class _HttpError(Exception):
    def __init__(self, code: int, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.code = code
        self.message = message
        self.headers = headers or {}


class _JobView:
    """The driver-maintained mirror of one job, readable by any
    front-end thread under the service lock. The driver writes; HTTP
    threads read — never the scheduler's own Tenant objects. The
    result is held raw and wire-encoded **lazily on the requesting
    thread** (cached), so a thousand finishing tenants never serialise
    base64 on the driver's critical path."""

    __slots__ = ("tenant_id", "problem", "token", "status", "gen",
                 "ngen", "error", "done", "request_id", "deadline",
                 "idempotency_key", "_raw", "_encoded", "_enc_lock")

    def __init__(self, tenant_id: str, problem: str, token: str,
                 request_id: str = "", deadline: Optional[float] = None,
                 idempotency_key: Optional[str] = None):
        self.tenant_id = tenant_id
        self.problem = problem
        self.token = token
        self.status = "submitted"
        self.gen = 0
        self.ngen: Optional[int] = None
        self.error: Optional[str] = None
        self.done = threading.Event()
        self.request_id = request_id
        #: absolute monotonic deadline for ADMISSION (None = none):
        #: the driver drops the submit command past it
        self.deadline = deadline
        self.idempotency_key = idempotency_key
        self._raw: Any = None
        self._encoded: Optional[Dict[str, Any]] = None
        self._enc_lock = threading.Lock()

    def set_result(self, raw: Any) -> None:
        self._raw = raw

    def result_payload(self) -> Optional[Dict[str, Any]]:
        if self._raw is None:
            return None
        with self._enc_lock:
            if self._encoded is None:
                self._encoded = wire.pack_result(self._raw)
            return self._encoded

    def as_dict(self) -> Dict[str, Any]:
        out = {"tenant_id": self.tenant_id, "problem": self.problem,
               "status": self.status, "gen": self.gen,
               "ngen": self.ngen}
        if self.error is not None:
            out["error"] = self.error
        return out


class EvolutionService:
    """Serve a :class:`Scheduler` over a loopback/LAN socket.

    :param root: scheduler root (journal + admission WAL + per-tenant
        run dirs); a restarted service over the same root replays the
        WAL and resumes drained/killed tenants.
    :param problems: ``{name: factory}`` where
        ``factory(tenant_id, params) -> Job`` builds the job
        server-side (the factory owns toolbox/key/init construction,
        so identical submissions are bit-reproducible — the WAL-replay
        determinism contract).
    :param tokens: ``{token: {"tenant": str, "max_jobs": int|None}}``
        bearer auth + per-token in-flight quota; ``None`` = open.
    :param autoscale: ``True`` (default policy) /
        :class:`AutoscalePolicy` / ``None`` (off).
    :param autoscale_every: driver steps between autoscale ticks.
    :param wal: admission WAL on/off (default on; off restores the
        PR 11 lose-on-kill admission, for overhead comparisons only).
    :param max_pending: bound on in-flight (not yet terminal) jobs —
        past it, submits are shed with 429 + ``Retry-After``
        (``load_shed`` journaled); ``None`` = unbounded. The command
        queue is bounded alongside it.
    :param retry_after_s: the ``Retry-After`` value (seconds) sent
        with shed/quota 429s.
    :param max_poll_s: server-side clamp for client-supplied long-poll
        ``timeout=`` values (malformed values are a 400, never a 500).
    :param watchdog_s: driver-stall budget: with no driver heartbeat
        for this long, journal ``driver_stall`` (+ stack dump), fire
        the HealthMonitor ``driver_stall`` alarm, flip ``/healthz`` to
        503. ``None`` = no watchdog.
    :param watchdog_exit: escalate a detected stall to process exit
        (``os._exit``) so a supervisor restarts into WAL/checkpoint
        recovery. Off by default.
    :param health: a :class:`~deap_tpu.telemetry.probes.HealthMonitor`
        receiving the watchdog's ``driver_stall`` alarms.
    :param fault_plan: a :class:`~deap_tpu.resilience.faultinject.
        FaultPlan` fired at the service's deterministic seams
        (``step`` / ``boundary`` / ``segment`` / ``http_response`` /
        ``wal_append`` / ``migration``; ``segment`` fires INSIDE the
        scheduler's segment-latency window, so a ``DelaySegment``
        there is attributable to the segment phase; ``migration``
        fires at the ownership-transfer seams ``after_offer`` /
        ``before_adopted`` / ``before_transferred`` — see
        ``KillDuringHandoff``) — the chaos-test hook.
    :param step_hook: optional ``hook(step_count)`` run on the driver
        thread after every scheduler step — the deterministic
        fault-injection seam (drain-mid-segment tests, bursty-load
        generators) in the spirit of ``resilience/faultinject.py``.
    :param alerts: the burn-rate alert plane (ISSUE 19): ``True``
        (default) builds an :class:`~deap_tpu.telemetry.alerts.
        AlertEngine` over :func:`~deap_tpu.telemetry.alerts.
        service_rules` (canary failures, shed rate, deadline-miss
        rate), journaling ``alert`` transition rows and serving
        ``GET /v1/alerts`` + the ``deap_alert_state`` gauge; pass an
        engine instance for custom rules, or ``None``/``False`` to
        disable. Firing alerts flip ``/healthz`` to ``degraded``
        (503).
    :param canary: a :class:`~deap_tpu.serving.canary.CanarySpec`
        (or prebuilt :class:`~deap_tpu.serving.canary.CanaryRunner`)
        enabling known-answer canary tenants — fixed-seed jobs
        submitted through the real front end at a boundary cadence,
        digest-checked against a precomputed (or trust-on-first-use)
        reference. ``None`` (default) = no canaries.
    :param compat_restore: open the checkpoint compat gate (ISSUE
        20): this build may restore checkpoints stamped by a
        DIFFERENT deap_tpu version — the rolling-upgrade adoption
        path. Every cross-version restore journals a
        ``compat_restore`` row; with the gate closed (default) such
        restores raise ``CheckpointFormatError`` loudly.
    :param scheduler_kwargs: forwarded to :class:`Scheduler`
        (``max_lanes``, ``segment_len``, ``fair_quantum``,
        ``metrics``, ``compile_cache``, ``trace_sample`` — the
        distributed-tracing knob: spans from the HTTP front end, the
        WAL fsync, the command queue and the scheduler lifecycle all
        land in the scheduler journal as ``trace_span`` rows, …).
    """

    def __init__(self, root: str,
                 problems: Dict[str, Callable[[str, dict], Job]], *,
                 host: str = "127.0.0.1", port: int = 0,
                 tokens: Optional[Dict[str, dict]] = None,
                 autoscale=None, autoscale_every: int = 1,
                 wal: bool = True,
                 max_pending: Optional[int] = None,
                 retry_after_s: float = 1.0,
                 max_poll_s: float = 600.0,
                 watchdog_s: Optional[float] = None,
                 watchdog_exit: bool = False,
                 health=None,
                 fault_plan=None,
                 step_hook: Optional[Callable[[int], None]] = None,
                 alerts=True,
                 canary=None,
                 compat_restore: bool = False,
                 **scheduler_kwargs):
        self.root = str(root)
        self.problems = dict(problems)
        self.tokens = dict(tokens) if tokens else None
        if autoscale is True:
            autoscale = AutoscalePolicy(AutoscaleConfig())
        self.policy: Optional[AutoscalePolicy] = autoscale or None
        self.autoscale_every = max(1, int(autoscale_every))
        self.max_pending = (int(max_pending) if max_pending else None)
        self.retry_after_s = float(retry_after_s)
        self.max_poll_s = float(max_poll_s)
        self.watchdog_s = (float(watchdog_s) if watchdog_s else None)
        self.watchdog_exit = bool(watchdog_exit)
        self.health = health
        self.fault_plan = fault_plan
        self.step_hook = step_hook
        scheduler_kwargs.setdefault("resume_tenants", True)
        self.scheduler = Scheduler(self.root,
                                   boundary_cb=self._on_boundary,
                                   fault_hook=self._sched_fault,
                                   **scheduler_kwargs)
        self.journal = self.scheduler.journal

        # ---- active observability plane (ISSUE 19): burn-rate alert
        # engine + known-answer canary tenants, both driven from the
        # boundary fan-out on the driver thread (deterministic order,
        # no extra threads, no clocks inside the engine)
        if alerts is True:
            alerts = AlertEngine(service_rules(),
                                 journal=self.journal,
                                 on_transition=self._on_alert)
        elif alerts:
            if alerts.journal is None:
                alerts.journal = self.journal
            if alerts.on_transition is None:
                alerts.on_transition = self._on_alert
        self.alerts: Optional[AlertEngine] = alerts or None
        if isinstance(canary, CanarySpec):
            canary = CanaryRunner(canary)
        self.canary: Optional[CanaryRunner] = canary or None
        self._canary_token: Optional[str] = None
        if self.canary is not None and self.tokens is not None:
            # internal quota-free bearer identity for the canary's
            # own submits; never handed out
            self._canary_token = "canary-" + os.urandom(12).hex()
            self.tokens[self._canary_token] = {"tenant": "canary",
                                               "max_jobs": None}
        self._last_boundary: Optional[float] = None
        # previous boundary's cumulative load counters — the deltas
        # are the live shed/deadline-miss rate samples the alert
        # engine burns on
        self._prev_load = {"arrivals": 0, "sheds": 0,
                           "deadline_misses": 0}

        self._lock = threading.Lock()
        # job factories run eager array ops; dozens of request threads
        # dispatching eagerly at once contend on the runtime — bound
        # the concurrency (2 builders keeps construction overlapped
        # with the driver without thrashing it)
        self._build_sem = threading.Semaphore(2)
        self._views: Dict[str, _JobView] = {}
        self._subs: Dict[str, List[queue.Queue]] = {}
        # bounded command queue: overload surfaces as a 429 at submit
        # time, never as an unbounded memory queue behind a wedged
        # driver (maxsize 0 = unbounded when load shedding is off)
        self._cmds: "queue.Queue" = queue.Queue(
            maxsize=(max(64, 4 * self.max_pending)
                     if self.max_pending else 0))
        self._seq = 0
        self._rid_seq = 0
        self._steps = 0
        self._idem: Dict[str, str] = {}   # idempotency key -> tenant
        # ---- zero-downtime operations (ISSUE 20): live migration
        # sequencing, durable-adoption index (offer_id -> tenant, for
        # idempotent re-offers), and the drain?handoff peer target
        self._migration_seq = 0
        self._adopted_offers: Dict[str, str] = {}
        self._handoff_peer: Optional[str] = None
        if compat_restore:
            # rolling upgrade: this (newer) build may restore
            # checkpoints stamped by a different deap_tpu version —
            # every such restore is journaled as ``compat_restore``
            from deap_tpu.support.checkpoint import set_compat_restore
            set_compat_restore(True)
        self._touched: set = set()        # tenant ids polled since
        #                                   the driver's last drain of
        #                                   the interaction set
        self._rep_jobs: Dict[str, Job] = {}   # driver-thread only
        self._drain_req = threading.Event()
        self._drained = threading.Event()
        self._closed = False
        # watchdog state: the driver refreshes _beat at every loop
        # iteration; the monitor compares against watchdog_s
        self._beat = time.monotonic()
        self._stalled = False
        self._watch_stop = threading.Event()
        self._exit_fn = os._exit   # injectable for tests

        # ---- startup ledger: phase wall-times journaled as
        # ``startup_phase`` rows + the deap_service_startup_phase_
        # seconds{phase} histogram (docs/advanced/coldstart.md)
        self._t_start = time.monotonic()
        self._startup_phases: Dict[str, float] = {}
        self._first_result_pending = True
        from deap_tpu.support import checkpoint as _ckpt_mod
        self._restore_s0 = _ckpt_mod.restore_seconds_total()
        # ---- warm handoff: the previous process's bucket-lattice
        # manifest (problem/params/lanes/horizon per bucket), read
        # BEFORE the driver starts; non-empty → the driver prewarms
        # the recorded lattice before pumping any submit, and
        # /healthz answers "warming" (503) until it finishes
        self._warm_manifest_path = os.path.join(self.root,
                                                WARM_MANIFEST_NAME)
        self._warm_recorded: Dict[str, Dict[str, Any]] = {}
        self._warm_dirty = False
        self._warm_plan = self._read_warm_manifest()
        self._warming = bool(self._warm_plan)
        # prewarm progress for the /healthz detail body
        self._warm_total = len(self._warm_plan)
        self._warm_done = 0

        # ---- durable admission: open (healing any torn tail) and
        # replay the WAL BEFORE any thread starts — recovered jobs are
        # queued as ordinary submit commands the driver applies first
        self.wal: Optional[AdmissionWAL] = None
        if wal:
            self.wal = AdmissionWAL(os.path.join(self.root,
                                                 "admission.wal"))
        # the driver starts FIRST: with a warm manifest present it
        # begins prewarming the recorded lattice immediately, fully
        # overlapped with the main thread's WAL replay below (the
        # job-factory builds) — the two dominant restart phases run
        # concurrently instead of back to back. The replay batch is
        # still the first submit command the driver can see: the HTTP
        # server (the only other producer) starts after replay.
        self._driver = threading.Thread(target=self._drive,
                                        name="deap-tpu-service-driver",
                                        daemon=True)
        if not _OVERLAP_REPLAY:
            if self.wal is not None:
                t0 = time.perf_counter()
                self._replay_wal()
                self._note_startup_phase(
                    "wal_replay", time.perf_counter() - t0)
        self._driver.start()
        if _OVERLAP_REPLAY and self.wal is not None:
            t0 = time.perf_counter()
            self._replay_wal()
            self._note_startup_phase(
                "wal_replay", time.perf_counter() - t0)

        self._httpd = _ServiceHTTPServer((host, port), self)
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="deap-tpu-service-http", daemon=True)
        self._http_thread.start()
        self._watchdog = None
        if self.watchdog_s:
            self._watchdog = threading.Thread(
                target=self._watch, name="deap-tpu-service-watchdog",
                daemon=True)
            self._watchdog.start()
        self.journal.event("service_request", route="start",
                           url=self.url,
                           problems=sorted(self.problems),
                           auth=self.tokens is not None,
                           autoscale=self.policy is not None,
                           wal=self.wal is not None,
                           watchdog_s=self.watchdog_s)

    # ------------------------------------------------- WAL admission ----

    def _replay_wal(self) -> None:
        """Resubmit every accepted-not-done WAL record: jobs that ran
        resume from their tenant-stamped checkpoints, jobs killed
        before admission re-run deterministically from their problem
        factory. Runs in ``__init__`` before the HTTP server exists,
        so replay can never race a fresh submit for the same
        idempotency key — the key map is complete before the first
        request lands."""
        state = self.wal.replay()
        # ownership resolution (ISSUE 20): pending tenants may have
        # been migrated/adopted away while we were down — the commit
        # files and peer WALs decide, and resolved tenants leave
        # state.pending before any job is built
        from deap_tpu.serving import migration as _migration
        transferred_away = _migration.resolve_replay(self, state)
        for oid, rec in state.adoptions.items():
            tid = str(rec.get("tenant_id") or "")
            if tid in state.pending:
                self._adopted_offers[oid] = tid
        self._idem.update(state.idempotency)
        replayed, failed = [], []
        batch: List[Tuple[Job, str]] = []
        for tid, rec in state.pending.items():
            problem = rec.get("problem")
            view = _JobView(tid, str(problem), str(rec.get("token", "")),
                            request_id=str(rec.get("request_id", "")),
                            idempotency_key=rec.get("idempotency_key"))
            self._views[tid] = view
            factory = self.problems.get(problem)
            if factory is None:
                view.status = "failed"
                view.error = f"unknown problem {problem!r} at replay"
                view.done.set()
                self._wal_done(tid, "failed")
                failed.append(tid)
                continue
            try:
                job = factory(tid, dict(rec.get("params") or {}))
            except Exception as e:
                view.status = "failed"
                view.error = f"{type(e).__name__}: {e}"
                view.done.set()
                self._wal_done(tid, "failed")
                failed.append(tid)
                continue
            job.request_id = rec.get("request_id") or None
            job._wal_params = dict(rec.get("params") or {})
            view.ngen = int(job.ngen)
            view.status = "recovered"
            batch.append((job, str(problem)))
            replayed.append(tid)
            # stitch the recovered job back onto its original trace:
            # the request id in the WAL record derives the same
            # trace id the pre-kill process used, and the replay
            # span parents on the request's deterministic root span
            # — one waterfall across the restart, no orphans
            tr = self.scheduler.tracer
            if tr is not None and job.request_id:
                tr.emit("request.replay", 0.0,
                        ctx=tr.context_for(job.request_id),
                        phase="replay", always=True, tenant_id=tid,
                        problem=str(problem))
        if batch:
            # ONE command for the whole recovered cohort: the driver
            # repacks all N tenants in a single boundary instead of N
            # one-at-a-time admissions — and a 200-tenant replay can
            # never deadlock a bounded command queue while the driver
            # is still busy prewarming
            self._cmds.put(("submit_many", batch))
        if state.records or state.tear_offset is not None:
            self.journal.event(
                "wal_replay", records=len(state.records),
                replayed=sorted(replayed), failed=sorted(failed),
                transferred=sorted(transferred_away),
                idempotency_keys=len(state.idempotency),
                torn_tail=state.tear_offset is not None)

    def _wal_accept_batch(self, fresh, token: str,
                          request_id: str) -> None:
        """One durability point for a whole submit batch: N accept
        records, one write, ONE fsync — the ACK follows only after
        the last record is on disk."""
        if self.wal is None or not fresh:
            return
        self.wal.append_many([
            ("accept", dict(tenant_id=job.tenant_id, problem=problem,
                            params=getattr(job, "_wal_params", None),
                            idempotency_key=view.idempotency_key,
                            request_id=request_id, token=token))
            for job, view, problem in fresh])
        self._fire_fault("wal_append", path=self.wal.path,
                         seq=self.wal.n_appended)

    def _wal_done(self, tenant_id: str, status: str) -> None:
        if self.wal is not None:
            try:
                self.wal.append("done", tenant_id=tenant_id,
                                status=status)
            except ValueError:
                pass  # closing race: the WAL replays it next start

    # ---------------------------------------- warm handoff + startup ----

    def _note_startup_phase(self, phase: str, seconds: float) -> None:
        """One startup-waterfall slice: journaled (``startup_phase``)
        and observed on ``deap_service_startup_phase_seconds``."""
        seconds = round(float(seconds), 6)
        self._startup_phases[phase] = seconds
        self.journal.event("startup_phase", phase=phase,
                           seconds=seconds)
        reg = self.scheduler.metrics
        if reg is not None:
            from deap_tpu.telemetry.metrics import \
                startup_phase_histogram
            startup_phase_histogram(reg).observe(seconds, phase=phase)

    def _read_warm_manifest(self) -> List[Dict[str, Any]]:
        """The previous process's lattice records (tolerant read: a
        missing/torn/foreign-format manifest is an empty plan)."""
        try:
            with open(self._warm_manifest_path, "r") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return []
        if not isinstance(doc, dict) \
                or doc.get("format") != WARM_MANIFEST_FORMAT:
            return []
        buckets = doc.get("buckets")
        return [b for b in buckets if isinstance(b, dict)] \
            if isinstance(buckets, list) else []

    def _write_warm_manifest(self) -> None:
        """Atomically persist the live lattice next to the WAL —
        driver thread only (it owns ``_warm_recorded``)."""
        doc = {"format": WARM_MANIFEST_FORMAT,
               "buckets": [dict(v, label=k) for k, v in
                           sorted(self._warm_recorded.items())]}
        tmp = self._warm_manifest_path + ".tmp"
        try:
            with open(tmp, "w") as fh:
                json.dump(doc, fh, sort_keys=True, indent=1)
            os.replace(tmp, self._warm_manifest_path)
            self._warm_dirty = False
        except OSError:
            pass  # best-effort: a missed write only costs warmth

    def _record_warm_bucket(self, label: str, problem: str,
                            params: Optional[dict], lanes: int,
                            horizon: int) -> None:
        """Fold one bucket observation into the warm manifest (driver
        thread only); persists when the lattice actually changed."""
        prev = self._warm_recorded.get(label)
        entry = {"problem": str(problem),
                 "params": dict(params or {}),
                 "lanes": int(lanes), "horizon": int(horizon)}
        if prev is not None:
            # keep the first representative's params (any tenant of
            # the bucket reproduces the same programs — bucket_key is
            # tenant-blind), refresh only the tuned knobs: per-tenant
            # param churn then never rewrites the manifest
            entry["problem"], entry["params"] = \
                prev["problem"], prev["params"]
            entry["horizon"] = max(entry["horizon"], prev["horizon"])
        if prev != entry:
            self._warm_recorded[label] = entry
            self._warm_dirty = True
        if self._warm_dirty:
            self._write_warm_manifest()

    def _warm_start(self) -> None:
        """Prewarm the recorded lattice BEFORE the driver pumps any
        command — runs on the driver thread (the scheduler's exclusive
        owner), so the replayed cohort's first repack finds its
        programs already loaded (from the artifact store when one is
        active, else compiled). ``/healthz`` answers ``warming`` (503)
        for the duration; any failure degrades to a normal cold start."""
        plan, self._warm_plan = self._warm_plan, []
        if not plan:
            self._warming = False
            return
        t0 = time.perf_counter()
        warmed = 0
        try:
            for rec in plan:
                self._warm_done += 1   # buckets attempted, for /healthz
                factory = self.problems.get(str(rec.get("problem")))
                if factory is None:
                    continue
                try:
                    job = factory("__prewarm__",
                                  dict(rec.get("params") or {}))
                    warmed += self.scheduler.prewarm(
                        [job], lane_counts=[int(rec.get("lanes", 1))])
                except Exception:
                    continue  # cold-compile fallback for this bucket
        finally:
            self._warming = False
            self._note_startup_phase("prewarm",
                                     time.perf_counter() - t0)
            self.journal.event("service_request", route="warm_start",
                               buckets=len(plan), warmed=warmed)

    def _note_first_result(self) -> None:
        """First tenant completion after start: close the startup
        ledger (restore delta + start→first-result wall)."""
        self._first_result_pending = False
        from deap_tpu.support.checkpoint import restore_seconds_total
        self._note_startup_phase(
            "restore", restore_seconds_total() - self._restore_s0)
        self._note_startup_phase(
            "first_result", time.monotonic() - self._t_start)

    def _fire_fault(self, event: str, **ctx) -> None:
        if self.fault_plan is not None:
            self.fault_plan.fire(event, **ctx)

    def _on_alert(self, tr: Dict[str, Any]) -> None:
        """Alert transitions → the ``deap_alert_state{name}`` gauge
        (0 inactive/resolved, 1 pending, 2 firing)."""
        reg = self.scheduler.metrics
        if reg is not None:
            from deap_tpu.telemetry.metrics import alert_state_gauge
            alert_state_gauge(reg).set(
                ALERT_STATE_VALUES[tr["to"]], name=tr["name"])

    def _alarm_metric(self, kind: str) -> None:
        """HealthMonitor alarms → ``deap_alarms_total{kind}`` —
        alarms used to reach only the journal (ISSUE 19 satellite)."""
        reg = self.scheduler.metrics
        if reg is not None:
            from deap_tpu.telemetry.metrics import alarms_total
            alarms_total(reg).inc(kind=kind)

    def _sched_fault(self, event: str, **ctx) -> None:
        """The scheduler's fault seam (``fault_hook``), stamped with
        the driver step count so step-addressed faults
        (``DelaySegment(step=n, event="segment")``) fire inside the
        segment-latency window of a chosen step."""
        self._fire_fault(event, step=self._steps + 1, **ctx)

    # ------------------------------------------------------- tracing ----

    def trace_context(self, request_id: str,
                      traceparent: Optional[str] = None):
        """The request's :class:`~deap_tpu.telemetry.tracing.
        TraceContext` (honouring an incoming ``traceparent`` header),
        or ``None`` when the scheduler was built without
        ``trace_sample``."""
        tr = self.scheduler.tracer
        if tr is None:
            return None
        return tr.context_for(request_id, traceparent)

    def _tspan(self, name: str, **kw):
        """A tracer span bound to the ambient request context — a
        no-op context manager when tracing is off or the caller is
        outside a traced request."""
        tr = self.scheduler.tracer
        if tr is None or tracing.current() is None:
            return contextlib.nullcontext()
        return tr.span(name, **kw)

    def _result_payload(self, view: _JobView):
        """``view.result_payload()`` with the first (cache-filling)
        wire encode timed into the *submitting* request's trace — a
        later poll pays the encode, so the span joins the trace that
        owns the tenant, not the poll's."""
        tr = self.scheduler.tracer
        if tr is None or not view.request_id \
                or view._encoded is not None or view._raw is None:
            return view.result_payload()
        t0 = time.perf_counter()
        payload = view.result_payload()
        tr.emit("wire.encode", time.perf_counter() - t0,
                ctx=tr.context_for(view.request_id),
                phase="wire_encode", tenant_id=view.tenant_id)
        return payload

    # ----------------------------------------------------- lifecycle ----

    @property
    def draining(self) -> bool:
        return self._drain_req.is_set()

    @property
    def stalled(self) -> bool:
        """The watchdog's current verdict (``/healthz`` mirrors it)."""
        return self._stalled

    def drain(self, wait: bool = True,
              timeout: Optional[float] = None) -> bool:
        """Begin graceful drain: refuse new submissions, finish the
        in-flight segment, checkpoint every resident tenant, journal
        ``service_drain``, end streams. Safe to call from any thread —
        including a signal handler (``wait=False`` there). Returns
        True once drained (always True when ``wait=False``... check
        :attr:`drained`)."""
        self._drain_req.set()
        try:
            self._cmds.put_nowait(("wake",))
        except queue.Full:
            pass  # the driver polls the drain flag regardless
        if wait:
            return self._drained.wait(timeout)
        return True

    @property
    def drained(self) -> bool:
        return self._drained.is_set()

    # ------------------------------------- zero-downtime operations ----

    def migrate(self, tenant_id: str, target_url: str,
                timeout_s: float = 30.0,
                wait_s: float = 120.0) -> Dict[str, Any]:
        """Live-migrate one tenant to the peer service at
        ``target_url``. Callable from any thread: the migration
        itself runs on the driver (extraction is a scheduler
        mutation), this call waits for its reply. Returns the
        migration result dict (``{"migrated": True, ...}`` /
        ``{"reclaimed": True, ...}``)."""
        reply: "queue.Queue" = queue.Queue()
        self._cmds.put(("migrate", str(tenant_id), str(target_url),
                        float(timeout_s), reply))
        try:
            return reply.get(timeout=wait_s)
        except queue.Empty:
            raise TimeoutError(
                f"migration of {tenant_id!r} did not complete within "
                f"{wait_s}s")

    def adopt_orphans(self, fleet_root: str,
                      process_id: Optional[str] = None) -> List[str]:
        """Adopt accepted-not-terminal tenants of DEAD fleet members
        (PR 19 federation root) onto this service; returns the
        adopted tenant ids. See
        :func:`deap_tpu.serving.migration.adopt_orphans`."""
        from deap_tpu.serving import migration as _migration
        return _migration.adopt_orphans(self, fleet_root,
                                        process_id=process_id)

    def _finish_migrated_view(self, tenant_id: str,
                              target: str) -> None:
        """Terminal bookkeeping for a transferred tenant: its view
        goes ``migrated`` (the re-offer signal for clients — like
        ``drained``, but naming a live new home) and its stream
        ends."""
        with self._lock:
            view = self._views.get(tenant_id)
        if view is None:
            return
        view.status = "migrated"
        view.error = None
        self._publish(tenant_id, {"event": "migrated",
                                  "tenant_id": tenant_id,
                                  "gen": view.gen, "target": target})
        self._publish(tenant_id, None)
        view.done.set()

    def _migration_candidates(self) -> List[str]:
        """Tenants eligible for a drain hand-off: live, service-
        admitted, not a canary (canaries are known-answer probes of
        THIS process — they die with it)."""
        skip: Tuple[str, ...] = ()
        if self.canary is not None:
            skip = (self.canary.spec.tenant_prefix,)
        out = []
        for tid, t in self.scheduler.tenants.items():
            if t.done:
                continue
            if any(tid.startswith(p) for p in skip):
                continue
            with self._lock:
                v = self._views.get(tid)
            if v is None or v.done.is_set():
                continue
            out.append(tid)
        return sorted(out)

    def install_signal_handlers(self):
        """Install a SIGTERM/SIGINT → :meth:`drain` handler (main
        thread only); returns the :class:`~deap_tpu.resilience.drain.
        DrainSignal` so the caller can uninstall it."""
        from deap_tpu.resilience.drain import DrainSignal
        ds = DrainSignal(lambda signum: self.drain(wait=False))
        ds.install()
        return ds

    def close(self, timeout: float = 30.0) -> None:
        if self._closed:
            return
        self._closed = True
        self._watch_stop.set()
        self.drain(wait=True, timeout=timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._http_thread.join(timeout=5)
        self._driver.join(timeout=timeout)
        if self.wal is not None:
            self.wal.close()

    def __enter__(self) -> "EvolutionService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ----------------------------------------------------- the driver ----

    def _drive(self) -> None:
        sched = self.scheduler
        sched.bind_driver()
        try:
            # warm handoff: restore the previous process's lattice
            # before touching the command queue — the WAL-replay batch
            # then repacks against already-loaded programs
            self._warm_start()
            while not self._drain_req.is_set():
                self._beat = time.monotonic()
                self._drain_touches()
                runnable = sched.runnable
                n = self._pump_commands(block=not runnable)
                # admission grace: while submissions are streaming in,
                # give the queue a few 10 ms windows before stepping —
                # rapid-fire submits land in ONE repack at a warmed
                # lattice point instead of compiling a 1-lane program
                # for the first arrival (measured: a 2.1 s stall)
                grace = 0
                while n and grace < 5 and not self._drain_req.is_set():
                    time.sleep(0.01)
                    n = self._pump_commands(block=False)
                    grace += 1
                if self._drain_req.is_set():
                    break
                if sched.runnable:
                    sched.step()
                    self._steps += 1
                    self._beat = time.monotonic()
                    self._fire_fault("step", step=self._steps)
                    if self.step_hook is not None:
                        self.step_hook(self._steps)
                    if self._steps % self.autoscale_every == 0:
                        self._autoscale_tick()
                elif self.canary is not None:
                    # idle bootstrap: with nothing runnable there are
                    # no boundaries, so the canary primes itself here
                    self.canary.prime(self)
            # ------------------------------------------- graceful drain
            self._pump_commands(block=False)
            # drain?handoff=<peer>: migrate residents to the peer
            # instead of parking them — a rolling upgrade's zero-
            # downtime path. Failures fall back to the park-and-
            # checkpoint drain below (migrate_tenant reclaims on any
            # refused/unreachable offer, so a failed candidate is
            # back in the scheduler for checkpoint_all)
            migrated: List[str] = []
            peer = self._handoff_peer
            if peer:
                from deap_tpu.serving import migration as _migration
                for tid in self._migration_candidates():
                    try:
                        res = _migration.migrate_tenant(self, tid,
                                                        peer)
                    except Exception as e:
                        self.journal.event(
                            "migration_offer", phase="error",
                            tenant_id=tid, target=peer,
                            error=f"{type(e).__name__}: {e}")
                        continue
                    if res.get("migrated"):
                        migrated.append(tid)
            saved = sched.checkpoint_all()
            open_views = []
            with self._lock:
                for v in self._views.values():
                    if not v.done.is_set():
                        v.status = "drained"
                        open_views.append(v)
            self.journal.event(
                "service_drain",
                checkpointed=sorted(saved),
                open_tenants=sorted(v.tenant_id for v in open_views),
                migrated=sorted(migrated),
                steps=self._steps)
            for v in open_views:
                self._publish(v.tenant_id,
                              {"event": "drained",
                               "tenant_id": v.tenant_id, "gen": v.gen})
                self._publish(v.tenant_id, None)
                v.done.set()
        finally:
            try:
                sched.close()
            finally:
                self._drained.set()

    def _drain_touches(self) -> None:
        """Fold the front end's interaction set into the tenants'
        idleness clocks (the spill actuator's signal) — driver thread
        only, so the scheduler contract holds."""
        with self._lock:
            if not self._touched:
                return
            touched, self._touched = self._touched, set()
        for tid in touched:
            t = self.scheduler.tenants.get(tid)
            if t is not None:
                t.note_interaction()

    def _pump_commands(self, block: bool) -> int:
        try:
            cmd = self._cmds.get(timeout=0.05) if block \
                else self._cmds.get_nowait()
        except queue.Empty:
            return 0
        n = 0
        while True:
            self._apply(cmd)
            n += 1
            try:
                cmd = self._cmds.get_nowait()
            except queue.Empty:
                return n

    def _apply(self, cmd: Tuple) -> None:
        if cmd[0] == "wake":
            return
        if cmd[0] == "submit":
            _, job, problem = cmd
            self._apply_submit(job, problem)
        elif cmd[0] == "submit_many":
            # 3-tuples are WAL-replay era commands with no enqueue
            # stamp; fresh submits carry one for the cmd.queue span
            t_enq = cmd[2] if len(cmd) > 2 else None
            for job, problem in cmd[1]:
                self._apply_submit(job, problem, t_enq=t_enq)
        elif cmd[0] == "migrate":
            _, tid, target, timeout_s, reply = cmd
            from deap_tpu.serving import migration as _migration
            try:
                res = _migration.migrate_tenant(self, tid, target,
                                                timeout_s=timeout_s)
            except Exception as e:
                res = {"migrated": False,
                       "error": f"{type(e).__name__}: {e}"}
                self.journal.event("migration_offer", phase="error",
                                   tenant_id=tid, target=target,
                                   error=res["error"])
            reply.put(res)

    def _apply_submit(self, job: Job, problem: str,
                      t_enq: Optional[float] = None) -> None:
        # admission is ASYNCHRONOUS: the front end already built the
        # Job (factories run on request threads — they must be
        # thread-safe pure constructors), ACKed, and registered the
        # view; the driver only performs the single-threaded scheduler
        # mutation. Scheduler-side errors surface through the mirror
        # (status "failed") and the stream's terminal event.
        tid = job.tenant_id
        with self._lock:
            view = self._views[tid]
        # the command-queue handoff latency (front-end ACK → driver
        # pickup) as a detail span — sampled, per tenant
        tr = self.scheduler.tracer
        if tr is not None and t_enq is not None and view.request_id:
            tr.emit("cmd.queue", max(0.0, time.monotonic() - t_enq),
                    ctx=tr.context_for(view.request_id),
                    tenant_id=tid)
        # deadline admission control: an expired command is DROPPED
        # here — it never reaches the scheduler; the client's result
        # poll sees 504
        if view.deadline is not None \
                and time.monotonic() > view.deadline:
            view.error = "deadline expired before admission"
            view.status = "deadline_exceeded"
            view.done.set()
            self.journal.event("deadline_exceeded", tenant_id=tid,
                               problem=problem, stage="driver",
                               request_id=view.request_id)
            self.scheduler.note_deadline_miss()
            self._wal_done(tid, "deadline_exceeded")
            self._publish(tid, {"event": "deadline_exceeded",
                                "tenant_id": tid})
            self._publish(tid, None)
            return
        try:
            self.scheduler.submit(job)
        except Exception as e:
            view.error = f"{type(e).__name__}: {e}"
            view.status = "failed"
            view.done.set()
            self.journal.event("service_request", route="submit",
                               tenant_id=tid, problem=problem,
                               request_id=view.request_id,
                               error=view.error)
            self._wal_done(tid, "failed")
            self._publish(tid, {"event": "failed", "tenant_id": tid,
                                "error": view.error})
            self._publish(tid, None)
            return
        bucket = self.scheduler.buckets[bucket_key(job)]
        self._rep_jobs.setdefault(bucket.label, job)
        self._record_warm_bucket(bucket.label, problem,
                                 getattr(job, "_wal_params", None),
                                 bucket.max_lanes, bucket.horizon)
        tenant = self.scheduler.tenants[tid]
        view.status = ("resuming" if tenant.has_checkpoint
                       else "queued")
        self.journal.event("service_request", route="submit",
                           tenant_id=tid, problem=problem,
                           request_id=view.request_id,
                           resume=tenant.has_checkpoint)

    # boundary fan-out: runs on the driver thread inside step()
    def _on_boundary(self, bucket_label: str,
                     updates: List[Dict[str, Any]]) -> None:
        self._beat = time.monotonic()
        self._last_boundary = self._beat
        self._fire_fault("boundary", step=self._steps + 1,
                         bucket=bucket_label)
        for u in updates:
            t = u["tenant"]
            with self._lock:
                view = self._views.get(t.id)
                has_subs = bool(self._subs.get(t.id))
            if view is None:
                continue
            view.gen = u["gen"]
            ev = {"event": "segment", "tenant_id": t.id,
                  "bucket": bucket_label,
                  "gen_from": u["gen_before"], "gen": u["gen"]}
            if has_subs and u["chunk"] is not None:
                # the per-segment results: this segment's logbook
                # record rows, byte-exact on the wire
                ev["records"] = wire.pack(u["chunk"])
            self._publish(t.id, ev)
            if u["finished"]:
                raw = t.result
                try:
                    # the silent-wrong-answer seam: a CorruptResult
                    # fault raises here and the raw result is
                    # perturbed BEFORE the view publishes it — every
                    # success signal below still fires, only the
                    # canary's digest compare can tell
                    self._fire_fault("result", step=self._steps + 1,
                                     tenant_id=t.id)
                except InjectedCorruption:
                    raw = corrupt_pytree(raw)
                view.set_result(raw)
                view.status = t.status
                self._wal_done(t.id, t.status)
                if self._first_result_pending:
                    self._note_first_result()
                view.done.set()
                self._publish(t.id, {"event": t.status,
                                     "tenant_id": t.id,
                                     "gen": u["gen"]})
                self._publish(t.id, None)
        if self.canary is not None or self.alerts is not None:
            self._observability_tick()

    def _observability_tick(self) -> None:
        """Driver thread, once per boundary fan-out: canary verdicts
        and cadence submissions first (so an injected corruption is
        alarmed at the boundary it finishes), then the live alert
        samples — this boundary's shed/deadline-miss deltas — and one
        deterministic alert-engine tick."""
        t = time.monotonic() - self._t_start
        if self.canary is not None:
            self.canary.on_boundary(self, t)
        if self.alerts is None:
            return
        counts = self.scheduler.load_counts()
        arrivals = sum(counts["arrivals"].values())
        d_arr = arrivals - self._prev_load["arrivals"]
        d_shed = counts["sheds"] - self._prev_load["sheds"]
        d_miss = (counts["deadline_misses"]
                  - self._prev_load["deadline_misses"])
        self._prev_load = {"arrivals": arrivals,
                           "sheds": counts["sheds"],
                           "deadline_misses": counts["deadline_misses"]}
        offered = d_arr + d_shed
        if offered > 0:
            self.alerts.observe(t, "shed_rate", d_shed / offered)
            self.alerts.observe(t, "deadline_miss_rate",
                                d_miss / max(1, d_arr))
        self.alerts.tick(t)

    # ------------------------------------------------------ watchdog ----

    def _watch(self) -> None:
        """The driver-stall monitor: compare the driver's heartbeat
        against ``watchdog_s``; on a stall, journal ``driver_stall``
        with a stack dump of the driver thread, fire the HealthMonitor
        alarm, flip ``/healthz`` to 503 and (opt-in) escalate to
        process exit so a supervisor restarts into WAL/checkpoint
        recovery. Re-arms — and journals the recovery — when the
        heartbeat returns."""
        interval = min(self.watchdog_s / 4.0, 0.5)
        while not self._watch_stop.wait(interval):
            if self._drain_req.is_set():
                # drain's checkpoint_all can legitimately take long;
                # the watchdog stands down once drain begins
                continue
            age = time.monotonic() - self._beat
            if age <= self.watchdog_s:
                if self._stalled:
                    self._stalled = False
                    self.journal.event("driver_stall", recovered=True,
                                       steps=self._steps)
                continue
            if self._stalled:
                continue  # already reported; wait for recovery
            self._stalled = True
            frames = sys._current_frames().get(self._driver.ident)
            stack = ("".join(traceback.format_stack(frames))
                     if frames is not None
                     else "<driver thread not running>")
            self.journal.event(
                "driver_stall", stalled_s=round(age, 3),
                steps=self._steps, budget_s=self.watchdog_s,
                escalate=self.watchdog_exit, stack=stack[-4000:])
            if self.health is not None:
                self.health.driver_stall(stalled_s=round(age, 3),
                                         steps=self._steps)
            self._alarm_metric("driver_stall")
            if self.watchdog_exit:
                # no drain, no flush beyond the journal line above
                # (journal writes flush per row): the recovery path is
                # the supervisor restarting into WAL replay + resume
                self._exit_fn(70)

    def _autoscale_tick(self) -> None:
        if self.policy is None:
            return
        sched = self.scheduler
        snap = sched.slo_snapshot()
        decision = self.policy.decide(snap)
        if not decision:
            return
        for label, n in decision.lane_counts.items():
            before = snap[label]["lanes"]
            applied = sched.set_bucket_lanes(label, n)
            if label in self._warm_recorded:
                # the tuned knob follows into the warm manifest, so a
                # restart prewarms the lattice point the autoscaler
                # actually converged on, not the configured default
                rec = self._warm_recorded[label]
                self._record_warm_bucket(label, rec["problem"],
                                         rec["params"], applied,
                                         rec["horizon"])
            self.journal.event(
                "autoscale_decision", action="lanes", bucket=label,
                lanes_from=before, lanes_to=applied,
                reason=decision.reasons.get(label, ""),
                queue_depth=snap[label]["queue_depth"],
                queue_wait_p99=snap[label]["queue_wait_p99"])
        for label, n in decision.prewarm:
            job = self._rep_jobs.get(label)
            if job is None:
                continue
            # compile the predicted lattice point in the BACKGROUND:
            # XLA compilation releases the GIL, so the driver keeps
            # stepping while the program the next scale-up needs is
            # built — a prewarm on the driver thread measured as a
            # multi-second admission stall under burst load. The
            # worker touches only the engine's jit caches (thread-safe
            # in jax), never scheduler state.
            threading.Thread(
                target=self._background_prewarm, args=(label, n),
                name=f"deap-tpu-prewarm-{n}", daemon=True).start()
        for tid in decision.spill:
            try:
                sched.request_spill(tid)
            except KeyError:
                continue
            t = sched.tenants.get(tid)
            self.journal.event("autoscale_decision", action="spill",
                               tenant_id=tid,
                               **(sched._rid(t) if t is not None
                                  else {}))

    def _background_prewarm(self, label: str, n_lanes: int) -> None:
        """Compile one (bucket, lane-count) lattice point off the
        driver thread. Reads the bucket's engine/horizon once and runs
        an inactive dummy batch through the jitted segment — pure
        compile-cache population, no scheduler state touched."""
        import numpy as np
        try:
            bucket = self.scheduler._bucket_by(label)
        except KeyError:
            return
        eng, horizon = bucket.engine, bucket.horizon
        job = self._rep_jobs.get(label)
        if job is None:
            return
        t0 = time.perf_counter()
        try:
            lane = eng.lane_init(job.key, job.init, job.ngen,
                                 job.hyper)
            probe = eng.pack([lane], n_lanes=n_lanes, horizon=horizon)
            probe["ngen"] = np.zeros_like(np.asarray(probe["ngen"]))
            eng.advance(probe, self.scheduler.segment_len)
        except Exception as e:
            self.journal.event("autoscale_decision", action="prewarm",
                               bucket=label, lanes=n_lanes,
                               error=f"{type(e).__name__}: {e}")
            return
        self.journal.event(
            "autoscale_decision", action="prewarm", bucket=label,
            lanes=n_lanes, background=True,
            compile_s=round(time.perf_counter() - t0, 4))

    # ------------------------------------------------- pub/sub plumbing ----

    def _subscribe(self, tid: str) -> "queue.Queue":
        q: "queue.Queue" = queue.Queue()
        with self._lock:
            self._subs.setdefault(tid, []).append(q)
        return q

    def _unsubscribe(self, tid: str, q: "queue.Queue") -> None:
        with self._lock:
            subs = self._subs.get(tid, [])
            if q in subs:
                subs.remove(q)
            if not subs:
                self._subs.pop(tid, None)

    def _publish(self, tid: str, event: Optional[dict]) -> None:
        with self._lock:
            subs = list(self._subs.get(tid, []))
        for q in subs:
            q.put(event)

    # ----------------------------------------------------- HTTP surface ----

    def _auth(self, headers) -> Tuple[str, dict]:
        """Returns (token, info); raises :class:`_HttpError` (and
        journals ``auth_rejected``) on missing/unknown tokens."""
        if self.tokens is None:
            return "", {}
        auth = headers.get("Authorization", "")
        token = auth[7:] if auth.startswith("Bearer ") else ""
        if not token:
            self.journal.event("auth_rejected", reason="missing_token")
            raise _HttpError(401, "missing bearer token")
        info = self.tokens.get(token)
        if info is None:
            self.journal.event("auth_rejected", reason="unknown_token")
            raise _HttpError(403, "unknown token")
        return token, info

    def _active_jobs(self, token: Optional[str] = None) -> int:
        with self._lock:
            return sum(1 for v in self._views.values()
                       if not v.done.is_set()
                       and (token is None or v.token == token))

    def _check_quota(self, token: str, info: dict,
                     n_new: int = 1) -> None:
        max_jobs = info.get("max_jobs") if info else None
        if max_jobs is None:
            return
        active = self._active_jobs(token)
        if active + n_new > int(max_jobs):
            self.journal.event(
                "auth_rejected", reason="quota",
                tenant=info.get("tenant"), max_jobs=int(max_jobs),
                active=active)
            raise _HttpError(
                429,
                f"quota exceeded: {active} in-flight + "
                f"{n_new} new jobs > max_jobs={max_jobs}",
                headers={"Retry-After": self._retry_after()})

    def _retry_after(self) -> str:
        return str(max(1, int(round(self.retry_after_s))))

    def _check_load(self, n_new: int, request_id: str) -> None:
        """The load-shedding gate: past ``max_pending`` in-flight
        jobs, submits are refused with 429 + Retry-After — the bounded
        queue never hangs a client and never 500s."""
        if self.max_pending is None:
            return
        active = self._active_jobs()
        if active + n_new > self.max_pending:
            self.journal.event("load_shed", active=active,
                               new=n_new,
                               max_pending=self.max_pending,
                               request_id=request_id)
            self.scheduler.note_shed(n_new)
            raise _HttpError(
                429,
                f"overloaded: {active} jobs in flight + {n_new} new "
                f"> max_pending={self.max_pending}; retry later",
                headers={"Retry-After": self._retry_after()})

    def _view_for(self, tid: str, token: str) -> _JobView:
        with self._lock:
            view = self._views.get(tid)
        if view is None:
            raise _HttpError(404, f"unknown tenant {tid!r}")
        if self.tokens is not None and view.token != token:
            self.journal.event("auth_rejected", reason="foreign_tenant",
                               tenant_id=tid)
            raise _HttpError(403, "tenant belongs to another token")
        with self._lock:
            self._touched.add(tid)
        return view

    def _q_float(self, qs, name: str, default: float,
                 max_value: Optional[float] = None) -> float:
        """Parse one float query parameter defensively: malformed
        values are a 400 (never an unhandled ValueError → 500) and the
        result is clamped to ``[0, max_value]`` — an unclamped
        client-supplied ``timeout=`` must not pin a request thread
        for an arbitrary duration (service.py:677,701 pre-ISSUE 12)."""
        raw = qs.get(name, [None])[0]
        if raw is None or raw == "":
            value = float(default)
        else:
            try:
                value = float(raw)
            except ValueError:
                raise _HttpError(400, f"malformed {name}={raw!r}: "
                                      "expected a number")
        if value < 0.0:
            value = 0.0
        if max_value is not None:
            value = min(value, float(max_value))
        return value

    def _deadline_of(self, spec: dict, headers) -> Optional[float]:
        """The spec's admission deadline as an absolute monotonic
        stamp (``deadline_s`` field, falling back to an
        ``X-Deadline-S`` request header); malformed values are 400."""
        raw = spec.get("deadline_s")
        if raw is None:
            raw = headers.get("X-Deadline-S")
        if raw is None:
            return None
        try:
            d = float(raw)
        except (TypeError, ValueError):
            raise _HttpError(400, f"malformed deadline_s={raw!r}")
        return time.monotonic() + max(0.0, d)

    def _build_one(self, spec: dict, token: str, info: dict):
        problem = spec.get("problem")
        if problem not in self.problems:
            raise _HttpError(404, f"unknown problem {problem!r} "
                                  f"(have: {sorted(self.problems)})")
        tid = spec.get("tenant_id")
        if tid is None:
            with self._lock:
                self._seq += 1
                prefix = (info.get("tenant", "job")
                          if info else "job")
                tid = f"{prefix}-{self._seq}"
        tid = str(tid)
        # build the Job HERE, on the request thread: factories are
        # pure constructors (seed → arrays), so clients construct jobs
        # off the driver's critical path — moving this to the driver
        # measured ~2.7 s of serial admission stall at 1k tenants.
        # Construction errors report synchronously; the semaphore
        # bounds concurrent eager dispatch. tenant_id collisions are
        # re-checked at registration.
        params = dict(spec.get("params") or {})
        try:
            with self._build_sem:
                job = self.problems[problem](tid, dict(params))
        except Exception as e:
            raise _HttpError(400, f"{type(e).__name__}: {e}")
        if job.tenant_id != tid:
            raise _HttpError(400,
                             f"problem factory {problem!r} returned "
                             f"tenant id {job.tenant_id!r}, expected "
                             f"{tid!r}")
        # stash the raw params for the WAL accept record (replay
        # rebuilds the job through the same factory)
        job._wal_params = params
        view = _JobView(tid, problem, token)
        view.ngen = int(job.ngen)
        return job, view, problem

    def _idem_hit(self, key: Optional[str], token: str
                  ) -> Optional[_JobView]:
        """An existing tenant for this idempotency key (token-checked)
        — the safe-retry path: the client's first submit may have been
        accepted and durably WAL-logged while its response was lost."""
        if not key:
            return None
        with self._lock:
            tid = self._idem.get(str(key))
            view = self._views.get(tid) if tid is not None else None
        if view is None:
            return None
        if self.tokens is not None and view.token != token:
            self.journal.event("auth_rejected", reason="foreign_tenant",
                               tenant_id=view.tenant_id)
            raise _HttpError(403, "idempotency key belongs to another "
                                  "token")
        return view

    def _handle_submit(self, body: dict, token: str, info: dict,
                       headers, request_id: str) -> Tuple[int, dict]:
        """Single (``{"problem", "params", "tenant_id"?,
        "idempotency_key"?, "deadline_s"?}``) or batch
        (``{"jobs": [spec, ...]}``) submission — the batch form costs
        one HTTP round trip for N jobs, which matters when the client
        and server share cores."""
        if self.draining:
            raise _HttpError(503, "service is draining",
                             headers={"Retry-After": self._retry_after()})
        specs = body.get("jobs")
        batch = specs is not None
        if not batch:
            specs = [body]
        if not isinstance(specs, list) or not specs:
            raise _HttpError(400, '"jobs" must be a non-empty list')

        # resolve idempotent replays FIRST: retries of already-accepted
        # jobs cost no quota, no load-shed slot, no rebuild
        resolved: List[Optional[_JobView]] = []
        n_new = 0
        for s in specs:
            if not isinstance(s, dict):
                raise _HttpError(400, "each job spec must be an object")
            hit = self._idem_hit(s.get("idempotency_key"), token)
            resolved.append(hit)
            if hit is None:
                n_new += 1
            else:
                self.journal.event("idempotent_replay",
                                   tenant_id=hit.tenant_id,
                                   via="idempotency_key",
                                   request_id=request_id)
        if n_new:
            self._check_quota(token, info, n_new=n_new)
            self._check_load(n_new, request_id)

        # deadlines: a spec already expired at the front end is 504
        # right here — it never enters the command queue
        deadlines = [self._deadline_of(s, headers) for s in specs]
        now = time.monotonic()
        for s, d, hit in zip(specs, deadlines, resolved):
            if hit is None and d is not None and now > d:
                self.journal.event("deadline_exceeded",
                                   tenant_id=s.get("tenant_id"),
                                   problem=s.get("problem"),
                                   stage="frontend",
                                   request_id=request_id)
                self.scheduler.note_deadline_miss()
                raise _HttpError(504, "deadline expired before "
                                      "admission")

        built = []   # (job, view, problem) for the genuinely-new specs
        with self._tspan("submit.build", phase="build",
                         n_jobs=n_new):
            for s, hit, d in zip(specs, resolved, deadlines):
                if hit is not None:
                    continue
                job, view, problem = self._build_one(s, token, info)
                view.request_id = request_id
                view.deadline = d
                view.idempotency_key = s.get("idempotency_key")
                built.append((job, view, problem))
        with self._lock:
            dup = []
            for i, (job, view, _) in enumerate(built):
                old = self._views.get(job.tenant_id)
                if old is None:
                    continue
                if old.problem == view.problem \
                        and old.status != "failed":
                    # tenant-id replay: the same identity resubmitted
                    # (a post-restart client re-offering a drained/
                    # recovered job) maps to the live view instead of
                    # admitting a twin or 409ing the resume path
                    built[i] = (None, old, view.problem)
                else:
                    dup.append(job.tenant_id)
            if dup:
                raise _HttpError(409, f"tenant id(s) {dup} already "
                                      "submitted")
            for job, view, _ in built:
                if job is None:
                    continue
                self._views[job.tenant_id] = view
                if view.idempotency_key:
                    self._idem[str(view.idempotency_key)] = \
                        job.tenant_id
        fresh = [(job, view, problem) for job, view, problem in built
                 if job is not None]
        for i, (job, view, problem) in enumerate(built):
            if job is None and view.status != "failed":
                self.journal.event("idempotent_replay",
                                   tenant_id=view.tenant_id,
                                   via="tenant_id",
                                   request_id=request_id)
        for job, _, _ in fresh:
            job.request_id = request_id
        # durability point: every accept record is fsync'd BEFORE the
        # ACK below — "the client heard yes" implies "a restart
        # replays it" (one fsync for the whole batch)
        wal_cm = (self._tspan("wal.fsync", phase="wal_fsync",
                              always=True, n_jobs=len(fresh))
                  if self.wal is not None and fresh
                  else contextlib.nullcontext())
        with wal_cm:
            self._wal_accept_batch(fresh, token, request_id)
        if fresh:
            # async admission: ACK now, the driver applies at its next
            # command pump — a request thread never waits out a segment
            try:
                self._cmds.put_nowait(
                    ("submit_many",
                     [(job, problem) for job, _, problem in fresh],
                     time.monotonic()))
            except queue.Full:
                # bounded command queue saturated: shed — the WAL
                # records stand, so a retry (or restart) replays them;
                # views are withdrawn so the retry is a fresh submit
                with self._lock:
                    for job, view, _ in fresh:
                        self._views.pop(job.tenant_id, None)
                        if view.idempotency_key:
                            self._idem.pop(str(view.idempotency_key),
                                           None)
                self.journal.event(
                    "load_shed", reason="command_queue_full",
                    new=len(fresh), request_id=request_id)
                self.scheduler.note_shed(len(fresh))
                raise _HttpError(
                    429, "command queue full; retry later",
                    headers={"Retry-After": self._retry_after()})
        if self._drained.is_set():
            # lost race with a concurrent drain: the driver's final
            # pump may never see this command — fail the views loudly
            for job, view, _ in fresh:
                view.status = "drained"
                view.done.set()
        # the response tenant ids, in spec order (replays included)
        tids = []
        it = iter(built)
        for hit in resolved:
            if hit is not None:
                tids.append(hit.tenant_id)
            else:
                tids.append(next(it)[1].tenant_id)
        if batch:
            return 200, {"tenant_ids": tids, "status": "submitted"}
        return 200, {"tenant_id": tids[0], "status": "submitted"}

    def handle(self, method: str, path: str, headers, body: bytes,
               request_id: str = "") -> Tuple[int, str, bytes, bool]:
        """Route one request; returns (code, content-type, body,
        stream?) — ``stream`` means the caller takes over the socket
        (NDJSON). Front-end threads only: never touches the
        scheduler."""
        parsed = urllib.parse.urlparse(path)
        route = parsed.path.rstrip("/") or "/"
        qs = urllib.parse.parse_qs(parsed.query)
        if route == "/healthz" and method == "GET":
            firing = (self.alerts.firing()
                      if self.alerts is not None else [])
            status = ("stalled" if self._stalled
                      else "draining" if self.draining
                      else "warming" if self._warming
                      else "degraded" if firing else "ok")
            code = 200 if status == "ok" else 503
            # the detail body is additive (ISSUE 19): existing probes
            # keep the status-string + 200-only-on-ok contract
            out = {
                "status": status,
                "jobs": len(self._views),
                "problems": sorted(self.problems),
                "watchdog": {"enabled": self.watchdog_s is not None,
                             "budget_s": self.watchdog_s,
                             "stalled": self._stalled},
                "warming": {"active": self._warming,
                            "buckets_done": self._warm_done,
                            "buckets_total": self._warm_total},
                "startup_phases": dict(self._startup_phases),
                "seconds_since_boundary": (
                    round(time.monotonic() - self._last_boundary, 3)
                    if self._last_boundary is not None else None),
                "steps": self._steps,
                "firing_alerts": firing,
            }
            if self.canary is not None:
                out["canary"] = self.canary.snapshot()
            return code, "application/json", \
                json.dumps(out).encode(), False
        if route == "/metrics" and method == "GET":
            # the unified serving surface: the same registry text
            # serve_metrics() exposes, on the service's own port
            reg = self.scheduler.metrics
            text = reg.metrics_text() if reg is not None else ""
            return 200, ("text/plain; version=0.0.4; charset=utf-8"), \
                text.encode(), False
        if route == "/v1/alerts" and method == "GET":
            # unauthenticated like /healthz and /metrics: the alert
            # surface is operator plumbing, not tenant data
            eng = self.alerts
            out = {"alerts": (eng.snapshot()
                              if eng is not None else []),
                   "firing": (eng.firing()
                              if eng is not None else []),
                   "transitions": (len(eng.transitions)
                                   if eng is not None else 0)}
            return 200, "application/json", \
                json.dumps(out).encode(), False
        if route == "/v1/migrate" and method == "POST":
            # peer-to-peer adoption endpoint (ISSUE 20): a source
            # driver offers one tenant (spec + inline checkpoint
            # bytes); the reply is the adoption ACK. Unauthenticated
            # like /healthz — peer identity is deployment plumbing
            # (loopback/LAN trust), not tenant data: the adopted
            # job's own token rides in the offer and gates all
            # subsequent client access exactly as it did on the
            # source.
            from deap_tpu.serving import migration as _migration
            spec = json.loads(body or b"{}")
            self.journal.event(
                "service_request", route="migrate",
                request_id=request_id,
                tenant_id=str(spec.get("tenant_id") or ""),
                offer_id=str(spec.get("offer_id") or ""))
            code, out = _migration.adopt_tenant(self, spec)
            return code, "application/json", \
                json.dumps(out).encode(), False
        token, info = self._auth(headers)
        if route == "/v1/jobs" and method == "POST":
            payload = json.loads(body or b"{}")
            # the request's ROOT span: deterministic id derived from
            # the request id, so post-restart replay spans can parent
            # onto it without the original row (always on — the
            # waterfall's spine). A client traceparent, if any, is
            # already the ambient context and becomes its parent.
            with self._tspan("request",
                             span_id=tracing.root_span_id(request_id),
                             always=True, route="/v1/jobs"):
                code, out = self._handle_submit(payload, token, info,
                                                headers, request_id)
            return code, "application/json", \
                json.dumps(out).encode(), False
        if route == "/v1/drain" and method == "POST":
            # ?handoff=<peer-url>: migrate residents to the peer
            # instead of parking them (rolling upgrade, ISSUE 20)
            peer = qs.get("handoff", [None])[0]
            if peer:
                self._handoff_peer = str(peer)
            self.journal.event("service_request", route="drain",
                               request_id=request_id,
                               handoff=peer or None)
            self.drain(wait=False)
            out = {"draining": True, "handoff": peer or None}
            return 200, "application/json", \
                json.dumps(out).encode(), False
        if route == "/v1/results" and method == "GET":
            # batch result fetch: one request, N tenants — the
            # long-poll deadline is shared across the batch
            ids = [i for i in qs.get("ids", [""])[0].split(",") if i]
            if not ids:
                raise _HttpError(400, "ids=<tid,[tid...]> required")
            views = [self._view_for(urllib.parse.unquote(tid), token)
                     for tid in ids]
            if qs.get("wait", ["0"])[0] not in ("0", ""):
                deadline = time.monotonic() + self._q_float(
                    qs, "timeout", default=min(300.0, self.max_poll_s),
                    max_value=self.max_poll_s)
                for v in views:
                    v.done.wait(max(0.0,
                                    deadline - time.monotonic()))
            out = {}
            for v in views:
                entry = v.as_dict()
                payload = (self._result_payload(v)
                           if v.done.is_set() else None)
                if payload is not None:
                    entry["result"] = payload
                out[v.tenant_id] = entry
            return 200, "application/json", \
                json.dumps({"results": out}).encode(), False
        if route.startswith("/v1/jobs/") and method == "GET":
            parts = route.split("/")[3:]
            tid = urllib.parse.unquote(parts[0])
            sub = parts[1] if len(parts) > 1 else ""
            view = self._view_for(tid, token)
            if sub == "":
                return 200, "application/json", \
                    json.dumps(view.as_dict()).encode(), False
            if sub == "result":
                if qs.get("wait", ["0"])[0] not in ("0", ""):
                    timeout = self._q_float(
                        qs, "timeout",
                        default=min(300.0, self.max_poll_s),
                        max_value=self.max_poll_s)
                    view.done.wait(timeout)
                if view.status == "deadline_exceeded":
                    return 504, "application/json", \
                        json.dumps(view.as_dict()).encode(), False
                if not view.done.is_set():
                    return 202, "application/json", \
                        json.dumps(view.as_dict()).encode(), False
                out = view.as_dict()
                payload = self._result_payload(view)
                if payload is not None:
                    out["result"] = payload
                return 200, "application/json", \
                    json.dumps(out).encode(), False
            if sub == "stream":
                return 200, "application/x-ndjson", b"", True
        raise _HttpError(404, f"no route {method} {route}")

    def next_request_id(self, headers) -> str:
        """The request's trace id: the client's ``X-Request-Id`` when
        present, else a generated one — echoed in the response header
        and stamped into every journal row the request touches."""
        rid = headers.get("X-Request-Id")
        if rid:
            return str(rid)[:64]
        with self._lock:
            self._rid_seq += 1
            return f"req-{os.getpid():x}-{self._rid_seq:x}"

    def stream_events(self, tid: str, token: str, write_line) -> None:
        """Drive one NDJSON stream: status line first, then every
        published event until the terminal sentinel (or service
        close). Runs on the request thread; reads only the mirror."""
        view = self._view_for(tid, token)
        q = self._subscribe(tid)
        try:
            write_line({"event": "status", **view.as_dict()})
            if view.done.is_set():
                # finished before we subscribed: emit the terminal
                # event directly from the mirror
                write_line({"event": view.status,
                            "tenant_id": tid, "gen": view.gen})
                return
            while True:
                try:
                    ev = q.get(timeout=0.5)
                except queue.Empty:
                    if self._drained.is_set() or self._closed:
                        return
                    continue
                if ev is None:
                    return
                write_line(ev)
        finally:
            self._unsubscribe(tid, q)


class _ServiceHTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, service: EvolutionService):
        self.service = service
        super().__init__(addr, _Handler)


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def svc(self) -> EvolutionService:
        return self.server.service  # type: ignore[attr-defined]

    def log_message(self, *args):  # requests are journal rows, not logs
        pass

    def _respond(self, code: int, ctype: str, payload: bytes,
                 extra: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _drop_check(self, route: str) -> bool:
        """Fire the fault plan's ``http_response`` seam; True means
        the fault already decided the response — an injected drop
        (connection closed without replying) or an injected 429
        (answered here with Retry-After). Either way the request's
        server-side effects stand."""
        try:
            self.svc._fire_fault("http_response", route=route,
                                 method=self.command)
        except InjectedDrop:
            self.close_connection = True
            return True
        except InjectedReject as e:
            # the loadgen's deterministic retry-storm source: every
            # rejected client sees the same Retry-After and comes
            # back in one herd — counted as a shed like a real 429
            self.svc.journal.event("load_shed", reason="injected_429",
                                   route=route)
            self.svc.scheduler.note_shed()
            self._respond(
                429, "application/json",
                json.dumps({"error": str(e)}).encode(),
                extra={"Retry-After":
                       str(max(1, int(round(e.retry_after_s))))})
            return True
        return False

    def _dispatch(self, method: str) -> None:
        rid = self.svc.next_request_id(self.headers)
        # trace propagation: a client traceparent continues the
        # client's trace; otherwise (with tracing on) the context
        # derives deterministically from the request id. Echoed in the
        # response so the client can correlate either way.
        tctx = self.svc.trace_context(rid,
                                      self.headers.get("traceparent"))
        ids = {"X-Request-Id": rid}
        if tctx is not None:
            ids["traceparent"] = tctx.traceparent()
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            try:
                with tracing.use(tctx):
                    code, ctype, payload, stream = self.svc.handle(
                        method, self.path, self.headers, body, rid)
            except _HttpError as e:
                if self._drop_check(self.path):
                    return
                self._respond(e.code, "application/json", json.dumps(
                    {"error": e.message}).encode(),
                    extra={**ids, **e.headers})
                return
            except json.JSONDecodeError as e:
                self._respond(400, "application/json", json.dumps(
                    {"error": f"bad JSON body: {e}"}).encode(),
                    extra=ids)
                return
            if self._drop_check(self.path):
                return
            if not stream:
                self._respond(code, ctype, payload, extra=ids)
                return
            # NDJSON stream: no Content-Length; the connection closes
            # when the stream ends (HTTP/1.1 read-until-close)
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            for k, v in ids.items():
                self.send_header(k, v)
            self.send_header("Connection", "close")
            self.end_headers()

            def write_line(ev: dict) -> None:
                self.wfile.write(json.dumps(ev).encode() + b"\n")
                self.wfile.flush()

            parsed = urllib.parse.urlparse(self.path)
            tid = urllib.parse.unquote(parsed.path.rstrip("/")
                                       .split("/")[3])
            token, _ = self.svc._auth(self.headers)
            self.svc.stream_events(tid, token, write_line)
            self.close_connection = True
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True

    def do_GET(self):  # noqa: N802 (http.server API)
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")
