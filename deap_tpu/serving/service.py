"""Network service plane — the RPC front end over the ask-tell scheduler.

PR 7 built the multi-tenant :class:`~deap_tpu.serving.scheduler.
Scheduler` and PR 9 gave it SLO instruments, but submit/result still
meant calling Python methods in-process. This module is the missing
half of the "millions of users" story (ROADMAP item 1): a
**stdlib-only** HTTP front end (``http.server.ThreadingHTTPServer`` +
JSON — no new dependency) that serves evolution as a network service,
with an autoscaling control loop closing the SLO feedback path and a
graceful drain that reuses the resilience plane's checkpoint machinery.

**The queue handoff.** The scheduler is a single-threaded data
structure by contract (:class:`~deap_tpu.serving.scheduler.
SchedulerBusyError`); an HTTP server is many threads by construction.
The service resolves this with one **driver thread** that owns the
scheduler outright (``Scheduler.bind_driver``): front-end request
threads never touch it — they enqueue commands onto a
``queue.Queue`` and read a driver-maintained **mirror** of job state
(status/gen/result, updated only by the driver, read under a lock).
Submissions round-trip through the queue (the reply carries the tenant
id); status/result/stream reads are pure mirror reads. The scheduler
therefore runs exactly as it does in-process — same admission order,
same segment cadence — which is what makes the service's per-tenant
results **bit-identical** to in-process runs (``bench.py --service``
gates on the wire digest).

**The wire protocol** (all JSON; newline-delimited on streams):

====================================  =================================
``POST /v1/jobs``                     submit ``{"problem", "params",
                                      "tenant_id"?}`` → ``{"tenant_id"}``
``GET /v1/jobs/<id>``                 status ``{"status", "gen", "ngen"}``
``GET /v1/jobs/<id>/result[?wait=1]`` the wire-encoded result pytree
                                      (``serving.wire``: byte-exact
                                      arrays + digest)
``GET /v1/jobs/<id>/stream``          NDJSON per-segment events until a
                                      terminal event
``GET /healthz``                      liveness (``ok`` / ``draining``)
``GET /metrics``                      the scheduler's Prometheus
                                      registry (same text as
                                      ``serve_metrics`` — one port
                                      serves both planes)
``POST /v1/drain``                    begin graceful drain
====================================  =================================

**Problems, not pickles.** A network client cannot ship a toolbox;
the server is constructed with a registry of named **problem
factories** (``problems={"onemax": factory}``), each mapping a params
dict to a :class:`~deap_tpu.serving.tenant.Job`. Clients submit
``(problem, params)``; the server owns the program. Equal factories →
equal bucket keys → shared compiled programs across tenants, exactly
as in-process.

**Auth & quotas.** ``tokens={token: {"tenant": name, "max_jobs": n}}``
enables bearer-token auth: requests carry ``Authorization: Bearer
<token>``; a token sees only its own jobs; ``max_jobs`` bounds its
in-flight jobs (HTTP 429 past it). Rejections journal an
``auth_rejected`` event. *Within* the scheduler, fairness between
admitted tenants stays the existing ``fair_quantum`` eviction — quotas
bound admission, the quantum bounds residency.

**Autoscaling.** Every driver iteration (``autoscale_every``-th) reads
``Scheduler.slo_snapshot()`` (queue depth, queue-wait p99, occupancy —
the PR 9 instruments) into an :class:`~deap_tpu.serving.autoscale.
AutoscalePolicy`; applied decisions — lane-budget changes
(``set_bucket_lanes``), predicted-lattice prewarms
(``Scheduler.prewarm`` under the persistent compile cache) and
pressure spills (``request_spill``) — each journal an
``autoscale_decision`` event.

**Graceful drain.** On SIGTERM (:class:`deap_tpu.resilience.drain.
DrainSignal` — the resilience plane's signal pattern) or
``POST /v1/drain``: new submissions get 503, the in-flight segment
finishes, every resident tenant is checkpointed (tenant-stamped meta —
``Scheduler.checkpoint_all``), a ``service_drain`` event is journaled,
streams receive a terminal ``drained`` event, and the process may
exit. A new service over the same root resumes every drained tenant
bit-exactly on resubmission (``Scheduler(resume_tenants=True)``) —
pinned against an uninterrupted run by ``tests/test_service.py``.
"""

from __future__ import annotations

import http.server
import json
import queue
import threading
import time
import urllib.parse
from typing import Any, Callable, Dict, List, Optional, Tuple

from deap_tpu.serving import wire
from deap_tpu.serving.autoscale import AutoscaleConfig, AutoscalePolicy
from deap_tpu.serving.scheduler import Scheduler
from deap_tpu.serving.tenant import Job, bucket_key

__all__ = ["EvolutionService", "SERVICE_JOURNAL_KINDS"]

#: journal kinds this module writes (documented in the
#: docs/advanced/telemetry.md kind table; drift-gated by
#: tests/test_service.py)
SERVICE_JOURNAL_KINDS = ("service_request", "service_drain",
                         "autoscale_decision", "auth_rejected")


class _HttpError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class _JobView:
    """The driver-maintained mirror of one job, readable by any
    front-end thread under the service lock. The driver writes; HTTP
    threads read — never the scheduler's own Tenant objects. The
    result is held raw and wire-encoded **lazily on the requesting
    thread** (cached), so a thousand finishing tenants never serialise
    base64 on the driver's critical path."""

    __slots__ = ("tenant_id", "problem", "token", "status", "gen",
                 "ngen", "error", "done", "_raw", "_encoded",
                 "_enc_lock")

    def __init__(self, tenant_id: str, problem: str, token: str):
        self.tenant_id = tenant_id
        self.problem = problem
        self.token = token
        self.status = "submitted"
        self.gen = 0
        self.ngen: Optional[int] = None
        self.error: Optional[str] = None
        self.done = threading.Event()
        self._raw: Any = None
        self._encoded: Optional[Dict[str, Any]] = None
        self._enc_lock = threading.Lock()

    def set_result(self, raw: Any) -> None:
        self._raw = raw

    def result_payload(self) -> Optional[Dict[str, Any]]:
        if self._raw is None:
            return None
        with self._enc_lock:
            if self._encoded is None:
                self._encoded = wire.pack_result(self._raw)
            return self._encoded

    def as_dict(self) -> Dict[str, Any]:
        out = {"tenant_id": self.tenant_id, "problem": self.problem,
               "status": self.status, "gen": self.gen,
               "ngen": self.ngen}
        if self.error is not None:
            out["error"] = self.error
        return out


class EvolutionService:
    """Serve a :class:`Scheduler` over a loopback/LAN socket.

    :param root: scheduler root (journal + per-tenant run dirs); a
        restarted service over the same root resumes drained tenants.
    :param problems: ``{name: factory}`` where
        ``factory(tenant_id, params) -> Job`` builds the job
        server-side (the factory owns toolbox/key/init construction,
        so identical submissions are bit-reproducible).
    :param tokens: ``{token: {"tenant": str, "max_jobs": int|None}}``
        bearer auth + per-token in-flight quota; ``None`` = open.
    :param autoscale: ``True`` (default policy) /
        :class:`AutoscalePolicy` / ``None`` (off).
    :param autoscale_every: driver steps between autoscale ticks.
    :param step_hook: optional ``hook(step_count)`` run on the driver
        thread after every scheduler step — the deterministic
        fault-injection seam (drain-mid-segment tests, bursty-load
        generators) in the spirit of ``resilience/faultinject.py``.
    :param scheduler_kwargs: forwarded to :class:`Scheduler`
        (``max_lanes``, ``segment_len``, ``fair_quantum``,
        ``metrics``, ``compile_cache``, …).
    """

    def __init__(self, root: str,
                 problems: Dict[str, Callable[[str, dict], Job]], *,
                 host: str = "127.0.0.1", port: int = 0,
                 tokens: Optional[Dict[str, dict]] = None,
                 autoscale=None, autoscale_every: int = 1,
                 step_hook: Optional[Callable[[int], None]] = None,
                 **scheduler_kwargs):
        self.root = str(root)
        self.problems = dict(problems)
        self.tokens = dict(tokens) if tokens else None
        if autoscale is True:
            autoscale = AutoscalePolicy(AutoscaleConfig())
        self.policy: Optional[AutoscalePolicy] = autoscale or None
        self.autoscale_every = max(1, int(autoscale_every))
        self.step_hook = step_hook
        scheduler_kwargs.setdefault("resume_tenants", True)
        self.scheduler = Scheduler(self.root,
                                   boundary_cb=self._on_boundary,
                                   **scheduler_kwargs)
        self.journal = self.scheduler.journal

        self._lock = threading.Lock()
        # job factories run eager array ops; dozens of request threads
        # dispatching eagerly at once contend on the runtime — bound
        # the concurrency (2 builders keeps construction overlapped
        # with the driver without thrashing it)
        self._build_sem = threading.Semaphore(2)
        self._views: Dict[str, _JobView] = {}
        self._subs: Dict[str, List[queue.Queue]] = {}
        self._cmds: "queue.Queue" = queue.Queue()
        self._seq = 0
        self._steps = 0
        self._rep_jobs: Dict[str, Job] = {}   # driver-thread only
        self._drain_req = threading.Event()
        self._drained = threading.Event()
        self._closed = False

        self._driver = threading.Thread(target=self._drive,
                                        name="deap-tpu-service-driver",
                                        daemon=True)
        self._httpd = _ServiceHTTPServer((host, port), self)
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="deap-tpu-service-http", daemon=True)
        self._driver.start()
        self._http_thread.start()
        self.journal.event("service_request", route="start",
                           url=self.url,
                           problems=sorted(self.problems),
                           auth=self.tokens is not None,
                           autoscale=self.policy is not None)

    # ----------------------------------------------------- lifecycle ----

    @property
    def draining(self) -> bool:
        return self._drain_req.is_set()

    def drain(self, wait: bool = True,
              timeout: Optional[float] = None) -> bool:
        """Begin graceful drain: refuse new submissions, finish the
        in-flight segment, checkpoint every resident tenant, journal
        ``service_drain``, end streams. Safe to call from any thread —
        including a signal handler (``wait=False`` there). Returns
        True once drained (always True when ``wait=False``... check
        :attr:`drained`)."""
        self._drain_req.set()
        self._cmds.put(("wake",))
        if wait:
            return self._drained.wait(timeout)
        return True

    @property
    def drained(self) -> bool:
        return self._drained.is_set()

    def install_signal_handlers(self):
        """Install a SIGTERM/SIGINT → :meth:`drain` handler (main
        thread only); returns the :class:`~deap_tpu.resilience.drain.
        DrainSignal` so the caller can uninstall it."""
        from deap_tpu.resilience.drain import DrainSignal
        ds = DrainSignal(lambda signum: self.drain(wait=False))
        ds.install()
        return ds

    def close(self, timeout: float = 30.0) -> None:
        if self._closed:
            return
        self._closed = True
        self.drain(wait=True, timeout=timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._http_thread.join(timeout=5)
        self._driver.join(timeout=timeout)

    def __enter__(self) -> "EvolutionService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ----------------------------------------------------- the driver ----

    def _drive(self) -> None:
        sched = self.scheduler
        sched.bind_driver()
        try:
            while not self._drain_req.is_set():
                runnable = sched.runnable
                n = self._pump_commands(block=not runnable)
                # admission grace: while submissions are streaming in,
                # give the queue a few 10 ms windows before stepping —
                # rapid-fire submits land in ONE repack at a warmed
                # lattice point instead of compiling a 1-lane program
                # for the first arrival (measured: a 2.1 s stall)
                grace = 0
                while n and grace < 5 and not self._drain_req.is_set():
                    time.sleep(0.01)
                    n = self._pump_commands(block=False)
                    grace += 1
                if self._drain_req.is_set():
                    break
                if sched.runnable:
                    sched.step()
                    self._steps += 1
                    if self.step_hook is not None:
                        self.step_hook(self._steps)
                    if self._steps % self.autoscale_every == 0:
                        self._autoscale_tick()
            # ------------------------------------------- graceful drain
            self._pump_commands(block=False)
            saved = sched.checkpoint_all()
            open_views = []
            with self._lock:
                for v in self._views.values():
                    if not v.done.is_set():
                        v.status = "drained"
                        open_views.append(v)
            self.journal.event(
                "service_drain",
                checkpointed=sorted(saved),
                open_tenants=sorted(v.tenant_id for v in open_views),
                steps=self._steps)
            for v in open_views:
                self._publish(v.tenant_id,
                              {"event": "drained",
                               "tenant_id": v.tenant_id, "gen": v.gen})
                self._publish(v.tenant_id, None)
                v.done.set()
        finally:
            try:
                sched.close()
            finally:
                self._drained.set()

    def _pump_commands(self, block: bool) -> int:
        try:
            cmd = self._cmds.get(timeout=0.05) if block \
                else self._cmds.get_nowait()
        except queue.Empty:
            return 0
        n = 0
        while True:
            self._apply(cmd)
            n += 1
            try:
                cmd = self._cmds.get_nowait()
            except queue.Empty:
                return n

    def _apply(self, cmd: Tuple) -> None:
        if cmd[0] == "wake":
            return
        if cmd[0] == "submit":
            _, job, problem = cmd
            self._apply_submit(job, problem)
        elif cmd[0] == "submit_many":
            for job, problem in cmd[1]:
                self._apply_submit(job, problem)

    def _apply_submit(self, job: Job, problem: str) -> None:
        # admission is ASYNCHRONOUS: the front end already built the
        # Job (factories run on request threads — they must be
        # thread-safe pure constructors), ACKed, and registered the
        # view; the driver only performs the single-threaded scheduler
        # mutation. Scheduler-side errors surface through the mirror
        # (status "failed") and the stream's terminal event.
        tid = job.tenant_id
        with self._lock:
            view = self._views[tid]
        try:
            self.scheduler.submit(job)
        except Exception as e:
            view.error = f"{type(e).__name__}: {e}"
            view.status = "failed"
            view.done.set()
            self.journal.event("service_request", route="submit",
                               tenant_id=tid, problem=problem,
                               error=view.error)
            self._publish(tid, {"event": "failed", "tenant_id": tid,
                                "error": view.error})
            self._publish(tid, None)
            return
        bucket = self.scheduler.buckets[bucket_key(job)]
        self._rep_jobs.setdefault(bucket.label, job)
        tenant = self.scheduler.tenants[tid]
        view.status = ("resuming" if tenant.has_checkpoint
                       else "queued")
        self.journal.event("service_request", route="submit",
                           tenant_id=tid, problem=problem,
                           resume=tenant.has_checkpoint)

    # boundary fan-out: runs on the driver thread inside step()
    def _on_boundary(self, bucket_label: str,
                     updates: List[Dict[str, Any]]) -> None:
        for u in updates:
            t = u["tenant"]
            with self._lock:
                view = self._views.get(t.id)
                has_subs = bool(self._subs.get(t.id))
            if view is None:
                continue
            view.gen = u["gen"]
            ev = {"event": "segment", "tenant_id": t.id,
                  "bucket": bucket_label,
                  "gen_from": u["gen_before"], "gen": u["gen"]}
            if has_subs and u["chunk"] is not None:
                # the per-segment results: this segment's logbook
                # record rows, byte-exact on the wire
                ev["records"] = wire.pack(u["chunk"])
            self._publish(t.id, ev)
            if u["finished"]:
                view.set_result(t.result)
                view.status = t.status
                view.done.set()
                self._publish(t.id, {"event": t.status,
                                     "tenant_id": t.id,
                                     "gen": u["gen"]})
                self._publish(t.id, None)

    def _autoscale_tick(self) -> None:
        if self.policy is None:
            return
        sched = self.scheduler
        snap = sched.slo_snapshot()
        decision = self.policy.decide(snap)
        if not decision:
            return
        for label, n in decision.lane_counts.items():
            before = snap[label]["lanes"]
            applied = sched.set_bucket_lanes(label, n)
            self.journal.event(
                "autoscale_decision", action="lanes", bucket=label,
                lanes_from=before, lanes_to=applied,
                reason=decision.reasons.get(label, ""),
                queue_depth=snap[label]["queue_depth"],
                queue_wait_p99=snap[label]["queue_wait_p99"])
        for label, n in decision.prewarm:
            job = self._rep_jobs.get(label)
            if job is None:
                continue
            # compile the predicted lattice point in the BACKGROUND:
            # XLA compilation releases the GIL, so the driver keeps
            # stepping while the program the next scale-up needs is
            # built — a prewarm on the driver thread measured as a
            # multi-second admission stall under burst load. The
            # worker touches only the engine's jit caches (thread-safe
            # in jax), never scheduler state.
            threading.Thread(
                target=self._background_prewarm, args=(label, n),
                name=f"deap-tpu-prewarm-{n}", daemon=True).start()
        for tid in decision.spill:
            try:
                sched.request_spill(tid)
            except KeyError:
                continue
            self.journal.event("autoscale_decision", action="spill",
                               tenant_id=tid)

    def _background_prewarm(self, label: str, n_lanes: int) -> None:
        """Compile one (bucket, lane-count) lattice point off the
        driver thread. Reads the bucket's engine/horizon once and runs
        an inactive dummy batch through the jitted segment — pure
        compile-cache population, no scheduler state touched."""
        import numpy as np
        try:
            bucket = self.scheduler._bucket_by(label)
        except KeyError:
            return
        eng, horizon = bucket.engine, bucket.horizon
        job = self._rep_jobs.get(label)
        if job is None:
            return
        t0 = time.perf_counter()
        try:
            lane = eng.lane_init(job.key, job.init, job.ngen,
                                 job.hyper)
            probe = eng.pack([lane], n_lanes=n_lanes, horizon=horizon)
            probe["ngen"] = np.zeros_like(np.asarray(probe["ngen"]))
            eng.advance(probe, self.scheduler.segment_len)
        except Exception as e:
            self.journal.event("autoscale_decision", action="prewarm",
                               bucket=label, lanes=n_lanes,
                               error=f"{type(e).__name__}: {e}")
            return
        self.journal.event(
            "autoscale_decision", action="prewarm", bucket=label,
            lanes=n_lanes, background=True,
            compile_s=round(time.perf_counter() - t0, 4))

    # ------------------------------------------------- pub/sub plumbing ----

    def _subscribe(self, tid: str) -> "queue.Queue":
        q: "queue.Queue" = queue.Queue()
        with self._lock:
            self._subs.setdefault(tid, []).append(q)
        return q

    def _unsubscribe(self, tid: str, q: "queue.Queue") -> None:
        with self._lock:
            subs = self._subs.get(tid, [])
            if q in subs:
                subs.remove(q)
            if not subs:
                self._subs.pop(tid, None)

    def _publish(self, tid: str, event: Optional[dict]) -> None:
        with self._lock:
            subs = list(self._subs.get(tid, []))
        for q in subs:
            q.put(event)

    # ----------------------------------------------------- HTTP surface ----

    def _auth(self, headers) -> Tuple[str, dict]:
        """Returns (token, info); raises :class:`_HttpError` (and
        journals ``auth_rejected``) on missing/unknown tokens."""
        if self.tokens is None:
            return "", {}
        auth = headers.get("Authorization", "")
        token = auth[7:] if auth.startswith("Bearer ") else ""
        if not token:
            self.journal.event("auth_rejected", reason="missing_token")
            raise _HttpError(401, "missing bearer token")
        info = self.tokens.get(token)
        if info is None:
            self.journal.event("auth_rejected", reason="unknown_token")
            raise _HttpError(403, "unknown token")
        return token, info

    def _check_quota(self, token: str, info: dict,
                     n_new: int = 1) -> None:
        max_jobs = info.get("max_jobs") if info else None
        if max_jobs is None:
            return
        with self._lock:
            active = sum(1 for v in self._views.values()
                         if v.token == token and not v.done.is_set())
        if active + n_new > int(max_jobs):
            self.journal.event(
                "auth_rejected", reason="quota",
                tenant=info.get("tenant"), max_jobs=int(max_jobs),
                active=active)
            raise _HttpError(429,
                             f"quota exceeded: {active} in-flight + "
                             f"{n_new} new jobs > max_jobs={max_jobs}")

    def _view_for(self, tid: str, token: str) -> _JobView:
        with self._lock:
            view = self._views.get(tid)
        if view is None:
            raise _HttpError(404, f"unknown tenant {tid!r}")
        if self.tokens is not None and view.token != token:
            self.journal.event("auth_rejected", reason="foreign_tenant",
                               tenant_id=tid)
            raise _HttpError(403, "tenant belongs to another token")
        return view

    def _build_one(self, spec: dict, token: str, info: dict):
        problem = spec.get("problem")
        if problem not in self.problems:
            raise _HttpError(404, f"unknown problem {problem!r} "
                                  f"(have: {sorted(self.problems)})")
        tid = spec.get("tenant_id")
        if tid is None:
            with self._lock:
                self._seq += 1
                prefix = (info.get("tenant", "job")
                          if info else "job")
                tid = f"{prefix}-{self._seq}"
        tid = str(tid)
        # build the Job HERE, on the request thread: factories are
        # pure constructors (seed → arrays), so clients construct jobs
        # off the driver's critical path — moving this to the driver
        # measured ~2.7 s of serial admission stall at 1k tenants.
        # Construction errors report synchronously; the semaphore
        # bounds concurrent eager dispatch. tenant_id collisions are
        # re-checked at registration.
        try:
            with self._build_sem:
                job = self.problems[problem](
                    tid, dict(spec.get("params") or {}))
        except Exception as e:
            raise _HttpError(400, f"{type(e).__name__}: {e}")
        if job.tenant_id != tid:
            raise _HttpError(400,
                             f"problem factory {problem!r} returned "
                             f"tenant id {job.tenant_id!r}, expected "
                             f"{tid!r}")
        view = _JobView(tid, problem, token)
        view.ngen = int(job.ngen)
        return job, view, problem

    def _handle_submit(self, body: dict, token: str, info: dict
                       ) -> Tuple[int, dict]:
        """Single (``{"problem", "params", "tenant_id"?}``) or batch
        (``{"jobs": [spec, ...]}``) submission — the batch form costs
        one HTTP round trip for N jobs, which matters when the client
        and server share cores."""
        if self.draining:
            raise _HttpError(503, "service is draining")
        specs = body.get("jobs")
        batch = specs is not None
        if not batch:
            specs = [body]
        if not isinstance(specs, list) or not specs:
            raise _HttpError(400, '"jobs" must be a non-empty list')
        self._check_quota(token, info, n_new=len(specs))
        built = [self._build_one(s, token, info) for s in specs]
        with self._lock:
            dup = [j.tenant_id for j, _, _ in built
                   if j.tenant_id in self._views]
            if dup:
                raise _HttpError(409, f"tenant id(s) {dup} already "
                                      "submitted")
            for job, view, _ in built:
                self._views[job.tenant_id] = view
        # async admission: ACK now, the driver applies at its next
        # command pump — a request thread never waits out a segment
        self._cmds.put(("submit_many",
                        [(job, problem) for job, _, problem in built]))
        if self._drained.is_set():
            # lost race with a concurrent drain: the driver's final
            # pump may never see this command — fail the views loudly
            for _, view, _ in built:
                view.status = "drained"
                view.done.set()
        tids = [job.tenant_id for job, _, _ in built]
        if batch:
            return 200, {"tenant_ids": tids, "status": "submitted"}
        return 200, {"tenant_id": tids[0], "status": "submitted"}

    def handle(self, method: str, path: str, headers, body: bytes
               ) -> Tuple[int, str, bytes, bool]:
        """Route one request; returns (code, content-type, body,
        stream?) — ``stream`` means the caller takes over the socket
        (NDJSON). Front-end threads only: never touches the
        scheduler."""
        parsed = urllib.parse.urlparse(path)
        route = parsed.path.rstrip("/") or "/"
        qs = urllib.parse.parse_qs(parsed.query)
        if route == "/healthz" and method == "GET":
            code = 200 if not self.draining else 503
            return code, "application/json", json.dumps({
                "status": "draining" if self.draining else "ok",
                "jobs": len(self._views),
                "problems": sorted(self.problems)}).encode(), False
        if route == "/metrics" and method == "GET":
            # the unified serving surface: the same registry text
            # serve_metrics() exposes, on the service's own port
            reg = self.scheduler.metrics
            text = reg.metrics_text() if reg is not None else ""
            return 200, ("text/plain; version=0.0.4; charset=utf-8"), \
                text.encode(), False
        token, info = self._auth(headers)
        if route == "/v1/jobs" and method == "POST":
            payload = json.loads(body or b"{}")
            code, out = self._handle_submit(payload, token, info)
            return code, "application/json", \
                json.dumps(out).encode(), False
        if route == "/v1/drain" and method == "POST":
            self.journal.event("service_request", route="drain")
            self.drain(wait=False)
            return 200, "application/json", b'{"draining": true}', False
        if route == "/v1/results" and method == "GET":
            # batch result fetch: one request, N tenants — the
            # long-poll deadline is shared across the batch
            ids = [i for i in qs.get("ids", [""])[0].split(",") if i]
            if not ids:
                raise _HttpError(400, "ids=<tid,[tid...]> required")
            views = [self._view_for(urllib.parse.unquote(tid), token)
                     for tid in ids]
            if qs.get("wait", ["0"])[0] not in ("0", ""):
                deadline = time.monotonic() + float(
                    qs.get("timeout", ["300"])[0])
                for v in views:
                    v.done.wait(max(0.0,
                                    deadline - time.monotonic()))
            out = {}
            for v in views:
                entry = v.as_dict()
                payload = (v.result_payload()
                           if v.done.is_set() else None)
                if payload is not None:
                    entry["result"] = payload
                out[v.tenant_id] = entry
            return 200, "application/json", \
                json.dumps({"results": out}).encode(), False
        if route.startswith("/v1/jobs/") and method == "GET":
            parts = route.split("/")[3:]
            tid = urllib.parse.unquote(parts[0])
            sub = parts[1] if len(parts) > 1 else ""
            view = self._view_for(tid, token)
            if sub == "":
                return 200, "application/json", \
                    json.dumps(view.as_dict()).encode(), False
            if sub == "result":
                if qs.get("wait", ["0"])[0] not in ("0", ""):
                    timeout = float(qs.get("timeout", ["300"])[0])
                    view.done.wait(timeout)
                if not view.done.is_set():
                    return 202, "application/json", \
                        json.dumps(view.as_dict()).encode(), False
                out = view.as_dict()
                payload = view.result_payload()
                if payload is not None:
                    out["result"] = payload
                return 200, "application/json", \
                    json.dumps(out).encode(), False
            if sub == "stream":
                return 200, "application/x-ndjson", b"", True
        raise _HttpError(404, f"no route {method} {route}")

    def stream_events(self, tid: str, token: str, write_line) -> None:
        """Drive one NDJSON stream: status line first, then every
        published event until the terminal sentinel (or service
        close). Runs on the request thread; reads only the mirror."""
        view = self._view_for(tid, token)
        q = self._subscribe(tid)
        try:
            write_line({"event": "status", **view.as_dict()})
            if view.done.is_set():
                # finished before we subscribed: emit the terminal
                # event directly from the mirror
                write_line({"event": view.status,
                            "tenant_id": tid, "gen": view.gen})
                return
            while True:
                try:
                    ev = q.get(timeout=0.5)
                except queue.Empty:
                    if self._drained.is_set() or self._closed:
                        return
                    continue
                if ev is None:
                    return
                write_line(ev)
        finally:
            self._unsubscribe(tid, q)


class _ServiceHTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, service: EvolutionService):
        self.service = service
        super().__init__(addr, _Handler)


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def svc(self) -> EvolutionService:
        return self.server.service  # type: ignore[attr-defined]

    def log_message(self, *args):  # requests are journal rows, not logs
        pass

    def _respond(self, code: int, ctype: str, payload: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _dispatch(self, method: str) -> None:
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            try:
                code, ctype, payload, stream = self.svc.handle(
                    method, self.path, self.headers, body)
            except _HttpError as e:
                self._respond(e.code, "application/json", json.dumps(
                    {"error": e.message}).encode())
                return
            except json.JSONDecodeError as e:
                self._respond(400, "application/json", json.dumps(
                    {"error": f"bad JSON body: {e}"}).encode())
                return
            if not stream:
                self._respond(code, ctype, payload)
                return
            # NDJSON stream: no Content-Length; the connection closes
            # when the stream ends (HTTP/1.1 read-until-close)
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Connection", "close")
            self.end_headers()

            def write_line(ev: dict) -> None:
                self.wfile.write(json.dumps(ev).encode() + b"\n")
                self.wfile.flush()

            parsed = urllib.parse.urlparse(self.path)
            tid = urllib.parse.unquote(parsed.path.rstrip("/")
                                       .split("/")[3])
            token, _ = self.svc._auth(self.headers)
            self.svc.stream_events(tid, token, write_line)
            self.close_connection = True
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True

    def do_GET(self):  # noqa: N802 (http.server API)
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")
