"""Autoscaling control loop — SLO metrics in, lane budgets out.

The decision half of the service plane's control loop. The scheduler
already *measures* everything an autoscaler needs (PR 9: queue depth,
queue-wait p99, lane occupancy per bucket — :meth:`deap_tpu.serving.
scheduler.Scheduler.slo_snapshot`); this module turns those readings
into actions:

- **lane counts** — double a bucket's lane budget under sustained
  queue pressure, halve it under sustained idleness (pow-2 moves keep
  every setting on the compile lattice, so a scale-up is a program the
  bucket either already compiled or is about to prewarm);
- **prewarm targets** — when pressure first appears, predict the next
  lattice point and compile it *before* the scale-up lands (the
  controller routes these through ``serving.prewarm`` +
  ``enable_compile_cache``, so the predicted program is a disk read on
  the next process);
- **spill list** — under pressure with full lanes, long-resident
  tenants are swapped out to checkpoint (the scheduler's existing
  eviction machinery; spill just requests it ahead of the fairness
  quantum).

**Hysteresis, not thresholds.** Every action requires the triggering
condition to hold for N *consecutive* observations (``up_after`` /
``down_after``), and any applied change starts a per-bucket
``cooldown`` during which the bucket is left alone. An oscillating
queue depth (burst, empty, burst, …) therefore never flaps the lane
budget — pinned by ``tests/test_autoscale.py``, which drives this
module as a pure unit: synthetic snapshots in, decisions out, no
sockets, no jax (this file imports only the standard library).

The policy is deliberately separate from its actuation: the
:class:`~deap_tpu.serving.service.EvolutionService` driver thread owns
applying decisions to the scheduler and journaling each one as an
``autoscale_decision`` event.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Tuple

__all__ = ["AutoscaleConfig", "AutoscaleDecision", "AutoscalePolicy"]


@dataclasses.dataclass
class AutoscaleConfig:
    """Knobs of the control loop (documented in
    ``docs/advanced/serving.md``). Defaults are deliberately
    conservative: two consecutive pressured reads to scale up, three
    idle reads to scale down, two ticks of cooldown after any move."""

    #: lane-budget bounds (pad_pow2'd by the scheduler on apply)
    min_lanes: int = 1
    max_lanes: int = 64
    #: pressure = queue_depth >= queue_high, or queue-wait p99 above
    #: wait_p99_high seconds (when the histogram has data)
    queue_high: int = 1
    wait_p99_high: float = 1.0
    #: idle = zero queue and occupancy at or below occupancy_low
    occupancy_low: float = 0.5
    #: consecutive observations required before acting
    up_after: int = 2
    down_after: int = 3
    #: ticks a bucket is left alone after any applied change
    cooldown: int = 2
    #: a resident this many segments old is spillable under pressure
    spill_idle_segments: int = 4
    #: when the snapshot carries the true idleness signal
    #: (``gens_since_interaction``, the third element of each ``idle``
    #: tuple), a resident is spillable only after this many
    #: generations without a client interaction — mid-job residents
    #: whose clients are long-polling (gens-idle ~0) are never
    #: spilled, no matter how long they have held a lane (the
    #: spill-thrash fix: residency age alone spilled busy tenants)
    spill_idle_gens: int = 1
    #: emit a prewarm target for the next lattice point as soon as
    #: pressure is first observed (one step ahead of the scale-up)
    prewarm_ahead: bool = True


@dataclasses.dataclass
class AutoscaleDecision:
    """One tick's actions. Empty lists/dicts mean "leave everything
    alone" — the controller only journals non-trivial decisions."""

    #: bucket label -> new lane budget (only buckets that change)
    lane_counts: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: (bucket label, lane count) programs to compile ahead of need
    prewarm: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)
    #: tenant ids to swap out to checkpoint (pressure relief)
    spill: List[str] = dataclasses.field(default_factory=list)
    #: bucket label -> human-readable reason (journaled)
    reasons: Dict[str, str] = dataclasses.field(default_factory=dict)

    def __bool__(self) -> bool:
        return bool(self.lane_counts or self.prewarm or self.spill)


class _BucketCtl:
    """Per-bucket hysteresis state."""

    __slots__ = ("over", "under", "cooldown", "prewarmed")

    def __init__(self):
        self.over = 0       # consecutive pressured observations
        self.under = 0      # consecutive idle observations
        self.cooldown = 0   # ticks until this bucket may act again
        self.prewarmed = set()  # lane counts already targeted


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class AutoscalePolicy:
    """The pure decision function, with its hysteresis memory.

    ``decide`` consumes one snapshot — a mapping of bucket label to a
    stats dict with at least ``queue_depth``, ``occupancy``, ``lanes``
    and optionally ``queue_wait_p99`` (seconds or None) and ``idle``
    (iterable of ``(tenant_id, segments_resident)``) — exactly what
    :meth:`Scheduler.slo_snapshot` returns — and yields an
    :class:`AutoscaleDecision`. No clocks, no I/O: feeding the same
    snapshot sequence always yields the same decision sequence."""

    def __init__(self, config: Optional[AutoscaleConfig] = None):
        self.config = config or AutoscaleConfig()
        self._ctl: Dict[str, _BucketCtl] = {}

    # ------------------------------------------------------------------

    def _pressured(self, stats: Mapping[str, Any]) -> bool:
        cfg = self.config
        if int(stats.get("queue_depth", 0)) >= cfg.queue_high:
            return True
        p99 = stats.get("queue_wait_p99")
        return p99 is not None and float(p99) > cfg.wait_p99_high

    def _idle(self, stats: Mapping[str, Any]) -> bool:
        cfg = self.config
        return (int(stats.get("queue_depth", 0)) == 0
                and float(stats.get("occupancy", 0.0))
                <= cfg.occupancy_low)

    def decide(self, snapshot: Mapping[str, Mapping[str, Any]]
               ) -> AutoscaleDecision:
        cfg = self.config
        d = AutoscaleDecision()
        for label, stats in snapshot.items():
            ctl = self._ctl.setdefault(label, _BucketCtl())
            lanes = int(stats.get("lanes", 1))
            pressured = self._pressured(stats)
            idle = self._idle(stats)
            if ctl.cooldown > 0:
                # a bucket in cooldown is left alone AND its counters
                # stay frozen — observations during cooldown never
                # accumulate toward the next trigger
                ctl.cooldown -= 1
                ctl.over = ctl.under = 0
                continue
            # consecutive-observation counters: any break resets — an
            # oscillating signal never accumulates to a trigger
            ctl.over = ctl.over + 1 if pressured else 0
            ctl.under = ctl.under + 1 if idle else 0
            if pressured:
                target = min(_pow2(lanes) * 2, _pow2(cfg.max_lanes))
                if cfg.prewarm_ahead and target > lanes \
                        and target not in ctl.prewarmed:
                    # predict the lattice point one tick ahead of the
                    # scale-up so the compile is off the critical path
                    ctl.prewarmed.add(target)
                    d.prewarm.append((label, target))
                if ctl.over >= cfg.up_after:
                    if target > lanes:
                        d.lane_counts[label] = target
                        d.reasons[label] = (
                            f"scale_up: queue_depth="
                            f"{stats.get('queue_depth')} wait_p99="
                            f"{stats.get('queue_wait_p99')} for "
                            f"{ctl.over} ticks")
                        ctl.cooldown = cfg.cooldown
                        ctl.over = 0
                    elif float(stats.get("occupancy", 0.0)) >= 1.0:
                        # at the lane ceiling with a queue: relieve
                        # pressure by spilling genuinely idle tenants
                        # — gens-since-interaction first (a parked
                        # ask-tell tenant nobody polls), residency age
                        # as the tie-break / legacy 2-tuple fallback
                        def _spillable(t):
                            if t[1] < cfg.spill_idle_segments:
                                return False
                            return (len(t) < 3
                                    or t[2] >= cfg.spill_idle_gens)

                        spillable = sorted(
                            (t for t in stats.get("idle", ())
                             if _spillable(t)),
                            key=lambda t: (-(t[2] if len(t) > 2
                                             else t[1]), -t[1]))
                        take = spillable[:int(stats["queue_depth"])]
                        if take:
                            d.spill.extend(t[0] for t in take)
                            d.reasons[label] = (
                                f"spill: at max_lanes={lanes} with "
                                f"queue_depth={stats['queue_depth']}")
                            ctl.cooldown = cfg.cooldown
                            ctl.over = 0
            elif ctl.under >= cfg.down_after:
                target = max(_pow2(lanes) // 2,
                             _pow2(max(1, cfg.min_lanes)))
                if target < lanes:
                    d.lane_counts[label] = target
                    d.reasons[label] = (
                        f"scale_down: idle (occupancy="
                        f"{stats.get('occupancy'):.2f}) for "
                        f"{ctl.under} ticks")
                    ctl.cooldown = cfg.cooldown
                    ctl.under = 0
        return d
