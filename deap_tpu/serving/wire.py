"""Wire codec — bit-exact JSON encoding for the service plane.

The RPC front end (:mod:`deap_tpu.serving.service`) speaks
newline-delimited JSON, but the serving layer's correctness bar is
**bit-identity**: a result fetched over the socket must compare equal,
to the last mantissa bit, with the same job run in-process. JSON floats
round-trip through decimal text, so arrays never travel as number
lists — every ndarray is encoded as ``{"__nd__": dtype, shape,
base64(raw bytes)}`` (C-order, little-endian as stored), which is a
lossless byte-level transport for any dtype including float32/float64
NaN payloads and packed bools.

Two layers:

- the **array layer** (:func:`pack`/:func:`unpack`) — stdlib + numpy
  only, recursing over dicts/lists/tuples/scalars/ndarrays; this is
  all the client ever needs (``serving/client.py`` imports nothing
  heavier, so a scrape/submit box never initialises an XLA backend);
- the **result layer** (:func:`pack_result`) — server-side: flattens
  an arbitrary result pytree (populations, logbooks, halls of fame,
  strategy states) with ``jax.tree_util`` (imported lazily), converts
  typed PRNG-key leaves to their raw ``key_data``, and emits
  ``{"treedef": str, "leaves": [...], "digest": sha1}``. The digest
  covers every leaf's dtype/shape/bytes plus the treedef string, so
  "service result == in-process result" is one string compare —
  ``bench.py --service`` gates on exactly that.
"""

from __future__ import annotations

import base64
import hashlib
from typing import Any, Dict, List

import numpy as np

__all__ = ["pack", "unpack", "pack_result", "result_digest"]

_ND = "__nd__"
_TUPLE = "__tuple__"


def _pack_array(a: np.ndarray) -> Dict[str, Any]:
    a = np.ascontiguousarray(a)
    return {_ND: a.dtype.str, "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def pack(obj: Any) -> Any:
    """JSON-encodable transport form of ``obj``: ndarrays (and numpy
    scalars) become byte-exact ``__nd__`` blocks, tuples are tagged so
    they survive the round trip, dict/list/str/int/bool/None pass
    through. Floats that are *Python* floats pass through as JSON
    numbers — put anything that must be bit-exact in an array."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (np.ndarray, np.generic)):
        return _pack_array(np.asarray(obj))
    if isinstance(obj, dict):
        return {str(k): pack(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return {_TUPLE: [pack(v) for v in obj]}
    if isinstance(obj, list):
        return [pack(v) for v in obj]
    # fall through: anything array-like (jax arrays reach here)
    return _pack_array(np.asarray(obj))


def unpack(obj: Any) -> Any:
    """Inverse of :func:`pack` (numpy arrays out)."""
    if isinstance(obj, dict):
        if _ND in obj:
            raw = base64.b64decode(obj["b64"])
            return np.frombuffer(raw, dtype=np.dtype(obj[_ND])) \
                .reshape(obj["shape"]).copy()
        if _TUPLE in obj:
            return tuple(unpack(v) for v in obj[_TUPLE])
        return {k: unpack(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [unpack(v) for v in obj]
    return obj


def _leaf_array(leaf: Any) -> np.ndarray:
    """A leaf as a host ndarray; typed PRNG keys travel as raw
    key_data (uint32) — the same canonicalisation the checkpoint
    format uses."""
    import jax

    try:
        if jax.dtypes.issubdtype(getattr(leaf, "dtype", None),
                                 jax.dtypes.prng_key):
            return np.asarray(jax.random.key_data(leaf))
    except TypeError:
        pass
    return np.asarray(leaf)


def _canonicalize(result: Any) -> Any:
    """Replace host-side result objects that are NOT pytrees with
    deterministic pytree forms, so every flattened leaf is an array.
    Today that is the :class:`~deap_tpu.support.logbook.Logbook`
    (an opaque tree leaf — ``np.asarray`` of it would hash object
    pointers): it becomes a COLUMNAR dict — one stacked array per
    field over the generation axis — which carries the same bytes as
    the per-row form in a handful of leaves instead of rows×fields
    (per-row encoding measured ~1.3 ms/result at 30 generations, and
    it runs once per finishing tenant). Ragged logbooks (chapters with
    differing keys/shapes) fall back to a tuple of per-row dicts."""
    import jax
    from deap_tpu.support.logbook import Logbook

    def fix(leaf: Any) -> Any:
        if not isinstance(leaf, Logbook):
            return leaf
        rows = [{str(k): np.asarray(row[k]) for k in sorted(row)}
                for row in leaf]
        if rows:
            keys = list(rows[0])
            try:
                if all(list(r) == keys for r in rows):
                    return {"gens": len(rows),
                            "cols": {k: np.stack([r[k] for r in rows])
                                     for k in keys}}
            except ValueError:
                pass  # heterogeneous shapes: keep the row form
        return tuple(rows)

    return jax.tree_util.tree_map(
        fix, result,
        is_leaf=lambda x: isinstance(x, Logbook))


def pack_result(result: Any) -> Dict[str, Any]:
    """Server-side encoding of one tenant's solo-format result tuple
    (or any pytree): ``{"treedef", "leaves", "digest"}``. Decode the
    leaves with :func:`unpack`; compare results across transports with
    the digest. Logbooks are canonicalised to per-row dicts first (see
    :func:`_canonicalize`)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(_canonicalize(result))
    arrays = [_leaf_array(leaf) for leaf in leaves]
    packed: List[Any] = [_pack_array(a) for a in arrays]
    return {"treedef": str(treedef), "leaves": packed,
            "digest": _digest(str(treedef), arrays)}


def _digest(treedef: str, arrays: List[np.ndarray]) -> str:
    h = hashlib.sha1(treedef.encode())
    for a in arrays:
        h.update(str(a.dtype.str).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def result_digest(result: Any) -> str:
    """The bit-identity fingerprint of a result pytree — equal digests
    mean equal structure, dtypes, shapes and bytes."""
    return pack_result(result)["digest"]
