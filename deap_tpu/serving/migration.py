"""Live tenant migration, WAL ownership transfer, orphan adoption.

Zero-downtime operations (ISSUE 20): a tenant mid-run can move between
driver processes without losing a generation of work and without ever
being advanced by two drivers at once. The protocol is exactly-once
**by construction** — every state change is durable before the next
step depends on it, and a single atomic arbiter decides contested
ownership:

1. **Offer** (source, driver thread): the tenant is extracted from the
   scheduler at a segment boundary (checkpointed at its current gen,
   removed — :meth:`Scheduler.extract`), then an ``offer`` record is
   appended + fsync'd to the source WAL *before* anything is handed
   over (fsync-before-offer). An offered tenant stays ``pending`` in
   the source log: the offer is an intent, not a transfer.
2. **Adopt** (target, request thread): the target lands the offered
   checkpoint bytes in its own tenant directory, appends + fsyncs an
   ``adopted`` record to *its* WAL (the durable claim), then tries to
   create the **commit file** ``<source_root>/migrations/
   <offer_id>.commit`` with ``O_CREAT|O_EXCL``. The commit file is the
   arbiter: exactly one process can ever create it, so a racing
   reclaim (or a second adopter replaying the same synthesized orphan
   offer) loses deterministically — the loser voids its own adopted
   record with a ``done`` follow-up and walks away.
3. **Transfer** (source): only after the target ACKs (or the commit
   file proves the target won) does the source append ``transferred``,
   which folds the tenant out of its pending set. A crash at ANY seam
   leaves the tenant recoverable on exactly one side:

   - after offer-fsync, before the POST: no commit file exists, the
     source replays the tenant locally (and commits the offer to
     itself to shut the door on a late adopter);
   - after the target copied the checkpoint, before its adopted fsync:
     the target has no durable claim — the source reclaims;
   - after the target's adopted fsync + commit, before the source's
     ``transferred``: the commit file names the target, so the
     restarted source appends ``transferred`` retroactively and never
     resubmits.

**Orphan adoption** reuses the same machinery with a synthesized,
*deterministic* offer id (``orphan-<tenant>``): peers that discover a
dead fleet member (PR 19 federation metadata — recorded pid no longer
alive) each replay its WAL and race for the same commit file; the
second claimant loses the ``O_EXCL`` create and stands down.

Caveats, by design: commit files and orphan checkpoint pickup assume
the fleet shares a filesystem (the PR 19 federation-root assumption).
Liveness detection via pid is advisory — declaring a *live* member
dead and adopting its tenants is a split brain no file protocol can
fully fence; the deployment's supervisor owns that guarantee (the
chaos drill kills members before adoption runs). Live migration does
NOT need the shared root for the checkpoint itself: the offer carries
the checkpoint bytes inline (states are small — a population, a few
counters).
"""

from __future__ import annotations

import base64
import json
import os
import queue
import tempfile
import time
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from deap_tpu.serving.wal import scan_wal
from deap_tpu.support.checkpoint import checkpoint_meta

__all__ = [
    "MIGRATIONS_DIR",
    "MigrationError",
    "adopt_orphans",
    "adopt_tenant",
    "commit_path",
    "commits_for",
    "install_checkpoint",
    "migrate_tenant",
    "newest_tenant_checkpoint",
    "read_commit",
    "resolve_replay",
    "try_commit",
]

#: subdirectory of a driver's serving root holding per-offer commit
#: files — the single-writer arbiters of contested ownership
MIGRATIONS_DIR = "migrations"


class MigrationError(RuntimeError):
    """A migration step that cannot proceed (unknown tenant, no WAL,
    terminal tenant, unbuildable offer)."""


# ------------------------------------------------------------ commits ----


def _migrations_dir(source_root: str) -> str:
    path = os.path.join(str(source_root), MIGRATIONS_DIR)
    os.makedirs(path, exist_ok=True)
    return path


def commit_path(source_root: str, offer_id: str) -> str:
    return os.path.join(_migrations_dir(source_root),
                        f"{offer_id}.commit")


def try_commit(source_root: str, *, offer_id: str, tenant_id: str,
               owner_root: str, owner_wal: str,
               owner: str = "") -> Tuple[bool, Dict[str, Any]]:
    """Atomically decide the offer: ``O_CREAT|O_EXCL`` on the commit
    file means exactly one caller ever wins. Returns ``(won,
    commit_record)`` — on a loss the record is the *winner's* (so the
    loser can tell "I already own this" idempotent retries from a
    genuine loss)."""
    rec = {"offer_id": str(offer_id), "tenant_id": str(tenant_id),
           "owner_root": os.path.abspath(owner_root),
           "owner_wal": str(owner_wal), "owner": str(owner)}
    path = commit_path(source_root, offer_id)
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    except FileExistsError:
        return False, (read_commit(source_root, offer_id) or rec)
    try:
        os.write(fd, json.dumps(rec, sort_keys=True).encode("utf-8"))
        os.fsync(fd)
    finally:
        os.close(fd)
    return True, rec


def read_commit(source_root: str,
                offer_id: str) -> Optional[Dict[str, Any]]:
    try:
        with open(commit_path(source_root, offer_id), "rb") as fh:
            rec = json.loads(fh.read().decode("utf-8"))
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    return rec if isinstance(rec, dict) else None


def commits_for(source_root: str,
                tenant_id: str) -> List[Dict[str, Any]]:
    """Every commit record in ``source_root`` naming ``tenant_id``.
    The migrations dir is small (one file per completed arbitration),
    so reading them all is the simple, correct scan."""
    mdir = os.path.join(str(source_root), MIGRATIONS_DIR)
    try:
        names = sorted(os.listdir(mdir))
    except OSError:
        return []
    out = []
    for name in names:
        if not name.endswith(".commit"):
            continue
        rec = read_commit(source_root, name[:-len(".commit")])
        if rec is not None and rec.get("tenant_id") == str(tenant_id):
            out.append(rec)
    return out


def _foreign_commit(source_root: str, tenant_id: str
                    ) -> Optional[Dict[str, Any]]:
    """The commit (if any) that moved ``tenant_id`` OUT of
    ``source_root``. Self-owned commits are closed reclaims; a tenant
    leaves a root at most once, so any foreign-owned commit is the
    transfer."""
    root = os.path.abspath(source_root)
    for rec in commits_for(source_root, tenant_id):
        owner = rec.get("owner_root")
        if owner and os.path.abspath(owner) != root:
            return rec
    return None


# -------------------------------------------------------- checkpoints ----


def newest_tenant_checkpoint(root: str, tenant_id: str
                             ) -> Optional[Tuple[int, str]]:
    """``(step, path)`` of the newest checkpoint file in
    ``<root>/tenants/<tid>/ckpt`` whose meta verifies AND is stamped
    with this tenant id — the file a migration hands over. Walks
    newest-first and skips damage, like ``restore_latest``."""
    ckpt_dir = os.path.join(str(root), "tenants", str(tenant_id),
                            "ckpt")
    try:
        names = os.listdir(ckpt_dir)
    except OSError:
        return None
    steps = []
    for name in names:
        if name.startswith("ckpt_") and name.endswith(".pkl"):
            try:
                steps.append(int(name[5:-4]))
            except ValueError:
                continue
    for step in sorted(steps, reverse=True):
        path = os.path.join(ckpt_dir, f"ckpt_{step:08d}.pkl")
        try:
            meta = checkpoint_meta(path)
        except Exception:
            continue
        if meta.get("tenant_id") == str(tenant_id):
            return step, path
    return None


def install_checkpoint(root: str, tenant_id: str, step: int,
                       data: bytes) -> str:
    """Land handed-over checkpoint bytes in this root's tenant
    directory (tmp + rename, the checkpoint module's atomicity rule)
    and verify them — a torn hand-off must fail HERE, before any
    durable adoption record claims the tenant."""
    ckpt_dir = os.path.join(str(root), "tenants", str(tenant_id),
                            "ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)
    final = os.path.join(ckpt_dir, f"ckpt_{int(step):08d}.pkl")
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, final)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    meta = checkpoint_meta(final)   # CRC + stamp check; raises on rot
    if meta.get("tenant_id") != str(tenant_id):
        raise MigrationError(
            f"handed-over checkpoint {final} is stamped for "
            f"{meta.get('tenant_id')!r}, not {tenant_id!r}")
    return final


def copy_checkpoint(source_root: str, target_root: str,
                    tenant_id: str) -> Optional[str]:
    """Shared-filesystem checkpoint pickup (the orphan path): copy the
    source's newest valid tenant-stamped file into the target's tenant
    dir. Returns the installed path, or ``None`` when the tenant never
    ran (fresh deterministic re-run on the target)."""
    found = newest_tenant_checkpoint(source_root, tenant_id)
    if found is None:
        return None
    step, path = found
    with open(path, "rb") as fh:
        data = fh.read()
    return install_checkpoint(target_root, tenant_id, step, data)


# ------------------------------------------------------- source side ----


def migrate_tenant(service, tenant_id: str, target_url: str,
                   timeout_s: float = 30.0) -> Dict[str, Any]:
    """Move one live tenant to the peer at ``target_url``. DRIVER
    THREAD ONLY (extraction mutates the scheduler); front-end callers
    go through :meth:`EvolutionService.migrate`, which routes here via
    the command queue."""
    sched = service.scheduler
    wal = service.wal
    if wal is None:
        raise MigrationError("live migration requires the admission "
                             "WAL (service started with wal=False)")
    tenant = sched.tenants.get(tenant_id)
    if tenant is None:
        raise MigrationError(f"unknown tenant {tenant_id!r}")
    if tenant.done:
        raise MigrationError(f"tenant {tenant_id!r} is terminal")
    with service._lock:
        view = service._views.get(tenant_id)
    params = getattr(tenant.job, "_wal_params", None)
    if view is None or params is None:
        raise MigrationError(
            f"tenant {tenant_id!r} was not admitted through the "
            "service (no view/WAL params); only service-admitted "
            "tenants can migrate")
    problem = view.problem
    target = str(target_url).rstrip("/")

    t0 = time.perf_counter()
    desc = sched.extract(tenant_id)
    service._migration_seq += 1
    offer_id = (f"{tenant_id}-g{desc['gen']}-p{os.getpid()}"
                f"-m{service._migration_seq}")
    offer_fields = dict(tenant_id=tenant_id, offer_id=offer_id,
                        target=target, gen=desc["gen"],
                        problem=problem, params=dict(params),
                        idempotency_key=view.idempotency_key,
                        request_id=view.request_id, token=view.token)
    # fsync-before-offer: the intent is durable before ANY byte leaves
    # this process — a crash from here on replays the tenant exactly
    # once, by the resolution rule
    wal.append("offer", **offer_fields)
    service._fire_fault("wal_append", path=wal.path,
                        seq=wal.n_appended)
    service.journal.event("migration_offer", phase="offered",
                          tenant_id=tenant_id, offer_id=offer_id,
                          target=target, gen=desc["gen"])
    service._fire_fault("migration", seam="after_offer",
                        tenant_id=tenant_id, offer_id=offer_id)

    payload = dict(offer_fields, source=service.url,
                   source_root=service.root, source_wal=wal.path,
                   ngen=desc["ngen"])
    found = newest_tenant_checkpoint(service.root, tenant_id)
    if found is not None:
        step, path = found
        with open(path, "rb") as fh:
            payload["checkpoint"] = base64.b64encode(
                fh.read()).decode("ascii")
        payload["checkpoint_step"] = step

    out, err = None, None
    try:
        req = urllib.request.Request(
            target + "/v1/migrate",
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            out = json.loads(resp.read().decode("utf-8"))
    except Exception as e:
        err = f"{type(e).__name__}: {e}"

    if out is not None and out.get("adopted"):
        return _finish_transfer(service, view, desc, offer_id, target,
                                t0)

    # refused or unreachable: arbitrate. Winning the commit (or
    # already owning it) means the target never durably adopted — the
    # tenant is still ours and resubmits locally, bit-exact from its
    # checkpoint.
    won, commit = try_commit(service.root, offer_id=offer_id,
                             tenant_id=tenant_id,
                             owner_root=service.root,
                             owner_wal=wal.path, owner=service.url)
    mine = os.path.abspath(service.root)
    if won or os.path.abspath(commit.get("owner_root", "")) == mine:
        _reclaim(service, view, desc, problem, params)
        service.journal.event("migration_offer", phase="reclaimed",
                              tenant_id=tenant_id, offer_id=offer_id,
                              target=target,
                              error=err or json.dumps(out))
        return {"migrated": False, "reclaimed": True,
                "tenant_id": tenant_id, "offer_id": offer_id,
                "target": target, "error": err}
    # the target committed its adoption before we could reclaim (an
    # ACK lost on the wire) — the transfer stands
    return _finish_transfer(service, view, desc, offer_id, target, t0,
                            resolved=True)


def _finish_transfer(service, view, desc, offer_id: str, target: str,
                     t0: float, resolved: bool = False
                     ) -> Dict[str, Any]:
    tenant_id = desc["tenant_id"]
    service._fire_fault("migration", seam="before_transferred",
                        tenant_id=tenant_id, offer_id=offer_id)
    service.wal.append("transferred", tenant_id=tenant_id,
                       offer_id=offer_id, target=target)
    pause_s = round(time.perf_counter() - t0, 6)
    service.journal.event("migration_offer", phase="transferred",
                          tenant_id=tenant_id, offer_id=offer_id,
                          target=target, gen=desc["gen"],
                          resolved=resolved, pause_s=pause_s)
    service._finish_migrated_view(tenant_id, target)
    return {"migrated": True, "tenant_id": tenant_id,
            "offer_id": offer_id, "target": target,
            "resolved": resolved, "pause_s": pause_s}


def _reclaim(service, view, desc, problem: str, params: dict) -> None:
    """Failed offer, arbitration won: the tenant never left. Rebuild
    its job from the factory and resubmit on this driver — the
    checkpoint written at extraction resumes it bit-exact."""
    tenant_id = desc["tenant_id"]
    job = service.problems[problem](tenant_id, dict(params))
    job.request_id = view.request_id or None
    job._wal_params = dict(params)
    with service._lock:
        # admission already happened once; a stale admission deadline
        # must not drop the reclaim
        view.deadline = None
    service._apply_submit(job, problem)


# ------------------------------------------------------- target side ----


def adopt_tenant(service, spec: Dict[str, Any],
                 orphan: bool = False) -> Tuple[int, Dict[str, Any]]:
    """The target half: land the checkpoint, durably adopt, win the
    arbitration, submit. Runs on a request thread (live offers via
    ``POST /v1/migrate``) or any caller's thread (orphan adoption) —
    everything here is the thread-safe front-end surface; the
    scheduler mutation rides the command queue."""
    wal = service.wal
    if wal is None:
        return 503, {"adopted": False,
                     "error": "adoption requires the admission WAL"}
    if service.draining:
        return 503, {"adopted": False, "error": "service is draining"}
    tid = str(spec.get("tenant_id") or "")
    offer_id = str(spec.get("offer_id") or "")
    problem = spec.get("problem")
    if not tid or not offer_id:
        return 400, {"adopted": False,
                     "error": "tenant_id and offer_id required"}
    with service._lock:
        if service._adopted_offers.get(offer_id) == tid:
            # idempotent retry: we already durably adopted this offer
            # (the source's ACK was lost) — say yes again
            return 200, {"adopted": True, "tenant_id": tid,
                         "idempotent": True}
    factory = service.problems.get(problem)
    if factory is None:
        return 404, {"adopted": False,
                     "error": f"unknown problem {problem!r}"}
    params = dict(spec.get("params") or {})
    gen = int(spec.get("gen") or 0)
    source_root = str(spec.get("source_root") or "")

    # 1. land the checkpoint FIRST — if the bytes are torn, fail
    # before any durable claim exists
    has_ckpt = False
    try:
        blob = spec.get("checkpoint")
        step = spec.get("checkpoint_step")
        if blob is not None and step is not None:
            install_checkpoint(service.root, tid, int(step),
                               base64.b64decode(blob))
            has_ckpt = True
        elif source_root:
            has_ckpt = copy_checkpoint(source_root, service.root,
                                       tid) is not None
    except Exception as e:
        return 422, {"adopted": False,
                     "error": f"checkpoint rejected: "
                              f"{type(e).__name__}: {e}"}

    # 2. the target-side kill seam: checkpoint landed, adoption not
    # yet durable — a kill here leaves NO claim, the source reclaims
    service._fire_fault("migration", seam="before_adopted",
                        tenant_id=tid, offer_id=offer_id)

    # 3. durable adoption in OUR wal (fsync before any ACK)
    try:
        wal.append("adopted", tenant_id=tid, offer_id=offer_id,
                   source=spec.get("source"), source_root=source_root,
                   problem=problem, params=params,
                   idempotency_key=spec.get("idempotency_key"),
                   request_id=spec.get("request_id"),
                   token=spec.get("token"), gen=gen)
    except ValueError:
        return 503, {"adopted": False, "error": "WAL closed"}
    service._fire_fault("wal_append", path=wal.path,
                        seq=wal.n_appended)

    # 4. arbitration: first commit wins — against a reclaiming source
    # or a peer racing for the same orphan
    if source_root:
        won, commit = try_commit(source_root, offer_id=offer_id,
                                 tenant_id=tid,
                                 owner_root=service.root,
                                 owner_wal=wal.path,
                                 owner=service.url)
        mine = os.path.abspath(service.root)
        if not won and \
                os.path.abspath(commit.get("owner_root", "")) != mine:
            # lost: void our adopted record so OUR replay never
            # resubmits a tenant somebody else owns
            try:
                wal.append("done", tenant_id=tid,
                           status="adoption_lost")
            except ValueError:
                pass
            service.journal.event(
                "orphan_adopted" if orphan else "migration_adopted",
                tenant_id=tid, offer_id=offer_id, lost=True,
                winner=commit.get("owner_root"))
            return 409, {"adopted": False,
                         "error": "lost adoption race",
                         "winner": commit.get("owner_root")}

    code, out = _register_adopted(service, tid, problem, params, spec,
                                  has_ckpt)
    service.journal.event(
        "orphan_adopted" if orphan else "migration_adopted",
        tenant_id=tid, offer_id=offer_id,
        source=spec.get("source") or source_root or None, gen=gen,
        has_checkpoint=has_ckpt,
        request_id=str(spec.get("request_id") or ""))
    with service._lock:
        service._adopted_offers[offer_id] = tid
    return code, out


def _register_adopted(service, tid: str, problem: str, params: dict,
                      spec: Dict[str, Any], has_ckpt: bool
                      ) -> Tuple[int, Dict[str, Any]]:
    from deap_tpu.serving.service import _JobView
    try:
        with service._build_sem:
            job = service.problems[problem](tid, dict(params))
    except Exception as e:
        # adoption is already durable — the tenant is OURS even though
        # this build failed; surface it as a failed view (and let a
        # restart's replay retry the factory)
        err = f"{type(e).__name__}: {e}"
        view = _JobView(tid, str(problem),
                        str(spec.get("token") or ""),
                        request_id=str(spec.get("request_id") or ""),
                        idempotency_key=spec.get("idempotency_key"))
        view.status = "failed"
        view.error = err
        view.done.set()
        with service._lock:
            service._views.setdefault(tid, view)
        return 200, {"adopted": True, "tenant_id": tid,
                     "submitted": False, "error": err}
    job.request_id = spec.get("request_id") or None
    job._wal_params = dict(params)
    view = _JobView(tid, str(problem), str(spec.get("token") or ""),
                    request_id=str(spec.get("request_id") or ""),
                    idempotency_key=spec.get("idempotency_key"))
    view.ngen = int(job.ngen)
    view.status = "adopted"
    with service._lock:
        existing = service._views.get(tid)
        if existing is not None and not existing.done.is_set():
            # already live here (a replayed duplicate) — idempotent
            return 200, {"adopted": True, "tenant_id": tid}
        service._views[tid] = view
        if view.idempotency_key:
            service._idem[str(view.idempotency_key)] = tid
    try:
        service._cmds.put(("submit_many", [(job, str(problem))]),
                          timeout=5.0)
    except queue.Full:
        # the adoption is durable; a wedged command queue just defers
        # the resume to this process's own restart replay
        pass
    return 200, {"adopted": True, "tenant_id": tid,
                 "has_checkpoint": has_ckpt}


# ---------------------------------------------------- orphan adoption ----


def _member_alive(meta: Dict[str, Any]) -> bool:
    try:
        os.kill(int(meta["pid"]), 0)
    except (OSError, TypeError, ValueError, KeyError):
        return False
    return True


def adopt_orphans(service, fleet_root: str,
                  process_id: Optional[str] = None,
                  skip_prefixes: Tuple[str, ...] = ("canary",)
                  ) -> List[str]:
    """Scan the fleet directory (PR 19 federation root) for dead
    members and adopt their accepted-not-terminal tenants through the
    same transfer records as live migration. Deterministic offer ids
    (``orphan-<tenant>``) make racing peers contend for the SAME
    commit file — the second claimant loses the ``O_EXCL`` create and
    stands down. Canary tenants are skipped by default: they are
    known-answer probes of their home process, not user work."""
    from deap_tpu.telemetry import federation
    adopted: List[str] = []
    my_root = os.path.abspath(service.root)
    try:
        members = sorted(os.listdir(str(fleet_root)))
    except OSError:
        return []
    for member in members:
        if not os.path.isdir(os.path.join(str(fleet_root), member)):
            continue
        if process_id is not None and member == process_id:
            continue
        meta = federation.process_meta(fleet_root, member)
        if not meta:
            continue   # never registered (or meta torn): can't locate
            #            its serving root, nothing to adopt
        sroot = meta.get("serving_root")
        if not sroot or os.path.abspath(sroot) == my_root:
            continue
        if _member_alive(meta):
            continue
        wal_path = os.path.join(sroot, "admission.wal")
        if not os.path.exists(wal_path):
            continue
        state = scan_wal(wal_path)
        for tid in sorted(state.pending):
            rec = state.pending[tid]
            if any(tid.startswith(p) for p in skip_prefixes):
                continue
            if rec.get("problem") not in service.problems:
                continue
            if _foreign_commit(sroot, tid) is not None:
                continue   # already adopted by someone (maybe us)
            found = newest_tenant_checkpoint(sroot, tid)
            spec = dict(rec)
            spec.update(tenant_id=tid, offer_id=f"orphan-{tid}",
                        source=meta.get("url") or member,
                        source_root=sroot,
                        gen=found[0] if found else 0)
            code, out = adopt_tenant(service, spec, orphan=True)
            if code == 200 and out.get("adopted"):
                adopted.append(tid)
    return adopted


# -------------------------------------------------- restart resolution ----


def resolve_replay(service, state) -> List[str]:
    """Ownership resolution at WAL replay (source or target restart).
    Mutates ``state.pending`` in place, removing tenants this process
    no longer owns, and returns their ids. Runs in ``__init__`` before
    the HTTP server exists — no live races.

    - a foreign commit for a pending tenant → it was transferred (or
      orphan-adopted) away; append ``transferred`` so future replays
      skip the scan, and don't resubmit;
    - an unresolved outbound ``offer`` with no foreign commit → commit
      it to ourselves (shutting the door on a late adopter), then
      replay locally;
    - our own ``adopted`` record whose commit never landed (we crashed
      between the adopted fsync and the commit create) → finish the
      arbitration now: win → keep the tenant, lose → void it.
    """
    gone: List[str] = []
    mine = os.path.abspath(service.root)
    for tid in sorted(state.pending):
        rec = state.pending[tid]
        if rec.get("kind") == "adopted":
            sroot = rec.get("source_root") or ""
            oid = str(rec.get("offer_id") or "")
            if not sroot or not oid:
                continue
            won, commit = try_commit(sroot, offer_id=oid,
                                     tenant_id=tid, owner_root=mine,
                                     owner_wal=service.wal.path)
            owner = os.path.abspath(commit.get("owner_root", ""))
            if not won and owner != mine:
                try:
                    service.wal.append("done", tenant_id=tid,
                                       status="adoption_lost")
                except ValueError:
                    pass
                service.journal.event("migration_offer",
                                      phase="resolved", owner="peer",
                                      tenant_id=tid, offer_id=oid)
                state.pending.pop(tid, None)
                gone.append(tid)
            continue
        foreign = _foreign_commit(service.root, tid)
        offer = state.offers.get(tid)
        if foreign is None and offer is not None:
            won, commit = try_commit(
                service.root,
                offer_id=str(offer.get("offer_id")), tenant_id=tid,
                owner_root=mine, owner_wal=service.wal.path)
            owner = os.path.abspath(commit.get("owner_root", ""))
            if not won and owner != mine:
                foreign = commit
        if foreign is not None:
            try:
                service.wal.append(
                    "transferred", tenant_id=tid,
                    offer_id=foreign.get("offer_id"),
                    target=foreign.get("owner")
                    or foreign.get("owner_root"))
            except ValueError:
                pass
            service.journal.event(
                "migration_offer", phase="resolved", owner="target",
                tenant_id=tid, offer_id=foreign.get("offer_id"),
                target=foreign.get("owner_root"))
            state.pending.pop(tid, None)
            gone.append(tid)
        elif offer is not None:
            service.journal.event(
                "migration_offer", phase="resolved", owner="source",
                tenant_id=tid, offer_id=offer.get("offer_id"))
    return gone
