"""Seeded open-loop load generator over the service's wire surface.

The load observatory's traffic plane (ISSUE 17). Every committed bench
before this drove synchronized bursts of identical tenants — nothing
production-shaped. This module generates **deterministic, seeded
arrival schedules** under parameterized traffic models and replays
them open-loop (arrivals fire at their scheduled instants regardless
of how the service is coping — the model that actually finds
queueing collapse) through :class:`~deap_tpu.serving.client.
ServiceClient`:

- :class:`PoissonTraffic` — memoryless arrivals at a fixed rate;
- :class:`DiurnalTraffic` — a sinusoidally-modulated Poisson process
  (thinning), the day/night load shape;
- :class:`ParetoMixTraffic` — heavy-tailed job sizes (``ngen`` drawn
  from a Pareto tail) across a weighted family mix;
- :class:`ThunderingHerd` — a synchronized burst, for retry-storm
  drills against injected 429s (:class:`~deap_tpu.resilience.
  faultinject.Reject429`);
- **client abandonment** — any model can mark a fraction of arrivals
  with a seeded ``abandon_after_s``; their pollers close the socket
  mid-long-poll (:class:`~deap_tpu.serving.client.ClientAbandoned`)
  and the tenant idles server-side until spilled.

Determinism contract: a schedule is a pure function of
``(model parameters, seed)`` — no wall clock, no ambient RNG — and
:meth:`Schedule.to_jsonl` is byte-identical across runs
(``tests/test_loadgen.py`` pins it). Execution is wall-clock paced,
but *what* arrives and *when it was meant to* arrive is replayable.

**Journal replay**: :func:`schedule_from_journal` reconstructs the
arrival process of any past run from its journal's ``job_submitted``
rows (monotonic ``t`` stamps) and turns it back into a
:class:`Schedule` — any incident or bench becomes a reproducible
workload, replayable at 1×/N× speed against a live service.

Like the client it rides on, this module never initialises an XLA
backend: importable standalone on a submit box with no jax.
"""

from __future__ import annotations

import json
import math
import random
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

if "deap_tpu" in sys.modules:
    from deap_tpu.serving.client import (ClientAbandoned, RetryPolicy,
                                         ServiceClient, ServiceError)
else:
    # standalone load (no-jax box): pull the client in by file path —
    # it handles its own codec/retry/tracing standalone loads
    import importlib.util as _ilu
    import os as _os

    _spec = _ilu.spec_from_file_location(
        "_deap_tpu_serving_client_standalone",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      "client.py"))
    _client = _ilu.module_from_spec(_spec)
    sys.modules["_deap_tpu_serving_client_standalone"] = _client
    _spec.loader.exec_module(_client)
    ClientAbandoned = _client.ClientAbandoned
    RetryPolicy = _client.RetryPolicy
    ServiceClient = _client.ServiceClient
    ServiceError = _client.ServiceError

__all__ = ["Arrival", "Schedule", "TrafficModel", "PoissonTraffic",
           "DiurnalTraffic", "ParetoMixTraffic", "ThunderingHerd",
           "LoadgenReport", "RestartPlan", "UpgradePlan",
           "run_schedule", "schedule_from_journal",
           "replay_fidelity"]


# ------------------------------------------------------- schedule ----

@dataclass(frozen=True)
class Arrival:
    """One scheduled submission: offset ``t`` seconds from run start,
    the registered problem + params, a deterministic tenant id, and
    the client-behaviour draws (abandonment, storm membership)."""

    t: float
    problem: str
    params: Dict[str, Any]
    tenant_id: str
    family: str = "ea"
    abandon_after_s: Optional[float] = None
    storm: bool = False

    def to_json(self) -> str:
        return json.dumps(
            {"t": round(self.t, 6), "problem": self.problem,
             "params": self.params, "tenant_id": self.tenant_id,
             "family": self.family,
             "abandon_after_s": self.abandon_after_s,
             "storm": self.storm}, sort_keys=True)

    @classmethod
    def from_json(cls, line: str) -> "Arrival":
        d = json.loads(line)
        return cls(t=float(d["t"]), problem=d["problem"],
                   params=d["params"], tenant_id=d["tenant_id"],
                   family=d.get("family", "ea"),
                   abandon_after_s=d.get("abandon_after_s"),
                   storm=bool(d.get("storm", False)))


@dataclass(frozen=True)
class Schedule:
    """A fully-materialized arrival process. ``to_jsonl`` is the
    determinism surface: same model + seed → byte-identical text."""

    model: str
    seed: Optional[int]
    arrivals: Tuple[Arrival, ...]

    @property
    def duration_s(self) -> float:
        return self.arrivals[-1].t if self.arrivals else 0.0

    def to_jsonl(self) -> str:
        head = json.dumps({"model": self.model, "seed": self.seed,
                           "n": len(self.arrivals)}, sort_keys=True)
        return "\n".join([head] + [a.to_json()
                                   for a in self.arrivals]) + "\n"

    @classmethod
    def from_jsonl(cls, text: str) -> "Schedule":
        lines = [ln for ln in text.splitlines() if ln.strip()]
        head = json.loads(lines[0])
        return cls(model=head["model"], seed=head.get("seed"),
                   arrivals=tuple(Arrival.from_json(ln)
                                  for ln in lines[1:]))


def _tid(model: str, seed: Optional[int], i: int) -> str:
    return f"lg-{model}-{seed}-{i:05d}"


class TrafficModel:
    """Base: subclasses draw arrivals from one ``random.Random(seed)``
    — the only entropy source; touching the wall clock or the global
    RNG here would break the byte-identical-schedule contract."""

    name = "base"

    def __init__(self, problem: str, params: Optional[dict] = None,
                 n: int = 100, abandon_frac: float = 0.0,
                 abandon_range: Tuple[float, float] = (0.25, 2.0)):
        self.problem = str(problem)
        self.params = dict(params or {})
        self.n = int(n)
        self.abandon_frac = float(abandon_frac)
        self.abandon_range = (float(abandon_range[0]),
                              float(abandon_range[1]))

    def _offsets(self, rng: random.Random) -> List[float]:
        raise NotImplementedError

    def _arrival(self, rng: random.Random, seed: Optional[int],
                 i: int, t: float) -> Arrival:
        abandon = None
        if self.abandon_frac and rng.random() < self.abandon_frac:
            abandon = round(rng.uniform(*self.abandon_range), 4)
        return Arrival(t=round(t, 6), problem=self.problem,
                       params=dict(self.params),
                       tenant_id=_tid(self.name, seed, i),
                       abandon_after_s=abandon)

    def schedule(self, seed: int) -> Schedule:
        rng = random.Random(int(seed))
        arrivals = [self._arrival(rng, seed, i, t)
                    for i, t in enumerate(self._offsets(rng))]
        return Schedule(model=self.name, seed=int(seed),
                        arrivals=tuple(arrivals))


class PoissonTraffic(TrafficModel):
    """Memoryless arrivals: exponential inter-arrival times at
    ``rate_per_s``."""

    name = "poisson"

    def __init__(self, rate_per_s: float, **kw):
        super().__init__(**kw)
        self.rate_per_s = float(rate_per_s)

    def _offsets(self, rng: random.Random) -> List[float]:
        t, out = 0.0, []
        for _ in range(self.n):
            t += rng.expovariate(self.rate_per_s)
            out.append(t)
        return out


class DiurnalTraffic(TrafficModel):
    """A non-homogeneous Poisson process with sinusoidal intensity
    (trough ``base_rate`` → crest ``peak_rate`` over ``period_s``),
    generated by Lewis–Shedler thinning: candidates at the peak rate,
    each kept with probability ``rate(t)/peak_rate``. The compressed
    day/night shape every production arrival log shows."""

    name = "diurnal"

    def __init__(self, base_rate: float, peak_rate: float,
                 period_s: float, **kw):
        super().__init__(**kw)
        if peak_rate < base_rate:
            raise ValueError("peak_rate must be >= base_rate")
        self.base_rate = float(base_rate)
        self.peak_rate = float(peak_rate)
        self.period_s = float(period_s)

    def _rate(self, t: float) -> float:
        swing = (self.peak_rate - self.base_rate) / 2.0
        mid = self.base_rate + swing
        return mid - swing * math.cos(2 * math.pi * t / self.period_s)

    def _offsets(self, rng: random.Random) -> List[float]:
        t, out = 0.0, []
        while len(out) < self.n:
            t += rng.expovariate(self.peak_rate)
            if rng.random() <= self._rate(t) / self.peak_rate:
                out.append(t)
        return out


class ParetoMixTraffic(TrafficModel):
    """Heavy-tailed job sizes over a weighted family mix: each
    arrival's ``ngen`` is ``ngen_min * Pareto(alpha)`` capped at
    ``ngen_cap`` (alpha ≤ 2 → infinite-variance tails, the "one whale
    tenant behind forty minnows" shape), drawn for a problem picked
    from ``mix``: ``(family_tag, problem, base_params, weight)``
    tuples spanning whatever EA/CMA/GP/island problems the target
    service registers."""

    name = "pareto_mix"

    def __init__(self, rate_per_s: float,
                 mix: Sequence[Tuple[str, str, dict, float]],
                 alpha: float = 1.5, ngen_min: int = 10,
                 ngen_cap: int = 640, **kw):
        kw.setdefault("problem", mix[0][1])
        super().__init__(**kw)
        self.rate_per_s = float(rate_per_s)
        self.mix = [(str(f), str(p), dict(par), float(w))
                    for f, p, par, w in mix]
        self.alpha = float(alpha)
        self.ngen_min = int(ngen_min)
        self.ngen_cap = int(ngen_cap)

    def _offsets(self, rng: random.Random) -> List[float]:
        t, out = 0.0, []
        for _ in range(self.n):
            t += rng.expovariate(self.rate_per_s)
            out.append(t)
        return out

    def _arrival(self, rng, seed, i, t) -> Arrival:
        weights = [w for _, _, _, w in self.mix]
        fam, problem, base, _ = rng.choices(self.mix,
                                            weights=weights)[0]
        ngen = min(self.ngen_cap,
                   int(self.ngen_min * rng.paretovariate(self.alpha)))
        params = {**self.params, **base, "ngen": ngen}
        abandon = None
        if self.abandon_frac and rng.random() < self.abandon_frac:
            abandon = round(rng.uniform(*self.abandon_range), 4)
        return Arrival(t=round(t, 6), problem=problem, params=params,
                       tenant_id=_tid(self.name, seed, i), family=fam,
                       abandon_after_s=abandon)


class ThunderingHerd(TrafficModel):
    """A synchronized burst at ``at_s`` (± seeded ``jitter_s``): every
    arrival is storm-flagged, so :func:`run_schedule` gives it a
    retrying client — against a service injecting 429s
    (:class:`~deap_tpu.resilience.faultinject.Reject429`) or a real
    ``max_pending`` shed, all rejected clients honour the same
    ``Retry-After`` and come back as one herd."""

    name = "herd"

    def __init__(self, at_s: float = 0.0, jitter_s: float = 0.05,
                 **kw):
        super().__init__(**kw)
        self.at_s = float(at_s)
        self.jitter_s = float(jitter_s)

    def _offsets(self, rng: random.Random) -> List[float]:
        return sorted(self.at_s + rng.uniform(0.0, self.jitter_s)
                      for _ in range(self.n))

    def _arrival(self, rng, seed, i, t) -> Arrival:
        a = super()._arrival(rng, seed, i, t)
        return Arrival(t=a.t, problem=a.problem, params=a.params,
                       tenant_id=a.tenant_id, family=a.family,
                       abandon_after_s=a.abandon_after_s, storm=True)


# --------------------------------------------------------- replay ----

def _read_rows(source) -> List[Dict[str, Any]]:
    """Journal rows from a path (torn-tail tolerant, like
    ``read_journal``) or pass-through from an iterable of dicts."""
    if not isinstance(source, (str, bytes)):
        return [r for r in source if isinstance(r, dict)]
    rows = []
    with open(source, "r") as fh:
        for line in fh:
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail / partial write
    return rows


def schedule_from_journal(source, problem: str,
                          params: Optional[dict] = None,
                          speed: float = 1.0,
                          use_ngen: bool = True,
                          tenant_prefix: str = "rp-"
                          ) -> Schedule:
    """Reconstruct the arrival process of a recorded run from its
    journal and return it as a replayable :class:`Schedule`.

    ``job_submitted`` rows carry the scheduler-side admission instants
    as monotonic ``t`` stamps (datable via the header's
    ``wall_start``); their deltas ARE the recorded inter-arrival
    process. ``speed=2.0`` replays at twice the recorded pace
    (offsets halved). Job *content* is re-anchored to ``problem`` /
    ``params`` (journals don't record submit params) with each row's
    recorded ``ngen`` preserved by default — the arrival process and
    per-job size profile of the incident, against today's problem
    registry."""
    rows = [r for r in _read_rows(source)
            if r.get("kind") == "job_submitted"
            and isinstance(r.get("t"), (int, float))]
    if not rows:
        return Schedule(model="replay", seed=None, arrivals=())
    speed = float(speed)
    if speed <= 0:
        raise ValueError("speed must be positive")
    rows.sort(key=lambda r: r["t"])
    t0 = rows[0]["t"]
    arrivals = []
    for i, r in enumerate(rows):
        p = dict(params or {})
        if use_ngen and r.get("ngen") is not None:
            p.setdefault("ngen", int(r["ngen"]))
        arrivals.append(Arrival(
            t=round((r["t"] - t0) / speed, 6), problem=problem,
            params=p,
            tenant_id=f"{tenant_prefix}{r.get('tenant_id', i)}",
            family=str(r.get("family", "ea"))))
    return Schedule(model="replay", seed=None,
                    arrivals=tuple(arrivals))


def replay_fidelity(recorded: Schedule, results:
                    Sequence["ArrivalResult"]) -> Dict[str, Any]:
    """How faithfully a run reproduced its schedule: per-arrival
    absolute error between scheduled and actual submit offsets (both
    re-anchored to their first arrival), max/mean seconds."""
    actual = {r.tenant_id: r.submit_t for r in results
              if r.submit_t is not None}
    pairs = [(a.t, actual[a.tenant_id]) for a in recorded.arrivals
             if a.tenant_id in actual]
    if not pairs:
        return {"n": 0, "max_abs_err_s": None, "mean_abs_err_s": None}
    base_s = min(t for t, _ in pairs)
    base_a = min(t for _, t in pairs)
    errs = [abs((ta - base_a) - (ts - base_s)) for ts, ta in pairs]
    return {"n": len(errs),
            "max_abs_err_s": round(max(errs), 4),
            "mean_abs_err_s": round(sum(errs) / len(errs), 4)}


# --------------------------------------------------------- runner ----

#: Job statuses after which polling stops — everything else
#: ("queued", "running", "evicted", ...) means keep waiting.
_TERMINAL = frozenset(
    {"finished", "stopped", "failed", "drained", "deadline_exceeded",
     "migrated"})


@dataclass
class ArrivalResult:
    """One arrival's fate: scheduled vs actual submit offset, final
    status (``finished`` / ``abandoned`` / ``shed`` / ``error``) and
    the result digest when one was fetched. ``done_t`` is the run
    offset at which a result digest landed (the restart scenario's
    time-to-first-result signal)."""

    tenant_id: str
    sched_t: float
    submit_t: Optional[float] = None
    status: str = "pending"
    digest: Optional[str] = None
    gen: Optional[int] = None
    error: Optional[str] = None
    done_t: Optional[float] = None


@dataclass
class RestartPlan:
    """Kill-and-restart the service mid-schedule (the ISSUE 18 warm-
    handoff drill): at run offset ``at_s`` (schedule time — scaled by
    the runner's ``speed`` like every arrival), :func:`run_schedule`
    calls ``restart()`` on a side thread. The callable owns the whole
    outage — kill the process, respawn it over the same root, wait for
    ready — and returns the (possibly new) base URL. Arrivals landing
    during or after the outage retry against the returned URL
    (idempotency keys make the re-offers safe), and the report gains
    ``time_to_first_result_after_restart_s`` — exactly the
    ``first_result`` slice the restarted service journals as its own
    ``startup_phase`` row, measured from the client side."""

    at_s: float
    restart: Any  # Callable[[], str] — returns the post-restart URL


@dataclass
class UpgradePlan:
    """Rolling upgrade mid-schedule (the ISSUE 20 zero-downtime
    drill): at run offset ``at_s``, :func:`run_schedule` calls
    ``handoff()`` on a side thread. The callable owns the whole
    rollout — spawn the new-version service, ``POST
    /v1/drain?handoff=<new_url>`` on the old one, wait for the old
    process to exit — and returns the new base URL. Unlike
    :class:`RestartPlan` there is **no outage**: the old service keeps
    answering until every resident has been handed off, so a worker
    only re-offers after its tenant reports the terminal ``migrated``
    status (digest-less — the result lives on the adopting side), and
    the re-offer's idempotency key maps onto the adopted tenant
    because the key rides the ownership-transfer offer."""

    at_s: float
    handoff: Any  # Callable[[], str] — returns the new base URL


@dataclass
class LoadgenReport:
    """A run's outcome: per-arrival results + tallies."""

    model: str
    seed: Optional[int]
    speed: float
    wall_s: float
    results: List[ArrivalResult] = field(default_factory=list)
    #: restart drill (set when run with a :class:`RestartPlan`): run
    #: offsets of the outage start / the service answering again, and
    #: the first result digest landed after the restart — the
    #: client-side mirror of the service's own ``startup_phase
    #: first_result`` journal row
    restart_t: Optional[float] = None
    restart_ready_t: Optional[float] = None
    time_to_first_result_after_restart_s: Optional[float] = None
    #: upgrade drill (set when run with an :class:`UpgradePlan`): run
    #: offsets of the rollout start / the old service fully drained
    #: into the new one, plus how many arrivals observed the
    #: ``migrated`` status and re-offered to the new side
    upgrade_t: Optional[float] = None
    upgrade_ready_t: Optional[float] = None
    migrated_reoffers: Optional[int] = None

    @property
    def counts(self) -> Dict[str, int]:
        c: Dict[str, int] = {}
        for r in self.results:
            c[r.status] = c.get(r.status, 0) + 1
        return c

    def digests(self) -> Dict[str, str]:
        return {r.tenant_id: r.digest for r in self.results
                if r.digest is not None}


def run_schedule(schedule: Schedule, base_url: str,
                 token: Optional[str] = None, *,
                 speed: float = 1.0,
                 max_workers: int = 16,
                 poll_timeout_s: float = 600.0,
                 storm_retry: Optional[RetryPolicy] = None,
                 restart: Optional[RestartPlan] = None,
                 upgrade: Optional[UpgradePlan] = None,
                 journal=None) -> LoadgenReport:
    """Replay ``schedule`` against a live service, **open-loop**: each
    arrival fires at its scheduled offset (scaled by ``speed``)
    whether or not earlier ones completed — a saturated service gets
    *more* load, not a politely waiting client. Each arrival runs on
    its own worker with its own :class:`ServiceClient` (one
    connection per thread, the client's contract): submit (with the
    tenant id as idempotency key — storm retries must not
    double-admit), then long-poll the result; abandonment draws close
    the poll socket mid-wait. With a ``journal``, the run lands as
    one ``loadgen_run`` row next to the service's own evidence."""
    speed = float(speed)
    if speed <= 0:
        raise ValueError("speed must be positive")
    if restart is not None and upgrade is not None:
        raise ValueError("restart and upgrade plans are mutually "
                         "exclusive (one mid-run event per drill)")
    # both plans share the machinery: a side thread fires the event
    # at `at_s`, workers park on `plan_ready` and re-offer once
    # against the URL the callable returns
    plan_at = (restart.at_s if restart is not None
               else upgrade.at_s if upgrade is not None else None)
    plan_call = (restart.restart if restart is not None
                 else upgrade.handoff if upgrade is not None
                 else None)
    reoffer_statuses = (("drained",) if restart is not None
                        else ("drained", "migrated"))
    arrivals = sorted(schedule.arrivals, key=lambda a: a.t)
    results = {a.tenant_id: ArrivalResult(a.tenant_id, a.t)
               for a in arrivals}
    sem = threading.Semaphore(max(1, int(max_workers)))
    threads: List[threading.Thread] = []
    t_run0 = time.monotonic()

    # restart drill state: workers read the CURRENT base url through
    # the holder (the restart callable may move the service), and a
    # worker that dies into the outage parks on `restart_ready` before
    # its one retry instead of hammering a dead socket
    url_holder = [base_url]
    restart_marks: Dict[str, Optional[float]] = {"t": None, "ready": None}
    reoffer_count = [0]
    restart_ready = threading.Event()
    if plan_call is None:
        restart_ready.set()

    def _fire_plan() -> None:
        delay = plan_at / speed - (time.monotonic() - t_run0)
        if delay > 0:
            time.sleep(delay)
        restart_marks["t"] = time.monotonic() - t_run0
        try:
            url_holder[0] = plan_call() or url_holder[0]
        finally:
            restart_marks["ready"] = time.monotonic() - t_run0
            restart_ready.set()

    def _work(a: Arrival) -> None:
        res = results[a.tenant_id]
        attempts = 2 if plan_call is not None else 1
        try:
            for attempt in range(attempts):
                retry = storm_retry if a.storm else None
                try:
                    with ServiceClient(url_holder[0], token=token,
                                       timeout=poll_timeout_s,
                                       retry=retry,
                                       abandon_after_s=a.abandon_after_s
                                       ) as client:
                        res.submit_t = time.monotonic() - t_run0
                        client.submit(a.problem, params=a.params,
                                      tenant_id=a.tenant_id,
                                      idempotency_key=a.tenant_id)
                        # The service clamps each long-poll to its own
                        # max_poll_s and returns a non-terminal
                        # snapshot, so poll in a loop until a terminal
                        # status or the overall budget runs out.
                        deadline = time.monotonic() + poll_timeout_s
                        out: Dict[str, Any] = {}
                        while True:
                            left = deadline - time.monotonic()
                            if left <= 0:
                                break
                            out = client.result(a.tenant_id, wait=True,
                                                timeout=left)
                            if out.get("status",
                                       "finished") in _TERMINAL:
                                break
                        res.status = out.get("status", "pending")
                        res.gen = out.get("gen")
                        r = out.get("result") or {}
                        res.digest = r.get("digest")
                        if res.digest is not None:
                            res.done_t = time.monotonic() - t_run0
                    if res.digest is None \
                            and res.status in reoffer_statuses \
                            and plan_call is not None \
                            and attempt + 1 < attempts:
                        # the service checkpointed us and went down
                        # (restart) or handed us to a peer (upgrade's
                        # ``migrated``) — that is the event, not a
                        # final fate: park and re-offer below
                        if res.status == "migrated":
                            reoffer_count[0] += 1
                    else:
                        return
                except ClientAbandoned:
                    res.status = "abandoned"
                    return
                except ServiceError as e:
                    if e.code < 500 or attempt + 1 >= attempts:
                        res.status = ("shed" if e.code == 429
                                      else "error")
                        res.error = f"HTTP {e.code}"
                        return
                except Exception as e:  # noqa: BLE001 — isolation
                    if attempt + 1 >= attempts:
                        res.status = "error"
                        res.error = f"{type(e).__name__}: {e}"
                        return
                # the arrival died into the outage: wait out the
                # respawn, then re-offer once — the tenant id IS the
                # idempotency key, so the retry can never double-admit
                restart_ready.wait(timeout=poll_timeout_s)
        finally:
            sem.release()

    restart_thread: Optional[threading.Thread] = None
    if plan_call is not None:
        restart_thread = threading.Thread(
            target=_fire_plan, daemon=True,
            name=("loadgen-restart" if restart is not None
                  else "loadgen-upgrade"))
        restart_thread.start()

    for a in arrivals:
        # open-loop pacing: sleep to the arrival's instant, then fire
        delay = a.t / speed - (time.monotonic() - t_run0)
        if delay > 0:
            time.sleep(delay)
        sem.acquire()
        th = threading.Thread(target=_work, args=(a,), daemon=True,
                              name=f"loadgen-{a.tenant_id}")
        threads.append(th)
        th.start()
    for th in threads:
        th.join()
    if restart_thread is not None:
        restart_thread.join(timeout=poll_timeout_s)
    report = LoadgenReport(model=schedule.model, seed=schedule.seed,
                           speed=speed,
                           wall_s=round(time.monotonic() - t_run0, 4),
                           results=[results[a.tenant_id]
                                    for a in arrivals])
    if restart is not None and restart_marks["t"] is not None:
        report.restart_t = round(restart_marks["t"], 4)
        if restart_marks["ready"] is not None:
            report.restart_ready_t = round(restart_marks["ready"], 4)
        after = [r.done_t for r in report.results
                 if r.done_t is not None
                 and r.done_t >= restart_marks["t"]]
        if after:
            report.time_to_first_result_after_restart_s = round(
                min(after) - restart_marks["t"], 4)
    if upgrade is not None and restart_marks["t"] is not None:
        report.upgrade_t = round(restart_marks["t"], 4)
        if restart_marks["ready"] is not None:
            report.upgrade_ready_t = round(restart_marks["ready"], 4)
        report.migrated_reoffers = reoffer_count[0]
    if journal is not None:
        extra: Dict[str, Any] = {}
        if report.restart_t is not None:
            extra.update(
                restart_t=report.restart_t,
                restart_ready_t=report.restart_ready_t,
                time_to_first_result_after_restart_s=(
                    report.time_to_first_result_after_restart_s))
        if report.upgrade_t is not None:
            extra.update(
                upgrade_t=report.upgrade_t,
                upgrade_ready_t=report.upgrade_ready_t,
                migrated_reoffers=report.migrated_reoffers)
        journal.event("loadgen_run", model=schedule.model,
                      seed=schedule.seed, speed=speed,
                      n_arrivals=len(arrivals),
                      planned_s=round(schedule.duration_s / speed, 4),
                      wall_s=report.wall_s, **report.counts, **extra)
    return report
