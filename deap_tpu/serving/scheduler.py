"""Ask-tell scheduler — multi-tenant serving over the multi-run engine.

The host control loop in front of :class:`~deap_tpu.serving.multirun.
MultiRunEngine`: jobs are **submitted**, admitted into **shape buckets**
(:func:`~deap_tpu.serving.tenant.bucket_key`), packed up to
``max_lanes`` tenants per device batch on the pow-2 lane lattice, and
advanced **segment by segment** — the ResilientRun cadence: every
segment boundary is a host sync where telemetry rows drain (journaled
per ``tenant_id``), health tripwires run (an early-stop frees the
lane), finished tenants return their solo-format results, and the
crash-consistent per-tenant checkpoint is written. That checkpoint is
also the **swap unit**: when jobs queue behind a full batch, resident
tenants past their fairness quantum are evicted at the boundary
(checkpoint → drop lane) and later resumed bit-exactly
(``restore_latest(tenant_id=...)`` — co-located tenant dirs can't
cross-restore).

Compile economics: a bucket compiles one program per (lane-count,
key-horizon, segment-length) lattice point; :func:`prewarm` compiles
the expected lattice at startup (one journaled ``prewarm`` event per
bucket), and :func:`~deap_tpu.support.compilecache.enable_compile_cache`
persists the executables so the next process's cold start is a disk
read (``bench.py --coldstart``).

Single-device, single-thread by design — the loop is a *cadence*, not
a server; an RPC front end calls :meth:`Scheduler.submit` /
:meth:`Scheduler.step` on its own schedule, **from one thread**. The
scheduler is guarded, not locked: concurrent entry from a second
thread raises :class:`SchedulerBusyError` instead of corrupting bucket
state, and once a front end declares its driver thread
(:meth:`Scheduler.bind_driver` — the
:class:`~deap_tpu.serving.service.EvolutionService` queue-handoff
contract), any mutating call from another thread is rejected outright.
Every future scaling PR (mesh sharding, TPU relay windows) slots in
below ``advance``.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from deap_tpu.serving.multirun import MultiRunEngine
from deap_tpu import tuning
from deap_tpu.serving.tenant import Job, Tenant, bucket_key, pad_pow2
from deap_tpu.support.compilecache import enable_compile_cache
from deap_tpu.telemetry import tracing
from deap_tpu.telemetry.meter import Meter
from deap_tpu.telemetry.metrics import (MetricsServer,
                                        SERVING_SEGMENT_BUCKETS,
                                        SERVING_WAIT_BUCKETS,
                                        phase_histogram,
                                        resolve_registry, serve_metrics)
from deap_tpu.telemetry.run import RunTelemetry

__all__ = ["Scheduler", "SchedulerBusyError", "prewarm"]


class SchedulerBusyError(RuntimeError):
    """A mutating :class:`Scheduler` call entered from a second thread
    while another call was in flight (or from a non-driver thread after
    :meth:`Scheduler.bind_driver`). The scheduler's bucket state is a
    single-threaded data structure by contract — raising here is what
    keeps a misbehaving front end from corrupting it. Route the call
    through the owning driver thread (the
    :class:`~deap_tpu.serving.service.EvolutionService` command queue
    is exactly that handoff)."""


class _Bucket:
    """One shape bucket: its engine, admission queue and residency."""

    def __init__(self, key, engine: MultiRunEngine, max_lanes: int):
        self.key = key
        # the bucket's metric/journal label: family + program digest —
        # short, stable, and readable on a Grafana legend
        self.label = f"{key[0]}:{str(key[1])[:10]}"
        self.engine = engine
        self.queue: List[Tenant] = []
        self.residents: List[Tenant] = []
        self.batch: Optional[Dict[str, Any]] = None
        self.horizon = 1
        # per-bucket lane budget — the autoscaler's actuator
        # (pad_pow2'd; starts at the scheduler default)
        self.max_lanes = int(max_lanes)

    @property
    def runnable(self) -> bool:
        return bool(self.queue) or bool(self.residents)


class _ServingInstruments:
    """The scheduler's Prometheus instruments — the per-bucket /
    per-tenant SLO surface ``/metrics`` exports (create-or-get, so
    several schedulers can share one registry)."""

    def __init__(self, registry):
        self.queue_depth = registry.gauge(
            "deap_serving_queue_depth",
            "jobs waiting for a lane, per bucket", labels=("bucket",))
        self.occupancy = registry.gauge(
            "deap_serving_lane_occupancy",
            "fraction of max_lanes holding a resident tenant",
            labels=("bucket",))
        # per-metric bucket overrides (ISSUE 17): BENCH_SERVICE.json
        # measured burst queue-wait p99 at 14.2 s — DEFAULT_BUCKETS
        # would round any windowed percentile past 10 s up to the
        # 30 s bound; these tuples keep burst-range reads finite
        self.queue_wait_s = registry.histogram(
            "deap_serving_queue_wait_seconds",
            "seconds from submission/eviction to (re)admission",
            labels=("bucket",), buckets=SERVING_WAIT_BUCKETS)
        self.segment_s = registry.histogram(
            "deap_serving_segment_seconds",
            "wall seconds per scheduler segment (advance + drain sync)",
            labels=("bucket",), buckets=SERVING_SEGMENT_BUCKETS)
        self.admissions = registry.counter(
            "deap_serving_admissions_total",
            "fresh tenant admissions", labels=("bucket",))
        self.evictions = registry.counter(
            "deap_serving_evictions_total",
            "tenants evicted past their fairness quantum",
            labels=("bucket",))
        self.resumes = registry.counter(
            "deap_serving_resumes_total",
            "tenants resumed from their checkpoint swap unit",
            labels=("bucket",))
        self.finished = registry.counter(
            "deap_serving_tenants_finished_total",
            "tenants that completed (or early-stopped)",
            labels=("bucket",))
        self.tenant_gens = registry.gauge(
            "deap_serving_tenant_gens_per_sec",
            "per-tenant generations/second over the last segment",
            labels=("tenant_id",))
        # family-labelled residency: GP / island / scan-family lanes
        # are distinguishable on /metrics without touching the label
        # tuples of the instruments above (create-or-get pins them)
        self.family_residents = registry.gauge(
            "deap_serving_family_residents",
            "resident tenants per bucket, labelled by engine family",
            labels=("bucket", "family"))


class Scheduler:
    """Admit → pack → advance → drain/evict, one segment per step.

    :param root: serving root directory — the shared journal
        (``<root>/journal.jsonl``) plus one run dir per tenant
        (``<root>/tenants/<id>/``: checkpoints, isolated from every
        other tenant).
    :param max_lanes: tenants packed per device batch (padded up to the
        pow-2 lattice with inactive dummy lanes).
    :param segment_len: generations per segment — the
        eviction/telemetry/checkpoint granularity, exactly
        ``ResilientRun``'s ``segment_len``.
    :param fair_quantum: segments a resident tenant may hold a lane
        while others queue; beyond it the tenant is evicted at the next
        boundary (checkpoint as swap unit). ``None`` disables eviction.
    :param checkpoint_every: write each resident tenant's checkpoint
        every n-th boundary (1 = every boundary; ``None``/0 = only when
        evicting — cheaper, but a crash then loses since-admission
        progress).
    :param telemetry: thread a per-bucket Meter (+ each job's probes)
        through the batched scan and journal per-generation rows under
        each ``tenant_id``. Costs the stacked meter output; off → only
        lifecycle events are journaled.
    :param compile_cache: path → :func:`enable_compile_cache` before
        the first compile (persistent across processes).
    :param metrics: the SLO metrics surface — ``True`` (default)
        records per-bucket queue depth / occupancy / queue-wait /
        segment latency and per-tenant gens/s into the process
        :class:`~deap_tpu.telemetry.metrics.MetricsRegistry`
        (``deap_serving_*`` instruments; expose them with
        :meth:`serve_metrics` or the module-level
        :func:`deap_tpu.telemetry.serve_metrics`). Pass a registry to
        isolate, ``None``/``False`` to disable. Host-side counters
        only — nothing rides the compiled programs.
    :param trace_sample: distributed-tracing knob. ``None`` (default)
        → tracing off, the zero-overhead path. A float in [0, 1] →
        a :class:`~deap_tpu.telemetry.tracing.Tracer` bound to the
        scheduler journal: per-segment detail spans (queue wait →
        admission → segment[i] → checkpoint) emit as ``trace_span``
        rows for the sampled fraction of traces; the terminal
        ``finished`` span is always on. With metrics on, every span
        with a phase observes ``deap_service_phase_seconds{phase=...}``
        regardless of the sampling decision. ``1.0`` is the
        full-fidelity latency-investigation mode: it additionally
        activates a :class:`~deap_tpu.telemetry.costs.
        ProgramObservatory` so bucket compiles land in the waterfall
        as HLO-linked ``compile`` spans.
    """

    def __init__(self, root: str, *, max_lanes: int = 8,
                 segment_len: int = 10,
                 fair_quantum: Optional[int] = 1,
                 checkpoint_every: Optional[int] = 1,
                 telemetry: bool = True,
                 compile_cache: Optional[str] = None,
                 journal_fsync_every: Optional[int] = None,
                 metrics=True,
                 resume_tenants: bool = False,
                 boundary_cb: Optional[Callable] = None,
                 fault_hook: Optional[Callable] = None,
                 trace_sample: Optional[float] = None):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._artifact_store = None
        if compile_cache:
            enable_compile_cache(compile_cache)
            # sibling executable-artifact store: restarted processes
            # deserialize the lattice's programs instead of compiling
            # them (docs/advanced/coldstart.md); best-effort — a store
            # that cannot be created leaves the compile path untouched
            try:
                from deap_tpu.support.artifacts import \
                    enable_artifact_store
                self._artifact_store = enable_artifact_store()
            except Exception:
                self._artifact_store = None
        self.max_lanes = int(max_lanes)
        if segment_len == "auto":
            # env DEAP_TPU_TUNE_SEGMENT_LEN → tuning-cache winner
            # (probed by bench.py --tuning) → the static 10
            segment_len = tuning.resolve_int("segment_len", default=10,
                                             program="scheduler")
        self.segment_len = int(segment_len)
        self.fair_quantum = fair_quantum
        self.checkpoint_every = checkpoint_every
        self.telemetry = bool(telemetry)
        #: re-admit a tenant id whose run dir already holds a
        #: checkpoint by RESUMING it (the restart half of a service
        #: drain) instead of starting from generation 0
        self.resume_tenants = bool(resume_tenants)
        #: optional host hook called at every segment boundary with
        #: ``(bucket_label, updates)`` where updates is a list of
        #: per-tenant dicts (tenant, gen_before, gen, chunk, finished)
        #: — the service's streaming fan-out point
        self.boundary_cb = boundary_cb
        #: optional ``hook(event, **ctx)`` fired at the scheduler's
        #: deterministic fault seams — today one seam: ``"segment"``,
        #: fired between a segment's device dispatch and its drain
        #: barrier, i.e. INSIDE the segment-latency measurement
        #: window. The service wires this to its fault plan so a
        #: :class:`~deap_tpu.resilience.faultinject.DelaySegment`
        #: with ``event="segment"`` shows up in the segment phase's
        #: spans/histogram — the attribution-demo seam (ISSUE 17)
        self.fault_hook = fault_hook
        from deap_tpu.telemetry.journal import RunJournal
        self.journal = RunJournal(
            os.path.join(self.root, "journal.jsonl"),
            fsync_every=journal_fsync_every)
        self.metrics = resolve_registry(metrics)
        self._minst = (_ServingInstruments(self.metrics)
                       if self.metrics is not None else None)
        #: distributed-tracing plane: ``trace_sample=None`` (default)
        #: keeps tracing fully off — today's zero-overhead path; a
        #: float in [0,1] enables the Tracer (lifecycle spans always
        #: on, detail spans sampled per trace at that rate). Spans
        #: land in this journal as ``trace_span`` rows and — when
        #: metrics are on — observe the per-phase latency histogram.
        self.trace_sample = trace_sample
        self._observatory = None
        if trace_sample is None:
            self.tracer = None
        else:
            phase_obs = None
            if self.metrics is not None:
                hist = phase_histogram(self.metrics)
                phase_obs = lambda phase, s: hist.observe(s, phase=phase)
            self.tracer = tracing.Tracer(journal=self.journal,
                                         sample=float(trace_sample),
                                         phase_observe=phase_obs)
            # FULL-FIDELITY tracing (sample >= 1.0, the latency-
            # investigation mode) also activates the program
            # observatory so every bucket compile journals a
            # `program_profile` (trace ids stamp into it, the compile
            # span links the HLO hash into the waterfall). Sampled
            # production tracing does NOT: an active observatory
            # switches every instrumented program to the explicit
            # AOT lower/compile path, which skips jit's C++ dispatch
            # fastpath on EVERY call — a per-step tax the sampled
            # tripwire (bench.py --tracing, <= 3%) would flag.
            if float(trace_sample) >= 1.0:
                from deap_tpu.telemetry.costs import ProgramObservatory
                self._observatory = ProgramObservatory(
                    journal=self.journal)
                self._observatory.__enter__()
        self._metrics_server: Optional[MetricsServer] = None
        self.buckets: Dict[Any, _Bucket] = {}
        self.tenants: Dict[str, Tenant] = {}
        self._boundaries = 0
        self._rr: List[Any] = []  # round-robin bucket order
        self._spill: set = set()  # tenant ids to swap out at the
        #                           next boundary (autoscaler pressure)
        # load counters (ISSUE 17): arrivals per bucket label plus
        # global sheds / deadline misses. Their OWN lock, not the
        # _exclusive guard — the service's request threads increment
        # sheds/misses while the driver owns the scheduler, and the
        # per-boundary `slo` journal row folds the cumulative values
        # in so windowed rates compute from the journal alone
        self._load_lock = threading.Lock()
        self._arrivals: Dict[str, int] = {}
        self._sheds = 0
        self._deadline_misses = 0
        # single-threaded-contract guard: RLock so the owner re-enters
        # (run → step), non-blocking so a second thread gets a loud
        # SchedulerBusyError instead of silently corrupted buckets
        self._guard = threading.RLock()
        self._driver_thread: Optional[threading.Thread] = None

    # ------------------------------------------------ thread contract ----

    def bind_driver(self,
                    thread: Optional[threading.Thread] = None) -> None:
        """Declare ``thread`` (default: the calling thread) the owner:
        from now on every mutating call from any OTHER thread raises
        :class:`SchedulerBusyError` immediately — the lock-owner
        assertion behind the service's queue-handoff contract."""
        self._driver_thread = thread or threading.current_thread()

    @contextlib.contextmanager
    def _exclusive(self, op: str):
        cur = threading.current_thread()
        if self._driver_thread is not None and \
                cur is not self._driver_thread:
            raise SchedulerBusyError(
                f"Scheduler.{op} called from thread {cur.name!r} but "
                f"the scheduler is bound to driver thread "
                f"{self._driver_thread.name!r}; enqueue the request to "
                "the driver instead (see serving/service.py)")
        if not self._guard.acquire(blocking=False):
            raise SchedulerBusyError(
                f"Scheduler.{op} called concurrently from thread "
                f"{cur.name!r} while another scheduler call is in "
                "flight; the scheduler is single-threaded by contract "
                "— serialise calls through one driver thread")
        try:
            yield
        finally:
            self._guard.release()

    # -------------------------------------------------------- admission ----

    def submit(self, job: Job) -> str:
        """Queue a job; returns its tenant id. Jobs with equal bucket
        keys share one compiled program (see :func:`bucket_key`).
        Single-threaded by contract: a concurrent call from a second
        thread raises :class:`SchedulerBusyError` (see
        :meth:`bind_driver`)."""
        with self._exclusive("submit"):
            return self._submit(job)

    def _submit(self, job: Job) -> str:
        if job.tenant_id in self.tenants:
            raise ValueError(f"tenant id {job.tenant_id!r} already "
                             "submitted")
        if job.family in ("ea_mu_plus_lambda", "ea_mu_comma_lambda") \
                and (job.mu is None or job.lambda_ is None):
            raise ValueError(f"{job.family} job needs mu/lambda_")
        if job.family == "gp" and job.spec is None:
            raise ValueError("gp job needs spec= (a GpJobSpec)")
        if job.family == "island" and job.spec is None:
            raise ValueError("island job needs spec= (an IslandJobSpec)")
        bkey = bucket_key(job)
        bucket = self.buckets.get(bkey)
        if bucket is None:
            bucket = _Bucket(bkey, self._make_engine(job),
                             self.max_lanes)
            self.buckets[bkey] = bucket
            self._rr.append(bkey)
            if job.family == "gp":
                self._tune_gp_admission(bucket, job)
        tenant = Tenant(job, self.root)
        if self.resume_tenants and tenant.probe_checkpoint():
            # the restart half of a service drain: this tenant id left
            # a tenant-stamped checkpoint behind — admission resumes it
            self.journal.event("tenant_checkpoint_found",
                               tenant_id=tenant.id)
        self.tenants[tenant.id] = tenant
        bucket.queue.append(tenant)
        bucket.horizon = max(bucket.horizon, pad_pow2(int(job.ngen)))
        self.journal.event("job_submitted", tenant_id=tenant.id,
                           family=job.family, ngen=int(job.ngen),
                           bucket=repr(bkey[:2]),
                           **self._rid(tenant))
        with self._load_lock:
            self._arrivals[bucket.label] = \
                self._arrivals.get(bucket.label, 0) + 1
        if self._minst is not None:
            self._minst.queue_depth.set(len(bucket.queue),
                                        bucket=bucket.label)
        return tenant.id

    def _tune_gp_admission(self, bucket: _Bucket, job: Job) -> None:
        """The dispatch tuner's headline consumer: batched vs solo GP
        admission, decided at first bucket creation.

        PR 14 made the union-mask batched engine the static choice —
        measured faster per tenant on this CPU, a guess elsewhere
        (the union-mask's over-evaluation cost is backend- and
        vocabulary-dependent). With a tuner active, this probes one
        ``segment_len``-generation segment with the bucket's actual
        spec at full lane width vs a single lane (the prewarm pattern:
        fresh ``lane_init`` from the job, tenant state untouched),
        normalises to per-lane-segment cost, bit-checks lane 0 across
        both (the engine's structural batched==solo identity), and —
        when solo wins — routes the bucket through ``max_lanes=1``,
        the autoscaler's own actuator. The probe compiles the 1-lane
        program; the full-width compile would have happened at first
        admission anyway. Journaled as ``tuning_decision`` (and the
        winner persists, so the next process routes without probing);
        stale winners evicted when the bucket's program drifts
        (``hlo_drift`` → :func:`deap_tpu.tuning.note_hlo_drift`)."""
        lanes = pad_pow2(self.max_lanes)
        if lanes <= 1:
            return
        if (tuning.active_tuner() is None
                and tuning.env_override("gp_batch") is None):
            return
        eng = bucket.engine
        horizon = max(bucket.horizon, pad_pow2(int(job.ngen)))
        candidates = {"batched": None, "solo": None}
        if tuning.active_tuner() is not None:
            lane = eng.lane_init(job.key, job.init, job.ngen,
                                 job.hyper)

            def probe(n_lanes):
                def fn():
                    batch = eng.pack([lane] * n_lanes,
                                     n_lanes=n_lanes, horizon=horizon)
                    out, _ = eng.advance(batch, self.segment_len)
                    return eng.unpack(out, 0)
                return fn

            candidates = {"batched": (probe(lanes), float(lanes)),
                          "solo": (probe(1), 1.0)}
        choice = tuning.resolve(
            "gp_batch",
            bucket=(str(bucket.key[0]), str(bucket.key[1])[:16],
                    lanes, self.segment_len),
            default="batched", candidates=candidates, check="bitwise",
            program=bucket.label)
        if choice == "solo":
            bucket.max_lanes = 1
            self.journal.event("tuned_admission", bucket=bucket.label,
                               choice=choice, max_lanes=1)

    def _make_engine(self, job: Job) -> MultiRunEngine:
        tel = None
        if self.telemetry:
            tel = RunTelemetry(self.journal, meter=Meter(),
                               spans=False, init_backend=False)
        if job.family == "gp":
            from deap_tpu.serving.gp_multirun import GpMultiRunEngine
            return GpMultiRunEngine(
                job.spec, telemetry=tel, probes=job.probes,
                stats=job.stats,
                halloffame_size=job.halloffame_size)
        if job.family == "island":
            from deap_tpu.serving.gp_multirun import \
                IslandMultiRunEngine
            return IslandMultiRunEngine(
                job.toolbox, job.spec, telemetry=tel,
                probes=job.probes)
        kwargs: Dict[str, Any] = {}
        if job.family == "ea_generate_update":
            kwargs.update(spec=job.spec, state_template=job.init)
        return MultiRunEngine(
            job.family, job.toolbox, mu=job.mu, lambda_=job.lambda_,
            stats=job.stats, telemetry=tel, probes=job.probes,
            halloffame_size=job.halloffame_size, **kwargs)

    # ---------------------------------------------------------- prewarm ----

    def prewarm(self, jobs: Iterable[Job],
                lane_counts: Optional[Sequence[int]] = None) -> int:
        """Compile each template job's bucket lattice before serving:
        for every distinct bucket among ``jobs``, pack an inactive
        dummy batch at each lattice lane count and run one segment
        through the jitted program. With a persistent compile cache
        enabled this is a disk read after the first process. Journals
        one ``prewarm`` event per (bucket, lane-count); returns the
        number of programs warmed."""
        with self._exclusive("prewarm"):
            return self._prewarm(jobs, lane_counts)

    def _prewarm(self, jobs: Iterable[Job],
                 lane_counts: Optional[Sequence[int]] = None) -> int:
        counts = (tuple(int(c) for c in lane_counts) if lane_counts
                  else (pad_pow2(self.max_lanes),))
        warmed = 0
        seen = set()
        for job in jobs:
            bkey = bucket_key(job)
            if bkey in seen:
                continue
            seen.add(bkey)
            bucket = self.buckets.get(bkey)
            if bucket is None:
                bucket = _Bucket(bkey, self._make_engine(job),
                                 self.max_lanes)
                self.buckets[bkey] = bucket
                self._rr.append(bkey)
            horizon = pad_pow2(int(job.ngen))
            bucket.horizon = max(bucket.horizon, horizon)
            eng = bucket.engine
            lane = eng.lane_init(job.key, job.init, job.ngen,
                                 job.hyper)
            for n_lanes in counts:
                t0 = time.perf_counter()
                probe = eng.pack([lane], n_lanes=pad_pow2(n_lanes),
                                 horizon=bucket.horizon)
                # ngen=0 everywhere: the program compiles, no tenant
                # state advances
                probe["ngen"] = np.zeros_like(np.asarray(probe["ngen"]))
                eng.advance(probe, self.segment_len)
                warmed += 1
                self.journal.event(
                    "prewarm", bucket=repr(bkey[:2]),
                    family=eng.family, lanes=pad_pow2(n_lanes),
                    horizon=bucket.horizon,
                    segment_len=self.segment_len,
                    compile_s=round(time.perf_counter() - t0, 4))
        return warmed

    # ------------------------------------------------------- the cadence ----

    def step(self) -> bool:
        """One scheduling round: pick the next runnable bucket
        (round-robin), ensure its batch is packed (admitting /
        resuming / evicting at this boundary), advance one segment,
        drain the boundary. Returns False when nothing is runnable."""
        with self._exclusive("step"):
            bucket = self._next_bucket()
            if bucket is None:
                return False
            self._repack(bucket)
            if not bucket.residents:
                return True  # everything spilled; next round readmits
            # ambient trace context for the segment: the batch is
            # shared, so compiles/span-bridge rows inside advance()
            # are attributed to a representative tenant (the first
            # resident with a request id) — per-tenant device time is
            # emitted exactly in _drain_boundary
            rep_ctx = next((c for c in map(self._tctx,
                                           bucket.residents)
                            if c is not None), None)
            t0 = time.perf_counter()
            with tracing.use(rep_ctx):
                batch, seg = bucket.engine.advance(bucket.batch,
                                                   self.segment_len)
            bucket.batch = batch
            if self.fault_hook is not None:
                # in-segment fault seam: between device dispatch and
                # the drain barrier — a DelaySegment here lands inside
                # seg_s, the segment spans and the segment histogram
                self.fault_hook("segment", bucket=bucket.label)
            self._drain_boundary(bucket, seg, t_start=t0)
            return True

    @property
    def runnable(self) -> bool:
        """Any bucket has queued or resident tenants left."""
        return any(b.runnable for b in self.buckets.values())

    def run(self, max_steps: Optional[int] = None) -> Dict[str, tuple]:
        """Drive :meth:`step` until every submitted job finished (or
        ``max_steps``); returns ``{tenant_id: solo-format result}``."""
        steps = 0
        while self.step():
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return {t.id: t.result for t in self.tenants.values()
                if t.result is not None}

    def serve_metrics(self, host: str = "127.0.0.1", port: int = 0):
        """Expose this scheduler's registry at ``/metrics`` on a
        daemon thread (stdlib ``http.server``); returns the
        :class:`~deap_tpu.telemetry.metrics.MetricsServer` (``.url``
        is the scrape target). Closed with the scheduler."""
        if self.metrics is None:
            raise ValueError("Scheduler was built with metrics "
                             "disabled; nothing to serve")
        if self._metrics_server is None:
            self._metrics_server = serve_metrics(self.metrics,
                                                 host=host, port=port)
        return self._metrics_server

    def close(self) -> None:
        if self._observatory is not None:
            self._observatory.__exit__(None, None, None)
            self._observatory = None
        self.journal.summary(
            tenants=len(self.tenants),
            finished=sum(t.done for t in self.tenants.values()))
        self.journal.close()
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        if self._artifact_store is not None:
            self._artifact_store.deactivate()
            self._artifact_store = None

    def __enter__(self) -> "Scheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------- internals ----

    @staticmethod
    def _rid(tenant: Tenant) -> Dict[str, str]:
        """The tenant's submitting request id as journal-row kwargs —
        empty for in-process submits, so rows stay clean."""
        rid = getattr(tenant.job, "request_id", None)
        return {"request_id": rid} if rid else {}

    def _tctx(self, tenant: Tenant):
        """The tenant's trace context (derived from its submitting
        request id — the same derivation a restarted process makes, so
        traces stitch across kill -9), or ``None`` when tracing is off
        or the tenant was submitted in-process without a request id."""
        if self.tracer is None:
            return None
        rid = getattr(tenant.job, "request_id", None)
        if not rid:
            return None
        return self.tracer.context_for(rid)

    def _tspan(self, tenant: Tenant, name: str, dur_s: float,
               phase: Optional[str] = None, always: bool = False,
               **attrs: Any) -> None:
        """Emit one tenant span parented on the request's
        deterministic root span. Per-segment detail respects the
        sampling knob (the phase histogram still observes every one);
        only terminal lifecycle events pass ``always=True`` — at 1k
        tenants the detail spans are ~10 journal rows per tenant, and
        journalling all of them regardless of ``trace_sample`` is
        exactly the overhead the sampled tripwire exists to catch."""
        ctx = self._tctx(tenant)
        if ctx is None:
            return
        self.tracer.emit(name, dur_s, ctx=ctx, phase=phase,
                         always=always, tenant_id=tenant.id, **attrs)

    def _checkpoint_traced(self, engine, tenant: Tenant,
                           name: str) -> str:
        """Checkpoint a tenant and account the write to its trace."""
        t0 = time.perf_counter()
        path = tenant.checkpoint(engine)
        self._tspan(tenant, name, time.perf_counter() - t0,
                    phase="checkpoint", gen=tenant.gen)
        return path

    def _next_bucket(self) -> Optional[_Bucket]:
        for _ in range(len(self._rr)):
            bkey = self._rr.pop(0)
            self._rr.append(bkey)
            if self.buckets[bkey].runnable:
                return self.buckets[bkey]
        return None

    def _evict(self, bucket: _Bucket, t: Tenant, reason: str) -> None:
        path = self._checkpoint_traced(bucket.engine, t,
                                       "checkpoint.evict")
        self.journal.event("tenant_evicted", tenant_id=t.id, gen=t.gen,
                           path=path, reason=reason, **self._rid(t))
        t.evict()
        bucket.residents.remove(t)
        bucket.queue.append(t)
        if self._minst is not None:
            self._minst.evictions.inc(bucket=bucket.label)

    def _repack(self, bucket: _Bucket) -> None:
        """Boundary admission control: spill requested/surplus
        residents, evict over-quantum residents when jobs queue, fill
        free lanes from the queue, and (re)pack the batch only when
        residency changed."""
        eng = bucket.engine
        changed = bucket.batch is None
        repack_t0 = time.perf_counter()
        newly_resident: List[Tenant] = []

        # requested spills (autoscaler pressure relief) — checkpoint
        # and park regardless of the fairness quantum
        if self._spill:
            for t in [t for t in bucket.residents
                      if t.id in self._spill]:
                self._evict(bucket, t, reason="spill")
                self._spill.discard(t.id)
                changed = True

        # lane-budget shrink (autoscaler scale-down): surplus
        # residents swap out, longest-resident first
        over = len(bucket.residents) - bucket.max_lanes
        if over > 0:
            for t in sorted(bucket.residents,
                            key=lambda t: -t.segments_resident)[:over]:
                self._evict(bucket, t, reason="scale_down")
                changed = True

        # eviction — only under contention, only past the quantum
        if bucket.queue and self.fair_quantum is not None:
            free = bucket.max_lanes - len(bucket.residents)
            want = len(bucket.queue) - free
            if want > 0:
                victims = sorted(
                    (t for t in bucket.residents
                     if t.segments_resident >= self.fair_quantum),
                    key=lambda t: -t.segments_resident)[:want]
                for t in victims:
                    self._evict(bucket, t, reason="fair_quantum")
                    changed = True

        # admission — resume from checkpoint or fresh-init
        while bucket.queue and len(bucket.residents) < bucket.max_lanes:
            t = bucket.queue.pop(0)
            # the queue-wait SLO sample: exact seconds in the journal
            # row (bucket-resolution in the Prometheus histogram)
            wait_s = max(0.0, time.monotonic() - t.enqueued_at)
            if self._minst is not None:
                self._minst.queue_wait_s.observe(wait_s,
                                                 bucket=bucket.label)
            # detail span (sampled): time queued before this admission
            # (re-queued evictees get one span per wait)
            self._tspan(t, "queue.wait", wait_s, phase="queue_wait",
                        resumed=bool(t.has_checkpoint))
            newly_resident.append(t)
            if t.has_checkpoint:
                t.restore(eng)
                self.journal.event("tenant_resumed", tenant_id=t.id,
                                   gen=t.gen,
                                   wait_s=round(wait_s, 4),
                                   **self._rid(t))
                if self._minst is not None:
                    self._minst.resumes.inc(bucket=bucket.label)
            else:
                t.lane = eng.lane_init(t.job.key, t.job.init,
                                       t.job.ngen, t.job.hyper)
                self.journal.event("tenant_admitted", tenant_id=t.id,
                                   ngen=int(t.job.ngen),
                                   wait_s=round(wait_s, 4),
                                   **self._rid(t))
                if self._minst is not None:
                    self._minst.admissions.inc(bucket=bucket.label)
                for row in eng.lane_meter_rows((), 0, lane=t.lane):
                    self._journal_row(t, row)
            t.status = Tenant.RUNNING
            t.segments_resident = 0
            bucket.residents.append(t)
            changed = True
        if self._minst is not None:
            self._minst.queue_depth.set(len(bucket.queue),
                                        bucket=bucket.label)
            self._minst.occupancy.set(
                len(bucket.residents) / bucket.max_lanes,
                bucket=bucket.label)
            self._minst.family_residents.set(
                len(bucket.residents), bucket=bucket.label,
                family=eng.family)

        if changed and bucket.residents:
            lanes = []
            for slot, t in enumerate(bucket.residents):
                t.slot = slot
                lanes.append(t.lane)
            bucket.batch = eng.pack(
                lanes, n_lanes=pad_pow2(len(lanes), bucket.max_lanes),
                horizon=bucket.horizon)
        if newly_resident:
            # admission/pack cost, attributed to every tenant admitted
            # at this boundary (the repack is one shared host step, so
            # each span carries the whole elapsed time — an upper
            # bound per tenant, exact for the boundary)
            pack_s = time.perf_counter() - repack_t0
            for t in newly_resident:
                self._tspan(t, "admit.pack", pack_s, phase="admission",
                            bucket=bucket.label)

    def _journal_row(self, tenant: Tenant, row: dict) -> None:
        self.journal.event("meter", tenant_id=tenant.id, **row)
        health = tenant.job.health
        if health is not None:
            for alarm in health.check_row(row, gen=row.get("gen")):
                self.journal.event("alarm", tenant_id=tenant.id,
                                   **alarm)
                if self.metrics is not None:
                    from deap_tpu.telemetry.metrics import alarms_total
                    alarms_total(self.metrics).inc(
                        kind=alarm.get("alarm", "unknown"))

    def _drain_boundary(self, bucket: _Bucket, seg: Dict[str, Any],
                        t_start: Optional[float] = None) -> None:
        """The per-segment host sync: rows → tenants/journal/health,
        completion, checkpoints — plus the segment's SLO sample
        (latency, per-tenant gens/s, queue/occupancy) into the metrics
        registry and one ``slo`` journal event."""
        eng = bucket.engine
        self._boundaries += 1
        gens = np.asarray(bucket.batch["gen"])
        # materialising `gens` is the segment's completion barrier —
        # wall time from advance() dispatch to here is the segment SLO
        seg_s = (time.perf_counter() - t_start
                 if t_start is not None else None)
        gens_advanced = 0
        finished: List[Tenant] = []
        updates: List[Dict[str, Any]] = []
        for t in list(bucket.residents):
            i = t.slot
            gen_before = t.gen
            chunk = eng.lane_records((seg,), i)
            if chunk is not None:
                t.record_chunks.append(chunk)
            for row in eng.lane_meter_rows((seg,), i,
                                           gen_start=gen_before):
                self._journal_row(t, row)
            t.gen = int(gens[i])
            gens_advanced += t.gen - gen_before
            if self._minst is not None and seg_s:
                self._minst.tenant_gens.set(
                    round((t.gen - gen_before) / seg_s, 3),
                    tenant_id=t.id)
            if seg_s is not None:
                # detail span (sampled): this tenant's segment share
                # (device time is batched — the wall seconds are the
                # segment's; gen_before/gen delimit the lane's work)
                self._tspan(t, "segment", seg_s, phase="device",
                            gen_before=gen_before, gen=t.gen,
                            bucket=bucket.label)
            t.segments_resident += 1
            t.lane = eng.unpack(bucket.batch, i)
            health = t.job.health
            stop = health is not None and health.stop_requested
            if t.gen >= int(t.job.ngen) or stop:
                t.result = eng.lane_result(
                    t.lane, eng.concat_records(t.record_chunks))
                if stop and t.gen < int(t.job.ngen):
                    t.status = Tenant.STOPPED
                    t.stopped_at = t.gen
                else:
                    t.status = Tenant.FINISHED
                self.journal.event(
                    "tenant_finished", tenant_id=t.id, gen=t.gen,
                    status=t.status, **self._rid(t))
                # instant lifecycle span marking the terminal state
                self._tspan(t, "finished", 0.0, gen=t.gen,
                            status=t.status, always=True)
                if self._minst is not None:
                    self._minst.finished.inc(bucket=bucket.label)
                finished.append(t)
            elif self.checkpoint_every and \
                    self._boundaries % self.checkpoint_every == 0:
                self._checkpoint_traced(eng, t, "checkpoint")
            updates.append({"tenant": t, "gen_before": gen_before,
                            "gen": t.gen, "chunk": chunk,
                            "finished": t in finished})
        if finished:
            for t in finished:
                bucket.residents.remove(t)
                t.slot = None
            bucket.batch = None  # repack next round

        self.journal.event(
            "segment", bucket=repr(bucket.key[:2]),
            family=eng.family, lanes=int(len(gens)),
            residents=len(bucket.residents) + len(finished),
            finished=[t.id for t in finished])
        # the boundary's SLO sample: one journal row (the report's
        # scheduler-SLO timeline) and the Prometheus instruments
        occupancy = len(bucket.residents) / bucket.max_lanes
        slo: Dict[str, Any] = {
            "bucket": bucket.label, "lanes": int(len(gens)),
            "residents": len(bucket.residents),
            "queue_depth": len(bucket.queue),
            "occupancy": round(occupancy, 4),
            "gens_advanced": int(gens_advanced),
        }
        if seg_s is not None:
            slo["segment_s"] = round(seg_s, 6)
            if seg_s > 0:
                slo["gens_per_sec"] = round(gens_advanced / seg_s, 3)
        # cumulative load counters (ISSUE 17): journal-only consumers
        # (loadgen SLO curves, report.py --slo) difference consecutive
        # rows for windowed arrival/shed/deadline-miss rates — no
        # /metrics scrape needed
        with self._load_lock:
            slo["arrivals"] = self._arrivals.get(bucket.label, 0)
            slo["sheds"] = self._sheds
            slo["deadline_misses"] = self._deadline_misses
        self.journal.event("slo", **slo)
        if self._minst is not None:
            if seg_s is not None:
                self._minst.segment_s.observe(seg_s,
                                              bucket=bucket.label)
            self._minst.queue_depth.set(len(bucket.queue),
                                        bucket=bucket.label)
            self._minst.occupancy.set(occupancy, bucket=bucket.label)
            self._minst.family_residents.set(
                len(bucket.residents), bucket=bucket.label,
                family=eng.family)
        if self.boundary_cb is not None:
            self.boundary_cb(bucket.label, updates)

    # ----------------------------------------- control-plane surface ----
    # (the autoscaler's sensors and actuators, and the drain hook —
    # all single-threaded: call from the driver thread only)

    def _bucket_by(self, which) -> _Bucket:
        if which in self.buckets:
            return self.buckets[which]
        for b in self.buckets.values():
            if b.label == which:
                return b
        raise KeyError(f"no bucket {which!r}")

    def set_bucket_lanes(self, which, n_lanes: int) -> int:
        """Set one bucket's lane budget (pad_pow2'd, >= 1) — the
        autoscaler's actuator. Growing takes effect at the next
        boundary's admission; shrinking below current residency swaps
        the surplus out (checkpoint as swap unit, ``scale_down``
        eviction reason). Returns the applied (padded) count."""
        with self._exclusive("set_bucket_lanes"):
            bucket = self._bucket_by(which)
            bucket.max_lanes = pad_pow2(max(1, int(n_lanes)))
            return bucket.max_lanes

    def request_spill(self, tenant_id: str) -> None:
        """Mark a resident tenant for swap-out at the next boundary of
        its bucket (checkpoint → queue tail), regardless of the
        fairness quantum — the autoscaler's pressure-relief actuator."""
        with self._exclusive("request_spill"):
            if tenant_id not in self.tenants:
                raise KeyError(f"unknown tenant {tenant_id!r}")
            self._spill.add(tenant_id)

    def note_shed(self, n: int = 1) -> None:
        """Count ``n`` load-shed submissions (429s). Callable from ANY
        thread — the service's request handlers shed while the driver
        owns the scheduler, so this deliberately bypasses the
        ``_exclusive`` contract (its own lock, touches nothing the
        driver mutates). Folded into every per-boundary ``slo``
        journal row and :meth:`slo_snapshot`."""
        with self._load_lock:
            self._sheds += int(n)

    def note_deadline_miss(self, n: int = 1) -> None:
        """Count ``n`` admission-deadline misses (504s) — same
        any-thread contract as :meth:`note_shed`."""
        with self._load_lock:
            self._deadline_misses += int(n)

    def load_counts(self) -> Dict[str, Any]:
        """Cumulative load counters: ``{"arrivals": {label: n},
        "sheds": n, "deadline_misses": n}`` — any-thread safe."""
        with self._load_lock:
            return {"arrivals": dict(self._arrivals),
                    "sheds": self._sheds,
                    "deadline_misses": self._deadline_misses}

    def slo_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-bucket control-plane sensor read: queue depth, lane
        budget/residency/occupancy, queue-wait p99 (bucket-resolution,
        from the metrics histogram when enabled) and the resident
        tenants' ``(id, segments_resident, gens_since_interaction)``
        idle candidates — exactly the inputs
        :class:`deap_tpu.serving.autoscale.AutoscalePolicy` decides
        on. The third element is the true idleness signal: how many
        generations a tenant has advanced since a client last touched
        it — the spill actuator prefers genuinely parked ask-tell
        tenants over mid-job residents whose clients are long-polling
        (the BENCH_SERVICE bursty-pair spill-thrash fix)."""
        with self._exclusive("slo_snapshot"):
            with self._load_lock:
                arrivals = dict(self._arrivals)
                sheds = self._sheds
                misses = self._deadline_misses
            snap: Dict[str, Dict[str, Any]] = {}
            for b in self.buckets.values():
                wait_p99 = None
                if self._minst is not None:
                    wait_p99 = self._minst.queue_wait_s.quantile(
                        0.99, bucket=b.label)
                snap[b.label] = {
                    "family": b.engine.family,
                    "queue_depth": len(b.queue),
                    "residents": len(b.residents),
                    "lanes": b.max_lanes,
                    "occupancy": len(b.residents) / b.max_lanes,
                    "queue_wait_p99": wait_p99,
                    "arrivals": arrivals.get(b.label, 0),
                    "sheds": sheds,
                    "deadline_misses": misses,
                    "idle": tuple((t.id, t.segments_resident,
                                   t.gens_since_interaction)
                                  for t in b.residents),
                }
            return snap

    def extract(self, tenant_id: str) -> Dict[str, Any]:
        """Checkpoint (when resident) and **remove** one tenant — the
        live-migration primitive (ISSUE 20). Unlike :meth:`_evict` the
        tenant is not re-queued: it leaves this scheduler entirely,
        because ownership is moving to another driver process. Returns
        a handoff descriptor (``tenant_id`` / ``gen`` / ``ngen`` /
        ``has_checkpoint`` / ``ckpt_dir``) the migration protocol
        offers to the target. Driver thread only; raises ``KeyError``
        for unknown tenants and ``ValueError`` for terminal ones (a
        finished tenant's result lives in its view — nothing to
        move)."""
        with self._exclusive("extract"):
            t = self.tenants.get(tenant_id)
            if t is None:
                raise KeyError(f"unknown tenant {tenant_id!r}")
            if t.done:
                raise ValueError(f"tenant {tenant_id!r} is terminal "
                                 f"({t.status}); nothing to migrate")
            for b in self.buckets.values():
                if t in b.residents:
                    self._checkpoint_traced(b.engine, t,
                                            "checkpoint.migrate")
                    t.evict()
                    b.residents.remove(t)
                    # residency changed mid-lattice: slots are stale,
                    # repack at the next boundary (the finished-tenant
                    # path's rule)
                    b.batch = None
                    if self._minst is not None:
                        self._minst.occupancy.set(
                            len(b.residents) / b.max_lanes,
                            bucket=b.label)
                    break
                if t in b.queue:
                    b.queue.remove(t)
                    if self._minst is not None:
                        self._minst.queue_depth.set(len(b.queue),
                                                    bucket=b.label)
                    break
            del self.tenants[tenant_id]
            self._spill.discard(tenant_id)
            return {"tenant_id": t.id, "gen": int(t.gen),
                    "ngen": int(t.job.ngen),
                    "has_checkpoint": bool(t.has_checkpoint),
                    "ckpt_dir": os.path.join(t.run_dir, "ckpt")}

    def checkpoint_all(self) -> List[str]:
        """Checkpoint every resident tenant (tenant-stamped v2/v3
        meta) — the graceful-drain hook: after the in-flight segment
        finished, this persists every running tenant so a restarted
        scheduler (``resume_tenants=True``) resumes them bit-exactly.
        Queued-never-started tenants need no checkpoint (a fresh
        admission is deterministic). Returns the checkpointed tenant
        ids."""
        with self._exclusive("checkpoint_all"):
            saved = []
            for b in self.buckets.values():
                for t in b.residents:
                    self._checkpoint_traced(b.engine, t,
                                            "checkpoint.drain")
                    saved.append(t.id)
            return saved


def prewarm(scheduler: Scheduler, jobs: Iterable[Job],
            lane_counts: Optional[Sequence[int]] = None) -> int:
    """Module-level alias for :meth:`Scheduler.prewarm` — compile the
    shape-bucket lattice at scheduler startup (one journaled
    ``prewarm`` event per bucket/lane-count)."""
    return scheduler.prewarm(jobs, lane_counts=lane_counts)
