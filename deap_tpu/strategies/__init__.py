"""Strategy engines — ask-tell optimisers with pytree state.

Counterpart of /root/reference/deap/cma.py (CMA-ES family) plus the
reference's example-level strategies promoted to first-class citizens
(DE, PSO, PBIL, EMNA — examples/de, examples/pso, examples/eda).
"""

from deap_tpu.strategies.cma import (
    CMAState,
    MOState,
    OnePlusLambdaState,
    Strategy,
    StrategyMultiObjective,
    StrategyOnePlusLambda,
    hypervolume_contributions_2d,
)
from deap_tpu.strategies.bipop import bipop_cmaes
from deap_tpu.strategies.de import DifferentialEvolution
from deap_tpu.strategies.eda import EMNA, EMNAState, PBIL, PBILState
from deap_tpu.strategies.multiswarm import (
    MultiSwarmPSO,
    MultiSwarmState,
    SpeciationPSO,
    SpeciationState,
    species_seeds,
)
from deap_tpu.strategies.pso import PSO, SwarmState

__all__ = [
    "bipop_cmaes",
    "MultiSwarmPSO",
    "MultiSwarmState",
    "SpeciationPSO",
    "SpeciationState",
    "species_seeds",
    "CMAState",
    "MOState",
    "OnePlusLambdaState",
    "Strategy",
    "StrategyMultiObjective",
    "StrategyOnePlusLambda",
    "hypervolume_contributions_2d",
    "DifferentialEvolution",
    "EMNA",
    "EMNAState",
    "PBIL",
    "PBILState",
    "PSO",
    "SwarmState",
]
