"""BIPOP-CMA-ES — bi-population restart regime with stopping criteria.

Counterpart of the reference's BIPOP example
(/root/reference/examples/es/cma_bipop.py:58-199), promoted to a
first-class strategy: alternating large-population (IPOP doubling) and
small-population restart regimes budgeted against each other
(cma_bipop.py:62-76), each inner CMA-ES run terminated by the standard
Hansen criteria — MaxIter, TolHistFun, EqualFunVals, TolX, TolUpSigma,
Stagnation, ConditionCov, NoEffectAxis, NoEffectCoor
(cma_bipop.py:106-190).

The inner generate→evaluate→update loop is the jit-compiled
:class:`~deap_tpu.strategies.cma.Strategy`; the restart/stopping logic is
inherently data-dependent scalar control flow and runs on host, pulling
a handful of scalars per generation.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.strategies.cma import Strategy
from deap_tpu.support.logbook import Logbook


def bipop_cmaes(key: jax.Array, evaluate: Callable, dim: int,
                sigma0: float = 2.0, nrestarts: int = 10,
                centroid_low: float = -4.0, centroid_high: float = 4.0,
                spec: FitnessSpec = FitnessSpec((-1.0,)),
                tolhistfun: float = 1e-12, tolx: float = 1e-12,
                tolupsigma: float = 1e20, conditioncov: float = 1e14,
                verbose: bool = False,
                ) -> Tuple[np.ndarray, float, List[Logbook]]:
    """Run BIPOP-CMA-ES; returns ``(best_x, best_f, logbooks)`` with one
    logbook per restart (columns gen/evals/restart/regime/min/avg/max,
    cma_bipop.py:104-106). ``evaluate`` is batched ``[λ, dim] -> [λ]``
    raw objective values; minimisation by default via ``spec``."""
    w0 = float(spec.warray[0])
    lambda0 = 4 + int(3 * math.log(dim))
    nsmallpopruns = 0
    smallbudget: List[int] = []
    largebudget: List[int] = []
    logbooks: List[Logbook] = []
    best_x: Optional[np.ndarray] = None
    best_f = math.inf
    i = 0

    while i < (nrestarts + nsmallpopruns):
        key, k_reg, k_c, k_run = jax.random.split(key, 4)
        u = np.asarray(jax.random.uniform(k_reg, (2,)))
        # regime choice (cma_bipop.py:64-76): first and last restart are
        # always regime 1; regime 2 runs while its budget trails
        if (0 < i < (nrestarts + nsmallpopruns) - 1
                and sum(smallbudget) < sum(largebudget)):
            lambda_ = int(lambda0 * (
                0.5 * (2 ** (i - nsmallpopruns) * lambda0) / lambda0
            ) ** (float(u[0]) ** 2))
            sigma = 2 * 10 ** (-2 * float(u[1]))
            nsmallpopruns += 1
            regime = 2
            smallbudget.append(0)
        else:
            lambda_ = 2 ** (i - nsmallpopruns) * lambda0
            sigma = sigma0
            regime = 1
            largebudget.append(0)
        lambda_ = max(lambda_, 2)

        # termination constants (cma_bipop.py:80-93)
        if regime == 1:
            maxiter = 100 + 50 * (dim + 3) ** 2 / math.sqrt(lambda_)
        else:
            maxiter = 0.5 * largebudget[-1] / lambda_
        tolhistfun_iter = 10 + int(math.ceil(30.0 * dim / lambda_))
        equalfunvals_k = int(math.ceil(0.1 + lambda_ / 4.0))

        centroid = jax.random.uniform(k_c, (dim,), minval=centroid_low,
                                      maxval=centroid_high)
        strat = Strategy(centroid=np.asarray(centroid), sigma=sigma,
                         lambda_=lambda_, spec=spec)
        state = strat.initial_state()

        @jax.jit
        def gen_step(k, st):
            genomes = strat.generate(k, st)
            values = evaluate(genomes)
            return strat.update(st, genomes, values), genomes, values

        logbook = Logbook()
        logbooks.append(logbook)
        conditions: Dict[str, bool] = {}
        equalfunvalues: List[int] = []
        bestvalues: List[float] = []
        medianvalues: List[float] = []
        mins: deque = deque(maxlen=tolhistfun_iter)
        t = 0

        while not conditions:
            k_run, k_gen = jax.random.split(k_run)
            state, genomes, values = gen_step(k_gen, state)
            # ascending weighted values: vals[-1] best, vals[-k] k-th best
            # (the reference's sorted population, cma_bipop.py:133-136)
            raw_np = np.asarray(values)
            vals = np.sort(raw_np * w0)
            raw = np.sort(raw_np)
            # best-so-far in the *weighted* direction so a maximisation
            # spec tracks maxima, not minima
            gen_best_i = int(np.argmax(raw_np * w0))
            if best_x is None or raw_np[gen_best_i] * w0 > best_f * w0:
                best_f = float(raw_np[gen_best_i])
                best_x = np.asarray(genomes)[gen_best_i]
            logbook.record(gen=t, evals=lambda_, restart=i, regime=regime,
                           min=float(raw[0]), avg=float(raw.mean()),
                           max=float(raw[-1]))
            if verbose:
                print(logbook.stream)

            # bookkeeping mirrors cma_bipop.py:133-146, in weighted
            # (maximisation) terms so any spec direction works
            equalfunvalues.append(
                int(vals[-1] == vals[-equalfunvals_k]))
            bestvalues.append(float(vals[-1]))
            medianvalues.append(float(vals[int(round(len(vals) / 2.0)) - 1]))
            if regime == 1 and i > 0:
                largebudget[-1] += lambda_
            elif regime == 2:
                smallbudget[-1] += lambda_
            t += 1
            stagnation_iter = int(math.ceil(0.2 * t + 120 + 30.0 * dim
                                            / lambda_))
            noeffectaxis_index = t % dim

            # stopping criteria (cma_bipop.py:152-190)
            st = jax.device_get(state)
            if t >= maxiter:
                conditions["MaxIter"] = True
            mins.append(float(vals[-1]))
            if (len(mins) == mins.maxlen
                    and max(mins) - min(mins) < tolhistfun):
                conditions["TolHistFun"] = True
            if t > dim and sum(equalfunvalues[-dim:]) / float(dim) > 1.0 / 3:
                conditions["EqualFunVals"] = True
            if (np.all(st.pc < tolx)
                    and np.all(np.sqrt(np.diag(st.C)) < tolx)):
                conditions["TolX"] = True
            if float(st.sigma) / sigma > float(st.diagD[-1] ** 2) * tolupsigma:
                conditions["TolUpSigma"] = True
            # weighted values grow on improvement, so stagnation is the
            # recent medians NOT exceeding the older window (the
            # reference's >= on raw minima, flipped into weighted terms)
            if (len(bestvalues) > stagnation_iter
                    and np.median(bestvalues[-20:]) <= np.median(
                        bestvalues[-stagnation_iter:-stagnation_iter + 20])
                    and np.median(medianvalues[-20:]) <= np.median(
                        medianvalues[-stagnation_iter:-stagnation_iter + 20])):
                conditions["Stagnation"] = True
            if float(st.cond) > conditioncov:
                conditions["ConditionCov"] = True
            if np.all(st.centroid == st.centroid
                      + 0.1 * st.sigma * st.diagD[-noeffectaxis_index]
                      * st.B[-noeffectaxis_index]):
                conditions["NoEffectAxis"] = True
            if np.any(st.centroid == st.centroid
                      + 0.2 * st.sigma * np.diag(st.C)):
                conditions["NoEffectCoor"] = True

        if verbose:
            print("Stopped because of condition%s %s"
                  % (":" if len(conditions) == 1 else "s:",
                     ",".join(conditions)))
        i += 1

    return best_x, best_f, logbooks
