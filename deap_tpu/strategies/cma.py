"""CMA-ES strategies — ask-tell engines with pytree state.

TPU-native counterpart of /root/reference/deap/cma.py: ``Strategy``
(Hansen's CMA-ES, cma.py:30-205), ``StrategyOnePlusLambda`` ((1+λ)
Cholesky CMA, cma.py:208-325) and ``StrategyMultiObjective`` (MO-CMA-ES,
Voss/Hansen/Igel 2010, cma.py:328-547).

Where the reference mutates strategy attributes in place, each strategy
here is a *static configuration object* whose ``generate(key, state)``
and ``update(state, genomes, values)`` methods are pure functions over an
immutable state pytree — so the whole generate → evaluate → update cycle
jits into a single XLA program per generation (driven by
``algorithms.ea_generate_update``, counterpart of eaGenerateUpdate,
algorithms.py:440-503). Eigendecomposition / Cholesky factorisations run
on device (`jnp.linalg.eigh` / analytic rank-one updates), and the
O(dim²)–O(dim³) linear algebra of the update lands on the MXU.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax

from deap_tpu.core.fitness import FitnessSpec, lex_sort_desc
from deap_tpu.mo.emo import nd_rank


# =============================================================== Strategy ====

@struct.dataclass
class CMAState:
    """Mutable part of Hansen CMA-ES (the attributes the reference
    updates in ``Strategy.update``, cma.py:123-171)."""

    centroid: jnp.ndarray     # [dim]
    sigma: jnp.ndarray        # scalar
    C: jnp.ndarray            # [dim, dim] covariance
    B: jnp.ndarray            # [dim, dim] eigenbasis
    diagD: jnp.ndarray        # [dim] sqrt eigenvalues (ascending)
    ps: jnp.ndarray           # [dim] step-size evolution path
    pc: jnp.ndarray           # [dim] covariance evolution path
    count: jnp.ndarray        # scalar int32 — update_count

    @property
    def BD(self) -> jnp.ndarray:
        return self.B * self.diagD

    @property
    def cond(self) -> jnp.ndarray:
        """Condition number of C (ratio of extreme axis lengths)."""
        return self.diagD[-1] / self.diagD[0]


def _resolve_eigh_impl(dim: int) -> str:
    """``eigh_impl='auto'`` through the dispatch tuner: race LAPACK
    against the Jacobi sweeps on a representative SPD matrix of this
    dimension. The two solvers are *not* bit-identical, so — unlike
    every other knob — the probe cross-checks by reconstruction
    residual (``‖B·diag(d)·Bᵀ − C‖ ≤ 1e-3·‖C‖``, both bases must
    reconstruct C) instead of bitwise equality, and 'auto' is opt-in
    rather than the constructor default ('lapack' keeps exact parity
    with the reference trajectory pins)."""
    from deap_tpu import tuning

    candidates = {"lapack": None, "jacobi": None}
    check: object = None
    if tuning.active_tuner() is not None:
        from deap_tpu.ops.linalg import eigh_jacobi

        key = jax.random.key(0)
        A = jax.random.normal(key, (dim, dim), jnp.float32)
        C = A @ A.T / dim + jnp.eye(dim, dtype=jnp.float32)
        lapack = jax.jit(jnp.linalg.eigh)
        jacobi = jax.jit(eigh_jacobi)
        candidates = {"lapack": lambda: lapack(C),
                      "jacobi": lambda: jacobi(C)}

        def check(results):
            norm = float(jnp.linalg.norm(C))
            for d, B in results.values():
                resid = B @ jnp.diag(d) @ B.T - C
                if float(jnp.linalg.norm(resid)) > 1e-3 * norm:
                    return False
            return True

    return tuning.resolve(
        "eigh_impl", bucket=(tuning.shape_bucket(dim),),
        default="lapack", candidates=candidates, check=check,
        program="cma_eigh")


class Strategy:
    """Hansen CMA-ES (cma.py:30-205). Parameter defaults follow the
    reference's table (cma.py:41-78): lambda_ = 4 + 3 ln N, mu = λ/2,
    superlinear recombination weights, and the standard cs/damps/ccum/
    ccov1/ccovmu learning rates.

    Usage (ask-tell, like eaGenerateUpdate)::

        strat = Strategy(centroid=[5.0]*N, sigma=0.5, lambda_=20)
        state = strat.initial_state()
        toolbox.register("generate", strat.generate)
        toolbox.register("update", strat.update)
    """

    def __init__(self, centroid, sigma: float, lambda_: Optional[int] = None,
                 mu: Optional[int] = None, weights: str = "superlinear",
                 cmatrix=None, spec: FitnessSpec = FitnessSpec((-1.0,)),
                 eigen_gap: int = 1, eigh_impl: str = "lapack",
                 **params):
        """``eigen_gap`` is Hansen's lazy eigenupdate: recompute the
        eigenbasis (B, diagD) only every ``eigen_gap`` generations,
        sampling and the ps path using the stale basis in between —
        the canonical CMA-ES cost control (pycma's
        ``lazy_gap_evals``), worth roughly the whole eigh when the
        decomposition dominates (it is the largest op in the update
        on accelerators). Default 1 recomputes every generation like
        the reference's update loop (cma.py:123-171), keeping
        benchmark comparisons loop-for-loop honest.

        ``eigh_impl`` picks the covariance eigendecomposition:
        ``'lapack'`` (default — ``jnp.linalg.eigh``, exact parity with
        the reference trajectory pins) or ``'jacobi'``
        (:func:`deap_tpu.ops.linalg.eigh_jacobi`, a pure-XLA
        fixed-sweep solver). Under the multi-run serving engine
        (:mod:`deap_tpu.serving.multirun`), which vmaps this strategy's
        update across tenant lanes, LAPACK's batching rule is a serial
        per-lane loop — ``'jacobi'`` keeps the eigendecomposition
        vectorised ACROSS lanes (the eigh-loop bound on the committed
        3.0× CMA serving number), and is the only formulation on
        backends without LAPACK (TPU). Measured on CPU the serial
        LAPACK loop still wins at dim 8 (``bench.py --mesh``, 0.57×)
        — hence the lapack default there. The two solvers are not
        bit-identical to each other, so a bucket must use one
        consistently; solo==batched bit-identity holds within either
        (``tests/test_sharding_plan.py``)."""
        self._centroid0 = np.asarray(centroid, np.float32)
        self.dim = int(self._centroid0.shape[0])
        self._sigma0 = float(sigma)
        self._cmatrix0 = (np.eye(self.dim, dtype=np.float32) if cmatrix is None
                         else np.asarray(cmatrix, np.float32))
        self.spec = spec
        self.lambda_ = int(lambda_ if lambda_ is not None
                           else 4 + 3 * math.log(self.dim))
        self.chiN = math.sqrt(self.dim) * (
            1 - 1.0 / (4.0 * self.dim) + 1.0 / (21.0 * self.dim ** 2))
        if eigen_gap != int(eigen_gap) or eigen_gap < 1:
            raise ValueError(
                f"eigen_gap must be an integer >= 1, got {eigen_gap!r}")
        self.eigen_gap = int(eigen_gap)
        if eigh_impl == "auto":
            eigh_impl = _resolve_eigh_impl(self.dim)
        if eigh_impl not in ("lapack", "jacobi"):
            raise ValueError(f"unknown eigh_impl {eigh_impl!r} "
                             "(expected 'lapack', 'jacobi' or 'auto')")
        self.eigh_impl = eigh_impl
        if eigh_impl == "jacobi":
            from deap_tpu.ops.linalg import eigh_jacobi
            self._eigh = eigh_jacobi
        else:
            self._eigh = jnp.linalg.eigh
        self._compute_params(mu, weights, params)

    def _compute_params(self, mu, rweights, params):
        """λ-dependent parameters (cma.py:173-205)."""
        self.mu = int(mu if mu is not None else self.lambda_ / 2)
        if rweights == "superlinear":
            w = math.log(self.mu + 0.5) - np.log(np.arange(1, self.mu + 1))
        elif rweights == "linear":
            w = self.mu + 0.5 - np.arange(1, self.mu + 1)
        elif rweights == "equal":
            w = np.ones(self.mu)
        else:
            raise RuntimeError("Unknown weights : %s" % rweights)
        w = w / w.sum()
        self.weights = jnp.asarray(w, jnp.float32)
        self.mueff = float(1.0 / np.sum(w ** 2))

        dim, mueff = self.dim, self.mueff
        self.cc = params.get("ccum", 4.0 / (dim + 4.0))
        self.cs = params.get("cs", (mueff + 2.0) / (dim + mueff + 3.0))
        self.ccov1 = params.get("ccov1", 2.0 / ((dim + 1.3) ** 2 + mueff))
        ccovmu = params.get(
            "ccovmu", 2.0 * (mueff - 2.0 + 1.0 / mueff) / ((dim + 2.0) ** 2 + mueff))
        self.ccovmu = min(1 - self.ccov1, ccovmu)
        damps = 1.0 + 2.0 * max(0.0, math.sqrt((mueff - 1.0) / (dim + 1.0)) - 1.0) + self.cs
        self.damps = params.get("damps", damps)

    def initial_state(self, sigma: Optional[float] = None,
                      centroid=None) -> CMAState:
        """Fresh state; ``sigma``/``centroid`` override the constructor
        values per *state* — the multi-tenant serving layer shares one
        Strategy configuration (λ, weights, learning rates are static
        per compiled bucket) across tenants whose runs differ only in
        these initial-state knobs (deap_tpu/serving/)."""
        C = jnp.asarray(self._cmatrix0)
        evals, B = self._eigh(C)
        c0 = (self._centroid0 if centroid is None
              else np.asarray(centroid, np.float32))
        if c0.shape != (self.dim,):
            raise ValueError(
                f"centroid override shape {c0.shape} != ({self.dim},)")
        return CMAState(
            centroid=jnp.asarray(c0),
            sigma=jnp.float32(self._sigma0 if sigma is None else sigma),
            C=C, B=B, diagD=jnp.sqrt(evals),
            ps=jnp.zeros(self.dim), pc=jnp.zeros(self.dim),
            count=jnp.int32(0))

    def generate(self, key: jax.Array, state: CMAState) -> jnp.ndarray:
        """λ samples ~ centroid + σ · z · (B·D)ᵀ (cma.py:111-121)."""
        arz = jax.random.normal(key, (self.lambda_, self.dim))
        return state.centroid + state.sigma * arz @ state.BD.T

    #: gauges published to a telemetry Meter (telemetry.strategy_probe)
    metric_names = ("sigma", "cond", "ps_norm")

    def metrics(self, state: CMAState) -> dict:
        """Adaptation health as scalars, evaluable inside the scanned
        step: step size, covariance condition number (diverging cond is
        the canonical CMA-ES degeneracy signal), and the step-size
        evolution-path norm."""
        return {"sigma": state.sigma, "cond": state.cond,
                "ps_norm": jnp.linalg.norm(state.ps)}

    def update(self, state: CMAState, genomes: jnp.ndarray,
               values: jnp.ndarray) -> CMAState:
        """Covariance/step-size update from the evaluated offspring
        (cma.py:123-171). ``values`` are raw objectives; ordering uses the
        weighted (maximisation) convention like the reference's
        ``population.sort(key=fitness, reverse=True)``."""
        w = self.spec.wvalues(values if values.ndim == 2 else values[:, None])
        order = lex_sort_desc(w)
        sorted_pop = genomes[order][: self.mu]                     # [mu, dim]

        old_centroid = state.centroid
        centroid = self.weights @ sorted_pop
        c_diff = centroid - old_centroid

        # Step-size evolution path: ps ← (1-cs)ps + √(cs(2-cs)µeff)/σ · C^(-1/2)·Δ
        invsqrtC_cdiff = state.B @ ((1.0 / state.diagD) * (state.B.T @ c_diff))
        ps = (1 - self.cs) * state.ps + (
            math.sqrt(self.cs * (2 - self.cs) * self.mueff) / state.sigma
            * invsqrtC_cdiff)

        count = state.count + 1
        hsig = (jnp.linalg.norm(ps)
                / jnp.sqrt(1.0 - (1.0 - self.cs) ** (2.0 * count.astype(jnp.float32)))
                / self.chiN) < (1.4 + 2.0 / (self.dim + 1.0))
        hsig = hsig.astype(jnp.float32)

        pc = (1 - self.cc) * state.pc + hsig * (
            math.sqrt(self.cc * (2 - self.cc) * self.mueff) / state.sigma * c_diff)

        artmp = sorted_pop - old_centroid                          # [mu, dim]
        C = ((1 - self.ccov1 - self.ccovmu
              + (1 - hsig) * self.ccov1 * self.cc * (2 - self.cc)) * state.C
             + self.ccov1 * jnp.outer(pc, pc)
             + self.ccovmu * (self.weights * artmp.T) @ artmp / state.sigma ** 2)

        sigma = state.sigma * jnp.exp(
            (jnp.linalg.norm(ps) / self.chiN - 1.0) * self.cs / self.damps)

        def fresh_basis(_):
            evals, B = self._eigh(C)
            return B, jnp.sqrt(jnp.maximum(evals, 1e-30))

        if self.eigen_gap == 1:
            B, diagD = fresh_basis(None)
        else:
            # lazy eigenupdate (see __init__): between refreshes the
            # stale basis keeps sampling valid — C itself is always
            # current, only its factorisation lags
            B, diagD = lax.cond(
                count % self.eigen_gap == 0, fresh_basis,
                lambda _: (state.B, state.diagD), None)
        return CMAState(centroid=centroid, sigma=sigma, C=C, B=B,
                        diagD=diagD, ps=ps, pc=pc, count=count)


# ==================================================== StrategyOnePlusLambda ==

@struct.dataclass
class OnePlusLambdaState:
    """State of the (1+λ)-CMA-ES (cma.py:246-257)."""

    parent: jnp.ndarray        # [dim]
    parent_w: jnp.ndarray      # [nobj] weighted fitness of the parent
    sigma: jnp.ndarray         # scalar
    C: jnp.ndarray             # [dim, dim]
    A: jnp.ndarray             # [dim, dim] lower Cholesky of C
    pc: jnp.ndarray            # [dim]
    psucc: jnp.ndarray         # scalar — smoothed success rate


class StrategyOnePlusLambda:
    """(1+λ) CMA-ES with success-rule step-size control (Igel/Hansen/Roth
    2007; cma.py:208-325). The parent improves only when an offspring is
    at least as good; covariance adapts by a rank-one update whose form
    depends on the smoothed success rate vs. ``pthresh``."""

    def __init__(self, parent, parent_fitness, sigma: float,
                 spec: FitnessSpec = FitnessSpec((-1.0,)), **params):
        self._parent0 = np.asarray(parent, np.float32)
        self._parent_fitness0 = np.atleast_1d(
            np.asarray(parent_fitness, np.float32))
        self.dim = int(self._parent0.shape[0])
        self._sigma0 = float(sigma)
        self.spec = spec
        # λ-dependent parameters (cma.py:259-276)
        self.lambda_ = int(params.get("lambda_", 1))
        self.d = params.get("d", 1.0 + self.dim / (2.0 * self.lambda_))
        self.ptarg = params.get("ptarg", 1.0 / (5 + math.sqrt(self.lambda_) / 2.0))
        self.cp = params.get("cp", self.ptarg * self.lambda_ / (2 + self.ptarg * self.lambda_))
        self.cc = params.get("cc", 2.0 / (self.dim + 2.0))
        self.ccov = params.get("ccov", 2.0 / (self.dim ** 2 + 6.0))
        self.pthresh = params.get("pthresh", 0.44)

    def initial_state(self) -> OnePlusLambdaState:
        eye = jnp.eye(self.dim)
        return OnePlusLambdaState(
            parent=jnp.asarray(self._parent0),
            parent_w=self.spec.wvalues(jnp.asarray(self._parent_fitness0)),
            sigma=jnp.float32(self._sigma0),
            C=eye, A=eye, pc=jnp.zeros(self.dim),
            psucc=jnp.float32(self.ptarg))

    def generate(self, key: jax.Array, state: OnePlusLambdaState) -> jnp.ndarray:
        """λ samples ~ parent + σ · z·Aᵀ (cma.py:278-289)."""
        arz = jax.random.normal(key, (self.lambda_, self.dim))
        return state.parent + state.sigma * arz @ state.A.T

    #: gauges published to a telemetry Meter (telemetry.strategy_probe)
    metric_names = ("sigma", "psucc")

    def metrics(self, state: OnePlusLambdaState) -> dict:
        """Step size and the smoothed success rate the 1/5th-style rule
        steers on — the two scalars that explain (1+λ) stagnation."""
        return {"sigma": state.sigma, "psucc": state.psucc}

    def update(self, state: OnePlusLambdaState, genomes: jnp.ndarray,
               values: jnp.ndarray) -> OnePlusLambdaState:
        """Success-rate + rank-one covariance update (cma.py:291-325)."""
        w = self.spec.wvalues(values if values.ndim == 2 else values[:, None])
        # lexicographic "child at least as good as parent" — single- and
        # multi-objective weighted compare like Fitness.__le__
        from deap_tpu.core.fitness import lex_ge
        succ = lex_ge(w, state.parent_w[None, :])
        p_succ = jnp.mean(succ.astype(jnp.float32))
        psucc = (1 - self.cp) * state.psucc + self.cp * p_succ

        order = lex_sort_desc(w)
        best = genomes[order[0]]
        best_w = w[order[0]]
        improved = lex_ge(best_w, state.parent_w)

        x_step = (best - state.parent) / state.sigma
        below = psucc < self.pthresh
        pc_lo = (1 - self.cc) * state.pc + math.sqrt(self.cc * (2 - self.cc)) * x_step
        C_lo = (1 - self.ccov) * state.C + self.ccov * jnp.outer(pc_lo, pc_lo)
        pc_hi = (1 - self.cc) * state.pc
        C_hi = (1 - self.ccov) * state.C + self.ccov * (
            jnp.outer(pc_hi, pc_hi) + self.cc * (2 - self.cc) * state.C)
        pc_new = jnp.where(below, pc_lo, pc_hi)
        C_new = jnp.where(below, C_lo, C_hi)

        parent = jnp.where(improved, best, state.parent)
        parent_w = jnp.where(improved, best_w, state.parent_w)
        pc = jnp.where(improved, pc_new, state.pc)
        C = jnp.where(improved, C_new, state.C)

        sigma = state.sigma * jnp.exp(
            (psucc - self.ptarg) / (self.d * (1.0 - self.ptarg)))
        A = jnp.linalg.cholesky(C)
        return OnePlusLambdaState(parent=parent, parent_w=parent_w,
                                  sigma=sigma, C=C, A=A, pc=pc, psucc=psucc)


# ===================================================== StrategyMultiObjective

@struct.dataclass
class MOState:
    """Per-parent MO-CMA-ES state arrays (the reference's parallel lists,
    cma.py:383-390)."""

    x: jnp.ndarray            # [mu, dim] parent search points
    w: jnp.ndarray            # [mu, nobj] parent weighted fitness
    sigmas: jnp.ndarray       # [mu]
    A: jnp.ndarray            # [mu, dim, dim] lower Cholesky factors
    invA: jnp.ndarray         # [mu, dim, dim] inverse Cholesky factors
    pc: jnp.ndarray           # [mu, dim]
    psucc: jnp.ndarray        # [mu]


def _rank_one_update(invA, A, alpha, beta, v):
    """Incremental Cholesky factor update for C' = αC + β·vvᵀ
    (cma.py:471-485), batched over a leading axis. Keeps both A and A⁻¹
    in O(dim²) per member — no decomposition in the loop."""
    w = jnp.einsum("...ij,...j->...i", invA, v)
    norm_w2 = jnp.sum(w ** 2, axis=-1, keepdims=True)[..., None]   # [..,1,1]
    a = math.sqrt(alpha)
    root = jnp.sqrt(1.0 + beta / alpha * norm_w2)
    b = jnp.where(norm_w2 > 0, a / jnp.maximum(norm_w2, 1e-30) * (root - 1.0), 0.0)
    w_inv = jnp.einsum("...i,...ij->...j", w, invA)
    A_new = a * A + b * v[..., :, None] * w[..., None, :]
    invA_new = (1.0 / a) * invA - (
        b / (a ** 2 + a * b * norm_w2)) * w[..., :, None] * w_inv[..., None, :]
    # Below-threshold updates are mostly noise — skip (cma.py:475)
    skip = (jnp.max(jnp.abs(w), axis=-1) <= 1e-20)[..., None, None]
    return (jnp.where(skip, invA, invA_new), jnp.where(skip, A, A_new))


def hypervolume_contributions_2d(w: jnp.ndarray, mask: jnp.ndarray,
                                 ref: jnp.ndarray) -> jnp.ndarray:
    """Exclusive hypervolume contribution of each masked point, 2-objective
    exact, on device.

    ``w`` is weighted (maximisation) values; ``ref`` the (smaller) reference
    point. For a non-dominated 2-D set sorted by the first objective, each
    point's exclusive contribution is the rectangle to its successor /
    neighbour. Dominated points contribute 0.

    Sorted by descending first objective, the non-dominated staircase has
    strictly increasing second objective; the exclusive contribution of an
    active point is ``(x_i − x_next_active) · (y_i − y_prev_active)`` with
    the reference point closing both ends.
    """
    n = w.shape[0]
    big = jnp.float32(3.4e38)
    x = jnp.where(mask, w[:, 0], -big)
    y = jnp.where(mask, w[:, 1], -big)
    order = jnp.argsort(-x)            # descending x
    xs, ys = x[order], y[order]
    # y of the previous active point = running max of y before i
    ymax_before = jnp.concatenate([
        jnp.full((1,), -big), lax.cummax(ys, axis=0)[:-1]])
    active = (ys > ymax_before) & (xs > -big)
    # x of the next active point = max x among actives after i
    ax_rev = jnp.where(active, xs, -big)[::-1]
    next_active_x = lax.cummax(ax_rev, axis=0)[::-1]
    next_active_x = jnp.concatenate([next_active_x[1:], jnp.full((1,), -big)])
    x_low = jnp.where(next_active_x <= -big, ref[0], next_active_x)
    y_low = jnp.maximum(ymax_before, ref[1])
    contrib_sorted = jnp.where(active, (xs - x_low) * (ys - y_low), 0.0)
    contrib_sorted = jnp.maximum(contrib_sorted, 0.0)
    return jnp.zeros(n).at[order].set(contrib_sorted) * mask


class StrategyMultiObjective:
    """MO-CMA-ES (Voss/Hansen/Igel 2010; cma.py:328-547): µ independent
    (1+1) strategies, indicator-based environmental selection.

    ``generate`` returns a genome *pytree* ``{"x": [λ, dim], "parent":
    int32[λ]}`` so that ``update`` knows each offspring's parent without
    out-of-band state (the reference smuggles this through an ``_ps``
    attribute on the individuals, cma.py:408-426). Evaluators should read
    ``genomes["x"]``.

    Selection keeps the best µ of parents+offspring by (nd-rank, then
    leave-one-out hypervolume contribution on the boundary front —
    exact 2-objective device kernel; crowding-style density for nobj>2).
    """

    def __init__(self, population, fitnesses, sigma: float,
                 mu: Optional[int] = None, lambda_: int = 1,
                 spec: FitnessSpec = FitnessSpec((-1.0, -1.0)), **params):
        x0 = np.asarray(population, np.float32)
        self.mu = int(mu if mu is not None else x0.shape[0])
        self.lambda_ = int(lambda_)
        self.dim = int(x0.shape[1])
        self.spec = spec
        self._x0 = x0
        self._f0 = np.asarray(fitnesses, np.float32)
        self._sigma0 = float(sigma)
        # Step-size / covariance parameters (cma.py:374-381)
        self.d = params.get("d", 1.0 + self.dim / 2.0)
        self.ptarg = params.get("ptarg", 1.0 / (5.0 + 0.5))
        self.cp = params.get("cp", self.ptarg / (2.0 + self.ptarg))
        self.cc = params.get("cc", 2.0 / (self.dim + 2.0))
        self.ccov = params.get("ccov", 2.0 / (self.dim ** 2 + 6.0))
        self.pthresh = params.get("pthresh", 0.44)

    def initial_state(self) -> MOState:
        mu, dim = self.mu, self.dim
        eye = jnp.broadcast_to(jnp.eye(dim), (mu, dim, dim))
        return MOState(
            x=jnp.asarray(self._x0[:mu]),
            w=self.spec.wvalues(jnp.asarray(self._f0[:mu])),
            sigmas=jnp.full((mu,), self._sigma0, jnp.float32),
            A=eye, invA=eye,
            pc=jnp.zeros((mu, dim)),
            psucc=jnp.full((mu,), self.ptarg, jnp.float32))

    def generate(self, key: jax.Array, state: MOState):
        """λ offspring, each from a parent: its own index when λ == µ,
        else a uniformly-random member of the parents' first front
        (cma.py:394-428)."""
        k_z, k_p = jax.random.split(key)
        arz = jax.random.normal(k_z, (self.lambda_, self.dim))
        if self.lambda_ == self.mu:
            parent = jnp.arange(self.mu, dtype=jnp.int32)
        else:
            ranks = nd_rank(state.w)
            front = ranks == 0
            scores = jax.random.uniform(k_p, (self.lambda_, self.mu))
            parent = jnp.argmax(
                jnp.where(front[None, :], scores, -1.0), axis=1).astype(jnp.int32)
        x = (state.x[parent] + state.sigmas[parent, None]
             * jnp.einsum("pij,pj->pi", state.A[parent], arz))
        return {"x": x, "parent": parent}

    #: gauges published to a telemetry Meter (telemetry.strategy_probe)
    metric_names = ("sigma_mean", "sigma_min", "psucc_mean")

    def metrics(self, state: MOState) -> dict:
        """Population-level adaptation health of the µ independent
        (1+1) strategies."""
        return {"sigma_mean": jnp.mean(state.sigmas),
                "sigma_min": jnp.min(state.sigmas),
                "psucc_mean": jnp.mean(state.psucc)}

    # ------------------------------------------------------------ update ----

    def _select_mask(self, w_all: jnp.ndarray) -> jnp.ndarray:
        """Boolean mask keeping µ of the λ+µ candidates: whole fronts in
        rank order, boundary front trimmed by iterative least-hypervolume-
        contributor removal (cma.py:430-469)."""
        n = w_all.shape[0]
        ranks = nd_rank(w_all)
        sorted_ranks = jnp.sort(ranks)
        cut = sorted_ranks[self.mu - 1]
        ahead = ranks < cut
        mid = ranks == cut
        k_fill = self.mu - jnp.sum(ahead)

        # Reference point: worst in each (weighted) dimension, minus 1
        # (the reference computes it in minimisation space +1, cma.py:460-461).
        ref = jnp.min(w_all, axis=0) - 1.0

        nobj = w_all.shape[1]

        def drop_one(state):
            mask, remaining = state
            if nobj == 2:
                contrib = hypervolume_contributions_2d(w_all, mask, ref)
            else:
                # nobj > 2: density proxy (negated crowding) — documented
                # deviation; exact HV for high dims runs via the native
                # extension on host paths.
                d2 = jnp.sum((w_all[:, None, :] - w_all[None, :, :]) ** 2, -1)
                d2 = jnp.where(mask[None, :] & mask[:, None], d2, jnp.inf)
                d2 = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, d2)
                contrib = jnp.min(d2, axis=1)
            contrib = jnp.where(mask, contrib, jnp.inf)
            drop = jnp.argmin(contrib)
            return mask.at[drop].set(False), remaining - 1

        def cond(state):
            _, remaining = state
            return remaining > k_fill

        mid_kept, _ = lax.while_loop(cond, drop_one, (mid, jnp.sum(mid)))
        return ahead | mid_kept

    def update(self, state: MOState, genomes, values: jnp.ndarray) -> MOState:
        """Environmental selection + per-member success/covariance updates
        (cma.py:487-547). Candidate order is [offspring, parents], like
        the reference's ``population + self.parents``."""
        mu, lam, dim = self.mu, self.lambda_, self.dim
        off_x, parent_idx = genomes["x"], genomes["parent"]
        off_w = self.spec.wvalues(values)

        w_all = jnp.concatenate([off_w, state.w], axis=0)       # [λ+µ, nobj]
        chosen = self._select_mask(w_all)
        is_off = jnp.arange(lam + mu) < lam

        # --- parent-entry updates (scan preserves the reference's
        # sequential in-place accumulation over candidates, cma.py:508-538)
        def body(carry, i):
            psucc, sigmas = carry
            p = jnp.where(is_off[i], parent_idx[jnp.minimum(i, lam - 1)], 0)
            off = is_off[i]
            succ = chosen[i]
            new_p = jnp.where(succ, (1 - self.cp) * psucc[p] + self.cp,
                              (1 - self.cp) * psucc[p])
            new_s = sigmas[p] * jnp.exp(
                (new_p - self.ptarg) / (self.d * (1.0 - self.ptarg)))
            psucc = jnp.where(off, psucc.at[p].set(new_p), psucc)
            sigmas = jnp.where(off, sigmas.at[p].set(new_s), sigmas)
            return (psucc, sigmas), None

        (par_psucc, par_sigmas), _ = lax.scan(
            body, (state.psucc, state.sigmas), jnp.arange(lam + mu))

        # --- new entries for chosen offspring (copies of the parent set at
        # update start, cma.py:499-525), fully vectorised over offspring
        p = parent_idx
        last_steps = state.sigmas[p]
        o_psucc = (1 - self.cp) * state.psucc[p] + self.cp
        o_sigmas = state.sigmas[p] * jnp.exp(
            (o_psucc - self.ptarg) / (self.d * (1.0 - self.ptarg)))
        x_step = (off_x - state.x[p]) / last_steps[:, None]
        below = (o_psucc < self.pthresh)[:, None]
        pc_lo = (1 - self.cc) * state.pc[p] + math.sqrt(self.cc * (2 - self.cc)) * x_step
        pc_hi = (1 - self.cc) * state.pc[p]
        o_pc = jnp.where(below, pc_lo, pc_hi)
        alpha_lo, alpha_hi = 1 - self.ccov, 1 - self.ccov + self.cc * (2.0 - self.cc)
        inv_lo, A_lo = _rank_one_update(
            state.invA[p], state.A[p], alpha_lo, self.ccov, pc_lo)
        inv_hi, A_hi = _rank_one_update(
            state.invA[p], state.A[p], alpha_hi, self.ccov, pc_hi)
        below3 = below[:, :, None]
        o_A = jnp.where(below3, A_lo, A_hi)
        o_invA = jnp.where(below3, inv_lo, inv_hi)

        # --- assemble the next parent set: the µ chosen candidates; an
        # offspring brings its new entry, a surviving parent its (updated)
        # own entry (cma.py:540-547)
        sel_idx = jnp.argsort(jnp.where(chosen, jnp.arange(lam + mu),
                                        lam + mu))[:mu]
        off_sel = sel_idx < lam                      # chosen slot is an offspring
        oi = jnp.minimum(sel_idx, lam - 1)           # offspring index
        pi = jnp.clip(sel_idx - lam, 0, mu - 1)      # parent index

        def pick(off_arr, par_arr):
            o = jnp.take(off_arr, oi, axis=0)
            q = jnp.take(par_arr, pi, axis=0)
            m = off_sel.reshape((-1,) + (1,) * (o.ndim - 1))
            return jnp.where(m, o, q)

        x_all = jnp.concatenate([off_x, state.x], axis=0)
        new_x = x_all[sel_idx]
        new_w = w_all[sel_idx]
        return MOState(
            x=new_x, w=new_w,
            sigmas=pick(o_sigmas, par_sigmas),
            A=pick(o_A, state.A),
            invA=pick(o_invA, state.invA),
            pc=pick(o_pc, state.pc),
            psucc=pick(o_psucc, par_psucc))
