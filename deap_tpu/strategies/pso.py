"""Particle Swarm Optimisation — swarm state as a pytree, one fused step.

The reference keeps PSO as examples: the canonical velocity update with
per-particle bests and speed clamping
(/root/reference/examples/pso/basic.py:38-48), and the constricted
(chi/c) variant used by multiswarm PSO
(/root/reference/examples/pso/multiswarm.py:80-95). Both are provided
here as first-class strategies over tensor swarms.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from deap_tpu.core.fitness import FitnessSpec, lex_gt


@struct.dataclass
class SwarmState:
    x: jnp.ndarray          # [n, d] positions
    v: jnp.ndarray          # [n, d] velocities
    w: jnp.ndarray          # [n, nobj] current weighted fitness
    pbest_x: jnp.ndarray    # [n, d] personal best positions
    pbest_w: jnp.ndarray    # [n, nobj]
    gbest_x: jnp.ndarray    # [d] global best position
    gbest_w: jnp.ndarray    # [nobj]


class PSO:
    """Canonical PSO (basic.py): ``v += U(0,φ1)·(pbest−x) + U(0,φ2)·(gbest−x)``
    with per-component speed clamping to [smin, smax] magnitude, or the
    Clerc constriction variant (multiswarm.py) when ``chi`` is given:
    ``v += χ·(ce1·(pbest−x) + ce2·(gbest−x)) − (1−χ)·v``.
    """

    def __init__(self, evaluate: Callable, phi1: float = 2.0,
                 phi2: float = 2.0, smin: Optional[float] = None,
                 smax: Optional[float] = None, chi: Optional[float] = None,
                 spec: FitnessSpec = FitnessSpec((1.0,))):
        self.evaluate = evaluate
        self.phi1, self.phi2 = phi1, phi2
        self.smin, self.smax = smin, smax
        self.chi = chi
        self.spec = spec

    def init(self, key: jax.Array, n: int, dim: int, pmin: float,
             pmax: float, smin: float, smax: float) -> SwarmState:
        """Uniform positions in [pmin, pmax], speeds in [smin, smax]
        (basic.py:31-36)."""
        kx, kv = jax.random.split(key)
        x = jax.random.uniform(kx, (n, dim), minval=pmin, maxval=pmax)
        v = jax.random.uniform(kv, (n, dim), minval=smin, maxval=smax)
        nobj = self.spec.nobj
        neg = jnp.full((n, nobj), -jnp.inf)
        return SwarmState(x=x, v=v, w=neg, pbest_x=x, pbest_w=neg,
                          gbest_x=x[0], gbest_w=jnp.full((nobj,), -jnp.inf))

    def _eval_and_update_bests(self, s: SwarmState) -> SwarmState:
        values = self.evaluate(s.x)
        values = values[:, None] if values.ndim == 1 else values
        w = self.spec.wvalues(values)
        improve_p = lex_gt(w, s.pbest_w)
        pbest_x = jnp.where(improve_p[:, None], s.x, s.pbest_x)
        pbest_w = jnp.where(improve_p[:, None], w, s.pbest_w)
        ibest = jnp.argmax(pbest_w[:, 0])
        improve_g = lex_gt(pbest_w[ibest], s.gbest_w)
        gbest_x = jnp.where(improve_g, pbest_x[ibest], s.gbest_x)
        gbest_w = jnp.where(improve_g, pbest_w[ibest], s.gbest_w)
        return s.replace(w=w, pbest_x=pbest_x, pbest_w=pbest_w,
                         gbest_x=gbest_x, gbest_w=gbest_w)

    def _move(self, key: jax.Array, s: SwarmState) -> SwarmState:
        n, d = s.x.shape
        k1, k2 = jax.random.split(key)
        u1 = jax.random.uniform(k1, (n, d), maxval=self.phi1)
        u2 = jax.random.uniform(k2, (n, d), maxval=self.phi2)
        pull = u1 * (s.pbest_x - s.x) + u2 * (s.gbest_x[None, :] - s.x)
        if self.chi is not None:
            v = s.v + self.chi * pull - (1.0 - self.chi) * s.v
        else:
            v = s.v + pull
        if self.smin is not None and self.smax is not None:
            mag = jnp.abs(v)
            sign = jnp.sign(v) + (v == 0)  # copysign with 0 → positive
            mag = jnp.clip(mag, self.smin, self.smax)
            v = sign * mag
        return s.replace(v=v, x=s.x + v)

    def step(self, key: jax.Array, s: SwarmState) -> SwarmState:
        """evaluate → update bests → move (basic.py main loop :72-83)."""
        s = self._eval_and_update_bests(s)
        return self._move(key, s)

    def run(self, key: jax.Array, s: SwarmState, ngen: int,
            ) -> Tuple[SwarmState, jnp.ndarray]:
        def gen(s, k):
            s = self.step(k, s)
            return s, s.gbest_w[0]

        return lax.scan(gen, s, jax.random.split(key, ngen))
