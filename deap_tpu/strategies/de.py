"""Differential Evolution — DE/rand/1/bin as a compiled per-generation step.

The reference keeps DE as an example (per-agent Python loop,
/root/reference/examples/de/basic.py:66-76: pick three random donors,
binomial crossover with a guaranteed coordinate, greedy replacement); here
it is a first-class strategy whose whole generation is one fused device
step batched over the population, scannable over generations.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu.core.fitness import FitnessSpec, lex_gt
from deap_tpu.core.population import Population


class DifferentialEvolution:
    """DE/rand/1/bin (Storn & Price).

    :param evaluate: batched objective ``genomes [n, d] -> values``.
    :param F: differential weight (reference example uses 1).
    :param CR: crossover probability (reference example uses 0.25).
    :param spec: fitness weights (default single-objective minimisation).

    Semantics match the reference example: donors a, b, c are sampled with
    replacement from the population (selRandom, may include the agent), a
    random coordinate always crosses over, and the trial replaces the
    agent only if strictly better (``y.fitness > agent.fitness``,
    basic.py:75-76).
    """

    def __init__(self, evaluate: Callable, F: float = 1.0, CR: float = 0.25,
                 spec: FitnessSpec = FitnessSpec((-1.0,))):
        self.evaluate = evaluate
        self.F = F
        self.CR = CR
        self.spec = spec

    def step(self, key: jax.Array, pop: Population) -> Population:
        """One DE generation for every agent at once."""
        n, d = pop.genomes.shape
        k_abc, k_cr, k_idx = jax.random.split(key, 3)
        abc = jax.random.randint(k_abc, (3, n), 0, n)
        a, b, c = pop.genomes[abc[0]], pop.genomes[abc[1]], pop.genomes[abc[2]]
        mutant = a + self.F * (b - c)

        cross = jax.random.uniform(k_cr, (n, d)) < self.CR
        forced = jax.random.randint(k_idx, (n,), 0, d)
        cross = cross | (jnp.arange(d)[None, :] == forced[:, None])
        trial = jnp.where(cross, mutant, pop.genomes)

        values = self.evaluate(trial)
        values = values[:, None] if values.ndim == 1 else values
        w_new = self.spec.wvalues(values)
        better = lex_gt(w_new, pop.wvalues)
        genomes = jnp.where(better[:, None], trial, pop.genomes)
        fitness = jnp.where(better[:, None], values, pop.fitness)
        return pop.replace(genomes=genomes, fitness=fitness,
                           valid=jnp.ones_like(pop.valid))

    def run(self, key: jax.Array, pop: Population, ngen: int,
            ) -> Tuple[Population, jnp.ndarray]:
        """Scan ``ngen`` generations; returns the final population and the
        per-generation best weighted fitness trajectory."""
        values = self.evaluate(pop.genomes)
        pop = pop.with_fitness(values if values.ndim == 2 else values[:, None])

        def gen(pop, k):
            pop = self.step(k, pop)
            return pop, jnp.max(pop.wvalues[:, 0])

        return lax.scan(gen, pop, jax.random.split(key, ngen))
