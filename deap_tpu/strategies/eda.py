"""Estimation-of-distribution strategies: PBIL and EMNA.

The reference implements both inside examples driven by
``eaGenerateUpdate`` (/root/reference/examples/eda/pbil.py:27-51,
examples/eda/emna.py:33-64); here they are first-class ask-tell
strategies with pytree state, compatible with
``algorithms.ea_generate_update``.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from deap_tpu.core.fitness import FitnessSpec, lex_sort_desc


@struct.dataclass
class PBILState:
    prob_vector: jnp.ndarray   # [dim] Bernoulli parameters
    key: jnp.ndarray           # PRNG key for the update-side mutation


class PBIL:
    """Population-Based Incremental Learning (pbil.py:27-51): sample λ
    bitstrings from a probability vector; pull the vector toward the best
    sample; mutate each component with probability ``mut_prob`` by
    ``mut_shift`` toward a random bit."""

    def __init__(self, ndim: int, learning_rate: float = 0.3,
                 mut_prob: float = 0.1, mut_shift: float = 0.05,
                 lambda_: int = 20,
                 spec: FitnessSpec = FitnessSpec((1.0,))):
        self.ndim = ndim
        self.learning_rate = learning_rate
        self.mut_prob = mut_prob
        self.mut_shift = mut_shift
        self.lambda_ = lambda_
        self.spec = spec

    def initial_state(self, key: Optional[jax.Array] = None) -> PBILState:
        return PBILState(
            prob_vector=jnp.full((self.ndim,), 0.5),
            key=key if key is not None else jax.random.key(0))

    def generate(self, key: jax.Array, state: PBILState) -> jnp.ndarray:
        """λ Bernoulli samples of the probability vector (pbil.py:34-38)."""
        return jax.random.bernoulli(
            key, state.prob_vector, (self.lambda_, self.ndim)
        ).astype(jnp.float32)

    def update(self, state: PBILState, genomes: jnp.ndarray,
               values: jnp.ndarray) -> PBILState:
        """Learn toward the best sample, then mutate (pbil.py:40-51)."""
        w = self.spec.wvalues(values if values.ndim == 2 else values[:, None])
        best = genomes[lex_sort_desc(w)[0]]
        p = state.prob_vector * (1.0 - self.learning_rate) \
            + best * self.learning_rate
        key, k_m, k_b = jax.random.split(state.key, 3)
        do_mut = jax.random.bernoulli(k_m, self.mut_prob, (self.ndim,))
        bits = jax.random.bernoulli(k_b, 0.5, (self.ndim,)).astype(jnp.float32)
        p_mut = p * (1.0 - self.mut_shift) + bits * self.mut_shift
        return PBILState(prob_vector=jnp.where(do_mut, p_mut, p), key=key)


@struct.dataclass
class EMNAState:
    centroid: jnp.ndarray   # [dim]
    sigma: jnp.ndarray      # scalar isotropic std


class EMNA:
    """Estimation of Multivariate Normal Algorithm, global variant
    (Teytaud & Teytaud 2009; emna.py:33-64): fit an isotropic Gaussian to
    the µ best of λ samples each generation."""

    def __init__(self, centroid, sigma: float, mu: int, lambda_: int,
                 spec: FitnessSpec = FitnessSpec((-1.0,))):
        self._centroid0 = jnp.asarray(centroid, jnp.float32)
        self._sigma0 = float(sigma)
        self.dim = int(self._centroid0.shape[0])
        self.mu = mu
        self.lambda_ = lambda_
        self.spec = spec

    def initial_state(self) -> EMNAState:
        return EMNAState(centroid=self._centroid0,
                         sigma=jnp.float32(self._sigma0))

    def generate(self, key: jax.Array, state: EMNAState) -> jnp.ndarray:
        return state.centroid + state.sigma * jax.random.normal(
            key, (self.lambda_, self.dim))

    def update(self, state: EMNAState, genomes: jnp.ndarray,
               values: jnp.ndarray) -> EMNAState:
        """Mean/variance re-estimation from the µ best (emna.py:55-64)."""
        w = self.spec.wvalues(values if values.ndim == 2 else values[:, None])
        order = lex_sort_desc(w)
        z = genomes[order[: self.mu]] - state.centroid
        avg = jnp.mean(z, axis=0)
        sigma = jnp.sqrt(jnp.sum((z - avg) ** 2) / (self.mu * self.dim))
        return EMNAState(centroid=state.centroid + avg, sigma=sigma)
