"""Multi-swarm and speciation PSO for dynamic optimisation.

Counterparts of the reference's dynamic-landscape PSO examples:

- **MultiSwarmPSO** — Blackwell, Branke & Li 2008 multi-swarm PSO
  (/root/reference/examples/pso/multiswarm.py): several constricted
  swarms with anti-convergence (spawn a fresh swarm when all converge,
  kill the worst when too many roam, multiswarm.py:146-168),
  change detection by re-evaluating each swarm best
  (multiswarm.py:171-177), quantum-cloud re-diversification around the
  best (convertQuantum, multiswarm.py:58-76), and exclusion re-init of
  the worse of any two swarms closer than ``rexcl``
  (multiswarm.py:203-215).
- **SpeciationPSO** — speciation PSO (examples/pso/speciation.py):
  particles sorted best-first greedily form species around seeds within
  radius ``rs`` (speciation.py:133-146), species sizes capped at
  ``pmax`` with overflow re-initialised (speciation.py:160-166), the
  worst species replaced wholesale (speciation.py:175-177).

The reference grows/shrinks Python lists of swarms; here the swarm axis
has a static ``capacity`` and an ``active`` mask — add/remove become
mask flips, so the whole dynamic algorithm is one jit-compiled step.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

CHI = 0.729843788       # Clerc constriction (multiswarm.py:100)
C = 2.05


@struct.dataclass
class MultiSwarmState:
    x: jnp.ndarray          # [S, P, D] positions
    v: jnp.ndarray          # [S, P, D] velocities
    pbest_x: jnp.ndarray    # [S, P, D]
    pbest_f: jnp.ndarray    # [S, P] weighted fitness (-inf = no pbest yet)
    sbest_x: jnp.ndarray    # [S, D]
    sbest_f: jnp.ndarray    # [S]    (-inf = no swarm best yet)
    active: jnp.ndarray     # [S] bool
    nevals: jnp.ndarray     # scalar int32 running evaluation count


def _quantum_cloud(key: jax.Array, n: int, dim: int, centre: jnp.ndarray,
                   rcloud: float, dist: str) -> jnp.ndarray:
    """Quantum particle cloud around ``centre`` (convertQuantum,
    multiswarm.py:58-76): direction = normalised gaussian, radius scale
    by distribution ``gaussian`` | ``uvd`` | ``nuvd``."""
    k_pos, k_u = jax.random.split(key)
    pos = jax.random.normal(k_pos, (n, dim))
    norm = jnp.linalg.norm(pos, axis=-1, keepdims=True)
    norm = jnp.where(norm == 0, 1.0, norm)
    if dist == "gaussian":
        u = jnp.abs(jax.random.normal(k_u, (n, 1)) / 3.0) ** (1.0 / dim)
    elif dist == "uvd":
        u = jax.random.uniform(k_u, (n, 1)) ** (1.0 / dim)
    elif dist == "nuvd":
        u = jnp.abs(jax.random.normal(k_u, (n, 1)) / 3.0)
    else:
        raise ValueError(dist)
    return rcloud * pos * u / norm + centre


class MultiSwarmPSO:
    """Blackwell-Branke-Li multi-swarm PSO over a dynamic landscape.

    :param evaluate: batched ``x [n, d] -> f [n]`` (maximised). For
        MovingPeaks pass a closure over the current landscape state and
        call :meth:`step` between peak changes.
    """

    def __init__(self, evaluate: Callable, pmin: float, pmax: float,
                 rcloud: float = 0.5, nexcess: int = 3,
                 dist: str = "nuvd", chi: float = CHI, c: float = C):
        self.evaluate = evaluate
        self.pmin, self.pmax = pmin, pmax
        self.rcloud = rcloud
        self.nexcess = nexcess
        self.dist = dist
        self.chi, self.c = chi, c

    # ------------------------------------------------------------------ init ----

    def _fresh_swarm(self, key: jax.Array, nparticles: int, dim: int):
        kx, kv = jax.random.split(key)
        half = (self.pmax - self.pmin) / 2.0
        x = jax.random.uniform(kx, (nparticles, dim), minval=self.pmin,
                               maxval=self.pmax)
        v = jax.random.uniform(kv, (nparticles, dim), minval=-half,
                               maxval=half)
        return x, v

    def init(self, key: jax.Array, nswarms: int, nparticles: int, dim: int,
             capacity: Optional[int] = None) -> MultiSwarmState:
        S = capacity if capacity is not None else nswarms * 4
        keys = jax.random.split(key, S)
        x, v = jax.vmap(lambda k: self._fresh_swarm(k, nparticles, dim))(keys)
        neg = jnp.full((S, nparticles), -jnp.inf)
        return MultiSwarmState(
            x=x, v=v, pbest_x=x, pbest_f=neg,
            sbest_x=x[:, 0], sbest_f=jnp.full((S,), -jnp.inf),
            active=jnp.arange(S) < nswarms,
            nevals=jnp.int32(0),
        )

    # ------------------------------------------------------------------ step ----

    def _rexcl(self, s: MultiSwarmState) -> jnp.ndarray:
        """Exclusion radius (multiswarm.py:146): domain range /
        (2 · nswarms^(1/D))."""
        n_act = jnp.maximum(s.active.sum(), 1)
        dim = s.x.shape[-1]
        return (self.pmax - self.pmin) / (
            2.0 * n_act.astype(jnp.float32) ** (1.0 / dim))

    def step(self, key: jax.Array, s: MultiSwarmState) -> MultiSwarmState:
        S, P, D = s.x.shape
        k_spawn, k_quant, k_move, k_excl = jax.random.split(key, 4)
        rexcl = self._rexcl(s)

        # --- anti-convergence (multiswarm.py:148-168) -----------------------
        diff = s.x[:, :, None, :] - s.x[:, None, :, :]
        diam = jnp.sqrt((diff ** 2).sum(-1)).max(axis=(1, 2))     # [S]
        roaming = s.active & (diam > 2.0 * rexcl)
        n_roaming = roaming.sum()
        all_converged = n_roaming == 0
        # spawn: first inactive slot becomes a fresh random swarm
        can_spawn = ~s.active.all()
        spawn_slot = jnp.argmax(~s.active)
        fx, fv = self._fresh_swarm(k_spawn, P, D)
        do_spawn = all_converged & can_spawn
        sel_spawn = do_spawn & (jnp.arange(S) == spawn_slot)
        x = jnp.where(sel_spawn[:, None, None], fx[None], s.x)
        v = jnp.where(sel_spawn[:, None, None], fv[None], s.v)
        pbest_x = jnp.where(sel_spawn[:, None, None], fx[None], s.pbest_x)
        pbest_f = jnp.where(sel_spawn[:, None], -jnp.inf, s.pbest_f)
        sbest_f = jnp.where(sel_spawn, -jnp.inf, s.sbest_f)
        active = s.active | sel_spawn
        # kill: worst roaming swarm by best fitness when too many roam
        worst = jnp.argmin(jnp.where(roaming, sbest_f, jnp.inf))
        do_kill = n_roaming > self.nexcess
        active = active & ~(do_kill & (jnp.arange(S) == worst))
        s = s.replace(x=x, v=v, pbest_x=pbest_x, pbest_f=pbest_f,
                      sbest_f=sbest_f, active=active)

        # --- change detection + quantum re-diversification ------------------
        # re-evaluate each swarm best (multiswarm.py:171-177)
        has_sbest = s.sbest_f > -jnp.inf
        refit = self.evaluate(s.sbest_x)                            # [S]
        changed = s.active & has_sbest & (refit != s.sbest_f)
        nevals = s.nevals + (s.active & has_sbest).sum()
        clouds = jax.vmap(
            lambda k, c: _quantum_cloud(k, P, D, c, self.rcloud, self.dist)
        )(jax.random.split(k_quant, S), s.sbest_x)
        x = jnp.where(changed[:, None, None], clouds, s.x)
        pbest_f = jnp.where(changed[:, None], -jnp.inf, s.pbest_f)
        sbest_f = jnp.where(changed, -jnp.inf, s.sbest_f)
        s = s.replace(x=x, pbest_f=pbest_f, sbest_f=sbest_f)

        # --- constricted move (only particles with pbest AND swarm best,
        # multiswarm.py:181-184) --------------------------------------------
        has_p = s.pbest_f > -jnp.inf                                # [S, P]
        has_s = (s.sbest_f > -jnp.inf)[:, None]                     # [S, 1]
        k1, k2 = jax.random.split(k_move)
        ce1 = self.c * jax.random.uniform(k1, (S, P, D))
        ce2 = self.c * jax.random.uniform(k2, (S, P, D))
        pull = (ce1 * (s.sbest_x[:, None, :] - s.x)
                + ce2 * (s.pbest_x - s.x))
        vnew = s.v + self.chi * pull - (1.0 - self.chi) * s.v
        move = (has_p & has_s[:, :1])[:, :, None] * s.active[:, None, None]
        v = jnp.where(move, vnew, s.v)
        x = jnp.where(move, s.x + v, s.x)

        # --- evaluate + update attractors -----------------------------------
        f = self.evaluate(x.reshape(S * P, D)).reshape(S, P)
        nevals = nevals + s.active.sum() * P
        improve_p = f > s.pbest_f
        pbest_x = jnp.where(improve_p[:, :, None], x, s.pbest_x)
        pbest_f = jnp.where(improve_p, f, s.pbest_f)
        ibest = jnp.argmax(pbest_f, axis=1)                        # [S]
        cand_f = jnp.take_along_axis(pbest_f, ibest[:, None], 1)[:, 0]
        cand_x = jnp.take_along_axis(pbest_x, ibest[:, None, None], 1)[:, 0]
        improve_s = cand_f > s.sbest_f
        sbest_x = jnp.where(improve_s[:, None], cand_x, s.sbest_x)
        sbest_f = jnp.where(improve_s, cand_f, s.sbest_f)
        s = s.replace(x=x, v=v, pbest_x=pbest_x, pbest_f=pbest_f,
                      sbest_x=sbest_x, sbest_f=sbest_f, nevals=nevals)

        # --- exclusion (multiswarm.py:203-215): the worse of any two
        # close swarms re-initialises --------------------------------------
        dists = jnp.linalg.norm(
            s.sbest_x[:, None, :] - s.sbest_x[None, :, :], axis=-1)
        has = (s.sbest_f > -jnp.inf) & s.active
        close = (dists < rexcl) & has[:, None] & has[None, :] & (
            ~jnp.eye(S, dtype=bool))
        # exact reference semantics (multiswarm.py:203-215): sweep pairs
        # (s1 < s2) in index order, skip pairs with an already-marked
        # member, mark s1 when bestfit[s1] <= bestfit[s2] else s2. The
        # sweep is sequential by construction — a fori_loop over the
        # S(S-1)/2 pairs (S is small, the body is scalar).
        def pair_step(t, marked):
            s1 = t // S
            s2 = t % S
            eligible = ((s2 > s1) & close[s1, s2]
                        & ~marked[s1] & ~marked[s2])
            worse = jnp.where(s.sbest_f[s1] <= s.sbest_f[s2], s1, s2)
            return marked.at[worse].set(marked[worse] | eligible)

        reinit = lax.fori_loop(0, S * S, pair_step,
                               jnp.zeros((S,), bool))
        rx, rv = jax.vmap(lambda k: self._fresh_swarm(k, P, D))(
            jax.random.split(k_excl, S))
        x = jnp.where(reinit[:, None, None], rx, s.x)
        v = jnp.where(reinit[:, None, None], rv, s.v)
        pbest_f = jnp.where(reinit[:, None], -jnp.inf, s.pbest_f)
        sbest_f = jnp.where(reinit, -jnp.inf, s.sbest_f)
        return s.replace(x=x, v=v, pbest_f=pbest_f, sbest_f=sbest_f)

    def best(self, s: MultiSwarmState) -> Tuple[jnp.ndarray, jnp.ndarray]:
        i = jnp.argmax(jnp.where(s.active, s.sbest_f, -jnp.inf))
        return s.sbest_x[i], s.sbest_f[i]


# ------------------------------------------------------------- speciation ----

def species_seeds(x: jnp.ndarray, f: jnp.ndarray, rs: float,
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy best-first speciation (speciation.py:133-146): walking
    particles in fitness order, one becomes a *seed* iff no better seed
    lies within radius ``rs``; every particle joins the best seed within
    ``rs`` (itself if it is a seed).

    Returns ``(is_seed bool[n], species int32[n])`` where ``species[i]``
    is the index of particle i's seed.
    """
    n = x.shape[0]
    order = jnp.argsort(-f)                     # best first
    xs = x[order]
    d = jnp.linalg.norm(xs[:, None, :] - xs[None, :, :], axis=-1)

    def step(seed_mask, i):
        near_better_seed = (d[i] <= rs) & seed_mask & (jnp.arange(n) < i)
        is_seed = ~near_better_seed.any()
        return seed_mask.at[i].set(is_seed), is_seed

    seed_sorted, _ = lax.scan(step, jnp.zeros((n,), bool), jnp.arange(n))
    # species of sorted-particle i = first (best) seed within rs
    within = (d <= rs) & seed_sorted[None, :]
    first_seed_sorted = jnp.argmax(within, axis=1)  # seeds exist: i itself
    # map back to original indices
    inv = jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    is_seed = jnp.zeros((n,), bool).at[order].set(seed_sorted)
    species = order[first_seed_sorted][inv]
    return is_seed, species


@struct.dataclass
class SpeciationState:
    x: jnp.ndarray          # [n, d]
    v: jnp.ndarray          # [n, d]
    pbest_x: jnp.ndarray    # [n, d]
    pbest_f: jnp.ndarray    # [n]
    nevals: jnp.ndarray


class SpeciationPSO:
    """Speciation PSO on a dynamic landscape (examples/pso/speciation.py):
    species form around best-first seeds (radius ``rs``), each particle
    is pulled toward its species seed's best position, species are
    capped at ``pmax`` members (overflow re-initialised,
    speciation.py:160-166) and the worst species is replaced by fresh
    particles every generation (speciation.py:175-177). Change detection
    re-evaluates seed bests and converts species to quantum clouds
    (speciation.py:149-157)."""

    def __init__(self, evaluate: Callable, pmin: float, pmax: float,
                 rs: float, pmax_size: int = 10, rcloud: float = 1.0,
                 chi: float = CHI, c: float = C):
        self.evaluate = evaluate
        self.pmin, self.pmax = pmin, pmax
        self.rs = rs
        self.pmax_size = pmax_size
        self.rcloud = rcloud
        self.chi, self.c = chi, c

    def init(self, key: jax.Array, n: int, dim: int) -> SpeciationState:
        kx, kv = jax.random.split(key)
        half = (self.pmax - self.pmin) / 2.0
        x = jax.random.uniform(kx, (n, dim), minval=self.pmin,
                               maxval=self.pmax)
        v = jax.random.uniform(kv, (n, dim), minval=-half, maxval=half)
        return SpeciationState(x=x, v=v, pbest_x=x,
                               pbest_f=jnp.full((n,), -jnp.inf),
                               nevals=jnp.int32(0))

    def step(self, key: jax.Array, s: SpeciationState) -> SpeciationState:
        n, d = s.x.shape
        k_q, k_move, k_over, k_worst = jax.random.split(key, 4)

        # evaluate + personal bests (speciation.py:124-129)
        f = self.evaluate(s.x)
        improve = f > s.pbest_f
        pbest_x = jnp.where(improve[:, None], s.x, s.pbest_x)
        pbest_f = jnp.where(improve, f, s.pbest_f)
        nevals = s.nevals + n

        # species structure over personal bests
        is_seed, species = species_seeds(pbest_x, pbest_f, self.rs)
        seed_best_x = pbest_x[species]

        # change detection: re-evaluate seed bests. Static shapes force
        # a full-batch evaluate (the reference evaluates just the seeds,
        # speciation.py:149-150); nevals counts the real cost.
        seed_fit = self.evaluate(pbest_x)
        nevals = nevals + n
        changed = (is_seed & (seed_fit != pbest_f))[species].any()

        # quantum conversion of all species around their seeds
        cloud = _quantum_cloud(k_q, n, d, jnp.zeros((d,)), self.rcloud,
                               "nuvd") + seed_best_x
        # rank within species: strict total order (fitness, then index)
        # so ties still count toward the cap — the reference caps by
        # list position, which is likewise tie-insensitive
        # (speciation.py:160-166)
        idx = jnp.arange(n)
        better = (pbest_f[None, :] > pbest_f[:, None]) | (
            (pbest_f[None, :] == pbest_f[:, None]) & (idx[None, :] < idx[:, None]))
        same = species[None, :] == species[:, None]
        rank = (better & same).sum(axis=1)
        overflow = rank >= self.pmax_size

        # worst species = the last seed in fitness order
        worst_seed = jnp.argmin(jnp.where(is_seed, pbest_f, jnp.inf))
        in_worst = species == worst_seed

        # constricted move toward the species seed best
        k1, k2 = jax.random.split(k_move)
        ce1 = self.c * jax.random.uniform(k1, (n, d))
        ce2 = self.c * jax.random.uniform(k2, (n, d))
        pull = ce1 * (seed_best_x - s.x) + ce2 * (pbest_x - s.x)
        v = s.v + self.chi * pull - (1.0 - self.chi) * s.v
        moved_x = s.x + v

        half = (self.pmax - self.pmin) / 2.0
        fresh_x = jax.random.uniform(k_over, (n, d), minval=self.pmin,
                                     maxval=self.pmax)
        fresh_v = jax.random.uniform(k_worst, (n, d), minval=-half,
                                     maxval=half)

        # the worst species is replaced by fresh particles EVERY
        # generation, change or not (speciation.py:175-177 runs outside
        # the if/else); the pmax overflow cap only applies on
        # non-change generations (speciation.py:160-166 is in the else)
        reinit = overflow | in_worst
        x_changed = jnp.where(in_worst[:, None], fresh_x, cloud)
        x_normal = jnp.where(reinit[:, None], fresh_x, moved_x)
        x = jnp.where(changed, x_changed, x_normal)
        fresh_mask = jnp.where(changed, in_worst, reinit)
        v = jnp.where(fresh_mask[:, None], fresh_v, v)
        # quantum conversion and re-initialisation both reset bests
        # (speciation.py:155-157: del fitness/bestfit, best = None)
        reset = changed | reinit
        pbest_f = jnp.where(reset, -jnp.inf, pbest_f)
        pbest_x = jnp.where(reset[:, None], x, pbest_x)
        return s.replace(x=x, v=v, pbest_x=pbest_x, pbest_f=pbest_f,
                         nevals=nevals)

    def best(self, s: SpeciationState) -> Tuple[jnp.ndarray, jnp.ndarray]:
        i = jnp.argmax(s.pbest_f)
        return s.pbest_x[i], s.pbest_f[i]
