"""ctypes binding for the native hypervolume library.

The reference binds its C hypervolume through a hand-written CPython
module (/root/reference/deap/tools/_hypervolume/hv.cpp:29-121); here the
C++ core exports a plain C ABI and this module loads it with ctypes —
no compiled Python glue to keep in sync. Importing this module raises
if the shared library is missing (triggering the pure-Python fallback
in :mod:`deap_tpu.native`); build it with ``python -m
deap_tpu.native.build``.
"""

from __future__ import annotations

import ctypes
import pathlib

import numpy as np

_LIB_PATH = pathlib.Path(__file__).resolve().parent / "_libhv.so"
_SRC_PATH = pathlib.Path(__file__).resolve().parent / "src" / "hv.cpp"

if not _LIB_PATH.exists() or (
    _SRC_PATH.exists() and _SRC_PATH.stat().st_mtime > _LIB_PATH.stat().st_mtime
):
    # One cheap automatic (re)build attempt — on first use or when the
    # source is newer than the library — mirroring setup.py's optional
    # build with graceful failure (reference setup.py:93-108).
    from deap_tpu.native.build import build

    build(verbose=False, target="hv.cpp")

_lib = ctypes.CDLL(str(_LIB_PATH))

_lib.deap_tpu_hypervolume.restype = ctypes.c_double
_lib.deap_tpu_hypervolume.argtypes = [
    ctypes.POINTER(ctypes.c_double), ctypes.c_int, ctypes.c_int,
    ctypes.POINTER(ctypes.c_double)]
_lib.deap_tpu_hv_contributions.restype = None
_lib.deap_tpu_hv_contributions.argtypes = [
    ctypes.POINTER(ctypes.c_double), ctypes.c_int, ctypes.c_int,
    ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double)]


def _as_c(points, ref):
    pts = np.ascontiguousarray(points, dtype=np.float64)
    r = np.ascontiguousarray(ref, dtype=np.float64)
    if pts.ndim != 2 or pts.shape[1] != r.shape[0]:
        raise ValueError("points must be [n, d] with d == len(ref)")
    return pts, r


def hypervolume(points, ref) -> float:
    """Exact hypervolume (minimisation) of ``points`` w.r.t. ``ref``."""
    pts, r = _as_c(points, ref)
    n, d = pts.shape
    return float(_lib.deap_tpu_hypervolume(
        pts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n, d,
        r.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))


def hv_contributions(points, ref) -> np.ndarray:
    """Leave-one-out exclusive hypervolume contribution per point."""
    pts, r = _as_c(points, ref)
    n, d = pts.shape
    out = np.empty(n, dtype=np.float64)
    _lib.deap_tpu_hv_contributions(
        pts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n, d,
        r.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out
