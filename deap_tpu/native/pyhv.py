"""Pure-Python/numpy exact hypervolume (fallback for the C++ extension).

Counterpart of /root/reference/deap/tools/_hypervolume/pyhv.py (which
warns "expect this to be very slow", pyhv.py:35-36). This is an
independent implementation of the WFG exclusive-hypervolume recursion
(While, Fonseca et al. lineage) with a closed-form 2-D staircase fast
path — not a port of the reference's dimension-sweep code.

Convention: MINIMISATION relative to ``ref``; points not strictly below
``ref`` in every objective contribute nothing.
"""

from __future__ import annotations

import numpy as np


def _nondominated(pts: np.ndarray) -> np.ndarray:
    """Remove points weakly dominated by another (minimisation)."""
    n = len(pts)
    if n <= 1:
        return pts
    keep = np.ones(n, bool)
    for i in range(n):
        if not keep[i]:
            continue
        others = keep.copy()
        others[i] = False
        dom = (np.all(pts <= pts[i], axis=1)
               & np.any(pts < pts[i], axis=1) & others)
        if dom.any():
            keep[i] = False
    # drop exact duplicates, keep one copy
    uniq, idx = np.unique(pts[keep], axis=0, return_index=True)
    return uniq


def _hv2d(pts: np.ndarray, ref: np.ndarray) -> float:
    """Staircase: points sorted by f0 ascending have strictly descending
    f1 after nondominated filtering; sum the exclusive slabs."""
    order = np.argsort(pts[:, 0])
    pts = pts[order]
    f0 = np.append(pts[1:, 0], ref[0])
    return float(np.sum((f0 - pts[:, 0]) * (ref[1] - pts[:, 1])))


def _wfg(pts: np.ndarray, ref: np.ndarray) -> float:
    if len(pts) == 0:
        return 0.0
    if pts.shape[1] == 2:
        return _hv2d(pts, ref)
    if len(pts) == 1:
        return float(np.prod(ref - pts[0]))
    total = 0.0
    for i in range(len(pts)):
        p = pts[i]
        incl = float(np.prod(ref - p))
        rest = pts[i + 1:]
        if len(rest):
            limited = np.maximum(rest, p)
            limited = _nondominated(limited)
            total += incl - _wfg(limited, ref)
        else:
            total += incl
    return total


def hypervolume(points, ref) -> float:
    """Exact hypervolume of ``points`` (minimisation) w.r.t. ``ref``."""
    pts = np.asarray(points, dtype=np.float64)
    ref = np.asarray(ref, dtype=np.float64)
    if pts.ndim != 2 or pts.shape[1] != ref.shape[0]:
        raise ValueError("points must be [n, d] with d == len(ref)")
    pts = pts[np.all(pts < ref, axis=1)]
    pts = _nondominated(pts)
    return _wfg(pts, ref)
