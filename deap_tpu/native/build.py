"""Build the native hypervolume shared library.

Counterpart of the reference's extension build (setup.py:60 with its
graceful build-failure fallback, setup.py:35-53): ``python -m
deap_tpu.native.build`` compiles ``src/hv.cpp`` with g++ into
``_libhv.so`` next to this file; the ctypes binding picks it up on the
next import, and :mod:`deap_tpu.native` falls back to the pure-Python
WFG implementation when it is absent.
"""

from __future__ import annotations

import pathlib
import subprocess

HERE = pathlib.Path(__file__).resolve().parent
SRC = HERE / "src" / "hv.cpp"
LIB = HERE / "_libhv.so"


def build(verbose: bool = True) -> pathlib.Path:
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
           str(SRC), "-o", str(LIB)]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return LIB


if __name__ == "__main__":
    print(build())
