"""Build the native hypervolume shared library.

Counterpart of the reference's extension build (setup.py:60 with its
graceful build-failure fallback, setup.py:35-53): ``python -m
deap_tpu.native.build`` compiles ``src/hv.cpp`` with g++ into
``_libhv.so`` next to this file; the ctypes binding picks it up on the
next import, and :mod:`deap_tpu.native` falls back to the pure-Python
WFG implementation when it is absent.
"""

from __future__ import annotations

import pathlib
import subprocess

HERE = pathlib.Path(__file__).resolve().parent
SRC = HERE / "src" / "hv.cpp"
LIB = HERE / "_libhv.so"
TARGETS = {
    "hv.cpp": "_libhv.so",        # hypervolume (reference _hv.c/hv.cpp)
    "ant.cpp": "_libant.so",      # ant simulator (AntSimulatorFast.cpp)
}


def _compile(src: pathlib.Path, lib: pathlib.Path, verbose: bool) -> None:
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
           str(src), "-o", str(lib)]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)


def build(verbose: bool = True, target: str | None = None) -> pathlib.Path:
    """Compile the native sources. ``target`` names one source file
    (e.g. ``"hv.cpp"``) so each binding's staleness auto-rebuild stays
    independent of the other sources' health; default builds all."""
    items = ([(target, TARGETS[target])] if target is not None
             else list(TARGETS.items()))
    for src_name, lib_name in items:
        src = HERE / "src" / src_name
        if not src.exists():
            raise FileNotFoundError(
                f"native source {src} is missing; cannot build {lib_name}")
        _compile(src, HERE / lib_name, verbose)
    return HERE / TARGETS[target] if target else LIB


if __name__ == "__main__":
    print(build())
