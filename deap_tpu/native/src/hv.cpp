// Native exact hypervolume for deap_tpu.
//
// Counterpart of the reference's C extension (_hv.c / hv.cpp — the
// Fonseca–Paquete–López-Ibáñez dimension-sweep implementation,
// /root/reference/deap/tools/_hypervolume/_hv.c:59,1456). This is an
// independent implementation of the WFG exclusive-hypervolume recursion
// (While, Bradstreet & Barone 2012) with a 2-D staircase base case —
// written for this framework, not a port of the reference's AVL-tree
// sweep code. Exposed through a plain C ABI consumed via ctypes
// (deap_tpu/native/hv_binding.py), mirroring the reference's
// graceful-fallback import seam (deap/tools/indicator.py:3-8).
//
// Convention: MINIMISATION relative to `ref`; points not strictly below
// the reference point in every objective contribute nothing.

#include <algorithm>
#include <cstddef>
#include <vector>

namespace {

struct Front {
    // Flat row-major [n, d] storage with index indirection to avoid
    // copying rows during sorts.
    std::vector<double> data;
    int d = 0;

    std::size_t size() const { return d ? data.size() / d : 0; }
    const double* row(std::size_t i) const { return data.data() + i * d; }
    void push(const double* p) { data.insert(data.end(), p, p + d); }
};

double hv2d(Front& f, const double* ref) {
    // Staircase sweep: ascending f0, keep the running minimum of f1.
    const std::size_t n = f.size();
    std::vector<std::size_t> idx(n);
    for (std::size_t i = 0; i < n; ++i) idx[i] = i;
    std::sort(idx.begin(), idx.end(), [&](std::size_t a, std::size_t b) {
        const double *pa = f.row(a), *pb = f.row(b);
        return pa[0] < pb[0] || (pa[0] == pb[0] && pa[1] < pb[1]);
    });
    double vol = 0.0, ymin = ref[1];
    for (std::size_t i : idx) {
        const double* p = f.row(i);
        if (p[1] < ymin) {
            vol += (ref[0] - p[0]) * (ymin - p[1]);
            ymin = p[1];
        }
    }
    return vol;
}

double inclhv(const double* p, const double* ref, int d) {
    double v = 1.0;
    for (int k = 0; k < d; ++k) v *= ref[k] - p[k];
    return v;
}

// b weakly dominates a (minimisation); `strict` excludes equality.
inline bool dominates(const double* b, const double* a, int d) {
    bool any_lt = false;
    for (int k = 0; k < d; ++k) {
        if (b[k] > a[k]) return false;
        if (b[k] < a[k]) any_lt = true;
    }
    return any_lt;
}

inline bool equal_pt(const double* b, const double* a, int d) {
    for (int k = 0; k < d; ++k)
        if (b[k] != a[k]) return false;
    return true;
}

// Non-dominated filter (keeps one copy of duplicates), O(m² d).
Front nds(const Front& f) {
    const std::size_t n = f.size();
    Front out;
    out.d = f.d;
    std::vector<bool> keep(n, true);
    for (std::size_t a = 0; a < n; ++a) {
        if (!keep[a]) continue;
        for (std::size_t b = 0; b < n; ++b) {
            if (a == b || !keep[b]) continue;
            if (dominates(f.row(b), f.row(a), f.d) ||
                (b < a && equal_pt(f.row(b), f.row(a), f.d))) {
                keep[a] = false;
                break;
            }
        }
    }
    for (std::size_t a = 0; a < n; ++a)
        if (keep[a]) out.push(f.row(a));
    return out;
}

double wfg(Front& f, const double* ref);

// Exclusive hypervolume of point i against the points after it.
double exclhv(const Front& f, std::size_t i, const double* ref) {
    const int d = f.d;
    double v = inclhv(f.row(i), ref, d);
    const std::size_t n = f.size();
    if (i + 1 >= n) return v;
    Front lim;
    lim.d = d;
    std::vector<double> q(d);
    for (std::size_t j = i + 1; j < n; ++j) {
        const double *pi = f.row(i), *pj = f.row(j);
        for (int k = 0; k < d; ++k) q[k] = std::max(pi[k], pj[k]);
        lim.push(q.data());
    }
    Front limited = nds(lim);
    if (limited.size()) v -= wfg(limited, ref);
    return v;
}

double wfg(Front& f, const double* ref) {
    if (f.size() == 0) return 0.0;
    if (f.d == 1) {
        double m = ref[0];
        for (std::size_t i = 0; i < f.size(); ++i)
            m = std::min(m, f.row(i)[0]);
        return ref[0] - m;
    }
    if (f.d == 2) return hv2d(f, ref);
    // Sorting by the last objective descending shrinks limited sets
    // fastest (the classic WFG heuristic).
    const std::size_t n = f.size();
    std::vector<std::size_t> idx(n);
    for (std::size_t i = 0; i < n; ++i) idx[i] = i;
    const int d = f.d;
    std::sort(idx.begin(), idx.end(), [&](std::size_t a, std::size_t b) {
        return f.row(a)[d - 1] > f.row(b)[d - 1];
    });
    Front sorted;
    sorted.d = d;
    for (std::size_t i : idx) sorted.push(f.row(i));
    double total = 0.0;
    for (std::size_t i = 0; i < n; ++i) total += exclhv(sorted, i, ref);
    return total;
}

Front prepare(const double* data, int n, int d, const double* ref) {
    Front f;
    f.d = d;
    for (int i = 0; i < n; ++i) {
        const double* p = data + static_cast<std::size_t>(i) * d;
        bool below = true;
        for (int k = 0; k < d; ++k)
            if (p[k] >= ref[k]) { below = false; break; }
        if (below) f.push(p);
    }
    return nds(f);
}

}  // namespace

extern "C" {

// Exact hypervolume of `data` ([n, d] row-major, minimisation) w.r.t. ref.
double deap_tpu_hypervolume(const double* data, int n, int d,
                            const double* ref) {
    if (n <= 0 || d <= 0) return 0.0;
    Front f = prepare(data, n, d, ref);
    return wfg(f, ref);
}

// Leave-one-out exclusive contribution of every point (total minus the
// hypervolume without that point) — the quantity behind the reference's
// least-contributor indicator (deap/tools/indicator.py:10-31).
void deap_tpu_hv_contributions(const double* data, int n, int d,
                               const double* ref, double* out) {
    if (n <= 0 || d <= 0) return;
    const double total = deap_tpu_hypervolume(data, n, d, ref);
    std::vector<double> rest(static_cast<std::size_t>(n - 1) * d);
    for (int i = 0; i < n; ++i) {
        double* w = rest.data();
        for (int j = 0; j < n; ++j) {
            if (j == i) continue;
            const double* p = data + static_cast<std::size_t>(j) * d;
            std::copy(p, p + d, w);
            w += d;
        }
        out[i] = total - deap_tpu_hypervolume(rest.data(), n - 1, d, ref);
    }
}

}  // extern "C"
